"""Benchmark harness — runs on the real TPU chip (ambient platform left
as-is so the axon tunnel backend is used when present).

Workload: a TPC-H q1-shaped columnar pipeline (filter + projected arithmetic
+ group-by aggregation) over generated lineitem-like data, through the full
engine (DataFrame API -> overrides -> jitted XLA kernels).  Baseline: the
same query via pandas on the host CPU — the stand-in for the reference's
CPU-Spark baseline (BASELINE.md: ≥3× Spark-CPU is the north star).

Robustness contract (round-1 postmortem): this script ALWAYS prints exactly
one JSON line, even if the device backend hangs or the engine fails — a
watchdog thread emits a partial record and exits before the driver's
timeout.  Columns are float32 (TPU-native); repeats are few; rows default
to 1M so a full run fits the driver budget.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

#: TPC-H SF1 lineitem is ~6M rows; 8M keeps the workload representative
#: of the actual benchmark target.  The bench banks a result at 1M first
#: (fast even with a cold XLA compile cache), then upgrades to the full
#: size if budget remains — the watchdog emits the best result so far.
try:
    ROWS = int(float(sys.argv[1])) if len(sys.argv) > 1 else 8_000_000
except ValueError:
    ROWS = 8_000_000
WARM_ROWS = min(1_000_000, ROWS)
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "270"))

_lock = threading.Lock()
_printed = False
_result = {"metric": "tpch_q1_like_rows_per_sec", "value": 0,
           "unit": "rows/s", "vs_baseline": 0.0}


def _emit(**extra) -> None:
    """Print the single JSON result line exactly once."""
    global _printed
    with _lock:
        if _printed:
            return
        _printed = True
        out = dict(_result)
        out.update(extra)
        sys.stdout.write(json.dumps(out) + "\n")
        sys.stdout.flush()


def _watchdog() -> None:
    _emit(note="watchdog: budget exceeded, partial result")
    os._exit(0)


def make_data(rows: int):
    rng = np.random.default_rng(42)
    return {
        "returnflag": rng.integers(0, 3, rows).astype(np.int64),
        "linestatus": rng.integers(0, 2, rows).astype(np.int64),
        "quantity": (rng.random(rows) * 50).astype(np.float32),
        "extendedprice": (rng.random(rows) * 100_000).astype(np.float32),
        "discount": (rng.random(rows) * 0.1).astype(np.float32),
        "tax": (rng.random(rows) * 0.08).astype(np.float32),
    }


def run_pandas(data) -> tuple:
    """Baseline: best of two runs (same contract as the engine's
    min-of-repeats — one-shot timings swing 2-3x with machine state)."""
    t1, r = _run_pandas_once(data)
    t2, r = _run_pandas_once(data)
    return min(t1, t2), r


def _run_pandas_once(data) -> tuple:
    import pandas as pd
    df = pd.DataFrame(data)
    t0 = time.perf_counter()
    f = df[df.quantity < 24.0]
    disc_price = f.extendedprice * (1.0 - f.discount)
    charge = disc_price * (1.0 + f.tax)
    g = pd.DataFrame({
        "returnflag": f.returnflag, "linestatus": f.linestatus,
        "qty": f.quantity, "base": f.extendedprice,
        "disc_price": disc_price, "charge": charge,
        "disc": f.discount,
    }).groupby(["returnflag", "linestatus"]).agg(
        sum_qty=("qty", "sum"), sum_base=("base", "sum"),
        sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("base", "mean"),
        avg_disc=("disc", "mean"), count=("qty", "count"))
    g = g.sort_index()
    dt = time.perf_counter() - t0
    return dt, g


def run_engine(data) -> tuple:
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F

    sess = srt.session()
    df = sess.create_dataframe(pa.table(data))

    q = (df.filter(df.quantity < 24.0)
         .withColumn("disc_price",
                     df.extendedprice * (1.0 - df.discount))
         .withColumn("charge",
                     df.extendedprice * (1.0 - df.discount)
                     * (1.0 + df.tax))
         .groupBy("returnflag", "linestatus")
         .agg(F.sum(F.col("quantity")).alias("sum_qty"),
              F.sum(F.col("extendedprice")).alias("sum_base"),
              F.sum(F.col("disc_price")).alias("sum_disc_price"),
              F.sum(F.col("charge")).alias("sum_charge"),
              F.avg(F.col("quantity")).alias("avg_qty"),
              F.avg(F.col("extendedprice")).alias("avg_price"),
              F.avg(F.col("discount")).alias("avg_disc"),
              F.count("*").alias("count"))
         .orderBy("returnflag", "linestatus"))

    out = q.collect()  # warm-up: host->device upload + XLA compile
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = q.collect()
        times.append(time.perf_counter() - t0)
    return min(times), out


def _device_responsive(timeout_s: float) -> bool:
    """Probe the ambient device backend from a daemon thread; a hung TPU
    tunnel must not take the whole bench (and its JSON line) with it."""
    ok: list = []

    def probe():
        try:
            import jax.numpy as jnp
            float(jnp.sum(jnp.ones(8)))
            ok.append(True)
        except BaseException:
            pass

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


def main():
    wd = threading.Timer(BUDGET_S, _watchdog)
    wd.daemon = True
    wd.start()

    # Local-dev override: the ambient sitecustomize forces the axon tunnel
    # platform via jax.config (env vars can't override it).  The driver
    # leaves this unset so the real chip is used.  MUST run before the
    # package import below — its persistent-cache setup is platform-gated
    # (CPU AOT cache entries are a SIGILL hazard; TPU remote compiles are
    # the thing worth caching).
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    # Persistent XLA compilation cache: first-compile on the TPU tunnel
    # costs 20-60s per program; the package configures a host-scoped cache
    # dir under the repo, amortizing compiles across driver runs.
    try:
        import spark_rapids_tpu  # noqa: F401  (configures the cache + x64)
    except Exception:
        pass

    if not plat and not _device_responsive(60.0):
        # tunnel hung: re-exec onto the CPU platform so the bench still
        # produces a real number (noted as the fallback it is)
        import subprocess
        env = dict(os.environ)
        env["BENCH_PLATFORM"] = "cpu"
        env["BENCH_BUDGET_S"] = str(max(BUDGET_S - 90, 60))
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env, stdout=subprocess.PIPE, timeout=BUDGET_S - 75)
        line = proc.stdout.decode().strip().splitlines()
        out = json.loads(line[-1]) if line else {}
        out["note"] = ("device backend unresponsive; CPU-platform "
                       "fallback numbers")
        sys.stdout.write(json.dumps(out) + "\n")
        sys.stdout.flush()
        os._exit(0)

    tol = 2e-3  # float32 accumulation vs pandas float64
    note = None

    def measure(rows: int):
        """Bank one measurement into _result; returns the note (if any).
        Called smallest-size first so a budget/watchdog cutoff mid-way
        through the big size still reports a real number."""
        nonlocal note
        data = make_data(rows)
        cpu_time, cpu_result = run_pandas(data)
        eng_time, eng_result = run_engine(data)
        try:
            got = {(r["returnflag"], r["linestatus"]): r
                   for r in eng_result.to_pylist()}
            for (rf, ls), row in cpu_result.iterrows():
                g = got[(rf, ls)]
                assert g["count"] == int(row["count"]), "count mismatch"
                rel = abs(g["sum_qty"] - row["sum_qty"]) \
                    / max(1.0, abs(row["sum_qty"]))
                assert rel < tol, f"sum_qty rel err {rel}"
        except Exception as e:
            note = f"cross-check failed at {rows} rows: " \
                   f"{type(e).__name__}: {e}"
        _result.update(value=round(rows / eng_time),
                       vs_baseline=round(cpu_time / eng_time, 3),
                       rows=rows)

    try:
        measure(WARM_ROWS)
        if ROWS > WARM_ROWS:
            measure(ROWS)
    except BaseException as e:
        if _result.get("rows"):
            note = (note or "") + f"; larger size failed: " \
                f"{type(e).__name__}: {e}"
        else:
            _emit(note=f"engine failed: {type(e).__name__}: {e}")
            return
    # context: each host<->device sync over the axon tunnel costs a full
    # network round trip; with N sequential pipeline stages the floor is
    # N*rtt regardless of device speed, so report the measured rtt
    try:
        import jax
        import jax.numpy as jnp
        x = jnp.ones(8)
        float(jnp.sum(x) + 1.0)  # warm the EXACT timed expression
        t0 = time.perf_counter()
        float(jnp.sum(x) + 1.0)
        _result["sync_rtt_ms"] = round((time.perf_counter() - t0) * 1000, 1)
    except Exception:
        pass
    if note:
        _emit(note=note)
    else:
        _emit()


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # contract: one JSON line, no matter what
        _emit(note=f"unexpected failure: {type(e).__name__}: {e}")
    os._exit(0)  # don't hang on stray non-daemon backend threads
