"""Benchmark harness — runs on the real TPU chip (axon platform left as-is).

Workload: a TPC-H q1-shaped columnar pipeline (filter + projected arithmetic
+ group-by aggregation) over generated lineitem-like data, through the full
engine (DataFrame API -> overrides -> jitted XLA kernels).  Baseline: the
same query via pandas on the host CPU — the stand-in for the reference's
CPU-Spark baseline (BASELINE.md: ≥3× Spark-CPU is the north star).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

ROWS = int(float(sys.argv[1])) if len(sys.argv) > 1 else 4_000_000
REPEATS = 5


def make_data(rows: int):
    rng = np.random.default_rng(42)
    return {
        "returnflag": rng.integers(0, 3, rows).astype(np.int64),
        "linestatus": rng.integers(0, 2, rows).astype(np.int64),
        "quantity": (rng.random(rows) * 50).astype(np.float64),
        "extendedprice": (rng.random(rows) * 100_000).astype(np.float64),
        "discount": (rng.random(rows) * 0.1).astype(np.float64),
        "tax": (rng.random(rows) * 0.08).astype(np.float64),
    }


def run_pandas(data) -> tuple:
    import pandas as pd
    df = pd.DataFrame(data)
    t0 = time.perf_counter()
    f = df[df.quantity < 24.0]
    disc_price = f.extendedprice * (1.0 - f.discount)
    charge = disc_price * (1.0 + f.tax)
    g = pd.DataFrame({
        "returnflag": f.returnflag, "linestatus": f.linestatus,
        "qty": f.quantity, "base": f.extendedprice,
        "disc_price": disc_price, "charge": charge,
        "disc": f.discount,
    }).groupby(["returnflag", "linestatus"]).agg(
        sum_qty=("qty", "sum"), sum_base=("base", "sum"),
        sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("base", "mean"),
        avg_disc=("disc", "mean"), count=("qty", "count"))
    g = g.sort_index()
    dt = time.perf_counter() - t0
    return dt, g


def run_engine(data) -> tuple:
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F

    sess = srt.session()
    df = sess.create_dataframe(pa.table(data))

    def query():
        q = (df.filter(df.quantity < 24.0)
             .withColumn("disc_price",
                         df.extendedprice * (1.0 - df.discount))
             .withColumn("charge",
                         df.extendedprice * (1.0 - df.discount)
                         * (1.0 + df.tax))
             .groupBy("returnflag", "linestatus")
             .agg(F.sum(F.col("quantity")).alias("sum_qty"),
                  F.sum(F.col("extendedprice")).alias("sum_base"),
                  F.sum(F.col("disc_price")).alias("sum_disc_price"),
                  F.sum(F.col("charge")).alias("sum_charge"),
                  F.avg(F.col("quantity")).alias("avg_qty"),
                  F.avg(F.col("extendedprice")).alias("avg_price"),
                  F.avg(F.col("discount")).alias("avg_disc"),
                  F.count("*").alias("count"))
             .orderBy("returnflag", "linestatus"))
        return q.collect()

    out = query()  # warm-up: host->device upload + XLA compile
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = query()
        times.append(time.perf_counter() - t0)
    return min(times), out


def main():
    data = make_data(ROWS)
    cpu_time, cpu_result = run_pandas(data)
    tol = 1e-9
    try:
        eng_time, eng_result = run_engine(data)
    except Exception as e:  # f64-on-TPU unsupported path: retry in f32
        sys.stderr.write(f"f64 path failed ({type(e).__name__}: {e}); "
                         "retrying with float32 columns\n")
        for k in ("quantity", "extendedprice", "discount", "tax"):
            data[k] = data[k].astype(np.float32)
        tol = 1e-3
        eng_time, eng_result = run_engine(data)

    # cross-check results agree (bit-identical counts, fp-close sums)
    got = {(r["returnflag"], r["linestatus"]): r
           for r in eng_result.to_pylist()}
    for (rf, ls), row in cpu_result.iterrows():
        g = got[(rf, ls)]
        assert g["count"] == int(row["count"]), "count mismatch"
        assert abs(g["sum_qty"] - row["sum_qty"]) / max(1, row["sum_qty"]) < tol

    rows_per_sec = ROWS / eng_time
    print(json.dumps({
        "metric": "tpch_q1_like_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(cpu_time / eng_time, 3),
    }))


if __name__ == "__main__":
    main()
