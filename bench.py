"""Benchmark harness — fights for the real TPU chip for the whole budget.

Workload: a TPC-H q1-shaped columnar pipeline (filter + projected arithmetic
+ group-by aggregation) over generated lineitem-like data, through the full
engine (DataFrame API -> overrides -> jitted XLA kernels).  Baseline: the
same query via pandas on the host CPU — the stand-in for the reference's
CPU-Spark baseline (BASELINE.md: ≥3× Spark-CPU is the north star).

Architecture (round-2 postmortem: one 60s probe forfeited the whole round's
perf evidence to a transiently-hung tunnel):

  parent (this process, never imports jax)
    ├── CPU-insurance child: runs the full measurement on the CPU platform
    │     from t=0, concurrently — the fallback number costs no reserved
    │     budget and is ready whenever the device attempts give up
    └── device attempts, in a loop until the budget runs out:
          fresh subprocess each time (a hung backend init cannot be retried
          in-process), quick responsiveness probe, then the measurement.
          Each probe outcome is timestamped; if the tunnel is dead all
          round the JSON says exactly when it was tried.

The parent ALWAYS prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
import threading
import time

import numpy as np

#: TPC-H SF1 lineitem is ~6M rows; 8M keeps the workload representative
#: of the actual benchmark target.  The child banks a result at 1M first
#: (fast even with a cold XLA compile cache), then upgrades to the full
#: size — its watchdog emits the best result so far.
#: --suite runs the scale rig's full query set (TPC-H q1/q4/q6/q14/q22 +
#: TPC-DS q3/q7/q19/q42 shapes and the join/window/sort micro-queries),
#: streaming one JSON line per query (rows/s at warm timing) and a final
#: geomean summary line — same probe/fallback machinery as the default
#: single-query mode (VERDICT r2 #3: on-chip evidence beyond q1).
ARGS = [a for a in sys.argv[1:] if a != "--suite"]
SUITE = "--suite" in sys.argv[1:]
try:
    ROWS = int(float(ARGS[0])) if ARGS else (
        500_000 if SUITE else 8_000_000)
except ValueError:
    ROWS = 500_000 if SUITE else 8_000_000
WARM_ROWS = min(1_000_000, ROWS)
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S",
                                "1800" if SUITE else "270"))
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "30"))


def _ts() -> str:
    return time.strftime("%H:%M:%S", time.gmtime()) + "Z"


# --------------------------------------------------------------------------
# child: one measurement run (mode = "device" | "cpu")
# --------------------------------------------------------------------------

_lock = threading.Lock()
_printed = False
#: vs_baseline normalization caveat (VERDICT r4 weak #2): the only CPU
#: baseline availaible on this 1-core host is single-threaded pandas —
#: far below the "Spark-CPU cluster" bar in BASELINE.md.  The artifact
#: says so explicitly; gb_per_s_per_chip is the cross-repo-comparable
#: number (BASELINE.json north-star metric).
_result = {"metric": "tpch_q1_like_rows_per_sec", "value": 0,
           "unit": "rows/s", "vs_baseline": 0.0,
           "baseline": "pandas-1core", "chips": 1}


def _emit(**extra) -> None:
    """Print the single JSON result line exactly once (child side)."""
    global _printed
    with _lock:
        if _printed:
            return
        _printed = True
        out = dict(_result)
        out.update(extra)
        sys.stdout.write(json.dumps(out) + "\n")
        sys.stdout.flush()
    _bank_telemetry()


def _bank_telemetry() -> None:
    """Bank a telemetry snapshot beside the capture when the watcher
    asks for one (SRT_BENCH_TELEMETRY_DIR, set per bench mode by
    tools/tunnel_watcher.sh): the run's /metrics exposition text and
    the doctor's last ranked verdict, pid-stamped so orchestrator and
    children never clobber each other.  Best-effort — a diagnostics
    write must never take the measurement down."""
    sink = os.environ.get("SRT_BENCH_TELEMETRY_DIR")
    if not sink:
        return
    try:
        os.makedirs(sink, exist_ok=True)
        from spark_rapids_tpu.observability import doctor as OD
        from spark_rapids_tpu.observability import tracer as OT
        from spark_rapids_tpu.observability.metrics import get_registry
        pid = os.getpid()
        with open(os.path.join(sink, f"metrics-{pid}.prom"), "w") as f:
            f.write(get_registry().prometheus_text())
        tr = OT.get_tracer()
        events = tr.snapshot()
        if events:
            meta = tr.meta()
            doc = OD.diagnose(events, counters=meta.get("counters"),
                              dropped_events=int(
                                  meta.get("dropped_events", 0)))
            with open(os.path.join(sink,
                                   f"doctor-{pid}.json"), "w") as f:
                json.dump(doc, f, indent=2, default=str)
    except Exception:  # noqa: BLE001
        pass


def _bank_partial() -> None:
    """Atomically snapshot the banked-so-far result to the partial
    artifact (tmp + rename).  Called after EVERY completed measurement —
    q1 sizes, each join/window/sort shape, each suite query — so a
    watchdog cut, a wedged tunnel, or a SIGKILL never again loses numbers
    that were measured but unemitted (r4/r5 lost the join/window/sort and
    resident-delta figures exactly this way)."""
    path = os.environ.get("BENCH_PARTIAL_PATH")
    if not path:
        return
    try:
        with _lock:
            snap = dict(_result)
        snap["partial_banked_at"] = _ts()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(snap) + "\n")
        os.replace(tmp, path)
    except OSError:
        pass  # banking must never take the measurement down


def _read_partial(path: str):
    """The freshest partial-artifact record at ``path``, or None."""
    try:
        with open(path) as f:
            return json.loads(f.read().strip())
    except (OSError, ValueError):
        return None


def make_data(rows: int):
    rng = np.random.default_rng(42)
    return {
        "returnflag": rng.integers(0, 3, rows).astype(np.int64),
        "linestatus": rng.integers(0, 2, rows).astype(np.int64),
        "quantity": (rng.random(rows) * 50).astype(np.float32),
        "extendedprice": (rng.random(rows) * 100_000).astype(np.float32),
        "discount": (rng.random(rows) * 0.1).astype(np.float32),
        "tax": (rng.random(rows) * 0.08).astype(np.float32),
    }


def run_pandas(data) -> tuple:
    """Baseline: best of two runs (same contract as the engine's
    min-of-repeats — one-shot timings swing 2-3x with machine state)."""
    t1, r = _run_pandas_once(data)
    t2, r = _run_pandas_once(data)
    return min(t1, t2), r


def _run_pandas_once(data) -> tuple:
    import pandas as pd
    df = pd.DataFrame(data)
    t0 = time.perf_counter()
    f = df[df.quantity < 24.0]
    disc_price = f.extendedprice * (1.0 - f.discount)
    charge = disc_price * (1.0 + f.tax)
    g = pd.DataFrame({
        "returnflag": f.returnflag, "linestatus": f.linestatus,
        "qty": f.quantity, "base": f.extendedprice,
        "disc_price": disc_price, "charge": charge,
        "disc": f.discount,
    }).groupby(["returnflag", "linestatus"]).agg(
        sum_qty=("qty", "sum"), sum_base=("base", "sum"),
        sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("base", "mean"),
        avg_disc=("disc", "mean"), count=("qty", "count"))
    g = g.sort_index()
    dt = time.perf_counter() - t0
    return dt, g


def _shape_trace(sess, collect) -> dict:
    """One traced collect -> compact sync/compile/transfer summary
    (observability tracer; VERDICT r5 Missing #2: every banked shape
    carries its own diagnosis) PLUS the bottleneck doctor's ranked
    verdict (observability/doctor.py) — so every banked shape names its
    bottleneck, closing the "diagnose the 0.027x join" debt on any
    window this runs in.  Also returns the traced collect's wall time so
    callers can report tracing overhead.  Must never take the
    measurement down."""
    out = {}
    try:
        sess.conf.set("spark.rapids.tpu.trace.sink", "memory")
        t0 = time.perf_counter()
        collect()
        out["traced_seconds"] = time.perf_counter() - t0
        summary = sess.last_query_trace_summary
        if summary:
            out["trace_summary"] = summary
        try:
            from spark_rapids_tpu.observability import doctor as _doc
            out["doctor"] = _doc.compact(sess.diagnose_last_query())
        except Exception:
            pass
    except Exception:
        pass
    finally:
        try:
            sess.conf.set("spark.rapids.tpu.trace.sink", "")
        except Exception:
            pass
    return out


class PhaseTimeout(Exception):
    """A bench phase exhausted its own watchdog budget."""


def _run_phase(label: str, fn, budget_s: float, result: dict = None):
    """Run one bench phase on a daemon thread under its OWN watchdog
    budget (BENCH_r05 postmortem: a hung join micro consumed the whole
    run's budget and forced a stale replayed capture).  The phase's
    ``budget_ms``/``elapsed_ms``/``timed_out`` are banked into the
    artifact either way; on timeout the thread is abandoned (daemon) and
    PhaseTimeout raised so the caller can move to the next phase.

    ``result`` redirects the phase record into a caller-owned artifact
    dict (run_shape_set / the perf sentry) instead of the module-global
    child artifact — those callers bank their own partials."""
    rec = {"budget_ms": int(budget_s * 1000)}
    box: dict = {}

    def wrap():
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            box["err"] = e

    t0 = time.perf_counter()
    th = threading.Thread(target=wrap, daemon=True,
                          name=f"bench-{label}")
    th.start()
    th.join(max(budget_s, 1.0))
    rec["elapsed_ms"] = int((time.perf_counter() - t0) * 1000)
    rec["timed_out"] = th.is_alive()
    with _lock:
        (_result if result is None
         else result).setdefault("phases", {})[label] = rec
    if result is None:
        _bank_partial()
    if th.is_alive():
        raise PhaseTimeout(f"phase {label} exceeded its "
                           f"{budget_s:.0f}s budget")
    if "err" in box:
        raise box["err"]
    return box.get("out")


def _phase_budget(deadline: float, frac: float, cap: float) -> float:
    """Fraction of the remaining budget, capped, floored at 10s."""
    return max(10.0, min(cap, (deadline - time.time()) * frac))


def run_engine(data, measure_trace_overhead: bool = False) -> tuple:
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F

    sess = srt.session()
    df = sess.create_dataframe(pa.table(data))

    q = (df.filter(df.quantity < 24.0)
         .withColumn("disc_price",
                     df.extendedprice * (1.0 - df.discount))
         .withColumn("charge",
                     df.extendedprice * (1.0 - df.discount)
                     * (1.0 + df.tax))
         .groupBy("returnflag", "linestatus")
         .agg(F.sum(F.col("quantity")).alias("sum_qty"),
              F.sum(F.col("extendedprice")).alias("sum_base"),
              F.sum(F.col("disc_price")).alias("sum_disc_price"),
              F.sum(F.col("charge")).alias("sum_charge"),
              F.avg(F.col("quantity")).alias("avg_qty"),
              F.avg(F.col("extendedprice")).alias("avg_price"),
              F.avg(F.col("discount")).alias("avg_disc"),
              F.count("*").alias("count"))
         .orderBy("returnflag", "linestatus"))

    out = q.collect()  # warm-up: host->device upload + XLA compile
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = q.collect()
        times.append(time.perf_counter() - t0)
    eng_time = min(times)
    # one traced run per size: the artifact's q1 entry carries its own
    # sync/compile/transfer diagnosis next to the rows/s number
    trace_info = _shape_trace(sess, q.collect)

    overhead_fn = None
    if measure_trace_overhead:
        # the trace/chaos overhead measurements run as their OWN bench
        # phase (own watchdog budget), so a wedged overhead rerun can't
        # eat the q1 phase's budget — hence a closure handed back to
        # child_main instead of measuring inline
        def overhead_fn() -> dict:
            info = {}
            # tracing overhead on the q1 shape: min-of-repeats traced vs
            # the untraced min above (the first traced collect above
            # already warmed the tracer's code paths)
            try:
                sess.conf.set("spark.rapids.tpu.trace.sink", "memory")
                ttimes = []
                for _ in range(REPEATS):
                    t0 = time.perf_counter()
                    q.collect()
                    ttimes.append(time.perf_counter() - t0)
                info["trace_overhead"] = round(
                    min(ttimes) / max(eng_time, 1e-9) - 1.0, 4)
            except Exception:
                pass
            finally:
                sess.conf.set("spark.rapids.tpu.trace.sink", "")
            # chaos chokepoint overhead on the q1 shape: registry armed
            # but never firing (p=0) vs the untraced min above — bounds
            # what the fault-injection hooks cost a production
            # (chaos-off) run, where each chokepoint is one dict lookup
            # cheaper still
            try:
                from spark_rapids_tpu.robustness import (arm_chaos,
                                                         disarm_chaos)
                arm_chaos(seed=0, sites=None, probability=0.0)
                ctimes = []
                for _ in range(REPEATS):
                    t0 = time.perf_counter()
                    q.collect()
                    ctimes.append(time.perf_counter() - t0)
                info["chaos_overhead"] = round(
                    min(ctimes) / max(eng_time, 1e-9) - 1.0, 4)
            except Exception:
                pass
            finally:
                try:
                    disarm_chaos()
                except Exception:
                    pass
            return info
    trace_info.pop("traced_seconds", None)
    return eng_time, out, trace_info, overhead_fn


_RESIDENT_KEY = "spark.rapids.shuffle.localDeviceResident.enabled"


def _session_with_resident(resident: bool, force_shuffle: bool = False):
    """A session whose shuffle plane has the device-resident local tier
    explicitly on/off (VERDICT r4 #1: the on/off DELTA is the claim —
    the tier was built for the 0.016x join number but never measured).
    ``force_shuffle`` disables broadcast joins so the join shape rides
    the shuffle plane the tier actually serves."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.config import RapidsConf
    overrides = {_RESIDENT_KEY: "true" if resident else "false"}
    if force_shuffle:
        overrides["spark.rapids.sql.autoBroadcastJoinThreshold"] = 1
    conf = RapidsConf.get_global().copy(overrides)
    return srt.session(conf=conf)


def _gb_per_s(n_bytes: int, seconds: float) -> float:
    return round(n_bytes / max(seconds, 1e-9) / 1e9, 4)


def _wire_snapshot() -> tuple:
    try:
        from spark_rapids_tpu.columnar.prepack import STATS
        return (STATS["bytes_on_wire"], STATS["bytes_naive"])
    except Exception:
        return (0, 0)


def _wire_stats(prefix: str, snap: tuple) -> dict:
    """Device-side pre-pack wire accounting (columnar/prepack.py) for the
    serializing (resident-off) shuffle runs: how many bytes actually
    crossed vs a plain fetch (VERDICT r4 #3's bytes-on-wire metric)."""
    wire, naive = _wire_snapshot()
    wire, naive = wire - snap[0], naive - snap[1]
    if naive:
        return {f"{prefix}_bytes_on_wire": wire,
                f"{prefix}_bytes_naive": naive}
    return {}


def _measure_join(rows: int, resident: bool = True,
                  force_shuffle: bool = False) -> dict:
    """Star-join shape (TPC-DS q3-like): selective dim join + group agg.
    One q1 number does not demonstrate shuffle/join on-chip (VERDICT r3
    weak #2) — this and _measure_window ride in the default bench so
    every captured tunnel window carries all three shapes.  Measured with
    the device-resident shuffle tier on AND off; the primary
    ``join_rows_per_sec`` is the resident-on (production default) run."""
    import pandas as pd
    import pyarrow as pa
    from spark_rapids_tpu.sql import functions as F

    rng = np.random.default_rng(7)
    n_dim = max(rows // 100, 50)
    keyspace = max(rows // 20, 100)
    fact = {"fk": rng.integers(0, keyspace, rows),
            "x": rng.random(rows)}
    pks = rng.choice(keyspace, size=n_dim, replace=False)
    dim = {"pk": pks.astype(np.int64),
           "cat": rng.integers(0, 8, n_dim)}
    n_bytes = sum(v.nbytes for v in fact.values()) \
        + sum(v.nbytes for v in dim.values())

    fpd, dpd = pd.DataFrame(fact), pd.DataFrame(dim)

    def pandas_once():
        t0 = time.perf_counter()
        m = fpd.merge(dpd, left_on="fk", right_on="pk", how="inner")
        g = m.groupby("cat").agg(n=("x", "count"), sx=("x", "sum"))
        g = g.sort_index()
        return time.perf_counter() - t0, g

    t1, exp = pandas_once()
    # resident-off reruns only need the oracle, not a min-of-2 baseline
    cpu_time = min(t1, pandas_once()[0]) if resident else t1

    snap = _wire_snapshot()
    sess = _session_with_resident(resident, force_shuffle)
    f = sess.create_dataframe(pa.table(fact), num_partitions=4)
    d = sess.create_dataframe(pa.table(dim), num_partitions=2)
    q = (f.join(d, f.fk == d.pk, "inner")
         .groupBy("cat").agg(F.count("*").alias("n"),
                             F.sum(F.col("x")).alias("sx"))
         .orderBy("cat"))
    got = q.collect()  # warm-up
    from spark_rapids_tpu.sql.physical.join import STATS as _JSTATS
    jsnap = dict(_JSTATS)
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        got = q.collect()
        times.append(time.perf_counter() - t0)
    eng_time = min(times)
    # per-stage join breakdown (VERDICT r5 "What's missing" #2): stage
    # wall times from the last collect's metrics + sync/sort counts over
    # the timed repeats, so the artifact says WHERE join time goes
    join_stages = {
        k: round(v, 3) for k, v in sess.last_query_metrics.items()
        if k.startswith("join")}
    join_stages.update({
        f"_{k}_per_collect": round((_JSTATS[k] - jsnap[k]) / REPEATS, 2)
        for k in ("build_sorts", "host_readbacks", "fastpath_probes",
                  "spec_hits", "spec_misses")})
    gm = {r["cat"]: r for r in got.to_pylist()}
    for cat, row in exp.iterrows():
        assert gm[cat]["n"] == int(row["n"]), "join count mismatch"
        rel = abs(gm[cat]["sx"] - row["sx"]) / max(1.0, abs(row["sx"]))
        assert rel < 2e-3, f"join sum rel err {rel}"
    tag = "join_shuffle" if force_shuffle else "join"
    if not resident:
        out = {f"{tag}_resident_off_rows_per_sec": round(rows / eng_time)}
        out.update(_wire_stats(tag, snap))
        return out
    out = {f"{tag}_rows_per_sec": round(rows / eng_time),
           f"{tag}_vs_baseline": round(cpu_time / eng_time, 3),
           f"{tag}_rows": rows,
           f"{tag}_gb_per_s_per_chip": _gb_per_s(n_bytes, eng_time),
           f"{tag}_stage_metrics": join_stages}
    ti = _shape_trace(sess, q.collect)
    if ti.get("trace_summary"):
        out[f"{tag}_trace_summary"] = ti["trace_summary"]
    if ti.get("doctor"):
        out[f"{tag}_doctor"] = ti["doctor"]
    return out


def _measure_encoded_vs_raw(rows: int) -> dict:
    """Encoded columnar execution proof (docs/encoded_columns.md): each
    shape runs encoded-ON and encoded-OFF over identical data on the
    serializing shuffle plane (resident tier off, so wire bytes exist),
    banking bytes-on-wire and GB/s/chip per shape plus the wire
    reduction and a bit-parity flag.  The join shape is STRING-keyed on
    purpose: probing on integer codes instead of padded byte matrices is
    the fix aimed at the weakest measured shape (BENCH_r05 join 0.027x
    baseline)."""
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.sql import functions as F

    rng = np.random.default_rng(21)
    cats = [f"cat_{i:03d}" for i in range(24)]
    fact = pa.table({
        "k": [cats[i] for i in rng.integers(0, 24, rows)],
        "q": rng.integers(0, 100, rows),
        "v": rng.random(rows)})
    dim = pa.table({"k": cats, "w": np.arange(24.0)})
    n_bytes = fact.nbytes + dim.nbytes

    def mk(sess, shape):
        f = sess.create_dataframe(fact, num_partitions=4)
        d = sess.create_dataframe(dim, num_partitions=2)
        if shape == "agg":
            return (f.groupBy("k")
                    .agg(F.sum(F.col("v")).alias("sv"),
                         F.count("*").alias("c")).orderBy("k"))
        if shape == "filter_agg":
            return (f.filter(F.col("k") <= "cat_011").groupBy("k")
                    .agg(F.sum(F.col("q")).alias("sq")).orderBy("k"))
        return (f.join(d, on="k", how="inner").groupBy("k")
                .agg(F.count("*").alias("n"),
                     F.sum(F.col("v")).alias("sv")).orderBy("k"))

    out: dict = {}
    for shape in ("agg", "filter_agg", "join"):
        per = {}
        results = {}
        for enc in (True, False):
            conf = RapidsConf.get_global().copy({
                "spark.rapids.tpu.sql.encoded.enabled": enc,
                _RESIDENT_KEY: "false",
                "spark.rapids.sql.autoBroadcastJoinThreshold": 1,
            })
            sess = srt.session(conf=conf)
            q = mk(sess, shape)
            got = q.collect()  # warm-up: compiles + upload cache
            times = []
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                got = q.collect()
                times.append(time.perf_counter() - t0)
            el = min(times)
            m = sess.last_query_metrics
            tag = "encoded" if enc else "raw"
            per[tag] = {
                "rows_per_sec": round(rows / el),
                "gb_per_s_per_chip": _gb_per_s(n_bytes, el),
                "bytes_on_wire": int(m.get("shuffleBytesOnWire", 0)),
                "encoded_bytes_saved": int(
                    m.get("shuffleEncodedBytesSaved", 0)),
            }
            results[tag] = got.to_pylist()
        rec = {"encoded": per["encoded"], "raw": per["raw"],
               "parity": results["encoded"] == results["raw"],
               "rows": rows}
        raw_wire = per["raw"]["bytes_on_wire"]
        if raw_wire:
            rec["wire_reduction"] = round(
                1 - per["encoded"]["bytes_on_wire"] / raw_wire, 4)
        out[shape] = rec
    return {"encoded_vs_raw": out}


def _measure_whole_stage(rows: int) -> dict:
    """Whole-stage fusion evidence (ISSUE 7 acceptance): each shape runs
    fused (default: whole-stage + donation on) and killswitched
    (fusion.enabled=false, the per-op baseline) over identical data,
    banking the STAGE-SCOPE device dispatch count (stageOpDispatches:
    filters/projects/agg-partial/join-probe programs — the ops fusion
    absorbs), total compiled-program launches, sync-span counts from a
    traced run, rows/s, and a bit-parity flag.  The acceptance bar is a
    >= 3x dispatch drop on the filter_agg and join shapes.

    ISSUE 14 extends the banked set: ``sort_stage`` and ``window_stage``
    cover the sort/window stage terminals (>= 2x stage-dispatch
    reduction target), and the join record carries
    ``launches_per_probe_batch`` (fused single-program probe target:
    <= 12) plus the dispatch-coalescer counters when it engaged."""
    import pyarrow as pa
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.sql import functions as F

    rng = np.random.default_rng(23)
    keyspace = max(rows // 20, 100)
    fact = pa.table({
        "k": rng.integers(0, 16, rows).astype(np.int64),
        "q": rng.integers(0, 100, rows).astype(np.int64),
        "x": rng.random(rows),
        "fk": rng.integers(0, keyspace, rows).astype(np.int64)})
    dim = pa.table({"pk": np.arange(keyspace, dtype=np.int64),
                    "cat": rng.integers(0, 8, keyspace).astype(np.int64)})
    n_bytes = fact.nbytes + dim.nbytes

    def mk(sess, shape):
        f = sess.create_dataframe(fact, num_partitions=4)
        if shape == "filter_agg":
            # filter -> project -> partial agg: ONE stage program fused
            return (f.filter(F.col("q") < 50)
                    .withColumn("y", F.col("x") * 2.0)
                    .groupBy("k")
                    .agg(F.sum(F.col("y")).alias("sy"),
                         F.count("*").alias("c"))
                    .orderBy("k"))
        if shape == "sort_stage":
            # filter -> project -> project -> SORT terminal: one program
            return (f.filter(F.col("q") < 50)
                    .withColumn("y", F.col("x") * 2.0)
                    .withColumn("z", F.col("y") + F.col("q"))
                    .orderBy("k", "z"))
        if shape == "window_stage":
            # filter -> projects -> absorbed sort -> WINDOW terminal
            from spark_rapids_tpu.sql.window_api import Window as W
            w = W.partitionBy("k").orderBy("q")
            return (f.filter(F.col("q") < 50)
                    .withColumn("y", F.col("x") * 2.0)
                    .withColumn("z", F.col("y") + F.col("q"))
                    .withColumn("rn", F.row_number().over(w)))
        # join: selective filter -> project -> broadcast probe terminal
        d = sess.create_dataframe(dim)
        return (f.filter(F.col("q") < 5)
                .withColumn("y", F.col("x") + 1.0)
                .join(d, f.fk == d.pk, "inner"))

    out: dict = {}
    for shape in ("filter_agg", "join", "sort_stage", "window_stage"):
        per = {}
        results = {}
        for fused in (True, False):
            conf = RapidsConf.get_global().copy({
                "spark.rapids.tpu.sql.fusion.enabled": fused,
                "spark.rapids.tpu.sql.wholeStage.enabled": fused,
                "spark.rapids.tpu.sql.wholeStage.donation.enabled": fused,
                "spark.rapids.tpu.sql.wholeStage.sortWindowTerminal"
                ".enabled": fused,
                "spark.rapids.tpu.sql.join.fusedProbe.enabled": fused,
                "spark.rapids.tpu.sql.dispatch.coalesce.enabled": fused,
            })
            sess = srt.session(conf=conf)
            q = mk(sess, shape)
            got = q.collect()  # warm: compiles + speculation recording
            got = q.collect()  # second warm: spec-hit steady state
            times = []
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                got = q.collect()
                times.append(time.perf_counter() - t0)
            el = min(times)
            m = sess.last_query_metrics
            tag = "fused" if fused else "unfused"
            per[tag] = {
                "rows_per_sec": round(rows / el),
                "gb_per_s_per_chip": _gb_per_s(n_bytes, el),
                "stage_dispatches": int(m.get("stageOpDispatches", 0)),
                "device_dispatches": int(m.get("deviceDispatches", 0)),
                "whole_stage_ops": int(m.get("wholeStageOps", 0)),
                "unfused_ops": int(m.get("unfusedOps", 0)),
                "donated_batches": int(
                    m.get("wholeStageDonatedBatches", 0)),
            }
            probes = int(m.get("joinFastpathProbes", 0)
                         + m.get("joinFallbackProbes", 0))
            if probes:
                per[tag]["probe_batches"] = probes
                per[tag]["launches_per_probe_batch"] = round(
                    per[tag]["device_dispatches"] / probes, 2)
            if m.get("dispatchCoalescedLaunches"):
                per[tag]["coalesced_launches"] = int(
                    m["dispatchCoalescedLaunches"])
                per[tag]["coalesced_batches"] = int(
                    m.get("dispatchCoalescedBatches", 0))
            ti = _shape_trace(sess, q.collect)
            ts = ti.get("trace_summary")
            if ts:
                per[tag]["sync_count"] = ts.get("sync_count")
                per[tag]["trace_summary"] = ts
            if ti.get("doctor"):
                per[tag]["doctor"] = ti["doctor"]
            results[tag] = sorted(
                tuple(sorted(r.items())) for r in got.to_pylist())
        rec = {"fused": per["fused"], "unfused": per["unfused"],
               "parity": results["fused"] == results["unfused"],
               "rows": rows}
        fd = per["fused"]["stage_dispatches"]
        if fd:
            rec["dispatch_reduction"] = round(
                per["unfused"]["stage_dispatches"] / fd, 2)
        out[shape] = rec
    return {"whole_stage": out}


def _measure_window(rows: int, resident: bool = True) -> dict:
    """Window-heavy shape: per-key running sum + global reduction."""
    import pandas as pd
    import pyarrow as pa
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.window_api import Window as W

    rng = np.random.default_rng(8)
    n_keys = max(rows // 1000, 8)
    data = {"k": rng.integers(0, n_keys, rows),
            "t": rng.permutation(rows),
            "v": rng.random(rows)}
    n_bytes = sum(v.nbytes for v in data.values())
    pdf = pd.DataFrame(data)

    def pandas_once():
        t0 = time.perf_counter()
        s = pdf.sort_values("t").groupby("k")["v"].cumsum().sum()
        return time.perf_counter() - t0, s

    t1, exp_sum = pandas_once()
    cpu_time = min(t1, pandas_once()[0]) if resident else t1

    snap = _wire_snapshot()
    sess = _session_with_resident(resident)
    df = sess.create_dataframe(pa.table(data), num_partitions=4)
    w = W.partitionBy("k").orderBy("t")
    q = (df.withColumn("rs", F.sum(F.col("v")).over(w))
         .agg(F.sum(F.col("rs")).alias("total")))
    got = q.collect()  # warm-up
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        got = q.collect()
        times.append(time.perf_counter() - t0)
    eng_time = min(times)
    total = got.to_pylist()[0]["total"]
    rel = abs(total - exp_sum) / max(1.0, abs(exp_sum))
    assert rel < 2e-3, f"window total rel err {rel}"
    if not resident:
        out = {"window_resident_off_rows_per_sec": round(rows / eng_time)}
        out.update(_wire_stats("window", snap))
        return out
    out = {"window_rows_per_sec": round(rows / eng_time),
           "window_vs_baseline": round(cpu_time / eng_time, 3),
           "window_rows": rows,
           "window_gb_per_s_per_chip": _gb_per_s(n_bytes, eng_time)}
    ti = _shape_trace(sess, q.collect)
    if ti.get("trace_summary"):
        out["window_trace_summary"] = ti["trace_summary"]
    if ti.get("doctor"):
        out["window_doctor"] = ti["doctor"]
    return out


def _measure_sort(rows: int) -> dict:
    """Global-sort shape, plus the radix bake-off's frozen base timings —
    VERDICT r4 weak #4: the radix sort has never been measured anywhere
    but XLA:CPU (where it loses); this banks the TPU verdict."""
    import pandas as pd
    import pyarrow as pa
    import spark_rapids_tpu as srt

    rng = np.random.default_rng(9)
    data = {"k": rng.integers(-(1 << 62), 1 << 62, rows),
            "v": rng.random(rows)}
    n_bytes = sum(v.nbytes for v in data.values())
    pdf = pd.DataFrame(data)

    def pandas_once():
        t0 = time.perf_counter()
        s = pdf.sort_values("k")
        return time.perf_counter() - t0, s

    t1, exp = pandas_once()
    cpu_time = min(t1, pandas_once()[0])

    sess = srt.session()
    df = sess.create_dataframe(pa.table(data), num_partitions=4)
    q = df.orderBy("k")
    got = q.collect()  # warm-up
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        got = q.collect()
        times.append(time.perf_counter() - t0)
    eng_time = min(times)
    ks = np.asarray(got.column("k"))
    assert (np.diff(ks) >= 0).all(), "sort order violated"
    assert ks[0] == exp["k"].iloc[0] and ks[-1] == exp["k"].iloc[-1]
    out = {"sort_rows_per_sec": round(rows / eng_time),
           "sort_vs_baseline": round(cpu_time / eng_time, 3),
           "sort_rows": rows,
           "sort_gb_per_s_per_chip": _gb_per_s(n_bytes, eng_time)}
    ti = _shape_trace(sess, q.collect)
    if ti.get("trace_summary"):
        out["sort_trace_summary"] = ti["trace_summary"]
    if ti.get("doctor"):
        out["sort_doctor"] = ti["doctor"]
    try:
        import jax.numpy as jnp

        from spark_rapids_tpu.ops import radix_sort
        base = radix_sort.bakeoff_base(jnp)
        if base is not None:
            out["radix_bakeoff_us"] = {"radix64": base[0], "lax": base[1]}
        out["sort_impl"] = ("radix" if radix_sort.radix_wins(jnp, 64)
                            else "lax")
    except Exception:
        pass
    return out


def _measure_pipeline(rows: int) -> dict:
    """Serial vs pipelined engine over the TPC-H-ish multi-partition
    suite (testing/pipeline.py): wall-clock delta with a bit-parity
    assert, banked as ``pipeline_off_seconds`` / ``pipeline_on_seconds``
    / ``pipeline_speedup``.  On a single-core host there is little
    latency for the overlap to hide (the note says so); on the tunnel
    every transfer is a ~65ms round trip and the delta is the point."""
    from spark_rapids_tpu.testing import pipeline as _pl
    out = _pl.measure(rows, repeats=max(2, REPEATS - 1))
    try:
        import os as _os
        cores = len(_os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = 0
    out["pipeline_host_cores"] = cores
    if cores <= 1:
        out["pipeline_note"] = (
            "single-core host: thread overlap cannot exceed 1x unless "
            "the workload blocks on I/O or device round trips; the "
            "tunnel-RTT overlap is the target claim")
    return out


def _measure_serving(rows: int) -> dict:
    """Multi-tenant serving bench (ISSUE 9 acceptance, docs/serving.md):
    a mixed 80-query workload (20 distinct query templates x 4 rounds —
    the repeat pattern real dashboard traffic has) submitted from 8
    worker threads across 2 tenants through a ServingEngine, in three
    legs over identical data:

      no_sharing       kernel cache cleared per query, no broadcast/
                       result sharing — every query pays its own compile
      kernel_broadcast process-scoped kernel cache + shared broadcast
                       materializations (PR 7's stage-key cache hitting
                       ACROSS sessions)
      result_cache     + the plan-fingerprint -> cached-result tier
                       (repeats short-circuit entirely)

    Banks sustained QPS, per-query p50/p99 latency (admission wait
    included), admission-wait p99, sharing-tier hit counts, and a
    bit-parity verdict of legs 2/3 against leg 1."""
    import pandas as pd
    from concurrent.futures import ThreadPoolExecutor
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.serving import ServingEngine
    from spark_rapids_tpu.serving import broadcast_cache as _bc
    from spark_rapids_tpu.serving import result_cache as _rc
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.physical.kernel_cache import (
        cache_stats, clear_cache, release_compiled_programs)
    from spark_rapids_tpu.testing.scaletest import build_tables
    PAR, TENANTS, ROUNDS = 8, 2, 4
    THRESH = (20, 35, 50, 65, 80)
    tables = build_tables(rows)

    def q_filter_agg(sess, t):
        fact = sess.create_dataframe(tables["fact"], num_partitions=4)
        return (fact.filter(fact.q < t).groupBy("q")
                .agg(F.sum(fact.v).alias("sv"), F.count("*").alias("c"))
                .orderBy("q").collect())

    def q_join_agg(sess, t):
        fact = sess.create_dataframe(tables["fact"], num_partitions=4)
        dim = sess.create_dataframe(tables["dim"])
        return (fact.filter(fact.q < t).join(dim, on="k", how="inner")
                .groupBy("cat").agg(F.count("*").alias("n"),
                                    F.sum(fact.v).alias("sv"))
                .orderBy("cat").collect())

    def q_minmax_agg(sess, t):
        fact = sess.create_dataframe(tables["fact"], num_partitions=4)
        return (fact.filter(fact.q >= t).groupBy("q")
                .agg(F.min(fact.k).alias("mnk"),
                     F.max(fact.k).alias("mxk"),
                     F.count("*").alias("c"))
                .orderBy("q").collect())

    def q_left_join_agg(sess, t):
        fact = sess.create_dataframe(tables["fact"], num_partitions=4)
        dim = sess.create_dataframe(tables["dim"])
        return (fact.join(dim, on="k", how="left").filter(fact.q < t)
                .groupBy("cat").agg(F.sum(dim.w).alias("sw"),
                                    F.count("*").alias("n"))
                .orderBy("cat").collect())

    templates = [q_filter_agg, q_join_agg, q_minmax_agg, q_left_join_agg]
    distinct = [(fn, t) for t in THRESH for fn in templates]
    workload = distinct * ROUNDS  # 20 x 4 = 80, repeats interleaved

    def canon(table):
        df = table.to_pandas()
        return df.sort_values(list(df.columns), kind="mergesort") \
            .reset_index(drop=True)

    base_conf = {
        "spark.rapids.tpu.serving.maxConcurrentQueries": PAR,
    }

    def run_leg(tag: str, extra_conf: dict, clear_between: bool):
        _rc.clear()
        _bc.clear()
        clear_cache()
        eng = ServingEngine(conf=RapidsConf.get_global().copy(
            dict(base_conf, **extra_conf)))
        sessions: dict = {}
        lat = [0.0] * len(workload)
        results: list = [None] * len(workload)
        k0 = cache_stats()
        rc0, bc0 = _rc.stats(), _bc.stats()

        def run_one(i: int) -> None:
            fn, t = workload[i]
            tenant = f"tenant{i % TENANTS}"
            key = (threading.get_ident(), tenant)
            sess = sessions.get(key)
            if sess is None:
                sess = sessions[key] = eng.session(tenant=tenant)
            if clear_between:
                clear_cache()
            t0 = time.perf_counter()
            results[i] = fn(sess, t)
            lat[i] = (time.perf_counter() - t0) * 1e3

        t_start = time.perf_counter()
        with ThreadPoolExecutor(
                max_workers=PAR,
                thread_name_prefix=f"srt-serve-{tag}") as pool:
            list(pool.map(run_one, range(len(workload))))
        wall = time.perf_counter() - t_start
        adm = eng.admission_stats()
        k1 = cache_stats()
        rc1, bc1 = _rc.stats(), _bc.stats()
        eng.close()
        release_compiled_programs()
        ordered = sorted(lat)
        # repeats = rounds 2..N — the latencies the sharing tiers exist
        # to cut; the first round pays every leg's cold compiles
        repeats = sorted(lat[len(distinct):])

        def pctl(seq, q):
            return seq[min(len(seq) - 1, int(q * len(seq)))]

        rec = {
            "qps": round(len(workload) / wall, 3),
            "wall_s": round(wall, 3),
            "p50_ms": round(pctl(ordered, 0.50), 3),
            "p99_ms": round(pctl(ordered, 0.99), 3),
            "repeat_p50_ms": round(pctl(repeats, 0.50), 3),
            "repeat_p99_ms": round(pctl(repeats, 0.99), 3),
            "admission_wait_p99_ms": max(
                t["wait_ms_p99"] for t in adm["per_tenant"].values()),
            "kernel_cache_hits": k1["hits"] - k0["hits"],
            "kernel_compiles": k1["compiles"] - k0["compiles"],
            "broadcast_hits": bc1["hits"] - bc0["hits"],
            "result_cache_hits": rc1["hits"] - rc0["hits"],
        }
        return rec, results

    legs = {}
    leg_results = {}
    legs["no_sharing"], leg_results["no_sharing"] = run_leg(
        "none", {"spark.rapids.tpu.serving.resultCache.enabled": False,
                 "spark.rapids.tpu.serving.broadcastShare.enabled": False},
        clear_between=True)
    legs["kernel_broadcast"], leg_results["kernel_broadcast"] = run_leg(
        "kb", {"spark.rapids.tpu.serving.resultCache.enabled": False,
               "spark.rapids.tpu.serving.broadcastShare.enabled": True},
        clear_between=False)
    legs["result_cache"], leg_results["result_cache"] = run_leg(
        "rc", {"spark.rapids.tpu.serving.resultCache.enabled": True,
               "spark.rapids.tpu.serving.broadcastShare.enabled": True},
        clear_between=False)
    parity_failures = []
    ref = [canon(t) for t in leg_results["no_sharing"]]
    for tag in ("kernel_broadcast", "result_cache"):
        for i, table in enumerate(leg_results[tag]):
            try:
                pd.testing.assert_frame_equal(canon(table), ref[i],
                                              check_exact=True)
            except AssertionError:
                parity_failures.append(
                    f"{tag}/{i}:{workload[i][0].__name__}"
                    f"(t={workload[i][1]})")
    parity = not parity_failures
    _rc.clear()
    _bc.clear()
    return {"serving": {
        "workload_queries": len(workload),
        "distinct_queries": len(distinct),
        "parallelism": PAR, "tenants": TENANTS,
        "serving_rows": rows,
        "legs": legs,
        "parity": parity,
        **({"parity_failures": parity_failures[:8]}
           if parity_failures else {}),
        "sharing_speedup": round(
            legs["kernel_broadcast"]["qps"]
            / max(legs["no_sharing"]["qps"], 1e-9), 3),
        "result_cache_speedup": round(
            legs["result_cache"]["qps"]
            / max(legs["kernel_broadcast"]["qps"], 1e-9), 3),
        # THE repeated-query claim: repeat-window median latency with
        # the result tier vs without it (leg QPS folds first-round
        # compiles in and understates the hit-path win)
        "result_cache_repeat_speedup": round(
            legs["kernel_broadcast"]["repeat_p50_ms"]
            / max(legs["result_cache"]["repeat_p50_ms"], 1e-9), 3),
    }}


def _measure_lifecycle(rows: int) -> dict:
    """Query lifecycle bench (ISSUE 10, docs/robustness.md): banks

    * cancel-latency p50/p99 — cancel ISSUE to worker-threads-DRAINED,
      measured by the session epilogue (`last_cancel_latency_ms`) over N
      mid-flight cancels of a parallel join+agg query;
    * deadline-enforcement accuracy — how far past its deadline a doomed
      query actually runs before QueryDeadlineExceeded surfaces (poll
      latency + the longest uninterruptible dispatch);
    * QPS with pressure-aware degradation ON vs OFF under a saturating
      serving workload (thresholds forced low so every admitted query
      plans degraded), plus bit parity between the legs.
    """
    import pandas as pd
    from concurrent.futures import ThreadPoolExecutor
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.serving import ServingEngine, lifecycle as lc
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.testing.scaletest import build_tables
    tables = build_tables(rows)

    def q(sess):
        fact = sess.create_dataframe(tables["fact"], num_partitions=8)
        dim = sess.create_dataframe(tables["dim"])
        return (fact.join(dim, on="k", how="inner")
                .groupBy("cat").agg(F.count("*").alias("n"),
                                    F.sum(fact.v).alias("sv"))
                .orderBy("cat").collect())

    def pctl(seq, frac):
        seq = sorted(seq)
        return seq[min(len(seq) - 1, int(frac * len(seq)))]

    # --- cancel latency: issue -> threads drained ----------------------
    import spark_rapids_tpu as srt
    sess = srt.session(**{"spark.rapids.tpu.task.parallelism": 4})
    q(sess)  # warm compiles so latency measures the drain, not XLA
    cancel_lat = []
    for i in range(10):
        timer = threading.Timer(0.02, sess.cancel)
        timer.start()
        try:
            q(sess)
        except lc.QueryCancelled:
            if sess.last_cancel_latency_ms is not None:
                cancel_lat.append(sess.last_cancel_latency_ms)
        finally:
            timer.cancel()

    # --- deadline accuracy --------------------------------------------
    deadline_ms = 25
    doomed = srt.session(**{
        "spark.rapids.tpu.task.parallelism": 4,
        "spark.rapids.tpu.query.deadlineMs": deadline_ms})
    overshoot = []
    for i in range(6):
        t0 = time.perf_counter()
        try:
            q(doomed)
        except lc.QueryCancelled:
            overshoot.append(
                (time.perf_counter() - t0) * 1e3 - deadline_ms)

    # --- pressure-aware degradation: QPS on vs off ---------------------
    N_Q, PAR = 24, 8

    def serving_leg(pressure_on: bool):
        eng = ServingEngine(conf=RapidsConf.get_global().copy({
            "spark.rapids.tpu.serving.maxConcurrentQueries": 2,
            "spark.rapids.tpu.serving.pressure.enabled": pressure_on,
            # saturate instantly: any queue at all reads as pressure
            "spark.rapids.tpu.serving.pressure.queueDepth": 1,
            "spark.rapids.sql.concurrentGpuTasks": 2,
            "spark.rapids.tpu.task.parallelism": 4,
        }))
        sessions: dict = {}
        results: list = [None] * N_Q
        degraded = [0]

        def run_one(i):
            key = threading.get_ident()
            s = sessions.get(key)
            if s is None:
                s = sessions[key] = eng.session(tenant=f"t{i % 2}")
            results[i] = q(s)
            if s.last_query_metrics.get("pressureDegraded"):
                degraded[0] += 1
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=PAR) as pool:
            list(pool.map(run_one, range(N_Q)))
        wall = time.perf_counter() - t0
        eng.close()
        return {"qps": round(N_Q / wall, 3),
                "degraded_queries": degraded[0]}, results

    off, ref = serving_leg(False)
    on, got = serving_leg(True)
    parity = True
    for a, b in zip(ref, got):
        ca = a.to_pandas().sort_values(list(a.column_names),
                                       kind="mergesort")
        cb = b.to_pandas().sort_values(list(b.column_names),
                                       kind="mergesort")
        try:
            pd.testing.assert_frame_equal(ca.reset_index(drop=True),
                                          cb.reset_index(drop=True),
                                          check_exact=True)
        except AssertionError:
            parity = False
    return {"lifecycle": {
        "lifecycle_rows": rows,
        "cancel_latency_ms_p50": round(pctl(cancel_lat, 0.50), 3)
        if cancel_lat else None,
        "cancel_latency_ms_p99": round(pctl(cancel_lat, 0.99), 3)
        if cancel_lat else None,
        "cancels_measured": len(cancel_lat),
        "deadline_ms": deadline_ms,
        "deadline_overshoot_ms_p50": round(pctl(overshoot, 0.50), 3)
        if overshoot else None,
        "deadline_overshoot_ms_max": round(max(overshoot), 3)
        if overshoot else None,
        "pressure_off": off,
        "pressure_on": on,
        "pressure_parity": parity,
        "pressure_qps_delta": round(
            on["qps"] / max(off["qps"], 1e-9), 3),
    }}


def _probe_device(timeout_s: float) -> dict:
    """Cancellable bounded-timeout device probe with a classified
    outcome (``ok | degraded | timeout | refused``) and per-attempt
    timing — sentry.device_probe's QueryContext deadline machinery, the
    same cancellation path queries use.  A hung TPU tunnel orphans one
    daemon probe thread; it never takes the child (and its exit) with
    it, and it is never again a free-text "hung" string in the note."""
    try:
        from spark_rapids_tpu.observability import sentry as _sentry
        return _sentry.device_probe(timeout_s)
    except Exception:  # package half-importable: degrade, don't die
        box: dict = {}

        def probe():
            try:
                import jax
                import jax.numpy as jnp
                float(jnp.sum(jnp.ones(8)))
                box["platform"] = str(jax.default_backend())
            except BaseException as e:  # noqa: BLE001 - classified
                box["error"] = f"{type(e).__name__}: {e}"

        t0 = time.perf_counter()
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        out = {"elapsed_ms": round((time.perf_counter() - t0) * 1000, 1)}
        if t.is_alive():
            out["outcome"] = "timeout"
        elif "error" in box:
            out["outcome"] = "refused"
            out["error"] = str(box["error"])[:200]
        else:
            plat = box.get("platform")
            out["outcome"] = ("degraded" if plat in (None, "cpu")
                              else "ok")
            if plat:
                out["platform"] = plat
        return out


# --------------------------------------------------------------------------
# callable shape-set entrypoint (perf sentry / observability.sentry)
# --------------------------------------------------------------------------

#: the sentry's default capture set — join/sort/window/coalesce plus the
#: encoded-vs-raw wire comparison (``coalesce`` is the whole-stage fused
#: dispatch shape; vocabulary of spark.rapids.tpu.sentry.shapes)
SHAPE_SET = ("join", "sort", "window", "coalesce", "encoded")


def run_shape_set(shapes=None, rows: int = 4_000_000,
                  budget_s: float = None, artifact_path: str = None,
                  evidence: str = None, prepack: bool = True) -> dict:
    """Run the bench shape set as a LIBRARY call (the perf sentry's
    capture step) instead of the shell-only child protocol.  Each shape
    runs under its own ``_run_phase`` watchdog with an even split of the
    remaining budget, banking into a caller-owned artifact dict — one
    wedged shape forfeits neither the other shapes nor the window.  The
    artifact is rewritten atomically at ``artifact_path`` after every
    shape, so a caller that kills this process mid-set still recovers
    everything that finished.

    ``evidence`` overrides the platform-derived evidence class (the CI
    simulated-window mode stamps ``live`` while honestly marking
    ``simulated`` in its ledger record).  Imports jax in THIS process —
    the sentry daemon calls it via subprocess_shape_set.
    """
    shapes = [str(s) for s in (shapes if shapes is not None
                               else SHAPE_SET)]
    budget = float(BUDGET_S if budget_s is None else budget_s)
    deadline = time.time() + budget
    import jax
    platform = str(jax.default_backend())
    art = {"metric": "sentry_shape_set", "value": 0, "unit": "rows/s",
           "baseline": "pandas-1core", "chips": 1, "rows": int(rows),
           "platform": platform, "shapes": shapes,
           "evidence": evidence or ("cpu-fallback" if platform == "cpu"
                                    else "live")}

    def _bank():
        if not artifact_path:
            return
        try:
            parent = os.path.dirname(os.path.abspath(artifact_path))
            os.makedirs(parent, exist_ok=True)
            tmp = f"{artifact_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(art, default=str) + "\n")
            os.replace(tmp, artifact_path)
        except OSError:
            pass  # banking must never take the measurement down

    if prepack:
        # same rationale as the orchestrated run: prepack's 'auto' is
        # off on the CPU platform, and wire accounting must exist on
        # every capture this produces
        try:
            from spark_rapids_tpu.config import RapidsConf
            RapidsConf.get_global().set(
                "spark.rapids.tpu.d2h.prepack", "true")
        except Exception:
            pass
    fns = {
        "join": lambda: _measure_join(min(rows, 4_000_000)),
        "sort": lambda: _measure_sort(min(rows, 2_000_000)),
        "window": lambda: _measure_window(min(rows, 2_000_000)),
        "coalesce": lambda: _measure_whole_stage(
            min(max(rows // 8, 1), 1_000_000)),
        "encoded": lambda: _measure_encoded_vs_raw(
            min(max(rows // 4, 1), 1_000_000)),
    }
    notes = [f"unknown shape {s!r} skipped"
             for s in shapes if s not in fns]
    todo = [s for s in shapes if s in fns]
    for i, name in enumerate(todo):
        remaining = deadline - time.time()
        if remaining < 10:
            notes.append(f"budget exhausted before {name}")
            break
        slice_s = max(10.0, remaining / max(1, len(todo) - i))
        try:
            got = _run_phase(f"shape_{name}", fns[name], slice_s,
                             result=art)
            art.setdefault("extra_metrics", {}).update(got or {})
        except BaseException as e:  # noqa: BLE001 - next shape anyway
            notes.append(f"{name} shape failed: "
                         f"{type(e).__name__}: {e}")
        em = art.get("extra_metrics", {})
        for k in ("join_rows_per_sec", "sort_rows_per_sec",
                  "window_rows_per_sec", "whole_stage_rows_per_sec"):
            if em.get(k):
                art["value"] = em[k]
                break
        _bank()  # each shape banks the moment it completes
    if notes:
        art["note"] = "; ".join(notes)
        _bank()
    return art


def child_main(mode: str) -> None:
    deadline = float(os.environ.get("BENCH_CHILD_DEADLINE",
                                    time.time() + BUDGET_S))

    def watchdog():
        _emit(note="watchdog: budget exceeded, partial result")
        os._exit(0)

    wd = threading.Timer(max(deadline - time.time(), 1.0), watchdog)
    wd.daemon = True
    wd.start()

    if mode == "cpu":
        # MUST run before the package import — its persistent-cache setup
        # is platform-gated (CPU AOT cache entries are a SIGILL hazard;
        # TPU remote compiles are the thing worth caching).
        import jax
        jax.config.update("jax_platforms", "cpu")

    try:
        import spark_rapids_tpu  # noqa: F401  (configures cache + x64)
    except Exception:
        pass

    if mode == "device":
        att = _probe_device(PROBE_S)
        # the probe record IS the verdict line: classified outcome plus
        # per-attempt timing for the parent's probe_attempts bank; the
        # platform tells a live tunnel from jax silently falling back to
        # CPU after a failed TPU-plugin init (outcome=degraded)
        sys.stdout.write(json.dumps(
            dict(att, probe=att.get("outcome", "refused"))) + "\n")
        sys.stdout.flush()
        if att.get("outcome") not in ("ok", "degraded"):
            os._exit(3)

    import jax
    platform = jax.default_backend()

    if SUITE:
        _suite_child(platform)
        return

    tol = 2e-3  # float32 accumulation vs pandas float64
    note = None
    overhead_box: dict = {}

    def measure(rows: int):
        """Bank one measurement into _result.  Called smallest-size first
        so a budget/watchdog cutoff mid-way through the big size still
        reports a real number."""
        nonlocal note
        data = make_data(rows)
        n_bytes = sum(v.nbytes for v in data.values())
        cpu_time, cpu_result = run_pandas(data)
        eng_time, eng_result, trace_info, ofn = run_engine(
            data, measure_trace_overhead=(rows == WARM_ROWS))
        if ofn is not None:
            overhead_box["fn"] = ofn
        try:
            got = {(r["returnflag"], r["linestatus"]): r
                   for r in eng_result.to_pylist()}
            for (rf, ls), row in cpu_result.iterrows():
                g = got[(rf, ls)]
                assert g["count"] == int(row["count"]), "count mismatch"
                rel = abs(g["sum_qty"] - row["sum_qty"]) \
                    / max(1.0, abs(row["sum_qty"]))
                assert rel < tol, f"sum_qty rel err {rel}"
        except Exception as e:
            note = f"cross-check failed at {rows} rows: " \
                   f"{type(e).__name__}: {e}"
        _result.update(value=round(rows / eng_time),
                       vs_baseline=round(cpu_time / eng_time, 3),
                       rows=rows, platform=platform,
                       gb_per_s_per_chip=_gb_per_s(n_bytes, eng_time),
                       **trace_info)
        _bank_partial()

    # each q1 size is its own watchdog-budgeted phase: a hung warm-up no
    # longer forfeits the full-size attempt and vice versa
    try:
        _run_phase("q1_warm", lambda: measure(WARM_ROWS),
                   _phase_budget(deadline, 0.40, 150.0))
        if ROWS > WARM_ROWS:
            _run_phase("q1_full", lambda: measure(ROWS),
                       _phase_budget(deadline, 0.45, 240.0))
    except BaseException as e:
        if _result.get("rows"):
            note = (note or "") + f"; larger size failed: " \
                f"{type(e).__name__}: {e}"
        else:
            _emit(note=f"engine failed: {type(e).__name__}: {e}",
                  platform=platform)
            return
    # trace/chaos overhead reruns: own phase, own budget (the BENCH_r05
    # failure mode was exactly an unbudgeted rerun eating the run)
    if "fn" in overhead_box:
        try:
            info = _run_phase("q1_overheads", overhead_box["fn"],
                              _phase_budget(deadline, 0.25, 90.0))
            if info:
                _result.update(info)
                _bank_partial()
        except BaseException as e:
            note = (note or "") + f"; overhead phase failed: " \
                f"{type(e).__name__}: {e}"
    # join/window/sort shapes ride along (banked incrementally so a
    # watchdog cutoff keeps whatever finished); q1 stays the primary
    # metric for cross-round comparability.  Resident-on runs come first
    # (the production numbers), the resident-OFF reruns last — their
    # delta isolates what the device-resident shuffle tier buys
    # (VERDICT r4 next-round #1).
    join_rows = min(ROWS, 4_000_000)
    window_rows = min(ROWS, 2_000_000)
    # prepack on for EVERY shape run (its 'auto' is off on the CPU
    # platform): the resident on/off pairs must differ in the resident
    # tier ONLY, and the off-runs' wire accounting must exist on CPU
    # captures too.  q1 above ran under production-default settings.
    try:
        from spark_rapids_tpu.config import RapidsConf
        RapidsConf.get_global().set("spark.rapids.tpu.d2h.prepack", "true")
    except Exception:
        pass
    shuffle_rows = min(ROWS, 2_000_000)
    # pipeline-off vs pipeline-on over the TPC-H-ish multi-partition
    # suite (ISSUE 5 acceptance evidence): its own dedicated phase with
    # a real budget — inside the generic shape loop its 4-query double
    # suite (serial + pipelined, warm + repeats) outlives the loop's
    # 20-90s slice and the timeout would drop the acceptance metrics
    try:
        got = _run_phase("pipeline",
                         lambda: _measure_pipeline(min(ROWS // 16,
                                                       250_000)),
                         _phase_budget(deadline, 0.35, 150.0))
        _result.setdefault("extra_metrics", {}).update(got)
        _bank_partial()
    except BaseException as e:
        note = (note or "") + f"; pipeline shape failed: " \
            f"{type(e).__name__}: {e}"
    # multi-tenant serving (ISSUE 9 acceptance): sustained QPS + p50/p99
    # under the mixed 80-query workload at parallelism 8, three sharing
    # legs, bit parity — its own dedicated phase (the no-sharing leg
    # recompiles per query by design, so it needs a real budget)
    try:
        got = _run_phase("serving",
                         lambda: _measure_serving(min(ROWS // 80,
                                                      100_000)),
                         _phase_budget(deadline, 0.45, 300.0))
        _result.setdefault("extra_metrics", {}).update(got)
        _bank_partial()
    except BaseException as e:
        note = (note or "") + f"; serving shape failed: " \
            f"{type(e).__name__}: {e}"
    # query lifecycle (ISSUE 10 acceptance): cancel-latency p50/p99,
    # deadline-enforcement accuracy, and the pressure-degradation QPS
    # delta under saturation — its own phase so a wedged cancel (the
    # exact regression this guards) cannot eat the shape loop's budget
    try:
        got = _run_phase("lifecycle",
                         lambda: _measure_lifecycle(min(ROWS // 16,
                                                        250_000)),
                         _phase_budget(deadline, 0.30, 150.0))
        _result.setdefault("extra_metrics", {}).update(got)
        _bank_partial()
    except BaseException as e:
        note = (note or "") + f"; lifecycle shape failed: " \
            f"{type(e).__name__}: {e}"
    shapes = (
        ("join", lambda: _measure_join(join_rows)),
        ("window", lambda: _measure_window(window_rows)),
        # whole-stage fused vs killswitched dispatch/sync evidence
        # (ISSUE 7 acceptance: >= 3x stage-dispatch drop, bit parity)
        ("whole_stage",
         lambda: _measure_whole_stage(min(ROWS // 8, 1_000_000))),
        ("sort", lambda: _measure_sort(min(ROWS, 2_000_000))),
        # encoded-vs-raw (ISSUE 6 acceptance): bytes-on-wire + GB/s/chip
        # per shape, both representations, on the serializing plane
        ("encoded",
         lambda: _measure_encoded_vs_raw(min(ROWS // 4, 1_000_000))),
        # forced shuffle join: the shape the resident tier serves —
        # the default join may broadcast its small dim side
        ("join_shuffle",
         lambda: _measure_join(shuffle_rows, force_shuffle=True)),
        # the shuffle-join on/off delta is THE claim (VERDICT r4 #1)
        # — bank it before the pricier broadcast-shape rerun
        ("join_shuffle_resident_off",
         lambda: _measure_join(shuffle_rows, resident=False,
                               force_shuffle=True)),
        ("window_resident_off",
         lambda: _measure_window(window_rows, resident=False)),
        ("join_resident_off",
         lambda: _measure_join(join_rows, resident=False)))
    for i, (label, fn) in enumerate(shapes):
        remaining = deadline - time.time()
        if remaining < 25:
            break
        # every shape is its own watchdog-budgeted phase: one hung micro
        # (the BENCH_r05 join) can no longer consume the whole run
        budget = max(20.0, min(90.0, (remaining - 15)
                               / max(1, len(shapes) - i)))
        try:
            got = _run_phase(label, fn, budget)
            _result.setdefault("extra_metrics", {}).update(got)
            _bank_partial()  # each shape banks the moment it completes
        except BaseException as e:
            note = (note or "") + f"; {label} shape failed: " \
                f"{type(e).__name__}: {e}"
    em = _result.get("extra_metrics", {})
    for tag in ("join", "join_shuffle", "window"):
        on = em.get(f"{tag}_rows_per_sec")
        off = em.get(f"{tag}_resident_off_rows_per_sec")
        if on is not None and off is not None:
            em[f"{tag}_resident_speedup"] = round(on / max(off, 1), 3)
    # context: each host<->device sync over the axon tunnel costs a full
    # network round trip; with N sequential pipeline stages the floor is
    # N*rtt regardless of device speed, so report the measured rtt
    try:
        import jax.numpy as jnp
        x = jnp.ones(8)
        float(jnp.sum(x) + 1.0)  # warm the EXACT timed expression
        t0 = time.perf_counter()
        float(jnp.sum(x) + 1.0)
        _result["sync_rtt_ms"] = round((time.perf_counter() - t0) * 1000, 1)
    except Exception:
        pass
    _emit(**({"note": note} if note else {}))


def _suite_child(platform: str) -> None:
    """Run the scale rig query-by-query, streaming a JSON line per query
    so a budget cutoff still leaves partial evidence; the final summary
    line is the geometric mean of per-query rows/s.  Each query embeds a
    pandas-oracle correctness check (scaletest.py), so a reported number
    is also a verified result."""
    import math

    from spark_rapids_tpu.testing import scaletest
    import spark_rapids_tpu as srt
    rows = ROWS
    # NOTE: `rows` is banked only once a query completes — _final() uses
    # its presence to distinguish a real measurement from a zero-progress
    # record, so the parent's CPU insurance fallback still applies when
    # the device wedges on every query
    _result.update(metric="scale_suite_geomean_rows_per_sec",
                   platform=platform, queries=0)
    rates = []
    for r in scaletest.iter_suite(rows):
        if "error" in r:
            sys.stdout.write(json.dumps(r) + "\n")
            sys.stdout.flush()
            continue
        r["rows_per_sec"] = round(rows / max(r["warm_seconds"], 1e-9))
        r["platform"] = platform
        if r.get("tables_bytes"):
            r["gb_per_s_per_chip"] = _gb_per_s(r["tables_bytes"],
                                               r["warm_seconds"])
        sys.stdout.write(json.dumps(r) + "\n")
        sys.stdout.flush()
        rates.append(r["rows_per_sec"])
        # keep the banked summary current so the watchdog emits progress
        if rates:
            geo = math.exp(sum(math.log(max(x, 1)) for x in rates)
                           / len(rates))
            _result.update(value=round(geo), vs_baseline=0.0,
                           queries=len(rates), rows=rows)
            _bank_partial()
    _emit()


# --------------------------------------------------------------------------
# parent: orchestrate device attempts against the CPU insurance run
# --------------------------------------------------------------------------

class _Child:
    """Subprocess whose stdout lines are collected by a reader thread, so
    the parent can wait with timeouts without blocking on readline."""

    def __init__(self, mode: str, deadline: float,
                 partial_path: str = None):
        env = dict(os.environ)
        env["BENCH_CHILD"] = mode
        env["BENCH_CHILD_DEADLINE"] = str(deadline)
        if partial_path:
            env["BENCH_PARTIAL_PATH"] = partial_path
        self.partial_path = partial_path
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        self.lines: queue.Queue = queue.Queue()
        t = threading.Thread(target=self._read, daemon=True)
        t.start()

    def _read(self):
        for raw in self.proc.stdout:
            line = raw.decode(errors="replace").strip()
            if line.startswith("{"):
                try:
                    self.lines.put(json.loads(line))
                except ValueError:
                    pass
        self.lines.put(None)  # EOF

    def next_record(self, timeout: float):
        """Next JSON record, or None on EOF/timeout."""
        try:
            return self.lines.get(timeout=max(timeout, 0.1))
        except queue.Empty:
            return None

    def pause(self):
        """SIGSTOP — the insurance run must not contend for host CPU while
        a device child runs its timed measurement (it would inflate the
        device child's pandas baseline and thus vs_baseline)."""
        import signal
        try:
            self.proc.send_signal(signal.SIGSTOP)
        except OSError:
            pass

    def resume(self):
        import signal
        try:
            self.proc.send_signal(signal.SIGCONT)
        except OSError:
            pass

    def kill(self):
        try:
            self.proc.kill()
        except OSError:
            pass


def _final(rec) -> bool:
    return bool(rec) and "value" in rec and rec.get("rows")


def _usable_capture_record(rec) -> bool:
    """Acceptance predicate for a banked capture's FINAL record — shared
    with tools/tunnel_watcher.sh (which imports bench and calls this to
    decide whether a capture cycle banked anything replayable): a real
    measurement (_final), on the device platform (the live chip registers
    as "axon" — anything non-CPU), and not itself a replay ("captured_at"
    marks those; replaying one would launder an old measurement under a
    fresh timestamp)."""
    return bool(_final(rec) and rec.get("platform") not in (None, "cpu")
                and "captured_at" not in rec)


def _load_capture():
    """Freshest tunnel-window capture matching this mode, if any.

    tools/tunnel_watcher.sh runs for the whole round and banks full bench
    runs under .bench_capture/ during live tunnel windows (VERDICT r3
    Missing #1: the tunnel is dead for whole rounds, including — three
    times now — at driver bench time; the watcher captures on-chip
    numbers whenever a window opens so they are never lost).  Returns
    (timestamp, [records]) where the last record is the final summary
    with platform == "tpu", or None.
    """
    import glob
    cap_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_capture")
    want = "suite" if SUITE else "main"
    # fall back to the warm run's numbers if the main run never finished
    patterns = [f"run_*_{want}.out"] + ([] if SUITE else ["run_*_warm.out"])
    for pat in patterns:
        for path in sorted(glob.glob(os.path.join(cap_dir, pat)),
                           reverse=True):
            recs = []
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line.startswith("{"):
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if "probe" not in rec:
                            recs.append(rec)
            except OSError:
                continue
            if recs and _usable_capture_record(recs[-1]):
                ts = os.path.basename(path).split("_")[1]
                if not SUITE:
                    _graft_extra_metrics(cap_dir, recs[-1])
                return ts, recs
    return None


def _graft_extra_metrics(cap_dir, final) -> None:
    """A watchdog-cut main run banks only the shapes that finished before
    the cut (cold remote compiles can eat most of a window's budget).
    Merge the MISSING extra-metric keys from every other on-chip capture
    in the round, newest first — the freshest measurement of each shape
    wins, and a partial newest capture no longer hides a more complete
    older one."""
    import glob
    extras = final.setdefault("extra_metrics", {})
    grafted_from = []
    for path in sorted(glob.glob(os.path.join(cap_dir, "run_*.out")),
                       reverse=True):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if not (_usable_capture_record(rec)
                            and rec.get("extra_metrics")):
                        continue
                    missing = {k: v
                               for k, v in rec["extra_metrics"].items()
                               if not k.startswith("_")
                               and k not in extras}
                    if missing:
                        extras.update(missing)
                        grafted_from.append(
                            os.path.basename(path).split("_")[1])
        except OSError:
            continue
    if grafted_from:
        extras["_grafted_from"] = grafted_from
    if not extras:
        del final["extra_metrics"]


def _await_final(child: _Child, deadline: float, attempt: int = 0):
    """Next non-per-query record; suite per-query lines stream straight
    through to stdout as they arrive, stamped with the attempt number so
    retried/fallback runs of the same query stay distinguishable."""
    while True:
        rec = child.next_record(deadline - time.time())
        if rec is None or "query" not in rec:
            return rec
        if attempt:
            rec["attempt"] = attempt
        print(json.dumps(rec), flush=True)


def _recover_partials(paths):
    """Best device-platform partial-artifact record from this run's cut
    attempts (newest first), with missing extra-metric keys grafted from
    the older ones — a watchdog/SIGKILL cut mid-run no longer loses the
    shapes that DID complete."""
    best = None
    for p in sorted(paths, reverse=True):
        rec = _read_partial(p)
        if not rec or rec.get("platform") in (None, "cpu"):
            continue
        if best is None:
            if _final(rec):
                best = rec
        elif rec.get("extra_metrics"):
            extras = best.setdefault("extra_metrics", {})
            for k, v in rec["extra_metrics"].items():
                extras.setdefault(k, v)
    return best


def orchestrate() -> None:
    t0 = time.time()
    deadline = t0 + BUDGET_S - 8  # leave room to print before driver cutoff
    probes = []
    cap_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_capture")
    try:
        os.makedirs(cap_dir, exist_ok=True)
    except OSError:
        cap_dir = "/tmp"

    def _partial_path(tag):
        return os.path.join(cap_dir, f"partial_{os.getpid()}_{tag}.json")

    # insurance: full measurement on the CPU platform, from t=0
    cpu_child = _Child("cpu", deadline - 4, _partial_path("cpu"))

    device_result = None
    dev_partials = []
    attempts = []  # structured per-attempt telemetry (srt-ledger bank)

    def _bank_attempt(at, outcome, rec=None):
        att = {"at": at, "outcome": outcome}
        for k in ("elapsed_ms", "platform", "error"):
            if rec and rec.get(k) is not None:
                att[k] = rec[k]
        attempts.append(att)

    attempt = 0
    prev_error = None
    while time.time() < deadline - (PROBE_S + 35):
        attempt += 1
        probe_t = _ts()
        dev = _Child("device", deadline - 4,
                     _partial_path(f"device{attempt}"))
        dev_partials.append(dev.partial_path)
        # phase 1: wait for the probe verdict (import + probe + slack),
        # clamped so a wedged child can never push us past the deadline
        rec = dev.next_record(min(PROBE_S + 60, deadline - time.time()))
        if rec is None:
            # child died/wedged before a probe verdict landed
            probes.append(f"{probe_t} timeout")
            _bank_attempt(probe_t, "timeout")
            dev.kill()
        elif rec.get("probe") in ("timeout", "refused", "hung"):
            # "hung" is the legacy spelling of timeout (pre-sentry child)
            outcome = ("timeout" if rec.get("probe") == "hung"
                       else rec["probe"])
            probes.append(f"{probe_t} {outcome}")
            _bank_attempt(probe_t, outcome, rec)
            dev.kill()
        elif rec.get("probe") == "degraded" or (
                rec.get("probe") == "ok" and rec.get("platform") == "cpu"):
            # the "device" child came up on the ambient CPU platform —
            # a dead tunnel in its fail-fast mode (TPU-plugin init error,
            # jax falls back to CPU).  Its measurement would duplicate
            # the insurance child, so kill it; two in a row means the
            # backend is deterministically CPU-only and retries are
            # pointless.
            probes.append(f"{probe_t} degraded")
            _bank_attempt(probe_t, "degraded", rec)
            dev.kill()
            if len(probes) >= 2 and probes[-2].endswith(" degraded"):
                break
        elif rec.get("probe") == "ok":
            probes.append(f"{probe_t} ok")
            _bank_attempt(probe_t, "ok", rec)
            # phase 2: device is answering — give it the rest of the
            # budget, and stop the insurance run from contending for CPU
            # while the device child times its pandas baseline
            cpu_child.pause()
            rec = _await_final(dev, deadline, attempt)
            if _final(rec):
                device_result = rec
                break
            dev.kill()
            cpu_child.resume()
            err = rec.get("note") if rec else None
            probes.append(f"{_ts()} error: {str(err)[:100]}" if err
                          else f"{_ts()} died mid-run")
            if err and err == prev_error:
                break  # deterministic engine failure — retries won't help
            prev_error = err
        else:
            # crashed before probing (e.g. import failure) — surface it
            dev.kill()
            err = rec.get("note", "unrecognized child record")
            probes.append(f"{probe_t} error: {str(err)[:100]}")
            _bank_attempt(probe_t, "refused",
                          {"error": str(err)[:200]})
            if err == prev_error:
                break
            prev_error = err
        # back off before hammering the tunnel again; probes are cheap but
        # a recovering backend needs a gap
        if time.time() < deadline - (PROBE_S + 90):
            time.sleep(min(10.0 + 5.0 * attempt, 60.0))

    if device_result is None:
        # a device attempt that died mid-run may still have banked shapes
        # into its partial artifact — a real current measurement cut
        # short beats both the CPU fallback and any old capture replay
        partial = _recover_partials(dev_partials)
        if partial is not None:
            partial["note"] = ((partial.get("note", "") + "; ").lstrip("; ")
                               + "recovered from partial artifact (device "
                               "run cut mid-measurement)")
            device_result = partial

    if device_result is not None and device_result.get("platform") != "cpu":
        cpu_child.kill()
        # structured per-attempt telemetry (outcome + elapsed_ms), not a
        # bare count: the sentry ledger and bench_diff both read it
        device_result["probe_attempts"] = attempts
        device_result["probe_timeline"] = probes
        # evidence class is first-class (ROADMAP item 5: stale replays
        # must never masquerade as results): this is a real measurement
        # from THIS round's live tunnel window
        device_result["evidence"] = "live"
        print(json.dumps(device_result), flush=True)
        return

    # before surrendering to the CPU insurance number: replay the
    # freshest on-chip capture the round-long tunnel watcher banked
    # during a live window, if one exists — real TPU numbers measured
    # hours ago beat CPU numbers measured now
    # (not when a probe succeeded ON the device: then the tunnel is alive
    # and the engine itself failed — replaying an old healthy number
    # would mask a live regression; let the CPU fallback carry the error
    # note.  "degraded" probes — jax fell back to the CPU platform —
    # count as a dead tunnel here.)
    # empty probes (budget too small for even one attempt) also replays:
    # a banked on-chip number beats a CPU fallback in every no-live-device
    # outcome except a probe that REACHED the device (live regression)
    if device_result is None \
            and not any(p.endswith(" ok") for p in probes):
        cap = _load_capture()
        if cap is not None:
            ts, recs = cap
            cpu_child.kill()
            for rec in recs[:-1]:
                rec["captured_at"] = ts
                print(json.dumps(rec), flush=True)
            final = recs[-1]
            final["captured_at"] = ts
            final["note"] = ((final.get("note", "") + "; ").lstrip("; ") +
                             "replayed tunnel-window capture from " + ts +
                             " (tunnel dead at driver bench time; probes: " +
                             ", ".join(probes) + ")")
            final["probe_timeline"] = probes
            final["probe_attempts"] = attempts
            # a replay is NOT a result from this round — say so loudly at
            # the top level, not only buried in the note (bench_diff.py
            # refuses live-vs-stale comparison without --allow-stale)
            final["evidence"] = "stale-replay"
            final["outcome"] = "NO-LIVE-TUNNEL-WINDOW: numbers replayed " \
                               "from capture " + ts
            print(json.dumps(final), flush=True)
            return

    # fall back to the insurance number (device_result is always None
    # here: CPU-platform device children are killed at probe time, and a
    # non-CPU result returned above)
    fallback = device_result
    if fallback is None:
        cpu_child.resume()
        while True:
            rec = _await_final(cpu_child, deadline)
            if rec is None:
                break
            if _final(rec):
                fallback = rec
                break
    if fallback is None and cpu_child.partial_path:
        # even the insurance child got cut: its partial artifact still
        # carries whatever it banked before the deadline
        rec = _read_partial(cpu_child.partial_path)
        if _final(rec):
            fallback = rec
    cpu_child.kill()
    if fallback is None:
        fallback = {"metric": "tpch_q1_like_rows_per_sec", "value": 0,
                    "unit": "rows/s", "vs_baseline": 0.0}
    fallback["probe_timeline"] = probes
    fallback["probe_attempts"] = attempts
    fallback["evidence"] = "cpu-fallback"
    fallback["outcome"] = ("NO-LIVE-TUNNEL-WINDOW: CPU-platform "
                           "insurance numbers, not device evidence")
    if probes and all(p.endswith(" degraded") for p in probes):
        note = ("no TPU backend (jax fell back to the CPU platform); "
                "CPU-platform numbers; probes: " + ", ".join(probes))
    elif not probes:
        note = "no device attempt fit the budget; CPU-platform numbers"
    elif any(p.endswith(" ok") for p in probes):
        note = ("device answered probes but no measurement completed; "
                "CPU-platform fallback numbers; probes: " + ", ".join(probes))
    else:
        note = ("device backend unresponsive; CPU-platform fallback "
                "numbers; probes: " + ", ".join(probes))
    fallback["note"] = note
    print(json.dumps(fallback), flush=True)


if __name__ == "__main__":
    mode = os.environ.get("BENCH_CHILD")
    if mode:
        try:
            child_main(mode)
        except BaseException as e:
            _emit(note=f"unexpected failure: {type(e).__name__}: {e}")
        os._exit(0)  # don't hang on stray non-daemon backend threads
    else:
        try:
            orchestrate()
        except BaseException as e:
            _emit(note=f"orchestrator failure: {type(e).__name__}: {e}")
        os._exit(0)
