#!/usr/bin/env bash
# CI harness — the analog of the reference's jenkins/spark-premerge-build.sh
# + spark-tests.sh pipeline (SURVEY §2.11): build native libs, validate the
# API contract, regenerate docs (drift check), run the unit+integration
# suite on the virtual 8-device CPU mesh, run the scale rig, and finish
# with the driver entry checks (single-chip compile + multichip dryrun).
#
# Usage: ci/run_ci.sh [quick]
#   quick = skip the scale rig and use -x fail-fast on the suite.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

echo "=== [1/20] native libraries ==="
make -C native

echo "=== [2/20] API contract validation ==="
timeout 300 python tools/api_validation.py

echo "=== [3/20] docgen drift check ==="
timeout 300 python -m spark_rapids_tpu.docgen
if ! git diff --quiet -- docs tools/generated_files 2>/dev/null; then
    echo "WARNING: generated docs drifted from the committed copies:"
    git --no-pager diff --stat -- docs tools/generated_files || true
fi

echo "=== [4/20] traced query + chrome-trace schema check ==="
SRT_TRACE_OUT=$(mktemp -d)/trace.json
JAX_PLATFORMS=cpu timeout 300 python - "$SRT_TRACE_OUT" <<'PYEOF'
import sys
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
sess = srt.session(**{"spark.rapids.tpu.profile.enabled": True})
rng = np.random.default_rng(3)
n = 50_000
fact = sess.create_dataframe(pa.table(
    {"fk": rng.integers(0, 1000, n), "x": rng.random(n)}), num_partitions=2)
dim = sess.create_dataframe(pa.table(
    {"pk": np.arange(1000, dtype=np.int64), "cat": rng.integers(0, 8, 1000)}))
out = (fact.join(dim, fact.fk == dim.pk, "inner").groupBy("cat")
       .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
       .orderBy("cat")).collect()
assert out.num_rows == 8, out.num_rows
summary = sess.last_query_trace_summary
assert summary and summary["sync_count"] >= 1, summary
print("trace summary:", summary)
print(sess.profile_last_query())
sess.export_chrome_trace(sys.argv[1])
PYEOF
timeout 60 python tools/check_trace.py --min-events 10 "$SRT_TRACE_OUT"

echo "=== [5/20] performance flight recorder: metrics + history + doctor + bench_diff ==="
# ISSUE 8 acceptance: a traced query with the metrics registry and the
# flight recorder enabled must produce (a) a Prometheus export that
# passes the exposition-contract check, (b) a doctor diagnosis whose
# JSON passes the srt-doctor/1 schema check with a named verdict, and
# (c) a query_history record carrying the plan fingerprint + trace
# summary.  bench_diff then diffs the two banked round artifacts as a
# sentinel smoke test (same evidence class: both stale replays), and
# must REFUSE a live-vs-stale comparison without --allow-stale.
SRT_FR_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu timeout 300 python - "$SRT_FR_DIR" <<'PYEOF'
import sys, json, os
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
out = sys.argv[1]
sess = srt.session(**{"spark.rapids.tpu.metrics.enabled": True,
                      "spark.rapids.tpu.profile.enabled": True,
                      "spark.rapids.tpu.history.path":
                          os.path.join(out, "history.jsonl")})
rng = np.random.default_rng(3)
n = 50_000
fact = sess.create_dataframe(pa.table(
    {"fk": rng.integers(0, 1000, n), "x": rng.random(n)}), num_partitions=2)
dim = sess.create_dataframe(pa.table(
    {"pk": np.arange(1000, dtype=np.int64), "cat": rng.integers(0, 8, 1000)}))
q = (fact.join(dim, fact.fk == dim.pk, "inner").groupBy("cat")
     .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
     .orderBy("cat"))
assert q.collect().num_rows == 8
with open(os.path.join(out, "metrics.prom"), "w") as fh:
    fh.write(sess.metrics_prometheus())
snap = sess.metrics_snapshot()
assert any(c["name"] == "device_dispatches_total" for c in snap["counters"])
diag = sess.diagnose_last_query()
with open(os.path.join(out, "doctor.json"), "w") as fh:
    json.dump(diag, fh, indent=1)
print("doctor verdict:", diag["verdict"],
      [r["category"] for r in diag["ranked"][:3]])
hist = sess.query_history(1)
assert hist and hist[0]["plan_fingerprint"] and hist[0]["trace_summary"]
from spark_rapids_tpu.observability.history import read_history_file
assert read_history_file(os.path.join(out, "history.jsonl"))
print("flight recorder OK:", hist[0]["plan_fingerprint"],
      f"{hist[0]['duration_ms']:.0f}ms")
PYEOF
timeout 60 python tools/check_trace.py \
    --prometheus "$SRT_FR_DIR/metrics.prom" \
    --doctor "$SRT_FR_DIR/doctor.json"
# sentinel smoke: diff the two banked rounds (both stale replays -> same
# evidence class, allowed); then prove the live-vs-stale gate refuses
timeout 60 python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
printf '{"metric":"x","value":9,"rows":1,"platform":"tpu","evidence":"live"}' \
    > "$SRT_FR_DIR/live.json"
if python tools/bench_diff.py "$SRT_FR_DIR/live.json" BENCH_r05.json \
        >/dev/null 2>&1; then
    echo "ERROR: bench_diff failed to refuse live-vs-stale"; exit 1
fi

echo "=== [6/20] chaos soak: seeded faults, bit-identical results ==="
# Short seeded soak (docs/robustness.md): shuffle.fetch + spill.disk_read
# (and the other recoverable sites) armed over the TPC-H-ish suite; the
# harness itself asserts bit-identical results vs the clean run and that
# shuffleFetchRetries / shuffleBlocksRecomputed surfaced in
# last_query_metrics.  The exported trace must carry `fault` spans.
SRT_CHAOS_TRACE=$(mktemp -d)/chaos_trace.json
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    20000 --seed 11 --trace "$SRT_CHAOS_TRACE"
timeout 60 python tools/check_trace.py --require-cat fault \
    "$SRT_CHAOS_TRACE"

echo "=== [7/20] pipelined chaos soak: parallelism=4 + prefetch, bit-identical ==="
# The async execution layer (docs/async_pipeline.md) under seeded faults:
# the chaos session runs with task.parallelism=4 + prefetch queues +
# double-buffered transfers while the clean reference run stays serial —
# results must be bit-identical even when injected faults surface on
# prefetch producer / transfer stager / pool worker threads.  The
# exported trace must carry sem_wait spans (pool contention on the
# device semaphore) and still pass the schema check.
SRT_PIPE_TRACE=$(mktemp -d)/pipeline_trace.json
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    20000 --seed 11 --pipeline --trace "$SRT_PIPE_TRACE"
timeout 60 python tools/check_trace.py --require-cat sem_wait \
    "$SRT_PIPE_TRACE"

echo "=== [8/20] encoded chaos soak: encoding x parallelism 4 x prefetch ==="
# Encoded columnar execution (docs/encoded_columns.md) under seeded
# faults AND the async pipeline matrix: the chaos session keeps
# dictionary/RLE columns encoded through filters/joins/group-bys and
# the shuffle wire while running parallelism=4 + prefetch queues +
# double-buffered transfers; the clean reference run stays RAW and
# serial — results must be bit-identical, proving encoded frames
# (narrowed codes + dictionaries/refs) survive fetch retries, destroyed
# blocks, and lost-block recompute on pool/prefetch threads.  The
# exported trace must carry `encode` spans (scan-side dictionary
# encodes).  A second short SERIAL encoded soak covers the
# pipeline-off leg of the matrix.
SRT_ENC_TRACE=$(mktemp -d)/encoded_trace.json
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    20000 --seed 11 --encoded --pipeline --trace "$SRT_ENC_TRACE"
timeout 60 python tools/check_trace.py --require-cat encode \
    "$SRT_ENC_TRACE"
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    8000 --seed 11 --encoded

echo "=== [9/20] whole-stage fusion: plan shape + donation chaos soak ==="
# Whole-stage XLA compilation (docs/whole_stage.md): (a) the TPC-H-ish
# suite's plans must contain fused whole-stage nodes — an aggregate
# terminal (FusedStageExec wrapping the partial agg) and a probe-absorbed
# hash join; (b) the chaos soak runs with whole-stage + donation forced
# ON against a serial UNFUSED clean baseline, bit-identical under
# injected faults, and its trace must carry `stage` spans.
JAX_PLATFORMS=cpu timeout 300 python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical.fusion import FusedStageExec
from spark_rapids_tpu.sql.physical.aggregate import HashAggregateExec
from spark_rapids_tpu.sql.physical.join import BaseJoinExec

def find(plan, pred):
    out, stack = [], [plan]
    while stack:
        n = stack.pop()
        if pred(n):
            out.append(n)
        stack.extend(n.children)
    return out

sess = srt.session()
rng = np.random.default_rng(3)
n = 50_000
fact = sess.create_dataframe(pa.table(
    {"fk": rng.integers(0, 1000, n), "q": rng.integers(0, 100, n),
     "x": rng.random(n)}), num_partitions=4)
dim = sess.create_dataframe(pa.table(
    {"pk": np.arange(1000, dtype=np.int64),
     "cat": rng.integers(0, 8, 1000)}))
# q1-ish: scan -> filter -> project -> partial agg must plan as ONE
# FusedStageExec with a HashAggregate terminal
q1 = (fact.filter(F.col("q") < 50).withColumn("y", F.col("x") * 2.0)
      .groupBy("q").agg(F.sum(F.col("y")).alias("sy")))
p1 = sess.physical_plan(q1)
stages = find(p1, lambda m: isinstance(m, FusedStageExec)
              and isinstance(m.terminal, HashAggregateExec))
assert stages, "no aggregate-terminal whole-stage node:\n" + p1.tree_string()
# q3-ish: the broadcast join must absorb the probe-side chain
q2 = (fact.filter(F.col("q") < 30).join(dim, fact.fk == dim.pk, "inner"))
p2 = sess.physical_plan(q2)
joins = find(p2, lambda m: isinstance(m, BaseJoinExec))
assert joins and joins[0]._probe_steps, \
    "probe chain not absorbed:\n" + p2.tree_string()
print("plan-shape OK:", stages[0].simple_string())
print("plan-shape OK:", joins[0].simple_string())
PYEOF
SRT_WS_TRACE=$(mktemp -d)/whole_stage_trace.json
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    20000 --seed 11 --whole-stage --trace "$SRT_WS_TRACE"
timeout 60 python tools/check_trace.py --require-cat stage \
    "$SRT_WS_TRACE"

echo "=== [10/20] dispatch pipeline: sort/window terminals + fused probe + coalescer ==="
# ISSUE 14 acceptance: (a) plans form sort/window STAGE TERMINALS (the
# sort absorbs the map chain; a window over a matching sort absorbs the
# sort) and the broadcast join still absorbs its probe chain with the
# fused single-program probe armed; (b) the chaos soak runs with the
# full dispatch set armed (coalescer + terminals + fused probe) vs the
# serial unfused clean baseline, bit-identical under injected faults;
# (c) a traced coalesced stage run exports `stage` spans carrying
# `coalesced_n`, validated by check_trace --require-cat stage.
JAX_PLATFORMS=cpu timeout 300 python - <<'PYEOF'
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.window_api import Window as W
from spark_rapids_tpu.sql.physical.join import BaseJoinExec
from spark_rapids_tpu.sql.physical.sortlimit import SortExec
from spark_rapids_tpu.sql.physical.window import WindowExec

def find(plan, pred):
    out, stack = [], [plan]
    while stack:
        n = stack.pop()
        if pred(n):
            out.append(n)
        stack.extend(n.children)
    return out

sess = srt.session()
rng = np.random.default_rng(5)
n = 40_000
fact = sess.create_dataframe(pa.table(
    {"k": rng.integers(0, 16, n), "q": rng.integers(0, 100, n),
     "x": rng.random(n), "fk": rng.integers(0, 500, n)}))
dim = sess.create_dataframe(pa.table(
    {"pk": np.arange(500, dtype=np.int64),
     "cat": rng.integers(0, 8, 500)}))
# sort terminal: the ORDER BY absorbs the map chain into its program
q1 = (fact.filter(F.col("q") < 60).withColumn("y", F.col("x") * 2.0)
      .orderBy("k", "y"))
p1 = sess.physical_plan(q1)
sorts = find(p1, lambda m: isinstance(m, SortExec) and m._pre_steps)
assert sorts, "no sort-terminal stage:\n" + p1.tree_string()
# window terminal: the window absorbs its partition sort (and the sort
# absorbs the chain below it)
w = W.partitionBy("k").orderBy("q")
q2 = (fact.filter(F.col("q") < 60).withColumn("y", F.col("x") * 2.0)
      .withColumn("rn", F.row_number().over(w)))
p2 = sess.physical_plan(q2)
wins = find(p2, lambda m: isinstance(m, WindowExec)
            and m._sorter is not None)
assert wins, "no window-terminal stage:\n" + p2.tree_string()
# fused probe: the join still absorbs the probe-side chain
q3 = (fact.filter(F.col("q") < 30).join(dim, fact.fk == dim.pk, "inner"))
p3 = sess.physical_plan(q3)
joins = find(p3, lambda m: isinstance(m, BaseJoinExec))
assert joins and joins[0]._probe_steps, \
    "probe chain not absorbed:\n" + p3.tree_string()
print("plan-shape OK:", sorts[0].simple_string())
print("plan-shape OK:", wins[0].simple_string())
print("plan-shape OK:", joins[0].simple_string())
PYEOF
SRT_CO_TRACE=$(mktemp -d)/coalesce_trace.json
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    20000 --seed 11 --coalesce --trace "$SRT_CO_TRACE"
timeout 60 python tools/check_trace.py --require-cat stage \
    "$SRT_CO_TRACE"
# coalesced stage spans: drive a stage over a multi-batch stream (the
# exec-level harness tests/test_dispatch_budget.py pins) and assert the
# exported trace carries `coalesced_n` on a `stage` span
SRT_CON_TRACE=$(mktemp -d)/coalesced_n_trace.json
JAX_PLATFORMS=cpu SRT_CON_TRACE="$SRT_CON_TRACE" timeout 300 python - <<'PYEOF'
import json, os
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical.base import TaskContext
from spark_rapids_tpu.sql.physical.fusion import FusedStageExec
from spark_rapids_tpu.observability import tracer as OT

sess = srt.session()
rng = np.random.default_rng(7)
tab = pa.table({"k": rng.integers(0, 9, 512), "v": rng.random(512)})
df = (sess.create_dataframe(tab).filter(F.col("v") < 0.8)
      .withColumn("y", F.col("v") * 2.0).select("k", "y"))
plan = sess.physical_plan(df)
stack, stage = [plan], None
while stack:
    m = stack.pop()
    if isinstance(m, FusedStageExec):
        stage = m
        break
    stack.extend(m.children)
assert stage is not None, plan.tree_string()
inner = stage.children[0]

class Stub:
    output = inner.output
    children = ()
    def execute(self, pid, tctx):
        for _ in range(4):
            yield from inner.execute(pid, tctx)
    def num_partitions(self):
        return 1

stage.children = (Stub(),)
OT.get_tracer().reset(2048)
OT.TRACING["on"] = True
tctx = TaskContext(0, RapidsConf.get_global())
with tctx.as_current():
    outs = list(stage.execute(0, tctx))
events = OT.get_tracer().snapshot()
OT.TRACING["on"] = False
spans = [e for e in events if e.get("cat") == "stage"
         and (e.get("args") or {}).get("coalesced_n")]
assert spans, events
assert spans[0]["args"]["coalesced_n"] == 4, spans[0]
doc = {"traceEvents": [
    {"ph": "X", "cat": e["cat"], "name": e["name"], "ts": e["ts"],
     "dur": e["dur"], "pid": 1, "tid": e.get("tid", 0),
     "args": e.get("args") or {}} for e in events]}
with open(os.environ["SRT_CON_TRACE"], "w") as fh:
    json.dump(doc, fh)
print("coalesced_n span OK:", spans[0]["args"])
PYEOF
timeout 60 python tools/check_trace.py --require-cat stage \
    "$SRT_CON_TRACE"
grep -q coalesced_n "$SRT_CON_TRACE"

echo "=== [11/20] multi-tenant serving: concurrent sessions smoke ==="
# ISSUE 9 acceptance: N tenant sessions against one ServingEngine —
# (a) weighted-fair admission: a heavy flood cannot starve a light
# tenant (bounded wait, grant-order assertion at the controller);
# (b) cross-query result cache: a repeated query is served from the
# cache (hit counter) bit-identically; (c) the engine trace carries
# tenant-labeled spans and the Prometheus export carries the `tenant`
# label with zero dropped series (maxSeries bound respected); and the
# multi-session chaos soak proves bit-identical results for every
# tenant under injected faults.
SRT_SERVE_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu timeout 600 python - "$SRT_SERVE_DIR" <<'PYEOF'
import sys, os, json, threading
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.serving import AdmissionController, ServingEngine
from spark_rapids_tpu.serving import result_cache as RC
out = sys.argv[1]

# (a) admission fairness: heavy floods 8, light submits 2, one slot —
# with equal weights the light tenant's grants interleave near the front
ctrl = AdmissionController(max_concurrent=1)
blocker = ctrl.acquire("blocker")
order = []
def w(t):
    tk = ctrl.acquire(t); order.append(t); ctrl.release(tk)
ths = [threading.Thread(target=w, args=(t,))
       for t in ["heavy"]*8 + ["light"]*2]
[t.start() for t in ths]
import time
while ctrl.snapshot()["queued"] < 10: time.sleep(0.005)
ctrl.release(blocker)
[t.join(30) for t in ths]
pos = [i for i, t in enumerate(order) if t == "light"]
assert pos[0] <= 2 and pos[1] <= 4, f"light tenant starved: {order}"
print("admission fairness OK: light granted at", pos)

# (b)+(c) engine with result cache + metrics + tracing, 2 tenants
RC.clear()
eng = ServingEngine(**{
    "spark.rapids.tpu.metrics.enabled": True,
    "spark.rapids.tpu.profile.enabled": True,
    "spark.rapids.tpu.serving.resultCache.enabled": True,
    "spark.rapids.tpu.serving.broadcastShare.enabled": True,
    "spark.rapids.tpu.serving.maxConcurrentQueries": 2})
rng = np.random.default_rng(3)
n = 30_000
fact_t = pa.table({"fk": rng.integers(0, 100, n), "x": rng.random(n)})
dim_t = pa.table({"pk": np.arange(100, dtype=np.int64),
                  "cat": rng.integers(0, 8, 100)})
def q(sess):
    fact = sess.create_dataframe(fact_t, num_partitions=2)
    dim = sess.create_dataframe(dim_t)
    return (fact.join(dim, fact.fk == dim.pk, "inner").groupBy("cat")
            .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
            .orderBy("cat")).collect()
res = {}
def tenant_worker(t):
    s = eng.session(tenant=t)
    res[t] = [q(s), q(s)]
ths = [threading.Thread(target=tenant_worker, args=(f"t{i}",))
       for i in range(2)]
[t.start() for t in ths]; [t.join(120) for t in ths]
assert res["t0"][0].equals(res["t1"][0]), "cross-tenant parity"
assert res["t0"][0].equals(res["t0"][1]), "repeat parity"
rcs = RC.stats()
assert rcs["hits"] >= 2, f"result cache never hit: {rcs}"
print("result cache OK:", {k: rcs[k] for k in ("hits", "misses", "stores")})
hist = eng.query_history()
assert {r.get("tenant") for r in hist} == {"t0", "t1"}
diag = eng.diagnose_tenants()
assert set(diag) == {"t0", "t1"}
print("per-tenant verdicts:",
      {t: d["diagnosis"]["verdict"] for t, d in diag.items()})
snap = eng.metrics_snapshot()
assert snap["dropped_series"] == 0, "tenant label blew the maxSeries bound"
with open(os.path.join(out, "serving.prom"), "w") as fh:
    fh.write(eng.metrics_prometheus())
eng.export_chrome_trace(os.path.join(out, "serving_trace.json"))
eng.close()
print("serving smoke OK: admission", eng.admission_stats()["admitted"],
      "admitted,", len(hist), "history records")
PYEOF
timeout 60 python tools/check_trace.py --require-cat admission \
    --require-arg tenant "$SRT_SERVE_DIR/serving_trace.json" \
    --prometheus "$SRT_SERVE_DIR/serving.prom" --prometheus-label tenant
# multi-session chaos soak: >=2 tenants concurrently under faults,
# every tenant bit-identical to the serial clean run
JAX_PLATFORMS=cpu timeout 600 python -m spark_rapids_tpu.testing.chaos \
    10000 --seed 11 --multi-session

echo "=== [12/20] query lifecycle: leak sentinel + cancel semantics ==="
# ISSUE 10 acceptance: (a) the bounded leak sentinel — 2 tenants of
# mixed traffic with cancel races, per-query deadlines and fatal
# injection armed — must bank a CLEAN verdict (retention pins, catalog
# handles and registry cardinality return to the healthy baseline after
# the armed waves); (b) a deadline-cancelled traced query must export
# `cancel`-category spans (the issue->drained evidence the bench
# lifecycle phase banks as p50/p99) and leave zero held semaphore
# permits or live query contexts.
SRT_LC_DIR=$(mktemp -d)
# --sentry rides along (ISSUE 18): a fast-cadence sentry runs its full
# probe->bench->diff->ledger cycle beside the tenant soak and must
# leave no srt-sentry threads or probe QueryContexts after stop
JAX_PLATFORMS=cpu timeout 600 python tools/leak_sentinel.py \
    --seconds 45 --tenants 2 --rows 6000 --sentry \
    --out "$SRT_LC_DIR/leak.json"
JAX_PLATFORMS=cpu timeout 300 python - "$SRT_LC_DIR" <<'PYEOF'
import sys, threading, time
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.serving import lifecycle as lc
from spark_rapids_tpu.sql import functions as F
out = sys.argv[1]
sess = srt.session(**{"spark.rapids.tpu.profile.enabled": True,
                      "spark.rapids.tpu.task.parallelism": 4})
rng = np.random.default_rng(3)
n = 200_000
fact = sess.create_dataframe(pa.table(
    {"fk": rng.integers(0, 1000, n), "x": rng.random(n)}),
    num_partitions=8)
dim = sess.create_dataframe(pa.table(
    {"pk": np.arange(1000, dtype=np.int64),
     "cat": rng.integers(0, 8, 1000)}))
q = (fact.join(dim, fact.fk == dim.pk, "inner").groupBy("cat")
     .agg(F.count("*").alias("n"), F.sum(F.col("x")).alias("sx"))
     .orderBy("cat"))
assert q.collect().num_rows == 8  # warm compiles
timer = threading.Timer(0.02, sess.cancel)
timer.start()
try:
    q.collect()
    raise SystemExit("ERROR: cancel did not interrupt the query")
except lc.QueryCancelled:
    pass
finally:
    timer.cancel()
assert sess.last_cancel_latency_ms is not None
print(f"cancel drained in {sess.last_cancel_latency_ms:.1f}ms")
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
assert TpuSemaphore.get().active_tasks() == 0
assert not lc.live_queries()
sess.export_chrome_trace(out + "/cancel_trace.json")
PYEOF
timeout 60 python tools/check_trace.py --require-cat cancel \
    "$SRT_LC_DIR/cancel_trace.json"

echo "=== [13/20] pod-scale fault domain: process-kill chaos cluster ==="
# ISSUE 19 acceptance: a REAL 3-process shuffle topology survives a
# seeded SIGKILL mid-query (failure detection -> immediate failover ->
# lineage recompute, bit-identical to the no-fault digest) AND the
# zombie scenario (SIGSTOP past deadMs, re-registration bumps the
# fencing epoch, SIGCONT resumes the stale process) proves epoch
# fencing for real: zero stale blocks served, recovery bit-identical.
# The merged per-process traces must carry `fault`-category spans
# (peer.dead / fetch.failover / shuffle.recompute evidence).
SRT_CHAOS_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu timeout 600 python tools/chaos_cluster.py \
    --procs 3 --seed 7 --scenario sigkill --scenario zombie \
    --out "$SRT_CHAOS_DIR"
timeout 60 python tools/trace_merge.py "$SRT_CHAOS_DIR/merged.json" \
    "$SRT_CHAOS_DIR"/*/*.jsonl
timeout 60 python tools/check_trace.py --require-cat fault \
    --min-events 2 "$SRT_CHAOS_DIR/merged.json"
# the cluster leg of the leak sentinel: a kill/recover cycle must drain
# every heartbeat thread and fault-domain table at manager close
JAX_PLATFORMS=cpu timeout 300 python tools/leak_sentinel.py \
    --seconds 6 --rows 2000 --cluster \
    --out "$SRT_CHAOS_DIR/cluster_leak.json"

echo "=== [14/20] live telemetry plane: scrape + trace stitching over the shuffle wire ==="
# ISSUE 12 acceptance: (a) the embedded telemetry server answers
# /metrics (Prometheus contract with the tenant label, validated both
# from the scraped body and live via check_trace --endpoint) and
# /healthz WHILE tenant queries are in flight, and a degraded engine
# flips /healthz to 503; (b) a genuine two-process traced shuffle read
# leaves a requester fetch span in the driver's ring and a serve span
# under the SAME trace id in the peer process's ring; trace_merge.py
# merges the two event logs into one Perfetto trace whose
# cross-process flow events pass check_trace --flow; (c) engine close
# releases the port and the serve thread (leak-free).
SRT_TP_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu timeout 600 python - "$SRT_TP_DIR" <<'PYEOF'
import json, os, socket, subprocess, sys, threading
import urllib.error, urllib.request
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.observability import tracer as OT
from spark_rapids_tpu.observability.export import write_event_log
from spark_rapids_tpu.serving import ServingEngine
from spark_rapids_tpu.shuffle.manager import ShuffleManager
from spark_rapids_tpu.shuffle.tcp import TcpHeartbeatServer
from spark_rapids_tpu.sql import functions as F
out = sys.argv[1]

CHILD = r'''
import sys
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, pyarrow as pa
import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar.convert import arrow_to_device
from spark_rapids_tpu.observability import tracer as OT
from spark_rapids_tpu.observability.export import write_event_log
from spark_rapids_tpu.shuffle.manager import ShuffleManager
elog, driver = sys.argv[1], sys.argv[2]
OT.get_tracer().reset(session="peer-proc")
OT.TRACING["on"] = True
conf = srt.RapidsConf.get_global().copy({
    "spark.rapids.shuffle.mode": "ICI",
    "spark.rapids.shuffle.transport.type": "TCP",
    "spark.rapids.shuffle.tcp.native.enabled": False,
    "spark.rapids.shuffle.tcp.driverEndpoint": driver,
})
m = ShuffleManager(conf, executor_id="peer-exec")
rng = np.random.default_rng(7)
t = pa.table({"k": rng.integers(0, 8, 512), "v": rng.random(512)})
m.write_map_output(9, 0, [arrow_to_device(t)])
print("READY", flush=True)
sys.stdin.readline()   # parent fetched: dump the serve-side ring
tr = OT.get_tracer()
write_event_log(elog, tr.snapshot(), tr.meta())
m.close()
'''

srv = TcpHeartbeatServer()
child = subprocess.Popen(
    [sys.executable, "-c", CHILD, os.path.join(out, "peer.jsonl"),
     srv.endpoint],
    stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
    env=dict(os.environ, JAX_PLATFORMS="cpu"))
assert child.stdout.readline().strip() == "READY"

eng = ServingEngine(**{
    "spark.rapids.tpu.metrics.enabled": True,
    "spark.rapids.tpu.profile.enabled": True,
    "spark.rapids.tpu.telemetry.enabled": True,
    "spark.rapids.tpu.telemetry.port": 0})
host, port = eng.telemetry.host, eng.telemetry.port
base = eng.telemetry.endpoint

def get(route):
    try:
        with urllib.request.urlopen(base + route, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()

# (a) scrape mid-workload: the main thread hits every route while the
# worker still has tenant queries left to run
sess = eng.session(tenant="t0")
first_done = threading.Event()
def work():
    rng = np.random.default_rng(3)
    for i in range(3):
        df = sess.create_dataframe(pa.table(
            {"k": rng.integers(0, 8, 20_000),
             "x": rng.random(20_000)}), num_partitions=2)
        assert (df.groupBy("k").agg(F.sum(F.col("x")).alias("sx"))
                .orderBy("k")).collect().num_rows == 8
        first_done.set()
w = threading.Thread(target=work)
w.start()
assert first_done.wait(180)
st, body = get("/metrics")
assert st == 200 and "srt_" in body, (st, body[:200])
with open(os.path.join(out, "scrape.prom"), "w") as fh:
    fh.write(body)
st, hz = get("/healthz")
assert st == 200 and json.loads(hz)["status"] == "ok", (st, hz)
for route in ("/queries", "/doctor", "/slo"):
    st, b = get(route)
    assert st == 200, (route, st, b[:200])
    json.loads(b)
sys.path.insert(0, "tools")
import check_trace
assert check_trace.main(["--endpoint", base + "/metrics"]) == 0
w.join(180)

# (b) two-process traced shuffle read through the engine-armed tracer
conf = srt.RapidsConf.get_global().copy({
    "spark.rapids.shuffle.mode": "ICI",
    "spark.rapids.shuffle.transport.type": "TCP",
    "spark.rapids.shuffle.tcp.native.enabled": False,
    "spark.rapids.shuffle.tcp.driverEndpoint": srv.endpoint,
})
mp = ShuffleManager(conf, executor_id="driver-exec")
got = mp.read_reduce_partition(9, num_maps=1, reduce_id=0)
assert got is not None and got.num_rows_int == 512
mp.close()
tr = OT.get_tracer()
evs = tr.snapshot()
assert any(e["name"] == "shuffle.fetch.remote" for e in evs), \
    sorted({e["name"] for e in evs})
write_event_log(os.path.join(out, "driver.jsonl"), evs, tr.meta())
child.stdin.write("done\n"); child.stdin.flush()
assert child.wait(60) == 0

# (c) degraded -> 503; close -> port free, serve thread gone
eng.note_fatal(RuntimeError("injected for CI"), fingerprint="",
               tenant="t0")
st, hz = get("/healthz")
assert st == 503 and json.loads(hz)["status"] == "degraded", (st, hz)
eng.close()
probe = socket.socket()
probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
probe.bind((host, port))
probe.close()
assert not [t for t in threading.enumerate()
            if t.name.startswith("srt-telemetry-")]
srv.close()
print("telemetry plane OK:", base)
PYEOF
timeout 60 python tools/check_trace.py \
    --prometheus "$SRT_TP_DIR/scrape.prom" --prometheus-label tenant
timeout 60 python tools/trace_merge.py "$SRT_TP_DIR/merged.json" \
    "$SRT_TP_DIR/driver.jsonl" "$SRT_TP_DIR/peer.jsonl"
timeout 60 python tools/check_trace.py --flow "$SRT_TP_DIR/merged.json" \
    --min-events 2 "$SRT_TP_DIR/merged.json"

echo "=== [15/20] perf sentry: simulated-window e2e + evidence ledger ==="
# ISSUE 18 acceptance: the self-driving sentry, run unattended from
# tools/perf_sentry.py in simulated-window mode, must (a) append
# well-formed srt-ledger/1 records — artifact path on disk, evidence
# live, a bench_diff verdict against the auto-resolved live baseline,
# the doctor's ranked verdict and a machine-named follow-up with
# quantified lever evidence; (b) serve /sentry (srt-sentry/1) through
# the telemetry server and export srt_sentry_* registry series; (c)
# bench_diff --ledger must resolve the same live baseline from the CLI.
SRT_SENTRY_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu timeout 600 python tools/perf_sentry.py \
    --simulate-window --windows 2 --shapes sort --rows 4000 \
    --budget-s 120 --ledger "$SRT_SENTRY_DIR/ledger.jsonl" --json
JAX_PLATFORMS=cpu timeout 300 python - "$SRT_SENTRY_DIR" <<'PYEOF'
import json, os, sys, urllib.request
import jax; jax.config.update("jax_platforms", "cpu")
from spark_rapids_tpu.observability import sentry as S
from spark_rapids_tpu.observability.metrics import get_registry
from spark_rapids_tpu.observability.server import TelemetryServer
out = sys.argv[1]
led = S.EvidenceLedger(os.path.join(out, "ledger.jsonl"))
entries = led.entries()
assert len(entries) == 2, len(entries)
for e in entries:
    assert e["schema"] == "srt-ledger/1"
    assert e["evidence"] == "live", e
    assert os.path.exists(e["artifact"]), e["artifact"]
    assert e["doctor"]["verdict"], e
    assert e["followup"], e
# the second window's diff baseline is the FIRST window's artifact,
# auto-resolved from the ledger as the newest live-evidence entry
assert entries[1]["diff"]["baseline"] == entries[0]["artifact"], \
    entries[1]["diff"]
assert entries[1]["diff"]["verdict"] in ("ok", "regressed")
fu = entries[1]["followup"]
assert fu.startswith("STALE-EVIDENCE") or "; lever: " in fu, fu
# /sentry route contract + srt_sentry_* registry series, served live
s = S.PerfSentry(probe=lambda: {"outcome": "refused", "elapsed_ms": 0.1},
                 ledger=led.path)
s.run_once()   # closed window: banks probe telemetry, no capture
S.set_active(s)
srv = TelemetryServer(
    metrics_text=lambda: get_registry().prometheus_text(),
    healthz=lambda: (True, {}), queries=lambda: [],
    doctor=lambda: {}, slo=lambda: {})
sys.path.insert(0, "tools")
import check_trace
assert check_trace.main(["--endpoint", srv.endpoint + "/sentry"]) == 0
doc = json.loads(urllib.request.urlopen(
    srv.endpoint + "/sentry", timeout=10).read())
assert doc["schema"] == "srt-sentry/1"
assert doc["ledger"]["entries"] == 2, doc["ledger"]
assert doc["last_live_age_s"] is not None
assert "srt_sentry_probe_attempts_total" in get_registry().prometheus_text()
srv.close(); S.set_active(None)
print("sentry e2e OK:", led.path)
PYEOF
SRT_SENTRY_FRESH=$(JAX_PLATFORMS=cpu timeout 60 python -c "
import json, sys
lines = open('$SRT_SENTRY_DIR/ledger.jsonl').readlines()
print(json.loads(lines[-1])['artifact'])")
timeout 60 python tools/bench_diff.py \
    --ledger "$SRT_SENTRY_DIR/ledger.jsonl" "$SRT_SENTRY_FRESH"

echo "=== [16/20] test suite (virtual 8-device CPU mesh) ==="
if [ "$MODE" = quick ]; then
    # the <3-minute smoke tier (markers assigned in tests/conftest.py)
    python -m pytest tests/ -m quick -x -q
else
    # SHARDED into separate processes: one process compiling the whole
    # suite exhausts the XLA:CPU JIT code region and segfaults inside
    # backend_compile_and_load at ~500 tests (per-module cache release in
    # conftest delays but does not prevent it — round-4 postmortem after
    # two identical crashes at the same cumulative-compile point)
    # run EVERY shard even when one fails (set -e would stop at the
    # first, hiding failures in the remaining three quarters)
    rc=0
    python -m pytest tests/test_[a-e]*.py -q || rc=1
    python -m pytest tests/test_[f-n]*.py -q || rc=1
    python -m pytest tests/test_[o-r]*.py -q || rc=1
    python -m pytest tests/test_[s-z]*.py -q || rc=1
    [ "$rc" -eq 0 ]
fi

if [ "$MODE" != quick ]; then
    echo "=== [17/20] scale rig ==="
    SRT_SCALE_PLATFORM=cpu timeout 3600 \
        python -m spark_rapids_tpu.testing.scaletest 100000
else
    echo "=== [17/20] scale rig skipped (quick) ==="
fi

echo "=== [18/20] packaging: wheel builds and installs ==="
WHEELDIR=$(mktemp -d)
timeout 600 python -m pip wheel . --no-deps --no-build-isolation \
    -w "$WHEELDIR" -q
VENV=$(mktemp -d)/venv
python -m venv "$VENV"
"$VENV/bin/pip" install -q --no-deps --no-index "$WHEELDIR"/*.whl
# expose the ambient deps (jax/numpy/pyarrow are baked into the image,
# not downloadable here) to the otherwise-clean venv
python - "$VENV" <<'PYEOF'
import os, site, sys, sysconfig
venv = sys.argv[1]
dst = None
for root, dirs, files in os.walk(os.path.join(venv, "lib")):
    if root.endswith("site-packages"):
        dst = root
        break
src = sysconfig.get_paths()["purelib"]
with open(os.path.join(dst, "ambient_deps.pth"), "w") as fh:
    fh.write(src + "\n")
PYEOF
JAX_PLATFORMS=cpu timeout 300 env -C "$WHEELDIR" "$VENV/bin/python" -c "
import jax; jax.config.update('jax_platforms', 'cpu')
import spark_rapids_tpu, pyarrow as pa
s = spark_rapids_tpu.session()
t = s.create_dataframe(pa.table({'k': [1, 2, 1]})).groupBy('k').count().collect()
assert sorted(r['count'] for r in t.to_pylist()) == [1, 2]
print('wheel OK', spark_rapids_tpu.__version__)
"

echo "=== [19/20] driver entry checks ==="
XLA_FLAGS="--xla_force_host_platform_device_count=8" timeout 900 \
    python __graft_entry__.py

if [ "$MODE" = quick ]; then
    echo "=== [20/20] second-jax shim world skipped (quick) ==="
    echo "CI PASSED"
    exit 0
fi

echo "=== [20/20] second-jax shim world (gated) ==="
# The parallel-world leg the reference proves with its 14-version shim
# matrix (ShimLoader probing, SURVEY §2.11).  This image ships exactly
# one jaxlib and pip has zero egress (docs/perf_notes.md), so the leg
# GATES on a second interpreter rather than simulating one: point
# SRT_SECOND_JAX_PYTHON at any python whose jax version differs from
# the primary's, or drop one under /opt/pyenvs/*/bin/python3, and CI
# runs provider probing + the quick tier inside that world for real.
SECOND_PY="${SRT_SECOND_JAX_PYTHON:-}"
if [ -z "$SECOND_PY" ]; then
    primary_ver=$(python -c "import jax; print(jax.__version__)")
    for cand in /opt/pyenvs/*/bin/python3 /opt/python*/bin/python3; do
        [ -x "$cand" ] || continue
        # probe runnability, not just presence: a stray env with jax
        # but no pytest/pyarrow must be skipped, not fail CI red
        v=$("$cand" -c "import jax, pytest, pyarrow, numpy, pandas; \
print(jax.__version__)" 2>/dev/null) || continue
        if [ -n "$v" ] && [ "$v" != "$primary_ver" ]; then
            SECOND_PY="$cand"
            break
        fi
    done
fi
if [ -n "$SECOND_PY" ]; then
    echo "second jax world: $SECOND_PY"
    "$SECOND_PY" - <<'PYEOF'
import jax
from spark_rapids_tpu.shims import get_shim
print(f"jax {jax.__version__} -> provider: "
      f"{type(get_shim()).__name__}: {get_shim().description()}")
PYEOF
    JAX_PLATFORMS=cpu "$SECOND_PY" -m pytest tests/ -m quick -x -q
else
    echo "SKIPPED: no second jax installation found (single-jaxlib" \
         "image, zero pip egress — see docs/perf_notes.md); set" \
         "SRT_SECOND_JAX_PYTHON to enable this leg"
fi

echo "CI PASSED"
