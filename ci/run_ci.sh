#!/usr/bin/env bash
# CI harness — the analog of the reference's jenkins/spark-premerge-build.sh
# + spark-tests.sh pipeline (SURVEY §2.11): build native libs, validate the
# API contract, regenerate docs (drift check), run the unit+integration
# suite on the virtual 8-device CPU mesh, run the scale rig, and finish
# with the driver entry checks (single-chip compile + multichip dryrun).
#
# Usage: ci/run_ci.sh [quick]
#   quick = skip the scale rig and use -x fail-fast on the suite.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

echo "=== [1/6] native libraries ==="
make -C native

echo "=== [2/6] API contract validation ==="
timeout 300 python tools/api_validation.py

echo "=== [3/6] docgen drift check ==="
timeout 300 python -m spark_rapids_tpu.docgen
if ! git diff --quiet -- docs tools/generated_files 2>/dev/null; then
    echo "WARNING: generated docs drifted from the committed copies:"
    git --no-pager diff --stat -- docs tools/generated_files || true
fi

echo "=== [4/6] test suite (virtual 8-device CPU mesh) ==="
if [ "$MODE" = quick ]; then
    python -m pytest tests/ -x -q
else
    python -m pytest tests/ -q
fi

if [ "$MODE" != quick ]; then
    echo "=== [5/6] scale rig ==="
    SRT_SCALE_PLATFORM=cpu timeout 1200 \
        python -m spark_rapids_tpu.testing.scaletest 100000
else
    echo "=== [5/6] scale rig skipped (quick) ==="
fi

echo "=== [6/6] driver entry checks ==="
XLA_FLAGS="--xla_force_host_platform_device_count=8" timeout 900 \
    python __graft_entry__.py

echo "CI PASSED"
