// srt_native — host-side native kernels for spark_rapids_tpu, the analog
// of the reference's JNI layer around cuDF host utilities (SURVEY §2.10):
// row<->columnar string packing (RowConversion analog), Spark-exact hash
// reference implementations (com.nvidia.spark.rapids.jni.Hash), and the
// xxhash64 frame checksum used by the shuffle serializer.
//
// Exposed as a plain C ABI consumed through ctypes (no pybind11 in this
// toolchain); every function operates on caller-owned buffers.

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// string byte-matrix packing
// ---------------------------------------------------------------------------

// flat: concatenated UTF-8 bytes; offsets: int64[n+1] into flat.
// out_matrix: zeroed uint8[n * width]; out_lens: int32[n].
// Rows longer than width are truncated (callers size width to the max).
void srt_pack_strings(const uint8_t* flat, const int64_t* offsets,
                      int64_t n, int64_t width,
                      uint8_t* out_matrix, int32_t* out_lens) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t start = offsets[i];
        int64_t len = offsets[i + 1] - start;
        if (len > width) len = width;
        std::memcpy(out_matrix + i * width, flat + start,
                    static_cast<size_t>(len));
        out_lens[i] = static_cast<int32_t>(len);
    }
}

// inverse: matrix rows back to concatenated bytes; returns total length.
// out_flat must hold sum(lens); out_offsets: int64[n+1].
int64_t srt_unpack_strings(const uint8_t* matrix, const int32_t* lens,
                           int64_t n, int64_t width,
                           uint8_t* out_flat, int64_t* out_offsets) {
    int64_t pos = 0;
    out_offsets[0] = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t len = lens[i];
        if (len > width) len = static_cast<int32_t>(width);
        std::memcpy(out_flat + pos, matrix + i * width,
                    static_cast<size_t>(len));
        pos += len;
        out_offsets[i + 1] = pos;
    }
    return pos;
}

// PLAIN BYTE_ARRAY walk (parquet: sequence of u32le length + payload).
// Fills starts (payload offsets into data, int64[n]) and lens (int32[n]).
// Returns bytes consumed, or -1 on overrun/negative length.
int64_t srt_byte_array_walk(const uint8_t* data, int64_t size, int64_t n,
                            int64_t* starts, int32_t* lens) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (pos + 4 > size) return -1;
        uint32_t len;
        std::memcpy(&len, data + pos, 4);
        pos += 4;
        if (len > static_cast<uint64_t>(size - pos)) return -1;
        starts[i] = pos;
        lens[i] = static_cast<int32_t>(len);
        pos += len;
    }
    return pos;
}

// ---------------------------------------------------------------------------
// Spark-exact murmur3-x86-32 (reference jni.Hash semantics) — the
// independent host oracle the device kernels are validated against.
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k1(uint32_t k1) {
    k1 *= 0xcc9e2d51u;
    k1 = rotl32(k1, 15);
    k1 *= 0x1b873593u;
    return k1;
}

static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    return h1 * 5u + 0xe6546b64u;
}

static inline uint32_t fmix(uint32_t h1, uint32_t len) {
    h1 ^= len;
    h1 ^= h1 >> 16;
    h1 *= 0x85ebca6bu;
    h1 ^= h1 >> 13;
    h1 *= 0xc2b2ae35u;
    h1 ^= h1 >> 16;
    return h1;
}

void srt_murmur3_i32(const int32_t* vals, int64_t n, uint32_t seed,
                     int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint32_t h1 = mix_h1(seed, mix_k1(static_cast<uint32_t>(vals[i])));
        out[i] = static_cast<int32_t>(fmix(h1, 4));
    }
}

void srt_murmur3_i64(const int64_t* vals, int64_t n, uint32_t seed,
                     int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t v = static_cast<uint64_t>(vals[i]);
        uint32_t low = static_cast<uint32_t>(v);
        uint32_t high = static_cast<uint32_t>(v >> 32);
        uint32_t h1 = mix_h1(seed, mix_k1(low));
        h1 = mix_h1(h1, mix_k1(high));
        out[i] = static_cast<int32_t>(fmix(h1, 8));
    }
}

// Spark murmur3 for UTF-8 strings: 4-byte little-endian blocks, then
// SIGNED-byte tail mixing (Spark's hashUnsafeBytes semantics).
int32_t srt_murmur3_bytes(const uint8_t* data, int64_t len, uint32_t seed) {
    uint32_t h1 = seed;
    int64_t nblocks = len / 4;
    for (int64_t b = 0; b < nblocks; ++b) {
        uint32_t k1;
        std::memcpy(&k1, data + b * 4, 4);  // little-endian hosts only
        h1 = mix_h1(h1, mix_k1(k1));
    }
    for (int64_t i = nblocks * 4; i < len; ++i) {
        int32_t sb = static_cast<int8_t>(data[i]);  // sign-extended
        h1 = mix_h1(h1, mix_k1(static_cast<uint32_t>(sb)));
    }
    return static_cast<int32_t>(fmix(h1, static_cast<uint32_t>(len)));
}

// ---------------------------------------------------------------------------
// xxhash64 over raw bytes — shuffle frame integrity checksum
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t round1(uint64_t acc, uint64_t input) {
    acc += input * P2;
    acc = rotl64(acc, 31);
    return acc * P1;
}

static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
    acc ^= round1(0, val);
    return acc * P1 + P4;
}

static inline uint64_t read64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;
}

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

uint64_t srt_xxhash64_bytes(const uint8_t* data, int64_t len, uint64_t seed) {
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    uint64_t h;
    if (len >= 32) {
        uint64_t v1 = seed + P1 + P2;
        uint64_t v2 = seed + P2;
        uint64_t v3 = seed;
        uint64_t v4 = seed - P1;
        do {
            v1 = round1(v1, read64(p)); p += 8;
            v2 = round1(v2, read64(p)); p += 8;
            v3 = round1(v3, read64(p)); p += 8;
            v4 = round1(v4, read64(p)); p += 8;
        } while (p + 32 <= end);
        h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
        h = merge_round(h, v1);
        h = merge_round(h, v2);
        h = merge_round(h, v3);
        h = merge_round(h, v4);
    } else {
        h = seed + P5;
    }
    h += static_cast<uint64_t>(len);
    while (p + 8 <= end) {
        h ^= round1(0, read64(p));
        h = rotl64(h, 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        h ^= static_cast<uint64_t>(read32(p)) * P1;
        h = rotl64(h, 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h ^= (*p) * P5;
        h = rotl64(h, 11) * P1;
        ++p;
    }
    h ^= h >> 33;
    h *= P2;
    h ^= h >> 29;
    h *= P3;
    h ^= h >> 32;
    return h;
}

}  // extern "C"
