// Native cross-process shuffle data plane — the C++ core behind
// spark_rapids_tpu/shuffle/native_tcp.py (reference analog: the UCX
// transport module, shuffle-plugin/UCX.scala — native data movement with
// a single progress thread; here the progress thread is an epoll loop).
//
// Wire protocol (identical to the Python TcpShuffleTransport in
// spark_rapids_tpu/shuffle/tcp.py, so native and Python peers interop):
//   request : u32 magic | u8 op | i64 shuffle | i64 map | i64 reduce  (BE)
//   response: u8 status | u64 len | payload                           (BE)
// Only the block-fetch op (1) is served here; the JSON registry ops stay
// on the Python driver (control plane in Python, data plane native —
// mirroring the reference's Spark-RPC control / UCX data split).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x53525054;  // "SRPT"
constexpr uint8_t kOpFetch = 1;
constexpr uint8_t kFound = 0;
constexpr uint8_t kMissing = 1;
constexpr size_t kReqSize = 4 + 1 + 8 * 3;

inline uint64_t bswap64(uint64_t v) { return __builtin_bswap64(v); }

inline int64_t read_i64_be(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return static_cast<int64_t>(bswap64(v));
}

struct BlockKey {
  int64_t shuffle, map, reduce;
  bool operator==(const BlockKey& o) const {
    return shuffle == o.shuffle && map == o.map && reduce == o.reduce;
  }
};

struct BlockKeyHash {
  size_t operator()(const BlockKey& k) const {
    uint64_t h = 0x9e3779b97f4a7c15ull;
    for (int64_t v : {k.shuffle, k.map, k.reduce}) {
      h ^= static_cast<uint64_t>(v) + 0x9e3779b97f4a7c15ull + (h << 6) +
           (h >> 2);
    }
    return static_cast<size_t>(h);
  }
};

using BlockPtr = std::shared_ptr<std::vector<uint8_t>>;

struct Conn {
  int fd = -1;
  std::vector<uint8_t> in;   // partial request bytes
  std::string out;           // pending response bytes
  size_t out_off = 0;
};

// --------------------------------------------------------------------------
// Server: one epoll progress thread serving block fetches.
// --------------------------------------------------------------------------
struct Server {
  int lfd = -1, efd = -1, wake_fd = -1;
  int port = 0;
  std::thread th;
  std::mutex mu;  // guards store
  std::unordered_map<BlockKey, BlockPtr, BlockKeyHash> store;
  std::unordered_map<int, std::unique_ptr<Conn>> conns;
  bool stopping = false;

  bool start(const char* host, int want_port) {
    lfd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (lfd < 0) return false;
    int one = 1;
    ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return fail();
    if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return fail();
    if (::listen(lfd, 128) != 0) return fail();
    socklen_t alen = sizeof(addr);
    ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    efd = ::epoll_create1(0);
    wake_fd = ::eventfd(0, EFD_NONBLOCK);
    if (efd < 0 || wake_fd < 0) return fail();
    add_fd(lfd, EPOLLIN);
    add_fd(wake_fd, EPOLLIN);
    th = std::thread([this] { loop(); });
    return true;
  }

  bool fail() {
    if (lfd >= 0) ::close(lfd);
    if (efd >= 0) ::close(efd);
    if (wake_fd >= 0) ::close(wake_fd);
    lfd = efd = wake_fd = -1;
    return false;
  }

  void add_fd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    ::epoll_ctl(efd, EPOLL_CTL_ADD, fd, &ev);
  }

  void mod_fd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    ::epoll_ctl(efd, EPOLL_CTL_MOD, fd, &ev);
  }

  void close_conn(int fd) {
    ::epoll_ctl(efd, EPOLL_CTL_DEL, fd, nullptr);
    ::close(fd);
    conns.erase(fd);
  }

  void loop() {
    epoll_event evs[64];
    while (true) {
      int n = ::epoll_wait(efd, evs, 64, -1);
      if (n < 0) {
        if (errno == EINTR) continue;
        return;
      }
      for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == wake_fd) {
          // stop signal
          uint64_t v;
          (void)!::read(wake_fd, &v, 8);
          shutdown_all();
          return;
        }
        if (fd == lfd) {
          accept_all();
          continue;
        }
        auto it = conns.find(fd);
        if (it == conns.end()) continue;
        Conn* c = it->second.get();
        bool dead = false;
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
        if (!dead && (evs[i].events & EPOLLIN)) dead = !on_readable(c);
        if (!dead && (evs[i].events & EPOLLOUT)) dead = !on_writable(c);
        if (dead) close_conn(fd);
      }
    }
  }

  void shutdown_all() {
    for (auto& kv : conns) ::close(kv.first);
    conns.clear();
    ::close(lfd);
    ::close(efd);
    ::close(wake_fd);
  }

  void accept_all() {
    while (true) {
      int cfd = ::accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
      if (cfd < 0) return;
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto c = std::make_unique<Conn>();
      c->fd = cfd;
      conns[cfd] = std::move(c);
      add_fd(cfd, EPOLLIN);
    }
  }

  // returns false when the connection must close
  bool on_readable(Conn* c) {
    uint8_t buf[16384];
    while (true) {
      ssize_t got = ::recv(c->fd, buf, sizeof(buf), 0);
      if (got > 0) {
        c->in.insert(c->in.end(), buf, buf + got);
        continue;
      }
      if (got == 0) return false;  // peer closed
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      return false;
    }
    while (c->in.size() >= kReqSize) {
      const uint8_t* p = c->in.data();
      uint32_t magic;
      std::memcpy(&magic, p, 4);
      magic = ntohl(magic);
      uint8_t op = p[4];
      if (magic != kMagic || op != kOpFetch) return false;
      BlockKey key{read_i64_be(p + 5), read_i64_be(p + 13),
                   read_i64_be(p + 21)};
      c->in.erase(c->in.begin(), c->in.begin() + kReqSize);
      BlockPtr blk;
      {
        std::lock_guard<std::mutex> g(mu);
        auto it = store.find(key);
        if (it != store.end()) blk = it->second;
      }
      uint8_t head[9];
      head[0] = blk ? kFound : kMissing;
      uint64_t len = bswap64(blk ? blk->size() : 0);
      std::memcpy(head + 1, &len, 8);
      c->out.append(reinterpret_cast<char*>(head), 9);
      if (blk)
        c->out.append(reinterpret_cast<const char*>(blk->data()),
                      blk->size());
    }
    if (!c->out.empty() && !on_writable(c)) return false;
    return true;
  }

  bool on_writable(Conn* c) {
    while (c->out_off < c->out.size()) {
      ssize_t sent = ::send(c->fd, c->out.data() + c->out_off,
                            c->out.size() - c->out_off, MSG_NOSIGNAL);
      if (sent > 0) {
        c->out_off += static_cast<size_t>(sent);
        continue;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        mod_fd(c->fd, EPOLLIN | EPOLLOUT);
        return true;
      }
      return false;
    }
    c->out.clear();
    c->out_off = 0;
    mod_fd(c->fd, EPOLLIN);
    return true;
  }

  void stop() {
    uint64_t one = 1;
    (void)!::write(wake_fd, &one, 8);
    if (th.joinable()) th.join();
  }
};

// --------------------------------------------------------------------------
// Client: pooled blocking fetches (timeouts; one reconnect per fetch).
// --------------------------------------------------------------------------
struct Client {
  std::mutex mu;
  std::unordered_map<std::string, int> conns;
  // conf-driven socket timeout (spark.rapids.shuffle.tcp.readTimeoutMs);
  // SO_SNDTIMEO also bounds connect() on Linux
  int timeout_ms = 10000;

  int connect_to(const std::string& host, int port) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1 ||
        ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
            0) {
      ::close(fd);
      return -1;
    }
    return fd;
  }

  static bool send_all(int fd, const uint8_t* p, size_t n) {
    while (n) {
      ssize_t s = ::send(fd, p, n, MSG_NOSIGNAL);
      if (s <= 0) return false;
      p += s;
      n -= static_cast<size_t>(s);
    }
    return true;
  }

  static bool recv_all(int fd, uint8_t* p, size_t n) {
    while (n) {
      ssize_t g = ::recv(fd, p, n, 0);
      if (g <= 0) return false;
      p += g;
      n -= static_cast<size_t>(g);
    }
    return true;
  }

  // status: 0 found, 1 missing, 2 network failure
  int fetch(const std::string& host, int port, const BlockKey& key,
            uint8_t** out, uint64_t* out_len) {
    std::string ep = host + ":" + std::to_string(port);
    std::lock_guard<std::mutex> g(mu);
    for (int attempt = 0; attempt < 2; attempt++) {
      int fd;
      auto it = conns.find(ep);
      if (attempt == 0 && it != conns.end()) {
        fd = it->second;
      } else {
        if (it != conns.end()) {
          ::close(it->second);
          conns.erase(it);
        }
        fd = connect_to(host, port);
        if (fd < 0) continue;
        conns[ep] = fd;
      }
      uint8_t req[kReqSize];
      uint32_t magic = htonl(kMagic);
      std::memcpy(req, &magic, 4);
      req[4] = kOpFetch;
      for (int i = 0; i < 3; i++) {
        int64_t v = i == 0 ? key.shuffle : i == 1 ? key.map : key.reduce;
        uint64_t be = bswap64(static_cast<uint64_t>(v));
        std::memcpy(req + 5 + 8 * i, &be, 8);
      }
      uint8_t head[9];
      if (!send_all(fd, req, kReqSize) || !recv_all(fd, head, 9)) {
        ::close(fd);
        conns.erase(ep);
        continue;
      }
      if (head[0] == kMissing) return 1;
      uint64_t len;
      std::memcpy(&len, head + 1, 8);
      len = bswap64(len);
      uint8_t* buf = static_cast<uint8_t*>(::malloc(len ? len : 1));
      if (len && !recv_all(fd, buf, len)) {
        ::free(buf);
        ::close(fd);
        conns.erase(ep);
        continue;
      }
      *out = buf;
      *out_len = len;
      return 0;
    }
    return 2;
  }

  void close_all() {
    std::lock_guard<std::mutex> g(mu);
    for (auto& kv : conns) ::close(kv.second);
    conns.clear();
  }
};

std::mutex g_mu;
int64_t g_next = 1;
std::unordered_map<int64_t, std::unique_ptr<Server>> g_servers;
std::unordered_map<int64_t, std::unique_ptr<Client>> g_clients;

Server* server_of(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_servers.find(h);
  return it == g_servers.end() ? nullptr : it->second.get();
}

Client* client_of(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_clients.find(h);
  return it == g_clients.end() ? nullptr : it->second.get();
}

}  // namespace

extern "C" {

int64_t srt_shuffle_server_start(const char* host, int port) {
  auto s = std::make_unique<Server>();
  if (!s->start(host, port)) return -1;
  std::lock_guard<std::mutex> g(g_mu);
  int64_t h = g_next++;
  g_servers[h] = std::move(s);
  return h;
}

int srt_shuffle_server_port(int64_t h) {
  Server* s = server_of(h);
  return s ? s->port : -1;
}

void srt_shuffle_server_publish(int64_t h, int64_t shuffle, int64_t map,
                                int64_t reduce, const uint8_t* data,
                                uint64_t len) {
  Server* s = server_of(h);
  if (!s) return;
  auto blk = std::make_shared<std::vector<uint8_t>>(data, data + len);
  std::lock_guard<std::mutex> g(s->mu);
  s->store[BlockKey{shuffle, map, reduce}] = std::move(blk);
}

// local short-circuit; returns 0 found / 1 missing
int srt_shuffle_server_get(int64_t h, int64_t shuffle, int64_t map,
                           int64_t reduce, uint8_t** out,
                           uint64_t* out_len) {
  Server* s = server_of(h);
  if (!s) return 1;
  BlockPtr blk;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->store.find(BlockKey{shuffle, map, reduce});
    if (it != s->store.end()) blk = it->second;
  }
  if (!blk) return 1;
  uint8_t* buf = static_cast<uint8_t*>(::malloc(blk->size() ? blk->size()
                                                            : 1));
  std::memcpy(buf, blk->data(), blk->size());
  *out = buf;
  *out_len = blk->size();
  return 0;
}

int64_t srt_shuffle_server_block_count(int64_t h, int64_t shuffle) {
  Server* s = server_of(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> g(s->mu);
  if (shuffle < 0) return static_cast<int64_t>(s->store.size());
  int64_t n = 0;
  for (auto& kv : s->store)
    if (kv.first.shuffle == shuffle) n++;
  return n;
}

// fills out[3*i .. 3*i+2] with (shuffle, map, reduce); returns count
int64_t srt_shuffle_server_block_list(int64_t h, int64_t shuffle,
                                      int64_t* out, int64_t cap_blocks) {
  Server* s = server_of(h);
  if (!s) return 0;
  std::lock_guard<std::mutex> g(s->mu);
  int64_t n = 0;
  for (auto& kv : s->store) {
    if (shuffle >= 0 && kv.first.shuffle != shuffle) continue;
    if (n >= cap_blocks) break;
    out[3 * n] = kv.first.shuffle;
    out[3 * n + 1] = kv.first.map;
    out[3 * n + 2] = kv.first.reduce;
    n++;
  }
  return n;
}

void srt_shuffle_server_clear(int64_t h, int64_t shuffle) {
  Server* s = server_of(h);
  if (!s) return;
  std::lock_guard<std::mutex> g(s->mu);
  if (shuffle < 0) {
    s->store.clear();
    return;
  }
  for (auto it = s->store.begin(); it != s->store.end();) {
    if (it->first.shuffle == shuffle)
      it = s->store.erase(it);
    else
      ++it;
  }
}

void srt_shuffle_server_stop(int64_t h) {
  std::unique_ptr<Server> s;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_servers.find(h);
    if (it == g_servers.end()) return;
    s = std::move(it->second);
    g_servers.erase(it);
  }
  s->stop();
}

int64_t srt_shuffle_client_new() {
  std::lock_guard<std::mutex> g(g_mu);
  int64_t h = g_next++;
  g_clients[h] = std::make_unique<Client>();
  return h;
}

// applies to connections established AFTER the call (pooled sockets
// keep the timeout they were created with)
void srt_shuffle_client_set_timeout_ms(int64_t h, int ms) {
  Client* c = client_of(h);
  if (c && ms > 0) c->timeout_ms = ms;
}

int srt_shuffle_client_fetch(int64_t h, const char* host, int port,
                             int64_t shuffle, int64_t map, int64_t reduce,
                             uint8_t** out, uint64_t* out_len) {
  Client* c = client_of(h);
  if (!c) return 2;
  return c->fetch(host, port, BlockKey{shuffle, map, reduce}, out, out_len);
}

void srt_shuffle_client_close(int64_t h) {
  std::unique_ptr<Client> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(h);
    if (it == g_clients.end()) return;
    c = std::move(it->second);
    g_clients.erase(it);
  }
  c->close_all();
}

void srt_transport_buf_free(uint8_t* p) { ::free(p); }

}  // extern "C"
