"""Build hook: bundle the repo-root ``native/*.cpp`` sources into the
``spark_rapids_tpu.native`` package so installed artifacts are
self-contained (native/_loader.py compiles them on first use).  All other
metadata lives in pyproject.toml."""

import os
import shutil

from setuptools import setup
from setuptools.command.build_py import build_py


class BundleNativeSources(build_py):
    def run(self):
        super().run()
        root = os.path.dirname(os.path.abspath(__file__))
        src_dir = os.path.join(root, "native")
        dst_dir = os.path.join(self.build_lib, "spark_rapids_tpu", "native")
        if os.path.isdir(src_dir) and os.path.isdir(dst_dir):
            for name in os.listdir(src_dir):
                if name.endswith(".cpp"):
                    shutil.copy2(os.path.join(src_dir, name),
                                 os.path.join(dst_dir, name))


setup(cmdclass={"build_py": BundleNativeSources})
