"""spark_rapids_tpu — TPU-native columnar SQL acceleration framework.

A ground-up TPU/XLA re-design of the capabilities of the RAPIDS Accelerator
for Apache Spark (reference at /root/reference): a columnar dataframe/SQL
engine whose physical plans are rewritten so that supported operators execute
on TPUs as columnar batches via JAX/XLA (with Pallas kernels for hot ops),
falling back to a host (Arrow/numpy) engine per-operator when anything is
unsupported, while targeting bit-identical results to the host engine.
"""

__version__ = "0.3.0"

import jax as _jax

# Spark semantics are 64-bit (bigint, double, timestamp-micros); JAX defaults
# to 32-bit, so x64 must be on before any array is created.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: a first compile on the TPU tunnel costs
# 20-60s per program (remote compiler — docs/perf_notes.md), so every entry
# point into the engine must amortize compiles across processes/runs, not
# just bench.py.  Harmless no-op on backends without cache support.
import os as _os

def _host_fingerprint() -> str:
    """XLA:CPU AOT results are machine-feature specific but the cache key
    is not — loading an entry compiled on a wider-ISA machine risks SIGILL
    (observed as 'Target machine feature ... not supported' warnings).
    Scope the cache dir to this host's CPU flags."""
    import hashlib
    import platform
    feat = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feat += " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    return hashlib.sha256(feat.encode()).hexdigest()[:12]


try:  # pragma: no cover - depends on jax version/backend
    # CPU-platform processes skip the cache entirely: XLA:CPU AOT entries
    # embed compile-machine pseudo-features (+prefer-no-scatter/-gather)
    # that fail the loader's host check — observed as SIGILL-class fatal
    # crashes mid-suite — and CPU compiles are cheap to redo.  The cache
    # exists for the REMOTE TPU compiler (20-60s per program).
    _plat = str(_jax.config.jax_platforms or "")
    if _plat.split(",")[0] == "cpu":
        raise RuntimeError("cpu platform: persistent compile cache skipped")
    if not (_jax.config.jax_compilation_cache_dir
            or _os.environ.get("JAX_COMPILATION_CACHE_DIR")):
        # defer to any user-configured cache; otherwise default to a
        # host-scoped dir next to the package checkout
        _cache_dir = _os.environ.get(
            "SPARK_RAPIDS_TPU_JAX_CACHE",
            _os.path.join(_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__))), ".jax_cache",
                _host_fingerprint()))
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

from .types import (  # noqa: F401
    BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, STRING, BINARY, DATE,
    TIMESTAMP, NULL, ArrayType, BinaryType, BooleanType, ByteType, DataType,
    DateType, DecimalType, DoubleType, FloatType, IntegerType, LongType,
    MapType, NullType, ShortType, StringType, StructField, StructType,
    TimestampType)
from .config import RapidsConf  # noqa: F401
from .columnar import ColumnarBatch, DeviceColumn  # noqa: F401


def pin_host_platform() -> None:
    """Flip this process to the CPU platform AND drop the persistent
    compile cache.  For callers that decide on the host platform AFTER
    importing this package (the import-time cache setup saw the ambient
    TPU platform): XLA:CPU AOT cache entries fail the loader's
    machine-feature check and have caused SIGILL-class crashes."""
    try:
        _jax.config.update("jax_platforms", "cpu")
        _jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass


def session(conf=None, **conf_kwargs):
    """Create (or get) the TpuSession — entry point of the user API."""
    try:
        from .sql.session import TpuSession
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "the sql session layer is not available in this build") from e
    return TpuSession.get_or_create(conf, **conf_kwargs)
