"""spark_rapids_tpu — TPU-native columnar SQL acceleration framework.

A ground-up TPU/XLA re-design of the capabilities of the RAPIDS Accelerator
for Apache Spark (reference at /root/reference): a columnar dataframe/SQL
engine whose physical plans are rewritten so that supported operators execute
on TPUs as columnar batches via JAX/XLA (with Pallas kernels for hot ops),
falling back to a host (Arrow/numpy) engine per-operator when anything is
unsupported, while targeting bit-identical results to the host engine.
"""

__version__ = "0.1.0"

import jax as _jax

# Spark semantics are 64-bit (bigint, double, timestamp-micros); JAX defaults
# to 32-bit, so x64 must be on before any array is created.
_jax.config.update("jax_enable_x64", True)

from .types import (  # noqa: F401
    BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, STRING, BINARY, DATE,
    TIMESTAMP, NULL, ArrayType, BinaryType, BooleanType, ByteType, DataType,
    DateType, DecimalType, DoubleType, FloatType, IntegerType, LongType,
    MapType, NullType, ShortType, StringType, StructField, StructType,
    TimestampType)
from .config import RapidsConf  # noqa: F401
from .columnar import ColumnarBatch, DeviceColumn  # noqa: F401


def session(conf=None, **conf_kwargs):
    """Create (or get) the TpuSession — entry point of the user API."""
    try:
        from .sql.session import TpuSession
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "the sql session layer is not available in this build") from e
    return TpuSession.get_or_create(conf, **conf_kwargs)
