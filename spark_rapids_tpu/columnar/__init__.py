from .column import (DeviceColumn, bucket_capacity, bucket_width,
                     make_fixed_column, make_string_column, null_column,
                     scalar_column)
from .batch import ColumnarBatch
from .convert import (arrow_to_device, device_to_arrow, arrow_to_device_column,
                      device_column_to_arrow, pandas_to_device, device_to_pandas)

__all__ = [
    "DeviceColumn", "ColumnarBatch", "bucket_capacity", "bucket_width",
    "make_fixed_column", "make_string_column", "null_column", "scalar_column",
    "arrow_to_device", "device_to_arrow", "arrow_to_device_column",
    "device_column_to_arrow", "pandas_to_device", "device_to_pandas",
]
