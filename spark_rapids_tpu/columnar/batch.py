"""ColumnarBatch — the unit of execution, analog of the reference's
``ColumnarBatch`` of ``GpuColumnVector`` (``GpuColumnVector.java``) and cuDF
``Table``.  A batch is a set of equally-padded device columns plus a traced
``num_rows`` scalar; the padded capacity is the XLA shape key.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..types import DataType, StructField, StructType
from .column import DeviceColumn, bucket_capacity


@jax.tree_util.register_pytree_node_class
@dataclass
class ColumnarBatch:
    names: Tuple[str, ...]
    columns: Tuple[DeviceColumn, ...]
    #: traced 0-d int32 — keeps one compiled program per capacity bucket
    num_rows: jnp.ndarray

    def tree_flatten(self):
        return ((self.columns, self.num_rows), self.names)

    @classmethod
    def tree_unflatten(cls, names, leaves):
        columns, num_rows = leaves
        return cls(names, columns, num_rows)

    # --- construction -----------------------------------------------------
    @staticmethod
    def make(names: Sequence[str], columns: Sequence[DeviceColumn],
             num_rows) -> "ColumnarBatch":
        known = None
        if not isinstance(num_rows, jnp.ndarray):
            known = int(num_rows)
            num_rows = jnp.asarray(num_rows, dtype=jnp.int32)
        b = ColumnarBatch(tuple(names), tuple(columns), num_rows)
        if known is not None:
            # host-constructed count: num_rows_int must not pay a device
            # round trip to read back what the host just wrote
            b._nrows_host = known
        return b

    @staticmethod
    def empty(schema: StructType) -> "ColumnarBatch":
        from .column import null_column
        cap = bucket_capacity(0)
        cols = tuple(null_column(f.data_type, cap) for f in schema.fields)
        return ColumnarBatch.make(schema.names, cols, 0)

    # --- shape ------------------------------------------------------------
    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_rows_int(self) -> int:
        """Host-side row count.  Forces ONE device sync per batch, then
        memoizes — on the TPU tunnel every sync is a full network round
        trip (~65ms), so producers that already know the count on the host
        (two-phase aggregate, slicing) pre-seed it via
        :meth:`with_known_rows`."""
        cached = getattr(self, "_nrows_host", None)
        if cached is None:
            cached = int(self.num_rows)
            self._nrows_host = cached
        return cached

    def with_known_rows(self, n: int) -> "ColumnarBatch":
        """Record the host-known row count (skips the sync in
        ``num_rows_int``).  Caller contract: ``n == int(self.num_rows)``."""
        self._nrows_host = int(n)
        return self

    @property
    def num_rows_bound(self) -> int:
        """Host-known UPPER BOUND on the row count, without ever pulling
        from the device: the exact count when known, a producer-recorded
        bound (``with_rows_bound``), else the padded capacity.  Use for
        conservative control-flow decisions (out-of-core engagement,
        coalescing) where a sync per batch would serialize the tunnel."""
        cached = getattr(self, "_nrows_host", None)
        if cached is not None:
            return cached
        bound = getattr(self, "_nrows_bound", None)
        if bound is not None:
            return bound
        return self.capacity

    def with_rows_bound(self, n: int) -> "ColumnarBatch":
        """Record a host-known row-count upper bound (e.g. the speculated
        group-table size) for pull-free sizing decisions."""
        self._nrows_bound = int(n)
        return self

    def row_mask(self) -> jnp.ndarray:
        """bool[capacity]: True for live rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    @property
    def schema(self) -> StructType:
        return StructType(tuple(
            StructField(n, c.dtype, True)
            for n, c in zip(self.names, self.columns)))

    # --- access -----------------------------------------------------------
    def column(self, i) -> DeviceColumn:
        if isinstance(i, str):
            i = self.names.index(i)
        return self.columns[i]

    def with_columns(self, names: Sequence[str],
                     columns: Sequence[DeviceColumn]) -> "ColumnarBatch":
        return ColumnarBatch.make(names, columns, self.num_rows)

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch.make(
            [self.names[i] for i in indices],
            [self.columns[i] for i in indices], self.num_rows)

    # --- reshaping (host-orchestrated, device-executed) -------------------
    def repadded(self, new_capacity: int) -> "ColumnarBatch":
        cols = tuple(c.slice_capacity(new_capacity) for c in self.columns)
        b = ColumnarBatch(self.names, cols, self.num_rows)
        cached = getattr(self, "_nrows_host", None)
        if cached is not None:
            b._nrows_host = cached
        return b

    #: capacities at or below this skip shrinking entirely: the serializer
    #: ships live rows only, so small padding is free — while the
    #: num_rows sync shrunk() needs costs a full host round-trip (an RTT
    #: over the TPU tunnel)
    _SHRINK_MIN_CAPACITY = 4096

    def shrunk(self) -> "ColumnarBatch":
        """Drop excess capacity padding down to the row count's bucket.
        Host-side decision (syncs on num_rows); call at exec boundaries
        where the live row count can collapse (post-agg, post-split) so
        downstream kernels/serializers don't chew dead padding."""
        if self.capacity <= self._SHRINK_MIN_CAPACITY:
            return self
        cap = bucket_capacity(self.num_rows_int)
        if cap >= self.capacity:
            return self
        return self.repadded(cap)

    def sliced(self, start: int, length: int) -> "ColumnarBatch":
        """Host-side slice: returns a batch viewing rows [start, start+len).
        Implemented as a gather so the result is bucket-padded."""
        n = self.num_rows_int
        length = max(0, min(length, n - start))
        cap = bucket_capacity(length)
        idx = jnp.arange(cap, dtype=jnp.int32) + start
        valid = jnp.arange(cap, dtype=jnp.int32) < length
        cols = tuple(c.gather(idx, valid) for c in self.columns)
        return ColumnarBatch.make(self.names, cols, length)

    def gather(self, idx: jnp.ndarray, idx_valid: Optional[jnp.ndarray],
               out_rows) -> "ColumnarBatch":
        cols = tuple(c.gather(idx, idx_valid) for c in self.columns)
        return ColumnarBatch.make(self.names, cols, out_rows)

    @staticmethod
    def concat(batches: Sequence["ColumnarBatch"]) -> "ColumnarBatch":
        """Concatenate batches (cudf ``Table.concatenate`` analog).  Uses a
        gather per input into a fresh bucket so string widths re-align."""
        if not batches:
            raise ValueError("ColumnarBatch.concat requires at least one batch")
        batches = [b for b in batches if b.num_rows_int > 0] or list(batches[:1])
        if len(batches) == 1:
            return batches[0]
        total = sum(b.num_rows_int for b in batches)
        cap = bucket_capacity(total)
        out_cols: List[DeviceColumn] = []
        names = batches[0].names
        for ci in range(batches[0].num_cols):
            pieces = [b.columns[ci] for b in batches]
            out_cols.append(_concat_columns(pieces, [b.num_rows_int for b in batches], cap))
        out = ColumnarBatch.make(names, out_cols, total)
        # a real multi-batch concat gathers into fresh buffers: mark it
        # donation-eligible (memory/retention.py) — EXCEPT when an input
        # was encoded (dictionary objects are shared with the inputs);
        # may_donate declines encoded batches structurally anyway, but an
        # unmarked batch is the cheaper decline
        from ..memory.retention import mark_transient
        from .encoded import DictEncodedColumn, RLEColumn
        if not any(isinstance(c, (DictEncodedColumn, RLEColumn))
                   for c in out_cols):
            mark_transient(out)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return (f"ColumnarBatch(rows={self.num_rows_int}, cap={self.capacity}, "
                f"cols={list(zip(self.names, [c.dtype for c in self.columns]))})")


def _concat_columns(cols: Sequence[DeviceColumn], counts: Sequence[int],
                    out_capacity: int) -> DeviceColumn:
    from .column import DeviceColumn as DC
    from .encoded import DictEncodedColumn, try_concat_dict_columns
    if any(isinstance(c, DictEncodedColumn) for c in cols):
        if all(isinstance(c, DictEncodedColumn) for c in cols):
            enc = try_concat_dict_columns(cols, counts, out_capacity)
            if enc is not None:
                return enc
        # mixed / over-budget: fall through — the .data/.lengths property
        # accesses below materialize the encoded pieces (decline path)
    dtype = cols[0].dtype
    if cols[0].is_array_like:
        # align slot widths, then concat children at width-scaled counts
        # (each parent row owns a contiguous width-sized child block)
        width = max(c.array_width for c in cols)
        cols = [c.with_array_width(width) for c in cols]
        children = tuple(
            _concat_columns([c.children[k] for c in cols],
                            [n * width for n in counts],
                            out_capacity * width)
            for k in range(len(cols[0].children)))
        validity = _concat_1d([c.validity for c in cols], counts,
                              out_capacity, False)
        lengths = _concat_1d([c.lengths for c in cols], counts,
                             out_capacity, 0)
        return DC(dtype, None, validity, lengths, None, children)
    if cols[0].data is None:  # struct
        children = tuple(
            _concat_columns([c.children[k] for c in cols], counts, out_capacity)
            for k in range(len(cols[0].children)))
        validity = _concat_1d([c.validity for c in cols], counts, out_capacity, False)
        return DC(dtype, None, validity, children=children)
    datas = [c.data for c in cols]
    if datas[0].ndim == 2:
        width = max(d.shape[1] for d in datas)
        datas = [jnp.pad(d, ((0, 0), (0, width - d.shape[1]))) if d.shape[1] < width
                 else d for d in datas]
    data = _concat_nd(datas, counts, out_capacity)
    validity = _concat_1d([c.validity for c in cols], counts, out_capacity, False)
    lengths = (_concat_1d([c.lengths for c in cols], counts, out_capacity, 0)
               if cols[0].lengths is not None else None)
    aux = (_concat_1d([c.aux for c in cols], counts, out_capacity, 0)
           if cols[0].aux is not None else None)
    return DC(dtype, data, validity, lengths, aux)


def _concat_1d(arrs, counts, out_capacity, fill):
    if getattr(arrs[0], "dtype", None) == object:  # host nested columns
        return _concat_object(arrs, counts, out_capacity)
    live = [a[:n] for a, n in zip(arrs, counts)]
    cat = jnp.concatenate(live) if live else arrs[0][:0]
    pad = out_capacity - cat.shape[0]
    return jnp.pad(cat, (0, pad), constant_values=fill)


def _concat_nd(arrs, counts, out_capacity):
    if getattr(arrs[0], "dtype", None) == object:  # host nested columns
        return _concat_object(arrs, counts, out_capacity)
    live = [a[:n] for a, n in zip(arrs, counts)]
    cat = jnp.concatenate(live, axis=0) if live else arrs[0][:0]
    pad = [(0, out_capacity - cat.shape[0])] + [(0, 0)] * (cat.ndim - 1)
    return jnp.pad(cat, pad)


def _concat_object(arrs, counts, out_capacity):
    import numpy as np
    out = np.empty(out_capacity, dtype=object)
    pos = 0
    for a, n in zip(arrs, counts):
        out[pos:pos + n] = np.asarray(a)[:n]
        pos += n
    return out
