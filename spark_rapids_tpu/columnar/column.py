"""Device column layout — the TPU-native analog of cuDF's ``ColumnVector``
(reference consumes it as ``ai.rapids.cudf.ColumnVector`` via
``GpuColumnVector.java``; see SURVEY §2.10).

Layout rules (XLA-first):

* Every column is padded to a power-of-two row **capacity** so that XLA
  compiles one program per (schema, capacity-bucket) instead of one per row
  count.  Rows at index >= ``num_rows`` (tracked on the batch) are dead:
  their validity is False and their data is zero.
* Fixed-width types: ``data[capacity]`` with the type's numpy carrier dtype,
  ``validity[capacity]`` bool (True = valid; nulls hold zeroed data).
* STRING/BINARY: ``data[capacity, width]`` uint8 byte matrix (width is a
  power-of-two bucket) + ``lengths[capacity]`` int32.  This trades memory for
  static shapes and vectorizable string kernels on the VPU — the TPU answer
  to cuDF's offset+chars layout, which would force dynamic shapes under XLA.
* STRUCT: no own data, only ``children`` columns + own validity.
* ARRAY: ``data[capacity, width]`` is replaced by a child column holding
  ``capacity * width`` flattened elements plus ``lengths``; width buckets the
  max list length (same padding trick one level down).
* DECIMAL(p<=18): scaled int64 in ``data``. DECIMAL(p>18): ``data`` is the
  low 64 bits, ``aux`` the high 64 bits (Aggregation128Utils equivalent).

Columns are registered as JAX pytrees, so whole batches flow through ``jit``.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (ArrayType, BinaryType, DataType, DecimalType, MapType,
                     NullType, StringType, StructType)

_MIN_CAPACITY = 8
_MIN_WIDTH = 4


def bucket_capacity(num_rows: int, minimum: int = _MIN_CAPACITY) -> int:
    """Smallest power-of-two >= max(num_rows, minimum)."""
    n = max(int(num_rows), minimum, 1)
    return 1 << (n - 1).bit_length()


def bucket_width(max_len: int, minimum: int = _MIN_WIDTH) -> int:
    n = max(int(max_len), minimum, 1)
    return 1 << (n - 1).bit_length()


def is_string_like(dt: DataType) -> bool:
    return isinstance(dt, (StringType, BinaryType))


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceColumn:
    """One logical column resident in device memory."""

    dtype: DataType
    data: Optional[jnp.ndarray] = None          # None for STRUCT
    validity: Optional[jnp.ndarray] = None      # bool[capacity]
    lengths: Optional[jnp.ndarray] = None       # int32[capacity] strings/lists
    aux: Optional[jnp.ndarray] = None           # decimal128 high words
    children: Tuple["DeviceColumn", ...] = ()

    # --- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return ((self.data, self.validity, self.lengths, self.aux,
                 self.children), self.dtype)

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        data, validity, lengths, aux, children = leaves
        return cls(dtype, data, validity, lengths, aux, children)

    # --- shape info -------------------------------------------------------
    @property
    def capacity(self) -> int:
        if self.data is not None:
            return int(self.data.shape[0])
        if self.validity is not None:
            return int(self.validity.shape[0])
        return self.children[0].capacity

    @property
    def width(self) -> Optional[int]:
        if self.data is not None and self.data.ndim == 2:
            return int(self.data.shape[1])
        return None

    @property
    def is_array_like(self) -> bool:
        return isinstance(self.dtype, (ArrayType, MapType))

    @property
    def array_width(self) -> int:
        """Max-list-length bucket: the element child holds
        ``capacity * array_width`` flattened rows (row r's slots at
        ``r*w .. r*w+w-1``)."""
        assert self.is_array_like
        return self.children[0].capacity // max(self.capacity, 1)

    def with_validity(self, validity: jnp.ndarray) -> "DeviceColumn":
        return replace(self, validity=validity)

    def mask_dead_rows(self, row_mask: jnp.ndarray) -> "DeviceColumn":
        """Clear validity (and zero data) for rows beyond num_rows."""
        v = self.validity & row_mask if self.validity is not None else row_mask
        return replace(self, validity=v)

    # --- constructors for padding changes ---------------------------------
    def slice_capacity(self, new_capacity: int) -> "DeviceColumn":
        """Narrow or grow the capacity padding (device-side)."""
        if self.is_array_like:
            w = self.array_width
            return DeviceColumn(
                self.dtype, None,
                _fix_1d(self.validity, new_capacity, False),
                _fix_1d(self.lengths, new_capacity, 0),
                None,
                tuple(c.slice_capacity(new_capacity * w)
                      for c in self.children))

        def fix(arr, fill=0):
            if arr is None:
                return None
            cap = arr.shape[0]
            if cap == new_capacity:
                return arr
            if cap > new_capacity:
                return arr[:new_capacity]
            if getattr(arr, "dtype", None) == object:  # host nested column
                out = np.empty(new_capacity, dtype=object)
                out[:cap] = np.asarray(arr)
                return out
            pad = [(0, new_capacity - cap)] + [(0, 0)] * (arr.ndim - 1)
            return jnp.pad(arr, pad, constant_values=fill)

        return DeviceColumn(
            self.dtype, fix(self.data),
            fix(self.validity, False),
            fix(self.lengths),
            fix(self.aux),
            tuple(c.slice_capacity(new_capacity) for c in self.children))

    def gather(self, idx: jnp.ndarray, idx_valid: Optional[jnp.ndarray] = None
               ) -> "DeviceColumn":
        """Select rows by index (the JoinGatherer primitive).  ``idx`` may
        contain out-of-range sentinels; ``idx_valid`` marks which produce a
        valid row (False -> null output row, e.g. outer-join misses)."""
        safe = jnp.clip(idx, 0, self.capacity - 1)
        lengths = self.lengths[safe] if self.lengths is not None else None
        validity = (self.validity[safe] if self.validity is not None
                    else jnp.ones(idx.shape[0], dtype=bool))
        if idx_valid is not None:
            validity = validity & idx_valid
        if self.is_array_like:
            # row blocks: child row r*w+j follows its parent row
            w = self.array_width
            j = jnp.arange(w, dtype=safe.dtype)[None, :]
            child_idx = (safe[:, None] * w + j).reshape(-1)
            child_valid = (jnp.broadcast_to(
                validity[:, None], (idx.shape[0], w)).reshape(-1)
                if idx_valid is not None else None)
            children = tuple(c.gather(child_idx, child_valid)
                             for c in self.children)
            return DeviceColumn(self.dtype, None, validity, lengths, None,
                                children)
        data = self.data[safe] if self.data is not None else None
        aux = self.aux[safe] if self.aux is not None else None
        children = tuple(c.gather(idx, idx_valid) for c in self.children)
        return DeviceColumn(self.dtype, data, validity, lengths, aux, children)

    def with_array_width(self, new_width: int) -> "DeviceColumn":
        """Re-bucket an array column's slot width (grow or shrink)."""
        assert self.is_array_like
        w = self.array_width
        if new_width == w:
            return self
        cap = self.capacity
        r = jnp.arange(cap, dtype=jnp.int32)[:, None]
        j = jnp.arange(new_width, dtype=jnp.int32)[None, :]
        in_range = j < w
        child_idx = jnp.where(in_range, r * w + jnp.minimum(j, w - 1),
                              0).reshape(-1)
        child_valid = (in_range & (j < self.lengths[:, None])).reshape(-1)
        children = tuple(c.gather(child_idx, child_valid)
                         for c in self.children)
        lengths = jnp.minimum(self.lengths, new_width)
        return DeviceColumn(self.dtype, None, self.validity, lengths, None,
                            children)


def _fix_1d(arr, new_capacity: int, fill):
    if arr is None:
        return None
    cap = arr.shape[0]
    if cap == new_capacity:
        return arr
    if cap > new_capacity:
        return arr[:new_capacity]
    return jnp.pad(arr, (0, new_capacity - cap), constant_values=fill)


def make_array_column(dtype: DataType, lengths: jnp.ndarray,
                      children: Tuple["DeviceColumn", ...],
                      validity: Optional[jnp.ndarray] = None) -> DeviceColumn:
    """ARRAY/MAP column: ``children`` hold capacity*width flattened rows
    (one child for arrays; (keys, values) for maps)."""
    if validity is None:
        validity = jnp.ones(lengths.shape[0], dtype=bool)
    return DeviceColumn(dtype, None, validity, lengths=lengths,
                        children=tuple(children))


def make_fixed_column(dtype: DataType, data: jnp.ndarray,
                      validity: Optional[jnp.ndarray] = None) -> DeviceColumn:
    if validity is None:
        validity = jnp.ones(data.shape[0], dtype=bool)
    return DeviceColumn(dtype, data, validity)


def make_string_column(dtype: DataType, chars: jnp.ndarray,
                       lengths: jnp.ndarray,
                       validity: Optional[jnp.ndarray] = None) -> DeviceColumn:
    if validity is None:
        validity = jnp.ones(chars.shape[0], dtype=bool)
    return DeviceColumn(dtype, chars, validity, lengths=lengths)


def null_column(dtype: DataType, capacity: int) -> DeviceColumn:
    """All-null column of the given type."""
    validity = jnp.zeros(capacity, dtype=bool)
    if isinstance(dtype, ArrayType):
        child = null_column(dtype.element_type, capacity * _MIN_WIDTH)
        return make_array_column(dtype, jnp.zeros(capacity, dtype=jnp.int32),
                                 (child,), validity)
    if isinstance(dtype, MapType):
        keys = null_column(dtype.key_type, capacity * _MIN_WIDTH)
        vals = null_column(dtype.value_type, capacity * _MIN_WIDTH)
        return make_array_column(dtype, jnp.zeros(capacity, dtype=jnp.int32),
                                 (keys, vals), validity)
    if isinstance(dtype, StructType):
        children = tuple(null_column(f.data_type, capacity) for f in dtype.fields)
        return DeviceColumn(dtype, None, validity, children=children)
    if is_string_like(dtype):
        chars = jnp.zeros((capacity, _MIN_WIDTH), dtype=jnp.uint8)
        lengths = jnp.zeros(capacity, dtype=jnp.int32)
        return DeviceColumn(dtype, chars, validity, lengths=lengths)
    np_dtype = dtype.np_dtype if dtype.np_dtype is not None else np.dtype(np.int8)
    data = jnp.zeros(capacity, dtype=np_dtype)
    aux = jnp.zeros(capacity, dtype=jnp.int64) if (
        isinstance(dtype, DecimalType) and not dtype.is_long_backed) else None
    return DeviceColumn(dtype, data, validity, aux=aux)


def scalar_column(dtype: DataType, value: Any, capacity: int) -> DeviceColumn:
    """Broadcast a host scalar to a device column (cudf ``Scalar`` analog)."""
    if value is None:
        return null_column(dtype, capacity)
    validity = jnp.ones(capacity, dtype=bool)
    if is_string_like(dtype):
        raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        width = bucket_width(len(raw))
        row = np.zeros(width, dtype=np.uint8)
        row[:len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        chars = jnp.broadcast_to(jnp.asarray(row), (capacity, width))
        lengths = jnp.full(capacity, len(raw), dtype=jnp.int32)
        return DeviceColumn(dtype, chars, validity, lengths=lengths)
    if isinstance(dtype, DecimalType):
        import decimal
        unscaled = int(decimal.Decimal(value).scaleb(dtype.scale).to_integral_value())
        if dtype.is_long_backed:
            data = jnp.full(capacity, unscaled, dtype=jnp.int64)
            return DeviceColumn(dtype, data, validity)
        lo = unscaled & ((1 << 64) - 1)
        lo = lo - (1 << 64) if lo >= (1 << 63) else lo
        hi = unscaled >> 64
        return DeviceColumn(dtype, jnp.full(capacity, lo, dtype=jnp.int64),
                            validity, aux=jnp.full(capacity, hi, dtype=jnp.int64))
    import datetime as _dt
    from ..types import DateType, TimestampType
    if isinstance(dtype, DateType) and isinstance(value, _dt.date):
        value = (value - _dt.date(1970, 1, 1)).days
    elif isinstance(dtype, TimestampType) and isinstance(value, _dt.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=_dt.timezone.utc)
        value = int(value.timestamp() * 1_000_000)
    data = jnp.full(capacity, value, dtype=dtype.np_dtype)
    return DeviceColumn(dtype, data, validity)
