"""Host(Arrow) <-> device(JAX) batch conversion.

This is the TPU analog of the reference's transition layer:
``HostColumnarToGpu`` / ``GpuColumnarToRowExec`` / ``GpuRowToColumnarExec``
(SURVEY §2.2) with Arrow as the host columnar format.  Host decode is
vectorized numpy over Arrow buffers (no per-row Python) and the device upload
is a single ``jnp.asarray`` per buffer.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from ..observability import tracer as _trace
from .batch import ColumnarBatch
from .column import (DeviceColumn, bucket_capacity, bucket_width,
                     is_string_like, null_column)


# --------------------------------------------------------------------------
# Bulk device -> host fetch (single-pull D2H)
# --------------------------------------------------------------------------

#: compiled pack programs keyed by the leaf signature
_PACK_CACHE: dict = {}


def _word_packable(dt: str) -> bool:
    """Dtypes the pack program can turn into uint32 words on the TPU
    toolchain.  64-bit types can't use bitcast-convert (the X64-rewrite
    pass doesn't implement it) — ints split arithmetically and f64 goes
    through :func:`_f64_bits` (arithmetic IEEE-754 bit extraction)."""
    if dt == "bool":
        return True
    d = np.dtype(dt)
    if d.kind == "f":
        if d.itemsize == 4:
            return True
        if d.itemsize == 8:
            # exact bits on CPU; double-float pair on TPU unless the user
            # opted into storage-fidelity fetches
            return not _f64_as_pair() or _pack_f64_enabled()
        return False
    return d.kind in ("i", "u") and d.itemsize in (1, 2, 4, 8)


def u64_to_i64(u):
    """Two's-complement uint64 -> int64 WITHOUT 64-bit bitcast-convert
    (the TPU X64 rewrite doesn't implement it).  The one shared copy of
    this trick — device_parquet and ranks.f64_bits_i64 both route here."""
    big = u >= (jnp.uint64(1) << jnp.uint64(63))
    low = (u & jnp.uint64((1 << 63) - 1)).astype(jnp.int64)
    int64_min = jnp.int64(-(2 ** 62)) + jnp.int64(-(2 ** 62))
    return jnp.where(big, low + int64_min, low)


def _f64_bits(x):
    """IEEE-754 bit pattern of float64 as uint64, WITHOUT bitcast-convert
    (traced; exact).  The exponent is recovered by a 10-step power-of-two
    binary search — every multiply is by an exact power of two, so the
    normalized mantissa m ∈ [1,2) is the value's own 53-bit mantissa and
    ``(m-1)*2^52`` converts to uint64 exactly.  NaNs canonicalize to the
    quiet NaN (payloads are not preserved — Spark normalizes NaNs).
    Denormals encode as signed zero: XLA flushes f64 denormals to zero in
    EVERY operation on these backends (DAZ — even ``x == 0`` is true for
    them), so this matches the engine's own arithmetic semantics."""
    ax = jnp.abs(x)
    neg_zero = (x == 0.0) & (1.0 / x < 0)
    sign = jnp.where((x < 0) | neg_zero, jnp.uint64(1), jnp.uint64(0))
    m = ax
    e = jnp.zeros(x.shape, jnp.int32)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        big = m >= (2.0 ** k)
        m = jnp.where(big, m * (2.0 ** -k), m)
        e = e + jnp.where(big, k, 0)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        small = m < (2.0 ** (1 - k))
        m = jnp.where(small, m * (2.0 ** k), m)
        e = e - jnp.where(small, k, 0)
    normal = e >= -1022
    exp_field = jnp.where(normal, (e + 1023).astype(jnp.uint64),
                          jnp.uint64(0))
    mant = jnp.where(normal, ((m - 1.0) * (2.0 ** 52)).astype(jnp.uint64),
                     jnp.uint64(0))
    bits = (sign << jnp.uint64(63)) | (exp_field << jnp.uint64(52)) | mant
    bits = jnp.where(ax == 0.0, sign << jnp.uint64(63), bits)
    bits = jnp.where(jnp.isinf(x),
                     (sign << jnp.uint64(63)) | jnp.uint64(0x7FF0000000000000),
                     bits)
    bits = jnp.where(jnp.isnan(x), jnp.uint64(0x7FF8000000000000), bits)
    return bits


def _to_words(a):
    """Flatten one device array to little-endian uint32 words (traced).
    64-bit types are split arithmetically — the TPU toolchain's X64
    rewrite does not implement 64-bit bitcast-convert; sub-32-bit types
    pad to 4 bytes and pack 4 per word."""
    import jax
    a = a.reshape(-1)
    if a.dtype == jnp.bool_:
        a = a.astype(jnp.uint8)
    isz = a.dtype.itemsize
    if isz == 4:
        return jax.lax.bitcast_convert_type(a, jnp.uint32)
    if isz == 8:
        if jnp.issubdtype(a.dtype, jnp.floating):
            if jax.default_backend() == "cpu":
                u = _f64_bits(a)  # native f64: exact bit extraction
            else:
                # TPU "f64" is a double-float (f32 hi/lo pair — values
                # beyond f32 exponent range are already inf ON DEVICE and
                # plain device_get can't round-trip true f64 either).
                # The (hi, lo) pair IS the device's exact representation.
                # lo is rescaled by an exact power of two picked from
                # |hi|'s magnitude so it never lands in the f32-denormal
                # range (the TPU flushes those to zero); the host decoder
                # re-derives the same scale from hi.
                hi32 = a.astype(jnp.float32)
                ahi = jnp.abs(hi32)
                scale = jnp.where(ahi < 2.0 ** -30, 2.0 ** 64,
                                  jnp.where(ahi > 2.0 ** 97, 2.0 ** -64,
                                            1.0)).astype(a.dtype)
                lo32 = jnp.where(jnp.isfinite(hi32),
                                 ((a - hi32.astype(a.dtype)) * scale)
                                 .astype(jnp.float32),
                                 jnp.float32(0))
                pair = jnp.stack([hi32, lo32], axis=-1).reshape(-1)
                return jax.lax.bitcast_convert_type(pair, jnp.uint32)
        else:
            u = a.astype(jnp.uint64)
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return jnp.stack([lo, hi], axis=-1).reshape(-1)
    # 1- or 2-byte: widen to u32 lanes via [m,4]-u8 -> u32 bitcast
    b = jax.lax.bitcast_convert_type(a, jnp.uint8).reshape(-1) \
        if isz > 1 else a.astype(jnp.uint8)
    pad = (-b.size) % 4
    if pad:
        b = jnp.concatenate([b, jnp.zeros(pad, jnp.uint8)])
    return jax.lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)


def pack_leaves_traced(arrs, sig):
    """Traced body: pack device leaves into (words, other0, other1, ...).

    Word-packable leaves (bools, ints, f32) become ONE uint32 vector with
    8-byte-aligned segments; every other dtype (f64, ...) concatenates
    into one flat vector per dtype (sorted-dtype order) — no bitcast, so
    the X64-rewrite restriction doesn't apply.  Composable inside larger
    jitted programs (the whole-query tail fusion) or jitted alone."""
    other_dts = sorted({dt for _, dt in sig if not _word_packable(dt)})
    parts = []
    groups = {dt: [] for dt in other_dts}
    for a, (_, dt) in zip(arrs, sig):
        if not _word_packable(dt):
            groups[dt].append(a.reshape(-1))
            continue
        w = _to_words(a).reshape(-1)
        if w.size % 2:  # 8-byte-align segments (2 words)
            w = jnp.concatenate([w, jnp.zeros(1, jnp.uint32)])
        parts.append(w)
    words = (jnp.concatenate(parts) if len(parts) > 1
             else parts[0] if parts else jnp.zeros(0, jnp.uint32))
    others = []
    for dt in other_dts:
        g = groups[dt]
        others.append(jnp.concatenate(g) if len(g) > 1
                      else g[0] if g else jnp.zeros(0, np.dtype(dt)))
    return (words,) + tuple(others)


def unpack_buffers(host_bufs, sig):
    """Invert :func:`pack_leaves_traced` on fetched numpy buffers; returns
    the host leaves in signature order."""
    words = host_bufs[0].view(np.uint8)
    other_dts = sorted({dt for _, dt in sig if not _word_packable(dt)})
    other_buf = dict(zip(other_dts, host_bufs[1:]))
    other_off = {dt: 0 for dt in other_dts}
    out = []
    off = 0
    for shape, dt in sig:
        count = 1
        for s in shape:
            count *= s
        if not _word_packable(dt):
            o = other_off[dt]
            out.append(other_buf[dt][o:o + count].reshape(shape))
            other_off[dt] = o + count
            continue
        want_bool = dt == "bool"
        np_dt = np.dtype("uint8") if want_bool else np.dtype(dt)
        seg = count * np_dt.itemsize
        if np_dt == np.float64 and _f64_as_pair():
            pair = np.frombuffer(words, np.float32, count=2 * count,
                                 offset=off).reshape(-1, 2)
            hi = pair[:, 0].astype(np.float64)
            ahi = np.abs(pair[:, 0])
            scale = np.where(ahi < 2.0 ** -30, 2.0 ** -64,
                             np.where(ahi > 2.0 ** 97, 2.0 ** 64, 1.0))
            a = hi + pair[:, 1] * scale
        else:
            a = np.frombuffer(words, np_dt, count=count, offset=off)
        if want_bool:
            a = a.view(np.bool_)
        out.append(a.reshape(shape))
        off += seg + ((-seg) % 8)
    return out


def _f64_as_pair() -> bool:
    """Whether f64 words were packed as (hi, lo) float32 pairs (non-CPU
    backends — see :func:`_to_words`).  The pair is bit-faithful to every
    f64 the device can COMPUTE (its arithmetic flushes f32-denormal low
    components exactly like the extraction does); only raw storage of
    uploaded tiny values (~<1e-29) differs, gated by
    ``spark.rapids.tpu.d2h.packFloat64``."""
    import jax
    return jax.default_backend() != "cpu"


def _pack_f64_enabled() -> bool:
    from ..config import D2H_PACK_F64, RapidsConf
    try:
        return bool(RapidsConf.get_global().get(D2H_PACK_F64))
    except Exception:  # pragma: no cover
        return True


def _pack_program(sig):
    """Compiled pack program for :func:`bulk_device_get` (signature-keyed)."""
    import jax
    return jax.jit(lambda *arrs: pack_leaves_traced(arrs, sig))


def bulk_device_get(tree):
    """``jax.device_get`` with one transfer for the whole pytree: device
    leaves are byte-packed by a compiled kernel and unpacked from the one
    fetched buffer on the host; non-device leaves pass through unchanged."""
    import jax
    from ..robustness import faults as _faults
    from ..shims import tree_flatten
    _faults.maybe_inject("transfer.d2h", exc=ConnectionError)
    leaves, treedef = tree_flatten(tree)
    dev_idx = [i for i, l in enumerate(leaves)
               if isinstance(l, jax.Array) and not isinstance(l, np.ndarray)]
    if not dev_idx:
        return tree
    devs = [leaves[i] for i in dev_idx]
    sig = tuple((l.shape, str(l.dtype)) for l in devs)
    for _, dt in sig:
        if dt == "bool":
            continue
        try:
            np.dtype(dt)
        except TypeError:
            # e.g. bfloat16: numpy can't view it
            with _trace.span("d2h", "device_get.fallback", leaves=len(devs)):
                return jax.device_get(tree)
    # layout depends on the f64 encoding mode (backend + packFloat64
    # config), which can change mid-session — it must be part of the key
    cache_key = (sig, _f64_as_pair(), _pack_f64_enabled())
    pack = _PACK_CACHE.get(cache_key)
    if pack is None:
        pack = _PACK_CACHE[cache_key] = _pack_program(sig)
        if len(_PACK_CACHE) > 512:
            _PACK_CACHE.clear()
            _PACK_CACHE[cache_key] = pack
    tracing = _trace.TRACING["on"]
    t0 = time.perf_counter() if tracing else 0.0
    try:
        bufs = pack(*devs)
        for b in bufs:  # overlap the (few) transfers: one latency, not N
            b.copy_to_host_async()
        host = [np.asarray(b) for b in bufs]
    except Exception:
        # e.g. an exotic dtype the pack program can't lower on this
        # toolchain — correctness first, one pull per leaf as before
        with _trace.span("d2h", "device_get.fallback", leaves=len(devs)):
            return jax.device_get(tree)
    if tracing:
        _trace.get_tracer().complete(
            "d2h", "bulk_device_get", t0, time.perf_counter() - t0,
            bytes=sum(b.nbytes for b in host), leaves=len(devs))
    for i, leaf in zip(dev_idx, unpack_buffers(host, sig)):
        leaves[i] = leaf
    from ..shims import tree_unflatten
    return tree_unflatten(treedef, leaves)


# --------------------------------------------------------------------------
# Arrow -> device
# --------------------------------------------------------------------------

def split_ragged_strings(table: pa.Table,
                         threshold_bytes: int = 16 << 20,
                         min_saving: float = 4.0) -> list:
    """Split a table whose PADDED string footprint would blow up.

    The device string layout is a ``[capacity, width]`` byte matrix with
    width = the batch's max row length bucketed to a power of two — one
    10KB string makes every row pay 16KB (VERDICT r2 weak #5; the
    reference avoids this with cuDF's offsets+chars layout).  The
    TPU-native answer keeps every kernel's static shapes intact: cut the
    batch into width classes, so short rows ride a narrow matrix and the
    few long rows ride a small wide one.  Row order is not preserved
    (Spark makes no ordering promise before a sort).

    Returns [table] when splitting is unnecessary or unhelpful.
    """
    from .column import bucket_capacity, bucket_width
    n = table.num_rows
    if n < 2:
        return [table]
    str_cols = [i for i, f in enumerate(table.schema)
                if pa.types.is_string(f.type) or pa.types.is_binary(f.type)
                or pa.types.is_large_string(f.type)
                or pa.types.is_large_binary(f.type)]
    if not str_cols:
        return [table]
    cap = bucket_capacity(n)
    # per-row max length across string columns decides the row's class
    row_max = np.zeros(n, dtype=np.int64)
    widths = []
    for ci in str_cols:
        col = table.column(ci)
        lens = pa.compute.binary_length(col).fill_null(0)
        lens_np = lens.to_numpy(zero_copy_only=False).astype(np.int64)
        widths.append(bucket_width(int(lens_np.max()) if n else 0))
        np.maximum(row_max, lens_np, out=row_max)
    footprint = cap * sum(widths)
    if footprint <= threshold_bytes:
        return [table]
    # short class at the 99th-percentile width; only split when it
    # actually pays
    w_short = bucket_width(int(np.percentile(row_max, 99.0)))
    long_mask = row_max > w_short
    n_long = int(long_mask.sum())
    if n_long == 0 or n_long == n:
        return [table]
    w_full = bucket_width(int(row_max.max()))
    after = (bucket_capacity(n - n_long) * len(str_cols) * w_short
             + bucket_capacity(n_long) * len(str_cols) * w_full)
    if footprint < after * min_saving:
        return [table]
    mask = pa.array(long_mask)
    return [table.filter(pa.compute.invert(mask)), table.filter(mask)]


def split_for_upload(table: pa.Table, conf=None) -> list:
    """Conf-gated :func:`split_ragged_strings` — the one place scan paths
    read the threshold, so the in-memory and file-scan gates can't
    drift."""
    from ..config import RAGGED_STRING_SPLIT_BYTES, RapidsConf
    thr = int((conf or RapidsConf.get_global())
              .get(RAGGED_STRING_SPLIT_BYTES))
    return split_ragged_strings(table, thr) if thr > 0 else [table]


def arrow_to_device(table: pa.Table, capacity: Optional[int] = None,
                    conf=None) -> ColumnarBatch:
    from ..robustness import faults as _faults
    n = table.num_rows
    cap = capacity or bucket_capacity(n)
    _faults.maybe_inject("transfer.h2d", exc=ConnectionError,
                         bytes=table.nbytes)
    with _trace.span("h2d", "arrow_to_device", bytes=table.nbytes, rows=n):
        cols = [arrow_to_device_column(table.column(i), cap, conf=conf)
                for i in range(table.num_columns)]
        return ColumnarBatch.make(table.column_names, cols, n)


def arrow_to_device_column(arr, capacity: int, conf=None) -> DeviceColumn:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dtype = T.from_arrow(arr.type)
    n = len(arr)
    valid_np = np.zeros(capacity, dtype=bool)
    if n:
        valid_np[:n] = _valid_mask(arr)
    validity = jnp.asarray(valid_np)

    if isinstance(dtype, T.NullType):
        return null_column(dtype, capacity).with_validity(validity)

    if isinstance(dtype, (T.ArrayType, T.MapType)):
        return _list_to_device(arr, dtype, capacity, validity, n, conf=conf)

    if isinstance(dtype, T.StructType):
        children = tuple(arrow_to_device_column(arr.field(i), capacity,
                                                conf=conf)
                         for i in range(arr.type.num_fields))
        return DeviceColumn(dtype, None, validity, children=children)

    if is_string_like(dtype):
        # scan-side encoded retention: low-cardinality strings stay as
        # codes + dictionary (columnar/encoded.py) instead of eagerly
        # materializing the padded byte matrix — the decline path falls
        # through to the raw layout below
        from .encoded import enabled as _enc_on, encode_string_arrow
        if _enc_on(conf):
            enc = encode_string_arrow(arr, dtype, capacity, conf=conf)
            if enc is not None:
                return enc
        chars, lengths = _strings_to_matrix(arr, capacity)
        return DeviceColumn(dtype, jnp.asarray(chars), validity,
                            lengths=jnp.asarray(lengths))

    if isinstance(dtype, T.DecimalType):
        lo, hi = _decimal_words(arr, capacity)
        aux = jnp.asarray(hi) if not dtype.is_long_backed else None
        return DeviceColumn(dtype, jnp.asarray(lo), validity, aux=aux)

    np_data = _fixed_to_numpy(arr, dtype)
    out = np.zeros(capacity, dtype=dtype.np_dtype)
    out[:n] = np_data
    out[:n][~valid_np[:n]] = 0  # dead data zeroed for deterministic kernels
    if np.dtype(dtype.np_dtype).kind in ("i", "u"):
        from .encoded import enabled as _enc_on, encode_rle_numpy
        if _enc_on(conf):
            rle = encode_rle_numpy(dtype, out, valid_np, n, capacity)
            if rle is not None:
                return rle
    return DeviceColumn(dtype, jnp.asarray(out), validity)


def _list_to_device(arr, dtype, capacity: int, validity, n: int, conf=None
                    ) -> DeviceColumn:
    """Arrow List/Map -> padded row-block layout: child element r*w+j is
    slot j of row r; slots past the row's length are dead."""
    from .column import make_array_column
    if isinstance(arr.type, pa.MapType):
        arr = arr.cast(pa.map_(arr.type.key_type, arr.type.item_type))
        offsets = np.asarray(arr.offsets)
        child_arrays = [arr.keys, arr.items]
    else:
        if pa.types.is_large_list(arr.type):
            arr = arr.cast(pa.list_(arr.type.value_type))
        offsets = np.asarray(arr.offsets)
        child_arrays = [arr.values]
    lengths_np = (offsets[1:] - offsets[:-1]).astype(np.int32)
    valid_np = np.asarray(validity)[:n]
    lengths_np = np.where(valid_np, lengths_np, 0)
    width = bucket_width(int(lengths_np.max()) if n else 0)
    # take-index into the flattened arrow child; None -> null (dead slot)
    take = np.full(capacity * width, -1, dtype=np.int64)
    if n:
        row = np.repeat(np.arange(n), lengths_np)
        slot = np.arange(lengths_np.sum()) - np.repeat(
            np.cumsum(lengths_np) - lengths_np, lengths_np)
        src = np.repeat(offsets[:-1].astype(np.int64), lengths_np) + slot
        take[row * width + slot] = src
    import pyarrow.compute as pc
    idx = _null_take_indices(take)
    children = []
    for ch in child_arrays:
        if isinstance(ch, pa.ChunkedArray):
            ch = ch.combine_chunks()
        children.append(arrow_to_device_column(pc.take(ch, idx),
                                               capacity * width, conf=conf))
    lengths = np.zeros(capacity, dtype=np.int32)
    lengths[:n] = lengths_np
    return make_array_column(dtype, jnp.asarray(lengths), tuple(children),
                             validity)


def _null_take_indices(take: np.ndarray) -> pa.Array:
    """int64 indices with nulls where take < 0 (pyarrow take -> null)."""
    mask = take < 0
    safe = np.where(mask, 0, take)
    return pa.Array.from_buffers(
        pa.int64(), len(take),
        [pa.py_buffer(np.packbits(~mask, bitorder="little").tobytes()),
         pa.py_buffer(safe.astype(np.int64).tobytes())])


def _valid_mask(arr: pa.Array) -> np.ndarray:
    if arr.null_count == 0:
        return np.ones(len(arr), dtype=bool)
    return np.asarray(arr.is_valid())


def _fixed_to_numpy(arr: pa.Array, dtype: T.DataType) -> np.ndarray:
    if isinstance(dtype, T.DateType):
        arr = arr.cast(pa.int32())
    elif isinstance(dtype, T.TimestampType):
        arr = arr.cast(pa.timestamp("us")).cast(pa.int64())
    elif isinstance(dtype, T.BooleanType):
        pass
    if arr.null_count:
        zero = pa.scalar(False if pa.types.is_boolean(arr.type) else 0, type=arr.type)
        arr = arr.fill_null(zero)
    return np.asarray(arr.to_numpy(zero_copy_only=False)).astype(
        dtype.np_dtype, copy=False)


def _strings_to_matrix(arr: pa.Array, capacity: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    if pa.types.is_large_string(arr.type):
        arr = arr.cast(pa.string())
    elif pa.types.is_large_binary(arr.type):
        arr = arr.cast(pa.binary())
    n = len(arr)
    if arr.null_count:
        arr = arr.fill_null("" if pa.types.is_string(arr.type) else b"")
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int32,
                            count=(arr.offset + n + 1))[arr.offset:]
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None else \
        np.zeros(0, dtype=np.uint8)
    starts = offsets[:-1].astype(np.int64)  # absolute buffer positions
    lengths_np = (offsets[1:] - offsets[:-1]).astype(np.int32)
    width = bucket_width(int(lengths_np.max()) if n else 0)
    if n:
        # native single-pass pack (no O(total-bytes) index temporaries);
        # the numpy path below is the toolchain-free fallback
        from ..native import pack_strings as _native_pack
        packed = _native_pack(data, offsets.astype(np.int64), width,
                              capacity)
        if packed is not None:
            return packed
    chars = np.zeros((capacity, width), dtype=np.uint8)
    total = int(lengths_np.sum())
    if total:
        # within-row byte index is relative to each row's own start, not to
        # the raw buffer offset (which is nonzero for sliced arrays)
        local_starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths_np[:-1], out=local_starts[1:])
        row_idx = np.repeat(np.arange(n), lengths_np)
        within = np.arange(total) - np.repeat(local_starts, lengths_np)
        chars[row_idx, within] = data[np.repeat(starts, lengths_np) + within]
    lengths = np.zeros(capacity, dtype=np.int32)
    lengths[:n] = lengths_np
    return chars, lengths


def _decimal_words(arr: pa.Array, capacity: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    n = len(arr)
    bufs = arr.buffers()
    words = (np.frombuffer(bufs[1], dtype=np.int64)
             [(arr.offset * 2):(arr.offset + n) * 2]
             if bufs[1] is not None else np.zeros(0, dtype=np.int64))
    lo = np.zeros(capacity, dtype=np.int64)
    hi = np.zeros(capacity, dtype=np.int64)
    if n:
        lo[:n] = words[0::2]
        hi[:n] = words[1::2]
        mask = ~_valid_mask(arr)
        lo[:n][mask] = 0
        hi[:n][mask] = 0
    return lo, hi


# --------------------------------------------------------------------------
# device -> Arrow
# --------------------------------------------------------------------------

def device_to_arrow(batch: ColumnarBatch) -> pa.Table:
    # ONE bulk transfer for every leaf: per-array pulls each cost a full
    # host<->device round trip (~65ms over the TPU tunnel); large batches
    # additionally narrow on device first (columnar/prepack.py)
    from .prepack import prepacked_device_get
    batch = prepacked_device_get(batch)
    n = batch.num_rows_int
    arrays = [device_column_to_arrow(c, n) for c in batch.columns]
    return pa.table(arrays, names=list(batch.names))


def device_column_to_arrow(col: DeviceColumn, n: int) -> pa.Array:
    dtype = col.dtype
    valid = np.asarray(col.validity)[:n] if col.validity is not None else \
        np.ones(n, dtype=bool)
    mask = ~valid  # pyarrow mask semantics: True = null

    if isinstance(dtype, T.NullType):
        return pa.nulls(n)

    if isinstance(dtype, (T.ArrayType, T.MapType)):
        w = col.array_width
        lens = np.asarray(col.lengths)[:n].astype(np.int64)
        lens = np.where(valid, lens, 0)
        total = int(lens.sum())
        # child rows live at r*w .. r*w+len-1
        starts = np.cumsum(lens) - lens
        row = np.repeat(np.arange(n), lens)
        slot = np.arange(total) - np.repeat(starts, lens)
        child_idx = row * w + slot
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        kids = []
        for ch in col.children:
            flat = device_column_to_arrow(ch, ch.capacity)
            kids.append(flat.take(pa.array(child_idx, type=pa.int64())))
        # null rows: nulls in the offsets array mark null lists/maps
        off = pa.array(offsets,
                       mask=np.append(mask, False) if mask.any() else None)
        if isinstance(dtype, T.MapType):
            out = pa.MapArray.from_arrays(off, kids[0], kids[1])
        else:
            out = pa.ListArray.from_arrays(off, kids[0])
        return out.cast(T.to_arrow(dtype))

    if isinstance(dtype, T.StructType):
        children = [device_column_to_arrow(c, n) for c in col.children]
        return pa.StructArray.from_arrays(
            children, names=list(dtype.names),
            mask=pa.array(mask) if mask.any() else None)

    if is_string_like(dtype):
        return _matrix_to_strings(col, n, mask,
                                  binary=isinstance(dtype, T.BinaryType))

    if isinstance(dtype, T.DecimalType):
        lo = np.asarray(col.data)[:n]
        hi = (np.asarray(col.aux)[:n] if col.aux is not None
              else np.where(lo < 0, -1, 0).astype(np.int64))
        words = np.empty(n * 2, dtype=np.int64)
        words[0::2] = lo
        words[1::2] = hi
        return pa.Array.from_buffers(
            pa.decimal128(dtype.precision, dtype.scale), n,
            [_bitmap(valid), pa.py_buffer(words.tobytes())])

    data = np.asarray(col.data)[:n]
    if isinstance(dtype, T.DateType):
        return pa.array(data.astype(np.int32), type=pa.date32(),
                        mask=mask if mask.any() else None)
    if isinstance(dtype, T.TimestampType):
        return pa.array(data.astype(np.int64),
                        type=pa.timestamp("us", tz="UTC"),
                        mask=mask if mask.any() else None)
    return pa.array(data, type=T.to_arrow(dtype),
                    mask=mask if mask.any() else None)


def _bitmap(valid: np.ndarray) -> Optional[pa.Buffer]:
    if valid.all():
        return None
    return pa.py_buffer(np.packbits(valid, bitorder="little").tobytes())


def _matrix_to_strings(col: DeviceColumn, n: int, mask: np.ndarray,
                       binary: bool) -> pa.Array:
    chars = np.asarray(col.data)[:n]
    lengths = np.asarray(col.lengths)[:n].astype(np.int64)
    lengths = np.where(mask, 0, lengths)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    flat = np.zeros(total, dtype=np.uint8)
    if total:
        row_idx = np.repeat(np.arange(n), lengths)
        col_idx = np.arange(total) - np.repeat(offsets[:-1].astype(np.int64), lengths)
        flat[:] = chars[row_idx, col_idx]
    at = pa.binary() if binary else pa.utf8()
    return pa.Array.from_buffers(
        at, n, [_bitmap(~mask), pa.py_buffer(offsets.tobytes()),
                pa.py_buffer(flat.tobytes())])


# --------------------------------------------------------------------------
# pandas convenience
# --------------------------------------------------------------------------

def pandas_to_device(df) -> ColumnarBatch:
    return arrow_to_device(pa.Table.from_pandas(df, preserve_index=False))


def device_to_pandas(batch: ColumnarBatch):
    return device_to_arrow(batch).to_pandas()
