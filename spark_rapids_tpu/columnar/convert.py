"""Host(Arrow) <-> device(JAX) batch conversion.

This is the TPU analog of the reference's transition layer:
``HostColumnarToGpu`` / ``GpuColumnarToRowExec`` / ``GpuRowToColumnarExec``
(SURVEY §2.2) with Arrow as the host columnar format.  Host decode is
vectorized numpy over Arrow buffers (no per-row Python) and the device upload
is a single ``jnp.asarray`` per buffer.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as T
from .batch import ColumnarBatch
from .column import (DeviceColumn, bucket_capacity, bucket_width,
                     is_string_like, null_column)


# --------------------------------------------------------------------------
# Arrow -> device
# --------------------------------------------------------------------------

def arrow_to_device(table: pa.Table, capacity: Optional[int] = None
                    ) -> ColumnarBatch:
    n = table.num_rows
    cap = capacity or bucket_capacity(n)
    cols = [arrow_to_device_column(table.column(i), cap)
            for i in range(table.num_columns)]
    return ColumnarBatch.make(table.column_names, cols, n)


def arrow_to_device_column(arr, capacity: int) -> DeviceColumn:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dtype = T.from_arrow(arr.type)
    n = len(arr)
    valid_np = np.zeros(capacity, dtype=bool)
    if n:
        valid_np[:n] = _valid_mask(arr)
    validity = jnp.asarray(valid_np)

    if isinstance(dtype, T.NullType):
        return null_column(dtype, capacity).with_validity(validity)

    if isinstance(dtype, (T.ArrayType, T.MapType)):
        return _list_to_device(arr, dtype, capacity, validity, n)

    if isinstance(dtype, T.StructType):
        children = tuple(arrow_to_device_column(arr.field(i), capacity)
                         for i in range(arr.type.num_fields))
        return DeviceColumn(dtype, None, validity, children=children)

    if is_string_like(dtype):
        chars, lengths = _strings_to_matrix(arr, capacity)
        return DeviceColumn(dtype, jnp.asarray(chars), validity,
                            lengths=jnp.asarray(lengths))

    if isinstance(dtype, T.DecimalType):
        lo, hi = _decimal_words(arr, capacity)
        aux = jnp.asarray(hi) if not dtype.is_long_backed else None
        return DeviceColumn(dtype, jnp.asarray(lo), validity, aux=aux)

    np_data = _fixed_to_numpy(arr, dtype)
    out = np.zeros(capacity, dtype=dtype.np_dtype)
    out[:n] = np_data
    out[:n][~valid_np[:n]] = 0  # dead data zeroed for deterministic kernels
    return DeviceColumn(dtype, jnp.asarray(out), validity)


def _list_to_device(arr, dtype, capacity: int, validity, n: int
                    ) -> DeviceColumn:
    """Arrow List/Map -> padded row-block layout: child element r*w+j is
    slot j of row r; slots past the row's length are dead."""
    from .column import make_array_column
    if isinstance(arr.type, pa.MapType):
        arr = arr.cast(pa.map_(arr.type.key_type, arr.type.item_type))
        offsets = np.asarray(arr.offsets)
        child_arrays = [arr.keys, arr.items]
    else:
        if pa.types.is_large_list(arr.type):
            arr = arr.cast(pa.list_(arr.type.value_type))
        offsets = np.asarray(arr.offsets)
        child_arrays = [arr.values]
    lengths_np = (offsets[1:] - offsets[:-1]).astype(np.int32)
    valid_np = np.asarray(validity)[:n]
    lengths_np = np.where(valid_np, lengths_np, 0)
    width = bucket_width(int(lengths_np.max()) if n else 0)
    # take-index into the flattened arrow child; None -> null (dead slot)
    take = np.full(capacity * width, -1, dtype=np.int64)
    if n:
        row = np.repeat(np.arange(n), lengths_np)
        slot = np.arange(lengths_np.sum()) - np.repeat(
            np.cumsum(lengths_np) - lengths_np, lengths_np)
        src = np.repeat(offsets[:-1].astype(np.int64), lengths_np) + slot
        take[row * width + slot] = src
    import pyarrow.compute as pc
    idx = _null_take_indices(take)
    children = []
    for ch in child_arrays:
        if isinstance(ch, pa.ChunkedArray):
            ch = ch.combine_chunks()
        children.append(arrow_to_device_column(pc.take(ch, idx),
                                               capacity * width))
    lengths = np.zeros(capacity, dtype=np.int32)
    lengths[:n] = lengths_np
    return make_array_column(dtype, jnp.asarray(lengths), tuple(children),
                             validity)


def _null_take_indices(take: np.ndarray) -> pa.Array:
    """int64 indices with nulls where take < 0 (pyarrow take -> null)."""
    mask = take < 0
    safe = np.where(mask, 0, take)
    return pa.Array.from_buffers(
        pa.int64(), len(take),
        [pa.py_buffer(np.packbits(~mask, bitorder="little").tobytes()),
         pa.py_buffer(safe.astype(np.int64).tobytes())])


def _valid_mask(arr: pa.Array) -> np.ndarray:
    if arr.null_count == 0:
        return np.ones(len(arr), dtype=bool)
    return np.asarray(arr.is_valid())


def _fixed_to_numpy(arr: pa.Array, dtype: T.DataType) -> np.ndarray:
    if isinstance(dtype, T.DateType):
        arr = arr.cast(pa.int32())
    elif isinstance(dtype, T.TimestampType):
        arr = arr.cast(pa.timestamp("us")).cast(pa.int64())
    elif isinstance(dtype, T.BooleanType):
        pass
    if arr.null_count:
        zero = pa.scalar(False if pa.types.is_boolean(arr.type) else 0, type=arr.type)
        arr = arr.fill_null(zero)
    return np.asarray(arr.to_numpy(zero_copy_only=False)).astype(
        dtype.np_dtype, copy=False)


def _strings_to_matrix(arr: pa.Array, capacity: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    if pa.types.is_large_string(arr.type):
        arr = arr.cast(pa.string())
    elif pa.types.is_large_binary(arr.type):
        arr = arr.cast(pa.binary())
    n = len(arr)
    if arr.null_count:
        arr = arr.fill_null("" if pa.types.is_string(arr.type) else b"")
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int32,
                            count=(arr.offset + n + 1))[arr.offset:]
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None else \
        np.zeros(0, dtype=np.uint8)
    starts = offsets[:-1].astype(np.int64)  # absolute buffer positions
    lengths_np = (offsets[1:] - offsets[:-1]).astype(np.int32)
    width = bucket_width(int(lengths_np.max()) if n else 0)
    if n:
        # native single-pass pack (no O(total-bytes) index temporaries);
        # the numpy path below is the toolchain-free fallback
        from ..native import pack_strings as _native_pack
        packed = _native_pack(data, offsets.astype(np.int64), width,
                              capacity)
        if packed is not None:
            return packed
    chars = np.zeros((capacity, width), dtype=np.uint8)
    total = int(lengths_np.sum())
    if total:
        # within-row byte index is relative to each row's own start, not to
        # the raw buffer offset (which is nonzero for sliced arrays)
        local_starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths_np[:-1], out=local_starts[1:])
        row_idx = np.repeat(np.arange(n), lengths_np)
        within = np.arange(total) - np.repeat(local_starts, lengths_np)
        chars[row_idx, within] = data[np.repeat(starts, lengths_np) + within]
    lengths = np.zeros(capacity, dtype=np.int32)
    lengths[:n] = lengths_np
    return chars, lengths


def _decimal_words(arr: pa.Array, capacity: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    n = len(arr)
    bufs = arr.buffers()
    words = (np.frombuffer(bufs[1], dtype=np.int64)
             [(arr.offset * 2):(arr.offset + n) * 2]
             if bufs[1] is not None else np.zeros(0, dtype=np.int64))
    lo = np.zeros(capacity, dtype=np.int64)
    hi = np.zeros(capacity, dtype=np.int64)
    if n:
        lo[:n] = words[0::2]
        hi[:n] = words[1::2]
        mask = ~_valid_mask(arr)
        lo[:n][mask] = 0
        hi[:n][mask] = 0
    return lo, hi


# --------------------------------------------------------------------------
# device -> Arrow
# --------------------------------------------------------------------------

def device_to_arrow(batch: ColumnarBatch) -> pa.Table:
    # ONE bulk transfer for every leaf: per-array pulls each cost a full
    # host<->device round trip (~65ms over the TPU tunnel), while a single
    # device_get issues all copies concurrently — per-column conversion
    # below then touches only host memory
    import jax
    batch = jax.device_get(batch)
    n = batch.num_rows_int
    arrays = [device_column_to_arrow(c, n) for c in batch.columns]
    return pa.table(arrays, names=list(batch.names))


def device_column_to_arrow(col: DeviceColumn, n: int) -> pa.Array:
    dtype = col.dtype
    valid = np.asarray(col.validity)[:n] if col.validity is not None else \
        np.ones(n, dtype=bool)
    mask = ~valid  # pyarrow mask semantics: True = null

    if isinstance(dtype, T.NullType):
        return pa.nulls(n)

    if isinstance(dtype, (T.ArrayType, T.MapType)):
        w = col.array_width
        lens = np.asarray(col.lengths)[:n].astype(np.int64)
        lens = np.where(valid, lens, 0)
        total = int(lens.sum())
        # child rows live at r*w .. r*w+len-1
        starts = np.cumsum(lens) - lens
        row = np.repeat(np.arange(n), lens)
        slot = np.arange(total) - np.repeat(starts, lens)
        child_idx = row * w + slot
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        kids = []
        for ch in col.children:
            flat = device_column_to_arrow(ch, ch.capacity)
            kids.append(flat.take(pa.array(child_idx, type=pa.int64())))
        # null rows: nulls in the offsets array mark null lists/maps
        off = pa.array(offsets,
                       mask=np.append(mask, False) if mask.any() else None)
        if isinstance(dtype, T.MapType):
            out = pa.MapArray.from_arrays(off, kids[0], kids[1])
        else:
            out = pa.ListArray.from_arrays(off, kids[0])
        return out.cast(T.to_arrow(dtype))

    if isinstance(dtype, T.StructType):
        children = [device_column_to_arrow(c, n) for c in col.children]
        return pa.StructArray.from_arrays(
            children, names=list(dtype.names),
            mask=pa.array(mask) if mask.any() else None)

    if is_string_like(dtype):
        return _matrix_to_strings(col, n, mask,
                                  binary=isinstance(dtype, T.BinaryType))

    if isinstance(dtype, T.DecimalType):
        lo = np.asarray(col.data)[:n]
        hi = (np.asarray(col.aux)[:n] if col.aux is not None
              else np.where(lo < 0, -1, 0).astype(np.int64))
        words = np.empty(n * 2, dtype=np.int64)
        words[0::2] = lo
        words[1::2] = hi
        return pa.Array.from_buffers(
            pa.decimal128(dtype.precision, dtype.scale), n,
            [_bitmap(valid), pa.py_buffer(words.tobytes())])

    data = np.asarray(col.data)[:n]
    if isinstance(dtype, T.DateType):
        return pa.array(data.astype(np.int32), type=pa.date32(),
                        mask=mask if mask.any() else None)
    if isinstance(dtype, T.TimestampType):
        return pa.array(data.astype(np.int64),
                        type=pa.timestamp("us", tz="UTC"),
                        mask=mask if mask.any() else None)
    return pa.array(data, type=T.to_arrow(dtype),
                    mask=mask if mask.any() else None)


def _bitmap(valid: np.ndarray) -> Optional[pa.Buffer]:
    if valid.all():
        return None
    return pa.py_buffer(np.packbits(valid, bitorder="little").tobytes())


def _matrix_to_strings(col: DeviceColumn, n: int, mask: np.ndarray,
                       binary: bool) -> pa.Array:
    chars = np.asarray(col.data)[:n]
    lengths = np.asarray(col.lengths)[:n].astype(np.int64)
    lengths = np.where(mask, 0, lengths)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    flat = np.zeros(total, dtype=np.uint8)
    if total:
        row_idx = np.repeat(np.arange(n), lengths)
        col_idx = np.arange(total) - np.repeat(offsets[:-1].astype(np.int64), lengths)
        flat[:] = chars[row_idx, col_idx]
    at = pa.binary() if binary else pa.utf8()
    return pa.Array.from_buffers(
        at, n, [_bitmap(~mask), pa.py_buffer(offsets.tobytes()),
                pa.py_buffer(flat.tobytes())])


# --------------------------------------------------------------------------
# pandas convenience
# --------------------------------------------------------------------------

def pandas_to_device(df) -> ColumnarBatch:
    return arrow_to_device(pa.Table.from_pandas(df, preserve_index=False))


def device_to_pandas(batch: ColumnarBatch):
    return device_to_arrow(batch).to_pandas()
