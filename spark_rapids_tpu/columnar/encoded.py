"""Encoded column representations that survive through the engine.

"GPU Acceleration of SQL Analytics on Compressed Data" (PAPERS.md) shows
operators can run directly on encoded columns; the engine's profile says
it is transfer-bound, not compute-bound (BENCH_r05: 0.221 GB/s/chip on q1,
0.0134 on the join shape vs ~820 GB/s HBM).  This module generalizes the
``columnar/prepack.py`` narrow-before-the-wire trick into first-class
encoded batch citizens:

* :class:`DictEncodedColumn` — int32 codes + a shared :class:`Dictionary`
  of distinct values.  Scans keep low-cardinality string columns as
  codes+dict instead of eagerly materializing the padded byte matrix;
  joins probe on integer codes (sql/physical/join.py lowers both sides
  into the build dictionary's code space), group-bys and sorts run on
  codes via ``ops/ranks.column_sort_keys`` (the dictionary is always
  stored SORTED, so code order == value order), and the shuffle
  serializer ships narrowed codes with the dictionary sent once per
  batch (or once per exchange via the ref cache).

* :class:`RLEColumn` — run values + run ends for repetitive fixed-width
  columns; mainly a wire/scan representation (any gather materializes).

Decline-to-materialize discipline (the device-decode split, applied to
encoding): every operator that does not understand an encoded column
simply touches ``.data`` / ``.lengths`` / ``.aux`` / ``.children`` — those
are properties that transparently materialize (and memoize) the decoded
column, so unaware ops are bit-identical BY CONSTRUCTION, never wrong.
Aware ops (gather, concat, sort keys, join key lowering, the serializer)
check ``isinstance`` and stay in code space.  Materialized data for
null/dead rows is zeroed, matching the engine-wide "nulls hold zeroed
data" invariant (arrow_to_device does the same), so hashing/bloom paths
see identical bytes either way.

The kill switch is structural: ``spark.rapids.tpu.sql.encoded.enabled``
gates *creation* (scan encode + wire decode); with it off no encoded
column ever exists, every jitted program retraces on the plain treedef,
and the whole engine is back on the raw path.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from .column import DeviceColumn, bucket_capacity, bucket_width, \
    is_string_like

#: observability (tests + bench + last_query_metrics deltas).
#: materializations counts decode-on-access events (per traced program,
#: not per row); columns_encoded/declined track the scan-side gate.
STATS = {
    "columns_encoded": 0,          # dict columns created (scan/wire/concat)
    "rle_columns_encoded": 0,
    "columns_declined": 0,         # eligible but over cardinality budget
    "materializations": 0,         # encoded -> raw decodes (any site)
    "dict_filters": 0,             # filter predicates evaluated on the dict
    "join_code_lowerings": 0,      # join key pairs lowered to code space
    "join_code_declines": 0,
    "concat_unified": 0,           # dict-aware concats (incl. unify)
    "wire_dict_inline": 0,         # dictionaries shipped inline in a frame
    "wire_dict_refs": 0,           # dictionaries replaced by a cache ref
    "wire_code_bytes": 0,          # narrowed code bytes on the wire
    "wire_bytes_saved": 0,         # raw-matrix bytes minus encoded bytes
}

_LOCK = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _LOCK:
        STATS[key] += n


def stats_snapshot() -> dict:
    with _LOCK:
        return dict(STATS)


#: thread-local wire accounting: one frame serializes entirely on one
#: thread, so the per-frame bytes-saved delta is exact even when the
#: MULTITHREADED shuffle serializes frames concurrently
_WIRE_TLS = threading.local()


def begin_wire_account():
    prev = getattr(_WIRE_TLS, "saved", None)
    _WIRE_TLS.saved = 0
    return prev


def add_wire_saved(n: int) -> None:
    _bump("wire_bytes_saved", n)
    if getattr(_WIRE_TLS, "saved", None) is not None:
        _WIRE_TLS.saved += n


def end_wire_account(prev) -> int:
    cur = getattr(_WIRE_TLS, "saved", 0) or 0
    _WIRE_TLS.saved = prev
    return cur


# --------------------------------------------------------------------------
# configuration gates
# --------------------------------------------------------------------------

def enabled(conf=None) -> bool:
    from ..config import ENCODED_ENABLED, RapidsConf
    try:
        return bool((conf or RapidsConf.get_global()).get(ENCODED_ENABLED))
    except Exception:  # pragma: no cover - partial-init paths
        return False


def op_enabled(op: str, conf=None) -> bool:
    """Per-op opt-out (filter/join/aggregate/sort/shuffle).  Read at
    trace/lowering time — see docs/encoded_columns.md for the kernel-cache
    caveat on flipping these mid-session."""
    from ..config import ENCODED_OP_CONFS, RapidsConf
    entry = ENCODED_OP_CONFS.get(op)
    if entry is None:
        return True
    try:
        return bool((conf or RapidsConf.get_global()).get(entry))
    except Exception:  # pragma: no cover
        return True


def _max_cardinality(conf=None) -> int:
    from ..config import ENCODED_MAX_CARDINALITY, RapidsConf
    return int((conf or RapidsConf.get_global())
               .get(ENCODED_MAX_CARDINALITY))


def encode_params(conf=None) -> tuple:
    """The scan-side encode decision inputs — part of any cache key that
    stores encoded batches (e.g. the in-memory scan upload cache)."""
    return (enabled(conf), _max_cardinality(conf))


# --------------------------------------------------------------------------
# Dictionary — the shared distinct-value table
# --------------------------------------------------------------------------

#: process-global host-value + identity registry, keyed by content hash.
#: Entries are small (<= maxDictionaryCardinality values); the registry is
#: append-only up to a generous cap, after which new dictionaries simply
#: stop registering (wire frames then inline, join lowering declines) —
#: no eviction means a wire ref can never dangle in-process.
_REGISTRY_CAP = 4096
_HOST_VALUES: Dict[int, np.ndarray] = {}
_DICT_OBJECTS: Dict[int, "Dictionary"] = {}


def _register_host_values(content_hash: int, values: np.ndarray) -> None:
    with _LOCK:
        if content_hash not in _HOST_VALUES \
                and len(_HOST_VALUES) < _REGISTRY_CAP:
            _HOST_VALUES[content_hash] = values


def host_values_for(content_hash: int) -> Optional[np.ndarray]:
    with _LOCK:
        return _HOST_VALUES.get(content_hash)


def registered_dictionary(content_hash: int) -> Optional["Dictionary"]:
    with _LOCK:
        return _DICT_OBJECTS.get(content_hash)


def _register_dictionary(d: "Dictionary") -> "Dictionary":
    """Canonicalize by content hash so every frame/batch carrying the same
    dictionary shares ONE object (identity short-circuits concat/join)."""
    with _LOCK:
        got = _DICT_OBJECTS.get(d.content_hash)
        if got is not None:
            return got
        if len(_DICT_OBJECTS) < _REGISTRY_CAP:
            _DICT_OBJECTS[d.content_hash] = d
        return d


def _hash_values(values: Sequence[bytes]) -> int:
    """Stable content hash of the sorted distinct values (xxhash64 when the
    native lib is present, else a seeded 64-bit FNV fold)."""
    payload = struct.pack("<I", len(values)) + b"\x00".join(values)
    try:
        from ..native import xxhash64_bytes
        h = xxhash64_bytes(payload, seed=len(payload))
        if h is not None:
            return int(h)
    except Exception:  # pragma: no cover - native lib optional
        pass
    h = 0xcbf29ce484222325
    for b in payload:
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h


class Dictionary:
    """Distinct values of a dict-encoded column, device-resident as a
    regular :class:`DeviceColumn` over ``size`` entries, plus static
    metadata.  Always SORTED ascending in engine byte order (lexicographic
    over the value bytes) and UNIQUE — code order therefore equals value
    order, which is what lets sorts/comparisons run on codes.

    The entry table's capacity is always > ``size``: index ``size`` is a
    guaranteed all-null spare row, used by the filter fast path to
    evaluate a predicate's null-input verdict in the same pass.
    """

    __slots__ = ("column", "size", "sorted", "content_hash")

    def __init__(self, column: DeviceColumn, size: int,
                 sorted_: bool, content_hash: int):
        self.column = column
        self.size = int(size)
        self.sorted = bool(sorted_)
        self.content_hash = int(content_hash)

    # --- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return ((self.column,), (self.size, self.sorted, self.content_hash))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        size, sorted_, content_hash = aux
        return cls(leaves[0], size, sorted_, content_hash)

    def host_values(self) -> Optional[np.ndarray]:
        """The sorted distinct values as a host object array of bytes, from
        the registry (populated at creation/deserialization; dictionaries
        are never built on-device)."""
        return host_values_for(self.content_hash)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Dictionary(size={self.size}, sorted={self.sorted}, "
                f"hash={self.content_hash:#x})")


def _register_pytrees():
    import jax
    jax.tree_util.register_pytree_node_class(Dictionary)


def dictionary_from_values(dtype: T.DataType,
                           values: Sequence[bytes]) -> Dictionary:
    """Build a (sorted, unique) dictionary from host byte values.  Callers
    must pass values already sorted ascending and deduplicated."""
    k = len(values)
    cap = bucket_capacity(k + 1)  # always leave the spare null slot
    width = bucket_width(max((len(v) for v in values), default=0))
    chars = np.zeros((cap, width), dtype=np.uint8)
    lengths = np.zeros(cap, dtype=np.int32)
    for i, v in enumerate(values):
        lengths[i] = len(v)
        if v:
            chars[i, :len(v)] = np.frombuffer(v, dtype=np.uint8)
    validity = np.zeros(cap, dtype=bool)
    validity[:k] = True
    import jax.numpy as jnp
    col = DeviceColumn(dtype, jnp.asarray(chars), jnp.asarray(validity),
                       lengths=jnp.asarray(lengths))
    h = _hash_values(list(values))
    vals = np.empty(k, dtype=object)
    vals[:k] = list(values)
    _register_host_values(h, vals)
    return _register_dictionary(Dictionary(col, k, True, h))


# --------------------------------------------------------------------------
# DictEncodedColumn
# --------------------------------------------------------------------------

def _trace_encode_span(name: str, **args):
    """Host-side encode/materialize span (cat ``encode``); silently skipped
    when the tracer is off."""
    from ..observability import tracer as _trace
    if not _trace.TRACING["on"]:
        return None
    return _trace.span("encode", name, **args)


class DictEncodedColumn(DeviceColumn):
    """codes + dictionary, masquerading as its logical :class:`DeviceColumn`.

    ``dtype`` is the LOGICAL type (StringType/BinaryType); ``codes`` is
    int32[capacity] with code 0 for null/dead rows; ``validity`` is the
    usual row-validity array.  ``join_codes`` (optional) carries this
    column's codes remapped into a join partner's dictionary space — set
    only by the join lowering immediately before the jitted join programs,
    cleared by any structural operation (gather/slice), and consumed by
    ``ops/join.join_search_keys``.

    Accessing ``.data`` / ``.lengths`` / ``.aux`` / ``.children``
    materializes (and memoizes) the decoded column — the decline path for
    every op that does not understand encoding.
    """

    def __init__(self, dtype: T.DataType, codes, dictionary: Dictionary,
                 validity, join_codes=None):
        # deliberately NOT calling the dataclass __init__: data/lengths/aux
        # are properties on this class
        self.dtype = dtype
        self.codes = codes
        self.dictionary = dictionary
        self.validity = validity
        self.join_codes = join_codes
        self._mat: Optional[DeviceColumn] = None

    # --- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return ((self.codes, self.validity, self.dictionary,
                 self.join_codes), self.dtype)

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        codes, validity, dictionary, join_codes = leaves
        return cls(dtype, codes, dictionary, validity, join_codes)

    # --- shape ------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.codes.shape[0])

    @property
    def width(self) -> Optional[int]:
        return self.materialized().width

    # --- decline-to-materialize safety net --------------------------------
    def materialized(self) -> DeviceColumn:
        """The decoded column: one gather of the dictionary by codes, with
        null/dead rows zeroed (engine invariant — hash/bloom/serializer
        paths must see the same bytes as the raw pipeline)."""
        m = self._mat
        if m is not None:
            return m
        import jax.numpy as jnp
        d = self.dictionary.column
        safe = jnp.clip(self.codes, 0, d.capacity - 1)
        data = jnp.where(self.validity[:, None], d.data[safe], 0)
        lengths = jnp.where(self.validity, d.lengths[safe], 0)
        m = DeviceColumn(self.dtype, data, self.validity, lengths=lengths)
        self._mat = m
        _bump("materializations")
        span = _trace_encode_span("dict.materialize", rows=self.capacity,
                                  dict_size=self.dictionary.size)
        if span is not None:
            with span:
                pass
        return m

    @property
    def data(self):
        return self.materialized().data

    @property
    def lengths(self):
        return self.materialized().lengths

    @property
    def aux(self):
        return None

    @property
    def children(self):
        return ()

    # --- structural ops (stay encoded) ------------------------------------
    def with_validity(self, validity) -> "DictEncodedColumn":
        return DictEncodedColumn(self.dtype, self.codes, self.dictionary,
                                 validity, self.join_codes)

    def mask_dead_rows(self, row_mask) -> "DictEncodedColumn":
        v = self.validity & row_mask if self.validity is not None else row_mask
        return self.with_validity(v)

    def with_join_codes(self, join_codes) -> "DictEncodedColumn":
        return DictEncodedColumn(self.dtype, self.codes, self.dictionary,
                                 self.validity, join_codes)

    def slice_capacity(self, new_capacity: int) -> "DictEncodedColumn":
        from .column import _fix_1d
        return DictEncodedColumn(
            self.dtype, _fix_1d(self.codes, new_capacity, 0),
            self.dictionary, _fix_1d(self.validity, new_capacity, False))

    def gather(self, idx, idx_valid=None) -> "DictEncodedColumn":
        """Row selection gathers CODES, not values — the encoding survives
        filters, join output assembly, group-by key emission, and sorts.
        ``join_codes`` does not survive (it is only valid for the exact
        batch pair the join lowering prepared)."""
        import jax.numpy as jnp
        safe = jnp.clip(idx, 0, self.capacity - 1)
        validity = self.validity[safe]
        if idx_valid is not None:
            validity = validity & idx_valid
        codes = jnp.where(validity, self.codes[safe], 0)
        return DictEncodedColumn(self.dtype, codes, self.dictionary,
                                 validity)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"DictEncodedColumn(rows={self.capacity}, "
                f"dict={self.dictionary.size}, dtype={self.dtype})")


# --------------------------------------------------------------------------
# RLEColumn
# --------------------------------------------------------------------------

class RLEColumn(DeviceColumn):
    """Run-length encoded fixed-width column: ``run_values`` (a plain
    DeviceColumn over ``num_runs`` entries, bucket-padded) + ``run_ends``
    (int32 exclusive end offsets, padded with capacity).  Row validity is
    stored explicitly (bool[capacity] — 1 byte/row; the win is the data
    words).  Primarily a scan/wire representation: any structural
    operation (gather/slice) materializes, by design.
    """

    def __init__(self, dtype: T.DataType, run_values: DeviceColumn,
                 run_ends, num_runs: int, validity):
        self.dtype = dtype
        self.run_values = run_values
        self.run_ends = run_ends
        self.num_runs = int(num_runs)
        self.validity = validity
        self._mat: Optional[DeviceColumn] = None

    def tree_flatten(self):
        return ((self.run_values, self.run_ends, self.validity),
                (self.dtype, self.num_runs))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        dtype, num_runs = aux
        run_values, run_ends, validity = leaves
        return cls(dtype, run_values, run_ends, num_runs, validity)

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def width(self) -> Optional[int]:
        return None

    def materialized(self) -> DeviceColumn:
        m = self._mat
        if m is not None:
            return m
        import jax.numpy as jnp
        idx = jnp.arange(self.capacity, dtype=jnp.int32)
        run_idx = jnp.searchsorted(self.run_ends, idx, side="right")
        run_idx = jnp.clip(run_idx, 0, self.run_values.capacity - 1)
        data = jnp.where(self.validity, self.run_values.data[run_idx], 0)
        aux = None
        if self.run_values.aux is not None:
            aux = jnp.where(self.validity, self.run_values.aux[run_idx], 0)
        m = DeviceColumn(self.dtype, data, self.validity, aux=aux)
        self._mat = m
        _bump("materializations")
        return m

    @property
    def data(self):
        return self.materialized().data

    @property
    def lengths(self):
        return None

    @property
    def aux(self):
        return self.materialized().aux

    @property
    def children(self):
        return ()

    def with_validity(self, validity) -> "RLEColumn":
        return RLEColumn(self.dtype, self.run_values, self.run_ends,
                         self.num_runs, validity)

    def mask_dead_rows(self, row_mask) -> "RLEColumn":
        v = self.validity & row_mask if self.validity is not None else row_mask
        return self.with_validity(v)

    def slice_capacity(self, new_capacity: int) -> DeviceColumn:
        return self.materialized().slice_capacity(new_capacity)

    def gather(self, idx, idx_valid=None) -> DeviceColumn:
        return self.materialized().gather(idx, idx_valid)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"RLEColumn(rows={self.capacity}, runs={self.num_runs}, "
                f"dtype={self.dtype})")


def _register_encoded_pytrees():
    import jax
    jax.tree_util.register_pytree_node_class(Dictionary)
    jax.tree_util.register_pytree_node_class(DictEncodedColumn)
    jax.tree_util.register_pytree_node_class(RLEColumn)


_register_encoded_pytrees()


# --------------------------------------------------------------------------
# encoding (host side — scans and the wire)
# --------------------------------------------------------------------------

def _cardinality_ok(k: int, n: int, max_cardinality: int) -> bool:
    """Encode when the dictionary is within budget.  The distinct/rows
    ratio rule only applies to LARGE columns: a tiny dim table with all-
    unique keys still encodes (its dictionary is trivially small and the
    join's code-space lowering needs both sides encoded)."""
    if k > max_cardinality:
        return False
    return n <= 1024 or k <= max(1, n // 2)


def encode_string_column_np(dtype: T.DataType, values: List[Optional[bytes]],
                            capacity: int,
                            max_cardinality: int) -> Optional[DictEncodedColumn]:
    """Dict-encode a host string/binary column (None = null).  Returns
    None (decline) when the cardinality exceeds the budget or encoding
    cannot shrink the representation."""
    n = len(values)
    present = [v for v in values if v is not None]
    distinct = sorted(set(present))
    k = len(distinct)
    if not _cardinality_ok(k, n, max_cardinality):
        _bump("columns_declined")
        return None
    d = dictionary_from_values(dtype, distinct)
    index = {v: i for i, v in enumerate(distinct)}
    codes_np = np.zeros(capacity, dtype=np.int32)
    valid_np = np.zeros(capacity, dtype=bool)
    for i, v in enumerate(values):
        if v is not None:
            codes_np[i] = index[v]
            valid_np[i] = True
    import jax.numpy as jnp
    _bump("columns_encoded")
    span = _trace_encode_span("dict.encode", rows=n, dict_size=k)
    if span is not None:
        with span:
            pass
    return DictEncodedColumn(dtype, jnp.asarray(codes_np), d,
                             jnp.asarray(valid_np))


def encode_string_arrow(arr, dtype: T.DataType, capacity: int,
                        conf=None) -> Optional[DictEncodedColumn]:
    """Scan-side retention: keep a low-cardinality arrow string/binary
    column as codes+dict.  Uses arrow's dictionary_encode (this ALSO
    covers parquet/ORC dictionary pages arriving pre-encoded from
    pyarrow) and re-sorts the dictionary into engine byte order."""
    import pyarrow as pa
    import pyarrow.compute as pc
    n = len(arr)
    if n == 0 or not is_string_like(dtype):
        return None
    max_card = _max_cardinality(conf)
    try:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            denc = arr
        else:
            denc = pc.dictionary_encode(arr)
        dict_vals = denc.dictionary
        k = len(dict_vals)
        if not _cardinality_ok(k, n, max_card):
            _bump("columns_declined")
            return None
        raw = [v.as_py() for v in dict_vals]
        as_bytes = [v.encode("utf-8") if isinstance(v, str) else bytes(v)
                    for v in raw]
        order = sorted(range(k), key=lambda i: as_bytes[i])
        sorted_vals = [as_bytes[i] for i in order]
        if len(set(sorted_vals)) != k:
            # distinct logical values with equal byte forms — be safe
            _bump("columns_declined")
            return None
        remap = np.zeros(k, dtype=np.int32)
        for new, old in enumerate(order):
            remap[old] = new
        d = dictionary_from_values(dtype, sorted_vals)
        idx = denc.indices
        valid_np = np.zeros(capacity, dtype=bool)
        valid_np[:n] = np.asarray(arr.is_valid()) if arr.null_count else True
        idx_np = np.asarray(idx.fill_null(0)) if idx.null_count \
            else np.asarray(idx)
        codes_np = np.zeros(capacity, dtype=np.int32)
        codes_np[:n] = remap[idx_np.astype(np.int64)]
        codes_np[:n][~valid_np[:n]] = 0
        import jax.numpy as jnp
        _bump("columns_encoded")
        span = _trace_encode_span("dict.encode", rows=n, dict_size=k)
        if span is not None:
            with span:
                pass
        return DictEncodedColumn(dtype, jnp.asarray(codes_np), d,
                                 jnp.asarray(valid_np))
    except Exception:  # pragma: no cover - arrow corner cases: decline
        _bump("columns_declined")
        return None


def retain_scan_dictionary(dtype: T.DataType, mat: np.ndarray,
                           lens_np: np.ndarray, dense_idx, valid,
                           n_rows: int, capacity: int, scatter,
                           conf=None) -> Optional[DictEncodedColumn]:
    """Device-decoder retention: keep an already-decoded dictionary page
    (parquet PLAIN/RLE_DICTIONARY, ORC DICTIONARY_V2) as codes + dict
    instead of eagerly gathering the padded byte matrix.  ``mat``/
    ``lens_np`` are the HOST dictionary entries, ``dense_idx`` the device
    array of per-nonnull-value dictionary indices, ``scatter`` the
    decoder's dense->row scatter (``_scatter_nonnull`` partial).  Returns
    None to decline (cardinality over budget, duplicate entries — e.g.
    repeated values across ORC stripe dictionaries — or encoding off);
    the caller then gathers exactly as before."""
    import jax.numpy as jnp
    k = int(len(lens_np))
    if not enabled(conf) or not is_string_like(dtype) \
            or not _cardinality_ok(k, n_rows, _max_cardinality(conf)):
        return None
    vals = [mat[i, :int(lens_np[i])].tobytes() for i in range(k)]
    if len(set(vals)) != k:
        return None
    order = sorted(range(k), key=vals.__getitem__)
    d = dictionary_from_values(dtype, [vals[i] for i in order])
    remap = np.zeros(max(k, 1), dtype=np.int32)
    for new, old in enumerate(order):
        remap[old] = new
    dense_codes = jnp.asarray(remap)[
        jnp.clip(dense_idx, 0, max(k - 1, 0)).astype(jnp.int32)]
    codes, v = scatter(dense_codes)
    _bump("columns_encoded")
    span = _trace_encode_span("dict.retain", rows=n_rows, dict_size=k)
    if span is not None:
        with span:
            pass
    return DictEncodedColumn(dtype, codes.astype(jnp.int32), d, v)


#: minimum compression ratio (rows per run) for RLE retention to engage
_RLE_MIN_RATIO = 4


def encode_rle_numpy(dtype: T.DataType, data_np: np.ndarray,
                     valid_np: np.ndarray, n: int,
                     capacity: int) -> Optional[RLEColumn]:
    """RLE-encode a fixed-width host column when its live prefix is
    run-compressible (>= _RLE_MIN_RATIO rows per run).  Validity changes
    break runs so each run is uniformly valued AND uniformly valid."""
    if n < 64 or data_np.ndim != 1:
        return None
    live = data_np[:n]
    live_valid = valid_np[:n]
    breaks = np.flatnonzero((live[1:] != live[:-1])
                            | (live_valid[1:] != live_valid[:-1]))
    num_runs = len(breaks) + 1
    if num_runs * _RLE_MIN_RATIO > n:
        return None
    ends = np.empty(num_runs, dtype=np.int32)
    ends[:-1] = breaks + 1
    ends[-1] = n
    starts = np.concatenate([[0], ends[:-1]])
    run_cap = bucket_capacity(num_runs)
    rv = np.zeros(run_cap, dtype=data_np.dtype)
    rvalid = np.zeros(run_cap, dtype=bool)
    rv[:num_runs] = live[starts]
    rvalid[:num_runs] = live_valid[starts]
    rends = np.full(run_cap, capacity, dtype=np.int32)
    rends[:num_runs] = ends
    import jax.numpy as jnp
    run_col = DeviceColumn(dtype, jnp.asarray(rv), jnp.asarray(rvalid))
    _bump("rle_columns_encoded")
    return RLEColumn(dtype, run_col, jnp.asarray(rends), num_runs,
                     jnp.asarray(valid_np))


def materialize_column(col: DeviceColumn) -> DeviceColumn:
    if isinstance(col, (DictEncodedColumn, RLEColumn)):
        return col.materialized()
    return col


def materialize_batch(batch):
    """Decode every encoded column (the op-level decline path)."""
    from .batch import ColumnarBatch
    if not any(isinstance(c, (DictEncodedColumn, RLEColumn))
               for c in batch.columns):
        return batch
    cols = tuple(materialize_column(c) for c in batch.columns)
    out = ColumnarBatch(batch.names, cols, batch.num_rows)
    cached = getattr(batch, "_nrows_host", None)
    if cached is not None:
        out._nrows_host = cached
    return out


def has_encoded_columns(batch) -> bool:
    return any(isinstance(c, (DictEncodedColumn, RLEColumn))
               for c in batch.columns)


def dictionary_from_wire(column: DeviceColumn, size: int, sorted_: bool,
                         content_hash: int) -> Dictionary:
    """Rebuild a dictionary from deserialized (host numpy) buffers,
    registering its host values and canonicalizing by content hash so
    every frame of one exchange shares a single object."""
    got = registered_dictionary(content_hash)
    if got is not None:
        return got
    if host_values_for(content_hash) is None:
        data = np.asarray(column.data)
        lengths = np.asarray(column.lengths)
        vals = np.empty(size, dtype=object)
        for i in range(size):
            vals[i] = bytes(data[i, :int(lengths[i])])
        _register_host_values(content_hash, vals)
    return _register_dictionary(
        Dictionary(column, size, sorted_, content_hash))


def materialize_np(col: DeviceColumn) -> DeviceColumn:
    """Host-side (numpy) materialization for deserialized encoded columns
    when the encoded kill switch is off — keeps the wire reader's
    host-buffers-only contract."""
    if isinstance(col, DictEncodedColumn):
        d = col.dictionary
        data = np.asarray(d.column.data)
        lengths = np.asarray(d.column.lengths)
        codes = np.asarray(col.codes)
        valid = np.asarray(col.validity)
        safe = np.clip(codes, 0, data.shape[0] - 1)
        out = np.where(valid[:, None], data[safe], 0).astype(np.uint8)
        out_len = np.where(valid, lengths[safe], 0).astype(np.int32)
        return DeviceColumn(col.dtype, out, valid, lengths=out_len)
    if isinstance(col, RLEColumn):
        valid = np.asarray(col.validity)
        cap = valid.shape[0]
        rends = np.asarray(col.run_ends)
        idx = np.searchsorted(rends, np.arange(cap), side="right")
        idx = np.clip(idx, 0, np.asarray(col.run_values.data).shape[0] - 1)
        data = np.where(valid, np.asarray(col.run_values.data)[idx], 0)
        aux = None
        if col.run_values.aux is not None:
            aux = np.where(valid, np.asarray(col.run_values.aux)[idx], 0)
        return DeviceColumn(col.dtype, data, valid, aux=aux)
    return col


# --------------------------------------------------------------------------
# dict-aware concat (exchange reduce, broadcast, join build sides)
# --------------------------------------------------------------------------

def try_concat_dict_columns(cols: Sequence[DeviceColumn],
                            counts: Sequence[int],
                            out_capacity: int) -> Optional[DictEncodedColumn]:
    """Concatenate dict-encoded pieces WITHOUT materializing: same
    dictionary -> concat codes; different dictionaries -> unify on the
    host (dictionaries are small, values live in the registry) and remap
    each piece's codes.  Returns None to decline (caller materializes)."""
    if not all(isinstance(c, DictEncodedColumn) for c in cols):
        return None
    import jax.numpy as jnp
    dtype = cols[0].dtype
    first = cols[0].dictionary
    if all(c.dictionary is first
           or c.dictionary.content_hash == first.content_hash
           for c in cols):
        codes = _concat_padded([c.codes for c in cols], counts,
                               out_capacity, 0)
        validity = _concat_padded([c.validity for c in cols], counts,
                                  out_capacity, False)
        _bump("concat_unified")
        return DictEncodedColumn(dtype, codes, first, validity)
    value_lists = []
    for c in cols:
        hv = c.dictionary.host_values()
        if hv is None:
            return None
        value_lists.append(hv)
    union = sorted(set(v for hv in value_lists for v in hv))
    if len(union) > _max_cardinality():
        return None
    d = dictionary_from_values(dtype, union)
    index = {v: i for i, v in enumerate(union)}
    remapped = []
    for c, hv in zip(cols, value_lists):
        mapping = np.zeros(bucket_capacity(len(hv) + 1), dtype=np.int32)
        for old, v in enumerate(hv):
            mapping[old] = index[v]
        m = jnp.asarray(mapping)
        safe = jnp.clip(c.codes, 0, mapping.shape[0] - 1)
        remapped.append(jnp.where(c.validity, m[safe], 0))
    codes = _concat_padded(remapped, counts, out_capacity, 0)
    validity = _concat_padded([c.validity for c in cols], counts,
                              out_capacity, False)
    _bump("concat_unified")
    return DictEncodedColumn(dtype, codes, d, validity)


def _concat_padded(arrs, counts, out_capacity, fill):
    import jax.numpy as jnp
    live = [a[:c] for a, c in zip(arrs, counts)]
    cat = jnp.concatenate(live) if live else arrs[0][:0]
    return jnp.pad(cat, (0, out_capacity - cat.shape[0]),
                   constant_values=fill)


# --------------------------------------------------------------------------
# join key lowering (probe on integer codes, not raw strings)
# --------------------------------------------------------------------------

#: remap tables are pure functions of the two dictionaries' contents —
#: cache per (probe hash, build hash) so B probe batches over one scan's
#: shared dictionary compute the table once
_MAP_CACHE: Dict[Tuple[int, int], np.ndarray] = {}


def map_codes_between(probe_dict: Dictionary,
                      build_dict: Dictionary) -> Optional[np.ndarray]:
    """Host remap table: probe dictionary code -> build dictionary code,
    -1 for values absent from the build side (the miss sentinel — never
    equal to any build code, so a missing value simply finds no match).
    O(|probe dict| log |build dict|) host work on the registry values."""
    ck = (probe_dict.content_hash, build_dict.content_hash)
    with _LOCK:
        got = _MAP_CACHE.get(ck)
    if got is not None:
        return got
    pv = probe_dict.host_values()
    bv = build_dict.host_values()
    if pv is None or bv is None:
        return None
    table = np.full(bucket_capacity(len(pv) + 1), -1, dtype=np.int32)
    bl = list(bv)
    pos = np.searchsorted(np.asarray(bv, dtype=object), pv)
    for i, v in enumerate(pv):
        p = int(pos[i])
        if p < len(bl) and bl[p] == v:
            table[i] = p
    with _LOCK:
        if len(_MAP_CACHE) > 1024:
            _MAP_CACHE.clear()
        _MAP_CACHE[ck] = table
    return table


def lower_join_codes(probe_col: DictEncodedColumn,
                     build_col: DictEncodedColumn
                     ) -> Optional[Tuple[DictEncodedColumn,
                                         DictEncodedColumn]]:
    """Prepare one key-column pair for code-space joining: the build side
    keeps its own (sorted) codes as join codes; the probe side's codes are
    remapped into the build dictionary (misses -> -1).  Equality of join
    codes is then exactly equality of values, and code ORDER on the build
    side equals value order (sorted dict), so the fast-path binary search
    is sound.  Null rows get join code 0 with validity False — excluded by
    the join's bad-row handling exactly like raw keys."""
    if probe_col.dictionary is build_col.dictionary or \
            probe_col.dictionary.content_hash == \
            build_col.dictionary.content_hash:
        return (probe_col.with_join_codes(probe_col.codes),
                build_col.with_join_codes(build_col.codes))
    if not build_col.dictionary.sorted:
        return None
    mapping = map_codes_between(probe_col.dictionary, build_col.dictionary)
    if mapping is None:
        return None
    import jax.numpy as jnp
    m = jnp.asarray(mapping)
    safe = jnp.clip(probe_col.codes, 0, mapping.shape[0] - 1)
    jc = jnp.where(probe_col.validity, m[safe], 0)
    return (probe_col.with_join_codes(jc),
            build_col.with_join_codes(build_col.codes))
