"""Device-side pre-pack for host fetches: shrink bytes BEFORE they cross
the wire (VERDICT r4 #3; reference analog: the nvcomp shuffle codecs,
``NvcompLZ4CompressionCodec.scala:26`` + ``TableCompressionCodec.scala`` —
the reference compresses table buffers on device before they travel).

The TPU-native twist: general-purpose byte codecs (LZ4/zstd) don't map to
XLA's static-shape model — the compressed size is data-dependent.  What
does map are *fixed-ratio* transforms chosen per buffer from a cheap
device-side probe:

  * integer bit-width narrowing  — int64/u64 columns whose live range fits
    in 1/2/4 bytes ship narrowed (up to 8x);
  * float64 -> float32           — when every value round-trips losslessly
    (on TPU, where "f64" is a double-float pair, this is exactly "the low
    component is zero" and halves the wire pair);
  * bool bit-packing             — validity masks and bool columns ship as
    bits, not bytes (8x).

Two-phase protocol (both phases are cached compiled programs):

  phase A ("probe"):  ONE small fetch of per-buffer (min, max) + f64
                      losslessness flags for the whole batch;
  phase B ("pack"):   a program specialized to the chosen width codes
                      emits the narrowed buffers fused into ONE uint32
                      word stream (via ``pack_leaves_traced``) — one
                      transfer for the whole batch, like
                      :func:`~spark_rapids_tpu.columnar.convert.bulk_device_get`.

The host widens everything back, so callers see bit-identical buffers
(floats: value-identical; the f32 path is only taken when lossless).
``STATS`` carries the bytes-on-wire accounting the bench reports.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Tuple

import numpy as np

#: wire accounting — bytes_naive is what a plain bulk fetch would have
#: pulled, bytes_on_wire what the prepacked fetch actually pulled
#: (+ the probe fetch, counted honestly).
STATS = {"prepacked_fetches": 0, "bytes_on_wire": 0, "bytes_naive": 0,
         "probe_bytes": 0, "fallbacks": 0}

_LOCK = threading.Lock()
_PROBE_CACHE: Dict = {}
_PACK_CACHE: Dict = {}

#: narrowing codes: per-leaf verdicts from the probe.  "keep" = ship
#: as-is; "bits" = bool bit-pack; "f32" = lossless f64 downcast;
#: "i1/i2/i4/u1/u2/u4" = integer narrowing target.
_INT_TARGETS = (
    ("i1", np.int8), ("i2", np.int16), ("i4", np.int32),
)
_UINT_TARGETS = (
    ("u1", np.uint8), ("u2", np.uint16), ("u4", np.uint32),
)


def _leaf_kind(dt: np.dtype) -> str:
    """Classification driving the probe: which narrowing family applies."""
    if dt == np.bool_:
        return "bool"
    if dt.kind == "i" and dt.itemsize >= 2:
        return "int"
    if dt.kind == "u" and dt.itemsize >= 2:
        return "uint"
    if dt.kind == "f" and dt.itemsize == 8:
        return "f64"
    return "other"


def _probe_program(sig):
    """Phase A: per-int-leaf (min, max) as int64 pairs + per-f64-leaf
    lossless flags, all in two small output arrays (one fetch)."""
    import jax
    import jax.numpy as jnp

    def probe(*arrs):
        mins, maxs, flags = [], [], []
        for a, (_, dts) in zip(arrs, sig):
            kind = _leaf_kind(np.dtype(dts))
            if kind in ("int", "uint"):
                flat = a.reshape(-1)
                # empty leaves narrow maximally; jnp.min on empty throws
                if flat.size == 0:
                    mins.append(jnp.int64(0))
                    maxs.append(jnp.int64(0))
                else:
                    # u64 max may exceed i64 — clamp via the sign trick:
                    # values >= 2^63 report i64-max, which keeps them wide
                    if kind == "uint" and np.dtype(dts).itemsize == 8:
                        big = jnp.max(flat)
                        clamped = jnp.where(
                            big >= jnp.uint64(1) << jnp.uint64(63),
                            jnp.uint64((1 << 63) - 1), big)
                        mins.append(jnp.min(flat).astype(jnp.int64))
                        maxs.append(clamped.astype(jnp.int64))
                    else:
                        mins.append(jnp.min(flat).astype(jnp.int64))
                        maxs.append(jnp.max(flat).astype(jnp.int64))
            elif kind == "f64":
                flat = a.reshape(-1)
                if flat.size == 0:
                    flags.append(jnp.bool_(True))
                else:
                    rt = flat.astype(jnp.float32).astype(flat.dtype)
                    flags.append(jnp.all(rt == flat))
        return (jnp.stack(mins) if mins else jnp.zeros(0, jnp.int64),
                jnp.stack(maxs) if maxs else jnp.zeros(0, jnp.int64),
                jnp.stack(flags) if flags else jnp.zeros(0, jnp.bool_))

    return jax.jit(probe)


def _choose_codes(sig, mins, maxs, flags) -> Tuple[str, ...]:
    codes: List[str] = []
    im = 0
    fm = 0
    for shape, dts in sig:
        dt = np.dtype(dts)
        kind = _leaf_kind(dt)
        if kind == "bool":
            codes.append("bits")
        elif kind == "int":
            lo, hi = int(mins[im]), int(maxs[im])
            im += 1
            code = "keep"
            for c, t in _INT_TARGETS:
                ii = np.iinfo(t)
                if np.dtype(t).itemsize < dt.itemsize \
                        and ii.min <= lo and hi <= ii.max:
                    code = c
                    break
            codes.append(code)
        elif kind == "uint":
            lo, hi = int(mins[im]), int(maxs[im])
            im += 1
            code = "keep"
            for c, t in _UINT_TARGETS:
                ii = np.iinfo(t)
                if np.dtype(t).itemsize < dt.itemsize and hi <= ii.max:
                    code = c
                    break
            codes.append(code)
        elif kind == "f64":
            codes.append("f32" if bool(flags[fm]) else "keep")
            fm += 1
        else:
            codes.append("keep")
    return tuple(codes)


_CODE_DTYPE = {"i1": np.int8, "i2": np.int16, "i4": np.int32,
               "u1": np.uint8, "u2": np.uint16, "u4": np.uint32,
               "f32": np.float32}


def _narrowed_sig(sig, codes):
    """The (shape, dtype) signature of the narrowed leaves, shared by the
    traced pack body and the host decoder (must never drift)."""
    out = []
    for (shape, dts), code in zip(sig, codes):
        if code == "keep":
            out.append((shape, dts))
        elif code == "bits":
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            out.append(((math.ceil(count / 8),), "uint8"))
        else:
            out.append((shape, str(np.dtype(_CODE_DTYPE[code]))))
    return tuple(out)


def _bitpack_traced(a):
    """Bool array -> little-endian bit-packed uint8 (traced; numpy
    ``packbits(bitorder='little')`` semantics)."""
    import jax.numpy as jnp
    flat = a.reshape(-1).astype(jnp.uint8)
    pad = (-flat.size) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    return (flat.reshape(-1, 8) * weights).sum(axis=1).astype(jnp.uint8)


def _pack_program(sig, codes):
    """Phase B: narrow each leaf per its code, then fuse every narrowed
    buffer into one word stream via ``pack_leaves_traced``."""
    import jax
    import jax.numpy as jnp

    from .convert import pack_leaves_traced
    nsig = _narrowed_sig(sig, codes)

    def pack(*arrs):
        narrowed = []
        for a, (_, dts), code in zip(arrs, sig, codes):
            if code == "keep":
                narrowed.append(a)
            elif code == "bits":
                narrowed.append(_bitpack_traced(a))
            else:
                narrowed.append(a.astype(_CODE_DTYPE[code]))
        return pack_leaves_traced(narrowed, nsig)

    return jax.jit(pack), nsig


def _widen(host_leaves, sig, codes):
    out = []
    for leaf, (shape, dts), code in zip(host_leaves, sig, codes):
        if code == "keep":
            out.append(leaf)
        elif code == "bits":
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            bits = np.unpackbits(leaf, count=count, bitorder="little")
            out.append(bits.astype(np.bool_).reshape(shape))
        else:
            out.append(leaf.astype(np.dtype(dts)).reshape(shape))
    return out


def _min_bytes() -> int:
    from ..config import D2H_PREPACK_MIN_BYTES, RapidsConf
    try:
        return int(RapidsConf.get_global().get(D2H_PREPACK_MIN_BYTES))
    except Exception:  # pragma: no cover
        return 1 << 20


def enabled() -> bool:
    """'auto' (default) = on when the device is remote (non-CPU backend:
    narrowing trades a little device compute + one probe RTT for a large
    wire saving); 'true' forces on (tests/CPU-mesh measurement), 'false'
    kills."""
    from ..config import D2H_PREPACK, RapidsConf
    try:
        mode = str(RapidsConf.get_global().get(D2H_PREPACK)).lower()
    except Exception:  # pragma: no cover
        mode = "auto"
    if mode in ("true", "on"):
        return True
    if mode in ("false", "off"):
        return False
    import jax
    return jax.default_backend() != "cpu"


def prepacked_device_get(tree):
    """Drop-in for ``bulk_device_get`` with device-side narrowing.

    Falls back to :func:`~.convert.bulk_device_get` whenever prepack is
    disabled, the batch is too small for the probe round trip to pay, or
    anything in the narrow path fails (correctness first)."""
    import jax

    from ..shims import tree_flatten, tree_unflatten
    from .convert import bulk_device_get
    if not enabled():
        return bulk_device_get(tree)
    leaves, treedef = tree_flatten(tree)
    dev_idx = [i for i, l in enumerate(leaves)
               if isinstance(l, jax.Array) and not isinstance(l, np.ndarray)]
    if not dev_idx:
        return tree
    devs = [leaves[i] for i in dev_idx]
    sig = tuple((l.shape, str(l.dtype)) for l in devs)
    naive = 0
    narrowable = 0
    for (shape, dts) in sig:
        try:
            isz = np.dtype(dts).itemsize
        except TypeError:
            return bulk_device_get(tree)  # exotic dtype: plain path
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        naive += count * isz
        if _leaf_kind(np.dtype(dts)) != "other":
            narrowable += count * isz
    if narrowable < _min_bytes():
        return bulk_device_get(tree)
    from ..observability import tracer as _trace
    tracing = _trace.TRACING["on"]
    import time as _time
    t0 = _time.perf_counter() if tracing else 0.0
    try:
        with _LOCK:
            probe = _PROBE_CACHE.get(sig)
            if probe is None:
                probe = _PROBE_CACHE[sig] = _probe_program(sig)
                if len(_PROBE_CACHE) > 256:
                    _PROBE_CACHE.clear()
                    _PROBE_CACHE[sig] = probe
        mins_d, maxs_d, flags_d = probe(*devs)
        for b in (mins_d, maxs_d, flags_d):
            b.copy_to_host_async()
        mins, maxs, flags = (np.asarray(mins_d), np.asarray(maxs_d),
                             np.asarray(flags_d))
        probe_nbytes = mins.nbytes + maxs.nbytes + flags.nbytes
        with _LOCK:  # shuffle writer/reader pools fetch concurrently
            STATS["probe_bytes"] += probe_nbytes
            STATS["bytes_on_wire"] += probe_nbytes  # probe crossed too
        codes = _choose_codes(sig, mins, maxs, flags)
        if all(c == "keep" for c in codes):
            return bulk_device_get(tree)
        # keep-f64 leaves ride pack_leaves_traced, whose word layout
        # depends on the f64 encoding mode (backend + packFloat64 conf) —
        # part of the key, like bulk_device_get's cache (convert.py)
        from .convert import _f64_as_pair, _pack_f64_enabled
        key = (sig, codes, _f64_as_pair(), _pack_f64_enabled())
        with _LOCK:
            entry = _PACK_CACHE.get(key)
            if entry is None:
                entry = _PACK_CACHE[key] = _pack_program(sig, codes)
                if len(_PACK_CACHE) > 256:
                    _PACK_CACHE.clear()
                    _PACK_CACHE[key] = entry
        pack, nsig = entry
        bufs = pack(*devs)
        for b in bufs:
            b.copy_to_host_async()
        host = [np.asarray(b) for b in bufs]
        from .convert import unpack_buffers
        narrowed_host = unpack_buffers(host, nsig)
        widened = _widen(narrowed_host, sig, codes)
        wire = sum(b.nbytes for b in host)
        with _LOCK:
            STATS["prepacked_fetches"] += 1
            STATS["bytes_on_wire"] += wire
            STATS["bytes_naive"] += naive
        if tracing:
            # probe + narrowed fetch: both crossings in one d2h span (the
            # fallback paths above land in bulk_device_get's own span)
            _trace.get_tracer().complete(
                "d2h", "prepacked_device_get", t0,
                _time.perf_counter() - t0, bytes=wire + probe_nbytes,
                bytes_naive=naive, leaves=len(devs))
    except Exception:  # pragma: no cover - toolchain-specific lowerings
        with _LOCK:
            STATS["fallbacks"] += 1
        return bulk_device_get(tree)
    for i, leaf in zip(dev_idx, widened):
        leaves[i] = leaf
    return tree_unflatten(treedef, leaves)
