"""Typed configuration registry — the TPU equivalent of the reference's
``RapidsConf.scala`` (Spark-style ``ConfEntry`` builder with docs, defaults,
``internal()``/``startupOnly()``/``commonlyUsed()`` attributes; reference
``RapidsConf.scala:120+``, 197 ``spark.rapids.*`` keys).

Keys keep the ``spark.rapids.*`` naming so a user of the reference finds the
same knobs; TPU-specific keys live under ``spark.rapids.tpu.*``.
``RapidsConf.help()`` -> :func:`help_text` emits the markdown config docs the
same way the reference's docgen does (``RapidsConf.scala:2057-2103``).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ConfEntry", "RapidsConf", "register", "ENTRIES", "help_text"]


@dataclass
class ConfEntry:
    key: str
    doc: str
    default: Any
    type_: type
    internal: bool = False
    startup_only: bool = False
    commonly_used: bool = False
    checker: Optional[Callable[[Any], bool]] = None

    def convert(self, raw: Any) -> Any:
        if raw is None:
            return self.default
        if self.type_ is bool:
            if isinstance(raw, bool):
                return raw
            return str(raw).strip().lower() in ("true", "1", "yes")
        if self.type_ is int:
            return int(raw)
        if self.type_ is float:
            return float(raw)
        if self.type_ is list:
            if isinstance(raw, (list, tuple)):
                return list(raw)
            return [s.strip() for s in str(raw).split(",") if s.strip()]
        return str(raw)


ENTRIES: Dict[str, ConfEntry] = {}


def register(key: str, doc: str, default: Any, type_: Optional[type] = None,
             internal: bool = False, startup_only: bool = False,
             commonly_used: bool = False) -> ConfEntry:
    e = ConfEntry(key, doc, default,
                  type_ or (type(default) if default is not None else str),
                  internal, startup_only, commonly_used)
    ENTRIES[key] = e
    return e


# --- SQL behavior (names follow reference RapidsConf.scala) -----------------
SQL_ENABLED = register(
    "spark.rapids.sql.enabled",
    "Enable or disable TPU acceleration of SQL operations.", True,
    commonly_used=True)
SQL_MODE = register(
    "spark.rapids.sql.mode",
    "executeOnGPU runs supported ops on the accelerator; explainOnly plans and "
    "reports what would run without touching the device.", "executeongpu")
EXPLAIN = register(
    "spark.rapids.sql.explain",
    "NONE | NOT_ON_GPU | ALL: log why operators are or are not placed on the "
    "accelerator.", "NOT_ON_GPU", commonly_used=True)
BATCH_SIZE_BYTES = register(
    "spark.rapids.sql.batchSizeBytes",
    "Target size in bytes for accelerator columnar batches "
    "(reference default 1 GiB; TPU default tuned for HBM slices).",
    1 << 30, commonly_used=True)
BATCH_SIZE_ROWS = register(
    "spark.rapids.sql.batchSizeRows",
    "Target row count cap per columnar batch (shape-bucketing granularity).",
    1 << 20)
MAX_READER_BATCH_SIZE_ROWS = register(
    "spark.rapids.sql.reader.batchSizeRows",
    "Soft cap on rows per batch produced by readers.", (1 << 31) - 1)
MAX_READER_BATCH_SIZE_BYTES = register(
    "spark.rapids.sql.reader.batchSizeBytes",
    "Soft cap on bytes per batch produced by readers.", (1 << 31) - 1)
SORT_OOC_TARGET_ROWS = register(
    "spark.rapids.sql.sort.outOfCore.targetRows",
    "Row budget per device-resident chunk in the out-of-core sort "
    "(reference GpuOutOfCoreSortIterator, GpuSortExec.scala:242): inputs "
    "larger than this are sorted as spillable runs and k-way merged in "
    "chunks of at most this many rows.", 1 << 22)
WINDOW_BATCH_TARGET_ROWS = register(
    "spark.rapids.sql.window.batchTargetRows",
    "Window inputs larger than this many rows are processed in "
    "key-complete chunks (every chunk holds whole partitions, cut at "
    "partition-key boundaries) instead of one concatenated batch — the "
    "reference's key-batched windows (GpuKeyBatchingIterator.scala). "
    "Bounded by the largest single partition.", 1 << 22)
JOIN_OUTPUT_CHUNK_ROWS = register(
    "spark.rapids.sql.join.outputChunkRows",
    "Join outputs larger than this many rows are gathered in chunks of "
    "this size instead of one worst-case buffer (reference "
    "JoinGatherer.scala:730 lazy chunked gather).", 1 << 22)
JOIN_BUILD_CACHE_ENABLED = register(
    "spark.rapids.sql.join.buildSideCache.enabled",
    "Cache the sorted build-side join keys on the build batch so a "
    "broadcast/shuffled hash join sorts its build side once and every "
    "probe batch only binary-searches it (the sort-based analog of the "
    "reference building its hash table once per build side, "
    "GpuHashJoin.scala:298).  Off falls back to the union-rank path, "
    "which re-sorts probe+build per probe batch.", True)
JOIN_SPECULATIVE_SIZING = register(
    "spark.rapids.sql.join.speculativeSizing.enabled",
    "Dispatch each probe batch's join gather at an output capacity "
    "predicted from the previous batch's selectivity BEFORE the blocking "
    "count readback, so the one sizing fetch overlaps the gather instead "
    "of serializing it; an overflow of the predicted bucket re-gathers "
    "at the exact size.", True)
JOIN_INITIAL_SELECTIVITY = register(
    "spark.rapids.sql.join.speculativeSizing.initialSelectivity",
    "First-batch output-rows-per-probe-row estimate used by speculative "
    "join output sizing before any realized selectivity is observed.",
    1.0)
CONCURRENT_TASKS = register(
    "spark.rapids.sql.concurrentGpuTasks",
    "Number of tasks that may hold the device semaphore concurrently "
    "(reference GpuSemaphore, RapidsConf.scala:535).", 1, commonly_used=True)
TIERED_PROJECT = register(
    "spark.rapids.sql.tiered.project.enabled",
    "Dedup common subexpressions via tiered projection.", True)
FUSION_ENABLED = register(
    "spark.rapids.tpu.sql.fusion.enabled",
    "Fuse filter/project chains (and their terminal hash aggregate) into "
    "one compiled XLA program per pipeline stage — whole-stage codegen, "
    "the TPU analog of the reference's tiered projection + kernel reuse "
    "(basicPhysicalOperators.scala:500, SURVEY §3.3).", True)
WHOLE_STAGE_ENABLED = register(
    "spark.rapids.tpu.sql.wholeStage.enabled",
    "Deepened whole-stage formation (docs/whole_stage.md): hash "
    "aggregates (partial/complete) and hash-join probe phases become "
    "stage TERMINALS — the upstream filter/project chain compiles into "
    "the terminal's own program under one stage-signature kernel-cache "
    "key, the fused filter mask feeds the aggregate/probe directly, and "
    "intermediates never materialize.  Off keeps only >=2-op map-chain "
    "fusion (requires spark.rapids.tpu.sql.fusion.enabled).", True)
WHOLE_STAGE_DONATION = register(
    "spark.rapids.tpu.sql.wholeStage.donation.enabled",
    "Donate a fused map-stage's input buffers to its compiled program "
    "(XLA donate_argnums) so the stage output reuses the input's HBM. "
    "Guarded by the batch retention registry (memory/retention.py): "
    "donation is declined whenever the batch is pinned by the scan "
    "upload cache, a broadcast, a materialized shuffle partition, the "
    "spill tier, a prefetch queue, or a transfer stager — or when its "
    "provenance is unknown or it carries shared-dictionary encoded "
    "columns.  Buffers are only physically reclaimed on real device "
    "backends (XLA:CPU ignores donation); the safety decision runs "
    "everywhere.", True)
WHOLE_STAGE_SORT_WINDOW = register(
    "spark.rapids.tpu.sql.wholeStage.sortWindowTerminal.enabled",
    "Sort/window stage terminals (docs/whole_stage.md): a SortExec or "
    "WindowExec absorbs the upstream filter/project chain into its own "
    "compiled program, and a WindowExec additionally absorbs the "
    "planner-inserted partition sort — partition sort + segmented frame "
    "evaluation ride ONE stage program instead of one dispatch per op. "
    "Requires spark.rapids.tpu.sql.wholeStage.enabled.", True)
JOIN_FUSED_PROBE = register(
    "spark.rapids.tpu.sql.join.fusedProbe.enabled",
    "Single-program probe pipeline: each probe batch runs multi-key "
    "search + run-end expansion + pair generation + the gather of ALL "
    "output columns on both sides as ONE compiled program that also "
    "returns the sizing scalars — at most two device launches per probe "
    "batch (the optional second handles a speculative-bucket overflow "
    "re-gather), with the one batched sizing readback unchanged.  Off "
    "keeps the separate probe-search and gather programs.", True)
DISPATCH_COALESCE_ENABLED = register(
    "spark.rapids.tpu.sql.dispatch.coalesce.enabled",
    "Dispatch coalescer for the many-small-partitions regime "
    "(docs/whole_stage.md): consecutive small same-shape batches entering "
    "a fused map stage are stacked on a leading axis and the stage "
    "program is vmapped over the stack INSIDE one compiled program — N "
    "batches, one device launch.  Only batches whose padded capacity "
    "bucket and column layout match coalesce (the padding buckets are "
    "the existing capacity quantization); tracer stage spans carry "
    "coalesced_n and deviceDispatches counts real launches.", True)
DISPATCH_COALESCE_MAX_BATCHES = register(
    "spark.rapids.tpu.sql.dispatch.coalesce.maxBatches",
    "Upper bound on the number of batches stacked into one coalesced "
    "stage launch.", 8)
DISPATCH_COALESCE_MAX_ROWS = register(
    "spark.rapids.tpu.sql.dispatch.coalesce.maxRows",
    "Only batches whose padded capacity is at or below this many rows "
    "are eligible for dispatch coalescing — large batches already "
    "amortize their launch overhead.", 1 << 16)
IMPROVED_FLOAT = register(
    "spark.rapids.sql.improvedFloatOps.enabled",
    "Allow float ops whose results may differ from CPU in ULPs.", True)
HAS_NANS = register(
    "spark.rapids.sql.hasNans",
    "Assume floating point data may contain NaNs.", True)
ANSI_ENABLED = register(
    "spark.sql.ansi.enabled",
    "ANSI mode: overflow/invalid-cast raise instead of null/wrap.", False)
CASE_SENSITIVE = register(
    "spark.sql.caseSensitive", "Case sensitive column resolution.", False)
SESSION_TIMEZONE = register(
    "spark.sql.session.timeZone", "Session timezone (UTC only on device, "
    "mirroring the reference's UTC-only timezone check).", "UTC")
SHUFFLE_PARTITIONS = register(
    "spark.sql.shuffle.partitions", "Default shuffle partition count.", 8)
AUTO_BROADCAST_THRESHOLD = register(
    "spark.rapids.sql.autoBroadcastJoinThreshold",
    "Maximum build-side size in bytes for which an equi-join uses a "
    "broadcast hash join instead of a shuffled hash join.",
    10 * 1024 * 1024, commonly_used=True)

# --- memory / runtime -------------------------------------------------------
ALLOC_FRACTION = register(
    "spark.rapids.memory.gpu.allocFraction",
    "Fraction of device HBM the buffer pool may use.", 0.85)
RESERVE_BYTES = register(
    "spark.rapids.memory.gpu.reserve",
    "Device memory reserved for XLA scratch/system.", 640 << 20)
OOM_SYNC_MODE = register(
    "spark.rapids.memory.oom.syncMode",
    "When the per-kernel OOM guard forces device synchronization: "
    "'always' blocks after every kernel (every execution-time OOM lands "
    "inside the guard, at one host-device round trip per kernel), 'never' "
    "lets dispatch stay asynchronous (OOM surfaces at the next "
    "materialization point), 'auto' syncs only under memory pressure — "
    "accounted pool usage above oom.syncWatermark, armed test OOM "
    "injection, or a recently observed device OOM.", "auto")
D2H_PREPACK = register(
    "spark.rapids.tpu.d2h.prepack",
    "Device-side pre-pack for host fetches (shuffle frames, spill, result "
    "collection): integer bit-width narrowing, lossless float64->float32 "
    "and bool bit-packing shrink bytes before they cross the host link "
    "(reference: nvcomp device codecs, NvcompLZ4CompressionCodec.scala). "
    "'auto' enables it when the device is remote (TPU tunnel), 'true' "
    "forces it everywhere (CPU-mesh measurement), 'false' disables.",
    "auto")
D2H_PREPACK_MIN_BYTES = register(
    "spark.rapids.tpu.d2h.prepack.minBytes",
    "Minimum narrowable payload (bytes) before the pre-pack probe round "
    "trip pays for itself; smaller batches ride the plain packed fetch.",
    1 << 20)
D2H_PACK_F64 = register(
    "spark.rapids.tpu.d2h.packFloat64",
    "Include float64 columns in the packed single-transfer D2H fetch. "
    "On TPU, f64 is an emulated double-float; the packed encoding is "
    "bit-faithful to every value the device can itself COMPUTE, but an "
    "uploaded-and-untouched f64 below ~1e-29 whose low component falls "
    "in the f32-denormal range loses those low bits (device arithmetic "
    "flushes them identically).  Set false to fetch f64 columns with "
    "full storage fidelity at one extra transfer round trip each.", True)
# --- encoded columnar execution (docs/encoded_columns.md) ------------------
ENCODED_ENABLED = register(
    "spark.rapids.tpu.sql.encoded.enabled",
    "Keep dictionary/RLE-encoded columns encoded THROUGH the engine "
    "instead of materializing at the scan: filters evaluate predicates "
    "on the dictionary, joins probe on integer codes, group-bys/sorts "
    "run on codes, and the shuffle serializer ships narrowed codes with "
    "each dictionary sent once per batch (or once per exchange via the "
    "ref cache).  This is the structural kill switch: off means no "
    "encoded column is ever created, so every plan takes the raw path.",
    True, commonly_used=True)
ENCODED_MAX_CARDINALITY = register(
    "spark.rapids.tpu.sql.encoded.maxDictionaryCardinality",
    "Columns with more distinct values than this decline dictionary "
    "encoding at the scan (and dictionary unification declines at "
    "concat).  Encoding also declines when distinct values exceed half "
    "the rows.", 4096)
ENCODED_FILTER_ENABLED = register(
    "spark.rapids.tpu.sql.encoded.filter.enabled",
    "Evaluate eligible single-column filter predicates once over the "
    "dictionary (plus its null slot) and select rows by code lookup "
    "instead of evaluating on every row.  Read at kernel-trace time.",
    True)
ENCODED_JOIN_ENABLED = register(
    "spark.rapids.tpu.sql.encoded.join.enabled",
    "Lower equi-join keys whose both sides are dictionary-encoded into "
    "the build side's integer code space (probe codes remapped on the "
    "host via the dictionary registry) so the join sorts/searches int32 "
    "codes instead of padded string matrices.", True)
ENCODED_AGG_SORT_ENABLED = register(
    "spark.rapids.tpu.sql.encoded.aggSort.enabled",
    "Group and sort dictionary-encoded columns by their integer codes "
    "(sorted dictionaries make code order == value order).  Read at "
    "kernel-trace time.", True)
ENCODED_SHUFFLE_ENABLED = register(
    "spark.rapids.tpu.sql.encoded.shuffle.enabled",
    "Ship encoded columns over the shuffle/broadcast wire as narrowed "
    "codes + dictionary (the encoded-batch wire format, frame version "
    "2) instead of materialized value buffers.", True)
ENCODED_SHUFFLE_DICT_REFS = register(
    "spark.rapids.tpu.sql.encoded.shuffle.dictRefs.enabled",
    "Replace repeated dictionaries in shuffle frames with a content-hash "
    "reference resolved from the in-process dictionary registry, so "
    "repeated batches of one exchange pay only code bytes.  Automatically "
    "bypassed (inline dictionaries) on multi-slice topologies where "
    "frames cross process boundaries.", True)

#: per-op opt-out lookup used by columnar.encoded.op_enabled
ENCODED_OP_CONFS = {
    "filter": ENCODED_FILTER_ENABLED,
    "join": ENCODED_JOIN_ENABLED,
    "aggsort": ENCODED_AGG_SORT_ENABLED,
    "shuffle": ENCODED_SHUFFLE_ENABLED,
}

OOM_SYNC_WATERMARK = register(
    "spark.rapids.memory.oom.syncWatermark",
    "Accounted-pool usage fraction above which syncMode=auto blocks "
    "after every kernel to catch allocation failures eagerly.", 0.6)
HOST_SPILL_STORAGE_SIZE = register(
    "spark.rapids.memory.host.spillStorageSize",
    "Host memory budget for spilled device buffers.", 1 << 30)
PINNED_POOL_SIZE = register(
    "spark.rapids.memory.pinnedPool.size",
    "Pinned host pool size for H2D/D2H staging.", 0)
SPILL_DIR = register(
    "spark.rapids.memory.spillDir", "Directory for the disk spill tier.",
    "/tmp/rapids_tpu_spill")
GPU_DEBUG = register(
    "spark.rapids.memory.gpu.debug",
    "Log every spill-catalog registration/removal with the owning call "
    "site — the reference's RMM debug allocation logging analog "
    "(RapidsConf.scala:366); leak_report() names still-registered "
    "handles and their origins.", False)
OOM_RETRY_ENABLED = register(
    "spark.rapids.sql.oomRetry.enabled",
    "Enable the retry-on-OOM state machine (withRetry framework).", True)
TEST_INJECT_RETRY_OOM = register(
    "spark.rapids.sql.test.injectRetryOOM",
    "Test hook: make the Nth retryable block throw a synthetic RetryOOM "
    "(reference RapidsConf.scala:1371).", 0, internal=True)
TEST_INJECT_SPLIT_OOM = register(
    "spark.rapids.sql.test.injectSplitAndRetryOOM",
    "Test hook: make the Nth retryable block throw SplitAndRetryOOM.",
    0, internal=True)

# --- adaptive execution + cost optimizer -----------------------------------
ADAPTIVE_ENABLED = register(
    "spark.sql.adaptive.enabled",
    "Adaptive query execution: joins re-decide broadcast-vs-shuffle from "
    "the build side's OBSERVED size at runtime (reference AQE integration, "
    "GpuOverrides.scala:4392-4452 + GpuCustomShuffleReaderExec).", True)
ADAPTIVE_COALESCE_ROWS = register(
    "spark.sql.adaptive.coalescePartitions.minRows",
    "Exchanges whose total map output has at most this many rows route "
    "everything to one reduce partition (AQE partition coalescing, "
    "GpuCustomShuffleReaderExec analog): tiny post-aggregation states "
    "stop paying per-partition split/launch/sync overhead.", 1 << 16)
SKEW_JOIN_ENABLED = register(
    "spark.sql.adaptive.skewJoin.enabled",
    "Skewed-partition splitting at exchange materialization (the "
    "reference's GpuCustomShuffleReaderExec skewed-partition specs, "
    "GpuCustomShuffleReaderExec.scala:87): a reduce partition whose row "
    "count exceeds skewedPartitionFactor x the median non-empty "
    "partition (and the row threshold) is kept as contiguous chunks "
    "instead of one batch, so a downstream shuffled hash join probes it "
    "chunk-by-chunk against the full build partition with bounded "
    "memory — proactively, not via the OOM-retry path.", True)
SKEW_JOIN_FACTOR = register(
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor",
    "A partition is skewed when its rows exceed this factor times the "
    "median non-empty partition's rows (Spark's default).", 5)
SKEW_JOIN_ROWS = register(
    "spark.sql.adaptive.skewJoin.skewedPartitionRowsThreshold",
    "...and also exceed this absolute row count (the rows analog of "
    "Spark's skewedPartitionThresholdInBytes).", 1 << 17)
OPTIMIZER_ENABLED = register(
    "spark.rapids.sql.optimizer.enabled",
    "Cost-based optimizer: flips subtrees back to the host engine when the "
    "estimated device benefit does not cover transition costs (reference "
    "CostBasedOptimizer.scala:54; off by default like the reference).",
    False)
OPTIMIZER_CPU_COST = register(
    "spark.rapids.sql.optimizer.cpu.exec.default",
    "Default CPU cost (seconds/row) per operator "
    "(RapidsConf.scala:1870).", 0.0002)
OPTIMIZER_GPU_COST = register(
    "spark.rapids.sql.optimizer.gpu.exec.default",
    "Default device cost (seconds/row) per operator "
    "(RapidsConf.scala:1882-1886).", 0.0001)
OPTIMIZER_TRANSITION_COST = register(
    "spark.rapids.sql.optimizer.transition.default",
    "Cost (seconds/row) of a host<->device transition boundary.", 0.0001)
OPTIMIZER_TRANSITION_FIXED = register(
    "spark.rapids.sql.optimizer.transition.fixedSeconds",
    "FIXED cost (seconds) of each host<->device transition boundary, "
    "independent of row count.  On the TPU tunnel every host pull is a "
    "full network round trip (~65ms measured, docs/perf_notes.md) that "
    "dwarfs per-row costs for small batches.  -1 (default) = auto: "
    "measure the sync round trip once per process and use that.", -1.0)

RAGGED_STRING_SPLIT_BYTES = register(
    "spark.rapids.sql.strings.raggedSplitBytes",
    "Scans split a batch into string width classes when its padded "
    "[capacity x width] byte-matrix footprint would exceed this many "
    "bytes and the split saves >=4x — so one long string doesn't make "
    "every row pay its width.  0 disables.", 16 << 20)

APPROX_PERCENTILE_STRATEGY = register(
    "spark.rapids.sql.approxPercentile.strategy",
    "approx_percentile implementation: 'exact' = sorted ordinal selection "
    "(Spark's exact-percentile rule; tighter than Spark's own sketch but "
    "needs every group's rows co-resident), 'tdigest' = device t-digest "
    "sketch (bounded O(groups x delta/2) state, interpolated results — "
    "the reference's documented-incompat behavior, "
    "GpuApproximatePercentile.scala), 'auto' = exact below "
    "tdigestThresholdRows, t-digest above.", "auto")
APPROX_PERCENTILE_TDIGEST_ROWS = register(
    "spark.rapids.sql.approxPercentile.tdigestThresholdRows",
    "In 'auto' mode, batches at or above this capacity digest via "
    "t-digest instead of exact selection.", 1 << 18)

BLOOM_JOIN_ENABLED = register(
    "spark.rapids.sql.join.bloomFilter.enabled",
    "Bloom-filter join runtime filters: the build side of a shuffled hash "
    "join builds a bloom filter over its join keys and the probe side "
    "drops non-members BELOW its exchange, shrinking both the shuffle and "
    "the probe (reference GpuBloomFilterMightContain.scala, "
    "shims/BloomFilterShims.scala spark330+).  Inner/left-semi joins only.",
    True)
BLOOM_JOIN_MAX_BUILD_ROWS = register(
    "spark.rapids.sql.join.bloomFilter.maxBuildRows",
    "Skip bloom construction when the build side exceeds this many rows "
    "(the filter stores one device byte per bit position, i.e. "
    "~bitsPerRow BYTES per build row after power-of-two rounding).",
    4_000_000)
BLOOM_JOIN_BITS_PER_ROW = register(
    "spark.rapids.sql.join.bloomFilter.bitsPerRow",
    "Bloom filter density; 8 bits/row with the derived hash count gives "
    "a ~2% false-positive rate.", 8)

# --- shuffle ---------------------------------------------------------------
SHUFFLE_DEVICE_RESIDENT = register(
    "spark.rapids.shuffle.localDeviceResident.enabled",
    "Keep local SORT/MULTITHREADED shuffle blocks device-resident in the "
    "spill catalog instead of serializing to host, when the producer and "
    "consumer share one process and slice.  Skips a D2H+H2D round trip "
    "per block (~65ms each over the TPU tunnel); the spill catalog still "
    "demotes blocks under memory pressure (reference device-direct "
    "shuffle: ShuffleBufferCatalog.scala + RapidsCachingWriter).", True)
SHUFFLE_MODE = register(
    "spark.rapids.shuffle.mode",
    "UCX|MULTITHREADED|SORT in the reference; here ICI|MULTITHREADED|SORT — "
    "ICI keeps partitions in device memory and exchanges over the "
    "interconnect with XLA collectives.", "MULTITHREADED")
SHUFFLE_TRANSPORT_CLASS = register(
    "spark.rapids.shuffle.transport.type",
    "LOCAL (in-process store) or TCP (cross-process block server + driver "
    "registry, the UCX-transport analog for cross-host fetches; "
    "RapidsShuffleTransport SPI).", "LOCAL")
SHUFFLE_TOPOLOGY_SLICES = register(
    "spark.rapids.shuffle.topology.numSlices",
    "Number of TPU slices the job spans.  1 (default) = single-slice: "
    "every exchange rides ICI (XLA collectives).  >1 enables the two-"
    "tier plane: a slice's own reduce partitions stay on ICI while "
    "blocks owned by peer slices cross DCN via the TCP transport "
    "(parallel/topology.py; reference UCX transport + peer registry).",
    1)
SHUFFLE_TOPOLOGY_SLICE_ID = register(
    "spark.rapids.shuffle.topology.sliceId",
    "This process's slice ordinal in [0, numSlices).", 0)
SHUFFLE_TCP_DRIVER_ENDPOINT = register(
    "spark.rapids.shuffle.tcp.driverEndpoint",
    "host:port of the driver heartbeat registry for the TCP transport "
    "(RapidsShuffleHeartbeatManager analog); empty = standalone.", "")
SHUFFLE_TCP_BIND_HOST = register(
    "spark.rapids.shuffle.tcp.bindHost",
    "Address the TCP shuffle block server binds and advertises; set to "
    "this host's reachable address for multi-host deployments.",
    "127.0.0.1")
SHUFFLE_TCP_NATIVE = register(
    "spark.rapids.shuffle.tcp.native.enabled",
    "Serve the TCP shuffle data plane from the native C++ transport "
    "(epoll block server + pooled client, native/srt_transport.cpp — the "
    "UCX-module analog); wire-compatible with the Python transport, "
    "which remains the fallback when the library can't build.", True)
SHUFFLE_EXECUTOR_ID = register(
    "spark.rapids.shuffle.executorId",
    "This process's executor id for shuffle peer discovery.", "exec-0")
SHUFFLE_WRITER_THREADS = register(
    "spark.rapids.shuffle.multiThreaded.writer.threads",
    "Threads for the multithreaded shuffle writer.", 8)
SHUFFLE_READER_THREADS = register(
    "spark.rapids.shuffle.multiThreaded.reader.threads",
    "Threads for the multithreaded shuffle reader.", 8)
SHUFFLE_COMPRESSION_CODEC = register(
    "spark.rapids.shuffle.compression.codec",
    "Shuffle batch compression codec: none|zstd|lz4hc.", "zstd")
SHUFFLE_CHECKSUM = register(
    "spark.rapids.shuffle.checksum",
    "Frame integrity checksum: auto (only when the native xxhash64 "
    "library is available — the pure-Python fallback is too slow for the "
    "hot path), true (always), false (never).", "auto")
SHUFFLE_MAX_BYTES_IN_FLIGHT = register(
    "spark.rapids.shuffle.maxBytesInFlight",
    "Cap on in-flight fetched shuffle bytes.", 128 << 20)
SHUFFLE_TCP_CONNECT_TIMEOUT_MS = register(
    "spark.rapids.shuffle.tcp.connectTimeoutMs",
    "Connect timeout for TCP shuffle block fetches and the driver "
    "registry client (previously hardcoded at 10s).", 10_000)
SHUFFLE_TCP_READ_TIMEOUT_MS = register(
    "spark.rapids.shuffle.tcp.readTimeoutMs",
    "Socket read/write timeout for TCP shuffle block fetches; a peer "
    "that accepts the connection but stops responding mid-frame "
    "surfaces as ShuffleFetchFailed instead of hanging the reduce "
    "task forever.", 30_000)

# --- robustness: resilient shuffle fetch ------------------------------------
SHUFFLE_FETCH_MAX_RETRIES = register(
    "spark.rapids.tpu.shuffle.fetch.maxRetries",
    "Bounded retries per shuffle block fetch before the manager falls "
    "back to lost-block recompute (or fails the read).  Each retry "
    "backs off exponentially from fetch.backoffMs with jitter.", 4)
SHUFFLE_FETCH_BACKOFF_MS = register(
    "spark.rapids.tpu.shuffle.fetch.backoffMs",
    "Base backoff between shuffle fetch retries; attempt N sleeps "
    "backoffMs * 2^(N-1) (+ up to 25% jitter), capped by the remaining "
    "per-reduce deadline.", 10)
SHUFFLE_FETCH_DEADLINE_MS = register(
    "spark.rapids.tpu.shuffle.fetch.deadlineMs",
    "Wall-clock deadline for assembling one reduce partition; retries "
    "stop when it expires (the FetchFailed->stage-retry analog of "
    "spark.network.timeout).", 30_000)
SHUFFLE_FETCH_BLACKLIST_AFTER = register(
    "spark.rapids.tpu.shuffle.fetch.blacklistAfter",
    "Consecutive fetch failures from one peer before it is transiently "
    "blacklisted (moved to last-resort ordering, not dropped — "
    "correctness never depends on the blacklist).", 2)
SHUFFLE_FETCH_BLACKLIST_MS = register(
    "spark.rapids.tpu.shuffle.fetch.blacklistMs",
    "How long a blacklisted peer stays benched; the next heartbeat "
    "refresh after expiry reinstates it with a clean slate.", 5_000)
SHUFFLE_FETCH_SPECULATIVE_P99 = register(
    "spark.rapids.tpu.shuffle.fetch.speculativeP99Factor",
    "Straggler mitigation for remote shuffle fetches: when a fetch "
    "against one peer runs longer than this factor times the rolling "
    "p99 of recent remote-fetch latencies, a speculative duplicate "
    "fetch is issued against the next candidate peer and the first "
    "answer wins (the hung fetch is abandoned, its socket dropped).  "
    "0 (default) disables speculation.", 0.0)

# --- robustness: pod-scale peer failure domain ------------------------------
PEERS_HEARTBEAT_MS = register(
    "spark.rapids.tpu.peers.heartbeatMs",
    "Interval of the shuffle manager's background heartbeat loop "
    "against the driver peer registry, which also feeds the phi-accrual "
    "failure detector (robustness/failure_detector.py).  0 (default) "
    "disables the background loop: heartbeats then ride fetch-time "
    "refreshes only, as before the failure detector existed.", 0)
PEERS_SUSPECT_MS = register(
    "spark.rapids.tpu.peers.suspectMs",
    "A peer with no heartbeat for this long (scaled by the phi-accrual "
    "estimate of its normal arrival jitter) transitions alive -> "
    "suspect: it drops to last-resort fetch ordering but is still "
    "tried.  Hysteresis: returning to alive requires consecutive "
    "on-time heartbeats, so a flapping peer doesn't thrash the "
    "ordering.", 3_000)
PEERS_DEAD_MS = register(
    "spark.rapids.tpu.peers.deadMs",
    "A peer with no heartbeat for this long is declared dead: in-flight "
    "fetches against it fail over immediately (no retry/backoff "
    "budget), its blocks recompute proactively via registered lineage "
    "callbacks, and its registry entry is fenced — re-registration "
    "bumps the peer's epoch so a zombie returning later cannot serve "
    "stale blocks.", 10_000)

# --- mesh data plane robustness ---------------------------------------------
MESH_COLLECTIVE_DEADLINE_MS = register(
    "spark.rapids.tpu.mesh.collectiveDeadlineMs",
    "Wall-clock deadline for one compiled mesh all_to_all exchange "
    "(parallel/mesh.py).  On expiry the exchange raises a typed "
    "timeout and the stage degrades to the local/TCP shuffle plane "
    "with a loud metric (mesh_collective_timeouts_total) instead of "
    "hanging; the launched program itself cannot be recalled (the "
    "watchdog is cooperative, like query deadlines).  0 (default) "
    "disables the watchdog and runs the collective inline.", 0)

# --- robustness: seeded chaos / fault injection -----------------------------
CHAOS_ENABLED = register(
    "spark.rapids.tpu.chaos.enabled",
    "Master switch for the seeded fault-injection registry "
    "(robustness/faults.py).  Off (default) costs one dict lookup per "
    "instrumented chokepoint; on, each armed site draws a deterministic "
    "seeded decision per traversal and raises a site-appropriate "
    "injected fault.  The unified surface also drives the synthetic-OOM "
    "sites the retry framework previously armed separately.", False)
CHAOS_SEED = register(
    "spark.rapids.tpu.chaos.seed",
    "Seed for the deterministic fault schedule: site X's Nth traversal "
    "makes the same inject/pass decision on every run with the same "
    "seed, independent of thread interleaving across sites.", 0)
CHAOS_SITES = register(
    "spark.rapids.tpu.chaos.sites",
    "Comma list of armed injection sites, each optionally 'site:prob' "
    "to override the global probability (e.g. "
    "'shuffle.fetch:0.3,spill.disk_read').  Empty arms EVERY site — "
    "note sites without a built-in recovery protocol (transfer.h2d, "
    "transfer.d2h, kernel.compile, device.fatal, query.cancel.race) "
    "then fail queries by design — with a TYPED error, never a wedged "
    "thread.  See docs/robustness.md for the site catalog.", "",
    type_=str)
CHAOS_PROBABILITY = register(
    "spark.rapids.tpu.chaos.probability",
    "Default injection probability per armed-site traversal.", 0.05)

SORT_RADIX = register(
    "spark.rapids.sql.sort.radix",
    "auto|on|off: stable LSD radix argsort (1-bit cumsum+scatter passes "
    "— linear VPU work instead of lax.sort's bitonic O(n log^2 n) "
    "compare-exchange network on TPU).  auto runs a one-time bake-off "
    "per backend and keeps the winner (ops/radix_sort.py; the reference "
    "leans on cuDF's GPU radix sort for the same reason).", "auto")

# --- I/O -------------------------------------------------------------------
PARQUET_READER_TYPE = register(
    "spark.rapids.sql.format.parquet.reader.type",
    "AUTO|PERFILE|MULTITHREADED|COALESCING multi-file reader strategy "
    "(reference GpuMultiFileReader.scala:176-373).", "AUTO")
MULTITHREAD_READ_NUM_THREADS = register(
    "spark.rapids.sql.multiThreadedRead.numThreads",
    "Thread pool size for multithreaded file reads.", 20)
PARQUET_ENABLED = register(
    "spark.rapids.sql.format.parquet.enabled", "Accelerate Parquet.", True)
ORC_ENABLED = register(
    "spark.rapids.sql.format.orc.enabled", "Accelerate ORC.", True)
CSV_ENABLED = register(
    "spark.rapids.sql.format.csv.enabled", "Accelerate CSV.", True)
JSON_ENABLED = register(
    "spark.rapids.sql.format.json.enabled", "Accelerate JSON.", False)
AVRO_ENABLED = register(
    "spark.rapids.sql.format.avro.enabled", "Accelerate Avro.", False)
CSV_DEVICE_DECODE = register(
    "spark.rapids.sql.format.csv.deviceDecode.enabled",
    "Parse CSV on the device: the host scans only newline/delimiter "
    "structure (vectorized); field bytes gather into matrices and parse "
    "through the same Spark-exact cast_strings kernels the CAST matrix "
    "uses.  Quoted fields, custom null markers, CRLF, ragged rows and "
    "parse failures against the plan schema decline to the host pyarrow "
    "reader (reference device parse: GpuCSVScan.scala:355 "
    "Table.readCSV).", True)
JSON_DEVICE_DECODE = register(
    "spark.rapids.sql.format.json.deviceDecode.enabled",
    "Parse JSON-lines on the device: the host scans only structure "
    "(quote spans by parity, structural colons/commas/braces outside "
    "strings, key and value byte spans — all vectorized), and value "
    "bytes gather into matrices and parse through the same Spark-exact "
    "cast_strings kernels the CAST matrix uses.  Escapes, nested "
    "objects/arrays, multiLine mode, single-quote syntax, CRLF and any "
    "value failing to parse as the plan schema's type decline to the "
    "host pyarrow reader (reference device parse: GpuJsonScan via "
    "GpuTextBasedPartitionReader, Table.readJSON).", True)
ORC_DEVICE_DECODE = register(
    "spark.rapids.sql.format.orc.deviceDecode.enabled",
    "Decode ORC stripes on the device: the host parses only structure "
    "(protobuf footers, compression block framing, RLEv2/byte-RLE run "
    "headers) and XLA programs do the per-value work — MSB bit-unpack, "
    "zigzag, DELTA prefix sums, PRESENT bit expansion, null scatter, "
    "dictionary remap, string-matrix gather.  Columns outside the "
    "envelope (timestamps, decimals, nested, RLEv1, PATCHED_BASE) fall "
    "back to host decode individually (reference device decode: "
    "GpuOrcScan.scala:893 Table.readORC).", True)
PARQUET_DEVICE_DECODE = register(
    "spark.rapids.sql.format.parquet.deviceDecode.enabled",
    "Decode parquet pages on the device: the host parses only structure "
    "(footer, page headers, RLE/bit-packed run boundaries) and XLA "
    "programs do all per-value work — bit-unpacking, dictionary gather, "
    "def-level null scatter, physical->logical finishing.  Columns "
    "outside the envelope (nested, mixed-encoding, exotic codecs) fall "
    "back to host decode individually.  Applies to PERFILE and "
    "MULTITHREADED parquet scans; COALESCING reads stay on the host "
    "decode (reference device decode: GpuParquetScan.scala:2649 "
    "Table.readParquet).", True)
PARQUET_PUSHDOWN_ENABLED = register(
    "spark.rapids.sql.format.parquet.filterPushdown.enabled",
    "Prune parquet row groups with footer column statistics against "
    "scan-adjacent filter conjuncts before decode (reference "
    "GpuParquetScan footer parse + block filtering, "
    "GpuParquetScan.scala:2765).", True)
READER_CHUNKED = register(
    "spark.rapids.sql.reader.chunked",
    "Read input files in multiple output batches (one per row-group run) "
    "instead of one batch per file, bounding peak memory (reference "
    "chunked readers, RapidsConf.scala:568).", True)
READER_CHUNKED_TARGET_ROWS = register(
    "spark.rapids.sql.reader.chunked.targetRows",
    "Row threshold that closes a chunk when chunked reading is on.",
    1 << 21)
FILECACHE_ENABLED = register(
    "spark.rapids.filecache.enabled",
    "Cache input data files on local disk keyed by (path, size, mtime) — "
    "the reference's file-cache feature (hook points "
    "GpuParquetScan/GpuOrcDataReader; impl shipped in the private jar).",
    False)
FILECACHE_PATH = register(
    "spark.rapids.filecache.path",
    "Directory for the local file cache (empty = system temp).", "")
FILECACHE_MAX_BYTES = register(
    "spark.rapids.filecache.maxBytes",
    "Evict least-recently-used cached files past this total size.",
    16 << 30)
FATAL_DUMP_PATH = register(
    "spark.rapids.tpu.fatalDump.path",
    "Directory for fatal-device-error diagnostics bundles (exception, "
    "backend/device state, spill catalog) — the GpuCoreDumpHandler "
    "analog; empty disables capture.", "")
FATAL_ERROR_EXIT = register(
    "spark.rapids.tpu.fatalErrorExit",
    "Self-terminate the process with exit code 20 on a fatal device "
    "error so an external scheduler replaces it (the reference "
    "executor's behavior, Plugin.scala:515-539). Off by default: this "
    "engine usually runs inside the user's process.", False)
PYTHON_WORKER_ISOLATED = register(
    "spark.rapids.python.worker.isolated",
    "Run pandas UDFs in separate worker PROCESSES with Arrow IPC "
    "exchange (reference python/rapids/daemon.py): a user function that "
    "kills its interpreter fails the task, not the session, and the "
    "concurrentPythonWorkers cap gates real processes.  false = "
    "in-process fast path (no crash containment).", True)
CONCURRENT_PYTHON_WORKERS = register(
    "spark.rapids.python.concurrentPythonWorkers",
    "Max concurrently-running user-Python sections (pandas UDFs, "
    "applyInPandas, mapInPandas) — bounds host memory held by parallel "
    "Arrow/pandas materializations (reference PythonWorkerSemaphore).", 4)
IO_REPLACE_PATHS = register(
    "spark.rapids.tpu.io.replacePaths",
    "Comma-separated 'scheme://old->new' prefix rewrites applied to scan "
    "paths before reading — the Alluxio path-replacement analog "
    "(reference AlluxioUtils.scala:671 spark.rapids.alluxio.pathsToReplace).",
    "")

# --- optimizer -------------------------------------------------------------
OPTIMIZER_ENABLED = register(
    "spark.rapids.sql.optimizer.enabled",
    "Cost-based CPU-vs-TPU optimizer (off by default like the reference).",
    False)
OPTIMIZER_DEFAULT_CPU_COST = register(
    "spark.rapids.sql.optimizer.cpu.exec.default",
    "Default CPU cost per row per op (seconds).", 0.0002)
OPTIMIZER_DEFAULT_GPU_COST = register(
    "spark.rapids.sql.optimizer.gpu.exec.default",
    "Default accelerator cost per row per op (seconds).", 0.0001)

# --- pipelined async execution ----------------------------------------------
TASK_PARALLELISM = register(
    "spark.rapids.tpu.task.parallelism",
    "Number of partitions execute_all runs concurrently on a bounded "
    "thread pool (the local-mode analog of Spark running N tasks per "
    "executor; reference SURVEY §2.7 per-task concurrency under the GPU "
    "semaphore).  1 (default) is the serial driver loop — bit-identical "
    "results either way: per-partition batch order and cross-partition "
    "result order are both preserved.  Device admission is still gated "
    "by spark.rapids.sql.concurrentGpuTasks; set it >= this value to "
    "actually overlap host and device work.  Nested plans (exchange map "
    "sides, broadcast builds, subqueries) always run serially inside "
    "their owning task.", 1, commonly_used=True)
PREFETCH_ENABLED = register(
    "spark.rapids.tpu.prefetch.enabled",
    "Insert AsyncPrefetchExec boundaries after planning: a bounded "
    "background queue decouples the expensive seams (file scans, "
    "host->device uploads, exchange reduce sides) from their consumer, "
    "so host decode/upload overlaps downstream compute (the reference's "
    "multithreaded reader prefetch, GpuMultiFileReader.scala:176).  "
    "Exceptions (including injected chaos faults) propagate through the "
    "queue to the consumer with their original type.  Off (default) "
    "keeps the fully synchronous pipeline.", False, commonly_used=True)
PREFETCH_DEPTH = register(
    "spark.rapids.tpu.prefetch.depth",
    "Bound on batches buffered per AsyncPrefetchExec queue; the producer "
    "blocks when the consumer falls this many batches behind (memory "
    "backpressure, the maxBytesInFlight analog at pipeline seams).", 2)
TRANSFER_DOUBLE_BUFFER = register(
    "spark.rapids.tpu.transfer.doubleBuffer.enabled",
    "Double-buffer backend transitions: HostToDeviceExec dispatches "
    "batch N+1's upload while batch N is consumed downstream, and "
    "DeviceToHostExec issues the prepacked fetch for batch N+1 before "
    "yielding batch N's result — at most ONE transfer in flight ahead "
    "of the consumer, still under the OOM-guard/spill protocol "
    "(reference stream-overlapped transfers, SURVEY §2.2).  Off "
    "(default) keeps transfers serialized with compute.", False)

# --- metrics / debug -------------------------------------------------------
METRICS_LEVEL = register(
    "spark.rapids.sql.metrics.level",
    "ESSENTIAL|MODERATE|DEBUG operator metric verbosity.", "MODERATE")
TRACE_ENABLED = register(
    "spark.rapids.tpu.trace.enabled",
    "Emit jax.profiler TraceMe ranges around operator execution "
    "(NVTX-range equivalent).", False)
TRACE_SINK = register(
    "spark.rapids.tpu.trace.sink",
    "Query-timeline tracer sink: '' (off), 'memory' (keep the ring "
    "buffer in process for profile_last_query() / "
    "session.export_chrome_trace(path)), or a directory path — each "
    "query additionally appends its timeline as a JSONL event log "
    "(query-<pid>-<n>.jsonl, the Spark eventLog/history analog).  The "
    "tracer attributes blocked readbacks, kernel trace+compile and "
    "H2D/D2H bytes to exec nodes; spark.rapids.tpu.profile.enabled "
    "implies sink=memory.", "")
TRACE_BUFFER_EVENTS = register(
    "spark.rapids.tpu.trace.bufferEvents",
    "Capacity of the tracer's bounded event ring buffer.  On overflow "
    "the OLDEST events are dropped (newest kept) and the trace summary "
    "reports dropped_events.", 65536)
PROFILE_ENABLED = register(
    "spark.rapids.tpu.profile.enabled",
    "Record per-exec wall time + batch counts during execution; read the "
    "report with session.profile_last_query() (the SQL-UI per-op "
    "GpuMetric view).", False)
METRICS_ENABLED = register(
    "spark.rapids.tpu.metrics.enabled",
    "Feed the process-wide metrics registry (observability/metrics.py): "
    "counters, gauges and log-bucketed latency histograms (p50/p95/p99) "
    "from the tracer, shuffle, spill/retention and kernel-cache "
    "chokepoints, labeled by query id and session id.  Export with "
    "session.metrics_prometheus() / metrics_snapshot().  Off (default) "
    "costs one dict lookup per chokepoint.", False, commonly_used=True)
METRICS_MAX_SERIES = register(
    "spark.rapids.tpu.metrics.maxSeries",
    "Cardinality bound on the metrics registry: past this many distinct "
    "(name, labels) series, NEW series are dropped and counted in "
    "metrics_dropped_series — an exec-name or label explosion can never "
    "OOM the driver.", 4096)
HISTORY_ENABLED = register(
    "spark.rapids.tpu.history.enabled",
    "Query flight recorder (observability/history.py): every query "
    "leaves one record (plan fingerprint, duration, last_query_metrics, "
    "trace_summary, decode engagement, wire bytes) in a bounded "
    "in-memory ring read back via session.query_history().  One dict "
    "build + list append per query.", True)
HISTORY_MAX_QUERIES = register(
    "spark.rapids.tpu.history.maxQueries",
    "Flight-recorder ring bound, in memory and on disk (the JSONL file "
    "compacts to the newest maxQueries records when it outgrows twice "
    "this).", 128)
HISTORY_PATH = register(
    "spark.rapids.tpu.history.path",
    "On-disk JSONL ring for the query flight recorder (the Spark "
    "history-server analog at flight-recorder weight); empty (default) "
    "keeps history in memory only.  Read back with "
    "observability.history.read_history_file().", "")
DUMP_ON_ERROR_PATH = register(
    "spark.rapids.sql.debug.dumpPath",
    "If set, dump failing batches to parquet here (DumpUtils equivalent).",
    "")
STABLE_SORT = register(
    "spark.rapids.sql.stableSort.enabled", "Force stable device sorts.", False)

# --- multi-tenant serving (serving/, docs/serving.md) -----------------------
SERVING_TENANT = register(
    "spark.rapids.tpu.serving.tenant",
    "Tenant identity of this session.  Stamped on metric series (the "
    "registry's `tenant` label), trace spans, and flight-recorder "
    "records; the serving tier's admission queue schedules and budgets "
    "by it.  Empty (default) means the anonymous single-tenant mode.",
    "")
SERVING_MAX_CONCURRENT = register(
    "spark.rapids.tpu.serving.maxConcurrentQueries",
    "How many admitted queries a ServingEngine lets execute at once "
    "across ALL tenants.  This caps driver-side concurrency; device "
    "admission below it is still arbitrated per task by "
    "spark.rapids.sql.concurrentGpuTasks and the device semaphore.",
    8, commonly_used=True)
SERVING_ADMISSION_TIMEOUT_MS = register(
    "spark.rapids.tpu.serving.admission.timeoutMs",
    "Upper bound on how long a query may wait in the admission queue "
    "before AdmissionTimeout is raised; 0 (default) waits forever.", 0)
SERVING_TENANT_WEIGHTS = register(
    "spark.rapids.tpu.serving.tenant.weights",
    "Comma list of tenant:weight pairs (e.g. 'etl:4,adhoc:1') for the "
    "weighted-fair admission queue: a tenant's share of admission slots "
    "is proportional to its weight.  Tenants not listed get "
    "spark.rapids.tpu.serving.tenant.defaultWeight.", "")
SERVING_TENANT_DEFAULT_WEIGHT = register(
    "spark.rapids.tpu.serving.tenant.defaultWeight",
    "Admission weight for tenants absent from "
    "spark.rapids.tpu.serving.tenant.weights.", 1.0)
SERVING_TENANT_BUDGETS = register(
    "spark.rapids.tpu.serving.tenant.memoryBudgets",
    "Comma list of tenant:bytes pairs capping the estimated input bytes "
    "a tenant may have ADMITTED at once.  The budget gates admission "
    "only — actual device memory stays arbitrated by the semaphore, "
    "OOM-guard and spill machinery.  A query whose lone estimate "
    "exceeds the budget still admits when the tenant has nothing else "
    "in flight (a budget must throttle, never wedge).", "")
SERVING_TENANT_DEFAULT_BUDGET = register(
    "spark.rapids.tpu.serving.tenant.defaultMemoryBudgetBytes",
    "Admission memory budget for tenants absent from "
    "spark.rapids.tpu.serving.tenant.memoryBudgets; 0 (default) means "
    "unbudgeted.", 0)
SERVING_RESULT_CACHE_ENABLED = register(
    "spark.rapids.tpu.serving.resultCache.enabled",
    "Cross-query result cache: a collect whose plan content fingerprint "
    "(operators + literals + input identity) matches a cached entry "
    "returns the cached Arrow table without executing.  Entries are "
    "invalidated when any input file's mtime/size changes and on every "
    "write through io_/writers.py; plans containing non-deterministic "
    "expressions or opaque UDFs are never cached.  Off (default) "
    "outside serving engines.", False, commonly_used=True)
SERVING_RESULT_CACHE_MAX_BYTES = register(
    "spark.rapids.tpu.serving.resultCache.maxBytes",
    "Byte bound on the result cache (Arrow table nbytes); least-"
    "recently-used entries evict past it.", 256 << 20)
SERVING_BROADCAST_SHARE = register(
    "spark.rapids.tpu.serving.broadcastShare.enabled",
    "Share materialized broadcast batches ACROSS queries and sessions "
    "by plan-content key (child subtree + literals + input identity + "
    "encode params).  Shared batches are pinned in the retention "
    "registry so whole-stage donation stays safe; entries follow the "
    "same file-mtime/write invalidation contract as the result cache.  "
    "Off (default) keeps broadcasts per-plan.", False)
SERVING_BROADCAST_SHARE_MAX_BYTES = register(
    "spark.rapids.tpu.serving.broadcastShare.maxBytes",
    "Byte bound on the shared broadcast cache; LRU entries evict (and "
    "unpin) past it.", 256 << 20)

# --- query lifecycle: cancellation, deadlines, degradation, quarantine ------
QUERY_DEADLINE_MS = register(
    "spark.rapids.tpu.query.deadlineMs",
    "Per-query wall-clock deadline: a collect running past it raises "
    "QueryDeadlineExceeded at the next lifecycle poll site (partition "
    "scheduler, prefetch queues, transfer stager, shuffle fetch, "
    "semaphore wait, spill I/O), releasing the semaphore, unpinning "
    "retention and draining prefetch queues on the way out.  0 "
    "(default) means no deadline.  Enforcement latency is bounded by "
    "the 50ms poll interval plus the longest uninterruptible device "
    "dispatch (serving/lifecycle.py).", 0, commonly_used=True)
QUERY_CANCEL_POLL_SITES = register(
    "spark.rapids.tpu.query.cancel.pollSites",
    "Comma list restricting which chokepoints poll the query's "
    "cancellation token (site catalog: admission, partition, sem_wait, "
    "prefetch, stager, shuffle, exchange, spill — docs/robustness.md). "
    "Empty (default) polls every site; a restricted list trades drain "
    "latency for even less poll overhead.", "", type_=str)
PRESSURE_ENABLED = register(
    "spark.rapids.tpu.serving.pressure.enabled",
    "Admission-aware graceful degradation (kill switch): when the "
    "serving admission queue is under pressure (depth or recent-wait "
    "thresholds below), newly-admitted queries plan with a shrunken "
    "resource profile — reduced concurrentGpuTasks share, smaller "
    "batch-rows target, speculative join sizing off — so a saturated "
    "engine degrades throughput-per-query gracefully instead of piling "
    "device working sets.  Off (default) plans every query identically "
    "regardless of queue state.", False, commonly_used=True)
PRESSURE_QUEUE_DEPTH = register(
    "spark.rapids.tpu.serving.pressure.queueDepth",
    "Admission queue depth at or above which the PressureSignal reports "
    "pressure (serving/lifecycle.py).", 4)
PRESSURE_WAIT_MS = register(
    "spark.rapids.tpu.serving.pressure.waitMs",
    "Recent admission-wait (rolling median across tenants) at or above "
    "which the PressureSignal reports pressure; 0 disables the wait "
    "signal (depth still applies).", 250.0)
PRESSURE_SHARE = register(
    "spark.rapids.tpu.serving.pressure.concurrentShare",
    "Fraction of spark.rapids.sql.concurrentGpuTasks a degraded plan "
    "keeps (floored at 1 task).", 0.5)
PRESSURE_BATCH_ROWS = register(
    "spark.rapids.tpu.serving.pressure.batchTargetRows",
    "Batch-rows target cap applied to degraded plans (only ever "
    "lowers spark.rapids.sql.batchSizeRows).", 1 << 18)
QUARANTINE_TTL_MS = register(
    "spark.rapids.tpu.serving.quarantine.ttlMs",
    "How long a plan fingerprint whose execution produced a "
    "FatalDeviceError stays quarantined (immediate retries raise "
    "QueryQuarantined instead of re-killing the device); 0 disables "
    "quarantine.", 60_000)
QUARANTINE_MAX_ENTRIES = register(
    "spark.rapids.tpu.serving.quarantine.maxEntries",
    "Size bound on the quarantine registry; oldest entries evict past "
    "it.", 128)
DEGRADED_PROBE_INTERVAL_MS = register(
    "spark.rapids.tpu.serving.degraded.probeIntervalMs",
    "Minimum spacing between device probe attempts while the engine is "
    "degraded after a fatal device error; admissions arriving between "
    "probes are refused with EngineDegraded.", 1_000)

# --- telemetry plane: scrape/health endpoint + SLO objectives ---------------
TELEMETRY_ENABLED = register(
    "spark.rapids.tpu.telemetry.enabled",
    "Kill switch for the embedded telemetry HTTP server "
    "(observability/server.py): a daemon-thread ThreadingHTTPServer "
    "bound to 127.0.0.1 serving /metrics (Prometheus exposition), "
    "/healthz (degraded/quarantine/admission/semaphore state, non-200 "
    "when the engine is degraded), /queries (flight-recorder ring), "
    "/doctor (last ranked verdicts) and /slo (per-tenant burn rates). "
    "Owned by the ServingEngine when serving, else by the TpuSession; "
    "shutdown is leak-free (no lingering thread or bound port).  Off "
    "(default) starts nothing and changes no behavior.",
    False, commonly_used=True)
TELEMETRY_PORT = register(
    "spark.rapids.tpu.telemetry.port",
    "TCP port for the telemetry server; 0 (default) binds an ephemeral "
    "port (read it back from engine.telemetry.port / "
    "session.telemetry.port).", 0, commonly_used=True)
SLO_LATENCY_MS = register(
    "spark.rapids.tpu.slo.latencyObjectiveMs",
    "Per-tenant latency objective: a query slower than this is a "
    "'slow' event against the latency error budget (observability/"
    "slo.py reads the per-tenant query_ms histograms).  0 (default) "
    "disables the latency SLO leg.", 0.0, commonly_used=True)
SLO_LATENCY_TARGET = register(
    "spark.rapids.tpu.slo.latencyTarget",
    "Fraction of queries that must meet the latency objective (the "
    "latency error budget is 1 - target).", 0.99)
SLO_ERROR_TARGET = register(
    "spark.rapids.tpu.slo.availabilityTarget",
    "Fraction of queries that must succeed (status=ok in "
    "queries_total); the availability error budget is 1 - target.",
    0.999)
SLO_WINDOWS_S = register(
    "spark.rapids.tpu.slo.burnWindowsS",
    "Comma list of burn-rate window lengths in seconds, shortest "
    "first; a tenant burning its error budget at rate >= 1 in the "
    "shortest window is 'burning' (slo-burn doctor verdict).",
    "300,3600", type_=str)

# --- self-driving perf sentry (observability/sentry.py) ---------------------
SENTRY_ENABLED = register(
    "spark.rapids.tpu.sentry.enabled",
    "Master switch for the self-driving perf sentry "
    "(observability/sentry.py): an autonomous daemon that probes for a "
    "live tunnel window with cancellable bounded-timeout device probes, "
    "runs the bench shape set on detection, diffs against the last "
    "live-evidence baseline and appends the verdict to the evidence "
    "ledger.  Consulted by tools/perf_sentry.py and "
    "sentry.maybe_start_from_conf(); nothing starts one implicitly — "
    "off (default) means the CLI exits without probing, so a conf push "
    "stops every sentry in the fleet.", False, commonly_used=True)
SENTRY_PROBE_INTERVAL_MS = register(
    "spark.rapids.tpu.sentry.probeIntervalMs",
    "Base interval between device probes while no window is open; "
    "failed probes back off exponentially from this interval (capped "
    "at 8x), a live window resets it.", 480_000, commonly_used=True)
SENTRY_PROBE_TIMEOUT_MS = register(
    "spark.rapids.tpu.sentry.probeTimeoutMs",
    "Hard per-probe budget: a probe still unanswered at the deadline "
    "is cancelled (QueryContext deadline machinery) and banked as "
    "outcome=timeout — a wedged tunnel can never hang the sentry.",
    30_000, commonly_used=True)
SENTRY_LEDGER_PATH = register(
    "spark.rapids.tpu.sentry.ledgerPath",
    "Append-only evidence ledger (srt-ledger/1 JSONL): one record per "
    "captured window with artifact path, evidence class, bench_diff "
    "verdict vs the last live baseline, doctor verdict and the "
    "machine-named next-bottleneck follow-up.  Empty (default) uses "
    "<repo>/.bench_capture/ledger.jsonl.", "", type_=str)
SENTRY_SHAPES = register(
    "spark.rapids.tpu.sentry.shapes",
    "Comma list of bench shapes the sentry runs on a live window "
    "(bench.run_shape_set vocabulary: join, sort, window, coalesce, "
    "encoded).", "join,sort,window,coalesce,encoded", type_=str)

# --- TPU-specific ----------------------------------------------------------
BUCKET_MIN_ROWS = register(
    "spark.rapids.tpu.shapeBucket.minRows",
    "Smallest shape bucket; batches are padded up to power-of-two row "
    "capacities so XLA compiles one program per (schema, bucket).", 16)
STRING_MAX_BYTES = register(
    "spark.rapids.tpu.string.maxBytes",
    "Per-bucket cap on padded string width (bytes per row).", 8192)
DEVICE_MESH_AXES = register(
    "spark.rapids.tpu.mesh.axes",
    "Comma list of mesh axis names for distributed exchange.", "data")
EXPLAIN_ONLY_PLATFORM = register(
    "spark.rapids.tpu.explainOnly.platform",
    "Platform assumed when planning in explainOnly mode without a TPU.",
    "tpu", internal=True)


class RapidsConf:
    """Immutable-ish snapshot of config values, resolved from defaults +
    overrides + ``SPARK_RAPIDS_*`` style environment variables."""

    _global_lock = threading.Lock()
    _global: Optional["RapidsConf"] = None

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._values: Dict[str, Any] = {}
        overrides = dict(overrides or {})
        for key, entry in ENTRIES.items():
            env_key = key.upper().replace(".", "_")
            raw = overrides.pop(key, os.environ.get(env_key))
            self._values[key] = entry.convert(raw)
        # unknown keys are kept verbatim (forward compat, like SQLConf)
        self._extra = overrides

    def get(self, key_or_entry, default: Any = None) -> Any:
        key = key_or_entry.key if isinstance(key_or_entry, ConfEntry) else key_or_entry
        if key in self._values:
            return self._values[key]
        return self._extra.get(key, default)

    def get_bool(self, key: str, default: bool = True) -> bool:
        """Boolean read of a possibly-unregistered key (per-expression /
        per-exec enable flags are dynamic: one per registered rule, like
        the reference's auto-generated conf-per-rule entries)."""
        raw = self.get(key, default)
        if isinstance(raw, bool):
            return raw
        return str(raw).strip().lower() in ("true", "1", "yes")

    def set(self, key: str, value: Any) -> "RapidsConf":
        if key in ENTRIES:
            self._values[key] = ENTRIES[key].convert(value)
        else:
            self._extra[key] = value
        return self

    def copy(self, overrides: Optional[Dict[str, Any]] = None) -> "RapidsConf":
        c = RapidsConf()
        c._values = dict(self._values)
        c._extra = dict(self._extra)
        for k, v in (overrides or {}).items():
            c.set(k, v)
        return c

    # Convenience typed accessors used across the engine -------------------
    @property
    def is_sql_enabled(self) -> bool:
        return bool(self.get(SQL_ENABLED))

    @property
    def is_explain_only(self) -> bool:
        return str(self.get(SQL_MODE)).lower() == "explainonly"

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_bytes(self) -> int:
        return int(self.get(BATCH_SIZE_BYTES))

    @property
    def batch_size_rows(self) -> int:
        return int(self.get(BATCH_SIZE_ROWS))

    @property
    def ansi_enabled(self) -> bool:
        return bool(self.get(ANSI_ENABLED))

    @property
    def concurrent_tasks(self) -> int:
        return int(self.get(CONCURRENT_TASKS))

    @property
    def shuffle_partitions(self) -> int:
        return int(self.get(SHUFFLE_PARTITIONS))

    @classmethod
    def get_global(cls) -> "RapidsConf":
        with cls._global_lock:
            if cls._global is None:
                cls._global = RapidsConf()
            return cls._global

    @classmethod
    def set_global(cls, conf: "RapidsConf") -> None:
        with cls._global_lock:
            cls._global = conf


def help_text(include_internal: bool = False) -> str:
    """Markdown config documentation, mirroring RapidsConf.help() docgen
    (reference RapidsConf.scala:2057-2103 emits docs/configs.md)."""
    lines = ["# Configuration", "",
             "Name | Description | Default Value", "-----|-------------|--------------"]
    for key in sorted(ENTRIES):
        e = ENTRIES[key]
        if e.internal and not include_internal:
            continue
        doc = e.doc.replace("|", "\\|")
        lines.append(f"{e.key} | {doc} | {e.default}")
    return "\n".join(lines) + "\n"
