"""Delta-analog ACID table format — the TPU-native counterpart of the
reference's ``delta-lake/`` module (22.7k LoC; ``GpuOptimisticTransaction``,
``GpuMergeIntoCommand``, ``GpuDeleteCommand``, ``GpuUpdateCommand``,
OPTIMIZE/Z-ORDER; SURVEY §2.9/L7): a transaction-logged parquet table with
snapshot reads, time travel, DELETE/UPDATE/MERGE executed through the
engine's own device pipeline, Z-ORDER clustering, and VACUUM.

All DML rewrites only the files that contain affected rows (file-level
copy-on-write, the reference's touched-file strategy)."""

from __future__ import annotations

import json
import os
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from .log import (AddFile, ConcurrentModificationException, DeltaLog,
                  Snapshot, add_action, metadata_action, remove_action)
from .zorder import zorder_indices

__all__ = ["DeltaTable", "DeltaLog", "ConcurrentModificationException"]


def _collect_stats(table: pa.Table) -> dict:
    """Per-file column statistics for data skipping (real Delta's `stats`
    JSON; the reference GPU-computes these in its write stats trackers,
    delta-lake/common GpuStatisticsCollection analog)."""
    import pyarrow.compute as pc
    mins: Dict[str, object] = {}
    maxs: Dict[str, object] = {}
    nulls: Dict[str, int] = {}
    for i, field_ in enumerate(table.schema):
        col = table.column(i)
        nulls[field_.name] = col.null_count
        t = field_.type
        if not (pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_string(t) or pa.types.is_date(t)
                or pa.types.is_timestamp(t) or pa.types.is_decimal(t)):
            continue
        if col.null_count == len(col):
            continue
        try:
            mm = pc.min_max(col)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
        except pa.ArrowNotImplementedError:
            continue
        if lo is None:
            continue
        for d, v in ((mins, lo), (maxs, hi)):
            if hasattr(v, "isoformat"):
                v = v.isoformat()
            elif type(v).__name__ == "Decimal":
                v = str(v)
            d[field_.name] = v
    return {"numRecords": table.num_rows, "minValues": mins,
            "maxValues": maxs, "nullCount": nulls}


def _write_data_file(table_path: str, table: pa.Table) -> dict:
    name = f"part-{uuid.uuid4().hex}.parquet"
    full = os.path.join(table_path, name)
    pq.write_table(table, full)
    return add_action(name, os.path.getsize(full), table.num_rows,
                      stats=_collect_stats(table))


class DeltaTable:
    def __init__(self, session, path: str):
        self._session = session
        self.path = path
        self.log = DeltaLog(path)

    # --- construction -----------------------------------------------------
    @staticmethod
    def forPath(session, path: str) -> "DeltaTable":
        dt = DeltaTable(session, path)
        if not dt.log.exists():
            raise FileNotFoundError(f"not a delta table: {path}")
        return dt

    @staticmethod
    def is_delta_table(path: str) -> bool:
        return DeltaLog(path).exists()

    @staticmethod
    def create(session, path: str, df=None, partition_by=()) -> "DeltaTable":
        """Create a table from a DataFrame (or an empty one from a later
        first append)."""
        dt = DeltaTable(session, path)
        os.makedirs(path, exist_ok=True)
        if df is not None:
            data = df.collect()
            actions = [metadata_action(df.schema, partition_by)]
            if data.num_rows:
                actions.append(_write_data_file(path, data))
            dt.log.commit(actions, "CREATE TABLE AS SELECT")
        return dt

    # --- read side ----------------------------------------------------------
    def toDF(self, version: Optional[int] = None,
             timestamp_ms: Optional[int] = None):
        if timestamp_ms is not None:
            if version is not None:
                raise ValueError(
                    "specify versionAsOf OR timestampAsOf, not both")
            version = self.log.version_as_of_timestamp(int(timestamp_ms))
        snap = self.log.snapshot(version)
        adds = [snap.files[p] for p in snap.file_paths]
        paths = [os.path.join(self.path, p) for p in snap.file_paths]
        if not paths:
            empty = snap.schema.empty_arrow_table() if hasattr(
                snap.schema, "empty_arrow_table") else self._empty(snap)
            return self._session.create_dataframe(empty)
        # Per-file alignment is needed in two interop cases: schema
        # evolution (older files lack newer columns -> nulls), and real
        # Delta partitioned tables, whose partition column VALUES live in
        # add.partitionValues rather than in the data files (protocol
        # spec; readers re-inject them as constants).
        want = self._empty(snap).schema
        has_pv = any(a.partition_values for a in adds)
        if has_pv or any(pq.read_schema(p).names != want.names
                         for p in paths):
            pieces = []
            for p, a in zip(paths, adds):
                t = pq.read_table(p)
                pv = a.partition_values or {}
                arrays = []
                for f in want:
                    if f.name in t.column_names:
                        arrays.append(t.column(f.name).cast(f.type))
                    elif f.name in pv and pv[f.name] is not None:
                        const = pa.array([pv[f.name]] * t.num_rows,
                                         type=pa.string()).cast(f.type)
                        arrays.append(const)
                    else:
                        arrays.append(pa.nulls(t.num_rows, f.type))
                pieces.append(pa.table(dict(zip(want.names, arrays))))
            return self._session.create_dataframe(pa.concat_tables(pieces))
        reader = self._session.read
        return reader.parquet(*paths)

    def _empty(self, snap: Snapshot) -> pa.Table:
        from .. import types as T
        return pa.schema([pa.field(f.name, T.to_arrow(f.data_type))
                          for f in snap.schema.fields]).empty_table()

    def history(self) -> List[dict]:
        return self.log.history()

    def version(self) -> int:
        return self.log.latest_version()

    # --- append / overwrite -------------------------------------------------
    def write_df(self, df, mode: str = "append",
                 partition_by: Sequence[str] = (),
                 merge_schema: bool = False):
        data = df.collect()
        snap = self.log.snapshot() if self.log.exists() else None
        part_cols = (tuple(partition_by) if partition_by
                     else (snap.partition_columns if snap else ()))
        actions: List[dict] = []
        if snap is None or snap.schema is None:
            actions.append(metadata_action(df.schema, part_cols))
        elif merge_schema:
            new_fields = [f for f in df.schema.fields
                          if f.name not in snap.schema.names]
            if new_fields:
                from .. import types as T
                unioned = T.StructType(tuple(snap.schema.fields)
                                       + tuple(new_fields))
                actions.append(metadata_action(
                    unioned, part_cols, snap.configuration))
        else:
            extra = [n for n in data.schema.names
                     if n not in snap.schema.names]
            if extra:
                raise ValueError(
                    f"schema mismatch: new columns {extra} (pass "
                    f"merge_schema=True to evolve the table schema)")
        if snap is not None:
            self._enforce_constraints(snap, data)
        if mode == "overwrite" and snap is not None:
            actions.extend(remove_action(p) for p in snap.file_paths)
        if data.num_rows:
            actions.extend(self._write_partitioned(data, part_cols))
        op = "WRITE" if mode == "append" else "OVERWRITE"
        self.log.commit(actions, op,
                        read_version=snap.version if snap else None)
        return self

    def _write_partitioned(self, data: pa.Table,
                           part_cols: Sequence[str]) -> List[dict]:
        """One data file per distinct partition-column tuple under
        hive-style ``col=value/`` directories (GpuFileFormatDataWriter's
        dynamic partitioning)."""
        if not part_cols:
            return [_write_data_file(self.path, data)]
        pdf = data.to_pandas()
        actions = []
        for vals, group in pdf.groupby(list(part_cols), sort=False,
                                       dropna=False):
            if not isinstance(vals, tuple):
                vals = (vals,)
            sub = "/".join(f"{c}={v}" for c, v in zip(part_cols, vals))
            os.makedirs(os.path.join(self.path, sub), exist_ok=True)
            piece = pa.Table.from_pandas(group, preserve_index=False,
                                         schema=data.schema)
            name = f"{sub}/part-{uuid.uuid4().hex}.parquet"
            full = os.path.join(self.path, name)
            pq.write_table(piece, full)
            actions.append(add_action(name, os.path.getsize(full),
                                      piece.num_rows,
                                      stats=_collect_stats(piece)))
        return actions

    # --- constraints --------------------------------------------------------
    def add_not_null_constraint(self, *columns: str):
        """NOT NULL invariants (reference GpuCheckDeltaInvariant analog),
        enforced on every write/update/merge."""
        snap = self.log.snapshot()
        cfg = dict(snap.configuration)
        existing = json.loads(cfg.get("delta.constraints.notNull", "[]"))
        cfg["delta.constraints.notNull"] = json.dumps(
            sorted(set(existing) | set(columns)))
        self.log.commit(
            [metadata_action(snap.schema, snap.partition_columns, cfg)],
            "ADD CONSTRAINT", read_version=snap.version)
        return self

    def add_check_constraint(self, name: str, column: str, op: str,
                             value) -> "DeltaTable":
        """CHECK (col <op> literal) constraint, serialized into the table
        configuration and enforced on writes.  NULL column values PASS
        (SQL CHECK semantics: only FALSE violates)."""
        if op not in ("=", "<", "<=", ">", ">="):
            raise ValueError(f"unsupported CHECK operator {op!r}")
        snap = self.log.snapshot()
        cfg = dict(snap.configuration)
        cfg[f"delta.constraints.{name}"] = json.dumps(
            {"column": column, "op": op, "value": value})
        self.log.commit(
            [metadata_action(snap.schema, snap.partition_columns, cfg)],
            "ADD CONSTRAINT", read_version=snap.version)
        return self

    def _enforce_constraints(self, snap: Snapshot, data: pa.Table):
        if not snap.configuration or data.num_rows == 0:
            return
        import pyarrow.compute as pc
        for key, raw in snap.configuration.items():
            if not key.startswith("delta.constraints."):
                continue
            if key == "delta.constraints.notNull":
                for col in json.loads(raw):
                    if col in data.column_names \
                            and data.column(col).null_count:
                        raise ValueError(
                            f"NOT NULL constraint violated for column "
                            f"{col}")
                continue
            spec = json.loads(raw)
            col = spec["column"]
            if col not in data.column_names:
                continue
            ops = {"=": pc.equal, "<": pc.less, "<=": pc.less_equal,
                   ">": pc.greater, ">=": pc.greater_equal}
            ok = ops[spec["op"]](data.column(col), spec["value"])
            # NULL passes (three-valued CHECK); count FALSE only
            violations = pc.sum(pc.equal(ok, False)).as_py() or 0
            if violations:
                raise ValueError(
                    f"CHECK constraint {key.rsplit('.', 1)[1]} violated "
                    f"by {violations} row(s): {col} {spec['op']} "
                    f"{spec['value']!r}")

    # --- data skipping ------------------------------------------------------
    def _files_matching(self, snap: Snapshot, cond) -> List[str]:
        """File paths whose stats admit a match for the condition — the
        data-skipping read of the per-file `stats` (files without stats
        or non-pushable predicates are conservatively kept)."""
        expr = getattr(cond, "expr", None)
        if expr is None or snap.schema is None:
            return snap.file_paths
        from ..io_.pushdown import extract_pushable, stats_possible
        from ..sql.expressions.core import AttributeReference
        attrs = [AttributeReference(f.name, f.data_type, True)
                 for f in snap.schema.fields]
        try:
            filters = extract_pushable(expr, attrs)
        except Exception:
            return snap.file_paths
        if not filters:
            return snap.file_paths
        out = []
        for p in snap.file_paths:
            st = snap.files[p].stats
            if not st:
                out.append(p)
                continue
            mins = st.get("minValues", {})
            maxs = st.get("maxValues", {})
            nullc = st.get("nullCount", {})
            nrec = st.get("numRecords")
            keep = True
            for col, op, lit in filters:
                if op == "isnull":
                    if nullc.get(col) == 0:
                        keep = False
                        break
                    continue
                if op == "isnotnull":
                    nc = nullc.get(col)
                    if nc is not None and nrec is not None and nc >= nrec:
                        keep = False
                        break
                    continue
                lo, hi = mins.get(col), maxs.get(col)
                if lo is None or hi is None:
                    continue
                if not stats_possible(lo, hi, op, lit):
                    keep = False
                    break
            if keep:
                out.append(p)
        return out

    # --- DML ----------------------------------------------------------------
    def _file_df(self, rel_path: str, snap: Optional[Snapshot] = None):
        """One file as a DataFrame.  For foreign partitioned tables the
        partition columns live in add.partitionValues, not the data file —
        inject them so DML rewrites carry the values forward (the rewritten
        file then stores the column physically, the engine-native form)."""
        full = os.path.join(self.path, rel_path)
        add = snap.files.get(rel_path) if snap is not None else None
        pv = add.partition_values if add is not None else None
        if not pv:
            return self._session.read.parquet(full)
        t = pq.read_table(full)
        want = self._empty(snap).schema
        arrays = []
        for f in want:
            if f.name in t.column_names:
                arrays.append(t.column(f.name).cast(f.type))
            elif f.name in pv and pv[f.name] is not None:
                arrays.append(pa.array([pv[f.name]] * t.num_rows,
                                       type=pa.string()).cast(f.type))
            else:
                arrays.append(pa.nulls(t.num_rows, f.type))
        return self._session.create_dataframe(
            pa.table(dict(zip(want.names, arrays))))

    def delete(self, condition=None) -> int:
        """DELETE FROM t WHERE condition; returns #rows deleted
        (GpuDeleteCommand analog: rewrite only touched files)."""
        snap = self.log.snapshot()
        actions: List[dict] = []
        deleted = 0
        candidates = snap.file_paths
        if condition is not None:
            dummy = self._session.create_dataframe(self._empty(snap))
            cond0 = condition(dummy) if callable(condition) else condition
            candidates = self._files_matching(snap, cond0)
        for rel in candidates:
            df = self._file_df(rel, snap)
            if condition is None:
                deleted += df.count()
                actions.append(remove_action(rel))
                continue
            cond = condition(df) if callable(condition) else condition
            hits = df.filter(cond).count()
            if hits == 0:
                continue
            deleted += hits
            # SQL three-valued logic: a NULL condition row is NOT deleted,
            # and ~NULL is still NULL — keep must be (NOT cond OR cond
            # IS NULL), not just NOT cond
            kept = df.filter(~cond | cond.isNull()).collect()
            actions.append(remove_action(rel))
            if kept.num_rows:
                actions.append(_write_data_file(self.path, kept))
        if actions:
            self.log.commit(actions, "DELETE", read_version=snap.version)
        return deleted

    def update(self, condition, set: Dict[str, object]) -> int:
        """UPDATE t SET col = expr WHERE condition; returns #rows updated
        (GpuUpdateCommand analog)."""
        from ..sql import functions as F
        snap = self.log.snapshot()
        actions: List[dict] = []
        updated = 0
        dummy = self._session.create_dataframe(self._empty(snap))
        cond0 = condition(dummy) if callable(condition) else condition
        for rel in self._files_matching(snap, cond0):
            df = self._file_df(rel, snap)
            cond = condition(df) if callable(condition) else condition
            hits = df.filter(cond).count()
            if hits == 0:
                continue
            updated += hits
            cols = []
            for name in df.columns:
                if name in set:
                    val = set[name]
                    val = val(df) if callable(val) else val
                    cols.append(F.when(cond, val)
                                .otherwise(df[name]).alias(name))
                else:
                    cols.append(df[name])
            out = df.select(*cols).collect()
            self._enforce_constraints(snap, out)
            actions.append(remove_action(rel))
            actions.append(_write_data_file(self.path, out))
        if actions:
            self.log.commit(actions, "UPDATE", read_version=snap.version)
        return updated

    def merge(self, source_df, on: Sequence[str]) -> "MergeBuilder":
        """MERGE INTO t USING source ON t.k = s.k (equi-key form;
        GpuMergeIntoCommand analog)."""
        return MergeBuilder(self, source_df, list(on))

    # --- maintenance --------------------------------------------------------
    def optimize_zorder(self, cols: Sequence[str],
                        target_files: int = 1) -> int:
        """OPTIMIZE t ZORDER BY (cols): rewrite the table clustered along
        the interleaved-bits curve (reference ZOrderRules + jni.ZOrder)."""
        snap = self.log.snapshot()
        if not snap.file_paths:
            return 0
        full = self.toDF().collect()
        if full.num_rows == 0:
            return 0
        order = zorder_indices(full, list(cols))
        clustered = full.take(pa.array(order))
        n = max(1, int(target_files))
        rows = clustered.num_rows
        per = -(-rows // n)
        actions = [remove_action(p, data_change=False)
                   for p in snap.file_paths]
        for i in range(0, rows, per):
            piece = clustered.slice(i, min(per, rows - i))
            a = _write_data_file(self.path, piece)
            a["add"]["dataChange"] = False
            actions.append(a)
        self.log.commit(actions, "OPTIMIZE ZORDER",
                        read_version=snap.version)
        return len(snap.file_paths)

    def vacuum(self) -> List[str]:
        """Remove data files no longer referenced by the LATEST snapshot
        (simplified: no retention window in local mode)."""
        snap = self.log.snapshot()
        live = set(snap.file_paths)
        removed = []
        for root, _dirs, names in os.walk(self.path):
            if os.path.basename(root) == "_delta_log":
                continue
            for name in names:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, self.path)
                if rel.endswith(".parquet") and rel not in live:
                    os.unlink(full)
                    removed.append(rel)
        return removed


class MergeBuilder:
    """whenMatchedUpdate / whenMatchedDelete / whenNotMatchedInsert —
    executed as engine joins (GpuMergeIntoCommand's modified-join plan)."""

    def __init__(self, table: DeltaTable, source_df, on: List[str]):
        self._t = table
        self._src = source_df
        self._on = on
        self._matched_update: Optional[Dict[str, object]] = None
        self._matched_delete = False
        self._insert = False

    def whenMatchedUpdate(self, set: Dict[str, object]) -> "MergeBuilder":
        self._matched_update = set
        return self

    def whenMatchedDelete(self) -> "MergeBuilder":
        self._matched_delete = True
        return self

    def whenNotMatchedInsertAll(self) -> "MergeBuilder":
        self._insert = True
        return self

    def execute(self) -> Dict[str, int]:
        from ..sql import functions as F
        t = self._t
        snap = t.log.snapshot()
        src = self._src
        keys = self._on
        stats = {"updated": 0, "deleted": 0, "inserted": 0}
        actions: List[dict] = []

        src_keys = src.select(*keys).collect()
        key_rows = (list(map(tuple, zip(*[src_keys[k].to_pylist()
                                          for k in keys])))
                    if src_keys.num_rows else [])
        if (self._matched_update is not None or self._matched_delete) and \
                len(key_rows) != len(set(key_rows)):
            # a target row matched by multiple source rows is ambiguous —
            # Delta raises here rather than fan-out-duplicating the target
            raise ValueError(
                "MERGE source has duplicate join keys; a matched target "
                "row would be updated/deleted ambiguously")
        key_sets = set(key_rows)

        for rel in snap.file_paths:
            df = t._file_df(rel, snap)
            tkeys = df.select(*keys).collect()
            rows = list(map(tuple, zip(*[tkeys[k].to_pylist()
                                         for k in keys]))) if \
                tkeys.num_rows else []
            touched = [i for i, r in enumerate(rows) if r in key_sets]
            if not touched:
                continue
            # rewrite this file through engine joins
            if self._matched_delete:
                out = df.join(src, on=keys, how="left_anti").collect()
                stats["deleted"] += len(touched)
            elif self._matched_update is not None:
                matched = df.join(src, on=keys, how="inner")
                cols = []
                for name in df.columns:
                    if name in self._matched_update:
                        v = self._matched_update[name]
                        v = v(df, src) if callable(v) else v
                        cols.append(F.lit(v).alias(name)
                                    if not hasattr(v, "expr")
                                    else v.alias(name))
                    else:
                        cols.append(df[name])
                updated = matched.select(*cols).collect()
                t._enforce_constraints(snap, updated)
                untouched = df.join(src, on=keys, how="left_anti").collect()
                out = (pa.concat_tables([untouched, updated])
                       if untouched.num_rows else updated)
                stats["updated"] += len(touched)
            else:
                continue
            actions.append(remove_action(rel))
            if out.num_rows:
                actions.append(_write_data_file(t.path, out))

        if self._insert:
            target = t.toDF()
            new_rows = src.join(target, on=keys, how="left_anti").collect()
            # align to the target schema (source may order columns freely)
            if new_rows.num_rows:
                cols = snap.schema.names if snap.schema else new_rows.schema.names
                new_rows = new_rows.select([c for c in cols])
                t._enforce_constraints(snap, new_rows)
                actions.append(_write_data_file(t.path, new_rows))
                stats["inserted"] += new_rows.num_rows
        if actions:
            t.log.commit(actions, "MERGE", read_version=snap.version)
        return stats
