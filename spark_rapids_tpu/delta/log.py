"""Delta-style transaction log — the analog of the reference's delta-lake
module core (``GpuOptimisticTransaction``; SURVEY §2.9/L7): an ordered
``_delta_log/{version:020d}.json`` of ndjson actions (metaData / add /
remove / commitInfo) whose replay yields the table snapshot, with
optimistic concurrency via exclusive-create commits.

This is a from-scratch, engine-native implementation of the protocol
SHAPE (actions, snapshots, time travel, atomic commits), not a port of
Delta Lake's — data files are the engine's own parquet writes."""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import types as T

_LOG_DIR = "_delta_log"


class ConcurrentModificationException(Exception):
    """Another writer committed this version first (OCC conflict)."""


def _schema_to_spec(schema: T.StructType):
    from ..shuffle.serializer import _spec_of
    return [[f.name, _spec_of(f.data_type)] for f in schema.fields]


def _spec_to_schema(spec) -> T.StructType:
    from ..shuffle.serializer import _spec_to_type
    return T.StructType(tuple(
        T.StructField(name, _spec_to_type(s), True) for name, s in spec))


@dataclass
class AddFile:
    path: str               # relative to the table root
    size: int
    num_records: int
    data_change: bool = True
    modification_time: int = 0


@dataclass
class Snapshot:
    version: int
    schema: Optional[T.StructType]
    partition_columns: Tuple[str, ...]
    files: Dict[str, AddFile]      # path -> AddFile (live set)

    @property
    def file_paths(self) -> List[str]:
        return sorted(self.files)


class DeltaLog:
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_path = os.path.join(table_path, _LOG_DIR)

    # --- log primitives ----------------------------------------------------
    def _version_file(self, v: int) -> str:
        return os.path.join(self.log_path, f"{v:020d}.json")

    def versions(self) -> List[int]:
        if not os.path.isdir(self.log_path):
            return []
        out = []
        for name in os.listdir(self.log_path):
            if name.endswith(".json"):
                try:
                    out.append(int(name[:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_version(self) -> int:
        vs = self.versions()
        return vs[-1] if vs else -1

    def exists(self) -> bool:
        return self.latest_version() >= 0

    def read_actions(self, version: int) -> List[dict]:
        with open(self._version_file(version)) as fh:
            return [json.loads(line) for line in fh if line.strip()]

    # --- snapshot ----------------------------------------------------------
    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        vs = self.versions()
        if not vs:
            raise FileNotFoundError(
                f"not a delta table (no {_LOG_DIR}): {self.table_path}")
        if version is None:
            version = vs[-1]
        elif version not in vs:
            raise ValueError(f"version {version} not in log (have {vs})")
        schema = None
        part_cols: Tuple[str, ...] = ()
        files: Dict[str, AddFile] = {}
        for v in vs:
            if v > version:
                break
            for action in self.read_actions(v):
                if "metaData" in action:
                    md = action["metaData"]
                    schema = _spec_to_schema(md["schema"])
                    part_cols = tuple(md.get("partitionColumns", ()))
                elif "add" in action:
                    a = action["add"]
                    files[a["path"]] = AddFile(
                        a["path"], a.get("size", 0),
                        a.get("numRecords", -1),
                        a.get("dataChange", True),
                        a.get("modificationTime", 0))
                elif "remove" in action:
                    files.pop(action["remove"]["path"], None)
        return Snapshot(version, schema, part_cols, files)

    # --- commit ------------------------------------------------------------
    def commit(self, actions: List[dict], operation: str,
               read_version: Optional[int] = None,
               max_retries: int = 10) -> int:
        """Atomically append the next log version (exclusive-create).  A
        losing race raises ConcurrentModificationException unless the
        caller's read snapshot is still valid (blind appends always win,
        like the reference's OptimisticTransaction conflict checking)."""
        os.makedirs(self.log_path, exist_ok=True)
        info = {"commitInfo": {
            "timestamp": int(time.time() * 1000),
            "operation": operation,
            "txnId": uuid.uuid4().hex,
        }}
        payload = "\n".join(json.dumps(a) for a in [info] + actions) + "\n"
        blind_append = all("remove" not in a for a in actions)
        for _ in range(max_retries):
            latest = self.latest_version()
            # a non-append commit whose read snapshot is stale must fail
            # even when it would win a FRESH version number — otherwise a
            # DELETE racing another DELETE silently resurrects rows
            if read_version is not None and not blind_append \
                    and latest > read_version:
                raise ConcurrentModificationException(
                    f"table advanced to v{latest} past read version "
                    f"{read_version} during a non-append commit")
            v = latest + 1
            try:
                with open(self._version_file(v), "x") as fh:
                    fh.write(payload)
                return v
            except FileExistsError:
                continue  # someone else won this version; re-validate
        raise ConcurrentModificationException(
            f"could not commit after {max_retries} attempts")

    # --- history -----------------------------------------------------------
    def history(self) -> List[dict]:
        out = []
        for v in reversed(self.versions()):
            for action in self.read_actions(v):
                if "commitInfo" in action:
                    ci = dict(action["commitInfo"])
                    ci["version"] = v
                    out.append(ci)
                    break
        return out


def metadata_action(schema: T.StructType,
                    partition_columns=()) -> dict:
    return {"metaData": {
        "id": uuid.uuid4().hex,
        "schema": _schema_to_spec(schema),
        "partitionColumns": list(partition_columns),
        "createdTime": int(time.time() * 1000),
    }}


def add_action(path: str, size: int, num_records: int,
               data_change: bool = True) -> dict:
    return {"add": {"path": path, "size": size, "numRecords": num_records,
                    "dataChange": data_change,
                    "modificationTime": int(time.time() * 1000)}}


def remove_action(path: str, data_change: bool = True) -> dict:
    return {"remove": {"path": path, "dataChange": data_change,
                       "deletionTimestamp": int(time.time() * 1000)}}
