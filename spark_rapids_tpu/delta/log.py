"""Delta-style transaction log — the analog of the reference's delta-lake
module core (``GpuOptimisticTransaction``; SURVEY §2.9/L7): an ordered
``_delta_log/{version:020d}.json`` of ndjson actions (metaData / add /
remove / commitInfo) whose replay yields the table snapshot, with
optimistic concurrency via exclusive-create commits.

This is a from-scratch, engine-native implementation of the protocol
SHAPE (actions, snapshots, time travel, atomic commits), not a port of
Delta Lake's — data files are the engine's own parquet writes."""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import types as T

_LOG_DIR = "_delta_log"


class ConcurrentModificationException(Exception):
    """Another writer committed this version first (OCC conflict)."""


def _schema_to_spec(schema: T.StructType):
    from ..shuffle.serializer import _spec_of
    return [[f.name, _spec_of(f.data_type)] for f in schema.fields]


def _spec_to_schema(spec) -> T.StructType:
    from ..shuffle.serializer import _spec_to_type
    return T.StructType(tuple(
        T.StructField(name, _spec_to_type(s), True) for name, s in spec))


@dataclass
class AddFile:
    path: str               # relative to the table root
    size: int
    num_records: int
    data_change: bool = True
    modification_time: int = 0
    #: per-file column statistics for data skipping (real Delta's `stats`
    #: JSON: numRecords / minValues / maxValues / nullCount)
    stats: Optional[dict] = None
    #: real Delta stores partition column VALUES per file (string-encoded)
    #: rather than writing the columns into the data files; readers
    #: re-inject them (`add.partitionValues` in the protocol spec)
    partition_values: Optional[Dict[str, Optional[str]]] = None


#: Spark-JSON-schema primitive names -> engine types (real Delta metaData
#: carries `schemaString`, a JSON-serialized Spark StructType)
_SPARK_PRIMITIVES = {
    "long": T.LONG, "integer": T.INT, "short": T.SHORT, "byte": T.BYTE,
    "double": T.DOUBLE, "float": T.FLOAT, "string": T.STRING,
    "boolean": T.BOOLEAN, "binary": T.BINARY, "date": T.DATE,
    "timestamp": T.TIMESTAMP,
}


def _spark_json_type(t):
    if isinstance(t, str):
        if t in _SPARK_PRIMITIVES:
            return _SPARK_PRIMITIVES[t]
        if t.startswith("decimal("):
            p, s = t[8:-1].split(",")
            return T.DecimalType(int(p), int(s))
        raise ValueError(f"unsupported Spark schema type {t!r}")
    kind = t.get("type")
    if kind == "struct":
        return T.StructType(tuple(
            T.StructField(f["name"], _spark_json_type(f["type"]),
                          bool(f.get("nullable", True)))
            for f in t["fields"]))
    if kind == "array":
        return T.ArrayType(_spark_json_type(t["elementType"]))
    if kind == "map":
        return T.MapType(_spark_json_type(t["keyType"]),
                         _spark_json_type(t["valueType"]))
    raise ValueError(f"unsupported Spark schema type {t!r}")


def schema_from_spark_json(schema_string: str) -> T.StructType:
    """Parse real Delta's ``schemaString`` (JSON-serialized Spark
    StructType) into the engine's schema model — the interop entry point
    for tables written by Spark/delta-rs (Delta protocol spec §Change
    Metadata; reference delta-lake/ readers consume the same shape)."""
    return _spark_json_type(json.loads(schema_string))


@dataclass
class Snapshot:
    version: int
    schema: Optional[T.StructType]
    partition_columns: Tuple[str, ...]
    files: Dict[str, AddFile]      # path -> AddFile (live set)
    #: table properties (real Delta's metaData.configuration — carries
    #: constraints as `delta.constraints.<name>` entries)
    configuration: Dict[str, str] = field(default_factory=dict)

    @property
    def file_paths(self) -> List[str]:
        return sorted(self.files)


class DeltaLog:
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_path = os.path.join(table_path, _LOG_DIR)

    # --- log primitives ----------------------------------------------------
    def _version_file(self, v: int) -> str:
        return os.path.join(self.log_path, f"{v:020d}.json")

    def versions(self) -> List[int]:
        if not os.path.isdir(self.log_path):
            return []
        out = []
        for name in os.listdir(self.log_path):
            if name.endswith(".json"):
                try:
                    out.append(int(name[:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_version(self) -> int:
        vs = self.versions()
        return vs[-1] if vs else -1

    def exists(self) -> bool:
        return self.latest_version() >= 0

    def read_actions(self, version: int) -> List[dict]:
        with open(self._version_file(version)) as fh:
            return [json.loads(line) for line in fh if line.strip()]

    # --- checkpoints --------------------------------------------------------
    #: write a parquet checkpoint every N commits (real Delta default 10)
    checkpoint_interval = 10

    def _checkpoint_file(self, v: int) -> str:
        return os.path.join(self.log_path, f"{v:020d}.checkpoint.parquet")

    def _last_checkpoint_path(self) -> str:
        return os.path.join(self.log_path, "_last_checkpoint")

    def last_checkpoint_version(self) -> Optional[int]:
        try:
            with open(self._last_checkpoint_path()) as fh:
                return int(json.load(fh)["version"])
        except (OSError, ValueError, KeyError):
            return None

    def write_checkpoint(self, version: Optional[int] = None) -> int:
        """Materialize the snapshot's reconstructing actions at `version`
        as one parquet file + the `_last_checkpoint` pointer, so replay
        reads O(interval) json files instead of the whole log (real
        Delta's `{v}.checkpoint.parquet` protocol shape)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        snap = self.snapshot(version)
        actions: List[dict] = []
        if snap.schema is not None:
            actions.append(metadata_action(
                snap.schema, snap.partition_columns, snap.configuration))
        for f in snap.files.values():
            a = add_action(f.path, f.size, f.num_records, f.data_change,
                           stats=f.stats)
            a["add"]["modificationTime"] = f.modification_time
            if f.partition_values is not None:
                a["add"]["partitionValues"] = f.partition_values
            actions.append(a)
        tbl = pa.table({"action": pa.array([json.dumps(a) for a in actions],
                                           type=pa.string())})
        pq.write_table(tbl, self._checkpoint_file(snap.version))
        tmp = self._last_checkpoint_path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"version": snap.version, "size": len(actions)}, fh)
        os.replace(tmp, self._last_checkpoint_path())
        return snap.version

    def _read_checkpoint(self, v: int) -> Optional[List[dict]]:
        """None -> caller replays the JSON log from version 0 instead.
        Spark-written checkpoints use a columnar layout (one column per
        action type) this engine does not parse; they are detected and
        skipped, which is correct as long as the JSON log has not been
        cleaned up past the checkpoint."""
        import pyarrow.parquet as pq
        try:
            tbl = pq.read_table(self._checkpoint_file(v))
        except OSError:
            return None
        if "action" not in tbl.column_names:  # foreign checkpoint layout
            return None
        return [json.loads(s) for s in tbl.column("action").to_pylist()]

    # --- snapshot ----------------------------------------------------------
    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        vs = self.versions()
        if not vs:
            raise FileNotFoundError(
                f"not a delta table (no {_LOG_DIR}): {self.table_path}")
        if version is None:
            version = vs[-1]
        elif version not in vs:
            raise ValueError(f"version {version} not in log (have {vs})")
        schema = None
        part_cols: Tuple[str, ...] = ()
        files: Dict[str, AddFile] = {}
        configuration: Dict[str, str] = {}
        start = 0
        ckpt = self.last_checkpoint_version()
        base_actions: List[dict] = []
        if ckpt is not None and ckpt <= version:
            loaded = self._read_checkpoint(ckpt)
            if loaded is not None:
                base_actions = loaded
                start = ckpt + 1

        def apply(action: dict):
            nonlocal schema, part_cols, configuration
            if "metaData" in action:
                md = action["metaData"]
                if "schema" in md:          # engine-native spec form
                    schema = _spec_to_schema(md["schema"])
                else:                       # real Delta: schemaString
                    schema = schema_from_spark_json(md["schemaString"])
                part_cols = tuple(md.get("partitionColumns", ()))
                configuration = dict(md.get("configuration", {}))
            elif "protocol" in action:
                # real Delta tables declare reader requirements; features
                # past the base protocol (deletion vectors, column
                # mapping) need reader support this engine doesn't have
                mrv = int(action["protocol"].get("minReaderVersion", 1))
                if mrv > 1:
                    raise ValueError(
                        f"unsupported Delta protocol: minReaderVersion="
                        f"{mrv} (this reader implements version 1)")
            elif "add" in action:
                a = action["add"]
                stats = a.get("stats")
                if isinstance(stats, str):
                    try:
                        stats = json.loads(stats)
                    except ValueError:
                        stats = None
                num = a.get("numRecords")   # engine-native extension
                if num is None:
                    num = (stats or {}).get("numRecords", -1)
                files[a["path"]] = AddFile(
                    a["path"], a.get("size", 0), num,
                    a.get("dataChange", True),
                    a.get("modificationTime", 0),
                    stats, a.get("partitionValues") or None)
            elif "remove" in action:
                files.pop(action["remove"]["path"], None)

        for action in base_actions:
            apply(action)
        for v in vs:
            if v < start:
                continue
            if v > version:
                break
            for action in self.read_actions(v):
                apply(action)
        return Snapshot(version, schema, part_cols, files, configuration)

    # --- commit ------------------------------------------------------------
    def commit(self, actions: List[dict], operation: str,
               read_version: Optional[int] = None,
               max_retries: int = 10) -> int:
        """Atomically append the next log version (exclusive-create).  A
        losing race raises ConcurrentModificationException unless the
        caller's read snapshot is still valid (blind appends always win,
        like the reference's OptimisticTransaction conflict checking)."""
        os.makedirs(self.log_path, exist_ok=True)
        info = {"commitInfo": {
            "timestamp": int(time.time() * 1000),
            "operation": operation,
            "txnId": uuid.uuid4().hex,
        }}
        payload = "\n".join(json.dumps(a) for a in [info] + actions) + "\n"
        blind_append = all("remove" not in a for a in actions)
        for _ in range(max_retries):
            latest = self.latest_version()
            # a non-append commit whose read snapshot is stale must fail
            # even when it would win a FRESH version number — otherwise a
            # DELETE racing another DELETE silently resurrects rows
            if read_version is not None and not blind_append \
                    and latest > read_version:
                raise ConcurrentModificationException(
                    f"table advanced to v{latest} past read version "
                    f"{read_version} during a non-append commit")
            v = latest + 1
            try:
                with open(self._version_file(v), "x") as fh:
                    fh.write(payload)
                if self.checkpoint_interval and v > 0 \
                        and v % self.checkpoint_interval == 0:
                    try:
                        self.write_checkpoint(v)
                    except Exception:
                        pass  # checkpoints are an optimization, never fatal
                return v
            except FileExistsError:
                continue  # someone else won this version; re-validate
        raise ConcurrentModificationException(
            f"could not commit after {max_retries} attempts")

    def _commit_timestamp(self, v: int):
        """First commitInfo timestamp of a version, scanning line by line
        (no need to parse every add/remove of a large commit); None when
        the commit carries no commitInfo (optional in the protocol)."""
        with open(self._version_file(v)) as fh:
            for line in fh:
                if not line.strip():
                    continue
                action = json.loads(line)
                if "commitInfo" in action:
                    return action["commitInfo"].get("timestamp")
        return None

    def version_as_of_timestamp(self, ts_ms: int) -> int:
        """Latest version whose commit timestamp is <= ts_ms (Spark's
        ``timestampAsOf``).  Commit timestamps are ADJUSTED to be
        monotonically non-decreasing first — the protocol does not
        guarantee ordering across writers/clock skew, and Delta applies
        the same adjustment before searching.  Raises like Delta when
        the timestamp precedes the table's first (adjusted) commit."""
        best = None
        prev = 0
        for v in self.versions():
            t = self._commit_timestamp(v)
            if t is None:
                # commitInfo is optional per the protocol; fall back to
                # the commit file's modification time (Delta's
                # DeltaHistoryManager does the same) rather than treating
                # the commit as timestamp 0 — which would resolve ANY
                # timestampAsOf to the latest version of a foreign table
                # written without commitInfo, silently reading data
                # committed after the requested time (advisor r3).
                t = int(os.path.getmtime(self._version_file(v)) * 1000)
            t = max(int(t), prev)
            prev = t
            if t <= ts_ms:
                best = v
        if best is None:
            raise ValueError(
                f"timestamp {ts_ms} is before the earliest commit of "
                f"{self.table_path}")
        return best

    # --- history -----------------------------------------------------------
    def history(self) -> List[dict]:
        out = []
        for v in reversed(self.versions()):
            for action in self.read_actions(v):
                if "commitInfo" in action:
                    ci = dict(action["commitInfo"])
                    ci["version"] = v
                    out.append(ci)
                    break
        return out


def metadata_action(schema: T.StructType, partition_columns=(),
                    configuration: Optional[Dict[str, str]] = None) -> dict:
    return {"metaData": {
        "id": uuid.uuid4().hex,
        "schema": _schema_to_spec(schema),
        "partitionColumns": list(partition_columns),
        "configuration": dict(configuration or {}),
        "createdTime": int(time.time() * 1000),
    }}


def add_action(path: str, size: int, num_records: int,
               data_change: bool = True,
               stats: Optional[dict] = None) -> dict:
    a = {"path": path, "size": size, "numRecords": num_records,
         "dataChange": data_change,
         "modificationTime": int(time.time() * 1000)}
    if stats is not None:
        a["stats"] = json.dumps(stats)
    return {"add": a}


def remove_action(path: str, data_change: bool = True) -> dict:
    return {"remove": {"path": path, "dataChange": data_change,
                       "deletionTimestamp": int(time.time() * 1000)}}
