"""Z-order (Morton) clustering — the analog of the reference's
``org/apache/spark/sql/rapids/zorder/`` + ``jni.ZOrder`` interleave-bits
kernels: rank each clustering column, interleave the rank bits, sort by
the resulting z-value so files cover compact hyper-rectangles of the key
space (data-skipping locality)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import pyarrow as pa

_BITS = 21  # bits per dimension (up to 3 dims fit a uint64 z-value)


def _column_ranks(col: pa.ChunkedArray) -> np.ndarray:
    """Dense rank of each value (nulls first) scaled into [0, 2^_BITS)."""
    vals = col.to_pandas()
    import pandas as pd
    r = pd.Series(vals).rank(method="dense", na_option="top").to_numpy()
    r = np.nan_to_num(r, nan=1.0) - 1.0
    hi = max(r.max(), 1.0)
    return np.minimum((r / hi * ((1 << _BITS) - 1)).astype(np.uint64),
                      (1 << _BITS) - 1)


def _interleave(ranks: List[np.ndarray]) -> np.ndarray:
    """Bit-interleave up to 3 dimensions into one uint64 z-value."""
    d = len(ranks)
    n = len(ranks[0])
    z = np.zeros(n, dtype=np.uint64)
    for bit in range(_BITS):
        for dim, r in enumerate(ranks):
            z |= (((r >> np.uint64(bit)) & np.uint64(1))
                  << np.uint64(bit * d + dim))
    return z


def zorder_indices(table: pa.Table, cols: Sequence[str]) -> np.ndarray:
    """Row order that clusters the table along the z-curve of ``cols``."""
    cols = list(cols)[:3]
    ranks = [_column_ranks(table[c]) for c in cols]
    z = _interleave(ranks)
    return np.argsort(z, kind="stable")
