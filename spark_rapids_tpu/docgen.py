"""Documentation generator — the analog of the reference's docgen stack:
``RapidsConf.help()`` -> docs/configs.md (``RapidsConf.scala:2057-2103``),
``SupportedOpsDocs`` -> docs/supported_ops.md and ``SupportedOpsForTools``
-> tools/generated_files/*.csv (``TypeChecks.scala:1777,2231``).

Run:  python -m spark_rapids_tpu.docgen [repo_root]
"""

from __future__ import annotations

import os
import sys
from typing import List

from .config import ENTRIES, help_text


def _exec_rows() -> List[tuple]:
    """(exec name, description) for every planned physical operator."""
    return [
        ("InMemoryScanExec", "scan of in-memory relations (host decode, "
         "cached device upload)"),
        ("FileScanExec", "parquet/orc/csv/json/avro file scans, "
         "PERFILE|MULTITHREADED|COALESCING reader strategies"),
        ("RangeExec", "range generation"),
        ("ProjectExec", "projection (fusable into whole-stage programs)"),
        ("FilterExec", "filter (fusable into whole-stage programs)"),
        ("FusedStageExec", "whole-stage program: filter/project chain + "
         "optional hash-aggregate terminal compiled as ONE donated-buffer "
         "XLA program (docs/whole_stage.md); "
         "spark.rapids.tpu.sql.wholeStage.enabled"),
        ("SampleExec", "random sampling"),
        ("ExpandExec", "grouping-sets expansion"),
        ("UnionExec", "union all"),
        ("HashAggregateExec", "partial/final/complete hash aggregation with "
         "spillable out-of-core merge"),
        ("SortExec", "in-core + out-of-core sort (spillable run merge)"),
        ("TakeOrderedAndProjectExec", "ORDER BY + LIMIT TopN"),
        ("LocalLimitExec", "per-partition limit"),
        ("GlobalLimitExec", "global limit + offset"),
        ("CoalescePartitionsExec", "partition coalescing"),
        ("WindowExec", "window functions, ROWS+RANGE frames"),
        ("GenerateExec", "explode/posexplode"),
        ("ShuffleExchangeExec", "hash/range/round-robin/single exchanges; "
         "local serializer plane, ICI mesh all_to_all plane, AQE "
         "partition coalescing"),
        ("BroadcastExchangeExec", "broadcast build sides"),
        ("ShuffledHashJoinExec", "co-partitioned hash join, chunked gather"),
        ("BroadcastHashJoinExec", "broadcast hash join"),
        ("NestedLoopJoinExec", "cartesian/conditional joins"),
        ("AdaptiveJoinExec", "AQE runtime broadcast-vs-shuffle re-decision"),
        ("MapInPandasExec", "mapInPandas (Arrow-fed Python)"),
        ("FlatMapGroupsInPandasExec", "applyInPandas per key group"),
        ("HostToDeviceExec / DeviceToHostExec", "backend transitions "
         "(double-buffered when spark.rapids.tpu.transfer.doubleBuffer."
         "enabled)"),
        ("AsyncPrefetchExec", "bounded background prefetch queue at "
         "pipeline seams (scans, uploads, exchange reduce sides); "
         "spark.rapids.tpu.prefetch.enabled"),
        ("CoalesceBatchesExec", "batch-size normalization"),
    ]


def supported_ops_md() -> str:
    from .sql.expressions.registry import EXPRESSION_REGISTRY
    lines = ["# Supported operators and expressions", "",
             "## Execs", "",
             "Exec | Description", "-----|------------"]
    for name, desc in _exec_rows():
        lines.append(f"{name} | {desc}")
    from .sql import typesig as TS
    from .sql.overrides import EXPR_SIGS
    cats = [c for c, _ in TS.MATRIX_CATEGORIES]
    lines += ["", "## Expressions", "",
              f"{len(EXPRESSION_REGISTRY)} expression classes are "
              "registered for device execution (anything else runs on the "
              "host engine per-operator).  Per-expression INPUT/OUTPUT "
              "type matrices below are the live tagging data "
              "(sql/overrides.py EXPR_SIGS; TypeChecks.scala analog) — "
              "S = supported on device, NS = falls back to the host "
              "engine for that type.", "",
              "Expression | Side | " + " | ".join(cats),
              "-----------|------|" + "|".join("---" for _ in cats)]
    for name in sorted(EXPRESSION_REGISTRY):
        es = EXPR_SIGS.get(name, TS.DEFAULT_EXPR_SIG)
        lines.append(f"{name} | input | "
                     + " | ".join(TS.matrix_row(es.input)))
        lines.append(f"{name} | result | "
                     + " | ".join(TS.matrix_row(es.output)))
    return "\n".join(lines) + "\n"


def supported_exprs_csv() -> str:
    from .sql import typesig as TS
    from .sql.expressions.registry import EXPRESSION_REGISTRY
    from .sql.overrides import EXPR_SIGS
    cats = [c for c, _ in TS.MATRIX_CATEGORIES]
    rows = ["Expression,Side," + ",".join(cats)]
    for name in sorted(EXPRESSION_REGISTRY):
        es = EXPR_SIGS.get(name, TS.DEFAULT_EXPR_SIG)
        rows.append(f"{name},input," + ",".join(TS.matrix_row(es.input)))
        rows.append(f"{name},result," + ",".join(TS.matrix_row(es.output)))
    return "\n".join(rows) + "\n"


def operators_score_csv() -> str:
    """Per-op speedup scores for qualification tooling (the
    operatorsScore.csv analog; scores mirror the reference defaults)."""
    rows = ["CPUOperator,Score"]
    for name, _ in _exec_rows():
        # combined rows ("A / B") expand to one CSV row per exec
        for part in name.split(" / "):
            rows.append(f"{part.strip()},3.0")
    return "\n".join(rows) + "\n"


def per_rule_flags_md() -> str:
    """One enable flag per registered expression/exec rule — the analog of
    the reference auto-generating a ``spark.rapids.sql.expression.*`` /
    ``.exec.*`` conf per GpuOverrides rule; all honored by the tagging
    layer (overrides.py) via ``RapidsConf.get_bool``."""
    from .sql.expressions.registry import EXPRESSION_REGISTRY
    from .sql.overrides import _EXEC_ENABLE_KEYS
    lines = ["", "## Per-rule enable flags", "",
             "Each registered rule has a boolean enable flag (default "
             "true); setting it false forces that op to the host engine.",
             "", "Name | Default", "-----|--------"]
    from .sql.overrides import UNFLAGGED_EXPRS as unflagged
    for key in sorted(set(_EXEC_ENABLE_KEYS.values())):
        lines.append(f"{key} | true")
    for name in sorted(EXPRESSION_REGISTRY):
        if name in unflagged:
            continue
        lines.append(f"spark.rapids.sql.expression.{name} | true")
    return "\n".join(lines) + "\n"


def generate(root: str) -> List[str]:
    docs = os.path.join(root, "docs")
    tools = os.path.join(root, "tools", "generated_files")
    os.makedirs(docs, exist_ok=True)
    os.makedirs(tools, exist_ok=True)
    written = []
    for path, content in [
        (os.path.join(docs, "configs.md"),
         help_text() + per_rule_flags_md()),
        (os.path.join(docs, "advanced_configs.md"),
         help_text(include_internal=True) + per_rule_flags_md()),
        (os.path.join(docs, "supported_ops.md"), supported_ops_md()),
        (os.path.join(tools, "supportedExprs.csv"), supported_exprs_csv()),
        (os.path.join(tools, "operatorsScore.csv"), operators_score_csv()),
    ]:
        with open(path, "w") as fh:
            fh.write(content)
        written.append(path)
    return written


def main() -> None:
    root = sys.argv[1] if len(sys.argv) > 1 else os.getcwd()
    for p in generate(root):
        print("wrote", p)


if __name__ == "__main__":
    main()
