"""Iceberg-analog table format (reference: the GPU Iceberg read path under
``sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/``, ~6k LoC).

Public surface:

* :class:`IcebergTable` — create / append / scan / schema evolution /
  position deletes / time travel / expire_snapshots
* ``session.read.format("iceberg").load(path)`` integration (session.py)
* transforms (identity, bucket, truncate, year/month/day/hour, void) with
  pruning predicates
"""

from .metadata import (ConcurrentCommitException, IceSchema, IceSnapshot,
                       PartitionSpec, TableMetadata)
from .table import IcebergTable
from .transforms import parse_transform

__all__ = ["IcebergTable", "IceSchema", "IceSnapshot", "PartitionSpec",
           "TableMetadata", "ConcurrentCommitException", "parse_transform"]
