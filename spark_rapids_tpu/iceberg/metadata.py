"""Iceberg-analog table metadata model.

The reference ships a GPU Iceberg *read* path as a Java port of Iceberg's
reader internals (``sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/``,
~6k LoC: Spark scan glue, schema/field-id pruning, partition-spec handling,
metrics).  This module is the TPU build's equivalent metadata layer, written
to the Iceberg v2 spec *shape* — JSON table metadata with schema-ids and
field-ids, avro manifest lists and manifests (via the repo's own avro
container codec, ``io_/avro_reader.py``), snapshot log with time travel —
so the scan layer (``table.py``) can do the same planning work the
reference's ``GpuSparkBatchQueryScan`` does: snapshot selection, partition
pruning through transforms, column-bound file skipping, field-id column
projection, and position-delete application.

Layout on disk (per Iceberg conventions):

    <table>/metadata/v<N>.metadata.json     table metadata, versioned
    <table>/metadata/snap-<id>.avro         manifest list, one per snapshot
    <table>/metadata/manifest-<uuid>.avro   manifest: data/delete file entries
    <table>/data/**.parquet                 data + position-delete files
"""

from __future__ import annotations

import json
import os
import re
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from .. import types as T

FORMAT_VERSION = 2

#: manifest entry / data file content codes (Iceberg spec)
DATA = 0
POSITION_DELETES = 1
EQUALITY_DELETES = 2

STATUS_EXISTING = 0
STATUS_ADDED = 1
STATUS_DELETED = 2


# ---------------------------------------------------------------------------
# schema with field ids
# ---------------------------------------------------------------------------

@dataclass
class NestedField:
    field_id: int
    name: str
    type_str: str          # primitive type name, e.g. "long", "string"
    required: bool = False

    def to_json(self) -> dict:
        return {"id": self.field_id, "name": self.name,
                "required": self.required, "type": self.type_str}

    @staticmethod
    def from_json(d: dict) -> "NestedField":
        return NestedField(d["id"], d["name"], d["type"],
                           d.get("required", False))


_TYPE_TO_ICE = {
    T.BOOLEAN: "boolean", T.INT: "int", T.LONG: "long", T.FLOAT: "float",
    T.DOUBLE: "double", T.STRING: "string", T.DATE: "date",
    T.TIMESTAMP: "timestamptz", T.BINARY: "binary",
}
_ICE_TO_TYPE = {v: k for k, v in _TYPE_TO_ICE.items()}
_ICE_TO_TYPE["timestamp"] = T.TIMESTAMP


def type_to_ice(dt) -> str:
    if dt in _TYPE_TO_ICE:
        return _TYPE_TO_ICE[dt]
    s = str(dt).lower()
    if s.startswith("decimal"):
        return s
    raise ValueError(f"unsupported iceberg type: {dt}")


def ice_to_type(s: str):
    if s in _ICE_TO_TYPE:
        return _ICE_TO_TYPE[s]
    if s.startswith("decimal"):
        import re
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", s)
        if m:
            return T.DecimalType(int(m.group(1)), int(m.group(2)))
    raise ValueError(f"unsupported iceberg type string: {s}")


@dataclass
class IceSchema:
    schema_id: int
    fields: List[NestedField] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"type": "struct", "schema-id": self.schema_id,
                "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d: dict) -> "IceSchema":
        return IceSchema(d.get("schema-id", 0),
                         [NestedField.from_json(f) for f in d["fields"]])

    def field_by_name(self, name: str) -> Optional[NestedField]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def field_by_id(self, fid: int) -> Optional[NestedField]:
        for f in self.fields:
            if f.field_id == fid:
                return f
        return None

    def to_struct_type(self) -> T.StructType:
        return T.StructType([
            T.StructField(f.name, ice_to_type(f.type_str), not f.required)
            for f in self.fields])

    def max_field_id(self) -> int:
        return max((f.field_id for f in self.fields), default=0)


# ---------------------------------------------------------------------------
# partition spec
# ---------------------------------------------------------------------------

@dataclass
class PartitionField:
    source_id: int        # field id in the table schema
    field_id: int         # partition field id (>= 1000)
    transform: str        # identity | bucket[N] | truncate[W] | year | ...
    name: str

    def to_json(self) -> dict:
        return {"source-id": self.source_id, "field-id": self.field_id,
                "transform": self.transform, "name": self.name}

    @staticmethod
    def from_json(d: dict) -> "PartitionField":
        return PartitionField(d["source-id"], d["field-id"], d["transform"],
                              d["name"])


@dataclass
class PartitionSpec:
    spec_id: int
    fields: List[PartitionField] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"spec-id": self.spec_id,
                "fields": [f.to_json() for f in self.fields]}

    @staticmethod
    def from_json(d: dict) -> "PartitionSpec":
        return PartitionSpec(d.get("spec-id", 0),
                             [PartitionField.from_json(f)
                              for f in d["fields"]])

    @property
    def is_unpartitioned(self) -> bool:
        return not self.fields


# ---------------------------------------------------------------------------
# snapshots
# ---------------------------------------------------------------------------

@dataclass
class IceSnapshot:
    snapshot_id: int
    timestamp_ms: int
    manifest_list: str             # path relative to table root
    parent_id: Optional[int] = None
    schema_id: int = 0
    summary: Dict[str, str] = field(default_factory=dict)
    #: v2 data sequence number (ordering for row-level delete scoping)
    sequence_number: int = 0

    def to_json(self) -> dict:
        d = {"snapshot-id": self.snapshot_id,
             "timestamp-ms": self.timestamp_ms,
             "manifest-list": self.manifest_list,
             "schema-id": self.schema_id,
             "sequence-number": self.sequence_number,
             "summary": self.summary}
        if self.parent_id is not None:
            d["parent-snapshot-id"] = self.parent_id
        return d

    @staticmethod
    def from_json(d: dict) -> "IceSnapshot":
        return IceSnapshot(d["snapshot-id"], d["timestamp-ms"],
                           d["manifest-list"],
                           d.get("parent-snapshot-id"),
                           d.get("schema-id", 0), d.get("summary", {}),
                           d.get("sequence-number", 0))


# ---------------------------------------------------------------------------
# manifests (avro)
# ---------------------------------------------------------------------------

@dataclass
class DataFile:
    """One data (or position-delete) file tracked by a manifest."""
    file_path: str                           # relative to table root
    content: int = DATA
    record_count: int = 0
    file_size: int = 0
    spec_id: int = 0
    # partition tuple: transform-result value per spec field (JSON-encoded
    # in the avro row; None for unpartitioned)
    partition: Tuple = ()
    # per-field-id min/max for file skipping (numeric/str only)
    lower_bounds: Dict[int, Any] = field(default_factory=dict)
    upper_bounds: Dict[int, Any] = field(default_factory=dict)
    null_counts: Dict[int, int] = field(default_factory=dict)
    #: v2 row-level deletes: the field ids an EQUALITY_DELETES file
    #: matches on (GpuDeleteFilter.java:94 equalityFieldIds), and the
    #: data sequence number ordering which deletes apply to which data
    #: (a delete applies to STRICTLY OLDER sequence numbers; 0 = unknown
    #: / oldest, so later deletes still apply)
    equality_ids: Tuple[int, ...] = ()
    sequence_number: int = 0


@dataclass
class ManifestEntry:
    status: int
    snapshot_id: int
    data_file: DataFile


def _part_encode(v: Any):
    """JSON-safe encoding for partition values (identity/truncate output
    date, timestamp and binary values that json can't represent)."""
    import base64
    import datetime as _dt
    if isinstance(v, _dt.datetime):
        return {"__ts__": v.isoformat()}
    if isinstance(v, _dt.date):
        return {"__date__": v.isoformat()}
    if isinstance(v, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(v)).decode("ascii")}
    return v


def _part_decode(v: Any):
    import base64
    import datetime as _dt
    if isinstance(v, dict):
        if "__ts__" in v:
            return _dt.datetime.fromisoformat(v["__ts__"])
        if "__date__" in v:
            return _dt.date.fromisoformat(v["__date__"])
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
    return v


def _bounds_json(b: Dict[int, Any]) -> str:
    return json.dumps({str(k): v for k, v in b.items()})


def _bounds_unjson(s: str) -> Dict[int, Any]:
    return {int(k): v for k, v in json.loads(s or "{}").items()}


_MANIFEST_COLS = ["status", "snapshot_id", "content", "file_path",
                  "record_count", "file_size", "spec_id", "partition",
                  "lower_bounds", "upper_bounds", "null_counts",
                  "equality_ids", "sequence_number"]


def write_manifest(table_root: str, entries: Sequence[ManifestEntry]) -> str:
    """Write entries as one avro manifest; returns path relative to root."""
    from ..io_.avro_reader import write_avro
    rel = f"metadata/manifest-{uuid.uuid4().hex}.avro"
    rows = {
        "status": [e.status for e in entries],
        "snapshot_id": [e.snapshot_id for e in entries],
        "content": [e.data_file.content for e in entries],
        "file_path": [e.data_file.file_path for e in entries],
        "record_count": [e.data_file.record_count for e in entries],
        "file_size": [e.data_file.file_size for e in entries],
        "spec_id": [e.data_file.spec_id for e in entries],
        "partition": [json.dumps([_part_encode(v)
                                  for v in e.data_file.partition])
                      for e in entries],
        "lower_bounds": [_bounds_json(e.data_file.lower_bounds)
                         for e in entries],
        "upper_bounds": [_bounds_json(e.data_file.upper_bounds)
                         for e in entries],
        "null_counts": [_bounds_json(e.data_file.null_counts)
                        for e in entries],
        "equality_ids": [json.dumps(list(e.data_file.equality_ids))
                         for e in entries],
        "sequence_number": [e.data_file.sequence_number
                            for e in entries],
    }
    tab = pa.table({c: rows[c] for c in _MANIFEST_COLS})
    write_avro(tab, os.path.join(table_root, rel))
    return rel


def normalize_data_path(p: str, table_root: str) -> str:
    """Real Iceberg metadata stores full location URIs; the engine keys
    files by table-relative paths.  Strip the scheme, relativize under
    the root, and fall back to the conventional ``data/`` suffix for
    tables that moved since they were written."""
    if p.startswith("file:"):
        # both URI forms appear in the wild: file:///abs (RFC) and
        # file:/abs (Hadoop Path.toString())
        p = "/" + p[len("file:"):].lstrip("/")
    else:
        m = re.match(r"^[A-Za-z][A-Za-z0-9+.-]*://[^/]*(/.*)$", p)
        if m:
            # s3://bucket/..., hdfs://nn/..., gs://... — not absolute OS
            # paths, so without this they skipped the relativize/suffix
            # fallback entirely and came back verbatim, producing a bogus
            # os.path.join(table_root, uri) read later (advisor r3).
            # Strip scheme://authority and let the /data/ / /metadata/
            # suffix fallback key the file under the local table root.
            inner = m.group(1)
            i = inner.rfind("/data/")
            if i < 0:
                i = inner.rfind("/metadata/")
            if i >= 0:
                return inner[i + 1:]
            raise ValueError(
                f"unsupported Iceberg data file location {p!r}: remote "
                f"scheme with no data/ or metadata/ path segment to "
                f"relativize under table root {table_root!r}")
    root = os.path.abspath(table_root)
    if os.path.isabs(p):
        ap = os.path.abspath(p)
        if ap.startswith(root + os.sep):
            return os.path.relpath(ap, root)
        # moved table: fall back to the conventional directory suffix —
        # the LAST occurrence, since the original root may itself contain
        # a /data/ or /metadata/ segment
        i = p.rfind("/data/")
        if i >= 0:
            return p[i + 1:]
        i = p.rfind("/metadata/")
        if i >= 0:
            return p[i + 1:]
    return p


def _read_real_manifest(tab, table_root: str) -> List[ManifestEntry]:
    """Manifest in the REAL Iceberg v2 avro layout: nested
    ``manifest_entry{status, snapshot_id, data_file: r2{content,
    file_path, file_format, partition, record_count,
    file_size_in_bytes, ...}}`` records (Iceberg spec — Manifests;
    reference iceberg/SparkBatchQueryScan reads the same).  Binary
    single-value bounds are not decoded (no file skipping for foreign
    manifests — correct, just unpruned)."""
    out = []
    for i in range(tab.num_rows):
        status = tab["status"][i].as_py()
        sid = tab["snapshot_id"][i].as_py() if "snapshot_id" in \
            tab.column_names else None
        seq = tab["sequence_number"][i].as_py() \
            if "sequence_number" in tab.column_names else None
        d = tab["data_file"][i].as_py() or {}
        part = d.get("partition")
        if isinstance(part, dict):
            partition = tuple(part.values())
        else:
            partition = ()
        out.append(ManifestEntry(
            int(status or 0), int(sid or 0),
            DataFile(
                file_path=normalize_data_path(d["file_path"], table_root),
                content=int(d.get("content") or 0),
                record_count=int(d.get("record_count") or 0),
                file_size=int(d.get("file_size_in_bytes") or 0),
                spec_id=int(d.get("spec_id") or 0),
                partition=partition,
                equality_ids=tuple(d.get("equality_ids") or ()),
                sequence_number=int(seq or 0))))
    return out


def read_manifest(table_root: str, rel_path: str) -> List[ManifestEntry]:
    from ..io_.avro_reader import read_avro
    tab = read_avro(os.path.join(table_root,
                                 normalize_data_path(rel_path, table_root)))
    if "data_file" in tab.column_names:  # real Iceberg nested layout
        return _read_real_manifest(tab, table_root)
    out = []
    for i in range(tab.num_rows):
        row = {c: (tab[c][i].as_py() if c in tab.column_names else None)
               for c in _MANIFEST_COLS}
        df = DataFile(
            file_path=row["file_path"], content=int(row["content"]),
            record_count=int(row["record_count"]),
            file_size=int(row["file_size"]), spec_id=int(row["spec_id"]),
            partition=tuple(_part_decode(v)
                            for v in json.loads(row["partition"] or "[]")),
            lower_bounds=_bounds_unjson(row["lower_bounds"]),
            upper_bounds=_bounds_unjson(row["upper_bounds"]),
            null_counts={k: int(v) for k, v in
                         _bounds_unjson(row["null_counts"]).items()},
            equality_ids=tuple(json.loads(row["equality_ids"] or "[]")),
            sequence_number=int(row["sequence_number"] or 0))
        out.append(ManifestEntry(int(row["status"]),
                                 int(row["snapshot_id"]), df))
    return out


def write_manifest_list(table_root: str, snapshot_id: int,
                        manifest_rels: Sequence[str]) -> str:
    from ..io_.avro_reader import write_avro
    rel = f"metadata/snap-{snapshot_id}.avro"
    tab = pa.table({"manifest_path": list(manifest_rels)})
    write_avro(tab, os.path.join(table_root, rel))
    return rel


def read_manifest_list(table_root: str, rel_path: str) -> List[str]:
    """Works for both layouts: the engine's flat list and real Iceberg's
    ``manifest_file`` records — both carry a ``manifest_path`` field."""
    from ..io_.avro_reader import read_avro
    tab = read_avro(os.path.join(table_root,
                                 normalize_data_path(rel_path, table_root)))
    return [normalize_data_path(v.as_py(), table_root)
            for v in tab["manifest_path"]]


# ---------------------------------------------------------------------------
# table metadata
# ---------------------------------------------------------------------------

@dataclass
class TableMetadata:
    location: str
    table_uuid: str
    last_updated_ms: int = 0
    last_column_id: int = 0
    current_schema_id: int = 0
    schemas: List[IceSchema] = field(default_factory=list)
    default_spec_id: int = 0
    partition_specs: List[PartitionSpec] = field(default_factory=list)
    current_snapshot_id: Optional[int] = None
    last_sequence_number: int = 0
    snapshots: List[IceSnapshot] = field(default_factory=list)
    snapshot_log: List[dict] = field(default_factory=list)
    properties: Dict[str, str] = field(default_factory=dict)

    # --- accessors --------------------------------------------------------
    def schema(self, schema_id: Optional[int] = None) -> IceSchema:
        sid = self.current_schema_id if schema_id is None else schema_id
        for s in self.schemas:
            if s.schema_id == sid:
                return s
        raise KeyError(f"schema-id {sid} not found")

    def spec(self, spec_id: Optional[int] = None) -> PartitionSpec:
        sid = self.default_spec_id if spec_id is None else spec_id
        for s in self.partition_specs:
            if s.spec_id == sid:
                return s
        raise KeyError(f"spec-id {sid} not found")

    def snapshot(self, snapshot_id: Optional[int] = None
                 ) -> Optional[IceSnapshot]:
        sid = self.current_snapshot_id if snapshot_id is None else snapshot_id
        if sid is None:
            return None
        for s in self.snapshots:
            if s.snapshot_id == sid:
                return s
        raise KeyError(f"snapshot-id {sid} not found")

    def snapshot_as_of(self, ts_ms: int) -> Optional[IceSnapshot]:
        best = None
        for entry in self.snapshot_log:
            if entry["timestamp-ms"] <= ts_ms:
                best = entry["snapshot-id"]
        return self.snapshot(best) if best is not None else None

    # --- serialization ----------------------------------------------------
    def to_json(self) -> dict:
        return {
            "format-version": FORMAT_VERSION,
            "table-uuid": self.table_uuid,
            "location": self.location,
            "last-updated-ms": self.last_updated_ms,
            "last-column-id": self.last_column_id,
            "current-schema-id": self.current_schema_id,
            "schemas": [s.to_json() for s in self.schemas],
            "default-spec-id": self.default_spec_id,
            "partition-specs": [s.to_json() for s in self.partition_specs],
            "current-snapshot-id": self.current_snapshot_id,
            "last-sequence-number": self.last_sequence_number,
            "snapshots": [s.to_json() for s in self.snapshots],
            "snapshot-log": self.snapshot_log,
            "properties": self.properties,
        }

    @staticmethod
    def from_json(d: dict) -> "TableMetadata":
        return TableMetadata(
            location=d["location"], table_uuid=d["table-uuid"],
            last_updated_ms=d.get("last-updated-ms", 0),
            last_column_id=d.get("last-column-id", 0),
            current_schema_id=d.get("current-schema-id", 0),
            schemas=[IceSchema.from_json(s) for s in d.get("schemas", [])],
            default_spec_id=d.get("default-spec-id", 0),
            partition_specs=[PartitionSpec.from_json(s)
                             for s in d.get("partition-specs", [])],
            current_snapshot_id=d.get("current-snapshot-id"),
            last_sequence_number=d.get("last-sequence-number", 0),
            snapshots=[IceSnapshot.from_json(s)
                       for s in d.get("snapshots", [])],
            snapshot_log=d.get("snapshot-log", []),
            properties=d.get("properties", {}))


def metadata_dir(table_path: str) -> str:
    return os.path.join(table_path, "metadata")


def _version_of(fname: str) -> int:
    # v<N>.metadata.json
    return int(fname[1:].split(".", 1)[0])


def latest_metadata_version(table_path: str) -> Optional[int]:
    d = metadata_dir(table_path)
    if not os.path.isdir(d):
        return None
    versions = [_version_of(f) for f in os.listdir(d)
                if f.startswith("v") and f.endswith(".metadata.json")]
    return max(versions) if versions else None


def read_table_metadata(table_path: str,
                        version: Optional[int] = None) -> TableMetadata:
    v = latest_metadata_version(table_path) if version is None else version
    if v is None:
        raise FileNotFoundError(f"not an iceberg table: {table_path}")
    with open(os.path.join(metadata_dir(table_path),
                           f"v{v}.metadata.json")) as fh:
        meta = TableMetadata.from_json(json.load(fh))
    meta.loaded_version = v
    return meta


def write_table_metadata(table_path: str, meta: TableMetadata,
                         base_version: Optional[int] = None) -> int:
    """Exclusive-create commit of metadata version ``base_version + 1``
    (the Iceberg optimistic-concurrency primitive).  ``base_version`` is
    the version the writer's metadata was READ from (``loaded_version``;
    None for table creation) — committing against the read version, not
    the directory's current tip, makes a lost concurrent commit surface as
    :class:`ConcurrentCommitException` instead of silently dropping the
    other writer's snapshots."""
    if base_version is None:
        base_version = getattr(meta, "loaded_version", None)
    v = 0 if base_version is None else base_version + 1
    meta.last_updated_ms = int(time.time() * 1000)
    d = metadata_dir(table_path)
    os.makedirs(d, exist_ok=True)
    target = os.path.join(d, f"v{v}.metadata.json")
    try:
        # exclusive create IS the commit: any concurrent writer that read
        # the same base loses the create race, never a silent overwrite
        with open(target, "x") as fh:
            json.dump(meta.to_json(), fh, indent=1)
    except FileExistsError:
        raise ConcurrentCommitException(
            f"metadata version {v} already committed (read your base "
            f"v{base_version} stale; refresh and retry)") from None
    meta.loaded_version = v
    return v


class ConcurrentCommitException(Exception):
    pass
