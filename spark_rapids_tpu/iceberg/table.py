"""Iceberg-analog table: snapshot reads with partition/bound pruning,
field-id schema evolution, position deletes, and writer support so tests
(and users) can build tables without an external catalog.

Read-path parity targets (reference
``sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/``):

* ``GpuSparkBatchQueryScan``   -> :meth:`IcebergTable.scan` /
  :meth:`to_df` (snapshot selection, residual filters, file pruning)
* ``SparkSchemaUtil``/pruning  -> field-id projection in
  :meth:`_read_data_file` (rename/add/drop evolution: columns resolve by
  id against each data file's stored schema, never by name)
* ``GpuDeleteFilter``          -> position-delete application (content=1
  files joined on (file_path, pos) before upload)

The write path (append/delete/schema evolution) exists so the format is
self-contained; it follows the metadata commit protocol in
``metadata.py`` (atomic version rename = optimistic concurrency).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from .. import types as T
from .metadata import (DATA, POSITION_DELETES, STATUS_ADDED, DataFile,
                       IceSchema, IceSnapshot, ManifestEntry, NestedField,
                       PartitionField, PartitionSpec, TableMetadata,
                       latest_metadata_version, read_manifest,
                       read_manifest_list, read_table_metadata, type_to_ice,
                       write_manifest, write_manifest_list,
                       write_table_metadata)
from .transforms import parse_transform

#: parquet key-value metadata key holding the file's iceberg schema
#: (field ids), the hook schema evolution resolves against
_SCHEMA_PROP = b"iceberg.schema"

_FIELD_ID_KEY = b"PARQUET:field_id"


class IcebergTable:
    def __init__(self, session, path: str,
                 meta: Optional[TableMetadata] = None):
        self._session = session
        self.path = path
        self.meta = meta or read_table_metadata(path)
        #: (file_path, schema-fingerprint, "resolve") -> projection spec
        #: so a wide deletes-free table doesn't re-read every footer per
        #: query (files are immutable; schema changes change the key)
        self._schema_match_cache: Dict[Tuple, Any] = {}
        #: device/host file split of the last _device_scan_df plan; None
        #: when the scan took the host assembly path (deletes present)
        self.last_scan_file_stats: Optional[Dict[str, int]] = None

    # ------------------------------------------------------------------
    # creation / loading
    # ------------------------------------------------------------------
    @staticmethod
    def exists(path: str) -> bool:
        return latest_metadata_version(path) is not None

    @staticmethod
    def create(session, path: str, schema: T.StructType,
               partition_by: Sequence[Tuple[str, str]] = ()
               ) -> "IcebergTable":
        """``partition_by``: (column, transform) pairs, e.g.
        ``[("day_col", "day"), ("id", "bucket[16]")]``."""
        if IcebergTable.exists(path):
            raise FileExistsError(f"iceberg table exists: {path}")
        fields = [NestedField(i + 1, f.name, type_to_ice(f.data_type),
                              not f.nullable)
                  for i, f in enumerate(schema.fields)]
        ice = IceSchema(0, fields)
        pfields = []
        for j, (col, tname) in enumerate(partition_by):
            src = ice.field_by_name(col)
            if src is None:
                raise KeyError(f"partition column {col} not in schema")
            parse_transform(tname)  # validate
            pfields.append(PartitionField(src.field_id, 1000 + j, tname,
                                          f"{col}_{tname.split('[')[0]}"))
        meta = TableMetadata(
            location=path, table_uuid=str(uuid.uuid4()),
            last_column_id=len(fields), current_schema_id=0,
            schemas=[ice], default_spec_id=0,
            partition_specs=[PartitionSpec(0, pfields)])
        write_table_metadata(path, meta)
        return IcebergTable(session, path, meta)

    @staticmethod
    def for_path(session, path: str) -> "IcebergTable":
        return IcebergTable(session, path)

    def refresh(self) -> "IcebergTable":
        self.meta = read_table_metadata(self.path)
        return self

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------
    def _column_bounds(self, schema: IceSchema, tab: pa.Table):
        lower, upper, nulls = {}, {}, {}
        for f in schema.fields:
            if f.name not in tab.column_names:
                continue
            col = tab[f.name]
            nulls[f.field_id] = col.null_count
            if col.length() - col.null_count == 0:
                continue
            try:
                import pyarrow.compute as pc
                mn = pc.min(col).as_py()
                mx = pc.max(col).as_py()
            except Exception:
                continue
            if isinstance(mn, (int, float, str)):
                lower[f.field_id] = mn
                upper[f.field_id] = mx
        return lower, upper, nulls

    def _write_parquet(self, tab: pa.Table, schema: IceSchema) -> str:
        """Write a data file whose parquet schema carries the iceberg
        field ids (both as PARQUET:field_id and a schema blob in the file
        metadata) so later reads resolve columns by id."""
        fields = []
        for f in schema.fields:
            if f.name not in tab.column_names:
                continue
            af = tab.schema.field(f.name)
            fields.append(af.with_metadata(
                {_FIELD_ID_KEY: str(f.field_id).encode()}))
        out_schema = pa.schema(fields, metadata={
            _SCHEMA_PROP: json.dumps(schema.to_json()).encode()})
        cols = [tab[f.name] for f in out_schema]
        tab2 = pa.Table.from_arrays(
            [c.combine_chunks() for c in cols], schema=out_schema)
        rel = os.path.join("data", f"{uuid.uuid4().hex}.parquet")
        full = os.path.join(self.path, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(tab2, full)
        return rel

    def _commit_snapshot(self, new_entries: List[ManifestEntry],
                         carried_manifests: List[str],
                         operation: str) -> IceSnapshot:
        sid = int(uuid.uuid4().int % (1 << 62))
        seq = self.meta.last_sequence_number + 1
        manifests = list(carried_manifests)
        if new_entries:
            for e in new_entries:
                e.snapshot_id = sid
                if not e.data_file.sequence_number:
                    e.data_file.sequence_number = seq
            manifests.append(write_manifest(self.path, new_entries))
        mlist = write_manifest_list(self.path, sid, manifests)
        now = int(time.time() * 1000)
        snap = IceSnapshot(
            snapshot_id=sid, timestamp_ms=now, manifest_list=mlist,
            parent_id=self.meta.current_snapshot_id,
            schema_id=self.meta.current_schema_id,
            summary={"operation": operation,
                     "added-files": str(len(new_entries))},
            sequence_number=seq)
        self.meta.last_sequence_number = seq
        self.meta.snapshots.append(snap)
        self.meta.current_snapshot_id = sid
        self.meta.snapshot_log.append(
            {"timestamp-ms": now, "snapshot-id": sid})
        write_table_metadata(self.path, self.meta)
        return snap

    def append(self, data) -> "IcebergTable":
        """Append a DataFrame / pyarrow table, splitting into one data file
        per partition tuple."""
        tab = data.collect() if hasattr(data, "collect") else data
        schema = self.meta.schema()
        spec = self.meta.spec()
        entries: List[ManifestEntry] = []
        for part_tab, part_vals in self._split_by_partition(tab, spec,
                                                            schema):
            rel = self._write_parquet(part_tab, schema)
            lower, upper, nulls = self._column_bounds(schema, part_tab)
            df = DataFile(file_path=rel, content=DATA,
                          record_count=part_tab.num_rows,
                          file_size=os.path.getsize(
                              os.path.join(self.path, rel)),
                          spec_id=spec.spec_id, partition=part_vals,
                          lower_bounds=lower, upper_bounds=upper,
                          null_counts=nulls)
            entries.append(ManifestEntry(STATUS_ADDED, 0, df))
        carried = self._current_manifests()
        self._commit_snapshot(entries, carried, "append")
        return self

    def _split_by_partition(self, tab: pa.Table, spec: PartitionSpec,
                            schema: IceSchema):
        if spec.is_unpartitioned or tab.num_rows == 0:
            yield tab, ()
            return
        transforms = [(schema.field_by_id(pf.source_id).name,
                       parse_transform(pf.transform))
                      for pf in spec.fields]
        keys = []
        for name, tr in transforms:
            keys.append([tr.apply(v.as_py()) for v in tab[name]])
        tuples = list(zip(*keys))
        order: Dict[Tuple, List[int]] = {}
        for i, t in enumerate(tuples):
            order.setdefault(t, []).append(i)
        for t, idxs in order.items():
            yield tab.take(pa.array(idxs, type=pa.int64())), t

    def _current_manifests(self) -> List[str]:
        snap = self.meta.snapshot()
        if snap is None:
            return []
        return read_manifest_list(self.path, snap.manifest_list)

    # ------------------------------------------------------------------
    # row-level deletes (v2 position deletes)
    # ------------------------------------------------------------------
    def delete_where(self, predicate) -> int:
        """Delete rows matching ``predicate`` (a python fn row-dict->bool
        or a (col, op, literal) triple) by writing position-delete files.
        Returns the number of deleted rows."""
        snap = self.meta.snapshot()
        if snap is None:
            return 0
        files, pos_files, eq_files = self._snapshot_files(snap)
        deleted = 0
        del_rows: Dict[str, List[int]] = {}
        delete_map = self._delete_position_map(snap, pos_files)
        # predicates address the CURRENT schema names (same contract as
        # scan()'s current reads)
        cur_schema = self.meta.schema()
        eq_deletes = self._equality_deletes(snap, cur_schema, eq_files)
        for df in files:
            tab = self._read_data_file(df, cur_schema)
            existing = delete_map.get(df.file_path, set())
            mask = self._eval_predicate(tab, predicate)
            # rows already removed by equality deletes must not count as
            # (re-)deleted — compute the live mask the scan would see
            if eq_deletes:
                live = np.ones(tab.num_rows, dtype=bool)
                for seq, names, keys in eq_deletes:
                    if not keys or (df.sequence_number
                                    and seq <= df.sequence_number):
                        continue
                    vals = list(zip(*[tab[n].to_pylist() for n in names]))
                    live &= np.array([t not in keys for t in vals],
                                     dtype=bool)
                mask = mask & live
            for pos in np.nonzero(mask)[0]:
                if int(pos) not in existing:
                    del_rows.setdefault(df.file_path, []).append(int(pos))
                    deleted += 1
        if not deleted:
            return 0
        entries = []
        for fpath, positions in del_rows.items():
            dtab = pa.table({
                "file_path": [fpath] * len(positions),
                "pos": pa.array(positions, type=pa.int64())})
            rel = os.path.join("data",
                               f"delete-{uuid.uuid4().hex}.parquet")
            full = os.path.join(self.path, rel)
            os.makedirs(os.path.dirname(full), exist_ok=True)
            pq.write_table(dtab, full)
            entries.append(ManifestEntry(STATUS_ADDED, 0, DataFile(
                file_path=rel, content=POSITION_DELETES,
                record_count=len(positions),
                file_size=os.path.getsize(full))))
        self._commit_snapshot(entries, self._current_manifests(), "delete")
        return deleted

    def delete_where_equality(self, keys: "pa.Table") -> "IcebergTable":
        """Commit an EQUALITY_DELETES file: every (current or future-read)
        data row whose values for ``keys``' columns equal one of the key
        rows is deleted.  Columns resolve against the current schema."""
        from .metadata import EQUALITY_DELETES
        schema = self.meta.schema()
        fids = []
        for name in keys.column_names:
            f = schema.field_by_name(name)
            if f is None:
                raise KeyError(name)
            fids.append(f.field_id)
        rel = os.path.join("data", f"eqdel-{uuid.uuid4().hex}.parquet")
        full = os.path.join(self.path, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        # stamp PARQUET:field_id so the delete keeps applying across
        # column renames (and foreign readers resolve it by id)
        stamped = pa.schema([
            pa.field(f.name, f.type,
                     metadata={b"PARQUET:field_id":
                               str(fid).encode()})
            for f, fid in zip(keys.schema, fids)])
        pq.write_table(keys.cast(stamped), full)
        entry = ManifestEntry(STATUS_ADDED, 0, DataFile(
            file_path=rel, content=EQUALITY_DELETES,
            record_count=keys.num_rows,
            file_size=os.path.getsize(full),
            equality_ids=tuple(fids)))
        self._commit_snapshot([entry], self._current_manifests(), "delete")
        return self

    def _eval_predicate(self, tab: pa.Table, predicate) -> np.ndarray:
        if callable(predicate):
            rows = tab.to_pylist()
            return np.array([bool(predicate(r)) for r in rows], dtype=bool)
        col, op, lit = predicate
        vals = tab[col].to_numpy(zero_copy_only=False)
        if op == "=":
            return vals == lit
        if op == "!=":
            return vals != lit
        if op == "<":
            return vals < lit
        if op == "<=":
            return vals <= lit
        if op == ">":
            return vals > lit
        if op == ">=":
            return vals >= lit
        if op == "in":
            return np.isin(vals, list(lit))
        raise ValueError(f"unsupported delete predicate op {op}")

    # ------------------------------------------------------------------
    # schema evolution
    # ------------------------------------------------------------------
    def _evolve(self, mutate) -> "IcebergTable":
        cur = self.meta.schema()
        new_fields = [NestedField(f.field_id, f.name, f.type_str, f.required)
                      for f in cur.fields]
        new_schema = IceSchema(cur.schema_id + 1, new_fields)
        mutate(new_schema)
        self.meta.schemas.append(new_schema)
        self.meta.current_schema_id = new_schema.schema_id
        write_table_metadata(self.path, self.meta)
        return self

    def add_column(self, name: str, dtype) -> "IcebergTable":
        def m(s: IceSchema):
            if s.field_by_name(name):
                raise ValueError(f"column {name} exists")
            self.meta.last_column_id += 1
            s.fields.append(NestedField(self.meta.last_column_id, name,
                                        type_to_ice(dtype), False))
        return self._evolve(m)

    def rename_column(self, old: str, new: str) -> "IcebergTable":
        def m(s: IceSchema):
            f = s.field_by_name(old)
            if f is None:
                raise KeyError(old)
            f.name = new
        return self._evolve(m)

    def drop_column(self, name: str) -> "IcebergTable":
        def m(s: IceSchema):
            f = s.field_by_name(name)
            if f is None:
                raise KeyError(name)
            s.fields.remove(f)
        return self._evolve(m)

    # ------------------------------------------------------------------
    # scan planning
    # ------------------------------------------------------------------
    def _snapshot_files(self, snap: IceSnapshot):
        """ONE manifest pass per scan, classified by content:
        (data_files, position_delete_files, equality_delete_files).
        Entries whose sequence number is null (real writers rely on v2
        INHERITANCE) resolve to the sequence of the snapshot that added
        them — mapping null to 0 would both let older equality deletes
        eat re-inserted rows and let newer deletes be skipped."""
        seq_of = {s.snapshot_id: s.sequence_number
                  for s in self.meta.snapshots}
        data: List[DataFile] = []
        pos: List[DataFile] = []
        eq: List[DataFile] = []
        for mrel in read_manifest_list(self.path, snap.manifest_list):
            for e in read_manifest(self.path, mrel):
                if e.status == 2:
                    continue
                df = e.data_file
                if not df.sequence_number:
                    df.sequence_number = seq_of.get(e.snapshot_id, 0)
                if df.content == DATA:
                    data.append(df)
                elif df.content == POSITION_DELETES:
                    pos.append(df)
                else:
                    eq.append(df)
        return data, pos, eq

    def _live_data_files(self, snap: IceSnapshot) -> List[DataFile]:
        return self._snapshot_files(snap)[0]

    def _delete_files(self, snap: IceSnapshot) -> List[DataFile]:
        return self._snapshot_files(snap)[1]

    def _equality_deletes(self, snap: IceSnapshot, schema,
                          eq_files=None):
        """[(sequence_number, key column names, {key tuples})] for every
        live EQUALITY_DELETES file (reference ``GpuDeleteFilter.java:94``
        equalityFieldIds): a data row is dropped when its values for the
        delete's field ids equal a delete row's (null == null, like
        Iceberg's equality delete semantics), and the delete's sequence
        number is strictly newer than the data file's."""
        if eq_files is None:
            eq_files = self._snapshot_files(snap)[2]
        out = []
        for df in eq_files:
                tab = pq.read_table(os.path.join(self.path, df.file_path))
                names = []
                for fid in df.equality_ids:
                    f = schema.field_by_id(int(fid))
                    if f is None:
                        raise ValueError(
                            f"equality delete {df.file_path} references "
                            f"unknown field id {fid}")
                    names.append(f.name)
                # delete files may carry historical column names; match
                # columns by embedded field id first, then by name
                cols = []
                for fid, name in zip(df.equality_ids, names):
                    idx = None
                    for j, pf in enumerate(tab.schema):
                        md = pf.metadata or {}
                        if md.get(b"PARQUET:field_id") == \
                                str(fid).encode():
                            idx = j
                            break
                    if idx is None:
                        idx = tab.column_names.index(name) \
                            if name in tab.column_names else None
                    if idx is None:
                        raise ValueError(
                            f"equality delete {df.file_path} lacks a "
                            f"column for field id {fid} ({name})")
                    cols.append(tab.column(idx).to_pylist())
                keys = set(zip(*cols)) if cols else set()
                out.append((df.sequence_number, names, keys))
        return out

    @staticmethod
    def _apply_equality_deletes(tab: pa.Table, file_seq: int,
                                eq_deletes) -> pa.Table:
        for seq, names, keys in eq_deletes:
            if not keys or (file_seq and seq <= file_seq):
                continue  # delete is not newer than the data
            vals = list(zip(*[tab[n].to_pylist() for n in names]))
            mask = pa.array([t not in keys for t in vals],
                            type=pa.bool_())
            tab = tab.filter(mask)
        return tab

    def _delete_position_map(self, snap: IceSnapshot,
                             pos_files=None) -> Dict[str, set]:
        """All position deletes for the snapshot, read ONCE per scan:
        {data_file_path: {deleted row positions}}."""
        from .metadata import normalize_data_path
        out: Dict[str, set] = {}
        for df in (pos_files if pos_files is not None
                   else self._delete_files(snap)):
            tab = pq.read_table(os.path.join(self.path, df.file_path))
            for fp, p in zip(tab["file_path"].to_pylist(),
                             tab["pos"].to_pylist()):
                # real delete files reference data files by full URI
                out.setdefault(normalize_data_path(fp, self.path),
                               set()).add(int(p))
        return out

    def _prune_files(self, files: List[DataFile],
                     filters: Sequence[Tuple[str, str, Any]],
                     schema: IceSchema) -> List[DataFile]:
        """Partition-transform pruning + column-bound (min/max) skipping —
        the planning the reference does via Iceberg's
        ``ManifestEvaluator``/``InclusiveMetricsEvaluator``."""
        if not filters:
            return files
        spec_cache: Dict[int, PartitionSpec] = {}
        out = []
        for df in files:
            spec = spec_cache.setdefault(df.spec_id,
                                         self.meta.spec(df.spec_id))
            keep = True
            for col, op, lit in filters:
                f = schema.field_by_name(col)
                if f is None:
                    continue
                # partition pruning
                for pi, pf in enumerate(spec.fields):
                    if pf.source_id == f.field_id and pi < len(df.partition):
                        tr = parse_transform(pf.transform)
                        if not tr.possible(df.partition[pi], op, lit):
                            keep = False
                            break
                if not keep:
                    break
                # min/max skipping (same overlap predicate as parquet
                # row-group pruning)
                from ..io_.pushdown import stats_possible
                lo = df.lower_bounds.get(f.field_id)
                hi = df.upper_bounds.get(f.field_id)
                if lo is not None and hi is not None and \
                        op in ("=", "<", "<=", ">", ">=", "in") and \
                        not stats_possible(lo, hi, op, lit):
                    keep = False
                if not keep:
                    break
            if keep:
                out.append(df)
        return out

    def _read_data_file(self, df: DataFile, schema: IceSchema) -> pa.Table:
        """Read one data file projecting the snapshot schema BY FIELD ID:
        renamed columns resolve to their old physical name, dropped columns
        are skipped, added columns null-fill."""
        full = os.path.join(self.path, df.file_path)
        ptab = pq.read_table(full)
        file_ids: Dict[int, str] = {}
        for af in ptab.schema:
            meta = af.metadata or {}
            if _FIELD_ID_KEY in meta:
                file_ids[int(meta[_FIELD_ID_KEY])] = af.name
        if not file_ids:
            # file carries no field ids (imported data): fall back to
            # name mapping, which is exactly Iceberg's
            # `schema.name-mapping.default` behavior for such files
            names = set(ptab.schema.names)
            file_ids = {f.field_id: f.name for f in schema.fields
                        if f.name in names}
        arrays, fields = [], []
        n = ptab.num_rows
        for f in schema.fields:
            atype = T.to_arrow(ice_to_type_cached(f.type_str))
            phys = file_ids.get(f.field_id)
            if phys is not None:
                col = ptab[phys].combine_chunks()
                if col.type != atype:
                    col = col.cast(atype)
                arrays.append(col)
            else:
                arrays.append(pa.nulls(n, type=atype))
            fields.append(pa.field(f.name, atype, not f.required))
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def _select_snapshot(self, snapshot_id: Optional[int],
                         as_of_timestamp_ms: Optional[int]
                         ) -> Tuple[Optional[IceSnapshot], Optional[int]]:
        """(snapshot, schema_id-to-read-with).  Current reads use the
        table's CURRENT schema (Iceberg semantics: schema evolves
        independently of snapshots); explicit time travel reads with the
        schema the snapshot was committed under."""
        if as_of_timestamp_ms is not None:
            snap = self.meta.snapshot_as_of(as_of_timestamp_ms)
        else:
            snap = self.meta.snapshot(snapshot_id)
        if snap is None:
            return None, None
        time_travel = (snapshot_id is not None
                       or as_of_timestamp_ms is not None)
        return snap, (snap.schema_id if time_travel else None)

    def scan(self, filters: Sequence[Tuple[str, str, Any]] = (),
             snapshot_id: Optional[int] = None,
             as_of_timestamp_ms: Optional[int] = None) -> List[pa.Table]:
        """Plan + execute the host-side read: returns one pa.Table per
        surviving data file (deletes applied, schema projected)."""
        snap, schema_id = self._select_snapshot(snapshot_id,
                                                as_of_timestamp_ms)
        if snap is None:
            return []
        schema = self.meta.schema(schema_id)
        data_files, pos_files, eq_files = self._snapshot_files(snap)
        files = self._prune_files(data_files, filters, schema)
        delete_map = self._delete_position_map(snap, pos_files)
        eq_deletes = self._equality_deletes(snap, schema, eq_files)
        out = []
        for df in files:
            tab = self._read_data_file(df, schema)
            dels = delete_map.get(df.file_path)
            if dels:
                keep = np.setdiff1d(np.arange(tab.num_rows),
                                    np.fromiter(dels, dtype=np.int64))
                tab = tab.take(pa.array(keep, type=pa.int64()))
            if eq_deletes:
                tab = self._apply_equality_deletes(
                    tab, df.sequence_number, eq_deletes)
            out.append(tab)
        return out

    def planned_files(self, filters: Sequence[Tuple[str, str, Any]] = ()
                      ) -> List[str]:
        """File list after pruning (for tests / EXPLAIN)."""
        snap = self.meta.snapshot()
        if snap is None:
            return []
        schema = self.meta.schema(snap.schema_id)
        return [f.file_path for f in
                self._prune_files(self._live_data_files(snap), filters,
                                  schema)]

    def _device_scan_df(self, filters, snapshot_id, as_of_timestamp_ms):
        """Per-FILE device decode with schema-evolution projection
        (VERDICT r4 #8 — the round-4 gate declined the whole scan when
        ANY column mismatched).  Each delete-free file becomes a
        ``read.parquet`` frame projected to the snapshot schema:

          * field-id (+ arrow-type) matches select the file column,
            renamed if the snapshot renamed it;
          * ids absent from the file (dropped+re-added columns allocate
            fresh ids, so stale same-NAME columns are skipped) null-fill
            via ``lit(NULL) CAST``;
          * a type mismatch (promotion) sends THAT FILE — not the scan —
            to the host id-resolving reader.

        Frames union into one plan; matching files keep riding
        ``io_/device_parquet.py``.  Returns the DataFrame, or None when
        deletes force the host assembly path.  ``last_scan_file_stats``
        reports the device/host file split for tests/EXPLAIN."""
        self.last_scan_file_stats = None  # host-assembly scans report None
        snap, schema_id = self._select_snapshot(snapshot_id,
                                                as_of_timestamp_ms)
        if snap is None:
            return None
        schema = self.meta.schema(schema_id)
        data_files, pos_files, eq_files = self._snapshot_files(snap)
        if pos_files or eq_files:
            return None
        files = self._prune_files(data_files, filters, schema)
        if not files:
            return None
        want = [(f.name, f.field_id, ice_to_type_cached(f.type_str))
                for f in schema.fields]
        # schema_id alone is not a valid cache key: in-place evolution
        # (add/rename/drop) can keep the id while changing the fields —
        # fingerprint the resolved field tuple instead
        fp = tuple((f.name, f.field_id, f.type_str)
                   for f in schema.fields)
        specs = []
        for df in files:
            full = os.path.join(self.path, df.file_path)
            key = (df.file_path, fp, "resolve")
            spec = self._schema_match_cache.get(key)
            if spec is None:
                try:
                    fs = pq.read_schema(full)
                except OSError:
                    return None
                by_id = {}
                by_name = {}
                has_ids = False
                for af in fs:
                    meta = af.metadata or {}
                    if _FIELD_ID_KEY in meta:
                        has_ids = True
                        by_id[int(meta[_FIELD_ID_KEY])] = af
                    by_name[af.name] = af
                cols = []
                host = False
                for name, fid, dt in want:
                    af = by_id.get(fid) if has_ids else by_name.get(name)
                    if af is None:
                        cols.append(("null", name))
                    elif af.type == T.to_arrow(dt):
                        cols.append(("col", af.name, name))
                    else:
                        host = True  # type promotion: host id-resolution
                        break
                if host:
                    spec = "host"
                elif (fs.names == [c[1] for c in cols if c[0] == "col"]
                        and all(c[0] == "col" and c[1] == c[2]
                                for c in cols)):
                    spec = "identity"
                else:
                    spec = cols
                self._schema_match_cache[key] = spec
            specs.append((df, full, spec))
        if all(s == "identity" for _, _, s in specs):
            self.last_scan_file_stats = {"device": len(specs), "host": 0}
            return self._session.read.parquet(*[p for _, p, _ in specs])
        from ..sql import functions as F
        # files sharing a projection spec share ONE multi-path scan node
        # (a 1000-file table after one rename is one scan + one select,
        # not a 999-deep union chain)
        groups: List[Tuple[Any, List]] = []   # (spec, [paths|data_files])
        for df, full, spec in specs:
            k = spec if isinstance(spec, str) else tuple(spec)
            if groups and groups[-1][0] == k:
                groups[-1][2].append(df if spec == "host" else full)
            else:
                groups.append((k, spec, [df if spec == "host" else full]))
        frames = []
        ndev = nhost = 0
        for _k, spec, members in groups:
            if spec == "host":
                for df in members:
                    frames.append(self._session.create_dataframe(
                        self._read_data_file(df, schema)))
                nhost += len(members)
                continue
            base = self._session.read.parquet(*members)
            ndev += len(members)
            if spec == "identity":
                frames.append(base)
                continue
            sel = []
            for item, (name, _fid, dt) in zip(spec, want):
                if item[0] == "null":
                    sel.append(F.lit(None).cast(dt).alias(name))
                else:
                    sel.append(F.col(item[1]).alias(item[2]))
            frames.append(base.select(*sel))
        out = frames[0]
        for f in frames[1:]:
            out = out.union(f)
        self.last_scan_file_stats = {"device": ndev, "host": nhost}
        return out

    def to_df(self, filters: Sequence[Tuple[str, str, Any]] = (),
              snapshot_id: Optional[int] = None,
              as_of_timestamp_ms: Optional[int] = None):
        """DataFrame over the scan: partitions = data files, so the engine
        parallelizes per-file like FileScanExec."""
        device = self._device_scan_df(filters, snapshot_id,
                                      as_of_timestamp_ms)
        if device is not None:
            return device
        parts = self.scan(filters, snapshot_id, as_of_timestamp_ms)
        if not parts:
            _snap, schema_id = self._select_snapshot(snapshot_id,
                                                     as_of_timestamp_ms)
            schema = self.meta.schema(schema_id).to_struct_type()
            empty = pa.schema([
                pa.field(f.name, T.to_arrow(f.data_type), f.nullable)
                for f in schema.fields]).empty_table()
            return self._session.create_dataframe(empty)
        whole = pa.concat_tables(parts)
        return self._session.create_dataframe(whole, partitions=parts)

    # ------------------------------------------------------------------
    # maintenance
    # ------------------------------------------------------------------
    def history(self) -> List[dict]:
        return [{"version": i, "snapshot_id": s.snapshot_id,
                 "timestamp_ms": s.timestamp_ms,
                 "operation": s.summary.get("operation")}
                for i, s in enumerate(self.meta.snapshots)]

    # --- metadata tables (Spark's `db.table.snapshots` / `.files`) --------
    def snapshots_df(self):
        """The `<table>.snapshots` metadata table as a DataFrame
        (reference exposes these through its Iceberg read path)."""
        rows = {
            "snapshot_id": [], "parent_id": [], "timestamp_ms": [],
            "operation": [], "schema_id": [],
        }
        for s in self.meta.snapshots:
            rows["snapshot_id"].append(s.snapshot_id)
            rows["parent_id"].append(s.parent_id)
            rows["timestamp_ms"].append(s.timestamp_ms)
            rows["operation"].append(s.summary.get("operation"))
            rows["schema_id"].append(s.schema_id)
        return self._session.create_dataframe(pa.table({
            "snapshot_id": pa.array(rows["snapshot_id"], pa.int64()),
            "parent_id": pa.array(rows["parent_id"], pa.int64()),
            "timestamp_ms": pa.array(rows["timestamp_ms"], pa.int64()),
            "operation": pa.array(rows["operation"], pa.string()),
            "schema_id": pa.array(rows["schema_id"], pa.int32()),
        }))

    def files_df(self):
        """The `<table>.files` metadata table: live data files of the
        current snapshot with record counts, sizes and partition values."""
        snap = self.meta.snapshot()
        files = self._live_data_files(snap) if snap is not None else []
        return self._session.create_dataframe(pa.table({
            "file_path": pa.array([f.file_path for f in files],
                                  pa.string()),
            "record_count": pa.array([f.record_count for f in files],
                                     pa.int64()),
            "file_size_bytes": pa.array([f.file_size for f in files],
                                        pa.int64()),
            "partition": pa.array([str(f.partition) for f in files],
                                  pa.string()),
        }))

    def rewrite_data_files(self, target_files: int = 1) -> int:
        """Compaction (`rewrite_data_files` action): concatenate the
        current snapshot's live rows (position deletes applied) into
        ``target_files`` new files and commit a REPLACE snapshot.
        Returns the number of files compacted away."""
        snap = self.meta.snapshot()
        if snap is None:
            return 0
        old_files = self._live_data_files(snap)
        if len(old_files) <= target_files:
            return 0
        schema = self.meta.schema(snap.schema_id)
        parts = self.scan()
        if not parts:
            return 0
        whole = pa.concat_tables(parts)
        n = max(1, int(target_files))
        per = -(-whole.num_rows // n)
        entries: List[ManifestEntry] = []
        for off in range(0, whole.num_rows, per):
            piece = whole.slice(off, min(per, whole.num_rows - off))
            rel = self._write_parquet(piece, schema)
            lower, upper, nulls = self._column_bounds(schema, piece)
            entries.append(ManifestEntry(STATUS_ADDED, 0, DataFile(
                file_path=rel, content=DATA, record_count=piece.num_rows,
                file_size=os.path.getsize(os.path.join(self.path, rel)),
                spec_id=self.meta.spec().spec_id,
                lower_bounds=lower, upper_bounds=upper,
                null_counts=nulls)))
        # REPLACE: no carried manifests — old data + delete files retire
        self._commit_snapshot(entries, [], "replace")
        return len(old_files)

    def expire_snapshots(self, older_than_ms: int) -> int:
        """Drop snapshot metadata older than the cutoff (keeping current);
        returns count removed."""
        cur = self.meta.current_snapshot_id
        before = len(self.meta.snapshots)
        self.meta.snapshots = [
            s for s in self.meta.snapshots
            if s.snapshot_id == cur or s.timestamp_ms >= older_than_ms]
        keep_ids = {s.snapshot_id for s in self.meta.snapshots}
        self.meta.snapshot_log = [
            e for e in self.meta.snapshot_log
            if e["snapshot-id"] in keep_ids]
        removed = before - len(self.meta.snapshots)
        if removed:
            write_table_metadata(self.path, self.meta)
        return removed


_ICE_CACHE: Dict[str, Any] = {}


def ice_to_type_cached(s: str):
    from .metadata import ice_to_type
    v = _ICE_CACHE.get(s)
    if v is None:
        v = _ICE_CACHE[s] = ice_to_type(s)
    return v
