"""Iceberg partition transforms (spec: identity, bucket[N], truncate[W],
year, month, day, hour, void) with the pruning contract the reference's
scan layer relies on: for each transform, given a column-level predicate we
can decide whether a partition value can possibly contain matching rows.

Bucket hashing follows the Iceberg single-value hash spec shape
(murmur3_x86_32 over the value's canonical byte encoding: 8-byte
little-endian for int/long/date/timestamp, UTF-8 for strings), implemented
here on the host since transforms run at planning time, not on the device
(reference: iceberg PartitionSpec/Transforms, consumed by
``GpuSparkBatchQueryScan``'s file filtering).
"""

from __future__ import annotations

import struct
from datetime import date, datetime, timezone
from typing import Any, Optional

_EPOCH = date(1970, 1, 1)


def _murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xcc9e2d51, 0x1b873593
    h = seed & 0xffffffff
    n = len(data)
    for i in range(0, n - n % 4, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xffffffff
        k = ((k << 15) | (k >> 17)) & 0xffffffff
        k = (k * c2) & 0xffffffff
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xffffffff
        h = (h * 5 + 0xe6546b64) & 0xffffffff
    tail = data[n - n % 4:]
    if tail:
        k = int.from_bytes(tail, "little")
        k = (k * c1) & 0xffffffff
        k = ((k << 15) | (k >> 17)) & 0xffffffff
        k = (k * c2) & 0xffffffff
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85ebca6b) & 0xffffffff
    h ^= h >> 13
    h = (h * 0xc2b2ae35) & 0xffffffff
    h ^= h >> 16
    return h


def _hash_value(v: Any) -> int:
    if isinstance(v, bool):
        raise ValueError("bucket over boolean is not supported")
    if isinstance(v, int):
        return _murmur3_32(struct.pack("<q", v))
    if isinstance(v, str):
        return _murmur3_32(v.encode("utf-8"))
    if isinstance(v, bytes):
        return _murmur3_32(v)
    if isinstance(v, datetime):
        micros = int(v.replace(tzinfo=v.tzinfo or timezone.utc)
                     .timestamp() * 1_000_000)
        return _murmur3_32(struct.pack("<q", micros))
    if isinstance(v, date):
        return _murmur3_32(struct.pack("<q", (v - _EPOCH).days))
    raise ValueError(f"unsupported bucket value type: {type(v)}")


def _to_days(v) -> int:
    if isinstance(v, datetime):
        v = v.date()
    if isinstance(v, date):
        return (v - _EPOCH).days
    return int(v)


def _to_datetime(v) -> datetime:
    if isinstance(v, datetime):
        return v
    if isinstance(v, date):
        return datetime(v.year, v.month, v.day)
    if isinstance(v, (int, float)):  # micros since epoch
        return datetime.fromtimestamp(v / 1e6, tz=timezone.utc)
    raise ValueError(f"cannot interpret {v!r} as a timestamp")


class Transform:
    """Apply + prune interface.  ``apply`` maps a source value to the
    partition value; ``possible`` answers "could a row with source value
    satisfying (op, literal) live in a partition with this value?" —
    conservative True when unknown."""

    name = "identity"

    def apply(self, v: Any) -> Any:
        raise NotImplementedError

    def possible(self, part_value: Any, op: str, literal: Any) -> bool:
        return True  # conservative default: cannot prune


class IdentityTransform(Transform):
    name = "identity"

    def apply(self, v):
        return v

    def possible(self, part_value, op, literal):
        if part_value is None:
            return op in ("isnull",)
        if op == "=":
            return part_value == literal
        if op == "!=":
            # identity partitioning: every row in the file shares the value
            return part_value != literal
        if op == "<":
            return part_value < literal
        if op == "<=":
            return part_value <= literal
        if op == ">":
            return part_value > literal
        if op == ">=":
            return part_value >= literal
        if op == "in":
            return part_value in literal
        if op == "isnull":
            return part_value is None
        if op == "isnotnull":
            return part_value is not None
        return True


class BucketTransform(Transform):
    def __init__(self, n: int):
        self.n = n
        self.name = f"bucket[{n}]"

    def apply(self, v):
        if v is None:
            return None
        return (_hash_value(v) & 0x7fffffff) % self.n

    def possible(self, part_value, op, literal):
        if op == "=":
            return part_value == self.apply(literal)
        if op == "in":
            return part_value in {self.apply(x) for x in literal}
        if op == "isnull":
            return part_value is None
        return True


class TruncateTransform(Transform):
    def __init__(self, w: int):
        self.w = w
        self.name = f"truncate[{w}]"

    def apply(self, v):
        if v is None:
            return None
        if isinstance(v, int):
            return v - (v % self.w)
        if isinstance(v, str):
            return v[:self.w]
        if isinstance(v, bytes):
            return v[:self.w]
        raise ValueError(f"truncate of {type(v)} unsupported")

    def possible(self, part_value, op, literal):
        if part_value is None:
            return op == "isnull"
        t = self.apply(literal)
        if op == "=":
            return part_value == t
        if op == "in":
            return part_value in {self.apply(x) for x in literal}
        if isinstance(literal, int):
            if op in ("<", "<="):
                return part_value <= t
            if op in (">", ">="):
                return part_value + self.w > t
        if isinstance(literal, str):
            if op in ("<", "<="):
                return part_value <= t
            if op in (">", ">="):
                return part_value >= t[:self.w] if t else True
        return True


class _TimeTransform(Transform):
    """year/month/day/hour — ordered integral partition values, so range
    predicates prune directly on the transformed literal."""

    def _ord(self, v) -> int:
        raise NotImplementedError

    def apply(self, v):
        return None if v is None else self._ord(v)

    def possible(self, part_value, op, literal):
        if part_value is None:
            return op == "isnull"
        try:
            t = self._ord(literal)
        except Exception:
            return True
        if op == "=":
            return part_value == t
        if op == "<":
            return part_value <= t
        if op == "<=":
            return part_value <= t
        if op == ">":
            return part_value >= t
        if op == ">=":
            return part_value >= t
        if op == "in":
            return part_value in {self._ord(x) for x in literal}
        return True


class YearTransform(_TimeTransform):
    name = "year"

    def _ord(self, v):
        if isinstance(v, (date, datetime)):
            return v.year - 1970
        return _to_datetime(v).year - 1970


class MonthTransform(_TimeTransform):
    name = "month"

    def _ord(self, v):
        if isinstance(v, (date, datetime)):
            return (v.year - 1970) * 12 + (v.month - 1)
        d = _to_datetime(v)
        return (d.year - 1970) * 12 + (d.month - 1)


class DayTransform(_TimeTransform):
    name = "day"

    def _ord(self, v):
        return _to_days(v)


class HourTransform(_TimeTransform):
    name = "hour"

    def _ord(self, v):
        d = _to_datetime(v)
        if d.tzinfo is None:
            # naive values are UTC everywhere in this module (_hash_value
            # does the same); never let the process TZ leak into partition
            # ordinals
            d = d.replace(tzinfo=timezone.utc)
        return int(d.timestamp() // 3600)


class VoidTransform(Transform):
    name = "void"

    def apply(self, v):
        return None


def parse_transform(name: str) -> Transform:
    if name == "identity":
        return IdentityTransform()
    if name == "void":
        return VoidTransform()
    if name == "year":
        return YearTransform()
    if name == "month":
        return MonthTransform()
    if name == "day":
        return DayTransform()
    if name == "hour":
        return HourTransform()
    if name.startswith("bucket[") and name.endswith("]"):
        return BucketTransform(int(name[7:-1]))
    if name.startswith("truncate[") and name.endswith("]"):
        return TruncateTransform(int(name[9:-1]))
    raise ValueError(f"unknown transform: {name}")
