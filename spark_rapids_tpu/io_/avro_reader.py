"""Avro Object Container File reader/writer (host decode -> Arrow).

TPU-native analog of the reference's in-repo Avro file parsing
(`org/apache/spark/sql/rapids/GpuAvroScan.scala`, `AvroDataFileReader.scala:478`,
`AvroFileWriter.scala:53`): the reference parses Avro container framing on the
host in Scala and builds device batches; here the host parse produces an Arrow
table that the scan framework uploads in one shot.

Supports: null/deflate codecs, primitive types, records, enums, fixed,
arrays, maps, unions with null (nullable), and the date /
timestamp-millis / timestamp-micros logical types.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from .. import types as T

_MAGIC = b"Obj\x01"


# --------------------------------------------------------------------------
# Binary decoding primitives
# --------------------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    def long(self) -> int:
        """zigzag varint"""
        b = self.buf
        pos = self.pos
        shift = 0
        acc = 0
        while True:
            byte = b[pos]
            pos += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        self.pos = pos
        return (acc >> 1) ^ -(acc & 1)

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _decode_value(r: _Reader, schema) -> Any:
    """Recursive single-datum decode against a (parsed-JSON) avro schema."""
    if isinstance(schema, str):
        kind = schema
        if kind == "null":
            return None
        if kind == "boolean":
            return r.boolean()
        if kind in ("int", "long"):
            return r.long()
        if kind == "float":
            return r.float_()
        if kind == "double":
            return r.double()
        if kind == "bytes":
            return r.bytes_()
        if kind == "string":
            return r.string()
        raise ValueError(f"unknown avro primitive {kind!r}")
    if isinstance(schema, list):  # union
        idx = r.long()
        return _decode_value(r, schema[idx])
    kind = schema["type"]
    if kind in ("record", "error"):
        return {f["name"]: _decode_value(r, f["type"])
                for f in schema["fields"]}
    if kind == "enum":
        return schema["symbols"][r.long()]
    if kind == "fixed":
        return r.read(schema["size"])
    if kind == "array":
        out: List[Any] = []
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:  # block with byte-size prefix
                n = -n
                r.long()
            for _ in range(n):
                out.append(_decode_value(r, schema["items"]))
        return out
    if kind == "map":
        m: Dict[str, Any] = {}
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                n = -n
                r.long()
            for _ in range(n):
                m[r.string()] = _decode_value(r, schema["values"])
        return m
    # e.g. {"type": "long", "logicalType": ...} — logical handled in arrow map
    return _decode_value(r, kind)


# --------------------------------------------------------------------------
# Schema mapping
# --------------------------------------------------------------------------

def _avro_to_arrow_type(schema) -> Tuple[pa.DataType, bool]:
    """Returns (arrow type, nullable)."""
    if isinstance(schema, str):
        return {
            "null": pa.null(), "boolean": pa.bool_(), "int": pa.int32(),
            "long": pa.int64(), "float": pa.float32(), "double": pa.float64(),
            "bytes": pa.binary(), "string": pa.string(),
        }[schema], schema == "null"
    if isinstance(schema, list):
        non_null = [s for s in schema if s != "null"]
        if len(non_null) != 1:
            raise ValueError("general avro unions are unsupported; "
                             "only [null, X]")
        t, _ = _avro_to_arrow_type(non_null[0])
        return t, True
    kind = schema["type"]
    logical = schema.get("logicalType")
    if logical == "date":
        return pa.date32(), False
    if logical == "timestamp-millis":
        return pa.timestamp("ms"), False
    if logical == "timestamp-micros":
        return pa.timestamp("us"), False
    if logical == "decimal":
        return pa.decimal128(schema["precision"], schema.get("scale", 0)), False
    if kind in ("record", "error"):
        fields = []
        for f in schema["fields"]:
            t, nullable = _avro_to_arrow_type(f["type"])
            fields.append(pa.field(f["name"], t, nullable=nullable))
        return pa.struct(fields), False
    if kind == "enum":
        return pa.string(), False
    if kind == "fixed":
        return pa.binary(schema["size"]), False
    if kind == "array":
        t, nullable = _avro_to_arrow_type(schema["items"])
        return pa.list_(pa.field("item", t, nullable=nullable)), False
    if kind == "map":
        t, nullable = _avro_to_arrow_type(schema["values"])
        return pa.map_(pa.string(), t), False
    if isinstance(kind, (str, list, dict)):
        return _avro_to_arrow_type(kind)
    raise ValueError(f"unsupported avro schema {schema!r}")


def _decimal_from_bytes(raw: bytes, scale: int):
    import decimal
    unscaled = int.from_bytes(raw, "big", signed=True)
    return decimal.Decimal(unscaled).scaleb(-scale)


class _FileHeader:
    def __init__(self, fh: BinaryIO):
        if fh.read(4) != _MAGIC:
            raise ValueError("not an avro object container file")
        r_meta: Dict[str, bytes] = {}
        r = _Reader(fh.read())  # header meta + all blocks; files are host-side
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                n = -n
                r.long()
            for _ in range(n):
                key = r.string()
                r_meta[key] = r.bytes_()
        self.sync = r.read(16)
        self.schema = json.loads(r_meta["avro.schema"])
        self.codec = r_meta.get("avro.codec", b"null").decode()
        self.body = r


def read_avro(path: str, options: Optional[Dict] = None,
              head_rows: Optional[int] = None) -> pa.Table:
    with open(path, "rb") as fh:
        hdr = _FileHeader(fh)
    schema = hdr.schema
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise ValueError("top-level avro schema must be a record")
    fields = schema["fields"]
    rows: List[Dict[str, Any]] = []
    r = hdr.body
    while not r.at_end():
        count = r.long()
        size = r.long()
        block = r.read(size)
        if hdr.codec == "deflate":
            block = zlib.decompress(block, -15)
        elif hdr.codec != "null":
            raise ValueError(f"unsupported avro codec {hdr.codec!r}")
        br = _Reader(block)
        for _ in range(count):
            rows.append({f["name"]: _decode_value(br, f["type"])
                         for f in fields})
            if head_rows is not None and len(rows) >= head_rows:
                break
        sync = r.read(16)
        if sync != hdr.sync:
            raise ValueError("avro sync marker mismatch (corrupt file)")
        if head_rows is not None and len(rows) >= head_rows:
            break

    arrow_fields = []
    converters = {}
    for f in fields:
        t, nullable = _avro_to_arrow_type(f["type"])
        arrow_fields.append(pa.field(f["name"], t, nullable=nullable))
        log = f["type"].get("logicalType") if isinstance(f["type"], dict) else None
        if log == "decimal":
            scale = f["type"].get("scale", 0)
            converters[f["name"]] = (
                lambda v, s=scale: None if v is None
                else _decimal_from_bytes(v, s))
    if converters:
        for row in rows:
            for name, conv in converters.items():
                row[name] = conv(row[name])
    arrow_schema = pa.schema(arrow_fields)
    if not rows:
        return arrow_schema.empty_table()
    return pa.Table.from_pylist(rows, schema=arrow_schema)


def avro_schema(path: str) -> T.StructType:
    with open(path, "rb") as fh:
        hdr = _FileHeader(fh)
    fields = []
    for f in hdr.schema["fields"]:
        t, nullable = _avro_to_arrow_type(f["type"])
        fields.append(T.StructField(f["name"], T.from_arrow(t), nullable))
    return T.StructType(fields)


# --------------------------------------------------------------------------
# Writer (null codec) — AvroFileWriter.scala:53 analog
# --------------------------------------------------------------------------

def _zigzag(out: bytearray, v: int) -> None:
    v = ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            break


def _arrow_to_avro_schema(field: pa.Field):
    t = field.type
    base: Any
    if pa.types.is_boolean(t):
        base = "boolean"
    elif pa.types.is_int32(t) or pa.types.is_int8(t) or pa.types.is_int16(t):
        base = "int"
    elif pa.types.is_int64(t):
        base = "long"
    elif pa.types.is_float32(t):
        base = "float"
    elif pa.types.is_float64(t):
        base = "double"
    elif pa.types.is_string(t) or pa.types.is_large_string(t):
        base = "string"
    elif pa.types.is_binary(t) or pa.types.is_large_binary(t):
        base = "bytes"
    elif pa.types.is_date32(t):
        base = {"type": "int", "logicalType": "date"}
    elif pa.types.is_timestamp(t):
        base = {"type": "long", "logicalType": "timestamp-micros"}
    elif pa.types.is_list(t):
        item = _arrow_to_avro_schema(pa.field("item", t.value_type))
        base = {"type": "array", "items": item}
    else:
        raise ValueError(f"cannot write {t} to avro")
    return ["null", base] if field.nullable else base


def _encode_value(out: bytearray, schema, v) -> None:
    if isinstance(schema, list):  # nullable union
        if v is None:
            _zigzag(out, 0)
            return
        _zigzag(out, 1)
        _encode_value(out, schema[1], v)
        return
    if isinstance(schema, dict):
        kind = schema["type"]
        if kind == "array":
            if v:
                _zigzag(out, len(v))
                for item in v:
                    _encode_value(out, schema["items"], item)
            _zigzag(out, 0)
            return
        _encode_value(out, kind, v)
        return
    if schema == "boolean":
        out.append(1 if v else 0)
    elif schema in ("int", "long"):
        _zigzag(out, int(v))
    elif schema == "float":
        out.extend(struct.pack("<f", float(v)))
    elif schema == "double":
        out.extend(struct.pack("<d", float(v)))
    elif schema == "string":
        raw = v.encode("utf-8")
        _zigzag(out, len(raw))
        out.extend(raw)
    elif schema == "bytes":
        raw = bytes(v)
        _zigzag(out, len(raw))
        out.extend(raw)
    else:
        raise ValueError(f"cannot encode avro type {schema!r}")


def write_avro(table: pa.Table, path: str, options: Optional[Dict] = None
               ) -> None:
    fields = [(f.name, _arrow_to_avro_schema(f)) for f in table.schema]
    schema = {"type": "record", "name": "topLevelRecord",
              "fields": [{"name": n, "type": s} for n, s in fields]}
    sync = os.urandom(16)
    header = bytearray()
    header.extend(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null"}
    _zigzag(header, len(meta))
    for k, v in meta.items():
        kb = k.encode()
        _zigzag(header, len(kb))
        header.extend(kb)
        _zigzag(header, len(v))
        header.extend(v)
    _zigzag(header, 0)
    header.extend(sync)

    # logical types are written as their physical carrier ints
    cast_fields = []
    for f in table.schema:
        if pa.types.is_timestamp(f.type):
            cast_fields.append(pa.field(f.name, pa.int64(), nullable=f.nullable))
        elif pa.types.is_date32(f.type):
            cast_fields.append(pa.field(f.name, pa.int32(), nullable=f.nullable))
        else:
            cast_fields.append(f)
    cols = []
    for f in table.schema:
        col = table.column(f.name)
        if pa.types.is_timestamp(f.type):
            col = col.cast(pa.timestamp("us")).cast(pa.int64())
        elif pa.types.is_date32(f.type):
            col = col.cast(pa.int32())
        cols.append(col)
    table = pa.table(cols, schema=pa.schema(cast_fields))

    body = bytearray()
    rows = table.to_pylist()
    if rows:
        block = bytearray()
        for row in rows:
            for name, s in fields:
                _encode_value(block, s, row[name])
        _zigzag(body, len(rows))
        _zigzag(body, len(block))
        body.extend(block)
        body.extend(sync)
    with open(path, "wb") as fh:
        fh.write(bytes(header))
        fh.write(bytes(body))
