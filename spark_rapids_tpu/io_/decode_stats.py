"""Device-decode engagement counters (VERDICT round 5, Weak #7).

Every device decoder (parquet/ORC/CSV/JSON/Avro) either ENGAGES a file
(builds device columns straight from raw bytes) or DECLINES it to the
host pyarrow path.  The decline is silent by design (correctness first),
which made the engagement *rate* unobservable — a regression that
declined every file would still pass every test.  This module is the
shared scoreboard: files/bytes engaged vs declined per format, with a
per-reason decline breakdown, surfaced per query in
``last_query_metrics`` (``<fmt>DecodeFilesEngaged`` / ``…Declined`` /
``…BytesEngaged`` / ``…BytesDeclined``) and in the scale-rig report.

Decoders that know WHY they declined call :func:`set_decline_reason`
just before returning None; the exec layer folds it into the per-reason
map (default reason: ``decoder-declined``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

FORMATS = ("parquet", "orc", "csv", "json", "avro")

#: per-format counters; decline_reasons maps reason -> file count
DECODE_STATS: Dict[str, dict] = {
    fmt: {"files_engaged": 0, "files_declined": 0,
          "bytes_engaged": 0, "bytes_declined": 0,
          "decline_reasons": {}}
    for fmt in FORMATS}

_LOCK = threading.Lock()
_TLS = threading.local()


def set_decline_reason(reason: str) -> None:
    """Record the reason for the decline this thread is about to report
    (consumed once by the next :func:`record_declined`)."""
    _TLS.reason = reason


def _take_reason(default: str) -> str:
    r = getattr(_TLS, "reason", None)
    _TLS.reason = None
    return r or default


def record_engaged(fmt: str, nbytes: int = 0) -> None:
    _TLS.reason = None  # stale hints must not leak into a later decline
    if fmt not in DECODE_STATS:
        return
    with _LOCK:
        s = DECODE_STATS[fmt]
        s["files_engaged"] += 1
        s["bytes_engaged"] += int(nbytes)


def record_declined(fmt: str, nbytes: int = 0,
                    reason: Optional[str] = None) -> None:
    if fmt not in DECODE_STATS:
        return
    reason = reason or _take_reason("decoder-declined")
    with _LOCK:
        s = DECODE_STATS[fmt]
        s["files_declined"] += 1
        s["bytes_declined"] += int(nbytes)
        s["decline_reasons"][reason] = \
            s["decline_reasons"].get(reason, 0) + 1


def snapshot() -> Dict[str, float]:
    """Flat counter snapshot (reasons excluded) — the per-query metrics
    delta base, mirroring robustness.stats_snapshot."""
    out: Dict[str, float] = {}
    with _LOCK:
        for fmt, s in DECODE_STATS.items():
            out[f"{fmt}DecodeFilesEngaged"] = s["files_engaged"]
            out[f"{fmt}DecodeFilesDeclined"] = s["files_declined"]
            out[f"{fmt}DecodeBytesEngaged"] = s["bytes_engaged"]
            out[f"{fmt}DecodeBytesDeclined"] = s["bytes_declined"]
    return out


def report() -> Dict[str, dict]:
    """Deep copy for human-facing reports (scale rig, bench artifacts)."""
    with _LOCK:
        return {fmt: {**s, "decline_reasons": dict(s["decline_reasons"])}
                for fmt, s in DECODE_STATS.items()}
