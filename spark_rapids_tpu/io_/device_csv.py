"""Device-side CSV decode (reference ``GpuCSVScan.scala:355`` —
``Table.readCSV`` takes a host buffer and parses on the GPU).  Same
architecture as the parquet/ORC decoders: the host does O(structure)
work ONLY — vectorized numpy scans for newline and delimiter positions —
and the device does the per-value work: field-byte gathers into matrices
(:func:`.device_parquet.gather_string_matrix`) and Spark-exact parsing
via the ``ops/cast_strings`` kernels (the CastStrings analog the cast
matrix already uses, so CSV-parsed and CAST-parsed values can never
disagree).

Decline-to-host discipline (pyarrow keeps serving what's outside the
envelope): quoted fields, custom null markers, multi-char separators,
CR/LF line endings, BOMs, blank interior lines, ragged rows — and any
file where a non-empty field fails to parse as the plan schema's type
(sample-based inference may have guessed a narrower type than the full
file supports; correctness beats the fast path).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar.batch import ColumnarBatch
from ..columnar.column import (DeviceColumn, bucket_capacity, bucket_width,
                               null_column)
from .device_parquet import (_buf_to_words, _max_string_matrix_bytes,
                             _pad_pow2, gather_string_matrix)


def decode_file(path: str, options: Dict, out_fields, tctx=None,
                conf=None, raw: Optional[bytes] = None
                ) -> Optional[ColumnarBatch]:
    """Decode one CSV file into a :class:`ColumnarBatch` typed by the
    plan's output fields, or ``None`` to decline to the host reader.
    Callers that already read the file pass ``raw`` so a decline does
    not re-read it from disk."""
    sep = str(options.get("sep", options.get("delimiter", ",")))
    if len(sep) != 1:
        return None
    if str(options.get("nullValue", "")) != "":
        return None  # custom null markers: host
    has_header = str(options.get("header", "true")).lower() == "true"

    if raw is None:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
    if not raw or raw.startswith(b"\xef\xbb\xbf"):
        return None
    buf = np.frombuffer(raw, np.uint8)
    if (buf == ord('"')).any() or (buf == 13).any():
        return None  # quoting / CRLF: host

    nl = np.flatnonzero(buf == 10)
    if raw[-1:] == b"\n":
        ends = nl.astype(np.int64)
    else:
        ends = np.append(nl, len(raw)).astype(np.int64)
    starts = np.concatenate([[0], nl + 1]).astype(np.int64)[:len(ends)]
    if len(starts) == 0 or (starts == ends).any():
        return None  # blank lines (Spark skips them): host
    if has_header:
        starts, ends = starts[1:], ends[1:]
    n = len(starts)
    if n == 0:
        return None

    ncols = len(out_fields)
    dp = np.flatnonzero(buf == ord(sep)).astype(np.int64)
    dp = dp[dp >= starts[0]]
    if ncols > 1:
        line_of = np.searchsorted(starts, dp, side="right") - 1
        counts = np.bincount(line_of, minlength=n)
        if not (counts == ncols - 1).all():
            return None  # ragged rows / stray delimiters: host
        dmat = dp.reshape(n, ncols - 1)
    else:
        if len(dp):
            return None  # separators in a single-column file
        dmat = np.zeros((n, 0), np.int64)
    col_starts = np.concatenate([starts[:, None], dmat + 1], axis=1)
    col_ends = np.concatenate([dmat, ends[:, None]], axis=1)
    col_lens = (col_ends - col_starts).astype(np.int32)

    capacity = bucket_capacity(n)
    max_bytes = _max_string_matrix_bytes(conf)
    words = _buf_to_words(raw)
    from ..ops import cast_strings as CS
    cols = []
    fail_counts = []
    for ci, fld in enumerate(out_fields):
        dt = fld.dtype if hasattr(fld, "dtype") else fld.data_type
        if isinstance(dt, T.NullType):
            cols.append(null_column(dt, capacity))
            continue
        lens_np = col_lens[:, ci]
        w = bucket_width(int(lens_np.max()))
        if capacity * w > max_bytes:
            return None  # ragged guard: the host path width-splits
        sp = np.zeros(capacity, np.int64)
        sp[:n] = col_starts[:, ci]
        lp = np.zeros(capacity, np.int32)
        lp[:n] = lens_np
        starts_d = jnp.asarray(sp)
        lens_d = jnp.asarray(lp)
        chars = gather_string_matrix(words, starts_d, lens_d, w, capacity)
        live = jnp.arange(capacity) < n
        present = (lens_d > 0) & live  # empty field = null (nullValue "")
        if isinstance(dt, (T.StringType, T.BinaryType)):
            cols.append(DeviceColumn(
                dt, chars, present,
                lengths=jnp.where(present, lens_d, 0)))
            continue
        if T.is_integral(dt):
            v, ok = CS.parse_long(jnp, chars, lens_d, present)
            if dt.np_dtype.itemsize < 8:
                info = np.iinfo(dt.np_dtype)
                ok = ok & (v >= int(info.min)) & (v <= int(info.max))
            data = v.astype(dt.np_dtype)
        elif isinstance(dt, (T.FloatType, T.DoubleType)):
            v, ok = CS.parse_double(jnp, chars, lens_d, present)
            data = v.astype(dt.np_dtype)
        elif isinstance(dt, T.BooleanType):
            data, ok = CS.parse_bool(jnp, chars, lens_d, present)
        elif isinstance(dt, T.DateType):
            data, ok = CS.parse_date(jnp, chars, lens_d, present)
        elif isinstance(dt, T.TimestampType):
            data, ok = CS.parse_timestamp(jnp, chars, lens_d, present)
        elif isinstance(dt, T.DecimalType) and dt.is_long_backed:
            data, ok = CS.parse_decimal(jnp, chars, lens_d, present,
                                        dt.precision, dt.scale)
        elif isinstance(dt, T.DecimalType):
            lo, hi, ok = CS.parse_decimal128(jnp, chars, lens_d, present,
                                             dt.precision, dt.scale)
            fail_counts.append(jnp.sum(present & ~ok))
            cols.append(DeviceColumn(dt, lo, ok & present, aux=hi))
            continue
        else:
            return None  # nested/unsupported plan type
        # a NON-EMPTY field the parser rejected means the plan's
        # (sample-inferred) type doesn't fit the full file — decline
        fail_counts.append(jnp.sum(present & ~ok))
        valid = ok & present
        cols.append(DeviceColumn(dt, jnp.where(valid, data, 0), valid))

    if fail_counts:
        total = int(jnp.stack(fail_counts).sum())
        if total:
            if tctx is not None:
                tctx.inc_metric("csvDeviceParseDeclines")
            return None
    if tctx is not None:
        tctx.inc_metric("csvDeviceDecodedFiles")
    names = [f.name for f in out_fields]
    return ColumnarBatch.make(tuple(names), cols, n)
