"""Device-side JSON-lines decode (reference ``GpuJsonScan`` riding
``GpuTextBasedPartitionReader.scala`` — ``Table.readJSON`` takes a host
buffer and parses on the GPU).  Same architecture as the CSV decoder
(:mod:`.device_csv`): the host does O(structure) work ONLY — vectorized
numpy scans locating quote spans (by quote-count parity), the structural
colons/commas/braces that sit OUTSIDE strings, and from them the key and
value byte spans per row — and the device does the per-value work: value
bytes gather into matrices (:func:`.device_parquet.gather_string_matrix`)
and parse through the Spark-exact ``ops/cast_strings`` kernels, so
JSON-parsed and CAST-parsed values can never disagree.

Decline-to-host discipline (pyarrow keeps serving what's outside the
envelope): any backslash escape, nested objects/arrays, single-quote
syntax, multiLine mode, CRLF/BOM, blank interior lines, non-object rows,
duplicate keys, malformed token structure, non-numeric number tokens
(``NaN``/``Infinity``/``-inf`` — the ``allowNonNumericNumbers`` surface
stays host-side; number spans are checked against the JSON number
character set so the permissive cast parsers can never see them) — and
any present value that fails to parse as the plan schema's type.  One
deliberate permissive edge vs strict Jackson: leading zeros in integers
parse rather than erroring.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..columnar.batch import ColumnarBatch
from ..columnar.column import (DeviceColumn, bucket_capacity, bucket_width,
                               null_column)
from .device_parquet import (_buf_to_words, _max_string_matrix_bytes,
                             gather_string_matrix)

_QUOTE, _COLON, _COMMA = ord('"'), ord(':'), ord(',')
_OBRACE, _CBRACE, _OBRACKET = ord('{'), ord('}'), ord('[')
_SPACE, _TAB, _NL = 32, 9, 10

#: value-token classes (host-side classification of the trimmed span)
_NUMBER, _STRING, _TRUE, _FALSE, _NULL = 0, 1, 2, 3, 4


def _in_string(q: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """True where ``pos`` falls strictly inside a quoted span.  With no
    escapes in the file (declined earlier), quotes strictly alternate
    open/close, so a position after an odd number of quotes is inside."""
    return (np.searchsorted(q, pos) % 2) == 1


def _structural(buf: np.ndarray, q: np.ndarray, byte: int) -> np.ndarray:
    pos = np.flatnonzero(buf == byte).astype(np.int64)
    return pos[~_in_string(q, pos)]


def _trim(buf: np.ndarray, vs: np.ndarray, ve: np.ndarray):
    """Trim spaces/tabs from both ends of half-open spans [vs, ve) —
    bounded iteration (each pass is one vectorized step; >32 pad spaces
    around a JSON value does not occur in machine-written data, and the
    caller declines if any span still starts/ends with whitespace)."""
    for _ in range(32):
        lead = (vs < ve) & np.isin(buf[np.minimum(vs, len(buf) - 1)],
                                   (_SPACE, _TAB))
        if not lead.any():
            break
        vs = vs + lead
    for _ in range(32):
        trail = (vs < ve) & np.isin(
            buf[np.maximum(ve - 1, 0)], (_SPACE, _TAB))
        if not trail.any():
            break
        ve = ve - trail
    return vs, ve


#: vectorized DFA over the JSON number grammar
#: ``-?\d+(\.\d+)?([eE][+-]?\d+)?`` — the strict production with the
#: integer part relaxed from ``(0|[1-9]\d*)`` to ``\d+``, the module's one
#: documented permissive edge (leading zeros in integers parse).  Rejects
#: everything else the permissive cast parsers would otherwise accept
#: where the host oracle errors: ``12.``, ``-.5``, ``1.e3``, ``-inf``,
#: bare ``-``, ``+5`` never reaches here (lead byte check).
#: states: 0 START, 1 SIGN, 2 INT(accept), 3 DOT, 4 FRAC(accept),
#: 5 EXP, 6 ESIGN, 7 EDIG(accept), 8 ERR
_NUM_DIGIT = np.array([2, 2, 2, 4, 4, 7, 7, 7, 8], np.int8)
_NUM_MINUS = np.array([1, 8, 8, 8, 8, 6, 8, 8, 8], np.int8)
_NUM_PLUS = np.array([8, 8, 8, 8, 8, 6, 8, 8, 8], np.int8)
_NUM_DOT = np.array([8, 8, 3, 8, 8, 8, 8, 8, 8], np.int8)
_NUM_E = np.array([8, 8, 5, 8, 5, 8, 8, 8, 8], np.int8)


def _number_grammar_ok(buf: np.ndarray, vs: np.ndarray,
                       ve: np.ndarray) -> bool:
    """True when every [vs, ve) span matches the JSON number grammar
    (vectorized: one table-lookup DFA step per byte column)."""
    lens = ve - vs
    w = int(lens.max())
    pos = np.minimum(vs[:, None] + np.arange(w), len(buf) - 1)
    b = buf[pos]
    live = np.arange(w)[None, :] < lens[:, None]
    state = np.zeros(len(vs), np.int8)
    for j in range(w):
        bj = b[:, j]
        ns = np.where((bj >= ord("0")) & (bj <= ord("9")),
                      _NUM_DIGIT[state], np.int8(8))
        ns = np.where(bj == ord("-"), _NUM_MINUS[state], ns)
        ns = np.where(bj == ord("+"), _NUM_PLUS[state], ns)
        ns = np.where(bj == ord("."), _NUM_DOT[state], ns)
        ns = np.where((bj == ord("e")) | (bj == ord("E")),
                      _NUM_E[state], ns)
        state = np.where(live[:, j], ns, state)
    return bool(np.isin(state, (2, 4, 7)).all())


def decode_file(path: str, options: Dict, out_fields, tctx=None,
                conf=None, raw: Optional[bytes] = None
                ) -> Optional[ColumnarBatch]:
    """Decode one JSON-lines file into a :class:`ColumnarBatch` typed by
    the plan's output fields, or ``None`` to decline to the host reader.
    Callers that already read the file pass ``raw`` so a decline does
    not re-read it from disk."""
    if str(options.get("multiLine", "false")).lower() == "true":
        return None
    if str(options.get("allowComments", "false")).lower() == "true":
        return None
    if str(options.get("primitivesAsString", "false")).lower() == "true":
        return None

    if raw is None:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
    if not raw or raw.startswith(b"\xef\xbb\xbf"):
        return None
    if b"\\" in raw or b"\r" in raw:
        return None  # escapes / CRLF: host
    buf = np.frombuffer(raw, np.uint8)

    q = np.flatnonzero(buf == _QUOTE).astype(np.int64)
    if len(q) % 2:
        return None  # unbalanced quotes
    # raw newline inside a string is invalid JSON anyway: host decides
    nl = np.flatnonzero(buf == _NL).astype(np.int64)
    if _in_string(q, nl).any():
        return None
    if raw[-1:] == b"\n":
        ends = nl
    else:
        ends = np.append(nl, len(raw)).astype(np.int64)
    starts = np.concatenate([[0], nl + 1]).astype(np.int64)[:len(ends)]
    if len(starts) == 0 or (starts == ends).any():
        return None  # blank lines: host
    n = len(starts)
    # every row must be exactly {...} with no padding around the braces
    if (buf[starts] != _OBRACE).any() or (buf[ends - 1] != _CBRACE).any():
        return None

    if len(_structural(buf, q, _OBRACKET)):
        return None  # arrays: host
    if len(_structural(buf, q, ord("'"))):
        return None  # single-quote syntax (allowSingleQuotes): host
    obr = _structural(buf, q, _OBRACE)
    cbr = _structural(buf, q, _CBRACE)
    if not (np.array_equal(obr, starts) and np.array_equal(cbr, ends - 1)):
        return None  # nested objects / stray braces: host
    colons = _structural(buf, q, _COLON)
    commas = _structural(buf, q, _COMMA)

    # nonspace prefix sums: NS[b] - NS[a] = nonspace count in [a, b)
    NS = np.concatenate(
        [[0], np.cumsum(~np.isin(buf, (_SPACE, _TAB)))]).astype(np.int64)

    def all_space(a, b):  # vectorized over span arrays
        return NS[b] - NS[a] == 0

    # ---- keys: the quote pair immediately before each structural colon
    qi = np.searchsorted(q, colons)
    if (qi < 2).any():
        return None
    kclose = q[qi - 1]
    kopen = q[qi - 2]
    if not all_space(kclose + 1, colons).all():
        return None  # junk between key close-quote and colon
    # the token before each key's open quote must be '{' or ',' —
    # catches missing commas ({"a":1 "b":2}) and leading commas
    ts = np.sort(np.concatenate([obr, cbr, colons, commas]))
    pi = np.searchsorted(ts, kopen) - 1
    if (pi < 0).any():
        return None
    pred = ts[pi]
    if (~np.isin(buf[pred], (_OBRACE, _COMMA))).any():
        return None
    if not all_space(pred + 1, kopen).all():
        return None
    # every comma must introduce a key (no trailing/dangling commas)
    if not np.array_equal(np.unique(pred[buf[pred] == _COMMA]), commas):
        return None

    line_of = np.searchsorted(starts, colons, side="right") - 1
    # duplicate keys: Jackson keeps the LAST occurrence, Spark flags the
    # row — decline so the host oracle decides.  Checked across ALL keys
    # per row, not just the pruned plan schema's: a duplicate of a pruned
    # column still makes the row's answer host-semantics-dependent
    if len(colons):
        klen_all = (q[np.searchsorted(q, colons) - 1]
                    - q[np.searchsorted(q, colons) - 2] - 1)
        kstart_all = q[np.searchsorted(q, colons) - 2] + 1
        wk = max(int(klen_all.max()), 1)
        kb = buf[np.minimum(kstart_all[:, None] + np.arange(wk),
                            len(buf) - 1)]
        kb = np.where(np.arange(wk)[None, :] < klen_all[:, None], kb, 0)
        rec = np.concatenate(
            [line_of[:, None], klen_all[:, None],
             kb.astype(np.int64)], axis=1)
        if len(np.unique(rec, axis=0)) < len(colons):
            return None
    # empty-object rows ({} / {  }) are valid: all columns null there
    ncolons = np.bincount(line_of, minlength=n)
    empty_rows = np.flatnonzero(ncolons == 0)
    if len(empty_rows) and not all_space(starts[empty_rows] + 1,
                                         ends[empty_rows] - 1).all():
        return None

    # ---- values: colon+1 up to the next structural comma / close brace
    term = np.sort(np.concatenate([commas, cbr]))
    tix = np.searchsorted(term, colons)
    if (tix >= len(term)).any():
        return None
    vend = term[tix]
    vs, ve = _trim(buf, colons + 1, vend)
    if (vs >= ve).any():
        return None  # empty value
    lead = buf[vs]
    trail = buf[ve - 1]
    if np.isin(lead, (_SPACE, _TAB)).any() or \
            np.isin(trail, (_SPACE, _TAB)).any():
        return None  # >32 pad spaces: outside the envelope

    # classify each value span
    cls = np.full(len(colons), -1, np.int8)
    is_num = ((lead >= ord("0")) & (lead <= ord("9"))) | (lead == ord("-"))
    if is_num.any() and not _number_grammar_ok(buf, vs[is_num], ve[is_num]):
        return None
    cls[is_num] = _NUMBER
    quoted = lead == _QUOTE
    if quoted.any():
        sq = np.searchsorted(q, vs[quoted])
        okq = ((sq % 2 == 0) & (sq + 1 < len(q)) & (q[sq] == vs[quoted])
               & (q[sq + 1] == ve[quoted] - 1))
        if not okq.all():
            return None  # value not exactly one quoted span
        cls[quoted] = _STRING
    lit = ~is_num & ~quoted
    if lit.any():
        lvs, lve = vs[lit], ve[lit]
        llen = lve - lvs
        five = buf[np.minimum(lvs[:, None] + np.arange(5), len(buf) - 1)]
        m_true = (llen == 4) & (five[:, :4] == np.frombuffer(
            b"true", np.uint8)).all(1)
        m_false = (llen == 5) & (five == np.frombuffer(
            b"false", np.uint8)).all(1)
        m_null = (llen == 4) & (five[:, :4] == np.frombuffer(
            b"null", np.uint8)).all(1)
        if not (m_true | m_false | m_null).all():
            return None  # bare token that is not true/false/null
        sub = np.full(len(lvs), _NULL, np.int8)
        sub[m_true] = _TRUE
        sub[m_false] = _FALSE
        cls[lit] = sub
    # string content spans exclude the quotes
    vs = np.where(cls == _STRING, vs + 1, vs)
    ve = np.where(cls == _STRING, ve - 1, ve)

    # ---- key -> column matching
    klen = kclose - kopen - 1
    kstart = kopen + 1
    names = [f.name for f in out_fields]
    maxk = max((len(s.encode()) for s in names), default=1) or 1
    kbytes = buf[np.minimum(kstart[:, None] + np.arange(maxk),
                            len(buf) - 1)]

    capacity = bucket_capacity(n)
    max_bytes = _max_string_matrix_bytes(conf)
    words = _buf_to_words(raw)
    from ..ops import cast_strings as CS
    cols = []
    fail_counts = []
    for fld in out_fields:
        dt = fld.dtype if hasattr(fld, "dtype") else fld.data_type
        nb = np.frombuffer(fld.name.encode(), np.uint8)
        if len(nb) == 0 or len(nb) > maxk:
            return None
        hit = (klen == len(nb)) & (
            kbytes[:, :len(nb)] == nb[None, :]).all(1)
        rows = line_of[hit]  # duplicates already declined (all-key check)
        if isinstance(dt, T.NullType):
            if (cls[hit] != _NULL).any():
                return None  # inferred all-null column has a value
            cols.append(null_column(dt, capacity))
            continue
        vcls = np.full(n, _NULL, np.int8)
        vcls[rows] = cls[hit]
        starts_np = np.zeros(n, np.int64)
        starts_np[rows] = vs[hit]
        lens_np = np.zeros(n, np.int64)
        lens_np[rows] = (ve - vs)[hit]
        present_np = vcls != _NULL

        # per-type token-class envelope (Jackson/Spark semantics: a
        # wrong-class token is a corrupt record, so: host)
        if isinstance(dt, T.StringType):
            want = vcls == _STRING
        elif isinstance(dt, T.BooleanType):
            want = (vcls == _TRUE) | (vcls == _FALSE)
        elif isinstance(dt, (T.DateType, T.TimestampType)):
            want = vcls == _STRING
        elif T.is_integral(dt) or isinstance(
                dt, (T.FloatType, T.DoubleType, T.DecimalType)):
            want = vcls == _NUMBER
        else:
            return None  # nested/unsupported plan type
        if (present_np & ~want).any():
            if tctx is not None:
                tctx.inc_metric("jsonDeviceParseDeclines")
            return None

        w = bucket_width(int(lens_np.max()) if len(rows) else 1)
        if capacity * w > max_bytes:
            return None  # ragged guard: the host path width-splits
        sp = np.zeros(capacity, np.int64)
        sp[:n] = starts_np
        lp = np.zeros(capacity, np.int32)
        lp[:n] = lens_np
        pv = np.zeros(capacity, bool)
        pv[:n] = present_np
        starts_d = jnp.asarray(sp)
        lens_d = jnp.asarray(lp)
        present = jnp.asarray(pv)
        chars = gather_string_matrix(words, starts_d, lens_d, w, capacity)
        if isinstance(dt, T.StringType):
            cols.append(DeviceColumn(
                dt, chars, present,
                lengths=jnp.where(present, lens_d, 0)))
            continue
        if T.is_integral(dt):
            v, ok = CS.parse_long(jnp, chars, lens_d, present)
            if dt.np_dtype.itemsize < 8:
                info = np.iinfo(dt.np_dtype)
                ok = ok & (v >= int(info.min)) & (v <= int(info.max))
            data = v.astype(dt.np_dtype)
        elif isinstance(dt, (T.FloatType, T.DoubleType)):
            v, ok = CS.parse_double(jnp, chars, lens_d, present)
            data = v.astype(dt.np_dtype)
        elif isinstance(dt, T.BooleanType):
            data, ok = CS.parse_bool(jnp, chars, lens_d, present)
        elif isinstance(dt, T.DateType):
            data, ok = CS.parse_date(jnp, chars, lens_d, present)
        elif isinstance(dt, T.TimestampType):
            data, ok = CS.parse_timestamp(jnp, chars, lens_d, present)
        elif isinstance(dt, T.DecimalType) and dt.is_long_backed:
            data, ok = CS.parse_decimal(jnp, chars, lens_d, present,
                                        dt.precision, dt.scale)
        else:  # decimal128
            lo, hi, ok = CS.parse_decimal128(jnp, chars, lens_d, present,
                                             dt.precision, dt.scale)
            fail_counts.append(jnp.sum(present & ~ok))
            cols.append(DeviceColumn(dt, lo, ok & present, aux=hi))
            continue
        # a present value the parser rejected means the plan's type
        # doesn't fit the data — decline, never null-fill
        fail_counts.append(jnp.sum(present & ~ok))
        valid = ok & present
        cols.append(DeviceColumn(dt, jnp.where(valid, data, 0), valid))

    if fail_counts:
        total = int(jnp.stack(fail_counts).sum())
        if total:
            if tctx is not None:
                tctx.inc_metric("jsonDeviceParseDeclines")
            return None
    if tctx is not None:
        tctx.inc_metric("jsonDeviceDecodedFiles")
    return ColumnarBatch.make(tuple(names), cols, n)
