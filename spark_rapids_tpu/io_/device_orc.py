"""Device-side ORC decode (reference ``GpuOrcScan.scala:893`` —
``Table.readORC`` takes a host buffer and decodes stripes on the GPU;
2726-LoC file).  Same architecture as :mod:`.device_parquet`: the host
parses *structure* (protobuf postscript/footer/stripe footers, compression
block framing, RLE run headers — all O(metadata)) and builds run-descriptor
tables; compiled XLA programs then do the per-value work on device:
MSB-first bit unpacking, zigzag decode, DELTA prefix sums, PRESENT bit
expansion, null scatter, dictionary remap and string-matrix gather.

Scope (per-column decline-to-host, like the parquet decoder's envelope):

  * types: boolean, tinyint..bigint, float, double, date, string/binary/
    varchar/char (DIRECT_V2 and DICTIONARY_V2);
  * integer RLEv2 sub-encodings SHORT_REPEAT / DIRECT / DELTA
    (PATCHED_BASE declines the column — rare: only outlier-heavy data);
  * compression NONE / ZLIB / SNAPPY / ZSTD (LZO/LZ4 decline the file);
  * timestamps, decimals, nested types, RLEv1 (pre-hive-0.12 writers)
    decline per column and ride the host pyarrow read.

Floats note: ORC stores IEEE little-endian raw streams — already the
device layout, so "decode" is a zero-copy host view plus the normal
upload; the device still does the null scatter.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device_parquet import _pad_pow2, _scatter_nonnull, _Unsupported

# --------------------------------------------------------------------------
# Minimal protobuf wire reader (hand-rolled, like device_parquet's thrift)
# --------------------------------------------------------------------------


class _ProtoReader:
    """Protobuf wire-format walker: yields (field_number, wire_type, value)
    where value is int (varint/fixed) or memoryview (length-delimited)."""

    def __init__(self, buf, pos: int = 0, end: Optional[int] = None):
        self.buf = memoryview(buf)
        self.pos = pos
        self.end = len(buf) if end is None else end

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def fields(self):
        while self.pos < self.end:
            key = self.varint()
            fid, wt = key >> 3, key & 7
            if wt == 0:
                yield fid, wt, self.varint()
            elif wt == 1:
                v = struct.unpack_from("<Q", self.buf, self.pos)[0]
                self.pos += 8
                yield fid, wt, v
            elif wt == 2:
                ln = self.varint()
                v = self.buf[self.pos:self.pos + ln]
                self.pos += ln
                yield fid, wt, v
            elif wt == 5:
                v = struct.unpack_from("<I", self.buf, self.pos)[0]
                self.pos += 4
                yield fid, wt, v
            else:
                raise _Unsupported(f"proto wire type {wt}")


def _packed_uints(mv) -> List[int]:
    r = _ProtoReader(mv)
    out = []
    while r.pos < r.end:
        out.append(r.varint())
    return out


@dataclass
class _Stripe:
    offset: int = 0
    index_length: int = 0
    data_length: int = 0
    footer_length: int = 0
    num_rows: int = 0


@dataclass
class _OrcType:
    kind: int = 0
    subtypes: List[int] = field(default_factory=list)
    field_names: List[str] = field(default_factory=list)


_COMPRESSION = {0: None, 1: "zlib", 2: "snappy", 3: "lzo", 4: "lz4",
                5: "zstd"}

_KIND_BOOLEAN, _KIND_BYTE, _KIND_SHORT, _KIND_INT, _KIND_LONG = 0, 1, 2, 3, 4
_KIND_FLOAT, _KIND_DOUBLE, _KIND_STRING, _KIND_BINARY = 5, 6, 7, 8
_KIND_DATE, _KIND_VARCHAR, _KIND_CHAR = 15, 16, 17

_STREAM_PRESENT, _STREAM_DATA, _STREAM_LENGTH = 0, 1, 2
_STREAM_DICTIONARY_DATA = 3
_ENC_DIRECT, _ENC_DICTIONARY, _ENC_DIRECT_V2, _ENC_DICTIONARY_V2 = 0, 1, 2, 3


def _parse_postscript(buf: bytes) -> Tuple[int, Optional[str], int, int]:
    """(footer_length, codec, compression_block_size, metadata_length)."""
    footer_len = comp = block = meta_len = 0
    for fid, _wt, v in _ProtoReader(buf).fields():
        if fid == 1:
            footer_len = v
        elif fid == 2:
            comp = v
        elif fid == 3:
            block = v
        elif fid == 5:
            meta_len = v
    if comp not in _COMPRESSION or _COMPRESSION[comp] in ("lzo", "lz4"):
        raise _Unsupported(f"ORC compression kind {comp}")
    return footer_len, _COMPRESSION[comp], block or 262144, meta_len


def _parse_footer(buf) -> Tuple[List[_Stripe], List[_OrcType], int]:
    stripes: List[_Stripe] = []
    types: List[_OrcType] = []
    num_rows = 0
    for fid, _wt, v in _ProtoReader(buf).fields():
        if fid == 3:
            s = _Stripe()
            for f2, _w2, v2 in _ProtoReader(v).fields():
                if f2 == 1:
                    s.offset = v2
                elif f2 == 2:
                    s.index_length = v2
                elif f2 == 3:
                    s.data_length = v2
                elif f2 == 4:
                    s.footer_length = v2
                elif f2 == 5:
                    s.num_rows = v2
            stripes.append(s)
        elif fid == 4:
            t = _OrcType()
            for f2, w2, v2 in _ProtoReader(v).fields():
                if f2 == 1:
                    t.kind = v2
                elif f2 == 2:
                    if w2 == 2:
                        t.subtypes.extend(_packed_uints(v2))
                    else:
                        t.subtypes.append(v2)
                elif f2 == 3:
                    t.field_names.append(bytes(v2).decode())
            types.append(t)
        elif fid == 6:
            num_rows = v
    return stripes, types, num_rows


@dataclass
class _StreamInfo:
    kind: int
    column: int
    length: int
    offset: int  # absolute file offset


def _parse_stripe_footer(buf, stripe: _Stripe
                         ) -> Tuple[List[_StreamInfo], Dict[int, Tuple[int, int]]]:
    """(streams with absolute offsets, {column: (encoding, dict_size)})."""
    streams: List[_StreamInfo] = []
    encodings: Dict[int, Tuple[int, int]] = {}
    col_i = 0
    pos = stripe.offset
    for fid, _wt, v in _ProtoReader(buf).fields():
        if fid == 1:
            kind = column = length = 0
            for f2, _w2, v2 in _ProtoReader(v).fields():
                if f2 == 1:
                    kind = v2
                elif f2 == 2:
                    column = v2
                elif f2 == 3:
                    length = v2
            streams.append(_StreamInfo(kind, column, length, pos))
            pos += length
        elif fid == 2:
            enc = dict_size = 0
            for f2, _w2, v2 in _ProtoReader(v).fields():
                if f2 == 1:
                    enc = v2
                elif f2 == 2:
                    dict_size = v2
            encodings[col_i] = (enc, dict_size)
            col_i += 1
    return streams, encodings


# --------------------------------------------------------------------------
# Compression block framing (per stream)
# --------------------------------------------------------------------------

def _decompress_stream(raw: bytes, codec: Optional[str]) -> bytes:
    if codec is None:
        return raw
    out = []
    pos = 0
    n = len(raw)
    while pos + 3 <= n:
        h = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        is_original = h & 1
        ln = h >> 1
        chunk = raw[pos:pos + ln]
        pos += ln
        if is_original:
            out.append(chunk)
        elif codec == "zlib":
            out.append(zlib.decompress(chunk, wbits=-15))
        elif codec == "snappy":
            import pyarrow as pa
            # raw snappy's preamble is the uncompressed length (uleb128),
            # which pyarrow wants passed explicitly
            size, _p = _read_varint(chunk, 0)
            out.append(pa.Codec("snappy").decompress(
                chunk, decompressed_size=size).to_pybytes())
        elif codec == "zstd":
            import zstandard
            out.append(zstandard.ZstdDecompressor().decompress(
                chunk, max_output_size=1 << 26))
        else:  # pragma: no cover - gated at postscript parse
            raise _Unsupported(f"codec {codec}")
    return b"".join(out)


# --------------------------------------------------------------------------
# Host walks: byte-RLE and RLEv2 -> run/segment descriptors
# --------------------------------------------------------------------------

_MAX_RUNS = 1 << 18  # structure-vs-data guard, like device_parquet


@dataclass
class _MsbRuns:
    """RLE/packed descriptors for the MSB expansion kernel (ORC packs
    values MSB-first, unlike parquet's LSB hybrid)."""

    out_start: List[int] = field(default_factory=list)
    src_bit: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)
    rle_val: List[int] = field(default_factory=list)

    def add_rle(self, out_start: int, value: int) -> None:
        self.out_start.append(out_start)
        self.src_bit.append(0)
        self.width.append(0)
        self.rle_val.append(value)

    def add_packed(self, out_start: int, src_bit: int, width: int) -> None:
        self.out_start.append(out_start)
        self.src_bit.append(src_bit)
        self.width.append(width)
        self.rle_val.append(0)

    def __len__(self) -> int:
        return len(self.out_start)


@dataclass
class _DeltaSegs:
    out_start: List[int] = field(default_factory=list)
    count: List[int] = field(default_factory=list)
    base: List[int] = field(default_factory=list)
    delta0: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)
    src_bit: List[int] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.out_start)


#: RLEv2 5-bit width code -> actual bit width ("closest fixed bits")
_FBS = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
        19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48, 56, 64]


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _walk_rlev2(buf, start: int, end: int, num_values: int, signed: bool,
                out_base: int, base_bit: int, runs: _MsbRuns,
                deltas: _DeltaSegs) -> None:
    """Walk RLEv2 run headers in ``buf[start:end)`` covering ``num_values``
    values.  SHORT_REPEAT/DIRECT append to ``runs`` (device unpacks and,
    for signed streams, zigzag-decodes); DELTA appends ready-to-sum
    segments (base/delta0 decoded host-side — they are per-run varints,
    i.e. structure, not data)."""
    pos = start
    produced = 0
    while produced < num_values and pos < end:
        if len(runs) + len(deltas) > _MAX_RUNS:
            raise _Unsupported("ORC run count guard")
        h = buf[pos]
        enc = h >> 6
        if enc == 0:                              # SHORT_REPEAT
            nbytes = ((h >> 3) & 0x7) + 1
            count = (h & 0x7) + 3
            val = int.from_bytes(bytes(buf[pos + 1:pos + 1 + nbytes]),
                                 "big")
            runs.add_rle(out_base + produced, val)
            pos += 1 + nbytes
            produced += count
        elif enc == 1:                            # DIRECT
            width = _FBS[(h >> 1) & 0x1F]
            count = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            runs.add_packed(out_base + produced,
                            base_bit + (pos - start) * 8, width)
            pos += (count * width + 7) // 8
            produced += count
        elif enc == 3:                            # DELTA
            wcode = (h >> 1) & 0x1F
            width = 0 if wcode == 0 else _FBS[wcode]
            count = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            if signed:
                raw, pos = _read_varint(buf, pos)
                base = _zigzag(raw)
            else:
                base, pos = _read_varint(buf, pos)
            raw, pos = _read_varint(buf, pos)
            delta0 = _zigzag(raw)
            deltas.out_start.append(out_base + produced)
            deltas.count.append(count)
            deltas.base.append(base)
            deltas.delta0.append(delta0)
            deltas.width.append(width)
            deltas.src_bit.append(base_bit + (pos - start) * 8)
            if width and count > 2:
                pos += ((count - 2) * width + 7) // 8
            produced += count
        else:                                     # PATCHED_BASE
            raise _Unsupported("RLEv2 PATCHED_BASE")
    if produced < num_values:
        raise _Unsupported("short RLEv2 stream")


def _popcount_msb_prefix(value: int, k: int) -> int:
    """Set bits among the first ``k`` MSB-first bits of a byte."""
    return bin(value >> (8 - k)).count("1") if k else 0


def _walk_byte_rle(buf, start: int, end: int, num_bytes: int,
                   out_base: int, base_bit: int, runs: _MsbRuns,
                   count_bits_upto: Optional[int] = None) -> int:
    """Byte-RLE (PRESENT / boolean / tinyint streams).  Byte-aligned, so
    the MSB/LSB distinction vanishes and runs reuse the same expansion
    kernel with width=8.  When ``count_bits_upto`` is given, also counts
    the set bits among the first that-many bits (MSB-first within each
    byte) — the PRESENT non-null count, in the same walk."""
    pos = start
    produced = 0
    bits = 0
    nbits = count_bits_upto or 0

    def _count(value: int, byte_lo: int, byte_hi: int) -> int:
        if not count_bits_upto:
            return 0
        full_end = min(byte_hi, nbits // 8)
        got = 0
        if full_end > byte_lo:
            got += bin(value).count("1") * (full_end - byte_lo)
        if byte_lo <= nbits // 8 < byte_hi and nbits % 8:
            got += _popcount_msb_prefix(value, nbits % 8)
        return got

    while produced < num_bytes and pos < end:
        if len(runs) > _MAX_RUNS:
            raise _Unsupported("ORC run count guard")
        c = buf[pos]
        pos += 1
        if c < 128:                               # run
            count = min(c + 3, num_bytes - produced)
            val = buf[pos]
            runs.add_rle(out_base + produced, val)
            bits += _count(val, produced, produced + count)
            pos += 1
            produced += count
        else:                                     # literals
            count = min(256 - c, num_bytes - produced)
            runs.add_packed(out_base + produced,
                            base_bit + (pos - start) * 8, 8)
            if count_bits_upto:
                for k in range(count):
                    bits += _count(buf[pos + k], produced + k,
                                   produced + k + 1)
            pos += count
            produced += count
    if produced < num_bytes:
        raise _Unsupported("short byte-RLE stream")
    return bits


def _host_rlev2(buf, start: int, end: int, n: int, signed: bool
                ) -> np.ndarray:
    """Host expansion of a small RLEv2 stream (string LENGTH streams and
    stripe dictionaries: O(n) numpy with per-run vector ops — these
    streams are tiny next to the data they describe)."""
    out = np.zeros(n, dtype=np.int64)
    pos = start
    produced = 0
    while produced < n and pos < end:
        h = buf[pos]
        enc = h >> 6
        if enc == 0:
            nbytes = ((h >> 3) & 0x7) + 1
            count = min((h & 0x7) + 3, n - produced)
            val = int.from_bytes(bytes(buf[pos + 1:pos + 1 + nbytes]),
                                 "big")
            if signed:
                val = _zigzag(val)
            out[produced:produced + count] = val
            pos += 1 + nbytes
            produced += count
        elif enc == 1:
            width = _FBS[(h >> 1) & 0x1F]
            count = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            nbytes = (count * width + 7) // 8
            chunk = np.frombuffer(buf, np.uint8, nbytes, pos)
            vals = _unpack_msb_host(chunk, count, width)
            if signed:
                vals = (vals >> 1) ^ -(vals & 1)
            take = min(count, n - produced)
            out[produced:produced + take] = vals[:take]
            pos += nbytes
            produced += take
        elif enc == 3:
            wcode = (h >> 1) & 0x1F
            width = 0 if wcode == 0 else _FBS[wcode]
            count = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            if signed:
                raw, pos = _read_varint(buf, pos)
                base = _zigzag(raw)
            else:
                base, pos = _read_varint(buf, pos)
            raw, pos = _read_varint(buf, pos)
            delta0 = _zigzag(raw)
            vals = np.zeros(count, dtype=np.int64)
            vals[0] = base
            if count > 1:
                inc = np.zeros(count, dtype=np.int64)
                inc[1] = delta0
                if count > 2:
                    if width:
                        nbytes = ((count - 2) * width + 7) // 8
                        chunk = np.frombuffer(buf, np.uint8, nbytes, pos)
                        mags = _unpack_msb_host(chunk, count - 2, width)
                        pos += nbytes
                    else:
                        mags = np.full(count - 2, abs(delta0),
                                       dtype=np.int64)
                    inc[2:] = np.where(delta0 < 0, -mags, mags)
                vals = base + np.cumsum(inc)
            take = min(count, n - produced)
            out[produced:produced + take] = vals[:take]
            produced += take
        else:
            raise _Unsupported("RLEv2 PATCHED_BASE")
    if produced < n:
        raise _Unsupported("short RLEv2 stream")
    return out


def _unpack_msb_host(chunk: np.ndarray, count: int, width: int
                     ) -> np.ndarray:
    bits = np.unpackbits(chunk)  # MSB-first by default
    take = bits[:count * width].reshape(count, width).astype(np.int64)
    weights = (1 << np.arange(width - 1, -1, -1)).astype(np.int64)
    return take @ weights


# --------------------------------------------------------------------------
# Device kernels
# --------------------------------------------------------------------------

from .device_parquet import byte_at_words as _byte_at  # shared kernel


def _win32_msb(words, bitpos):
    """32 MSB-first bits starting at absolute bit ``bitpos`` (traced):
    five consecutive stream bytes assembled big-endian, then shifted."""
    q = (bitpos >> 3).astype(jnp.int64)
    r = (bitpos & 7).astype(jnp.uint64)
    acc = jnp.zeros(bitpos.shape, jnp.uint64)
    for k in range(5):
        acc = (acc << jnp.uint64(8)) | _byte_at(words, q + k).astype(jnp.uint64)
    return (acc >> (jnp.uint64(8) - r)) & jnp.uint64(0xFFFFFFFF)


@partial(jax.jit, static_argnames=("out_cap",))
def _expand_runs_msb(words, out_start, src_bit, width, rle_val, out_cap):
    """ORC MSB-first run expansion -> uint64 raw values (width <= 64).
    RLE runs broadcast; packed runs window-read.  Tail values past the
    last run are garbage — callers mask by row count."""
    idx = jnp.arange(out_cap, dtype=jnp.int32)
    r = jnp.clip(jnp.searchsorted(out_start, idx, side="right") - 1,
                 0, out_start.shape[0] - 1)
    local = (idx - out_start[r]).astype(jnp.int64)
    w = width[r].astype(jnp.int64)
    bitpos = src_bit[r] + local * w
    hi = _win32_msb(words, bitpos)
    lo = _win32_msb(words, bitpos + 32)
    v64 = (hi << jnp.uint64(32)) | lo
    wshift = jnp.uint64(64) - w.astype(jnp.uint64)
    raw = v64 >> wshift
    return jnp.where(w == 0, rle_val[r].astype(jnp.uint64), raw)


@jax.jit
def _zigzag_device(u):
    from ..columnar.convert import u64_to_i64
    half = (u >> jnp.uint64(1)).astype(jnp.int64)
    return jnp.where((u & jnp.uint64(1)) > 0, -half - 1, half)


def _u64_as_i64(u):
    from ..columnar.convert import u64_to_i64
    return u64_to_i64(u)


@partial(jax.jit, static_argnames=("out_cap",))
def _expand_delta(words, out_start, count, base, delta0, width, src_bit,
                  out_cap):
    """DELTA segments -> int64 values via one global cumsum: increment 0
    at each segment head, delta0 at local 1, sign(delta0)*|packed| after;
    value = base[seg] + (c[i] - c[seg head])."""
    idx = jnp.arange(out_cap, dtype=jnp.int32)
    s = jnp.clip(jnp.searchsorted(out_start, idx, side="right") - 1,
                 0, out_start.shape[0] - 1)
    local = (idx - out_start[s]).astype(jnp.int64)
    w = width[s].astype(jnp.int64)
    bitpos = src_bit[s] + jnp.maximum(local - 2, 0) * w
    hi = _win32_msb(words, bitpos)
    lo = _win32_msb(words, bitpos + 32)
    raw = ((hi << jnp.uint64(32)) | lo) >> (jnp.uint64(64)
                                            - w.astype(jnp.uint64))
    mag = _u64_as_i64(raw)
    sign = jnp.where(delta0[s] < 0, jnp.int64(-1), jnp.int64(1))
    fixed = jnp.abs(delta0[s])
    step = jnp.where(w == 0, fixed, mag) * sign
    inc = jnp.where(local <= 0, jnp.int64(0),
                    jnp.where(local == 1, delta0[s], step))
    in_seg = (local >= 0) & (local < count[s])
    inc = jnp.where(in_seg, inc, 0)
    c = jnp.cumsum(inc)
    head = out_start[s]
    return base[s] + c - c[jnp.clip(head, 0, out_cap - 1)]


@partial(jax.jit, static_argnames=("out_cap",))
def _present_bits(byte_vals, row_base, byte_base, out_cap):
    """Expanded PRESENT bytes -> bool validity.  Bit streams restart per
    stripe (a stripe's rows need not be a multiple of 8), so logical row
    i maps through its stripe: local = i - row_base[s], byte =
    byte_base[s] + local>>3, bit = 7 - local&7 (MSB-first)."""
    i = jnp.arange(out_cap, dtype=jnp.int64)
    s = jnp.clip(jnp.searchsorted(row_base, i, side="right") - 1,
                 0, row_base.shape[0] - 1)
    local = i - row_base[s]
    k = jnp.clip(byte_base[s] + (local >> 3), 0, byte_vals.shape[0] - 1)
    b = byte_vals[k]
    return ((b >> (jnp.uint64(7) - (local & 7).astype(jnp.uint64)))
            & jnp.uint64(1)) > 0


from .device_parquet import gather_string_matrix as _gather_string_matrix


@partial(jax.jit, static_argnames=("width", "cap"))
def _gather_dict_matrix(dict_mat, dict_lens, idx, width, cap):
    safe = jnp.clip(idx, 0, dict_mat.shape[0] - 1)
    return dict_mat[safe][:, :width], dict_lens[safe]


# --------------------------------------------------------------------------
# Column decode plans
# --------------------------------------------------------------------------


@dataclass
class _ColPlan:
    """Accumulated per-column state across the selected stripes."""

    buf: bytearray = field(default_factory=bytearray)     # device bytes
    present_runs: _MsbRuns = field(default_factory=_MsbRuns)
    has_present: bool = False
    #: per-stripe (first logical row, first PRESENT byte in the expanded
    #: byte axis) — bit streams restart per stripe
    present_row_base: List[int] = field(default_factory=list)
    present_byte_base: List[int] = field(default_factory=list)
    present_bytes: int = 0
    #: boolean DATA is also a bit stream, on the dense (non-null) axis
    bool_dense_base: List[int] = field(default_factory=list)
    bool_byte_base: List[int] = field(default_factory=list)
    bool_bytes: int = 0
    val_runs: _MsbRuns = field(default_factory=_MsbRuns)
    val_deltas: _DeltaSegs = field(default_factory=_DeltaSegs)
    total_rows: int = 0
    total_nonnull: int = 0
    # strings
    str_starts: List[np.ndarray] = field(default_factory=list)  # per stripe
    str_lens: List[np.ndarray] = field(default_factory=list)
    dict_mats: List[np.ndarray] = field(default_factory=list)
    dict_lens: List[np.ndarray] = field(default_factory=list)
    #: per-stripe first dense index (dictionary index offsetting)
    dense_base: List[int] = field(default_factory=list)
    is_dict: Optional[bool] = None
    # floats: dense host views concatenated at decode time
    float_parts: List[np.ndarray] = field(default_factory=list)

    def append_buf(self, data: bytes) -> int:
        """Add stream bytes to the device buffer (8-byte aligned segments
        so bit positions stay word-local); returns the base bit."""
        pad = (-len(self.buf)) % 8
        self.buf.extend(b"\0" * pad)
        base_bit = len(self.buf) * 8
        self.buf.extend(data)
        return base_bit


def _runs_to_device(runs: _MsbRuns):
    n = _pad_pow2(len(runs), 8)

    def pad(a, fill=0):
        out = np.full(n, fill, dtype=np.int64)
        out[:len(runs)] = a
        return jnp.asarray(out)

    big = np.iinfo(np.int64).max
    out_start = np.full(n, big, dtype=np.int64)
    out_start[:len(runs)] = runs.out_start
    return (jnp.asarray(out_start), pad(runs.src_bit), pad(runs.width),
            pad(runs.rle_val))


def _deltas_to_device(segs: _DeltaSegs):
    n = _pad_pow2(len(segs), 8)
    big = np.iinfo(np.int64).max

    def pad(a, fill=0):
        out = np.full(n, fill, dtype=np.int64)
        out[:len(segs)] = a
        return jnp.asarray(out)

    out_start = np.full(n, big, dtype=np.int64)
    out_start[:len(segs)] = segs.out_start
    return (jnp.asarray(out_start), pad(segs.count), pad(segs.base),
            pad(segs.delta0), pad(segs.width), pad(segs.src_bit))


def _buf_to_words(buf) -> jnp.ndarray:
    data = bytes(buf) + b"\0" * 16
    pad = (-len(data)) % 4
    data += b"\0" * pad
    return jnp.asarray(np.frombuffer(data, dtype="<u4"))


def _int_values_device(plan: _ColPlan, n_dense: int, signed: bool):
    """Dense int64 values from the accumulated RLEv2 runs + delta segs."""
    cap = _pad_pow2(n_dense)
    words = _buf_to_words(plan.buf)
    vals = None
    if len(plan.val_runs):
        rs = _runs_to_device(plan.val_runs)
        raw = _expand_runs_msb(words, *rs, cap)
        vals = _zigzag_device(raw) if signed else _u64_as_i64(raw)
    if len(plan.val_deltas):
        ds = _deltas_to_device(plan.val_deltas)
        dvals = _expand_delta(words, *ds, cap)
        if vals is None:
            vals = dvals
        else:
            # membership test: index inside a delta segment's range
            idx = jnp.arange(cap, dtype=jnp.int64)
            s = jnp.clip(jnp.searchsorted(ds[0], idx, side="right") - 1,
                         0, ds[0].shape[0] - 1)
            in_delta = (idx >= ds[0][s]) & (idx < ds[0][s] + ds[1][s])
            vals = jnp.where(in_delta, dvals, vals)
    if vals is None:
        vals = jnp.zeros(cap, jnp.int64)
    return vals


def _stripe_bases(rows: List[int], bytes_: List[int]):
    n = _pad_pow2(len(rows), 8)
    big = np.iinfo(np.int64).max
    rb = np.full(n, big, dtype=np.int64)
    rb[:len(rows)] = rows
    bb = np.zeros(n, dtype=np.int64)
    bb[:len(bytes_)] = bytes_
    return jnp.asarray(rb), jnp.asarray(bb)


def _validity_device(plan: _ColPlan, n_rows: int, cap: int):
    if not plan.has_present:
        return jnp.ones(cap, bool) \
            if n_rows == cap else (jnp.arange(cap) < n_rows)
    byte_cap = _pad_pow2(plan.present_bytes)
    words = _buf_to_words(plan.buf)
    rs = _runs_to_device(plan.present_runs)
    bvals = _expand_runs_msb(words, *rs, byte_cap)
    rb, bb = _stripe_bases(plan.present_row_base, plan.present_byte_base)
    valid = _present_bits(bvals, rb, bb, cap)
    return valid & (jnp.arange(cap) < n_rows)


# --------------------------------------------------------------------------
# Per-stripe stream collection (host)
# --------------------------------------------------------------------------

_DEVICE_KINDS = {_KIND_BOOLEAN, _KIND_BYTE, _KIND_SHORT, _KIND_INT,
                 _KIND_LONG, _KIND_FLOAT, _KIND_DOUBLE, _KIND_DATE,
                 _KIND_STRING, _KIND_BINARY, _KIND_VARCHAR, _KIND_CHAR}

_STR_KINDS = {_KIND_STRING, _KIND_BINARY, _KIND_VARCHAR, _KIND_CHAR}


def _collect_stripe(plan: _ColPlan, kind: int, enc: int, dict_size: int,
                    streams: Dict[int, bytes], stripe_rows: int) -> None:
    """Fold one stripe's decompressed streams for one column into the
    accumulated plan.  Raises _Unsupported to decline the column."""
    if kind in _STR_KINDS:
        if enc == _ENC_DIRECT_V2:
            is_dict = False
        elif enc == _ENC_DICTIONARY_V2:
            is_dict = True
        else:
            raise _Unsupported(f"string encoding {enc}")
        if plan.is_dict is None:
            plan.is_dict = is_dict
        elif plan.is_dict != is_dict:
            raise _Unsupported("mixed string encodings across stripes")
    elif enc not in (_ENC_DIRECT, _ENC_DIRECT_V2):
        raise _Unsupported(f"encoding {enc} for kind {kind}")
    v2 = enc in (_ENC_DIRECT_V2, _ENC_DICTIONARY_V2)
    if kind in (_KIND_SHORT, _KIND_INT, _KIND_LONG, _KIND_DATE) and not v2:
        raise _Unsupported("RLEv1 integer stream")

    present = streams.get(_STREAM_PRESENT)
    nonnull = stripe_rows
    if present is not None:
        plan.has_present = True
        nbytes = (stripe_rows + 7) // 8
        base_bit = plan.append_buf(present)
        plan.present_row_base.append(plan.total_rows)
        plan.present_byte_base.append(plan.present_bytes)
        nonnull = _walk_byte_rle(present, 0, len(present), nbytes,
                                 plan.present_bytes, base_bit,
                                 plan.present_runs,
                                 count_bits_upto=stripe_rows)
        plan.present_bytes += nbytes
    elif plan.has_present:
        # earlier stripes had nulls, this one doesn't: an all-ones
        # present run keeps the mapping uniform
        nbytes = (stripe_rows + 7) // 8
        plan.present_row_base.append(plan.total_rows)
        plan.present_byte_base.append(plan.present_bytes)
        plan.present_runs.add_rle(plan.present_bytes, 0xFF)
        plan.present_bytes += nbytes

    data = streams.get(_STREAM_DATA, b"")
    plan.dense_base.append(plan.total_nonnull)
    if kind == _KIND_BOOLEAN:
        nbytes = (nonnull + 7) // 8
        base_bit = plan.append_buf(data)
        plan.bool_dense_base.append(plan.total_nonnull)
        plan.bool_byte_base.append(plan.bool_bytes)
        _walk_byte_rle(data, 0, len(data), nbytes, plan.bool_bytes,
                       base_bit, plan.val_runs)
        plan.bool_bytes += nbytes
    elif kind == _KIND_BYTE:
        base_bit = plan.append_buf(data)
        _walk_byte_rle(data, 0, len(data), nonnull, plan.total_nonnull,
                       base_bit, plan.val_runs)
    elif kind in (_KIND_SHORT, _KIND_INT, _KIND_LONG, _KIND_DATE):
        base_bit = plan.append_buf(data)
        _walk_rlev2(data, 0, len(data), nonnull, True,
                    plan.total_nonnull, base_bit, plan.val_runs,
                    plan.val_deltas)
    elif kind in (_KIND_FLOAT, _KIND_DOUBLE):
        dt = np.dtype("<f4" if kind == _KIND_FLOAT else "<f8")
        want = nonnull * dt.itemsize
        if len(data) < want:
            raise _Unsupported("short float stream")
        plan.float_parts.append(np.frombuffer(data, dt, count=nonnull))
    elif kind in _STR_KINDS:
        lens_buf = streams.get(_STREAM_LENGTH, b"")
        if plan.is_dict:
            ddata = streams.get(_STREAM_DICTIONARY_DATA, b"")
            dlens = _host_rlev2(lens_buf, 0, len(lens_buf), dict_size,
                                False).astype(np.int64)
            starts = np.zeros(dict_size + 1, dtype=np.int64)
            np.cumsum(dlens, out=starts[1:])
            if int(starts[-1]) > len(ddata):
                raise _Unsupported("short dictionary blob")
            w = int(dlens.max()) if dict_size else 0
            mat = np.zeros((max(dict_size, 1), max(w, 1)), dtype=np.uint8)
            blob = np.frombuffer(ddata, np.uint8, count=int(starts[-1]))
            for r in range(dict_size):
                ln = int(dlens[r])
                mat[r, :ln] = blob[starts[r]:starts[r] + ln]
            plan.dict_mats.append(mat)
            plan.dict_lens.append(dlens.astype(np.int32))
            base_bit = plan.append_buf(data)
            _walk_rlev2(data, 0, len(data), nonnull, False,
                        plan.total_nonnull, base_bit, plan.val_runs,
                        plan.val_deltas)
        else:
            lens = _host_rlev2(lens_buf, 0, len(lens_buf), nonnull, False)
            total = int(lens.sum())
            if total > len(data):
                raise _Unsupported("short string blob")
            base_bit = plan.append_buf(data)
            starts = (np.cumsum(lens) - lens) + base_bit // 8
            plan.str_starts.append(starts)
            plan.str_lens.append(lens.astype(np.int32))
    else:  # pragma: no cover - gated by _DEVICE_KINDS
        raise _Unsupported(f"kind {kind}")
    plan.total_rows += stripe_rows
    plan.total_nonnull += nonnull


# --------------------------------------------------------------------------
# Column finishing: plans -> DeviceColumn
# --------------------------------------------------------------------------


def _finish_column(plan: _ColPlan, kind: int, dtype, n_rows: int,
                   capacity: int, max_str_bytes: int, conf=None):
    from ..columnar.column import DeviceColumn, bucket_width
    valid = _validity_device(plan, n_rows, capacity)
    n_dense = plan.total_nonnull

    if kind == _KIND_BOOLEAN:
        byte_cap = _pad_pow2(plan.bool_bytes)
        words = _buf_to_words(plan.buf)
        rs = _runs_to_device(plan.val_runs)
        bvals = _expand_runs_msb(words, *rs, byte_cap)
        db, bb = _stripe_bases(plan.bool_dense_base, plan.bool_byte_base)
        dense = _present_bits(bvals, db, bb, _pad_pow2(n_dense))
        data, valid = _scatter_nonnull(dense, valid, n_rows, capacity)
        return DeviceColumn(dtype, data, valid)

    if kind in (_KIND_BYTE, _KIND_SHORT, _KIND_INT, _KIND_LONG,
                _KIND_DATE):
        signed_walk = kind not in (_KIND_BYTE,)
        vals = _int_values_device(plan, max(n_dense, 1),
                                  signed=False if kind == _KIND_BYTE
                                  else True)
        np_dt = {_KIND_BYTE: jnp.int8, _KIND_SHORT: jnp.int16,
                 _KIND_INT: jnp.int32, _KIND_LONG: jnp.int64,
                 _KIND_DATE: jnp.int32}[kind]
        if kind == _KIND_BYTE:
            # tinyint bytes are raw two's-complement
            vals = ((vals + 128) % 256) - 128
        dense = vals.astype(np_dt)
        data, valid = _scatter_nonnull(dense, valid, n_rows, capacity)
        return DeviceColumn(dtype, data, valid)

    if kind in (_KIND_FLOAT, _KIND_DOUBLE):
        parts = plan.float_parts or [np.zeros(0, np.float32)]
        host = np.concatenate(parts) if len(parts) > 1 else parts[0]
        pad = _pad_pow2(max(len(host), 1))
        buf = np.zeros(pad, dtype=host.dtype)
        buf[:len(host)] = host
        dense = jnp.asarray(buf)
        if kind == _KIND_DOUBLE:
            dense = dense.astype(jnp.float64)
        else:
            dense = dense.astype(jnp.float32)
        data, valid = _scatter_nonnull(dense, valid, n_rows, capacity)
        return DeviceColumn(dtype, data, valid)

    # strings
    if plan.is_dict:
        mats = plan.dict_mats
        w = max((m.shape[1] for m in mats), default=1)
        w = bucket_width(w)
        total_dict = sum(m.shape[0] for m in mats)
        if capacity * w > max_str_bytes:
            raise _Unsupported("string matrix too large")
        combined = np.zeros((max(total_dict, 1), w), dtype=np.uint8)
        lens_np = np.zeros(max(total_dict, 1), dtype=np.int32)
        offs = []
        at = 0
        for m, dl in zip(mats, plan.dict_lens):
            offs.append(at)
            combined[at:at + m.shape[0], :m.shape[1]] = m
            lens_np[at:at + m.shape[0]] = dl
            at += m.shape[0]
        idx = _int_values_device(plan, max(n_dense, 1), signed=False)
        # per-stripe dictionary offset by dense position
        db, ob = _stripe_bases(plan.dense_base, offs)
        j = jnp.arange(idx.shape[0], dtype=jnp.int64)
        s = jnp.clip(jnp.searchsorted(db, j, side="right") - 1,
                     0, db.shape[0] - 1)
        gidx = idx + ob[s]
        # encoded scan retention: keep the (single-stripe, or identical-
        # across-stripes) ORC dictionary as codes+dict; repeated values
        # across stripe dictionaries make the helper decline -> gather
        from ..columnar.encoded import retain_scan_dictionary
        enc = retain_scan_dictionary(
            dtype, combined, lens_np, gidx, valid, n_rows, capacity,
            lambda dense: _scatter_nonnull(dense, valid, n_rows, capacity),
            conf)
        if enc is not None:
            return enc
        mat_d = jnp.asarray(combined)
        lens_d = jnp.asarray(lens_np)
        chars, lens = _gather_dict_matrix(mat_d, lens_d, gidx, w,
                                          idx.shape[0])
        data, valid = _scatter_nonnull(chars, valid, n_rows, capacity)
        lens_data, _ = _scatter_nonnull(lens, valid, n_rows, capacity)
        return DeviceColumn(dtype, data, valid,
                            lengths=lens_data.astype(jnp.int32))

    starts = (np.concatenate(plan.str_starts) if len(plan.str_starts) > 1
              else (plan.str_starts[0] if plan.str_starts
                    else np.zeros(0, np.int64)))
    lens = (np.concatenate(plan.str_lens) if len(plan.str_lens) > 1
            else (plan.str_lens[0] if plan.str_lens
                  else np.zeros(0, np.int32)))
    w = bucket_width(int(lens.max()) if len(lens) else 0)
    if capacity * w > max_str_bytes:
        raise _Unsupported("string matrix too large")
    pad = _pad_pow2(max(len(starts), 1))
    sp = np.zeros(pad, np.int64)
    sp[:len(starts)] = starts
    lp = np.zeros(pad, np.int32)
    lp[:len(lens)] = lens
    words = _buf_to_words(plan.buf)
    chars = _gather_string_matrix(words, jnp.asarray(sp), jnp.asarray(lp),
                                  w, pad)
    data, valid = _scatter_nonnull(chars, valid, n_rows, capacity)
    lens_data, _ = _scatter_nonnull(jnp.asarray(lp), valid, n_rows,
                                    capacity)
    return DeviceColumn(dtype, data, valid,
                        lengths=lens_data.astype(jnp.int32))


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------


def _dtype_ok(kind: int, dtype) -> bool:
    from .. import types as T
    want = {_KIND_BOOLEAN: T.BooleanType, _KIND_BYTE: T.ByteType,
            _KIND_SHORT: T.ShortType, _KIND_INT: T.IntegerType,
            _KIND_LONG: T.LongType, _KIND_FLOAT: T.FloatType,
            _KIND_DOUBLE: T.DoubleType, _KIND_DATE: T.DateType,
            _KIND_STRING: (T.StringType,), _KIND_VARCHAR: (T.StringType,),
            _KIND_CHAR: (T.StringType,), _KIND_BINARY: (T.BinaryType,)}
    w = want.get(kind)
    if w is None:
        return False
    return isinstance(dtype, w if isinstance(w, tuple) else (w,))


def decode_file(path: str, stripes: Optional[List[int]] = None,
                tctx=None, orc_file=None, conf=None):
    """Decode (a subset of stripes of) one ORC file into a
    :class:`ColumnarBatch`, device-decoding every column the envelope
    supports and falling back to pyarrow per column otherwise.  Returns
    ``None`` when no column takes the device path (callers use their
    host read wholesale) — the same contract as
    :func:`.device_parquet.decode_file`."""
    import pyarrow.orc as pa_orc

    from .. import types as T
    from ..columnar.batch import ColumnarBatch
    from ..columnar.column import bucket_capacity
    from ..columnar.convert import arrow_to_device_column
    from .device_parquet import _max_string_matrix_bytes

    if orc_file is None:
        orc_file = pa_orc.ORCFile(path)
    schema = orc_file.schema

    with open(path, "rb") as f:
        raw = f.read()
    # file tail: ... postscript | ps_len-byte; the postscript's last
    # field is the magic, so bytes -4:-1 read b"ORC"
    if len(raw) < 5 or raw[-4:-1] != b"ORC" or raw[-1] == 0:
        from .decode_stats import set_decline_reason
        set_decline_reason("malformed-tail")
        return None
    ps_len = raw[-1]
    try:
        footer_len, codec, _block, _meta = _parse_postscript(
            raw[-1 - ps_len:-1])
        footer = _decompress_stream(
            raw[-1 - ps_len - footer_len:-1 - ps_len], codec)
        all_stripes, types, total_rows = _parse_footer(footer)
    except (_Unsupported, IndexError, ValueError, struct.error):
        from .decode_stats import set_decline_reason
        set_decline_reason("unsupported-footer")
        return None
    if not types or types[0].subtypes != list(
            range(1, len(types[0].subtypes) + 1)):
        # non-flat root layouts (nested types shift ids) decline per
        # column below via the id map; a wholly unexpected tree declines
        if not types:
            return None
    sel = list(range(len(all_stripes))) if stripes is None else list(stripes)
    if not sel:
        return None
    n_rows = sum(all_stripes[s].num_rows for s in sel)
    capacity = bucket_capacity(n_rows)
    max_str_bytes = _max_string_matrix_bytes(conf)

    root = types[0]
    field_type_id = {i: tid for i, tid in enumerate(root.subtypes)}

    # stripe footers parsed once, shared across columns
    stripe_meta = []
    try:
        for s in sel:
            st = all_stripes[s]
            foot_raw = raw[st.offset + st.index_length + st.data_length:
                           st.offset + st.index_length + st.data_length
                           + st.footer_length]
            streams, encodings = _parse_stripe_footer(
                _decompress_stream(foot_raw, codec), st)
            stripe_meta.append((st, streams, encodings))
    except (_Unsupported, IndexError, ValueError, struct.error):
        from .decode_stats import set_decline_reason
        set_decline_reason("unsupported-stripe-footer")
        return None

    device_cols: Dict[int, object] = {}
    host_fields: List[int] = []
    for fi, fld in enumerate(schema):
        tid = field_type_id.get(fi)
        try:
            dtype = T.from_arrow(fld.type)
        except Exception:
            dtype = None
        if (tid is None or tid >= len(types)
                or types[tid].kind not in _DEVICE_KINDS
                or dtype is None or not _dtype_ok(types[tid].kind, dtype)):
            host_fields.append(fi)
            continue
        kind = types[tid].kind
        plan = _ColPlan()
        try:
            for st, streams, encodings in stripe_meta:
                enc, dict_size = encodings.get(tid, (0, 0))
                col_streams: Dict[int, bytes] = {}
                for si in streams:
                    if si.column == tid and si.kind in (
                            _STREAM_PRESENT, _STREAM_DATA, _STREAM_LENGTH,
                            _STREAM_DICTIONARY_DATA):
                        body = raw[si.offset:si.offset + si.length]
                        col_streams[si.kind] = _decompress_stream(body,
                                                                  codec)
                _collect_stripe(plan, kind, enc, dict_size, col_streams,
                                st.num_rows)
            device_cols[fi] = _finish_column(plan, kind, dtype, n_rows,
                                             capacity, max_str_bytes,
                                             conf=conf)
            if tctx is not None:
                tctx.inc_metric("orcDeviceDecodedColumns")
        except _Unsupported:
            host_fields.append(fi)
        except (ValueError, IndexError, KeyError, struct.error, OSError):
            if tctx is not None:
                tctx.inc_metric("orcDeviceDecodeErrors")
            host_fields.append(fi)

    if not device_cols:
        from .decode_stats import set_decline_reason
        set_decline_reason("no-device-columns")
        return None
    if host_fields:
        names = [schema.field(fi).name for fi in host_fields]
        tbl = orc_file.read(columns=names)
        if stripes is not None:
            # pyarrow has no stripe-subset read; assemble from read_stripe
            import pyarrow as pa
            parts = [pa.Table.from_batches(
                [orc_file.read_stripe(s, columns=names)]) for s in sel]
            tbl = pa.concat_tables(parts)
        for k, fi in enumerate(host_fields):
            device_cols[fi] = arrow_to_device_column(tbl.column(k),
                                                     capacity)
            if tctx is not None:
                tctx.inc_metric("orcHostDecodedColumns")

    cols = [device_cols[fi] for fi in range(len(schema))]
    return ColumnarBatch.make([f.name for f in schema], cols, n_rows)
