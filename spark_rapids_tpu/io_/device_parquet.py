"""Device-side Parquet decode — the TPU-native analog of the reference's
on-GPU parquet decode (``GpuParquetScan.scala:2649`` ``Table.readParquet``:
host parses footers and assembles raw column-chunk bytes, the device decodes
encodings).  The split here follows the same line:

* **Host** (structure only, O(pages + runs), no per-value work): pyarrow
  footer metadata, a minimal Thrift-compact ``PageHeader`` reader, per-page
  decompression (no TPU byte-codec exists — the reference offloads this leg
  to nvcomp), and a walk of the RLE/bit-packed hybrid *run headers* that
  yields a run-descriptor table (a handful of entries per page).
* **Device** (all per-value work, one shape-bucketed XLA program per
  signature): bit-unpacking of packed runs and PLAIN sections via gather +
  shift arithmetic over uint32 words, RLE broadcast, dictionary-index
  gather, definition-level decode -> validity, non-null scatter (cumsum
  positions), and physical->carrier finishing (two's-complement bitcasts,
  IEEE-754 float64 reconstruction without 64-bit bitcast, timestamp unit
  scaling).

PLAIN value sections are degenerate bit-packed runs (width = 8*itemsize at a
byte-aligned bit offset), so ONE descriptor-driven kernel decodes a whole
column chunk — across all its pages and row groups — in a single call.

Anything outside the envelope (nested columns, mixed PLAIN/dictionary
chunks, exotic encodings/codecs, pathological run counts) falls back to the
host pyarrow decode **per column**; supported columns still decode on device
and the two merge into one batch — the same per-op fallback discipline the
reference applies at plan level.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# --------------------------------------------------------------------------
# Thrift compact-protocol reader (just enough for parquet PageHeader)
# --------------------------------------------------------------------------

_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


class _ThriftReader:
    """Minimal thrift compact-protocol cursor over a bytes object."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out, self.pos = _read_uleb(self.buf, self.pos)
        return out

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip(self, ftype: int) -> None:
        if ftype in (_CT_TRUE, _CT_FALSE):
            return
        if ftype == _CT_BYTE:
            self.pos += 1
        elif ftype in (_CT_I16, _CT_I32, _CT_I64):
            self.varint()
        elif ftype == _CT_DOUBLE:
            self.pos += 8
        elif ftype == _CT_BINARY:
            # NB: must read the varint BEFORE touching pos — augmented
            # assignment would snapshot pos before varint() advances it
            ln = self.varint()
            self.pos += ln
        elif ftype in (_CT_LIST, _CT_SET):
            h = self._byte()
            size = h >> 4
            etype = h & 0xF
            if size == 15:
                size = self.varint()
            for _ in range(size):
                self.skip(etype)
        elif ftype == _CT_MAP:
            size = self.varint()
            if size:
                h = self._byte()
                kt, vt = h >> 4, h & 0xF
                for _ in range(size):
                    self.skip(kt)
                    self.skip(vt)
        elif ftype == _CT_STRUCT:
            for _fid, ft in self.fields():
                self.skip(ft)
        else:
            raise ValueError(f"unknown thrift compact type {ftype}")

    def fields(self):
        """Yield (field_id, type) for one struct, consuming the STOP."""
        fid = 0
        while True:
            b = self._byte()
            if b == _CT_STOP:
                return
            delta = (b >> 4) & 0xF
            ftype = b & 0xF
            if delta:
                fid += delta
            else:
                fid = self.zigzag()
            yield fid, ftype


@dataclass
class _PageHeader:
    type: int = -1                 # 0 data, 2 dictionary, 3 data v2
    uncompressed_size: int = 0
    compressed_size: int = 0
    num_values: int = 0
    encoding: int = -1
    def_encoding: int = -1
    # v2 only
    num_nulls: int = -1
    def_len: int = 0
    rep_len: int = 0
    values_compressed: bool = True
    header_len: int = 0            # bytes consumed by the header itself


def _parse_page_header(buf: bytes, pos: int) -> _PageHeader:
    r = _ThriftReader(buf, pos)
    h = _PageHeader()
    for fid, ftype in r.fields():
        if fid == 1 and ftype == _CT_I32:
            h.type = r.zigzag()
        elif fid == 2 and ftype == _CT_I32:
            h.uncompressed_size = r.zigzag()
        elif fid == 3 and ftype == _CT_I32:
            h.compressed_size = r.zigzag()
        elif fid == 5 and ftype == _CT_STRUCT:      # DataPageHeader
            for sfid, sft in r.fields():
                if sfid == 1 and sft == _CT_I32:
                    h.num_values = r.zigzag()
                elif sfid == 2 and sft == _CT_I32:
                    h.encoding = r.zigzag()
                elif sfid == 3 and sft == _CT_I32:
                    h.def_encoding = r.zigzag()
                else:
                    r.skip(sft)
        elif fid == 7 and ftype == _CT_STRUCT:      # DictionaryPageHeader
            for sfid, sft in r.fields():
                if sfid == 1 and sft == _CT_I32:
                    h.num_values = r.zigzag()
                elif sfid == 2 and sft == _CT_I32:
                    h.encoding = r.zigzag()
                else:
                    r.skip(sft)
        elif fid == 8 and ftype == _CT_STRUCT:      # DataPageHeaderV2
            for sfid, sft in r.fields():
                if sfid == 1 and sft == _CT_I32:
                    h.num_values = r.zigzag()
                elif sfid == 2 and sft == _CT_I32:
                    h.num_nulls = r.zigzag()
                elif sfid == 4 and sft == _CT_I32:
                    h.encoding = r.zigzag()
                elif sfid == 5 and sft == _CT_I32:
                    h.def_len = r.zigzag()
                elif sfid == 6 and sft == _CT_I32:
                    h.rep_len = r.zigzag()
                elif sfid == 7:
                    h.values_compressed = (sft == _CT_TRUE)
                else:
                    r.skip(sft)
        else:
            r.skip(ftype)
    h.header_len = r.pos - pos
    return h


# --------------------------------------------------------------------------
# Encodings / codecs / guards
# --------------------------------------------------------------------------

_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8

_CODECS: Dict[str, Optional[str]] = {
    "UNCOMPRESSED": None,
    "SNAPPY": "snappy",
    "GZIP": "gzip",
    "ZSTD": "zstd",
}

#: per-page run-count guard: a hostile hybrid stream could make the O(runs)
#: host walk cost O(values) — beyond this the column goes to the host path
_MAX_RUNS_PER_PAGE = 4096

_PHYS_ITEMBITS = {"INT32": 32, "INT64": 64, "FLOAT": 32, "DOUBLE": 64,
                  "BOOLEAN": 1}

_PHYS_NP = {"INT32": np.int32, "INT64": np.int64,
            "FLOAT": np.float32, "DOUBLE": np.float64}


def _strings_matrix(values, lens: np.ndarray):
    """bytes sequence + lengths -> (zero-padded byte matrix with a
    power-of-two width bucket, int32 lengths) — the dictionary analog of
    the engine's string column layout."""
    from ..columnar.column import bucket_width
    width = bucket_width(int(lens.max()) if len(lens) else 0)
    mat = np.zeros((max(len(lens), 1), width), np.uint8)
    for i, v in enumerate(values):
        if v:
            mat[i, :len(v)] = np.frombuffer(v, np.uint8)
    return mat, lens.astype(np.int32)


class _Unsupported(Exception):
    """Internal: this column can't take the device path — fall back."""


class _DeclineFile(Exception):
    """Internal: the whole FILE must take the host path (per-column
    fallback would itself be unsafe — e.g. a ragged string column needs
    the host pipeline's width-class splitting, which only applies to
    whole host tables)."""


def _max_string_matrix_bytes(conf=None) -> int:
    """Cap on a device string matrix (capacity x width-bucket bytes) from
    a dictionary gather — the device-path twin of the engine's ragged-
    string upload guard (convert.split_for_upload)."""
    from ..config import RAGGED_STRING_SPLIT_BYTES, RapidsConf
    thr = int((conf or RapidsConf.get_global())
              .get(RAGGED_STRING_SPLIT_BYTES))
    return thr if thr > 0 else (1 << 62)


def _decompress(codec: Optional[str], data: bytes, out_size: int) -> bytes:
    if codec is None:
        return data
    import pyarrow as pa
    out = pa.Codec(codec).decompress(data, decompressed_size=out_size)
    return out.to_pybytes()


# --------------------------------------------------------------------------
# Hybrid (RLE / bit-packed) run-descriptor walk — host, O(runs)
# --------------------------------------------------------------------------

@dataclass
class _Runs:
    """Descriptor table for the device expansion kernel.  ``width == 0``
    marks an RLE run (broadcast ``rle_val``); otherwise the run is
    ``width``-bit packed starting at absolute bit ``src_bit`` of the
    uploaded chunk buffer."""

    out_start: List[int] = field(default_factory=list)
    src_bit: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)
    rle_val: List[int] = field(default_factory=list)

    def add_rle(self, out_start: int, value: int) -> None:
        self.out_start.append(out_start)
        self.src_bit.append(0)
        self.width.append(0)
        self.rle_val.append(value)

    def add_packed(self, out_start: int, src_bit: int, width: int) -> None:
        self.out_start.append(out_start)
        self.src_bit.append(src_bit)
        self.width.append(width)
        self.rle_val.append(0)

    def __len__(self) -> int:
        return len(self.out_start)


def _read_uleb(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _walk_hybrid(buf: bytes, start: int, end: int, bit_width: int,
                 num_values: int, out_base: int, base_bit: int,
                 runs: _Runs, count_eq: Optional[int] = None) -> int:
    """Walk RLE/bit-packed hybrid run headers in ``buf[start:end)`` covering
    ``num_values`` logical values, appending descriptors.  ``base_bit`` is
    the absolute bit position of ``buf[start]`` in the device buffer (chunk
    bytes upload verbatim, so source positions line up 1:1).  When
    ``count_eq`` is given, also counts values == count_eq in the SAME walk
    (vectorized popcount for packed groups) — the def-level non-null count
    the dense-stream offsets need, without a second pass."""
    pos = start
    produced = 0
    hits = 0
    vbytes = (bit_width + 7) // 8
    n0 = len(runs)
    while produced < num_values and pos < end:
        if len(runs) - n0 > _MAX_RUNS_PER_PAGE:
            raise _Unsupported("run count guard")
        header, pos = _read_uleb(buf, pos)
        if header & 1:                       # bit-packed groups of 8
            groups = header >> 1
            count = min(groups * 8, num_values - produced)
            runs.add_packed(out_base + produced,
                            base_bit + (pos - start) * 8, bit_width)
            if count_eq is not None:
                nbytes = groups * bit_width
                chunk = np.frombuffer(buf, np.uint8, nbytes, pos)
                bits = np.unpackbits(chunk, bitorder="little")
                if bit_width == 1:
                    hits += int(np.count_nonzero(bits[:count] == count_eq))
                else:
                    vals = bits[:count * bit_width].reshape(count, bit_width)
                    weights = (1 << np.arange(bit_width)).astype(np.int64)
                    hits += int(np.count_nonzero(
                        vals @ weights == count_eq))
            pos += groups * bit_width        # groups * 8 values * w bits / 8
            produced += count
        else:                                # RLE run
            count = min(header >> 1, num_values - produced)
            val = int.from_bytes(buf[pos:pos + vbytes], "little") \
                if vbytes else 0
            pos += vbytes
            runs.add_rle(out_base + produced, val)
            if count_eq is not None and val == count_eq:
                hits += count
            produced += count
    if produced < num_values:
        raise _Unsupported("short hybrid stream")
    return hits


# --------------------------------------------------------------------------
# Device kernels (shape-bucketed; jit caches one program per signature)
# --------------------------------------------------------------------------

def _pad_pow2(n: int, minimum: int = 8) -> int:
    n = max(int(n), minimum, 1)
    return 1 << (n - 1).bit_length()


@partial(jax.jit, static_argnames=("out_cap",))
def _expand_runs_u32(words, out_start, src_bit, width, rle_val, out_cap):
    """Expand a run-descriptor table into ``uint32[out_cap]`` raw values:
    bit-packed runs gather+shift from the word buffer, RLE runs broadcast.
    Out-of-range tail values are garbage — callers mask them."""
    idx = jnp.arange(out_cap, dtype=jnp.int32)
    r = jnp.clip(jnp.searchsorted(out_start, idx, side="right") - 1,
                 0, out_start.shape[0] - 1)
    local = (idx - out_start[r]).astype(jnp.int64)
    w = width[r]
    bitpos = src_bit[r] + local * w
    w0 = jnp.clip((bitpos >> 5).astype(jnp.int32), 0, words.shape[0] - 2)
    sh = (bitpos & 31).astype(jnp.uint32)
    lo = words[w0] >> sh
    hi = jnp.where(sh == 0, jnp.uint32(0),
                   words[w0 + 1] << (jnp.uint32(32) - sh))
    raw = lo | hi
    wu = w.astype(jnp.uint32)
    mask = jnp.where(wu >= 32, jnp.uint32(0xFFFFFFFF),
                     (jnp.uint32(1) << wu) - jnp.uint32(1))
    return jnp.where(w == 0, rle_val[r].astype(jnp.uint32), raw & mask)


@partial(jax.jit, static_argnames=("out_cap", "width"))
def _expand_flba(words, out_start, src_bit, out_cap, width):
    """FIXED_LEN_BYTE_ARRAY expansion: each value is `width` big-endian
    two's-complement bytes (parquet decimal storage) -> sign-extended
    (lo, hi) uint64 words.  Static byte loop (width <= 16)."""
    idx = jnp.arange(out_cap, dtype=jnp.int32)
    r = jnp.clip(jnp.searchsorted(out_start, idx, side="right") - 1,
                 0, out_start.shape[0] - 1)
    local = (idx - out_start[r]).astype(jnp.int64)
    base = src_bit[r] + local * (width * 8)
    lo = jnp.zeros(out_cap, jnp.uint64)
    hi = jnp.zeros(out_cap, jnp.uint64)
    first_byte = None
    for k in range(width):
        bitpos = base + k * 8
        w0 = jnp.clip((bitpos >> 5).astype(jnp.int32), 0,
                      words.shape[0] - 2)
        sh = (bitpos & 31).astype(jnp.uint32)
        b = ((words[w0] >> sh)
             | jnp.where(sh == 0, jnp.uint32(0),
                         words[w0 + 1] << (jnp.uint32(32) - sh))
             ) & jnp.uint32(0xFF)
        if k == 0:
            first_byte = b
        b64 = b.astype(jnp.uint64)
        pos = (width - 1 - k) * 8
        if pos < 64:
            lo = lo | (b64 << jnp.uint64(pos))
        else:
            hi = hi | (b64 << jnp.uint64(pos - 64))
    neg = (first_byte & jnp.uint32(0x80)) != 0
    if width < 8:
        fill_lo = jnp.uint64((~((1 << (width * 8)) - 1)) & ((1 << 64) - 1))
        lo = jnp.where(neg, lo | fill_lo, lo)
        hi = jnp.where(neg, jnp.uint64((1 << 64) - 1), hi)
    elif width == 8:
        hi = jnp.where(neg, jnp.uint64((1 << 64) - 1), hi)
    elif width < 16:
        fill_hi = jnp.uint64(
            (~((1 << ((width - 8) * 8)) - 1)) & ((1 << 64) - 1))
        hi = jnp.where(neg, hi | fill_hi, hi)
    return lo, hi


def _flba_bytes_to_words(entries, width: int):
    """Host: sequence of `width`-byte big-endian values -> (lo, hi) int64
    numpy arrays (used for small dictionary pages only)."""
    n = len(entries)
    if n == 0:
        return np.zeros(1, np.int64), np.zeros(1, np.int64)
    raw = np.frombuffer(b"".join(entries), np.uint8).reshape(n, width)
    lo = np.zeros(n, np.uint64)
    hi = np.zeros(n, np.uint64)
    for k in range(width):
        b = raw[:, k].astype(np.uint64)
        pos = (width - 1 - k) * 8
        if pos < 64:
            lo |= b << np.uint64(pos)
        else:
            hi |= b << np.uint64(pos - 64)
    neg = raw[:, 0] >= 128
    if width < 8:
        lo[neg] |= np.uint64((~((1 << (width * 8)) - 1)) & ((1 << 64) - 1))
        hi[neg] = np.uint64((1 << 64) - 1)
    elif width == 8:
        hi[neg] = np.uint64((1 << 64) - 1)
    elif width < 16:
        hi[neg] |= np.uint64(
            (~((1 << ((width - 8) * 8)) - 1)) & ((1 << 64) - 1))
    return lo.view(np.int64), hi.view(np.int64)


@partial(jax.jit, static_argnames=("out_cap",))
def _expand_runs_u64(words, out_start, src_bit, out_cap):
    """64-bit PLAIN expansion: each value is assembled from two 32-bit
    window reads (sections are byte- but not word-aligned, so each window
    may itself span two words)."""
    idx = jnp.arange(out_cap, dtype=jnp.int32)
    r = jnp.clip(jnp.searchsorted(out_start, idx, side="right") - 1,
                 0, out_start.shape[0] - 1)
    local = (idx - out_start[r]).astype(jnp.int64)
    bitpos = src_bit[r] + local * 64
    w0 = jnp.clip((bitpos >> 5).astype(jnp.int32), 0, words.shape[0] - 2)
    sh = (bitpos & 31).astype(jnp.uint32)
    lo0 = (words[w0] >> sh) | jnp.where(
        sh == 0, jnp.uint32(0), words[w0 + 1] << (jnp.uint32(32) - sh))
    w1 = jnp.clip(w0 + 1, 0, words.shape[0] - 2)
    hi0 = (words[w1] >> sh) | jnp.where(
        sh == 0, jnp.uint32(0), words[w1 + 1] << (jnp.uint32(32) - sh))
    return (hi0.astype(jnp.uint64) << jnp.uint64(32)) | lo0.astype(jnp.uint64)


def _u64_to_i64(raw):
    from ..columnar.convert import u64_to_i64
    return u64_to_i64(raw)


def _f64_from_bits(bits):
    """IEEE-754 bits -> float64 arithmetically (inverse of the engine's
    ``convert._f64_bits``; denormals flush to signed zero, matching the
    engine's DAZ semantics)."""
    sign = jnp.where((bits >> jnp.uint64(63)) > 0, -1.0, 1.0)
    expf = ((bits >> jnp.uint64(52)) & jnp.uint64(0x7FF)).astype(jnp.int32)
    mant = (bits & jnp.uint64((1 << 52) - 1)).astype(jnp.float64)
    frac = 1.0 + mant * (2.0 ** -52)
    val = sign * jnp.ldexp(frac, expf - 1023)
    val = jnp.where(expf == 0, sign * 0.0, val)
    val = jnp.where(expf == 0x7FF, sign * jnp.inf, val)
    return jnp.where((expf == 0x7FF) & (mant != 0.0), jnp.nan, val)


def byte_at_words(words, k):
    """Byte ``k`` of an uploaded little-endian word buffer (traced).
    Shared by the parquet and ORC string gathers."""
    w = jnp.clip((k >> 2).astype(jnp.int32), 0, words.shape[0] - 1)
    return (words[w] >> ((k & 3).astype(jnp.uint32) * 8)) & jnp.uint32(0xFF)


@partial(jax.jit, static_argnames=("width", "cap"))
def gather_string_matrix(words, starts, lens, width, cap):
    """Variable-length byte values at ``starts`` -> [cap, width] matrix
    (row r byte j = buf[starts[r] + j], zero past the row's length)."""
    j = jnp.arange(width, dtype=jnp.int64)[None, :]
    pos = starts[:, None].astype(jnp.int64) + j
    b = byte_at_words(words, pos)
    live = j < lens[:, None]
    return jnp.where(live, b, 0).astype(jnp.uint8)


@jax.jit
def _remap_indices(idx, group_starts, remap_offsets, remap):
    """Apply per-row-group dictionary remapping: dense value j belongs to
    group g = searchsorted(group_starts, j); its unioned-dictionary index
    is remap[remap_offsets[g] + local_idx]."""
    j = jnp.arange(idx.shape[0], dtype=jnp.int32)
    g = jnp.clip(jnp.searchsorted(group_starts, j, side="right") - 1,
                 0, remap_offsets.shape[0] - 1)
    pos = jnp.clip(remap_offsets[g] + idx, 0, remap.shape[0] - 1)
    return remap[pos]


@partial(jax.jit, static_argnames=("cap",))
def _scatter_nonnull(dense, valid, n, cap):
    """Place dense non-null values at their row positions; null and dead
    rows get zeroed data.  Returns (data, final_validity)."""
    rowlive = jnp.arange(cap, dtype=jnp.int32) < n
    v = valid & rowlive
    pos = jnp.cumsum(v.astype(jnp.int32)) - 1
    gathered = dense[jnp.clip(pos, 0, dense.shape[0] - 1)]
    zero = jnp.zeros((), dtype=dense.dtype)
    if dense.ndim == 2:
        return jnp.where(v[:, None], gathered, zero), v
    return jnp.where(v, gathered, zero), v


# --------------------------------------------------------------------------
# Column-chunk planning (host)
# --------------------------------------------------------------------------

@dataclass
class _ChunkPlan:
    """Host-side decode plan for one column over the selected row groups:
    the concatenated decompressed page payloads plus run descriptors."""

    buf: bytes = b""
    total_values: int = 0
    total_nonnull: int = 0
    def_runs: _Runs = field(default_factory=_Runs)
    val_runs: _Runs = field(default_factory=_Runs)
    dict_values: Optional[np.ndarray] = None
    dict_strings: Optional[Tuple[np.ndarray, np.ndarray]] = None
    is_dict: Optional[bool] = None
    nullable: bool = True
    # merged-plan only: per-row-group dictionaries usually diverge (each
    # writer chunk builds its own, in first-occurrence order), so indices
    # are remapped ON DEVICE into a unioned global dictionary:
    # value j of the dense stream belongs to group g = searchsorted(
    # group_starts, j); its global index is remap[remap_offsets[g] + idx]
    remap: Optional[np.ndarray] = None            # int32, concat per group
    remap_offsets: Optional[np.ndarray] = None    # int32[G]
    group_starts: Optional[np.ndarray] = None     # int32[G] dense offsets
    # PLAIN BYTE_ARRAY pages: per-page payload byte offsets into buf +
    # value lengths (host walk of the u32 prefixes; native helper or a
    # bounded python loop), consumed by the device gather kernel
    str_starts: List[np.ndarray] = field(default_factory=list)
    str_lens: List[np.ndarray] = field(default_factory=list)


def _plain_dict_values(phys: str, data: bytes, n: int) -> np.ndarray:
    np_t = _PHYS_NP.get(phys)
    if np_t is None:
        raise _Unsupported(f"dictionary of {phys}")
    return np.frombuffer(data, np_t, n)


def _plain_dict_strings(data: bytes, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Length-prefixed BYTE_ARRAY dictionary -> (byte matrix, lengths).
    Dictionaries are bounded by the writer's dict-size cap, so this host
    loop is O(dictionary), not O(rows)."""
    lens = np.empty(n, np.int32)
    vals: List[bytes] = []
    pos = 0
    for i in range(n):
        (ln,) = struct.unpack_from("<i", data, pos)
        pos += 4
        vals.append(data[pos:pos + ln])
        pos += ln
        lens[i] = ln
    return _strings_matrix(vals, lens)


#: python-loop ceiling for the PLAIN BYTE_ARRAY prefix walk when the
#: native helper is unavailable — beyond this the host loop would rival
#: the decode itself, so the column declines to pyarrow instead
_PY_WALK_MAX = 100_000


def _walk_byte_array(data: np.ndarray, n: int):
    """(payload starts int64[n], lens int32[n]) for n u32-length-prefixed
    values — native scan, or a bounded python loop."""
    from ..native import byte_array_walk
    try:
        out = byte_array_walk(data, n)
    except ValueError:
        raise _Unsupported("truncated BYTE_ARRAY section")
    if out is not None:
        return out
    if n > _PY_WALK_MAX:
        raise _Unsupported("PLAIN byte-array walk without native helper")
    starts = np.empty(n, np.int64)
    lens = np.empty(n, np.int32)
    buf = data.tobytes()
    pos = 0
    for i in range(n):
        if pos + 4 > len(buf):
            raise _Unsupported("truncated BYTE_ARRAY section")
        (ln,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        if ln > len(buf) - pos:
            raise _Unsupported("truncated BYTE_ARRAY section")
        starts[i] = pos
        lens[i] = ln
        pos += ln
    return starts, lens


def _plan_chunk(raw: bytes, cc, phys: str, nullable: bool,
                type_length: int = 0) -> _ChunkPlan:
    """Parse one column chunk's pages into a decode plan.  Raises
    ``_Unsupported`` for anything outside the device-decode envelope."""
    codec = _CODECS.get(cc.compression, "?")
    if codec == "?":
        raise _Unsupported(f"codec {cc.compression}")
    itembits = _PHYS_ITEMBITS.get(phys)
    if phys == "FIXED_LEN_BYTE_ARRAY":
        if not 0 < type_length <= 16:
            raise _Unsupported(f"FLBA width {type_length}")
        itembits = type_length * 8
    if itembits is None and phys != "BYTE_ARRAY":
        raise _Unsupported(f"physical type {phys}")
    plan = _ChunkPlan(nullable=nullable)
    max_def = 1 if nullable else 0

    pieces: List[bytes] = []
    piece_bits = 0
    pos = 0
    n_pages = 0
    while plan.total_values < cc.num_values and pos < len(raw):
        h = _parse_page_header(raw, pos)
        pos += h.header_len
        body = raw[pos:pos + h.compressed_size]
        pos += h.compressed_size
        n_pages += 1
        if n_pages > 100_000:
            raise _Unsupported("page count guard")

        if h.type == 2:                       # dictionary page
            if h.encoding not in (_ENC_PLAIN, _ENC_PLAIN_DICT):
                raise _Unsupported("non-PLAIN dictionary")
            data = _decompress(codec, body, h.uncompressed_size)
            if phys == "BYTE_ARRAY":
                plan.dict_strings = _plain_dict_strings(data, h.num_values)
            elif phys == "FIXED_LEN_BYTE_ARRAY":
                W = type_length
                plan.dict_values = np.asarray(
                    [data[i * W:(i + 1) * W]
                     for i in range(h.num_values)], dtype=object)
            else:
                plan.dict_values = _plain_dict_values(phys, data,
                                                      h.num_values)
            continue
        if h.type == 0:                       # data page v1
            data = _decompress(codec, body, h.uncompressed_size)
            vstart = 0
            nonnull = h.num_values
            if max_def:
                if h.def_encoding != _ENC_RLE:
                    raise _Unsupported("non-RLE def levels")
                (dlen,) = struct.unpack_from("<i", data, 0)
                nonnull = _walk_hybrid(data, 4, 4 + dlen, 1, h.num_values,
                                       plan.total_values, piece_bits + 32,
                                       plan.def_runs, count_eq=max_def)
                vstart = 4 + dlen
            enc = h.encoding
        elif h.type == 3:                     # data page v2
            if h.rep_len:
                raise _Unsupported("repetition levels")
            levels = body[:h.def_len]
            vals_raw = body[h.def_len:]
            if h.values_compressed:
                vals_raw = _decompress(
                    codec, vals_raw,
                    h.uncompressed_size - h.def_len - h.rep_len)
            data = levels + vals_raw
            nonnull = h.num_values - max(h.num_nulls, 0)
            if max_def:
                _walk_hybrid(data, 0, h.def_len, 1, h.num_values,
                             plan.total_values, piece_bits, plan.def_runs)
            enc, vstart = h.encoding, h.def_len
        else:
            raise _Unsupported(f"page type {h.type}")

        if enc in (_ENC_RLE_DICT, _ENC_PLAIN_DICT):
            if plan.is_dict is False:
                raise _Unsupported("mixed dict/plain pages")
            plan.is_dict = True
            if nonnull:
                idx_width = data[vstart]
                if idx_width > 32:
                    raise _Unsupported("index width > 32")
                _walk_hybrid(data, vstart + 1, len(data), idx_width, nonnull,
                             plan.total_nonnull,
                             piece_bits + (vstart + 1) * 8, plan.val_runs)
        elif enc == _ENC_PLAIN:
            if plan.is_dict is True:
                raise _Unsupported("mixed dict/plain pages")
            plan.is_dict = False
            if phys == "BYTE_ARRAY":
                if nonnull:
                    starts, lens = _walk_byte_array(
                        np.frombuffer(data, np.uint8, len(data) - vstart,
                                      vstart), nonnull)
                    plan.str_starts.append(starts
                                           + (piece_bits // 8 + vstart))
                    plan.str_lens.append(lens)
            elif nonnull:
                plan.val_runs.add_packed(plan.total_nonnull,
                                         piece_bits + vstart * 8, itembits)
        else:
            raise _Unsupported(f"encoding {enc}")

        plan.total_values += h.num_values
        plan.total_nonnull += nonnull
        pieces.append(data)
        piece_bits += len(data) * 8

    if plan.total_values < cc.num_values:
        raise _Unsupported("truncated chunk")
    plan.buf = b"".join(pieces)
    if plan.is_dict is None:
        plan.is_dict = False
    return plan


def _merge_plans(plans: List[_ChunkPlan], phys: str) -> _ChunkPlan:
    """Concatenate per-row-group plans into one chunk-spanning plan.  Dict
    plans union their per-group dictionaries into one global dictionary
    with per-group device-side index remapping (host cost is O(dictionary
    entries), never O(rows))."""
    out = _ChunkPlan(nullable=plans[0].nullable, is_dict=plans[0].is_dict)
    if plans[0].is_dict:
        _unify_dictionaries(plans, phys, out)
    bufs: List[bytes] = []
    bit_base = 0
    for p in plans:
        if p.is_dict != out.is_dict and p.total_nonnull:
            raise _Unsupported("dict/plain mix across row groups")
        for runs_src, runs_dst, base in (
                (p.def_runs, out.def_runs, out.total_values),
                (p.val_runs, out.val_runs, out.total_nonnull)):
            for i in range(len(runs_src)):
                runs_dst.out_start.append(base + runs_src.out_start[i])
                runs_dst.src_bit.append(bit_base + runs_src.src_bit[i])
                runs_dst.width.append(runs_src.width[i])
                runs_dst.rle_val.append(runs_src.rle_val[i])
        for s in p.str_starts:
            out.str_starts.append(s + bit_base // 8)
        out.str_lens.extend(p.str_lens)
        out.total_values += p.total_values
        out.total_nonnull += p.total_nonnull
        bufs.append(p.buf)
        bit_base += len(p.buf) * 8
    out.buf = b"".join(bufs)
    return out


def _unify_dictionaries(plans: List[_ChunkPlan], phys: str,
                        out: _ChunkPlan) -> None:
    """Union per-group dictionaries into one global dictionary and build
    per-group index remap tables (applied ON DEVICE).  When every group's
    dictionary is a prefix of the longest one — the single-writer
    fast path — the remap is the identity and is skipped entirely."""
    import pandas as pd

    per_group: List[np.ndarray] = []
    if phys == "BYTE_ARRAY":
        for p in plans:
            if p.dict_strings is None:
                if p.total_nonnull:
                    raise _Unsupported("missing dictionary")
                per_group.append(np.empty(0, object))
                continue
            mat, lens = p.dict_strings
            per_group.append(np.asarray(
                [mat[i, :lens[i]].tobytes() for i in range(len(lens))],
                dtype=object))
    else:
        np_t = (object if phys == "FIXED_LEN_BYTE_ARRAY"
                else _PHYS_NP[phys])
        for p in plans:
            if p.dict_values is None:
                if p.total_nonnull:
                    raise _Unsupported("missing dictionary")
                per_group.append(np.empty(0, np_t))
            else:
                per_group.append(p.dict_values)

    longest = max(per_group, key=len)
    prefix_ok = all(np.array_equal(g, longest[:len(g)]) for g in per_group)
    if prefix_ok:
        merged = longest
        remaps = None
    else:
        # first-occurrence-ordered union; O(total dictionary entries).
        # float dictionaries with NaN entries would break the pd.Index
        # lookup (NaN != NaN) — send those to the host path.
        nonempty = [g for g in per_group if len(g)]
        if phys in ("FLOAT", "DOUBLE") and any(
                np.isnan(g).any() for g in nonempty):
            raise _Unsupported("NaN in divergent float dictionaries")
        merged = pd.unique(np.concatenate(nonempty))
        index = pd.Index(merged)
        remaps = [index.get_indexer(g).astype(np.int32)
                  if len(g) else np.zeros(0, np.int32)
                  for g in per_group]

    if phys == "BYTE_ARRAY":
        lens = np.asarray([len(v) for v in merged], np.int32) \
            if len(merged) else np.zeros(0, np.int32)
        out.dict_strings = _strings_matrix(merged, lens)
    else:
        out.dict_values = np.asarray(merged) if len(merged) else None

    if remaps is not None:
        out.remap = np.concatenate(remaps) if any(len(r) for r in remaps) \
            else np.zeros(1, np.int32)
        offs = np.zeros(len(remaps), np.int64)
        np.cumsum([len(r) for r in remaps[:-1]], out=offs[1:])
        out.remap_offsets = offs.astype(np.int32)
        starts = np.zeros(len(plans), np.int64)
        np.cumsum([p.total_nonnull for p in plans[:-1]], out=starts[1:])
        out.group_starts = starts.astype(np.int32)


# --------------------------------------------------------------------------
# Device execution of a merged plan
# --------------------------------------------------------------------------

def _runs_to_device(runs: _Runs):
    r = max(len(runs), 1)
    rp = _pad_pow2(r, 4)
    big = np.iinfo(np.int32).max
    out_start = np.full(rp, big, np.int32)
    src_bit = np.zeros(rp, np.int64)
    width = np.zeros(rp, np.int32)
    rle_val = np.zeros(rp, np.int32)
    n = len(runs)
    if n:
        out_start[:n] = runs.out_start
        src_bit[:n] = runs.src_bit
        width[:n] = runs.width
        rle_val[:n] = runs.rle_val
    else:
        out_start[0] = 0
    return (jnp.asarray(out_start), jnp.asarray(src_bit),
            jnp.asarray(width), jnp.asarray(rle_val))


def _buf_to_words(buf: bytes):
    nwords = _pad_pow2((len(buf) + 3) // 4 + 2, 16)
    w = np.zeros(nwords, np.uint32)
    if buf:
        full = len(buf) // 4
        if full:
            w[:full] = np.frombuffer(buf, np.uint32, full)
        rem = len(buf) - full * 4
        if rem:
            tail = np.zeros(4, np.uint8)
            tail[:rem] = np.frombuffer(buf, np.uint8, rem, full * 4)
            w[full] = tail.view(np.uint32)[0]
    return jnp.asarray(w)


def _finish(v, phys: str, dtype, arrow_type):
    """Physical value -> the carrier dtype ``arrow_to_device`` would use
    (see ``convert._fixed_to_numpy``: dates int32 days, timestamps int64
    micros, decimals scaled int64)."""
    import pyarrow as pa

    from .. import types as T
    if phys == "INT64" and isinstance(dtype, T.TimestampType) and \
            pa.types.is_timestamp(arrow_type):
        # ns never reaches here: decode_file gates it to the host path,
        # whose safe arrow cast RAISES on sub-microsecond truncation —
        # silently flooring on device would diverge from that contract
        if arrow_type.unit == "ms":
            v = v * 1000
    if isinstance(dtype, T.DecimalType):
        return v.astype(jnp.int64)
    if isinstance(dtype, T.BooleanType):
        return v.astype(jnp.bool_) if v.dtype != jnp.bool_ else v
    return v.astype(dtype.np_dtype)


def _finish_decimal_words(lo, hi, valid, dtype, n_rows: int,
                          capacity: int):
    """(lo, hi) sign-extended int64 words -> the engine's decimal column
    layout: scaled int64 ``data`` for precision <= 18, else lo in ``data``
    and hi in ``aux`` (Aggregation128Utils-equivalent layout,
    columnar/column.py)."""
    from ..columnar.column import DeviceColumn
    data, v = _scatter_nonnull(lo, valid, jnp.int32(n_rows), capacity)
    if dtype.is_long_backed:
        return DeviceColumn(dtype, data, v)
    aux, _ = _scatter_nonnull(hi, valid, jnp.int32(n_rows), capacity)
    return DeviceColumn(dtype, data, v, aux=aux)


def _decode_column_device(plan: _ChunkPlan, phys: str, dtype, arrow_type,
                          capacity: int, n_rows: int,
                          max_str_bytes: int = 1 << 62,
                          type_length: int = 0, conf=None):
    """Run the device programs for one merged chunk plan -> DeviceColumn."""
    from ..columnar.column import DeviceColumn

    words = _buf_to_words(plan.buf)
    nn_cap = _pad_pow2(plan.total_nonnull)

    if plan.nullable and len(plan.def_runs):
        d_os, d_sb, d_w, d_rv = _runs_to_device(plan.def_runs)
        defs = _expand_runs_u32(words, d_os, d_sb, d_w, d_rv, capacity)
        valid = defs == 1
    else:
        valid = jnp.ones(capacity, jnp.bool_)

    v_os, v_sb, v_w, v_rv = _runs_to_device(plan.val_runs)
    if plan.is_dict:
        idx = _expand_runs_u32(words, v_os, v_sb, v_w, v_rv, nn_cap
                               ).astype(jnp.int32)
        if plan.remap is not None:
            # divergent per-group dictionaries: local -> global indices
            gp = _pad_pow2(len(plan.group_starts), 4)
            big = np.iinfo(np.int32).max
            gs = np.full(gp, big, np.int32)
            gs[:len(plan.group_starts)] = plan.group_starts
            ro = np.zeros(gp, np.int32)
            ro[:len(plan.remap_offsets)] = plan.remap_offsets
            idx = _remap_indices(idx, jnp.asarray(gs), jnp.asarray(ro),
                                 jnp.asarray(plan.remap))
        if phys == "BYTE_ARRAY":
            mat, lens = plan.dict_strings if plan.dict_strings is not None \
                else (np.zeros((1, 4), np.uint8), np.zeros(1, np.int32))
            # ragged-string guard: one long dictionary entry makes the
            # dense [capacity, width] matrix explode.  Per-column host
            # fallback would build the SAME matrix (arrow_to_device_column
            # has no width-class splitting) — so decline the whole file;
            # the scan's host pipeline then splits via split_for_upload.
            if capacity * mat.shape[1] > max_str_bytes:
                raise _DeclineFile("string matrix exceeds ragged guard")
            # encoded scan retention (docs/encoded_columns.md): keep the
            # parquet dictionary page as codes+dict instead of eagerly
            # gathering the padded byte matrix; None = decline -> gather
            from ..columnar.encoded import retain_scan_dictionary
            enc = retain_scan_dictionary(
                dtype, mat, lens, idx, valid, n_rows, capacity,
                lambda dense: _scatter_nonnull(dense, valid,
                                               jnp.int32(n_rows), capacity),
                conf)
            if enc is not None:
                return enc
            dmat = jnp.asarray(mat)
            dlen = jnp.asarray(lens if len(lens) else
                               np.zeros(1, np.int32))
            idx = jnp.clip(idx, 0, dmat.shape[0] - 1)
            data, v = _scatter_nonnull(dmat[idx], valid,
                                       jnp.int32(n_rows), capacity)
            lengths, _ = _scatter_nonnull(dlen[idx], valid,
                                          jnp.int32(n_rows), capacity)
            return DeviceColumn(dtype, data, v, lengths=lengths)
        if phys == "FIXED_LEN_BYTE_ARRAY":
            # decimal dictionary: host-decoded (lo, hi) words, two gathers
            entries = plan.dict_values if plan.dict_values is not None \
                else np.empty(0, object)
            lo_np, hi_np = _flba_bytes_to_words(list(entries), type_length)
            dlo, dhi = jnp.asarray(lo_np), jnp.asarray(hi_np)
            idx = jnp.clip(idx, 0, dlo.shape[0] - 1)
            return _finish_decimal_words(dlo[idx], dhi[idx], valid, dtype,
                                         n_rows, capacity)
        dvals = plan.dict_values
        if dvals is None or not len(dvals):
            dvals = np.zeros(1, _PHYS_NP[phys])
        darr = jnp.asarray(dvals)
        idx = jnp.clip(idx, 0, darr.shape[0] - 1)
        dense = _finish(darr[idx], phys, dtype, arrow_type)
    elif phys == "BYTE_ARRAY":
        # PLAIN strings: host-walked payload offsets, device gather
        from ..columnar.column import bucket_width
        starts = (np.concatenate(plan.str_starts) if plan.str_starts
                  else np.zeros(0, np.int64))
        lens = (np.concatenate(plan.str_lens) if plan.str_lens
                else np.zeros(0, np.int32))
        w = bucket_width(int(lens.max()) if len(lens) else 0)
        if capacity * w > max_str_bytes:
            raise _DeclineFile("string matrix exceeds ragged guard")
        pad = _pad_pow2(max(len(starts), 1))
        sp = np.zeros(pad, np.int64)
        sp[:len(starts)] = starts
        lp = np.zeros(pad, np.int32)
        lp[:len(lens)] = lens
        chars = gather_string_matrix(words, jnp.asarray(sp),
                                     jnp.asarray(lp), w, pad)
        data, v = _scatter_nonnull(chars, valid, jnp.int32(n_rows),
                                   capacity)
        lengths, _ = _scatter_nonnull(jnp.asarray(lp), valid,
                                      jnp.int32(n_rows), capacity)
        return DeviceColumn(dtype, data, v,
                            lengths=lengths.astype(jnp.int32))
    elif phys == "FIXED_LEN_BYTE_ARRAY":
        lo_u, hi_u = _expand_flba(words, v_os, v_sb, nn_cap, type_length)
        return _finish_decimal_words(_u64_to_i64(lo_u), _u64_to_i64(hi_u),
                                     valid, dtype, n_rows, capacity)
    elif phys == "INT64":
        raw = _expand_runs_u64(words, v_os, v_sb, nn_cap)
        dense = _finish(_u64_to_i64(raw), phys, dtype, arrow_type)
    elif phys == "DOUBLE":
        raw = _expand_runs_u64(words, v_os, v_sb, nn_cap)
        dense = _finish(_f64_from_bits(raw), phys, dtype, arrow_type)
    else:
        raw = _expand_runs_u32(words, v_os, v_sb, v_w, v_rv, nn_cap)
        if phys == "INT32":
            dense = _finish(jax.lax.bitcast_convert_type(raw, np.int32),
                            phys, dtype, arrow_type)
        elif phys == "FLOAT":
            dense = _finish(jax.lax.bitcast_convert_type(raw, np.float32),
                            phys, dtype, arrow_type)
        elif phys == "BOOLEAN":
            dense = _finish((raw & 1).astype(jnp.bool_), phys, dtype,
                            arrow_type)
        else:
            raise _Unsupported(f"finish {phys}")
    data, v = _scatter_nonnull(dense, valid, jnp.int32(n_rows), capacity)
    return DeviceColumn(dtype, data, v)


# --------------------------------------------------------------------------
# Public entry
# --------------------------------------------------------------------------

def _dtype_supported(dtype, arrow_type) -> bool:
    import pyarrow as pa

    from .. import types as T
    if dtype is None:
        return False
    if isinstance(dtype, (T.ArrayType, T.MapType, T.StructType, T.NullType,
                          T.BinaryType)):
        return False
    # decimals of every precision are in the envelope: INT32/INT64 backed
    # directly, FIXED_LEN_BYTE_ARRAY via the (lo, hi) word kernels
    if pa.types.is_timestamp(arrow_type) and arrow_type.unit not in (
            "us", "ms"):
        # ns -> us is lossy; the host path's safe cast raises — keep one
        # behavior by sending ns files to the host path
        return False
    return True


#: encodings we can never decode on device — seen in chunk METADATA they
#: let us skip the whole parse+decompress pass for that column
#: NB: BIT_PACKED is deliberately NOT here — parquet-mr (Spark/Hive)
#: lists it for the levels encoding of flat columns even when no value
#: data uses it; it is levels-only per spec, and the page parser already
#: rejects non-RLE def levels.  Rejecting it here would silently disable
#: device decode for every Spark-written file.
_UNSUPPORTED_ENCODINGS = {"DELTA_BINARY_PACKED", "DELTA_LENGTH_BYTE_ARRAY",
                          "DELTA_BYTE_ARRAY", "BYTE_STREAM_SPLIT"}


def _precheck_chunk_meta(cc) -> None:
    """Cheap metadata-only rejection BEFORE reading/decompressing pages:
    the column-chunk footer lists its encodings and codec, so columns that
    can't take the device path cost zero byte-level work."""
    if _CODECS.get(cc.compression, "?") == "?":
        raise _Unsupported(f"codec {cc.compression}")
    encs = set(cc.encodings)
    if encs & _UNSUPPORTED_ENCODINGS:
        raise _Unsupported(f"encodings {sorted(encs)}")
    # pure-PLAIN BYTE_ARRAY chunks decode on device (round 5): the host
    # walks only the u32 length prefixes — native scan, or a python loop
    # bounded PER CHUNK before any decompression happens
    if cc.physical_type == "BYTE_ARRAY" and not (
            encs & {"PLAIN_DICTIONARY", "RLE_DICTIONARY"}):
        from ..native import has
        if not has("srt_byte_array_walk") \
                and cc.num_values > _PY_WALK_MAX:
            raise _Unsupported(
                "PLAIN byte-array walk without native helper")


def decode_file(path: str, row_groups: Optional[Sequence[int]] = None,
                tctx=None, pf=None, conf=None):
    """Decode (a subset of row groups of) one parquet file into a
    :class:`ColumnarBatch`, device-decoding every column the envelope
    supports and falling back to pyarrow per column otherwise.

    Returns ``None`` when no column takes the device path, or when safe
    decode requires the host pipeline's whole-table handling (ragged
    strings) — callers then use their existing host read wholesale.
    """
    import pyarrow.parquet as pq

    from .. import types as T
    from ..columnar.batch import ColumnarBatch
    from ..columnar.column import bucket_capacity
    from ..columnar.convert import arrow_to_device_column

    if pf is None:
        pf = pq.ParquetFile(path)   # callers with an open handle pass it in
    md = pf.metadata
    schema = pf.schema_arrow
    rgs = list(range(md.num_row_groups)) if row_groups is None \
        else list(row_groups)
    if not rgs:
        return None
    n_rows = sum(md.row_group(rg).num_rows for rg in rgs)
    capacity = bucket_capacity(n_rows)

    # flat leaf index per top-level field (nested fields span >1 leaf and
    # their path contains '.'; those take the host path)
    leaf_of_field: Dict[int, int] = {}
    rg0 = md.row_group(rgs[0])
    for li in range(rg0.num_columns):
        path_in = rg0.column(li).path_in_schema
        if "." in path_in:
            continue
        fi = schema.get_field_index(path_in)
        if fi >= 0:
            leaf_of_field[fi] = li

    max_str_bytes = _max_string_matrix_bytes(conf)
    device_cols: Dict[int, object] = {}
    host_fields: List[int] = []
    with open(path, "rb") as fobj:
        for fi, fld in enumerate(schema):
            li = leaf_of_field.get(fi)
            try:
                dtype = T.from_arrow(fld.type)
            except Exception:
                dtype = None
            if li is None or not _dtype_supported(dtype, fld.type):
                host_fields.append(fi)
                continue
            try:
                plans = []
                phys = None
                type_length = int(getattr(md.schema.column(li), "length",
                                          0) or 0)
                for rg in rgs:
                    cc = md.row_group(rg).column(li)
                    phys = cc.physical_type
                    if cc.file_path:
                        raise _Unsupported("external chunk file")
                    if phys == "BYTE_ARRAY" and \
                            isinstance(dtype, T.DecimalType):
                        # legacy writers annotate variable-length
                        # BYTE_ARRAY as decimal — that shape is host-only
                        # (the string-dictionary kernel would mislabel it)
                        raise _Unsupported("BYTE_ARRAY decimal")
                    _precheck_chunk_meta(cc)
                    # offset 0 can never be a real page (files start with
                    # the PAR1 magic) — some writers emit 0 for "absent"
                    offs = [o for o in (cc.dictionary_page_offset,
                                        cc.data_page_offset)
                            if o is not None and o > 0]
                    fobj.seek(min(offs))
                    raw = fobj.read(cc.total_compressed_size)
                    plans.append(_plan_chunk(raw, cc, phys, fld.nullable,
                                             type_length))
                merged = _merge_plans(plans, phys)
                device_cols[fi] = _decode_column_device(
                    merged, phys, dtype, fld.type, capacity, n_rows,
                    max_str_bytes, type_length, conf=conf)
                if tctx is not None:
                    tctx.inc_metric("parquetDeviceDecodedColumns")
            except _Unsupported:
                host_fields.append(fi)
            except _DeclineFile:
                from .decode_stats import set_decline_reason
                set_decline_reason("ragged-strings")
                return None
            except (ValueError, IndexError, KeyError, struct.error,
                    OSError):
                # malformed/truncated chunks surface as low-level errors
                # from the hand-rolled parsers; the contract is per-column
                # fallback — pyarrow reports real corruption cleanly
                if tctx is not None:
                    tctx.inc_metric("parquetDeviceDecodeErrors")
                host_fields.append(fi)

    if not device_cols:
        from .decode_stats import set_decline_reason
        set_decline_reason("no-device-columns")
        return None
    if host_fields:
        names = [schema.field(fi).name for fi in host_fields]
        tbl = pf.read_row_groups(rgs, columns=names)
        for k, fi in enumerate(host_fields):
            device_cols[fi] = arrow_to_device_column(tbl.column(k), capacity)
            if tctx is not None:
                tctx.inc_metric("parquetHostDecodedColumns")

    cols = [device_cols[fi] for fi in range(len(schema))]
    return ColumnarBatch.make([f.name for f in schema], cols, n_rows)
