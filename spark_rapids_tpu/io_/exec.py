"""File scan exec with the reference's multi-file reader strategies
(``GpuMultiFileReader.scala:176-373``): PERFILE (one file per batch),
MULTITHREADED (thread-pool prefetch, cloud-friendly), COALESCING (combine
small files into one batch before upload).  Parquet reads add the
reference's host-side scan pipeline: path replacement + file cache
(``filecache.py``), footer-statistics row-group pruning against pushed
filter conjuncts (``pushdown.py``; ``GpuParquetScan.scala:2765``), and
chunked multi-batch reads (``spark.rapids.sql.reader.chunked``,
``RapidsConf.scala:568``)."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from .. import types as T
from ..columnar.convert import arrow_to_device
from ..config import (MULTITHREAD_READ_NUM_THREADS, PARQUET_PUSHDOWN_ENABLED,
                      PARQUET_READER_TYPE, READER_CHUNKED,
                      READER_CHUNKED_TARGET_ROWS, RapidsConf)
from ..sql.physical.base import CPU, TPU, PhysicalPlan, TaskContext
from . import registry
from .filecache import resolve_read_path


class FileScanExec(PhysicalPlan):
    def __init__(self, node, backend=TPU, conf: Optional[RapidsConf] = None,
                 files_per_partition: int = 1):
        super().__init__()
        self.backend = backend
        self.node = node
        self.conf = conf or RapidsConf.get_global()
        self.files = registry.expand_paths(node.paths)
        self.reader_type = str(self.conf.get(PARQUET_READER_TYPE)).upper()
        if self.reader_type == "AUTO":
            self.reader_type = "MULTITHREADED" if len(self.files) > 1 else "PERFILE"
        self._pool: Optional[ThreadPoolExecutor] = None
        #: (col, op, literal) conjuncts attached by the planner from a
        #: scan-adjacent filter; used for row-group pruning only — the
        #: device filter above still applies the full predicate
        self.pushed_filters: List = []

    @property
    def output(self):
        return self.node.output

    def num_partitions(self):
        if self.reader_type == "COALESCING":
            return 1
        return max(1, len(self.files))

    def _read(self, path, tctx: Optional[TaskContext] = None):
        path = resolve_read_path(path, self.conf)
        if self.node.fmt == "parquet" and self.pushed_filters and \
                bool(self.conf.get(PARQUET_PUSHDOWN_ENABLED)):
            import pyarrow.parquet as pq
            from .pushdown import prune_row_groups
            pf = pq.ParquetFile(path)
            keep = prune_row_groups(pf, self.pushed_filters)
            if keep is not None:
                total = pf.metadata.num_row_groups
                if tctx is not None:
                    tctx.inc_metric("rowGroupsTotal", total)
                    tctx.inc_metric("rowGroupsPruned", total - len(keep))
                if not keep:
                    return pf.schema_arrow.empty_table()
                return pf.read_row_groups(keep)
        return registry.read_file(self.node.fmt, path, self.node.options)

    def _read_chunked_orc(self, path, tctx: TaskContext):
        """ORC chunked reads: one pa.Table per stripe run up to the
        chunk-row target (pyarrow exposes per-stripe reads but not stripe
        statistics, so there is no ORC pruning — parity note vs parquet)."""
        import pyarrow as pa
        import pyarrow.orc as orc
        path = resolve_read_path(path, self.conf)
        f = orc.ORCFile(path)
        if tctx is not None:
            tctx.inc_metric("orcStripesTotal", f.nstripes)
        target = int(self.conf.get(READER_CHUNKED_TARGET_ROWS))
        run: List = []
        rows = 0
        for i in range(f.nstripes):
            run.append(pa.Table.from_batches([f.read_stripe(i)]))
            rows += run[-1].num_rows
            if rows >= target:
                yield pa.concat_tables(run)
                run, rows = [], 0
        if run:
            yield pa.concat_tables(run)
        if f.nstripes == 0:
            yield f.read()

    def _read_chunked(self, path, tctx: TaskContext):
        """Yield one pa.Table per run of row groups up to the chunk-row
        target (parquet PERFILE path only): peak memory is bounded by the
        chunk, not the file."""
        import pyarrow.parquet as pq
        from .pushdown import prune_row_groups
        path = resolve_read_path(path, self.conf)
        pf = pq.ParquetFile(path)
        keep = None
        if self.pushed_filters and bool(
                self.conf.get(PARQUET_PUSHDOWN_ENABLED)):
            keep = prune_row_groups(pf, self.pushed_filters)
        groups = list(range(pf.metadata.num_row_groups)) \
            if keep is None else keep
        if tctx is not None and keep is not None:
            tctx.inc_metric("rowGroupsTotal", pf.metadata.num_row_groups)
            tctx.inc_metric("rowGroupsPruned",
                            pf.metadata.num_row_groups - len(keep))
        if not groups:
            yield pf.schema_arrow.empty_table()
            return
        target = int(self.conf.get(READER_CHUNKED_TARGET_ROWS))
        run: List[int] = []
        rows = 0
        for rg in groups:
            run.append(rg)
            rows += pf.metadata.row_group(rg).num_rows
            if rows >= target:
                yield pf.read_row_groups(run)
                run, rows = [], 0
        if run:
            yield pf.read_row_groups(run)

    def execute(self, pid: int, tctx: TaskContext):
        import jax

        def upload_one(table):
            batch = arrow_to_device(table)
            if self.backend == CPU:
                batch = jax.device_get(batch)
            return batch

        def upload(table):
            """One batch per string-width class (split_for_upload);
            single-batch for the overwhelmingly common case."""
            from ..columnar.convert import split_for_upload
            pieces = split_for_upload(table, self.conf)
            if len(pieces) > 1:
                tctx.inc_metric("raggedStringSplits")
            return [upload_one(p) for p in pieces]

        if self.reader_type == "COALESCING":
            import pyarrow as pa
            n_threads = int(self.conf.get(MULTITHREAD_READ_NUM_THREADS))
            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                tables = list(pool.map(lambda p: self._read(p, tctx),
                                       self.files))
            if tables:
                yield from upload(pa.concat_tables(tables, promote_options="default"))
            return

        if pid >= len(self.files):
            return
        # input_file_name()/block expressions read these off the task
        # (reference InputFileName gated by InputFileBlockRule)
        tctx.input_file = self.files[pid]
        tctx.input_block_start = 0
        try:
            import os as _os
            tctx.input_block_length = _os.path.getsize(self.files[pid])
        except OSError:
            tctx.input_block_length = -1
        if self.reader_type == "MULTITHREADED":
            # per-partition prefetch through a shared pool: submit this file
            # read on a worker thread so decode overlaps device compute
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=int(self.conf.get(MULTITHREAD_READ_NUM_THREADS)))
            fut = self._pool.submit(self._read, self.files[pid], tctx)
            yield from upload(fut.result())
            return
        if self.node.fmt == "parquet" and bool(
                self.conf.get(READER_CHUNKED)):
            for table in self._read_chunked(self.files[pid], tctx):
                tctx.inc_metric("chunkedReadBatches")
                yield from upload(table)
            return
        if self.node.fmt == "orc" and bool(self.conf.get(READER_CHUNKED)):
            for table in self._read_chunked_orc(self.files[pid], tctx):
                tctx.inc_metric("chunkedReadBatches")
                yield from upload(table)
            return
        yield from upload(self._read(self.files[pid], tctx))

    def simple_string(self):
        extra = ""
        if self.pushed_filters:
            fs = ", ".join(f"{c} {op} {v!r}" for c, op, v in
                           self.pushed_filters)
            extra = f" pushed=[{fs}]"
        return (f"{self.node_name()} {self.node.fmt} "
                f"[{len(self.files)} files, {self.reader_type}]{extra}")
