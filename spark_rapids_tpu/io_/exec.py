"""File scan exec with the reference's multi-file reader strategies
(``GpuMultiFileReader.scala:176-373``): PERFILE (one file per batch),
MULTITHREADED (thread-pool prefetch, cloud-friendly), COALESCING (combine
small files into one batch before upload)."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from .. import types as T
from ..columnar.convert import arrow_to_device
from ..config import RapidsConf, MULTITHREAD_READ_NUM_THREADS, PARQUET_READER_TYPE
from ..sql.physical.base import CPU, TPU, PhysicalPlan, TaskContext
from . import registry


class FileScanExec(PhysicalPlan):
    def __init__(self, node, backend=TPU, conf: Optional[RapidsConf] = None,
                 files_per_partition: int = 1):
        super().__init__()
        self.backend = backend
        self.node = node
        self.conf = conf or RapidsConf.get_global()
        self.files = registry.expand_paths(node.paths)
        self.reader_type = str(self.conf.get(PARQUET_READER_TYPE)).upper()
        if self.reader_type == "AUTO":
            self.reader_type = "MULTITHREADED" if len(self.files) > 1 else "PERFILE"
        self._pool: Optional[ThreadPoolExecutor] = None

    @property
    def output(self):
        return self.node.output

    def num_partitions(self):
        if self.reader_type == "COALESCING":
            return 1
        return max(1, len(self.files))

    def _read(self, path):
        return registry.read_file(self.node.fmt, path, self.node.options)

    def execute(self, pid: int, tctx: TaskContext):
        import jax

        def upload(table):
            batch = arrow_to_device(table)
            if self.backend == CPU:
                batch = jax.device_get(batch)
            return batch

        if self.reader_type == "COALESCING":
            import pyarrow as pa
            n_threads = int(self.conf.get(MULTITHREAD_READ_NUM_THREADS))
            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                tables = list(pool.map(self._read, self.files))
            if tables:
                yield upload(pa.concat_tables(tables, promote_options="default"))
            return

        if pid >= len(self.files):
            return
        if self.reader_type == "MULTITHREADED":
            # per-partition prefetch through a shared pool: submit this file
            # read on a worker thread so decode overlaps device compute
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=int(self.conf.get(MULTITHREAD_READ_NUM_THREADS)))
            fut = self._pool.submit(self._read, self.files[pid])
            yield upload(fut.result())
            return
        yield upload(self._read(self.files[pid]))

    def simple_string(self):
        return (f"{self.node_name()} {self.node.fmt} "
                f"[{len(self.files)} files, {self.reader_type}]")
