"""File scan exec with the reference's multi-file reader strategies
(``GpuMultiFileReader.scala:176-373``): PERFILE (one file per batch),
MULTITHREADED (thread-pool prefetch, cloud-friendly), COALESCING (combine
small files into one batch before upload).  Parquet reads add the
reference's host-side scan pipeline: path replacement + file cache
(``filecache.py``), footer-statistics row-group pruning against pushed
filter conjuncts (``pushdown.py``; ``GpuParquetScan.scala:2765``), and
chunked multi-batch reads (``spark.rapids.sql.reader.chunked``,
``RapidsConf.scala:568``)."""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from .. import types as T
from ..columnar.convert import arrow_to_device
from ..config import (CSV_DEVICE_DECODE, JSON_DEVICE_DECODE,
                      MULTITHREAD_READ_NUM_THREADS,
                      ORC_DEVICE_DECODE, PARQUET_DEVICE_DECODE,
                      PARQUET_PUSHDOWN_ENABLED, PARQUET_READER_TYPE,
                      READER_CHUNKED, READER_CHUNKED_TARGET_ROWS,
                      RapidsConf)
from ..sql.physical.base import CPU, TPU, PhysicalPlan, TaskContext
from . import registry
from .filecache import resolve_read_path


class FileScanExec(PhysicalPlan):
    def __init__(self, node, backend=TPU, conf: Optional[RapidsConf] = None,
                 files_per_partition: int = 1):
        super().__init__()
        self.backend = backend
        self.node = node
        self.conf = conf or RapidsConf.get_global()
        self.files = registry.expand_paths(node.paths)
        self.reader_type = str(self.conf.get(PARQUET_READER_TYPE)).upper()
        if self.reader_type == "AUTO":
            self.reader_type = "MULTITHREADED" if len(self.files) > 1 else "PERFILE"
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()
        #: (col, op, literal) conjuncts attached by the planner from a
        #: scan-adjacent filter; used for row-group pruning only — the
        #: device filter above still applies the full predicate
        self.pushed_filters: List = []

    @property
    def output(self):
        return self.node.output

    def num_partitions(self):
        if self.reader_type == "COALESCING":
            return 1
        return max(1, len(self.files))

    def _read(self, path, tctx: Optional[TaskContext] = None):
        path = resolve_read_path(path, self.conf)
        if self.node.fmt == "parquet" and self.pushed_filters and \
                bool(self.conf.get(PARQUET_PUSHDOWN_ENABLED)):
            import pyarrow.parquet as pq
            from .pushdown import prune_row_groups
            pf = pq.ParquetFile(path)
            keep = prune_row_groups(pf, self.pushed_filters)
            if keep is not None:
                self._emit_prune_stats(
                    (pf.metadata.num_row_groups, len(keep)), tctx)
                if not keep:
                    return pf.schema_arrow.empty_table()
                return pf.read_row_groups(keep)
        return registry.read_file(self.node.fmt, path, self.node.options)

    def _read_chunked_orc(self, path, tctx: TaskContext):
        """ORC chunked reads: one pa.Table per stripe run up to the
        chunk-row target (pyarrow exposes per-stripe reads but not stripe
        statistics, so there is no ORC pruning — parity note vs parquet)."""
        import pyarrow as pa
        import pyarrow.orc as orc
        path = resolve_read_path(path, self.conf)
        f = orc.ORCFile(path)
        if tctx is not None:
            tctx.inc_metric("orcStripesTotal", f.nstripes)
        target = int(self.conf.get(READER_CHUNKED_TARGET_ROWS))
        run: List = []
        rows = 0
        for i in range(f.nstripes):
            run.append(pa.Table.from_batches([f.read_stripe(i)]))
            rows += run[-1].num_rows
            if rows >= target:
                yield pa.concat_tables(run)
                run, rows = [], 0
        if run:
            yield pa.concat_tables(run)
        if f.nstripes == 0:
            yield f.read()

    def _read_chunked(self, path, tctx: TaskContext):
        """Yield one pa.Table per run of row groups up to the chunk-row
        target (parquet PERFILE path only): peak memory is bounded by the
        chunk, not the file."""
        path = resolve_read_path(path, self.conf)
        pf, runs, prune_stats = self._parquet_runs(path)
        self._emit_prune_stats(prune_stats, tctx)
        if not runs:
            yield pf.schema_arrow.empty_table()
            return
        for run in runs:
            yield pf.read_row_groups(run)

    def _parquet_runs(self, path: str):
        """The ONE implementation of prune-then-split for parquet reads
        (both the host chunked path and the device-decode path use it, so
        the two can't drift): pushdown pruning, then row-group runs sized
        by the chunked-read row target (a single run when chunked reads
        are off).  Returns ``(pf, runs, prune_stats)`` with prune_stats
        either None or ``(total_groups, kept_groups)`` — the caller that
        commits to a path emits the metrics exactly once."""
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(path)
        keep = None
        stats = None
        if self.pushed_filters and bool(
                self.conf.get(PARQUET_PUSHDOWN_ENABLED)):
            from .pushdown import prune_row_groups
            keep = prune_row_groups(pf, self.pushed_filters)
            if keep is not None:
                stats = (pf.metadata.num_row_groups, len(keep))
        groups = list(range(pf.metadata.num_row_groups)) \
            if keep is None else keep
        if not bool(self.conf.get(READER_CHUNKED)):
            return pf, ([groups] if groups else []), stats
        target = int(self.conf.get(READER_CHUNKED_TARGET_ROWS))
        runs: List[List[int]] = []
        run: List[int] = []
        rows = 0
        for rg in groups:
            run.append(rg)
            rows += pf.metadata.row_group(rg).num_rows
            if rows >= target:
                runs.append(run)
                run, rows = [], 0
        if run:
            runs.append(run)
        return pf, runs, stats

    @staticmethod
    def _emit_prune_stats(stats, tctx: Optional[TaskContext]) -> None:
        if stats is not None and tctx is not None:
            total, kept = stats
            tctx.inc_metric("rowGroupsTotal", total)
            tctx.inc_metric("rowGroupsPruned", total - kept)

    def _execute_parquet_device(self, path: str, tctx: TaskContext,
                                upload):
        """Unified parquet partition executor when device decode is on:
        ONE footer parse + prune (``_parquet_runs``), then per-run device
        decode with per-run host fallback — the fallback reuses the open
        ``pf`` and goes through ``upload`` so the ragged-string width-class
        splitting applies exactly as on the host pipeline."""
        import jax

        from .device_parquet import decode_file

        path = resolve_read_path(path, self.conf)
        pf, runs, prune_stats = self._parquet_runs(path)
        self._emit_prune_stats(prune_stats, tctx)
        chunked = bool(self.conf.get(READER_CHUNKED))
        if not runs:
            yield from upload(pf.schema_arrow.empty_table())
            return
        from . import decode_stats as DS
        declined = False   # a whole-file decline holds for every run
        for run in runs:
            if chunked:
                tctx.inc_metric("chunkedReadBatches")
            run_bytes = sum(pf.metadata.row_group(rg).total_byte_size
                            for rg in run)
            batch = None if declined else decode_file(
                path, run, tctx, pf=pf, conf=self.conf)
            if batch is None:
                DS.record_declined(
                    "parquet", run_bytes,
                    reason="prior-decline" if declined else None)
                declined = True
                yield from upload(pf.read_row_groups(run))
            else:
                DS.record_engaged("parquet", run_bytes)
                yield batch if self.backend != CPU \
                    else jax.device_get(batch)

    def _execute_orc_device(self, path: str, tctx: TaskContext, upload):
        """ORC partition executor when device decode is on: stripe-run
        batching per the chunked-read target, per-run device decode with
        per-run host fallback (mirrors ``_execute_parquet_device``)."""
        import jax
        import pyarrow as pa
        import pyarrow.orc as pa_orc

        from .device_orc import decode_file

        path = resolve_read_path(path, self.conf)
        f = pa_orc.ORCFile(path)
        if tctx is not None:
            tctx.inc_metric("orcStripesTotal", f.nstripes)
        stripes = list(range(f.nstripes))
        if not stripes:
            yield from upload(f.read())
            return
        if bool(self.conf.get(READER_CHUNKED)):
            target = int(self.conf.get(READER_CHUNKED_TARGET_ROWS))
            runs: List[List[int]] = []
            run: List[int] = []
            # pyarrow exposes only file-level nrows, so batch stripes by
            # the average rows-per-stripe (uniform-stripe approximation)
            per = max(1, target // max(1, f.nrows // max(f.nstripes, 1)))
            for s in stripes:
                run.append(s)
                if len(run) >= per:
                    runs.append(run)
                    run = []
            if run:
                runs.append(run)
        else:
            runs = [stripes]
        from . import decode_stats as DS
        import os as _os
        try:
            fsize = _os.path.getsize(path)
        except OSError:
            fsize = 0
        declined = False
        for run in runs:
            if len(runs) > 1:
                tctx.inc_metric("chunkedReadBatches")
            run_bytes = fsize * len(run) // max(f.nstripes, 1)
            batch = None if declined else decode_file(
                path, run if len(runs) > 1 else None, tctx,
                orc_file=f, conf=self.conf)
            if batch is None:
                DS.record_declined(
                    "orc", run_bytes,
                    reason="prior-decline" if declined else None)
                declined = True
                if len(runs) > 1:
                    parts = [pa.Table.from_batches([f.read_stripe(s)])
                             for s in run]
                    yield from upload(pa.concat_tables(parts))
                else:
                    yield from upload(f.read())
            else:
                DS.record_engaged("orc", run_bytes)
                if self.backend == CPU:
                    batch = jax.device_get(batch)
                yield batch

    def _coalescing_device(self, infos, schema0, tctx: TaskContext,
                           upload):
        """COALESCING with device decode: decode each (pruned) file and
        concat on device.  Ragged-string fallbacks that split into width
        classes stay separate batches — re-concatenating them into one
        max-width matrix would rebuild exactly the blow-up the split
        exists to prevent."""
        import jax

        from ..columnar.batch import ColumnarBatch
        from .device_parquet import decode_file
        batches = []
        extra = []
        for path, pf, groups, prune_stats in infos:
            self._emit_prune_stats(prune_stats, tctx)
            if not groups:
                continue
            from . import decode_stats as DS
            nb = sum(pf.metadata.row_group(rg).total_byte_size
                     for rg in groups)
            batch = decode_file(path, groups, tctx, pf=pf, conf=self.conf)
            if batch is None:
                DS.record_declined("parquet", nb)
                pieces = upload(pf.read_row_groups(groups))
                if len(pieces) == 1:
                    batches.append(pieces[0])
                else:
                    extra.extend(pieces)
            else:
                DS.record_engaged("parquet", nb)
                batches.append(batch)
        if batches:
            tctx.inc_metric("coalescedDeviceConcat")
            out = ColumnarBatch.concat(batches)
            if self.backend == CPU:
                out = jax.device_get(out)
            yield out
        elif not extra:
            # everything pruned away: same empty-schema batch the host
            # path produces
            yield from upload(schema0.empty_table())
        yield from extra

    def _text_device_scan(self, pid, tctx, upload, opts, decode_fn,
                          host_read_fn):
        """Shared read-decode-decline protocol for the text-format device
        parsers (CSV and JSON): read the bytes once, try the device
        decoder, and on decline re-parse the SAME bytes on host — no
        second disk/cloud read.  Yields the batches and returns True when
        this path served the partition; False (unreadable file / decoder
        wants the full host machinery) lets the caller's host path run
        and raise its own errors."""
        import io as _io

        import jax
        path = resolve_read_path(self.files[pid], self.conf)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return False
        from . import decode_stats as DS
        fmt = registry._normalize_fmt(self.node.fmt, opts)
        batch = decode_fn(path, opts, self.node.output, tctx, self.conf,
                          raw=raw)
        if batch is not None:
            DS.record_engaged(fmt, len(raw))
            if self.backend == CPU:
                batch = jax.device_get(batch)
            yield batch
            return True
        DS.record_declined(fmt, len(raw))
        for piece in upload(host_read_fn(_io.BytesIO(raw), opts)):
            yield piece
        return True

    def execute(self, pid: int, tctx: TaskContext):
        import jax

        def upload_one(table):
            batch = arrow_to_device(table, conf=self.conf)
            if self.backend == CPU:
                batch = jax.device_get(batch)
            return batch

        def upload(table):
            """One batch per string-width class (split_for_upload);
            single-batch for the overwhelmingly common case."""
            from ..columnar.convert import split_for_upload
            pieces = split_for_upload(table, self.conf)
            if len(pieces) > 1:
                tctx.inc_metric("raggedStringSplits")
            return [upload_one(p) for p in pieces]

        if self.reader_type == "COALESCING":
            import pyarrow as pa
            # device decode per file + device concat (round 5): small
            # files combine ON DEVICE; per-file declines host-read and
            # join the same concat.  Footer-only schema agreement is
            # checked BEFORE any decode (a late mismatch must not throw
            # completed device work away); mismatched schemas take the
            # host promote-concat path below.
            if self.node.fmt == "parquet" and self.files and bool(
                    self.conf.get(PARQUET_DEVICE_DECODE)):
                infos = []
                schema0 = None
                ok = True
                for p in self.files:
                    path = resolve_read_path(p, self.conf)
                    try:
                        # honors pushdown row-group pruning, like _read
                        pf, runs, prune_stats = self._parquet_runs(path)
                    except OSError:
                        ok = False
                        break
                    if schema0 is None:
                        schema0 = pf.schema_arrow
                    elif pf.schema_arrow != schema0:
                        ok = False  # promotion needed: host concat path
                        break
                    groups = [g for run in runs for g in run]
                    infos.append((path, pf, groups, prune_stats))
                if ok:
                    yield from self._coalescing_device(infos, schema0,
                                                       tctx, upload)
                    return
            n_threads = int(self.conf.get(MULTITHREAD_READ_NUM_THREADS))
            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                tables = list(pool.map(lambda p: self._read(p, tctx),
                                       self.files))
            if tables:
                yield from upload(pa.concat_tables(tables, promote_options="default"))
            return

        if pid >= len(self.files):
            return
        # input_file_name()/block expressions read these off the task
        # (reference InputFileName gated by InputFileBlockRule)
        tctx.input_file = self.files[pid]
        tctx.input_block_start = 0
        try:
            import os as _os
            tctx.input_block_length = _os.path.getsize(self.files[pid])
        except OSError:
            tctx.input_block_length = -1
        # device decode covers PERFILE and MULTITHREADED parquet scans
        # (COALESCING concatenates host tables first); with it, the heavy
        # per-value work is on the device, so losing the host-decode
        # prefetch overlap in the MULTITHREADED case is a win, not a loss
        if self.node.fmt == "parquet" and bool(
                self.conf.get(PARQUET_DEVICE_DECODE)):
            yield from self._execute_parquet_device(self.files[pid], tctx,
                                                    upload)
            return
        if self.node.fmt == "orc" and bool(
                self.conf.get(ORC_DEVICE_DECODE)):
            yield from self._execute_orc_device(self.files[pid], tctx,
                                                upload)
            return
        opts = dict(self.node.options)
        text_fmt = registry._normalize_fmt(self.node.fmt, opts)
        if text_fmt == "csv" and bool(self.conf.get(CSV_DEVICE_DECODE)):
            from .device_csv import decode_file as _decode
            done = yield from self._text_device_scan(
                pid, tctx, upload, opts, _decode,
                registry.read_csv_source)
            if done:
                return
        if text_fmt == "json" and bool(self.conf.get(JSON_DEVICE_DECODE)):
            from .device_json import decode_file as _decode
            done = yield from self._text_device_scan(
                pid, tctx, upload, opts, _decode,
                registry.read_json_source)
            if done:
                return
        if self.reader_type == "MULTITHREADED":
            # per-partition prefetch through a shared pool: submit this file
            # read on a worker thread so decode overlaps device compute.
            # Lazy init is locked: under the parallel partition scheduler
            # several partitions race in here, and a lost pool would leak
            # its threads for the process lifetime.
            if self._pool is None:
                with self._pool_lock:
                    if self._pool is None:
                        self._pool = ThreadPoolExecutor(
                            max_workers=int(self.conf.get(
                                MULTITHREAD_READ_NUM_THREADS)))
            fut = self._pool.submit(self._read, self.files[pid], tctx)
            yield from upload(fut.result())
            return
        if self.node.fmt == "parquet" and bool(
                self.conf.get(READER_CHUNKED)):
            for table in self._read_chunked(self.files[pid], tctx):
                tctx.inc_metric("chunkedReadBatches")
                yield from upload(table)
            return
        if self.node.fmt == "orc" and bool(self.conf.get(READER_CHUNKED)):
            for table in self._read_chunked_orc(self.files[pid], tctx):
                tctx.inc_metric("chunkedReadBatches")
                yield from upload(table)
            return
        yield from upload(self._read(self.files[pid], tctx))

    def simple_string(self):
        extra = ""
        if self.pushed_filters:
            fs = ", ".join(f"{c} {op} {v!r}" for c, op, v in
                           self.pushed_filters)
            extra = f" pushed=[{fs}]"
        return (f"{self.node_name()} {self.node.fmt} "
                f"[{len(self.files)} files, {self.reader_type}]{extra}")
