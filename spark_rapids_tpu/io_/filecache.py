"""Local disk file cache + path-replacement rules.

Reference parity targets:

* the file-cache feature (``spark.rapids.filecache.*``; hook points in
  ``GpuParquetScan.scala`` / ``GpuOrcDataReader`` — the implementation
  ships in the closed ``rapids-4-spark-private`` jar, so this is a clean
  re-design, not a port): cache input files on fast local disk keyed by
  (path, size, mtime), LRU-evicted under a byte budget, so repeated scans
  of remote data pay the fetch once;
* Alluxio path replacement (``AlluxioUtils.scala:671``,
  ``spark.rapids.alluxio.pathsToReplace``): rewrite configured path
  prefixes to a co-located cache mount before reading.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Sequence

from ..config import (FILECACHE_ENABLED, FILECACHE_MAX_BYTES, FILECACHE_PATH,
                      IO_REPLACE_PATHS, RapidsConf)

#: observability (tests / metrics)
STATS = {"hits": 0, "misses": 0, "evictions": 0, "rewrites": 0}


def rewrite_path(path: str, conf: Optional[RapidsConf] = None) -> str:
    """Apply ``spark.rapids.tpu.io.replacePaths`` prefix rules
    ('old->new', comma-separated; first match wins)."""
    conf = conf or RapidsConf.get_global()
    rules = str(conf.get(IO_REPLACE_PATHS) or "")
    if not rules:
        return path
    for rule in rules.split(","):
        rule = rule.strip()
        if "->" not in rule:
            continue
        old, new = rule.split("->", 1)
        if old and path.startswith(old):
            STATS["rewrites"] += 1
            return new + path[len(old):]
    return path


class FileCache:
    """LRU disk cache of input files.  ``get_local`` returns a path that
    is guaranteed local: a cache copy when caching is on (and the source
    exists), else the source path itself."""

    _instance: Optional["FileCache"] = None
    _lock = threading.Lock()

    def __init__(self, root: str, max_bytes: int):
        self.root = root
        self.max_bytes = max_bytes
        os.makedirs(root, exist_ok=True)
        self._mu = threading.Lock()
        # key -> (cached_path, size); insertion order = LRU order
        self._entries: Dict[str, List] = {}
        self._total = 0

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None) -> "FileCache":
        conf = conf or RapidsConf.get_global()
        with cls._lock:
            if cls._instance is None:
                root = str(conf.get(FILECACHE_PATH) or "")
                if not root:
                    root = os.path.join(tempfile.gettempdir(),
                                        "srt-filecache")
                cls._instance = FileCache(
                    root, int(conf.get(FILECACHE_MAX_BYTES)))
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def _key(self, path: str) -> Optional[str]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        import hashlib
        raw = f"{os.path.abspath(path)}|{st.st_size}|{int(st.st_mtime_ns)}"
        return hashlib.sha256(raw.encode()).hexdigest()[:32]

    #: grace period before an entry may be evicted: a reader that was just
    #: handed a path must get to open it before LRU removal (the budget may
    #: transiently overshoot by the grace window's working set)
    _EVICT_GRACE_S = 60.0

    def get_local(self, path: str) -> str:
        import time
        key = self._key(path)
        if key is None:
            return path
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None and os.path.exists(ent[0]):
                STATS["hits"] += 1
                # refresh LRU position + last-touch time
                self._entries.pop(key)
                ent[2] = time.monotonic()
                self._entries[key] = ent
                return ent[0]
            STATS["misses"] += 1
        # copy outside the lock (large files)
        dst = os.path.join(self.root, key + "-" + os.path.basename(path))
        tmp = dst + f".tmp-{threading.get_ident()}"
        shutil.copyfile(path, tmp)
        os.replace(tmp, dst)
        size = os.path.getsize(dst)
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total -= old[1]  # concurrent miss on the same key
            self._entries[key] = [dst, size, time.monotonic()]
            self._total += size
            now = time.monotonic()
            while self._total > self.max_bytes and len(self._entries) > 1:
                old_key = next(iter(self._entries))
                opath, osize, otouch = self._entries[old_key]
                if old_key == key or now - otouch < self._EVICT_GRACE_S:
                    break  # recently handed out: a reader may not have
                    # opened it yet (entries are LRU-ordered, so nothing
                    # older remains)
                self._entries.pop(old_key)
                self._total -= osize
                STATS["evictions"] += 1
                try:
                    os.remove(opath)
                except OSError:
                    pass
        return dst


def resolve_read_path(path: str, conf: Optional[RapidsConf] = None) -> str:
    """Path-replacement rules, then the file cache when enabled."""
    conf = conf or RapidsConf.get_global()
    path = rewrite_path(path, conf)
    if bool(conf.get(FILECACHE_ENABLED)):
        return FileCache.get(conf).get_local(path)
    return path
