"""Parquet footer statistics pruning — the host-side half of the
reference's scan pipeline (``GpuParquetScan.scala``: host threads parse the
footer, filter row groups by predicate + statistics, assemble surviving
blocks; ``ParquetPartitionReader:2765``).

The planner attaches scan-adjacent filter conjuncts of the shape
``col <op> literal`` to the FileScanExec (``pushed_filters``); this module
evaluates them against each row group's column chunk min/max/null-count
statistics.  Pruning is conservative: a row group is skipped only when the
statistics PROVE no row can match; the full filter still runs on the
device afterwards, so pushdown never changes results."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

#: (column name, op, literal) with op in  = != < <= > >= in isnull isnotnull
PushedFilter = Tuple[str, str, Any]


def extract_pushable(condition, output) -> List[PushedFilter]:
    """Split a filter condition into pushable (col op literal) conjuncts.
    Unpushable conjuncts are simply not pushed (the device filter stays)."""
    from ..sql.expressions.core import AttributeReference, Literal
    from ..sql.expressions.predicates import (And, EqualTo, GreaterThan,
                                              GreaterThanOrEqual, In, IsNotNull,
                                              IsNull, LessThan,
                                              LessThanOrEqual)

    from ..sql.expressions.cast import Cast

    names = {a.name for a in output}
    out: List[PushedFilter] = []

    def as_literal(e):
        """Literal, possibly under type-coercion casts (the analyzer wraps
        int literals compared against bigint columns in CAST).  The python
        value is unchanged by a widening cast, which is the only coercion
        the analyzer inserts on the literal side."""
        while isinstance(e, Cast):
            e = e.children[0]
        return e if isinstance(e, Literal) else None

    def visit(e):
        if isinstance(e, And):
            for c in e.children:
                visit(c)
            return
        if isinstance(e, IsNull) and isinstance(e.children[0],
                                                AttributeReference):
            out.append((e.children[0].name, "isnull", None))
            return
        if isinstance(e, IsNotNull) and isinstance(e.children[0],
                                                   AttributeReference):
            out.append((e.children[0].name, "isnotnull", None))
            return
        ops = {EqualTo: "=", LessThan: "<", LessThanOrEqual: "<=",
               GreaterThan: ">", GreaterThanOrEqual: ">="}
        for cls, op in ops.items():
            if isinstance(e, cls):
                l, r = e.children
                ll, rl = as_literal(l), as_literal(r)
                if isinstance(l, AttributeReference) and rl is not None:
                    out.append((l.name, op, rl.value))
                elif isinstance(r, AttributeReference) and ll is not None:
                    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                            "=": "="}
                    out.append((r.name, flip[op], ll.value))
                return
        if isinstance(e, In):
            l = e.children[0]
            vals = [as_literal(v) for v in e.children[1:]]
            if isinstance(l, AttributeReference) and all(
                    v is not None for v in vals):
                out.append((l.name, "in", tuple(v.value for v in vals)))
            return

    visit(condition)
    return [f for f in out if f[0] in names]


def stats_possible(lo, hi, op: str, lit) -> bool:
    """Could any value in [lo, hi] satisfy (op, lit)?  The one shared
    min/max-overlap predicate behind every statistics skip (parquet row
    groups here; iceberg data-file bounds in ``iceberg/table.py``).
    Conservative: unknown comparisons (TypeError) keep the unit."""
    try:
        if op == "=":
            return lo <= lit <= hi
        if op == "<":
            return lo < lit
        if op == "<=":
            return lo <= lit
        if op == ">":
            return hi > lit
        if op == ">=":
            return hi >= lit
        if op == "in":
            return any(lo <= x <= hi for x in lit)
    except TypeError:
        return True
    return True


def _rg_possible(stats, op: str, lit) -> bool:
    """Can any row in a row group with these column statistics match?"""
    if stats is None or not stats.has_min_max:
        return True
    if op == "isnull":
        return stats.null_count is None or stats.null_count > 0
    if op == "isnotnull":
        return stats.num_values is None or stats.num_values > 0
    return stats_possible(stats.min, stats.max, op, lit)


def prune_row_groups(pf, filters: Sequence[PushedFilter]) -> Optional[List[int]]:
    """Surviving row-group indices for a ``pyarrow.parquet.ParquetFile``
    under the pushed filters; None = keep everything (no stats/filters)."""
    if not filters:
        return None
    md = pf.metadata
    name_to_col = {md.schema.column(i).name: i
                   for i in range(md.num_columns)}
    keep: List[int] = []
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        ok = True
        for col, op, lit in filters:
            ci = name_to_col.get(col)
            if ci is None:
                continue
            stats = g.column(ci).statistics
            if not _rg_possible(stats, op, lit):
                ok = False
                break
        if ok:
            keep.append(rg)
    return keep
