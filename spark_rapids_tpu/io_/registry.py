"""Format registry + schema inference for file sources (reference scan
framework SURVEY §2.5).  Host decode is pyarrow (the CPU-side parse the
reference does before device upload); the TPU gets one upload per batch."""

from __future__ import annotations

import glob as _glob
import os
from typing import Dict, List, Optional, Sequence

import pyarrow as pa

from .. import types as T


def expand_paths(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        elif os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if not f.startswith((".", "_")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    return out


def _normalize_fmt(fmt: str, options: Dict) -> str:
    """hive text tables are ^A-delimited headerless csv (reference
    GpuHiveTextFileFormat, org/apache/spark/sql/hive/rapids)."""
    if fmt in ("hivetext", "hive-text", "hive"):
        options.setdefault("sep", "\x01")
        options.setdefault("header", "false")
        return "csv"
    return fmt


def infer_schema(fmt: str, paths: Sequence[str], options: Dict) -> T.StructType:
    fmt = _normalize_fmt(fmt, options)
    files = expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no input files for {paths}")
    f0 = files[0]
    if fmt == "parquet":
        import pyarrow.parquet as pq
        schema = pq.read_schema(f0)
    elif fmt == "orc":
        import pyarrow.orc as orc
        schema = orc.ORCFile(f0).schema
    elif fmt == "csv":
        table = read_file(fmt, f0, options, head_rows=1000)
        schema = table.schema
    elif fmt == "json":
        table = read_file(fmt, f0, options, head_rows=1000)
        schema = table.schema
    elif fmt == "avro":
        from .avro_reader import avro_schema
        return avro_schema(f0)
    else:
        raise ValueError(f"unknown format {fmt}")
    return T.StructType(tuple(
        T.StructField(f.name, T.from_arrow(f.type), f.nullable)
        for f in schema))


def read_csv_source(src, options: Dict,
                    columns: Optional[List[str]] = None) -> pa.Table:
    """CSV parse over a path OR a file-like source (the device decoder's
    decline path re-parses the bytes it already read)."""
    import pyarrow.csv as pcsv
    has_header = str(options.get("header", "true")).lower() == "true"
    sep = options.get("sep", options.get("delimiter", ","))
    read_opts = pcsv.ReadOptions(
        autogenerate_column_names=not has_header)
    parse_opts = pcsv.ParseOptions(delimiter=sep)
    convert = pcsv.ConvertOptions(
        null_values=[options.get("nullValue", "")],
        strings_can_be_null=True)
    t = pcsv.read_csv(src, read_options=read_opts,
                      parse_options=parse_opts, convert_options=convert)
    if not has_header:
        t = t.rename_columns([f"_c{i}" for i in range(t.num_columns)])
    if columns:
        t = t.select(columns)
    return t


def read_json_source(src, options: Dict,
                     columns: Optional[List[str]] = None) -> pa.Table:
    """JSON-lines parse over a path OR a file-like source (the device
    decoder's decline path re-parses the bytes it already read)."""
    import pyarrow.json as pjson
    t = pjson.read_json(src)
    if columns:
        t = t.select(columns)
    return t


def read_file(fmt: str, path: str, options: Dict,
              columns: Optional[List[str]] = None,
              head_rows: Optional[int] = None) -> pa.Table:
    fmt = _normalize_fmt(fmt, options)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return pq.read_table(path, columns=columns)
    if fmt == "orc":
        import pyarrow.orc as orc
        return orc.ORCFile(path).read(columns=columns)
    if fmt == "csv":
        return read_csv_source(path, options, columns)
    if fmt == "json":
        return read_json_source(path, options, columns)
    if fmt == "avro":
        from .avro_reader import read_avro
        t = read_avro(path)
        if columns:
            t = t.select(columns)
        return t
    raise ValueError(f"unknown format {fmt}")
