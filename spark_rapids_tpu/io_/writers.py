"""File writers — the analog of the reference's write stack (SURVEY §2.5):
``ColumnarOutputWriter.scala:251`` (per-file writer), ``GpuFileFormatDataWriter
.scala:1135`` (single / dynamic-partition / concurrent-writer task writers),
``GpuInsertIntoHadoopFsRelationCommand.scala`` (job orchestration, save
modes), and ``BasicColumnarWriteStatsTracker.scala`` /
``GpuWriteStatsTracker.scala`` (stats).

Device batches are brought to host as Arrow (the D2H transition the reference
does before its GPU encoders hand bytes to the output stream) and encoded by
format-specific writers; parquet/orc get arrow-native encoders, csv/json are
text encodes, avro uses the in-repo container writer.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import urllib.parse
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import pyarrow as pa

from ..columnar.convert import device_to_arrow
from ..config import RapidsConf
from ..sql.physical.base import PhysicalPlan, TaskContext

_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv",
        "json": ".json", "avro": ".avro", "hivetext": ".txt",
        "hive-text": ".txt", "hive": ".txt"}


# --------------------------------------------------------------------------
# Per-format encoders (ColumnarOutputWriter analogs)
# --------------------------------------------------------------------------

def write_table(fmt: str, table: pa.Table, path: str, options: Dict) -> None:
    from .registry import _normalize_fmt
    from ..serving import note_write
    # per-file invalidation sweep (serving result/broadcast caches); the
    # job-level sweep in run_write_job covers the directory, this covers
    # direct single-file writes (delta/iceberg data files, tests)
    note_write(path)
    fmt = _normalize_fmt(fmt, options)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        codec = options.get("compression", "snappy")
        pq.write_table(table, path, compression=codec)
    elif fmt == "orc":
        import pyarrow.orc as orc
        orc.write_table(table, path)
    elif fmt == "csv":
        import pyarrow.csv as pcsv
        header = str(options.get("header", "true")).lower() == "true"
        sep = options.get("sep", options.get("delimiter", ","))
        opts = pcsv.WriteOptions(include_header=header, delimiter=sep)
        pcsv.write_csv(table, path, opts)
    elif fmt == "json":
        _write_ndjson(table, path)
    elif fmt == "avro":
        from .avro_reader import write_avro
        write_avro(table, path, options)
    else:
        raise ValueError(f"unknown write format {fmt!r}")


def _write_ndjson(table: pa.Table, path: str) -> None:
    import datetime
    import decimal

    def default(o):
        if isinstance(o, (datetime.date, datetime.datetime)):
            return o.isoformat()
        if isinstance(o, decimal.Decimal):
            return str(o)
        if isinstance(o, bytes):
            return o.decode("utf-8", "replace")
        raise TypeError(type(o))

    with open(path, "w") as fh:
        for row in table.to_pylist():
            fh.write(json.dumps(
                {k: v for k, v in row.items() if v is not None},
                default=default))
            fh.write("\n")


# --------------------------------------------------------------------------
# Write stats (BasicColumnarWriteStatsTracker analog)
# --------------------------------------------------------------------------

@dataclass
class WriteTaskStats:
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    write_time_s: float = 0.0
    partition_paths: List[str] = field(default_factory=list)

    def merge(self, other: "WriteTaskStats") -> None:
        self.num_files += other.num_files
        self.num_rows += other.num_rows
        self.num_bytes += other.num_bytes
        self.write_time_s += other.write_time_s
        for p in other.partition_paths:
            if p not in self.partition_paths:
                self.partition_paths.append(p)


# --------------------------------------------------------------------------
# Task-level writer: single-directory or dynamic partitioning
# (GpuFileFormatDataWriter.scala single/dynamic writers)
# --------------------------------------------------------------------------

def _escape_path_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    return urllib.parse.quote(str(v), safe="")


class TaskFileWriter:
    def __init__(self, fmt: str, base_path: str, partition_by: Sequence[str],
                 options: Dict, task_id: int):
        self.fmt = fmt
        self.base_path = base_path
        self.partition_by = list(partition_by)
        self.options = options
        self.task_id = task_id
        self.stats = WriteTaskStats()
        self._seq = 0

    def _file_name(self) -> str:
        name = (f"part-{self.task_id:05d}-{self._seq:03d}-"
                f"{uuid.uuid4().hex[:12]}{_EXT[self.fmt]}")
        self._seq += 1
        return name

    def write(self, table: pa.Table) -> None:
        if table.num_rows == 0:
            return
        t0 = time.perf_counter()
        if not self.partition_by:
            self._write_one(table, self.base_path)
        else:
            self._write_partitioned(table)
        self.stats.write_time_s += time.perf_counter() - t0

    def _write_one(self, table: pa.Table, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, self._file_name())
        write_table(self.fmt, table, path, self.options)
        self.stats.num_files += 1
        self.stats.num_rows += table.num_rows
        self.stats.num_bytes += os.path.getsize(path)

    def _write_partitioned(self, table: pa.Table) -> None:
        # split by distinct partition-column combos; data columns drop the
        # partition columns exactly like Hive-style layout expects
        part_cols = [table.column(c) for c in self.partition_by]
        data_table = table.drop_columns(self.partition_by)
        combos: Dict[tuple, List[int]] = {}
        py_cols = [c.to_pylist() for c in part_cols]
        for i in range(table.num_rows):
            key = tuple(col[i] for col in py_cols)
            combos.setdefault(key, []).append(i)
        for key, idxs in sorted(combos.items(),
                                key=lambda kv: tuple(map(repr, kv[0]))):
            sub = data_table.take(pa.array(idxs, type=pa.int64()))
            rel = "/".join(f"{c}={_escape_path_value(v)}"
                           for c, v in zip(self.partition_by, key))
            directory = os.path.join(self.base_path, rel)
            if rel not in self.stats.partition_paths:
                self.stats.partition_paths.append(rel)
            self._write_one(sub, directory)


# --------------------------------------------------------------------------
# Physical exec (DataWritingCommandExec / GpuInsertIntoHadoopFsRelation)
# --------------------------------------------------------------------------

class WriteFilesExec(PhysicalPlan):
    """Consumes the child's partitions, writes one-or-more files per task,
    returns aggregated stats.  Runs on the host side of the pipeline (the
    child plan ends with whatever transition the planner inserted)."""

    backend = "cpu"

    def __init__(self, child: PhysicalPlan, fmt: str, path: str,
                 partition_by: Sequence[str], options: Dict):
        super().__init__(child)
        self.fmt = fmt
        self.path = path
        self.partition_by = list(partition_by)
        self.options = options
        self.job_stats = WriteTaskStats()

    @property
    def output(self):
        return []

    def execute(self, pid: int, tctx: TaskContext):
        writer = TaskFileWriter(self.fmt, self.path, self.partition_by,
                                self.options, pid)
        for batch in self.children[0].execute(pid, tctx):
            if batch.num_rows_int:
                writer.write(device_to_arrow(batch))
        self.job_stats.merge(writer.stats)
        tctx.inc_metric("filesWritten", writer.stats.num_files)
        tctx.inc_metric("bytesWritten", writer.stats.num_bytes)
        return iter(())


def run_write_job(child: PhysicalPlan, fmt: str, path: str, mode: str,
                  partition_by: Sequence[str], options: Dict,
                  conf: Optional[RapidsConf] = None) -> WriteTaskStats:
    """Job orchestration incl. save-mode handling
    (GpuInsertIntoHadoopFsRelationCommand.scala:283 semantics)."""
    mode = (mode or "errorifexists").lower().replace("_", "")
    exists = os.path.exists(path) and bool(os.listdir(path)) \
        if os.path.isdir(path) else os.path.exists(path)
    if exists:
        if mode in ("error", "errorifexists"):
            raise FileExistsError(f"path {path} already exists")
        if mode == "ignore":
            return WriteTaskStats()
        if mode == "overwrite":
            shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    write_exec = WriteFilesExec(child, fmt, path, partition_by, options)
    write_exec.execute_all(conf)
    # job commit marker (Hadoop committer analog)
    with open(os.path.join(path, "_SUCCESS"), "w"):
        pass
    # serving-tier invalidation contract (docs/serving.md): every write
    # through this path sweeps the cross-query result/broadcast caches —
    # a cached result over files this job just rewrote must never be
    # served again
    from ..serving import note_write
    note_write(path)
    return write_exec.job_stats
