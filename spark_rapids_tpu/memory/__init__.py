"""Memory & scheduling runtime — the TPU redesign of the reference's
RMM-centered heart (SURVEY §2.6): device pool accounting over PjRt buffers,
a DEVICE→HOST→DISK spill catalog (``RapidsBufferCatalog.scala:62`` /
``RapidsBuffer.scala:59-63``), the retry-on-OOM state machine
(``RmmRapidsRetryIterator.scala:33``), the device task semaphore
(``GpuSemaphore.scala:34``), and deduped task-completion callbacks
(``ScalableTaskCompletion.scala:43``).
"""

from .device import DeviceManager
from .retry import (OomInjectionState, RetryOOM, SplitAndRetryOOM,
                    arm_oom_injection, split_spillable_in_half, with_retry,
                    with_retry_no_split)
from .semaphore import TpuSemaphore
from .spill import (ACTIVE_BATCHING_PRIORITY, ACTIVE_ON_DECK_PRIORITY,
                    BufferCatalog, HOST_MEMORY_PRIORITY,
                    OUTPUT_FOR_SHUFFLE_PRIORITY, SpillableColumnarBatch,
                    batch_device_bytes)
from .completion import ScalableTaskCompletion

__all__ = [
    "DeviceManager", "TpuSemaphore", "BufferCatalog",
    "SpillableColumnarBatch", "batch_device_bytes",
    "RetryOOM", "SplitAndRetryOOM", "with_retry", "with_retry_no_split",
    "split_spillable_in_half", "arm_oom_injection", "OomInjectionState",
    "ScalableTaskCompletion",
    "ACTIVE_ON_DECK_PRIORITY", "ACTIVE_BATCHING_PRIORITY",
    "OUTPUT_FOR_SHUFFLE_PRIORITY", "HOST_MEMORY_PRIORITY",
]
