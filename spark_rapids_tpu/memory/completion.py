"""Deduped per-task completion callbacks — ``ScalableTaskCompletion.scala:43``
analog.  Operators register cleanup (close spillables, release the
semaphore) keyed by an owner object; re-registering the same owner for the
same task is a no-op, so iterator chains can defensively register without
stacking duplicate callbacks.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Tuple


class ScalableTaskCompletion:
    _instance = None
    _class_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        # task id -> list of (owner key, callback)
        self._callbacks: Dict[int, List[Tuple[int, Callable[[], None]]]] = {}

    @classmethod
    def get(cls) -> "ScalableTaskCompletion":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def on_task_completion(self, task_id: int, owner: Any,
                           cb: Callable[[], None]) -> bool:
        """Register ``cb`` to run when the task completes; deduped by
        ``owner`` identity.  Returns False when already registered."""
        key = id(owner)
        with self._lock:
            cbs = self._callbacks.setdefault(task_id, [])
            if any(k == key for k, _ in cbs):
                return False
            cbs.append((key, cb))
            return True

    def task_completed(self, task_id: int):
        with self._lock:
            cbs = self._callbacks.pop(task_id, [])
        errors = []
        for _, cb in cbs:
            try:
                cb()
            except Exception as e:  # run all callbacks even if one fails
                errors.append(e)
        if errors:
            raise errors[0]

    def pending(self, task_id: int) -> int:
        with self._lock:
            return len(self._callbacks.get(task_id, ()))
