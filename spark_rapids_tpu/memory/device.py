"""Device manager — selects the chip and sizes the buffer pool, the analog
of ``GpuDeviceManager.scala:150,275``.  Where the reference creates an RMM
pool of ``allocFraction × free-memory`` minus a reserve, the TPU runtime has
no user-managed allocator: XLA/PjRt owns HBM.  What we manage is the
*accounted* pool: every live ``ColumnarBatch`` registered with the
:class:`~spark_rapids_tpu.memory.spill.BufferCatalog` counts against the pool
limit computed here, and crossing it triggers synchronous spill — the same
contract ``DeviceMemoryEventHandler.scala:37`` provides via RMM callbacks.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..config import ALLOC_FRACTION, RESERVE_BYTES, RapidsConf

#: fallback HBM size when the backend reports no memory stats (CPU tests)
_DEFAULT_HBM_BYTES = 16 << 30


class DeviceManager:
    _instance: Optional["DeviceManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[RapidsConf] = None,
                 pool_limit_override: Optional[int] = None):
        conf = conf or RapidsConf.get_global()
        self.alloc_fraction = float(conf.get(ALLOC_FRACTION))
        self.reserve_bytes = int(conf.get(RESERVE_BYTES))
        self._pool_limit_override = pool_limit_override
        self._device = None
        self._hbm_bytes: Optional[int] = None

    # --- singleton --------------------------------------------------------
    @classmethod
    def initialize(cls, conf: Optional[RapidsConf] = None,
                   pool_limit_override: Optional[int] = None
                   ) -> "DeviceManager":
        with cls._lock:
            cls._instance = cls(conf, pool_limit_override)
            return cls._instance

    @classmethod
    def get(cls) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def shutdown(cls):
        with cls._lock:
            cls._instance = None

    # --- device info -------------------------------------------------------
    @property
    def device(self):
        if self._device is None:
            import jax
            self._device = jax.local_devices()[0]
        return self._device

    def hbm_bytes(self) -> int:
        if self._hbm_bytes is None:
            stats = None
            try:
                stats = self.device.memory_stats()
            except Exception:
                stats = None
            if stats and stats.get("bytes_limit"):
                self._hbm_bytes = int(stats["bytes_limit"])
            else:
                self._hbm_bytes = _DEFAULT_HBM_BYTES
        return self._hbm_bytes

    def pool_limit_bytes(self) -> int:
        if self._pool_limit_override is not None:
            return self._pool_limit_override
        limit = int(self.hbm_bytes() * self.alloc_fraction) - self.reserve_bytes
        return max(limit, 1 << 20)

    def bytes_in_use(self) -> int:
        try:
            stats = self.device.memory_stats()
            if stats and stats.get("bytes_in_use") is not None:
                return int(stats["bytes_in_use"])
        except Exception:
            pass
        return 0
