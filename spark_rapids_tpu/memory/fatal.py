"""Fatal device-error handling — the ``GpuCoreDumpHandler`` /
fatal-``CudaFatalException`` analog (reference ``Plugin.scala:515-539``:
a fatal CUDA error makes the executor capture a GPU core dump
(``GpuCoreDumpHandler.scala:57+``), log nvidia-smi state, and
self-terminate with exit code 20 so Spark reschedules the work on a
healthy executor; non-fatal errors stay task-local).

TPU analog: a runtime ``XlaRuntimeError`` that is NOT a memory condition
means the device/tunnel is in an unknown state.  The guard captures a
diagnostics bundle (exception, backend/device info, spill-catalog state,
live config) to ``spark.rapids.tpu.fatalDump.path`` and raises
:class:`FatalDeviceError`; with ``spark.rapids.tpu.fatalErrorExit`` the
process self-terminates with exit code 20 like the reference executor
(off by default — this engine usually runs in the user's process)."""

from __future__ import annotations

import os
import time
import traceback
from typing import Optional

#: reference exit code for fatal device errors (Plugin.scala:515-539)
FATAL_EXIT_CODE = 20

#: observability for tests
STATS = {"fatal_errors": 0, "dumps_written": 0}


class FatalDeviceError(RuntimeError):
    """The device runtime failed outside the OOM protocol; computation
    state is unknown and the query must not be retried in-process."""

    def __init__(self, message: str, dump_path: Optional[str] = None):
        super().__init__(message)
        self.dump_path = dump_path


def is_fatal_device_error(exc: BaseException) -> bool:
    """XlaRuntimeError that is NOT a memory condition (those go through
    the spill/retry protocol in oom_guard).  Chaos-injected faults are
    never fatal: the device did not actually fail, so the fatal handler
    must not dump diagnostics or (with fatalErrorExit) kill the process
    over a synthetic error."""
    from ..robustness.faults import InjectedFault
    if isinstance(exc, InjectedFault):
        return False
    from .oom_guard import is_device_oom
    name = type(exc).__name__
    if "XlaRuntimeError" not in name:
        return False
    return not is_device_oom(exc)


def _diagnostics(exc: BaseException) -> str:
    lines = [f"fatal device error at {time.strftime('%Y-%m-%dT%H:%M:%S')}",
             "", "exception:",
             "".join(traceback.format_exception(exc)).rstrip(), ""]
    # identity stamps: WHICH tenant/session/query hit the fatal — the
    # quarantine protocol (serving/lifecycle.py) fails only that query,
    # so the post-mortem must not have to guess whose plan it was
    try:
        from ..serving import lifecycle as _lc
        from ..sql.physical.base import TaskContext
        t = TaskContext.current()
        q = _lc.current()
        lines.append(
            "query identity: "
            f"tenant={((q.tenant if q else '') or (t.tenant if t else '')) or '(none)'} "
            f"session={(q.session_id if q else '') or '(none)'} "
            f"query={(q.query_id if q else 0) or '(none)'} "
            f"partition={t.partition_id if t else '(none)'}")
        if q is not None and q.cancelled:
            lines.append(f"query was cancelled: {q.reason}")
    except Exception:
        pass
    # the last bottleneck-doctor verdict recorded in this process: what
    # the engine believed it was bound on right before the device died
    try:
        from ..observability import doctor as _doc
        lv = getattr(_doc, "LAST_VERDICT", None)
        if lv:
            lines.append(
                f"last doctor verdict: {lv.get('verdict')} "
                f"(age {time.monotonic() - lv.get('at', 0.0):.1f}s)")
    except Exception:
        pass
    lines.append("")
    try:
        import jax
        lines.append(f"jax {jax.__version__}, backend "
                     f"{jax.default_backend()}")
        for d in jax.devices():
            lines.append(f"  device: {d}")
            stats = getattr(d, "memory_stats", lambda: None)()
            if stats:
                lines.append(f"    memory_stats: {stats}")
    except Exception as e:  # the backend may be the thing that died
        lines.append(f"(device enumeration failed: {type(e).__name__}: {e})")
    try:
        from .spill import BufferCatalog
        cat = BufferCatalog.get()
        lines.append(f"spill catalog: device={cat.device_bytes}B "
                     f"host={cat.host_bytes}B spills={cat.spill_count} "
                     f"unspills={cat.unspill_count}")
    except Exception:
        pass
    return "\n".join(lines) + "\n"


def handle_fatal(exc: BaseException, conf=None) -> "FatalDeviceError":
    """Capture diagnostics and build the FatalDeviceError to raise; exits
    the process instead when fatalErrorExit is set (reference executor
    behavior)."""
    from ..config import (FATAL_DUMP_PATH, FATAL_ERROR_EXIT, RapidsConf)
    conf = conf or RapidsConf.get_global()
    STATS["fatal_errors"] += 1
    dump_path = None
    target = str(conf.get(FATAL_DUMP_PATH) or "")
    if target:
        try:
            os.makedirs(target, exist_ok=True)
            import tempfile
            fd, dump_path = tempfile.mkstemp(
                prefix=f"fatal-{int(time.time())}-{os.getpid()}-",
                suffix=".txt", dir=target)
            with os.fdopen(fd, "w") as fh:
                fh.write(_diagnostics(exc))
            STATS["dumps_written"] += 1
        except OSError:
            dump_path = None
    err = FatalDeviceError(
        f"fatal device error (diagnostics: {dump_path or 'not captured'})"
        f": {type(exc).__name__}: {exc}", dump_path)
    if bool(conf.get(FATAL_ERROR_EXIT)):
        # the reference executor exits so the scheduler replaces it
        import sys
        sys.stderr.write(str(err) + "\n")
        sys.stderr.flush()
        os._exit(FATAL_EXIT_CODE)
    return err
