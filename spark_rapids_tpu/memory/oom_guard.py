"""Real-allocator hookup — the ``DeviceMemoryEventHandler.scala:37``
analog.  XLA owns HBM, so there is no RMM callback to install; instead
every compiled kernel invocation runs under this guard: a runtime
RESOURCE_EXHAUSTED from the device triggers a synchronous spill of the
catalog's device buffers and ONE retry; a second failure surfaces as
``SplitAndRetryOOM`` so the retry framework can halve the operator's
spillable inputs (``RmmRapidsRetryIterator`` contract).
"""

from __future__ import annotations

from typing import Callable

#: observability for tests/metrics
STATS = {"oom_caught": 0, "oom_retry_ok": 0, "oom_split_raised": 0,
         "eager_syncs": 0, "lazy_dispatches": 0}

#: wall-clock until which the guard stays in eager-sync mode after a real
#: device OOM (a sick device earns per-kernel supervision for a while)
_defensive_until = 0.0
_DEFENSIVE_WINDOW_S = 300.0


def _should_sync() -> bool:
    """Decide whether to pay a blocking device sync after this kernel.

    On the TPU tunnel every ``block_until_ready`` is a full network round
    trip, and XLA pipelines async dispatches — so blocking after every
    kernel serializes the whole query on RTT.  ``syncMode=auto`` keeps the
    async pipeline when memory pressure is low and flips to per-kernel
    supervision when an OOM is plausible: accounted pool usage above the
    watermark, armed test injection, or a recent real OOM.  A deferred OOM
    surfaces at the next materialization point (the D2H transition or a
    host pull), where the producing kernel can no longer be re-run; the
    session's collect loop recovers with a WHOLE-QUERY retry — by then the
    guard is in its defensive window, so the re-run syncs eagerly and any
    recurring OOM lands inside the failing kernel's own spill-and-retry.
    """
    import time

    from ..config import OOM_SYNC_MODE, OOM_SYNC_WATERMARK, RapidsConf
    conf = RapidsConf.get_global()
    mode = str(conf.get(OOM_SYNC_MODE)).lower()
    if mode == "always":
        return True
    if mode == "never":
        return False
    # auto:
    if time.monotonic() < _defensive_until:
        return True
    from .retry import injection_state
    st = injection_state()
    if st.retry_ooms or st.split_ooms:
        return True
    try:
        from .device import DeviceManager
        from .spill import BufferCatalog
        cat = BufferCatalog.get()
        limit = DeviceManager.get().pool_limit_bytes()
        if limit > 0 and cat.device_bytes >= limit * float(
                conf.get(OOM_SYNC_WATERMARK)):
            return True
    except Exception:  # pragma: no cover — accounting must never kill a query
        return True
    return False


def is_device_oom(exc: BaseException) -> bool:
    """Heuristic match of PjRt/XLA allocation failures (the error type
    lives in jaxlib and its message carries RESOURCE_EXHAUSTED / OOM)."""
    name = type(exc).__name__
    msg = str(exc)
    if name == "XlaRuntimeError" or "XlaRuntimeError" in name:
        return ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                or "out of memory" in msg or "OOM" in msg)
    return False


def guard_device_oom(fn: Callable, retriable: bool = True) -> Callable:
    """Wrap a compiled kernel: on device OOM, spill-all + retry once, then
    escalate to SplitAndRetryOOM (input halving).

    ``retriable=False`` is the donated-buffer contract (whole-stage
    donation, docs/whole_stage.md): a call whose inputs were donated to
    XLA cannot be re-run with the same arguments — the donor buffers are
    already invalid — so the guard spills and escalates immediately; the
    session's whole-query retry loop re-materializes the inputs."""

    def _sync(result, force: bool = False):
        # jit dispatch is ASYNC: an execution-time OOM surfaces when the
        # result is consumed, which would be outside this guard — force
        # materialization so the failure lands in our try block.  Under
        # low memory pressure (syncMode=auto) the sync is skipped so the
        # dispatch pipeline stays async over the tunnel; a deferred OOM is
        # caught at the next materialization point and flips the guard
        # into a defensive eager window.
        if not force and not _should_sync():
            STATS["lazy_dispatches"] += 1
            return result
        STATS["eager_syncs"] += 1
        try:
            import jax
            return jax.block_until_ready(result)
        except ImportError:  # pragma: no cover
            return result

    def wrapped(*args, **kwargs):
        try:
            return _sync(fn(*args, **kwargs))
        except Exception as e:  # noqa: BLE001 — filtered below
            if not is_device_oom(e):
                from .fatal import handle_fatal, is_fatal_device_error
                if is_fatal_device_error(e):
                    # device/tunnel state unknown: capture diagnostics,
                    # don't enter the spill/retry protocol
                    from ..sql.physical.base import TaskContext
                    task = TaskContext.current()
                    raise handle_fatal(
                        e, conf=task.conf if task else None) from e
                raise
            STATS["oom_caught"] += 1
            global _defensive_until
            import time as _time
            _defensive_until = _time.monotonic() + _DEFENSIVE_WINDOW_S
            from .spill import BufferCatalog
            BufferCatalog.get().spill_all_device()
            if not retriable:
                # donated inputs are gone; escalate without a same-args
                # retry (the whole-query retry re-plans and re-runs)
                STATS["oom_split_raised"] += 1
                from .retry import SplitAndRetryOOM
                raise SplitAndRetryOOM(
                    f"device OOM in a donated-buffer program (inputs "
                    f"invalidated, same-args retry impossible): {e}"
                ) from None
            try:
                result = _sync(fn(*args, **kwargs), force=True)
            except Exception as e2:  # noqa: BLE001
                if is_device_oom(e2):
                    STATS["oom_split_raised"] += 1
                    from .retry import SplitAndRetryOOM
                    raise SplitAndRetryOOM(
                        f"device OOM persisted after spilling all "
                        f"buffers: {e2}") from None
                # the retry itself may hit a WEDGED device (the exact
                # scenario fatal handling exists for)
                from .fatal import handle_fatal, is_fatal_device_error
                if is_fatal_device_error(e2):
                    from ..sql.physical.base import TaskContext
                    task = TaskContext.current()
                    raise handle_fatal(
                        e2, conf=task.conf if task else None) from e2
                raise
            STATS["oom_retry_ok"] += 1
            return result

    wrapped.__name__ = getattr(fn, "__name__", "kernel")
    return wrapped
