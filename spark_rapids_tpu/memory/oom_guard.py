"""Real-allocator hookup — the ``DeviceMemoryEventHandler.scala:37``
analog.  XLA owns HBM, so there is no RMM callback to install; instead
every compiled kernel invocation runs under this guard: a runtime
RESOURCE_EXHAUSTED from the device triggers a synchronous spill of the
catalog's device buffers and ONE retry; a second failure surfaces as
``SplitAndRetryOOM`` so the retry framework can halve the operator's
spillable inputs (``RmmRapidsRetryIterator`` contract).
"""

from __future__ import annotations

from typing import Callable

#: observability for tests/metrics
STATS = {"oom_caught": 0, "oom_retry_ok": 0, "oom_split_raised": 0}


def is_device_oom(exc: BaseException) -> bool:
    """Heuristic match of PjRt/XLA allocation failures (the error type
    lives in jaxlib and its message carries RESOURCE_EXHAUSTED / OOM)."""
    name = type(exc).__name__
    msg = str(exc)
    if name == "XlaRuntimeError" or "XlaRuntimeError" in name:
        return ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                or "out of memory" in msg or "OOM" in msg)
    return False


def guard_device_oom(fn: Callable) -> Callable:
    """Wrap a compiled kernel: on device OOM, spill-all + retry once, then
    escalate to SplitAndRetryOOM (input halving)."""

    def _sync(result):
        # jit dispatch is ASYNC: an execution-time OOM surfaces when the
        # result is consumed, which would be outside this guard — force
        # materialization so the failure lands in our try block
        try:
            import jax
            return jax.block_until_ready(result)
        except ImportError:  # pragma: no cover
            return result

    def wrapped(*args, **kwargs):
        try:
            return _sync(fn(*args, **kwargs))
        except Exception as e:  # noqa: BLE001 — filtered below
            if not is_device_oom(e):
                from .fatal import handle_fatal, is_fatal_device_error
                if is_fatal_device_error(e):
                    # device/tunnel state unknown: capture diagnostics,
                    # don't enter the spill/retry protocol
                    from ..sql.physical.base import TaskContext
                    task = TaskContext.current()
                    raise handle_fatal(
                        e, conf=task.conf if task else None) from e
                raise
            STATS["oom_caught"] += 1
            from .spill import BufferCatalog
            BufferCatalog.get().spill_all_device()
            try:
                result = _sync(fn(*args, **kwargs))
            except Exception as e2:  # noqa: BLE001
                if is_device_oom(e2):
                    STATS["oom_split_raised"] += 1
                    from .retry import SplitAndRetryOOM
                    raise SplitAndRetryOOM(
                        f"device OOM persisted after spilling all "
                        f"buffers: {e2}") from None
                # the retry itself may hit a WEDGED device (the exact
                # scenario fatal handling exists for)
                from .fatal import handle_fatal, is_fatal_device_error
                if is_fatal_device_error(e2):
                    from ..sql.physical.base import TaskContext
                    task = TaskContext.current()
                    raise handle_fatal(
                        e2, conf=task.conf if task else None) from e2
                raise
            STATS["oom_retry_ok"] += 1
            return result

    wrapped.__name__ = getattr(fn, "__name__", "kernel")
    return wrapped
