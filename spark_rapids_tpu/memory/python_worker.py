"""Python-worker semaphore — caps how many user-Python evaluations (pandas
UDFs, applyInPandas groups, mapInPandas iterators) run concurrently, the
``PythonWorkerSemaphore`` analog
(``com/nvidia/spark/rapids/python/PythonWorkerSemaphore.scala``; cap conf
``spark.rapids.python.concurrentPythonWorkers``).

The reference throttles GPU-sharing PySpark worker *processes*; here the
python execs release the DEVICE semaphore while user code runs
(``python_execs._semaphore_released``), so this semaphore bounds the other
resource those sections consume: host memory held by concurrent
pandas/Arrow materializations.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from ..config import CONCURRENT_PYTHON_WORKERS, RapidsConf

#: observability for tests
STATS = {"acquires": 0, "peak": 0, "current": 0}
_stats_lock = threading.Lock()


class PythonWorkerSemaphore:
    _instance: Optional["PythonWorkerSemaphore"] = None
    _class_lock = threading.Lock()

    def __init__(self, permits: int):
        self.permits = max(1, int(permits))
        self._sem = threading.BoundedSemaphore(self.permits)

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None
            ) -> "PythonWorkerSemaphore":
        conf = conf or RapidsConf.get_global()
        with cls._class_lock:
            want = int(conf.get(CONCURRENT_PYTHON_WORKERS))
            if cls._instance is None or cls._instance.permits != max(1, want):
                cls._instance = cls(want)
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._class_lock:
            cls._instance = None

    @contextmanager
    def running_python(self):
        self._sem.acquire()
        with _stats_lock:
            STATS["acquires"] += 1
            STATS["current"] += 1
            STATS["peak"] = max(STATS["peak"], STATS["current"])
        try:
            yield
        finally:
            with _stats_lock:
                STATS["current"] -= 1
            self._sem.release()
