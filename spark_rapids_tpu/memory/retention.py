"""Batch retention registry — the donation-safety contract for whole-stage
XLA programs (docs/whole_stage.md).

A fused stage may hand its input batch to XLA with ``donate_argnums`` so
the output reuses the input's HBM instead of allocating fresh buffers.
Donation invalidates the donor arrays, so it is ONLY sound when the stage
is the batch's sole owner.  This module tracks the two facts that decide
that:

* **pins** — a refcount per batch object, taken by every subsystem that
  RETAINS batches beyond a single producer->consumer handoff: the scan
  upload cache (basic.py ``_cached_upload``), broadcast exchanges,
  materialized shuffle partitions, the spill catalog, async prefetch
  queues while a batch is enqueued, and the double-buffer transfer stager
  while a transfer is in flight.  A pinned batch is never donated.
* **transient marks** — an opt-in marker set by producers whose outputs
  are freshly computed, single-owner device buffers (range generation,
  host->device uploads, multi-batch concats, join gathers, fused-stage
  outputs).  Unmarked batches are declined: a batch of unknown provenance
  may share leaf arrays with a retained batch (column-level aliasing that
  object-identity pins cannot see — e.g. a rename wrapper over a cached
  upload), so the safe default is "not donatable".

Both checks are conservative: a false pin or a missing mark only costs a
skipped donation, never correctness.  Encoded columns are declined
structurally — their dictionaries are shared ACROSS batches by design
(docs/encoded_columns.md), so donating one batch's pytree would free a
dictionary other batches still reference.

Pins key on ``id(batch)``; a pinner holds a strong reference for the
pin's lifetime, so the id cannot be recycled while the pin is live.  A
weakref reaper drops stale entries when a pinned batch is garbage
collected without an explicit unpin (e.g. spill-catalog registrants whose
handle outlives the batch object).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Tuple

from ..observability import metrics as _om

#: observability for tests (test_whole_stage.py donation-safety suite)
STATS = {"pins": 0, "unpins": 0, "donated": 0, "declined_pinned": 0,
         "declined_not_transient": 0, "declined_encoded": 0}

_LOCK = threading.Lock()
_PINS: Dict[int, int] = {}          # id(batch) -> refcount
_REAPERS: Dict[int, Any] = {}       # id(batch) -> weakref (GC cleanup)


def _drop(bid: int) -> None:
    with _LOCK:
        _PINS.pop(bid, None)
        _REAPERS.pop(bid, None)


def pin_batch(batch) -> None:
    """Record that ``batch`` is retained by a subsystem (see module doc).
    Idempotent per retainer via refcounting; pair with :func:`unpin_batch`
    at release, or rely on the GC reaper for retainers whose release point
    is the batch's own death."""
    if batch is None:
        return
    with _LOCK:
        bid = id(batch)
        _PINS[bid] = _PINS.get(bid, 0) + 1
        STATS["pins"] += 1
        if bid not in _REAPERS:
            try:
                _REAPERS[bid] = weakref.ref(
                    batch, lambda _r, bid=bid: _drop(bid))
            except TypeError:  # non-weakrefable carrier: entry stays until
                pass           # explicitly unpinned (conservative)


def unpin_batch(batch) -> None:
    if batch is None:
        return
    with _LOCK:
        bid = id(batch)
        n = _PINS.get(bid)
        if n is None:
            return
        STATS["unpins"] += 1
        if n <= 1:
            _PINS.pop(bid, None)
            _REAPERS.pop(bid, None)
        else:
            _PINS[bid] = n - 1


def is_pinned(batch) -> bool:
    with _LOCK:
        return _PINS.get(id(batch), 0) > 0


def pinned_count() -> int:
    with _LOCK:
        return len(_PINS)


# --------------------------------------------------------------------------
# transient provenance marks
# --------------------------------------------------------------------------

def mark_transient(batch):
    """Mark ``batch`` as freshly computed and single-owner (set only at
    producer sites whose output buffers cannot alias retained batches).
    Returns the batch for chaining."""
    try:
        batch._srt_transient = True
    except AttributeError:  # pragma: no cover - frozen/odd carriers
        pass
    return batch


def is_transient(batch) -> bool:
    return bool(getattr(batch, "_srt_transient", False))


# --------------------------------------------------------------------------
# the donation verdict
# --------------------------------------------------------------------------

def _has_encoded_columns(batch) -> bool:
    from ..columnar.encoded import DictEncodedColumn, RLEColumn
    return any(isinstance(c, (DictEncodedColumn, RLEColumn))
               for c in getattr(batch, "columns", ()))


def may_donate(batch) -> Tuple[bool, str]:
    """(ok, decline_reason) — whether a fused stage may donate ``batch``'s
    buffers to its compiled program.  Reasons: ``not_transient`` (unknown
    provenance), ``pinned`` (retained by the upload cache / broadcast /
    materialized shuffle / spill tier / prefetch queue / transfer stager),
    ``encoded`` (dictionary buffers are shared across batches)."""
    if not is_transient(batch):
        STATS["declined_not_transient"] += 1
        _om.inc("donation_declined_total", reason="not_transient")
        return False, "not_transient"
    if is_pinned(batch):
        STATS["declined_pinned"] += 1
        _om.inc("donation_declined_total", reason="pinned")
        return False, "pinned"
    if _has_encoded_columns(batch):
        STATS["declined_encoded"] += 1
        _om.inc("donation_declined_total", reason="encoded")
        return False, "encoded"
    return True, ""


def count_donated() -> None:
    STATS["donated"] += 1
    _om.inc("donation_granted_total")


def stats_snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(STATS)
