"""Retry-on-OOM framework — the TPU port of ``RmmRapidsRetryIterator.scala``
(`:33,341,410,484,514`): device work is expressed as attempts over spillable
inputs; an attempt that raises :class:`RetryOOM` is re-run after a
synchronous spill, and one that raises :class:`SplitAndRetryOOM` has its
input split in half and each half retried (`:371,439`).  Synthetic OOM
injection for tests mirrors ``spark.rapids.sql.test.injectRetryOOM``
(`RapidsConf.scala:1371`, throw site `RmmRapidsRetryIterator.scala:562`).

The seeded chaos registry (robustness/faults.py) folds these hooks into
its unified surface: arming the ``memory.oom.retry`` / ``memory.oom.split``
sites via ``spark.rapids.tpu.chaos.*`` injects the same RetryOOM /
SplitAndRetryOOM faults through the same recovery protocol, so one conf
controls every fault site in the engine.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

from ..robustness.faults import maybe_inject_oom
from .spill import BufferCatalog, SpillableColumnarBatch

A = TypeVar("A")
B = TypeVar("B")

_MAX_RETRIES = 32


class RetryOOM(MemoryError):
    """Device allocation failed; the attempt may succeed after a spill."""


class SplitAndRetryOOM(MemoryError):
    """Device allocation failed and spilling is not enough; the input must
    be split into smaller pieces."""


class OomInjectionState(threading.local):
    """Thread-local synthetic-OOM arming (conftest ``inject_oom`` marker
    analog)."""

    def __init__(self):
        self.retry_ooms = 0
        self.split_ooms = 0

    def arm(self, retry: int = 0, split: int = 0):
        self.retry_ooms = int(retry)
        self.split_ooms = int(split)

    def maybe_throw(self, splittable: bool = True):
        if self.retry_ooms > 0:
            self.retry_ooms -= 1
            raise RetryOOM("injected RetryOOM (test hook)")
        # split injections only land on sites that CAN split — a no-split
        # site receiving SplitAndRetryOOM is a task failure by contract,
        # and the reference's forceSplitAndRetryOOM likewise targets the
        # splittable retry iterators (RmmSpark test hooks)
        if self.split_ooms > 0 and splittable:
            self.split_ooms -= 1
            e = SplitAndRetryOOM("injected SplitAndRetryOOM (test hook)")
            e.injected = True
            raise e


_injection = OomInjectionState()


def arm_oom_injection(retry: int = 0, split: int = 0):
    """Arm synthetic OOMs for the current thread; next `retry` attempts
    throw RetryOOM and the following `split` attempts SplitAndRetryOOM."""
    _injection.arm(retry, split)


def injection_state() -> OomInjectionState:
    return _injection


def split_spillable_in_half(sb: SpillableColumnarBatch
                            ) -> List[SpillableColumnarBatch]:
    """Default split policy (``RmmRapidsRetryIterator.splitSpillableInHalfByRows``).
    Halves inherit the parent's catalog and spill priority."""
    batch = sb.get()
    n = batch.num_rows_int
    if n == 0:
        # a 0-row batch holds (near) nothing: splitting is impossible but
        # a retry is correct and bounded by the retry cap (degenerate
        # batches arise in anti-join / empty-partition pipelines; failing
        # the task here is useless).  Spill here — the SplitAndRetryOOM
        # branch of with_retry does not — so the retry actually runs
        # under relieved memory pressure.  The parent is RETURNED (not
        # closed): the n==0 case re-queues it instead of replacing it.
        sb.catalog.spill_all_device()
        return [sb]
    if n < 2:
        raise SplitAndRetryOOM(
            f"cannot split a {n}-row batch any further (GpuOOM)")
    half = n // 2
    left = batch.sliced(0, half)
    right = batch.sliced(half, n - half)
    out = [SpillableColumnarBatch.create(left, sb.priority, sb.catalog),
           SpillableColumnarBatch.create(right, sb.priority, sb.catalog)]
    sb.close()
    return out


def with_retry(inputs: Iterable[A], fn: Callable[[A], B],
               split: Optional[Callable[[A], List[A]]] = None,
               catalog: Optional[BufferCatalog] = None) -> Iterator[B]:
    """Run ``fn`` over each input with OOM rollback.  ``inputs`` should be
    spillable (typically :class:`SpillableColumnarBatch`) so that a spill
    between attempts actually frees device memory.  With a ``split`` policy,
    SplitAndRetryOOM replaces the failing input by its pieces; without one it
    propagates (``withRetryNoSplit`` semantics).  Takes ownership of the
    inputs: each is closed once its attempt succeeds, like the reference's
    AutoCloseable contract."""
    catalog = catalog or BufferCatalog.get()
    stack: List[A] = list(inputs)
    stack.reverse()
    item: Optional[A] = None

    def _close(x):
        if x is not None and hasattr(x, "close"):
            x.close()

    try:
        while stack:
            item = stack.pop()
            attempts = 0
            while True:
                attempts += 1
                if attempts > _MAX_RETRIES:
                    raise MemoryError(
                        f"giving up after {_MAX_RETRIES} OOM retries (GpuOOM)")
                try:
                    _injection.maybe_throw(splittable=split is not None)
                    # unified chaos surface: seeded OOM injection rides
                    # the exact same recovery path as the legacy hook
                    maybe_inject_oom(splittable=split is not None)
                    result = fn(item)
                    _close(item)
                    item = None
                    yield result
                    break
                except RetryOOM:
                    catalog.spill_all_device()
                except SplitAndRetryOOM as soom:
                    if split is None:
                        raise
                    # split closes the parent and returns its pieces —
                    # except the 0-row degenerate case, which re-queues
                    # the SAME (unclosed) input after spilling
                    try:
                        pieces = split(item)
                    except SplitAndRetryOOM:
                        # unsplittable input: a REAL device OOM here is a
                        # task failure by contract, but an INJECTED one
                        # degrades to spill+retry — the test hook must
                        # exercise recovery paths, not invent failures
                        # real memory pressure would not cause
                        if getattr(soom, "injected", False):
                            catalog.spill_all_device()
                            continue
                        raise
                    item = None
                    pieces.reverse()
                    stack.extend(pieces)
                    item = stack.pop()
    finally:
        # ownership contract: on any failure or abandoned generator, close
        # the in-flight item and everything still queued
        _close(item)
        for rest in stack:
            _close(rest)


def with_retry_no_split(item: A, fn: Callable[[A], B],
                        catalog: Optional[BufferCatalog] = None) -> B:
    """Single-input, no-split retry (``withRetryNoSplit`` `:484`)."""
    return next(iter(with_retry([item], fn, split=None, catalog=catalog)))
