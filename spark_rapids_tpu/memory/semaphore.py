"""Device task semaphore — limits how many tasks touch the chip at once,
the ``GpuSemaphore.scala:34-342`` analog.  On TPU the motivation is even
sharper than on GPU: one chip runs one XLA program at a time, so admitting
more tasks than ``spark.rapids.sql.concurrentGpuTasks`` only piles up HBM
working sets.  Tasks acquire before first device use and release around
host-side waits (IO, python) so CPU work overlaps device work.

Reentrant per task: nested acquires by the same task are deduped, matching
the reference's per-task tracking (`GpuSemaphore.scala:106`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..config import CONCURRENT_TASKS, RapidsConf
from ..observability import metrics as _om
from ..observability import tracer as _trace


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _class_lock = threading.Lock()

    def __init__(self, permits: int):
        self.permits = max(1, int(permits))
        self._sem = threading.Semaphore(self.permits)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._holders: Dict[int, int] = {}  # task id -> acquire depth
        self._acquiring: set = set()        # tasks mid-acquire (race guard)
        self.total_wait_s = 0.0

    # --- lifecycle ---------------------------------------------------------
    @classmethod
    def initialize(cls, conf: Optional[RapidsConf] = None,
                   permits: Optional[int] = None) -> "TpuSemaphore":
        conf = conf or RapidsConf.get_global()
        if permits is None:
            permits = int(conf.get(CONCURRENT_TASKS))
        with cls._class_lock:
            cls._instance = cls(permits)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = cls(int(RapidsConf.get_global()
                                        .get(CONCURRENT_TASKS)))
            return cls._instance

    @classmethod
    def shutdown(cls):
        with cls._class_lock:
            cls._instance = None

    # --- acquire/release ---------------------------------------------------
    def acquire_if_necessary(self, task_id: int, tctx=None):
        from ..serving import lifecycle as _lc
        # lifecycle poll site `sem_wait`: polled BEFORE the first acquire
        # attempt (so a cancel landing pre-wait is honored even when the
        # permit is free) and between 50ms acquire polls while blocked —
        # a cancelled task leaves the wait within one poll interval
        # holding nothing; the raise below the _acquiring guard is safe
        # (the finally clears the guard and notifies)
        _lc.check_cancel("sem_wait")
        with self._lock:
            # wait out another thread of the SAME task that is mid-acquire,
            # so one task never takes two permits
            while task_id in self._acquiring:
                self._cond.wait()
            if task_id in self._holders:
                self._holders[task_id] += 1
                return
            self._acquiring.add(task_id)
        t0 = time.perf_counter()
        acquired = False
        try:
            while not self._sem.acquire(timeout=_lc.POLL_S):
                _lc.check_cancel("sem_wait")
            acquired = True
        finally:
            waited = time.perf_counter() - t0
            with self._lock:
                self._acquiring.discard(task_id)
                if acquired:
                    self._holders[task_id] = 1
                self.total_wait_s += waited
                self._cond.notify_all()
        if tctx is not None:
            tctx.inc_metric("semaphoreWaitTime", waited)
        if waited > 1e-6 and _trace.TRACING["on"]:
            _trace.get_tracer().complete("sem_wait", "semaphore.acquire",
                                         t0, waited, task=task_id)
        _om.observe("sem_wait_ms", waited * 1e3)

    def release_if_necessary(self, task_id: int):
        with self._lock:
            depth = self._holders.get(task_id)
            if depth is None:
                return
            if depth > 1:
                self._holders[task_id] = depth - 1
                return
            del self._holders[task_id]
        self._sem.release()

    def holds(self, task_id: int) -> bool:
        with self._lock:
            return task_id in self._holders

    def active_tasks(self) -> int:
        with self._lock:
            return len(self._holders)

    class _Scoped:
        def __init__(self, sem: "TpuSemaphore", task_id: int, tctx):
            self.sem, self.task_id, self.tctx = sem, task_id, tctx

        def __enter__(self):
            self.sem.acquire_if_necessary(self.task_id, self.tctx)
            return self

        def __exit__(self, *exc):
            self.sem.release_if_necessary(self.task_id)

    def scoped(self, task_id: int, tctx=None) -> "_Scoped":
        return TpuSemaphore._Scoped(self, task_id, tctx)
