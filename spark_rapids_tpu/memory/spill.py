"""Spill framework — DEVICE→HOST→DISK tiers behind one catalog, the TPU
equivalent of ``RapidsBufferCatalog.scala:62`` + the three stores
(``RapidsDeviceMemoryStore``/``RapidsHostMemoryStore``/``RapidsDiskStore``)
and ``SpillableColumnarBatch.scala:29``.

A registered batch lives in exactly one tier:

* DEVICE — the live jax arrays (accounted against the DeviceManager pool);
* HOST   — numpy copies (accounted against the host spill budget,
  ``spark.rapids.memory.host.spillStorageSize``);
* DISK   — one pickle file per buffer under ``spark.rapids.memory.spillDir``.

``synchronous_spill`` walks buffers lowest-priority-first (the
``SpillPriorities.scala`` contract) device→host, overflowing host→disk when
the host budget is exceeded.  ``get`` transparently unspills
(``RapidsBufferCatalog.unspill`` `:633`).  Everything is thread-safe: the
multithreaded shuffle and IO pools touch the catalog concurrently.
"""

from __future__ import annotations

import errno
import os
import pickle
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..config import HOST_SPILL_STORAGE_SIZE, SPILL_DIR, RapidsConf
from ..observability import metrics as _om
from ..observability import tracer as _trace
from ..robustness import faults as _faults
from .device import DeviceManager

#: bounded retries for the disk tier's reads/writes: a transiently torn
#: spill I/O (or a chaos-injected one) re-attempts with a short backoff
#: instead of failing the query; a persistent error still raises
_DISK_IO_ATTEMPTS = 5


class SpillDiskFull(OSError):
    """ENOSPC from the spill disk tier — NON-retriable: a full disk does
    not heal on a millisecond backoff, and retrying five times just
    multiplies the latency of the inevitable.  The overflow path catches
    this and keeps the buffer RESIDENT at host (over-limit but correct)
    instead of failing the query; ``spill_disk_full_total`` counts the
    events."""


def _retry_disk_io(fn, what: str):
    from ..serving import lifecycle as _lc
    delay = 0.001
    for attempt in range(_DISK_IO_ATTEMPTS):
        # lifecycle poll site `spill`: a cancelled query abandons its
        # disk-tier I/O (and the retry backoff) instead of finishing a
        # spill nobody will read
        _lc.check_cancel("spill")
        try:
            return fn()
        except OSError as e:
            if getattr(e, "errno", None) == errno.ENOSPC:
                _om.inc("spill_disk_full_total")
                raise SpillDiskFull(
                    errno.ENOSPC,
                    f"spill disk full during {what}") from e
            if attempt == _DISK_IO_ATTEMPTS - 1:
                raise
            _lc.cancellable_sleep(delay, "spill")
            delay *= 2

# spill order: lower value spills first (SpillPriorities.scala:83 semantics,
# inverted to "priority = keep-on-device desire")
OUTPUT_FOR_SHUFFLE_PRIORITY = -100
HOST_MEMORY_PRIORITY = -50
ACTIVE_BATCHING_PRIORITY = 0
ACTIVE_ON_DECK_PRIORITY = 100

DEVICE, HOST, DISK = "device", "host", "disk"


def batch_device_bytes(batch: ColumnarBatch) -> int:
    """Accounted size: sum of leaf array nbytes."""
    import jax
    total = 0
    from ..shims import tree_flatten
    for leaf in tree_flatten(batch)[0]:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


@dataclass
class _Buffer:
    handle: int
    tier: str
    size: int
    priority: int
    treedef: Any = None
    leaves: Optional[List[Any]] = None     # device or host arrays
    disk_path: Optional[str] = None
    was_device: bool = True                # False for host-backend batches
    seq: int = 0                           # tie-break: older spills first
    origin: str = ""                       # registration site (debug mode)
    tenant: str = ""                       # registering task's tenant


class BufferCatalog:
    """Handle registry + tiered stores + spill policy (singleton per
    process, like the reference's ``RapidsBufferCatalog.singleton``)."""

    _instance: Optional["BufferCatalog"] = None
    _class_lock = threading.Lock()

    def __init__(self, conf: Optional[RapidsConf] = None):
        conf = conf or RapidsConf.get_global()
        self._lock = threading.RLock()
        self._buffers: Dict[int, _Buffer] = {}
        self._next_handle = 1
        self._seq = 0
        self.host_limit = int(conf.get(HOST_SPILL_STORAGE_SIZE))
        self.spill_dir = str(conf.get(SPILL_DIR))
        self.device_bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        self.spill_count = 0
        self.unspill_count = 0
        from ..config import GPU_DEBUG
        self.debug = bool(conf.get(GPU_DEBUG))
        #: tenant -> device-byte budget for tenant-aware spill ordering
        #: (set by ServingEngine from the admission budgets; 0/absent =
        #: unbudgeted).  Over-budget tenants' buffers spill FIRST.
        self._tenant_budgets: Dict[str, int] = {}
        self._tenant_default_budget = 0

    @classmethod
    def get(cls) -> "BufferCatalog":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _debug_enabled(self) -> bool:
        """Live flag: the running task's session conf wins (sessions
        don't mutate the process-global conf — test isolation depends on
        that), else whatever the catalog was constructed with."""
        if self.debug:
            return True
        from ..sql.physical.base import TaskContext
        t = TaskContext.current()
        if t is None:
            return False
        from ..config import GPU_DEBUG
        return bool(t.conf.get(GPU_DEBUG))

    @classmethod
    def reset(cls, conf: Optional[RapidsConf] = None) -> "BufferCatalog":
        with cls._class_lock:
            if cls._instance is not None:
                cls._instance.close_all()
            cls._instance = cls(conf)
            return cls._instance

    def leak_report(self):
        """Still-registered buffers — the MemoryCleaner leak-tracking
        analog (reference Plugin.scala:425-440): after a query finishes
        every SpillableColumnarBatch must have been closed, so anything
        listed here is a leaked handle.  Entries carry the registration
        site when spark.rapids.memory.gpu.debug is on."""
        with self._lock:
            return [{"handle": b.handle, "size": b.size, "tier": b.tier,
                     "origin": b.origin or "(enable "
                     "spark.rapids.memory.gpu.debug for call sites)"}
                    for b in self._buffers.values()]

    # --- registration ------------------------------------------------------
    def add_batch(self, batch: ColumnarBatch,
                  priority: int = ACTIVE_BATCHING_PRIORITY) -> int:
        """Register a batch.  Device-resident batches are charged against the
        accounted pool (spilling others first if needed); host-backend
        (numpy-leaf) batches start at the HOST tier and never count as HBM."""
        import jax
        from ..shims import tree_flatten
        # spill-tier retention pin (donation-safety, memory/retention.py):
        # the registrant's batch shares leaves with the catalog record, so
        # a fused stage must never donate it while registered.  The pin
        # lifts via the registry's GC reaper when the batch object dies.
        from . import retention as _ret
        _ret.pin_batch(batch)
        leaves, treedef = tree_flatten(batch)
        was_device = any(isinstance(l, jax.Array) for l in leaves)
        size = batch_device_bytes(batch)
        if was_device and not self.ensure_headroom(size,
                                                   already_resident=True):
            # even after spilling everything else the batch cannot fit the
            # pool — escalate so the retry framework halves the input
            # (RmmRapidsRetryIterator/GpuOOM contract, VERDICT r1 weak #10:
            # the headroom verdict must not be ignored)
            from .retry import SplitAndRetryOOM
            raise SplitAndRetryOOM(
                f"batch of {size} bytes cannot fit the device pool "
                f"(limit {DeviceManager.get().pool_limit_bytes()})")
        origin = ""
        debug = self._debug_enabled()
        if debug:
            import traceback
            for frame in reversed(traceback.extract_stack(limit=8)):
                if "memory/spill.py" not in frame.filename:
                    origin = (f"{frame.filename}:{frame.lineno} "
                              f"{frame.name}")
                    break
        # tenant plumbed from the running task (TaskContext.tenant) so
        # the spill policy can evict the over-budget tenant's batches
        # first (docs/serving.md "pressure-aware degradation")
        from ..sql.physical.base import TaskContext
        _t = TaskContext.current()
        tenant = _t.tenant if _t is not None else ""
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._seq += 1
            tier = DEVICE if was_device else HOST
            self._buffers[h] = _Buffer(h, tier, size, priority, treedef,
                                       list(leaves), was_device=was_device,
                                       seq=self._seq, origin=origin,
                                       tenant=tenant)
            if was_device:
                self.device_bytes += size
            else:
                self.host_bytes += size
        if debug:
            import logging
            logging.getLogger("spark_rapids_tpu.memory").info(
                "buffer +%d %dB tier=%s at %s", h, size, tier, origin)
        return h

    def get_batch(self, handle: int) -> ColumnarBatch:
        """Materialize on the original backend, unspilling if needed."""
        import jax
        with self._lock:
            buf = self._buffers[handle]
            if buf.tier == DISK:
                self._disk_to_host(buf)
            if buf.tier == HOST and buf.was_device:
                self._host_to_device(buf)
            leaves = buf.leaves
            treedef = buf.treedef
        from ..shims import tree_unflatten
        return tree_unflatten(treedef, leaves)

    def remove(self, handle: int):
        with self._lock:
            buf = self._buffers.pop(handle, None)
            if buf is None:
                return
            if buf.tier == DEVICE:
                self.device_bytes -= buf.size
            elif buf.tier == HOST:
                self.host_bytes -= buf.size
            elif buf.tier == DISK:
                self.disk_bytes -= buf.size
                if buf.disk_path and os.path.exists(buf.disk_path):
                    os.unlink(buf.disk_path)

        if self._debug_enabled():
            import logging
            logging.getLogger("spark_rapids_tpu.memory").info(
                "buffer -%d %dB tier=%s", handle, buf.size, buf.tier)

    def close_all(self):
        with self._lock:
            for h in list(self._buffers):
                self.remove(h)

    def tier_of(self, handle: int) -> str:
        with self._lock:
            return self._buffers[handle].tier

    # --- spill policy ------------------------------------------------------
    def set_tenant_budgets(self, budgets: Dict[str, int],
                           default_budget: int = 0) -> None:
        """Install per-tenant device-byte budgets for spill ordering
        (ServingEngine wires the admission budgets here).  Budgets only
        reorder eviction — they never block registration."""
        with self._lock:
            self._tenant_budgets = {k: int(v) for k, v in budgets.items()}
            self._tenant_default_budget = max(0, int(default_budget))

    def _over_budget_tenants(self) -> set:
        """Tenants whose DEVICE-tier registered bytes exceed their budget
        (callers hold the lock).  O(buffers) — spill decisions are rare
        next to the D2H work they trigger."""
        if not self._tenant_budgets and self._tenant_default_budget <= 0:
            return set()
        usage: Dict[str, int] = {}
        for b in self._buffers.values():
            if b.tier == DEVICE and b.tenant:
                usage[b.tenant] = usage.get(b.tenant, 0) + b.size
        over = set()
        for t, used in usage.items():
            budget = int(self._tenant_budgets.get(
                t, self._tenant_default_budget))
            if budget > 0 and used > budget:
                over.add(t)
        return over

    def synchronous_spill(self, target_device_bytes: int) -> int:
        """Spill device buffers until accounted device usage <= target.
        Eviction order is ``(tenant_over_budget, priority, seq)``: an
        over-budget tenant's batches spill FIRST (tenant-aware pressure
        response, docs/serving.md), then lowest priority, oldest first
        (the ``RapidsBufferCatalog.synchronousSpill`` `:589` contract)."""
        spilled = 0
        with self._lock:
            over = self._over_budget_tenants()
            candidates = sorted(
                (b for b in self._buffers.values() if b.tier == DEVICE),
                key=lambda b: (0 if b.tenant in over else 1,
                               b.priority, b.seq))
            for buf in candidates:
                if self.device_bytes <= target_device_bytes:
                    break
                self._device_to_host(buf)
                spilled += buf.size
                self.spill_count += 1
        return spilled

    def ensure_headroom(self, request_bytes: int,
                        already_resident: bool = False) -> bool:
        """Make room for an incoming allocation; the DeviceMemoryEventHandler
        equivalent.  Returns True if the request now fits the pool.

        Pressure is judged on BOTH the accounted registered bytes and the
        backend's actual ``bytes_in_use`` (live kernel intermediates the
        bookkeeping cannot see), so a real chip near HBM exhaustion spills
        even when the catalog's own ledger looks comfortable.
        ``already_resident``: the requested bytes are ALREADY on device
        (add_batch registering a computed batch) — real usage must not
        count them twice."""
        dm = DeviceManager.get()
        limit = dm.pool_limit_bytes()

        def used_now():
            real = dm.bytes_in_use()
            if not already_resident:
                real += request_bytes
            return max(self.device_bytes + request_bytes, real)

        with self._lock:
            if used_now() <= limit:
                return True
            self.synchronous_spill(max(0, limit - request_bytes))
            return used_now() <= limit

    def spill_all_device(self) -> int:
        return self.synchronous_spill(0)

    # --- tier movement (callers hold the lock) -----------------------------
    def _device_to_host(self, buf: _Buffer):
        import jax
        # one concurrent D2H for all leaves (per-array pulls each cost a
        # full tunnel round trip)
        with _trace.span("spill", "spill.deviceToHost", bytes=buf.size):
            buf.leaves = list(jax.device_get(buf.leaves))
        _om.inc("spill_bytes_total", buf.size, dir="deviceToHost")
        buf.tier = HOST
        self.device_bytes -= buf.size
        self.host_bytes += buf.size
        if self.host_bytes > self.host_limit:
            self._overflow_host_to_disk()

    def _overflow_host_to_disk(self):
        candidates = sorted(
            (b for b in self._buffers.values() if b.tier == HOST),
            key=lambda b: (b.priority, b.seq))
        for buf in candidates:
            if self.host_bytes <= self.host_limit:
                break
            try:
                self._host_to_disk(buf)
            except SpillDiskFull:
                # disk-full fallback: keep this (and the remaining
                # lowest-priority) buffers resident at host — the tier
                # runs over its limit, loudly, rather than failing the
                # query on an unwritable spill
                break

    def _host_to_disk(self, buf: _Buffer):
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, f"buf-{uuid.uuid4().hex}.spill")

        def _write():
            # the chaos site sits inside the retried closure so every
            # attempt re-draws its own seeded decision
            _faults.maybe_inject("spill.disk_write", exc=OSError,
                                 bytes=buf.size)
            with open(path, "wb") as f:
                pickle.dump(buf.leaves, f, protocol=pickle.HIGHEST_PROTOCOL)
        with _trace.span("spill", "spill.hostToDisk", bytes=buf.size):
            try:
                _retry_disk_io(_write, "spill.disk_write")
            except SpillDiskFull:
                try:
                    os.unlink(path)   # a partial file must not leak
                except OSError:
                    pass
                raise
        _om.inc("spill_bytes_total", buf.size, dir="hostToDisk")
        buf.leaves = None
        buf.disk_path = path
        buf.tier = DISK
        self.host_bytes -= buf.size
        self.disk_bytes += buf.size

    def _disk_to_host(self, buf: _Buffer):
        def _read():
            _faults.maybe_inject("spill.disk_read", exc=OSError,
                                 bytes=buf.size)
            with open(buf.disk_path, "rb") as f:
                return pickle.load(f)
        with _trace.span("spill", "spill.diskToHost", bytes=buf.size):
            buf.leaves = _retry_disk_io(_read, "spill.disk_read")
        _om.inc("spill_bytes_total", buf.size, dir="diskToHost")
        os.unlink(buf.disk_path)
        buf.disk_path = None
        buf.tier = HOST
        self.disk_bytes -= buf.size
        self.host_bytes += buf.size
        self.unspill_count += 1

    def _host_to_device(self, buf: _Buffer):
        import jax
        # a False verdict here is deliberately tolerated (transient
        # oversubscription): the split path itself must materialize a
        # too-big parent to slice it, so raising would deadlock recovery —
        # a real allocation failure during unspill is caught by the
        # kernel-level oom_guard on the next device op instead
        self.ensure_headroom(buf.size)
        with _trace.span("spill", "spill.unspillToDevice", bytes=buf.size):
            buf.leaves = [jax.device_put(l) if isinstance(l, np.ndarray)
                          else l for l in buf.leaves]
        _om.inc("spill_bytes_total", buf.size, dir="unspillToDevice")
        buf.tier = DEVICE
        self.host_bytes -= buf.size
        self.device_bytes += buf.size
        self.unspill_count += 1


class SpillableColumnarBatch:
    """Owns a batch registered with the catalog; the working-set currency of
    out-of-core operators (``SpillableColumnarBatch.scala:29,192``).  While
    an operator isn't actively computing on a batch it holds one of these,
    so the catalog may demote it under memory pressure."""

    def __init__(self, handle: int, num_rows: Optional[int], size: int,
                 catalog: BufferCatalog,
                 priority: int = ACTIVE_BATCHING_PRIORITY):
        self._handle: Optional[int] = handle
        self._num_rows = num_rows
        self.size_bytes = size
        self.priority = priority
        self._catalog = catalog

    @property
    def num_rows(self) -> int:
        """Host row count, pulled LAZILY: registering a batch whose count
        only exists on the device must not cost a tunnel round trip unless
        someone actually needs the number."""
        if self._num_rows is None:
            self._num_rows = self.get().num_rows_int
        return self._num_rows

    @staticmethod
    def create(batch: ColumnarBatch,
               priority: int = ACTIVE_BATCHING_PRIORITY,
               catalog: Optional[BufferCatalog] = None
               ) -> "SpillableColumnarBatch":
        catalog = catalog or BufferCatalog.get()
        size = batch_device_bytes(batch)
        h = catalog.add_batch(batch, priority)
        return SpillableColumnarBatch(h, getattr(batch, "_nrows_host", None),
                                      size, catalog, priority)

    @property
    def catalog(self) -> BufferCatalog:
        return self._catalog

    def get(self) -> ColumnarBatch:
        if self._handle is None:
            raise ValueError("SpillableColumnarBatch already closed")
        return self._catalog.get_batch(self._handle)

    def get_and_close(self) -> ColumnarBatch:
        b = self.get()
        self.close()
        return b

    def close(self):
        if self._handle is not None:
            self._catalog.remove(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
