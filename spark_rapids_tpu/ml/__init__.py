"""ML handoff — the ``ColumnarRdd`` / ``InternalColumnarRddConverter``
analog (reference ``ColumnarRdd.scala``, ``README.md:48-56``,
``org/apache/spark/sql/rapids/execution/InternalColumnarRddConverter.scala:611``;
BASELINE milestone 5 "accelerated XGBoost handoff").

The reference exports a query's GPU columnar batches to ML frameworks
without bouncing through rows.  Here the analog is stronger: engine
batches already ARE jax device arrays, so the handoff is zero-copy by
construction — a query's output flows straight into jax/flax/optax
training without leaving the device.

* :func:`columnar_rdd` — per-partition device ``ColumnarBatch`` list, the
  raw export (GpuBringBackToHost never inserted).
* :func:`to_features` — (X, y) dense jax matrices for model training:
  live rows only, features column-stacked, one configurable dtype.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..columnar.batch import ColumnarBatch


def columnar_rdd(df) -> List[ColumnarBatch]:
    """Execute ``df`` and return its DEVICE batches (one per partition,
    jax-array columns, padded layout preserved).  The planner runs the
    normal placement pipeline but skips the final DeviceToHost transition
    (``GpuBringBackToHost`` analog stays out of the plan)."""
    from ..sql.planner import Planner
    from ..sql.physical.base import TPU

    session = df._session
    planner = Planner(session._conf)
    phys = planner.plan(df._plan)
    if phys.backend != TPU:
        raise ValueError(
            "columnar_rdd requires the query to end on the device; the "
            f"plan ends on {phys.backend} — check session.explain(df)")
    from ..sql.physical.base import collect_metrics
    batches = [b for b in phys.execute_all(session._conf)
               if b.num_rows_int > 0]
    session.last_query_metrics = collect_metrics(phys)
    return batches


def to_features(df, feature_cols: Sequence[str],
                label_col: Optional[str] = None, dtype=None
                ) -> Tuple:
    """Dense (X, y) jax arrays from a query's device output: X is
    ``[n_rows, n_features]``, y is ``[n_rows]`` (None when no label
    column is named).  Rows are compacted (padding stripped); features
    cast to ``dtype`` (default float32, the TPU-native width)."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    cols = list(feature_cols) + ([label_col] if label_col else [])
    batches = columnar_rdd(df.select(*cols))
    if not batches:
        empty = jnp.zeros((0, len(feature_cols)), dtype=dtype)
        return empty, (jnp.zeros((0,), dtype=dtype) if label_col else None)
    xs, ys = [], []
    for b in batches:
        n = b.num_rows_int
        name_to_col = dict(zip(b.names, b.columns))

        def dense(name):
            col = name_to_col[name]
            if col.data is None or col.data.ndim != 1:
                raise ValueError(f"column {name!r} is not numeric")
            if col.validity is not None and not bool(
                    col.validity[:n].all()):
                # silent 0.0-for-NULL would corrupt training data
                raise ValueError(
                    f"column {name!r} contains NULLs — filter or fill "
                    "them in the query before the handoff")
            return col.data[:n].astype(dtype)

        xs.append(jnp.stack([dense(c) for c in feature_cols], axis=1))
        if label_col:
            ys.append(dense(label_col))
    X = jnp.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
    y = None
    if label_col:
        y = jnp.concatenate(ys, axis=0) if len(ys) > 1 else ys[0]
    return X, y
