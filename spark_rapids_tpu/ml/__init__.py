"""ML handoff — the ``ColumnarRdd`` / ``InternalColumnarRddConverter``
analog (reference ``ColumnarRdd.scala``, ``README.md:48-56``,
``org/apache/spark/sql/rapids/execution/InternalColumnarRddConverter.scala:611``;
BASELINE milestone 5 "accelerated XGBoost handoff").

The reference exports a query's GPU columnar batches to ML frameworks
without bouncing through rows.  Here the analog is stronger: engine
batches already ARE jax device arrays, so the handoff is zero-copy by
construction — a query's output flows straight into jax/flax/optax
training without leaving the device.

* :func:`columnar_rdd` — per-partition device ``ColumnarBatch`` list, the
  raw export (GpuBringBackToHost never inserted).
* :func:`to_features` — (X, y) dense jax matrices for model training:
  live rows only, features column-stacked, one configurable dtype.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..columnar.batch import ColumnarBatch


def columnar_rdd(df) -> List[ColumnarBatch]:
    """Execute ``df`` and return its DEVICE batches (one per partition,
    jax-array columns, padded layout preserved).  The planner runs the
    normal placement pipeline but skips the final DeviceToHost transition
    (``GpuBringBackToHost`` analog stays out of the plan)."""
    from ..sql.planner import Planner
    from ..sql.physical.base import TPU

    session = df._session
    planner = Planner(session._conf)
    phys = planner.plan(df._plan)
    if phys.backend != TPU:
        raise ValueError(
            "columnar_rdd requires the query to end on the device; the "
            f"plan ends on {phys.backend} — check session.explain(df)")
    from ..sql.physical.base import collect_metrics
    batches = [b for b in phys.execute_all(session._conf)
               if b.num_rows_int > 0]
    session.last_query_metrics = collect_metrics(phys)
    return batches


def to_features(df, feature_cols: Sequence[str],
                label_col: Optional[str] = None, dtype=None
                ) -> Tuple:
    """Dense (X, y) jax arrays from a query's device output: X is
    ``[n_rows, n_features]``, y is ``[n_rows]`` (None when no label
    column is named).  Rows are compacted (padding stripped); features
    cast to ``dtype`` (default float32, the TPU-native width)."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    cols = list(feature_cols) + ([label_col] if label_col else [])
    batches = columnar_rdd(df.select(*cols))
    if not batches:
        empty = jnp.zeros((0, len(feature_cols)), dtype=dtype)
        return empty, (jnp.zeros((0,), dtype=dtype) if label_col else None)
    xs, ys = [], []
    for b in batches:
        n = b.num_rows_int
        name_to_col = dict(zip(b.names, b.columns))

        def dense(name):
            col = name_to_col[name]
            if col.data is None or col.data.ndim != 1:
                raise ValueError(f"column {name!r} is not numeric")
            if col.validity is not None and not bool(
                    col.validity[:n].all()):
                # silent 0.0-for-NULL would corrupt training data
                raise ValueError(
                    f"column {name!r} contains NULLs — filter or fill "
                    "them in the query before the handoff")
            return col.data[:n].astype(dtype)

        xs.append(jnp.stack([dense(c) for c in feature_cols], axis=1))
        if label_col:
            ys.append(dense(label_col))
    X = jnp.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
    y = None
    if label_col:
        y = jnp.concatenate(ys, axis=0) if len(ys) > 1 else ys[0]
    return X, y


def to_torch(df, feature_cols: Sequence[str],
             label_col: Optional[str] = None) -> Tuple:
    """(X, y) as torch tensors — the XGBoost-style host-framework handoff
    (the reference's ColumnarRdd feeds XGBoost4J; torch stands in as the
    resident host-ML framework in this image).  The device→host move is
    one packed transfer (bulk_device_get) and the torch tensors wrap the
    fetched numpy buffers zero-copy."""
    import numpy as np
    import torch

    from ..columnar.convert import bulk_device_get
    X, y = to_features(df, feature_cols, label_col)
    host = bulk_device_get({"X": X, "y": y})
    tx = torch.from_numpy(np.ascontiguousarray(host["X"]))
    ty = (torch.from_numpy(np.ascontiguousarray(host["y"]))
          if host["y"] is not None else None)
    return tx, ty


def minibatches(df, feature_cols: Sequence[str], label_col: str,
                batch_size: int, *, epochs: int = 1, seed: int = 0,
                drop_remainder: bool = True):
    """Device-resident minibatch iterator over a query's output: the ETL
    stays in the engine, training data never leaves HBM, and each epoch
    reshuffles with a deterministic key — the idiomatic jax input
    pipeline over SQL results."""
    import jax
    import jax.numpy as jnp

    X, y = to_features(df, feature_cols, label_col)
    n = X.shape[0]
    if n == 0:
        return
    key = jax.random.PRNGKey(seed)
    for _ in range(epochs):
        key, sub = jax.random.split(key)
        perm = jax.random.permutation(sub, n)
        Xp, yp = X[perm], y[perm]
        end = n - (n % batch_size) if drop_remainder else n
        for off in range(0, end, batch_size):
            yield Xp[off:off + batch_size], yp[off:off + batch_size]


def to_features_sharded(df, feature_cols: Sequence[str],
                        label_col: Optional[str] = None, *, mesh=None,
                        dtype=None) -> Tuple:
    """Multi-chip ``to_features``: (X, y) laid out as row-sharded
    ``jax.Array``s over a device mesh (axis "data"), so a pjit/shard_map
    training step consumes the query output with NO host gather and NO
    resharding — the ETL→training handoff at the scale the reference's
    ColumnarRdd feeds distributed XGBoost (BASELINE config 5).

    Rows are zero-padded up to a device-count multiple (returned
    ``n_rows`` gives the live count; padded labels are 0 and padded
    features 0 — mask with ``jnp.arange(X.shape[0]) < n_rows`` in the
    loss).  Returns (X, y, n_rows)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import device_mesh

    X, y = to_features(df, feature_cols, label_col, dtype=dtype)
    mesh = mesh or device_mesh()
    if mesh is None:
        return X, y, X.shape[0]
    n_dev = mesh.devices.size
    n = X.shape[0]
    pad = (-n) % n_dev
    if pad:
        X = jnp.concatenate(
            [X, jnp.zeros((pad, X.shape[1]), dtype=X.dtype)])
        if y is not None:
            y = jnp.concatenate([y, jnp.zeros((pad,), dtype=y.dtype)])
    axis = mesh.axis_names[0]
    xsh = NamedSharding(mesh, PartitionSpec(axis, None))
    ysh = NamedSharding(mesh, PartitionSpec(axis))
    X = jax.device_put(X, xsh)
    if y is not None:
        y = jax.device_put(y, ysh)
    return X, y, n


def fit_gradient_boosting(df, feature_cols: Sequence[str], label_col: str,
                          *, n_trees: int = 30, max_depth: int = 4,
                          lr: float = 0.3, n_bins: int = 16):
    """Gradient-boosted regression trees trained ON DEVICE over the
    query's output — the engine-native answer to BASELINE config 5's
    "accelerated XGBoost handoff" (reference: ColumnarRdd feeding
    XGBoost4J-Gpu).

    TPU-first design: OBLIVIOUS trees (CatBoost-style symmetric trees —
    every level applies ONE (feature, threshold) split to all nodes), so
    the model is dense tensors and both training and inference are pure
    vectorized ops with STATIC shapes:

    * candidate thresholds are per-feature quantile bins (computed once);
    * a level's split search scores every (feature, bin) candidate at
      once — one vmapped segment-sum of residuals over proposed leaf
      ids, gain = sum over leaves of (Σr)²/count (variance reduction);
    * leaf assignment is D comparisons + bit packing; no data-dependent
      Python control flow reaches the jitted path.

    Returns (predict_fn, model, final_mse): ``predict_fn(X)`` is
    jittable; ``model`` holds (features[T,D], thresholds[T,D],
    leaf_values[T, 2^D], base)."""
    import jax
    import jax.numpy as jnp

    X, y = to_features(df, feature_cols, label_col)
    n = X.shape[0]
    if n == 0:
        raise ValueError("cannot fit on an empty query result")
    n_leaves = 1 << max_depth

    # per-feature candidate thresholds: quantile bins over the data
    qs = jnp.linspace(0.0, 1.0, n_bins + 2)[1:-1]
    thresholds = jnp.quantile(X, qs, axis=0).T          # [d, n_bins]

    def level_scores(Xa, thra, resid, leaf_ids, level):
        """Gain for every (feature, bin) candidate at one level."""
        def one_feature(xcol, thrs):
            def one_thr(t):
                new = leaf_ids * 2 + (xcol > t).astype(jnp.int32)
                seg = 2 << level
                s = jax.ops.segment_sum(resid, new, num_segments=seg)
                c = jax.ops.segment_sum(jnp.ones_like(resid), new,
                                        num_segments=seg)
                return jnp.sum(s * s / jnp.maximum(c, 1.0))
            return jax.vmap(one_thr)(thrs)
        return jax.vmap(one_feature, in_axes=(1, 0))(Xa, thra)

    # X/thresholds are jit ARGUMENTS, not closure captures: capturing
    # would bake the dataset into the executable as a constant (compile
    # time and HBM scale with the data, doubling residency)
    @jax.jit
    def build_tree(resid, Xa, thra):
        leaf_ids = jnp.zeros(n, dtype=jnp.int32)
        feats = jnp.zeros(max_depth, dtype=jnp.int32)
        thrs = jnp.zeros(max_depth, dtype=Xa.dtype)
        for level in range(max_depth):      # static unroll: D is small
            scores = level_scores(Xa, thra, resid, leaf_ids, level)
            flat = jnp.argmax(scores)
            f, b = flat // n_bins, flat % n_bins
            t = thra[f, b]
            feats = feats.at[level].set(f.astype(jnp.int32))
            thrs = thrs.at[level].set(t)
            leaf_ids = leaf_ids * 2 + (Xa[:, f] > t).astype(jnp.int32)
        s = jax.ops.segment_sum(resid, leaf_ids, num_segments=n_leaves)
        c = jax.ops.segment_sum(jnp.ones_like(resid), leaf_ids,
                                num_segments=n_leaves)
        values = lr * s / jnp.maximum(c, 1.0)
        return feats, thrs, values, values[leaf_ids]

    base = jnp.mean(y)
    pred = jnp.full(n, base, dtype=X.dtype)
    all_f, all_t, all_v = [], [], []
    for _ in range(n_trees):
        feats, thrs, values, delta = build_tree(y - pred, X, thresholds)
        pred = pred + delta
        all_f.append(feats)
        all_t.append(thrs)
        all_v.append(values)
    model = (jnp.stack(all_f), jnp.stack(all_t), jnp.stack(all_v), base)

    def predict_fn(Xq, model=model):
        feats, thrs, values, base_ = model
        def one_tree(f, t, v):
            bits = (Xq[:, f] > t[None, :]).astype(jnp.int32)  # [n, D]
            weights = 2 ** jnp.arange(f.shape[0] - 1, -1, -1)
            idx = jnp.sum(bits * weights[None, :], axis=1)
            return v[idx]
        per_tree = jax.vmap(one_tree)(feats, thrs, values)   # [T, n]
        return base_ + jnp.sum(per_tree, axis=0)

    mse = float(jnp.mean((predict_fn(X) - y) ** 2))
    return jax.jit(predict_fn), model, mse


def fit_linear_regression(df, feature_cols: Sequence[str], label_col: str,
                          *, steps: int = 200, lr: float = 0.1,
                          l2: float = 0.0):
    """End-to-end SQL→ML demonstration (BASELINE milestone 5's
    "accelerated XGBoost handoff" spirit): least-squares fit by jitted
    full-batch gradient descent over the query's DEVICE output — the ETL
    result is consumed by an optax-style training loop without ever
    leaving the accelerator.  Returns (weights, bias, final_mse)."""
    import jax
    import jax.numpy as jnp

    X, y = to_features(df, feature_cols, label_col)
    n, d = X.shape
    if n == 0:
        raise ValueError("cannot fit on an empty query result")
    # standardize for a well-conditioned fixed learning rate
    mu, sd = X.mean(axis=0), X.std(axis=0) + 1e-12
    Xs = (X - mu) / sd

    def loss(params):
        w, b = params
        pred = Xs @ w + b
        return jnp.mean((pred - y) ** 2) + l2 * jnp.sum(w * w)

    @jax.jit
    def step(params):
        g = jax.grad(loss)(params)
        from ..shims import tree_map
        return tree_map(lambda p, gg: p - lr * gg, params, g)

    params = (jnp.zeros(d, X.dtype), jnp.asarray(0.0, X.dtype))
    for _ in range(steps):
        params = step(params)
    w_s, b_s = params
    # un-standardize back to input space
    w = w_s / sd
    b = b_s - jnp.sum(w_s * mu / sd)
    mse = float(jnp.mean((X @ w + b - y) ** 2))
    return w, b, mse
