"""ctypes binding to the native host-kernel library (``native/``), the
JNI-layer analog of the reference (SURVEY §2.10).  The library is built
lazily with g++ on first use and cached next to the sources; every entry
point has a pure-Python fallback so the framework still runs where no
toolchain exists (callers check ``available()``)."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from ._loader import find_or_build
        so = find_or_build("libsrt_native.so", "srt_native.cpp")
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        _register(lib)
        _lib = lib
        return _lib


#: (name, restype, argtypes) for every exported symbol
_SYMBOLS = [
    ("srt_pack_strings", None,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
      ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]),
    ("srt_unpack_strings", ctypes.c_int64,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
      ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]),
    ("srt_byte_array_walk", ctypes.c_int64,
     [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
      ctypes.c_void_p, ctypes.c_void_p]),
    ("srt_murmur3_i32", None,
     [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
      ctypes.c_void_p]),
    ("srt_murmur3_i64", None,
     [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
      ctypes.c_void_p]),
    ("srt_murmur3_bytes", ctypes.c_int32,
     [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32]),
    ("srt_xxhash64_bytes", ctypes.c_uint64,
     [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]),
]


def _register(lib: ctypes.CDLL) -> None:
    """Declare symbol signatures PER SYMBOL: a stale prebuilt .so missing
    only newer symbols keeps its older fast paths; wrappers for absent
    symbols degrade to pure Python via :func:`_sym`."""
    for name, restype, argtypes in _SYMBOLS:
        try:
            fn = getattr(lib, name)
        except AttributeError:
            continue
        if restype is not None:
            fn.restype = restype
        fn.argtypes = argtypes


def _sym(name: str):
    """The ctypes function for ``name``, or None when the lib or the
    symbol is unavailable (pure-Python fallback)."""
    lib = _load()
    if lib is None:
        return None
    try:
        return getattr(lib, name)
    except AttributeError:
        return None


def available() -> bool:
    return _load() is not None


def has(name: str) -> bool:
    """Whether a specific exported symbol is loadable (stale prebuilt
    libraries may lack newer symbols while keeping the rest)."""
    return _sym(name) is not None


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------

def pack_strings(flat: np.ndarray, offsets: np.ndarray, width: int,
                 capacity: int):
    """(matrix uint8[capacity, width], lens int32[capacity]) from
    concatenated bytes + int64 offsets[n+1]."""
    lib = _load()
    n = len(offsets) - 1
    if lib is None or n == 0:
        return None
    matrix = np.zeros((capacity, width), dtype=np.uint8)
    lens = np.zeros(capacity, dtype=np.int32)
    flat = np.ascontiguousarray(flat, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lib.srt_pack_strings(
        flat.ctypes.data, offsets.ctypes.data, n, width,
        matrix.ctypes.data, lens.ctypes.data)
    return matrix, lens


def byte_array_walk(data: np.ndarray, n: int):
    """(starts int64[n], lens int32[n]) for a PLAIN BYTE_ARRAY section
    (u32le length-prefixed values); None when the native lib is absent,
    raises ValueError on a truncated/overrunning section."""
    fn = _sym("srt_byte_array_walk")
    if fn is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    starts = np.empty(n, dtype=np.int64)
    lens = np.empty(n, dtype=np.int32)
    used = fn(data.ctypes.data, len(data), n,
              starts.ctypes.data, lens.ctypes.data)
    if used < 0:
        raise ValueError("truncated BYTE_ARRAY section")
    return starts, lens


def unpack_strings(matrix: np.ndarray, lens: np.ndarray, n: int):
    """(flat uint8, offsets int64[n+1]) from a padded byte matrix."""
    lib = _load()
    if lib is None:
        return None
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    lens32 = np.ascontiguousarray(lens[:n], dtype=np.int32)
    total = int(np.minimum(lens32, matrix.shape[1]).sum())
    flat = np.empty(total, dtype=np.uint8)
    offsets = np.empty(n + 1, dtype=np.int64)
    lib.srt_unpack_strings(matrix.ctypes.data, lens32.ctypes.data, n,
                           matrix.shape[1], flat.ctypes.data,
                           offsets.ctypes.data)
    return flat, offsets


def murmur3_i64(vals: np.ndarray, seed: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = np.empty(len(vals), dtype=np.int32)
    lib.srt_murmur3_i64(vals.ctypes.data, len(vals),
                        np.uint32(seed), out.ctypes.data)
    return out


def murmur3_i32(vals: np.ndarray, seed: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int32)
    out = np.empty(len(vals), dtype=np.int32)
    lib.srt_murmur3_i32(vals.ctypes.data, len(vals),
                        np.uint32(seed), out.ctypes.data)
    return out


def murmur3_bytes(data: bytes, seed: int) -> Optional[int]:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    return int(lib.srt_murmur3_bytes(
        buf.ctypes.data if len(buf) else None, len(buf), np.uint32(seed)))


def xxhash64_bytes(data, seed: int = 0) -> int:
    """Frame checksum; falls back to a pure-Python xxhash64 so the wire
    format is identical with or without the native library."""
    lib = _load()
    if lib is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        return int(lib.srt_xxhash64_bytes(
            buf.ctypes.data if len(buf) else None, len(buf),
            np.uint64(seed)))
    return _xxhash64_py(bytes(data), seed)


# --- pure-Python xxhash64 (fallback; identical output) ----------------------

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl(x, r):
    return ((x << r) | (x >> (64 - r))) & _M64


def _round(acc, inp):
    acc = (acc + inp * _P2) & _M64
    return (_rotl(acc, 31) * _P1) & _M64


def _merge(acc, val):
    acc ^= _round(0, val)
    return (acc * _P1 + _P4) & _M64


def _xxhash64_py(data: bytes, seed: int) -> int:
    import struct
    n = len(data)
    pos = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M64
        v2 = (seed + _P2) & _M64
        v3 = seed & _M64
        v4 = (seed - _P1) & _M64
        while pos + 32 <= n:
            a, b, c, d = struct.unpack_from("<QQQQ", data, pos)
            v1, v2 = _round(v1, a), _round(v2, b)
            v3, v4 = _round(v3, c), _round(v4, d)
            pos += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12)
             + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = _merge(h, v)
    else:
        h = (seed + _P5) & _M64
    h = (h + n) & _M64
    while pos + 8 <= n:
        (k,) = struct.unpack_from("<Q", data, pos)
        h = ((_rotl(h ^ _round(0, k), 27) * _P1) + _P4) & _M64
        pos += 8
    if pos + 4 <= n:
        (k,) = struct.unpack_from("<I", data, pos)
        h = ((_rotl(h ^ ((k * _P1) & _M64), 23) * _P2) + _P3) & _M64
        pos += 4
    while pos < n:
        h = (_rotl(h ^ ((data[pos] * _P5) & _M64), 11) * _P1) & _M64
        pos += 1
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h
