"""Locate-or-build logic for the native C++ host libraries.

Search order (reference analog: the JNI jar bundles prebuilt .so files,
``dist/README.md``; here the wheel stays pure-Python and ships the C++
sources, compiled on first use wherever a toolchain exists):

1. a prebuilt ``.so`` next to this package (installed-wheel layout, when a
   builder chose to ship binaries) or in the repo-root ``native/`` dir
   (development checkout layout);
2. failing that, the matching ``.cpp`` from either location, compiled with
   g++ into the first writable directory (next to the source, else
   ``~/.cache/spark_rapids_tpu/native``).

Every caller has a pure-Python fallback, so returning ``None`` degrades
features, never breaks them.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional


def _candidate_dirs() -> list:
    pkg = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.normpath(os.path.join(pkg, "..", "..", "native"))
    return [pkg, repo]


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "spark_rapids_tpu", "native")


def _src_tag(src: str) -> str:
    """Short content hash — compile outputs carry it in their filename so
    a library built from older sources can never shadow newer ones (the
    repo-root dir is exempt: its plain-named .so is Makefile-managed)."""
    import hashlib
    with open(src, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()[:10]


def find_or_build(libname: str, srcname: str,
                  extra_flags: tuple = ()) -> Optional[str]:
    """Path to a loadable shared library, building it if necessary."""
    pkg, repo = _candidate_dirs()
    repo_so = os.path.join(repo, libname)
    if os.path.exists(repo_so):
        return repo_so
    stem, ext = os.path.splitext(libname)
    for d in (pkg, repo):
        src = os.path.join(d, srcname)
        if not os.path.exists(src):
            continue
        tagged = f"{stem}-{_src_tag(src)}{ext}"
        for outdir in (d, _cache_dir()):
            so = os.path.join(outdir, tagged)
            if os.path.exists(so):
                return so
            try:
                os.makedirs(outdir, exist_ok=True)
            except OSError:
                continue
            try:
                subprocess.run(
                    ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                     *extra_flags, "-o", so, src],
                    check=True, capture_output=True, timeout=120)
                return so
            except Exception:
                continue
    return None
