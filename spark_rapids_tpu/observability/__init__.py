"""Query-timeline observability — structured tracer, Chrome-trace/JSONL
export, and per-query attribution reports.

The engine's perf story lives or dies on data-movement accounting (the
Theseus / "GPU-era analytical processing" argument): a rows/s number
without knowing how much wall time was blocked readbacks, kernel
trace+compile, or H2D/D2H bytes is not a diagnosis.  This package is the
TPU analog of the reference's SQL-UI GpuMetric plumbing + NVTX ranges +
Spark eventLog, recast as one in-process timeline:

* :mod:`.tracer` — thread-safe bounded ring buffer of span/counter
  events (categories ``op``/``kernel_compile``/``sync``/``h2d``/``d2h``/
  ``spill``/``shuffle``/``sem_wait``), near-zero overhead when disabled.
* :mod:`.export` — Chrome trace-event JSON (Perfetto-loadable) and an
  append-only JSONL event log per query (eventLog/history analog).
* :mod:`.report` — per-query attribution: blocking-readback count & ms
  per exec, kernel hit/miss & compile ms, bytes on the wire, spill and
  semaphore-wait time.
* :mod:`.metrics` — process-wide registry (counters / gauges /
  log-bucketed p50/p95/p99 histograms) fed by the tracer, shuffle,
  spill/retention and kernel-cache chokepoints; Prometheus + JSON export.
* :mod:`.history` — bounded query flight recorder (plan fingerprint,
  metrics, trace summary per query; in-memory ring + on-disk JSONL).
* :mod:`.doctor` — ranked bottleneck attribution (sync / compile /
  h2d-d2h / dispatch / sem_wait / spill / shuffle -bound verdicts with
  the exec-level spans and counters that justify them).
"""

from .metrics import METRICS, MetricsRegistry, get_registry
from .tracer import (TRACING, QueryTracer, current_exec, get_tracer,
                     pop_exec, push_exec, span)

__all__ = ["TRACING", "QueryTracer", "get_tracer", "span", "push_exec",
           "pop_exec", "current_exec", "METRICS", "MetricsRegistry",
           "get_registry"]
