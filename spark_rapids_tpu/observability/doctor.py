"""Automated bottleneck doctor — ranked, machine-readable attribution of
where a query's time went, from a tracer timeline + metrics snapshot.

The verdict taxonomy (docs/observability.md):

==================  ======================================================
``sync-bound``      blocking scalar readbacks (cat ``sync``) dominate —
                    each is a full host<->device round trip on the tunnel
``compile-bound``   kernel trace+compile (cat ``kernel_compile``) — cold
                    cache; warm reruns are the fix, not kernel work
``h2d-d2h-bound``   transfer spans (cats ``h2d``+``d2h``) — bytes crossing
                    the host link; prepack/resident tiers are the levers
``dispatch-bound``  many small compiled-program launches with little
                    attributed span time — per-op Python dispatch + launch
                    overhead; whole-stage fusion is the lever
``sem_wait-bound``  device-semaphore waits (cat ``sem_wait``) — tasks
                    contending for chip admission
``spill-bound``     spill tier movement (cat ``spill``)
``shuffle-bound``   exchange materialization + frame (de)serialization
                    (cat ``shuffle``) and queue waits (cat ``queue``)
==================  ======================================================

:func:`diagnose` consumes raw tracer events (best fidelity: exec-level
evidence spans ride each verdict); :func:`diagnose_summary` degrades to a
compact ``trace_summary`` (bench artifacts, replay captures).  Both emit
the same schema, validated by ``tools/check_trace.py --doctor``:

.. code-block:: json

   {"schema": "srt-doctor/1", "verdict": "sync-bound",
    "ranked": [{"category": "sync-bound", "ms": 120.3, "count": 18,
                "share": 0.61,
                "evidence": {"top_execs": [...], "counters": {...}}}],
    "wall_ms": 197.0, "attributed_ms": 151.2,
    "trace_truncated": false, "caveats": []}

CLI (CI runs this against the traced-query event log):

    python -m spark_rapids_tpu.observability.doctor <eventlog.jsonl>
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

SCHEMA = "srt-doctor/1"

#: most recent diagnose() verdict in this process ({"verdict", "at"}) —
#: stamped into fatal-device diagnostic dumps (memory/fatal.py) so a
#: quarantine event records what the engine was bound on pre-mortem
LAST_VERDICT: "Optional[Dict[str, Any]]" = None

#: tracer category -> verdict category
_CAT_TO_VERDICT = {
    "sync": "sync-bound",
    "kernel_compile": "compile-bound",
    "h2d": "h2d-d2h-bound",
    "d2h": "h2d-d2h-bound",
    "sem_wait": "sem_wait-bound",
    "spill": "spill-bound",
    "shuffle": "shuffle-bound",
    "queue": "shuffle-bound",
    "admission": "admission-bound",
}

VERDICTS = ("sync-bound", "compile-bound", "h2d-d2h-bound",
            "dispatch-bound", "sem_wait-bound", "spill-bound",
            "shuffle-bound", "admission-bound",
            # a tenant consuming its declared SLO error budget faster
            # than allotted (observability/slo.py names the tenant and
            # its dominant bottleneck in the entry's evidence)
            "slo-burn",
            # the query paid for the pod-scale fault domain: peers
            # declared dead, zombie responses fenced, failovers and
            # recomputes (shuffle/manager.py + robustness/
            # failure_detector.py quantify the evidence)
            "peer-failure")

#: verdict -> the remedial lever the follow-up names.  Every verdict
#: kind carries quantified lever evidence (``evidence.levers``) with the
#: same precision dispatch-bound always had, so the sentry's
#: machine-named follow-ups (observability/sentry.py) are actionable for
#: any bottleneck, not just launch counts.
LEVERS = {
    "sync-bound": "fuse/pipeline the blocking readbacks (async d2h)",
    "compile-bound": "warm the persistent kernel cache / shape buckets",
    "h2d-d2h-bound": "prepack + device-resident tier (cut wire bytes)",
    "dispatch-bound": "whole-stage fusion (cut launches per stage)",
    "sem_wait-bound": "raise semaphore permits or admission weights",
    "spill-bound": "raise the memory budget / spill tier sizing",
    "shuffle-bound": "device-resident shuffle tier / coalesced exchange",
    "admission-bound": "tenant weight, memory budget, "
                       "maxConcurrentQueries",
    "slo-burn": "rebalance the burning tenant's SLO budget or load",
    "peer-failure": "replace/restart the dead peer; tighten "
                    "peers.{suspectMs,deadMs} to detect sooner",
}


def _lever_evidence(entry: Dict[str, Any],
                    stages: int = 0) -> Dict[str, Any]:
    """Quantified lever numbers for one ranked entry, keyed by what the
    verdict's lever actually moves (readbacks for sync, launches for
    dispatch, bytes for transfer...).  Best-effort from the entry's
    existing evidence — degraded summaries simply carry fewer keys."""
    cat = entry["category"]
    ms, n = float(entry["ms"]), int(entry["count"])
    ev = entry.get("evidence") or {}
    lv: Dict[str, Any] = {}
    if cat == "sync-bound":
        lv["readbacks"] = n
        if stages and n:
            lv["readbacks_per_stage"] = round(n / stages, 2)
        if n:
            lv["ms_per_readback"] = round(ms / n, 3)
    elif cat == "compile-bound":
        lv["compiles"] = n
        if n:
            lv["ms_per_compile"] = round(ms / n, 3)
    elif cat == "h2d-d2h-bound":
        for k in ("h2d_bytes", "d2h_bytes", "bytes"):
            if ev.get(k):
                lv[k] = int(ev[k])
    elif cat == "dispatch-bound":
        lv["device_dispatches"] = int(ev.get("device_dispatches", n))
        if ev.get("launches_per_probe_batch") is not None:
            lv["launches_per_probe_batch"] = \
                ev["launches_per_probe_batch"]
    elif cat == "sem_wait-bound":
        lv["wait_ms"] = round(ms, 3)
        if n:
            lv["waits"] = n
    elif cat == "spill-bound":
        lv["spill_ms"] = round(ms, 3)
    elif cat == "shuffle-bound":
        lv["shuffle_ms"] = round(ms, 3)
        if ev.get("bytes"):
            lv["bytes_on_wire"] = int(ev["bytes"])
    elif cat == "admission-bound":
        lv["wait_ms"] = round(ms, 3)
        lv["waiters"] = n
    elif cat == "slo-burn":
        for k in ("tenant", "burn_rate", "window_s"):
            if ev.get(k) is not None:
                lv[k] = ev[k]
    elif cat == "peer-failure":
        for k in ("dead_peers", "stale_epochs", "dead_failovers",
                  "proactive_recomputes"):
            if ev.get(k) is not None:
                lv[k] = ev[k]
        lv["recovery_ms"] = round(ms, 3)
    top = None
    execs = ev.get("top_execs")
    if execs:
        top = execs[0].get("exec")
    if ev.get("top_exec"):
        top = ev["top_exec"]
    if top:
        lv["top_exec"] = top
    return lv


def _stamp_levers(ranked: List[Dict[str, Any]], stages: int = 0) -> None:
    """Stamp ``evidence.levers`` (quantified) + ``evidence.lever`` (the
    named remedy) onto every ranked entry — ISSUE 18: every verdict
    kind, not just dispatch-bound, must justify its follow-up with
    numbers.  Idempotent."""
    for e in ranked:
        ev = e.setdefault("evidence", {})
        ev["levers"] = _lever_evidence(e, stages)
        ev["lever"] = LEVERS.get(e["category"], "")

#: per-launch overhead floor used to estimate dispatch-bound time when
#: the trace cannot attribute it directly (Python dispatch + XLA launch;
#: on the real tunnel each uncovered launch can cost a full RTT, so this
#: deliberately UNDER-estimates — a dispatch-bound verdict from this
#: floor is conservative)
DEFAULT_DISPATCH_COST_MS = 0.05

#: launches below this count never yield a dispatch-bound verdict
DISPATCH_FLOOR = 32

#: kernel keys named in dispatch-bound evidence (top launch sources)
DISPATCH_TOP_K = 5


def _dispatch_evidence(dispatches: int,
                       metrics: Dict[str, Any],
                       dispatch_by_key: Optional[Dict[str, int]]
                       ) -> Dict[str, Any]:
    """Actionable dispatch-bound evidence: WHICH programs launch and HOW
    OFTEN per unit of work.  ``launches_per_probe_batch`` is the join
    perf-model number (ISSUE 14: one probe batch should cost ≤12
    launches end to end); ``top_kernels`` ranks the per-key launch
    counters (``dispatches{kernel}``) so the verdict names the
    originating exec instead of just a total — kernel labels are
    ``ExecName#hash``, so the heaviest key IS the exec to fuse."""
    ev: Dict[str, Any] = {"device_dispatches": dispatches}
    probes = int(metrics.get("joinFastpathProbes", 0)
                 + metrics.get("joinFallbackProbes", 0))
    if probes > 0:
        ev["probe_batches"] = probes
        ev["launches_per_probe_batch"] = round(
            dispatches / max(1, probes), 2)
    if dispatch_by_key is None:
        # in-process diagnosis: the kernel cache's per-key launch
        # counters are live and scoped to the last clear_cache()
        try:
            from ..sql.physical import kernel_cache as _kc
            dispatch_by_key = _kc.dispatch_stats_by_key()
        except Exception:  # pragma: no cover - import cycle safety
            dispatch_by_key = {}
    if dispatch_by_key:
        top = sorted(dispatch_by_key.items(), key=lambda kv: -kv[1])
        top = top[:DISPATCH_TOP_K]
        ev["top_kernels"] = [
            {"kernel": k, "launches": int(n)} for k, n in top]
        ev["top_exec"] = top[0][0].split("#", 1)[0]
    return ev


def _verdict_entry(category: str, ms: float, count: int,
                   evidence: Dict[str, Any]) -> Dict[str, Any]:
    return {"category": category, "ms": round(ms, 3), "count": int(count),
            "evidence": evidence}


def _self_times(events: List[Dict[str, Any]]) -> List[float]:
    """SELF milliseconds per attributed event: duration minus spans
    nested inside it on the same thread.  Container spans
    (``exchange.materialize`` wraps its child's whole execution, kernel
    compiles included) would otherwise double-count nested time and let
    a shuffle verdict absorb what is really compile or sync — or plain
    operator — time.  ``op``/``stage`` spans participate in the nesting
    stack as NEUTRAL containers: they absorb their children's time (so a
    shuffle span doesn't pay for the child plan's compute) but are never
    themselves attributed to a verdict."""
    idx = [i for i, ev in enumerate(events)
           if ev.get("cat", "") in _CAT_TO_VERDICT
           or ev.get("cat", "") in ("op", "stage")]
    out = [0.0] * len(events)
    by_tid: Dict[Any, List[int]] = {}
    for i in idx:
        by_tid.setdefault(events[i].get("tid"), []).append(i)
    for tids in by_tid.values():
        # sort by start; ties put the LONGER (outer) span first
        tids.sort(key=lambda i: (float(events[i].get("ts", 0.0)),
                                 -float(events[i].get("dur", 0.0))))
        stack: List[int] = []  # open enclosing spans, innermost last
        for i in tids:
            ts = float(events[i].get("ts", 0.0))
            dur = float(events[i].get("dur", 0.0))
            while stack:
                j = stack[-1]
                jts = float(events[j].get("ts", 0.0))
                jdur = float(events[j].get("dur", 0.0))
                if ts < jts + jdur:  # i nests inside j
                    out[j] -= dur / 1e3  # direct parent pays once
                    break
                stack.pop()
            out[i] += dur / 1e3
            stack.append(i)
    return [max(0.0, ms) for ms in out]


def diagnose(events: List[Dict[str, Any]],
             counters: Optional[Dict[str, float]] = None,
             metrics: Optional[Dict[str, Any]] = None,
             wall_ms: Optional[float] = None,
             dropped_events: int = 0,
             dispatch_cost_ms: float = DEFAULT_DISPATCH_COST_MS,
             dispatch_by_key: Optional[Dict[str, int]] = None
             ) -> Dict[str, Any]:
    """Ranked bottleneck diagnosis from a tracer snapshot.

    ``events`` is the tracer's event list (``dur`` in µs); ``counters``
    the tracer's aggregate counters; ``metrics`` the session's
    ``last_query_metrics``; ``wall_ms`` the query wall time when known
    (shares are computed against it, else against total attributed ms).
    """
    counters = counters or {}
    metrics = metrics or {}
    self_ms = _self_times(events)
    # per-verdict totals + per-(verdict, exec) evidence rows
    totals: Dict[str, Dict[str, float]] = {}
    by_exec: Dict[str, Dict[str, Dict[str, float]]] = {}
    for i, ev in enumerate(events):
        cat = ev.get("cat", "")
        verdict = _CAT_TO_VERDICT.get(cat)
        if verdict is None:
            continue
        ms = self_ms[i]
        args = ev.get("args") or {}
        nbytes = int(args.get("bytes", 0))
        t = totals.setdefault(verdict, {"ms": 0.0, "n": 0, "bytes": 0})
        t["ms"] += ms
        t["n"] += 1
        t["bytes"] += nbytes
        node = ev.get("exec") or "(driver)"
        rows = by_exec.setdefault(verdict, {})
        row = rows.setdefault(node, {"ms": 0.0, "n": 0, "bytes": 0})
        row["ms"] += ms
        row["n"] += 1
        row["bytes"] += nbytes

    ranked: List[Dict[str, Any]] = []
    for verdict, t in totals.items():
        top = sorted(by_exec.get(verdict, {}).items(),
                     key=lambda kv: -kv[1]["ms"])[:3]
        evidence: Dict[str, Any] = {"top_execs": [
            dict({"exec": name}, ms=round(r["ms"], 3), count=int(r["n"]),
                 **({"bytes": int(r["bytes"])} if r["bytes"] else {}))
            for name, r in top]}
        if t["bytes"]:
            evidence["bytes"] = int(t["bytes"])
        ranked.append(_verdict_entry(verdict, t["ms"], t["n"], evidence))

    attributed_ms = sum(e["ms"] for e in ranked)
    # dispatch-bound: launches the spans above do not explain.  Estimate
    # from the launch count at the conservative per-launch floor, capped
    # by the unattributed wall when the wall is known.
    dispatches = int(counters.get("deviceDispatches",
                                  metrics.get("deviceDispatches", 0)) or 0)
    if dispatches >= DISPATCH_FLOOR:
        est = dispatches * dispatch_cost_ms
        if wall_ms is not None:
            est = min(est, max(0.0, wall_ms - attributed_ms))
        if est > 0:
            ev = _dispatch_evidence(dispatches, metrics, dispatch_by_key)
            ev["stage_op_dispatches"] = int(
                metrics.get("stageOpDispatches", 0))
            ev["estimated"] = True
            ev["per_dispatch_ms"] = dispatch_cost_ms
            ranked.append(_verdict_entry(
                "dispatch-bound", est, dispatches, ev))

    # peer-failure: the query crossed the pod-scale fault domain —
    # quantified from the fault-domain metric deltas, with the ms cost
    # attributed from the fault-cat trace spans (dead declarations,
    # fenced zombie responses, recomputes)
    dead_peers = int(metrics.get("peersDeclaredDead", 0) or 0)
    stale_epochs = int(metrics.get("staleEpochsRefused", 0) or 0)
    failovers = int(metrics.get("deadPeerFailovers", 0) or 0)
    if dead_peers or stale_epochs or failovers:
        pf_ms = sum(
            self_ms[i] for i, ev in enumerate(events)
            if ev.get("cat") == "fault"
            and str(ev.get("name", "")).startswith(
                ("peer.", "shuffle.recompute", "shuffle.fetch.stale")))
        pf_ev = {"dead_peers": dead_peers, "stale_epochs": stale_epochs,
                 "dead_failovers": failovers,
                 "proactive_recomputes": int(
                     metrics.get("proactiveRecomputes", 0) or 0)}
        ranked.append(_verdict_entry(
            "peer-failure", pf_ms,
            dead_peers + stale_epochs + failovers, pf_ev))

    ranked.sort(key=lambda e: -e["ms"])
    denom = wall_ms if wall_ms else (attributed_ms or 1.0)
    for e in ranked:
        e["share"] = round(min(1.0, e["ms"] / max(denom, 1e-9)), 4)
    _stamp_levers(ranked, stages=sum(
        1 for ev in events if ev.get("cat") == "stage"))

    caveats: List[str] = []
    truncated = bool(dropped_events)
    if truncated:
        caveats.append(
            f"trace ring overflowed: {int(dropped_events)} oldest events "
            f"dropped — attribution UNDERCOUNTS early-query time (raise "
            f"spark.rapids.tpu.trace.bufferEvents)")
    if not events:
        caveats.append("no trace events: diagnosis is counters-only")
    out = {
        "schema": SCHEMA,
        "verdict": ranked[0]["category"] if ranked else "no-bottleneck",
        "ranked": ranked,
        "attributed_ms": round(attributed_ms, 3),
        "trace_truncated": truncated,
        "caveats": caveats,
    }
    if wall_ms is not None:
        out["wall_ms"] = round(float(wall_ms), 3)
    # remembered process-wide so a later fatal-device dump can record
    # what the engine believed it was bound on (memory/fatal.py)
    global LAST_VERDICT
    import time as _t
    LAST_VERDICT = {"verdict": out["verdict"], "at": _t.monotonic()}
    return out


def diagnose_summary(summary: Dict[str, Any],
                     metrics: Optional[Dict[str, Any]] = None,
                     wall_ms: Optional[float] = None,
                     evidence: Optional[str] = None,
                     evidence_age_s: Optional[float] = None
                     ) -> Dict[str, Any]:
    """Degraded-fidelity diagnosis from a compact ``trace_summary``
    (bench artifacts / replay captures — no per-exec evidence; note the
    summary's ``sync_ms`` already folds blocking d2h time in, so the
    transfer verdict here rides byte counts + the residual).

    ``evidence``/``evidence_age_s`` stamp the measurement's provenance
    (bench.py evidence classes).  A non-live class is marked loudly —
    :func:`followup` refuses to name a next bottleneck from it: a
    replay's bottleneck was true hours ago and chasing it wastes the
    next live window (ISSUE 18)."""
    metrics = metrics or {}
    ranked: List[Dict[str, Any]] = []

    def add(category: str, ms: float, count: int, **ev: Any) -> None:
        if ms > 0 or count > 0:
            ranked.append(_verdict_entry(category, ms, count, dict(ev)))

    add("sync-bound", float(summary.get("sync_ms", 0.0)),
        int(summary.get("sync_count", 0)),
        note="summary sync_ms folds blocking d2h fetch time in")
    add("compile-bound", float(summary.get("compile_ms", 0.0)),
        int(summary.get("compile_count", 0)))
    add("spill-bound", float(summary.get("spill_ms", 0.0)), 0)
    add("sem_wait-bound", float(summary.get("sem_wait_ms", 0.0)), 0)
    h2d, d2h = (int(summary.get("h2d_bytes", 0)),
                int(summary.get("d2h_bytes", 0)))
    if h2d or d2h:
        ranked.append(_verdict_entry(
            "h2d-d2h-bound", 0.0, 0,
            {"h2d_bytes": h2d, "d2h_bytes": d2h,
             "note": "bytes only: summary carries no transfer ms"}))
    dispatches = int(summary.get("device_dispatches",
                                 metrics.get("deviceDispatches", 0)) or 0)
    if dispatches >= DISPATCH_FLOOR:
        # summaries may bank the per-key launch table (bench artifacts);
        # when absent, evidence degrades to totals + probe-batch ratio
        ev = _dispatch_evidence(
            dispatches, metrics,
            dict(summary.get("dispatch_by_key") or {}))
        ev["estimated"] = True
        add("dispatch-bound", dispatches * DEFAULT_DISPATCH_COST_MS,
            dispatches, **ev)
    ranked.sort(key=lambda e: -e["ms"])
    attributed_ms = sum(e["ms"] for e in ranked)
    denom = wall_ms if wall_ms else (attributed_ms or 1.0)
    for e in ranked:
        e["share"] = round(min(1.0, e["ms"] / max(denom, 1e-9)), 4)
    _stamp_levers(ranked)
    caveats = ["diagnosed from compact trace_summary: no exec-level "
               "spans, transfer time folded into sync-bound"]
    if summary.get("trace_truncated") or summary.get("dropped_events"):
        caveats.append("trace was truncated (dropped_events > 0)")
    out = {
        "schema": SCHEMA,
        "verdict": ranked[0]["category"] if ranked else "no-bottleneck",
        "ranked": ranked,
        "attributed_ms": round(attributed_ms, 3),
        "trace_truncated": bool(summary.get("trace_truncated")
                                or summary.get("dropped_events")),
        "caveats": caveats,
    }
    if wall_ms is not None:
        out["wall_ms"] = round(float(wall_ms), 3)
    if evidence is not None:
        out["evidence"] = str(evidence)
        if evidence_age_s is not None:
            out["evidence_age_s"] = round(float(evidence_age_s), 1)
        if evidence != "live":
            age = (f" aged {float(evidence_age_s):.0f}s"
                   if evidence_age_s is not None else "")
            caveats.append(
                f"STALE-EVIDENCE: diagnosed from {evidence} "
                f"evidence{age} — next-bottleneck follow-ups are "
                f"refused until a live window recaptures")
    return out


def evidence_age_s(captured_at: Any,
                   now: Optional[float] = None) -> Optional[float]:
    """Seconds since a capture's UTC ``captured_at`` stamp
    (``%Y-%m-%dT%H:%M:%SZ``, the tunnel-watcher filename stamp bench.py
    grafts onto replays), or None when unparseable."""
    import calendar
    import time as _t
    try:
        then = calendar.timegm(
            _t.strptime(str(captured_at), "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError, OverflowError):
        return None
    return max(0.0, (now if now is not None else _t.time()) - then)


def diagnose_artifact(rec: Dict[str, Any],
                      now: Optional[float] = None) -> Dict[str, Any]:
    """Degraded diagnosis over one whole bench artifact: every
    ``*trace_summary`` dict in it (q1 + each shape) aggregates into one
    summary, and the artifact's evidence class + replay age stamp the
    output so the stale-evidence gate applies (ISSUE 18 — the sentry's
    ledger verdicts ride this)."""
    agg: Dict[str, float] = {}

    def walk(obj: Any) -> None:
        if not isinstance(obj, dict):
            return
        for k, v in obj.items():
            if k.endswith("trace_summary") and isinstance(v, dict):
                for sk, sv in v.items():
                    if isinstance(sv, (int, float)) \
                            and not isinstance(sv, bool):
                        agg[sk] = agg.get(sk, 0.0) + sv
            elif isinstance(v, dict):
                walk(v)

    walk(rec)
    ev = rec.get("evidence")
    if not ev:
        if "captured_at" in rec:
            ev = "stale-replay"
        elif rec.get("platform") in (None, "cpu"):
            ev = "cpu-fallback"
        else:
            ev = "live"
    age = (evidence_age_s(rec.get("captured_at"), now=now)
           if "captured_at" in rec else None)
    return diagnose_summary(agg, evidence=str(ev), evidence_age_s=age)


def followup(diag: Dict[str, Any],
             evidence: Optional[str] = None,
             evidence_age_s: Optional[float] = None) -> str:
    """Machine-named next-bottleneck follow-up with quantified lever
    evidence, e.g. ``sync-bound: readbacks=18, ms_per_readback=6.7,
    top_exec=ShuffleExchangeExec; lever: fuse/pipeline the blocking
    readbacks``.  Provenance defaults to the diagnosis's own
    ``evidence`` stamps; anything non-live gets a loud STALE-EVIDENCE
    marker instead of a follow-up — a bottleneck measured on a replay
    is not a bottleneck to chase now."""
    if evidence is None:
        evidence = str(diag.get("evidence") or "live")
    if evidence_age_s is None:
        evidence_age_s = diag.get("evidence_age_s")
    verdict = diag.get("verdict", "no-bottleneck")
    if evidence != "live":
        age = (f" aged {float(evidence_age_s):.0f}s"
               if evidence_age_s is not None else "")
        return (f"STALE-EVIDENCE: verdict '{verdict}' from {evidence} "
                f"evidence{age} — follow-up refused; recapture on a "
                f"live window")
    ranked = diag.get("ranked") or []
    if verdict == "no-bottleneck" or not ranked:
        return "no-bottleneck: nothing to chase"
    top = ranked[0]
    lv = dict((top.get("evidence") or {}).get("levers") or {})
    if not lv:
        # compact() rows inline their quantified keys instead
        for k in ("readbacks_per_stage", "device_dispatches",
                  "launches_per_probe_batch", "bytes", "h2d_bytes",
                  "d2h_bytes", "top_exec"):
            if top.get(k) is not None:
                lv[k] = top[k]
        if not lv:
            lv = {"ms": top.get("ms"), "count": top.get("count")}
    parts = ", ".join(f"{k}={v}" for k, v in lv.items())
    lever = LEVERS.get(verdict, "")
    return (f"{verdict}: {parts}"
            + (f"; lever: {lever}" if lever else ""))


def diagnose_tenants(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-TENANT bottleneck verdicts from flight-recorder records (the
    serving tier's ``engine.diagnose_tenants()``): records group by their
    ``tenant`` stamp, each group's trace summaries aggregate into one
    degraded-fidelity :func:`diagnose_summary`, and admission-queue wait
    (``admissionWaitMs`` in each record's metrics) joins the ranking as
    ``admission-bound`` — a tenant whose time goes to waiting for slots
    needs a weight/budget change, not a kernel fix."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        groups.setdefault(str(rec.get("tenant") or "default"),
                          []).append(rec)
    out: Dict[str, Any] = {}
    for tenant, recs in sorted(groups.items()):
        durs = sorted(float(r.get("duration_ms", 0.0)) for r in recs)
        agg: Dict[str, float] = {}
        adm_ms = 0.0
        adm_n = 0
        for r in recs:
            for k, v in (r.get("trace_summary") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    agg[k] = agg.get(k, 0.0) + v
            w = (r.get("metrics") or {}).get("admissionWaitMs", 0.0)
            if w:
                adm_ms += float(w)
                adm_n += 1
        wall = sum(durs) + adm_ms
        diag = diagnose_summary(agg, wall_ms=wall or None)
        if adm_ms > 0:
            diag["ranked"].append(_verdict_entry(
                "admission-bound", adm_ms, adm_n,
                {"note": "time queued before execution; levers: tenant "
                         "weight, memory budget, maxConcurrentQueries"}))
            diag["ranked"].sort(key=lambda e: -e["ms"])
            denom = wall or sum(e["ms"] for e in diag["ranked"]) or 1.0
            for e in diag["ranked"]:
                e["share"] = round(min(1.0, e["ms"] / max(denom, 1e-9)), 4)
            diag["verdict"] = diag["ranked"][0]["category"]
            diag["attributed_ms"] = round(
                sum(e["ms"] for e in diag["ranked"]), 3)
            _stamp_levers(diag["ranked"])

        def _pctl(q: float) -> float:
            if not durs:
                return 0.0
            return durs[min(len(durs) - 1, int(q * len(durs)))]

        out[tenant] = {
            "queries": len(recs),
            "failed": sum(1 for r in recs
                          if r.get("status") != "ok"),
            "p50_ms": round(_pctl(0.50), 3),
            "p99_ms": round(_pctl(0.99), 3),
            "admission_wait_ms": round(adm_ms, 3),
            "diagnosis": compact(diag),
        }
    return out


def compact(diag: Dict[str, Any], top: int = 3) -> Dict[str, Any]:
    """Bench-artifact form: verdict + top-N {category, ms, share, count}
    (evidence trimmed to its counters; bench banks this per shape)."""
    rows = []
    for e in diag.get("ranked", [])[:top]:
        row = {"category": e["category"], "ms": e["ms"],
               "share": e.get("share", 0.0), "count": e["count"]}
        ev = e.get("evidence", {})
        for k in ("bytes", "device_dispatches", "h2d_bytes", "d2h_bytes",
                  "launches_per_probe_batch", "top_exec", "top_kernels",
                  "levers"):
            if ev.get(k):
                row[k] = ev[k]
        rows.append(row)
    out = {"verdict": diag.get("verdict", "no-bottleneck"), "ranked": rows}
    if diag.get("trace_truncated"):
        out["trace_truncated"] = True
    return out


# --------------------------------------------------------------------------
# CLI: diagnose an exported event log (JSONL) or Chrome trace JSON
# --------------------------------------------------------------------------

def _events_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Chrome trace-event JSON -> tracer-shaped events (dur stays µs;
    exec rides args.exec in the export)."""
    out = []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args") or {})
        out.append({"cat": ev.get("cat", ""), "name": ev.get("name", ""),
                    "ts": float(ev.get("ts", 0.0)),
                    "dur": float(ev.get("dur", 0.0)),
                    "tid": ev.get("tid", 0),
                    "exec": args.pop("exec", ""), "args": args})
    return out


def _load(path: str):
    """[(meta, events)] from a JSONL event log or a Chrome trace file."""
    with open(path) as fh:
        head = fh.read(1)
    if head == "{":
        with open(path) as fh:
            first = json.loads(fh.readline())
        if "traceEvents" in first:  # single-line chrome trace
            return [({}, _events_from_chrome(first))]
    from .export import read_event_log
    try:
        return read_event_log(path)
    except ValueError:
        with open(path) as fh:
            return [({}, _events_from_chrome(json.load(fh)))]


def main(argv: List[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 1
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        out_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    logs = _load(argv[0])
    if not logs:
        print("no queries found in", argv[0], file=sys.stderr)
        return 1
    # diagnose the LAST query in the log (newest appended)
    meta, events = logs[-1]
    diag = diagnose(events, counters=meta.get("counters"),
                    dropped_events=int(meta.get("dropped_events", 0)))
    text = json.dumps(diag, indent=1)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
