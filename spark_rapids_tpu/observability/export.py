"""Trace export — Chrome trace-event JSON (Perfetto-loadable) and an
append-only JSONL event log per query (the Spark eventLog/history analog).

Chrome trace-event schema (the subset we emit, validated by
tools/check_trace.py and the tracer tests):

* every event carries ``ph``, ``ts``, ``pid``, ``tid``, ``name``;
* spans are ``ph == "X"`` complete events with ``dur`` (µs);
* aggregate counters export as ``ph == "C"`` counter events;
* thread/process names ride ``ph == "M"`` metadata events.

JSONL log layout: line 1 is a ``{"meta": ...}`` header (query id, wall
epoch, capacity, drop count, counters); each following line is one event
exactly as the tracer recorded it — so a round trip through
:func:`write_event_log`/:func:`read_event_log` is lossless.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple


def chrome_trace(events: List[Dict[str, Any]],
                 meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Events (tracer snapshot) -> Chrome trace-event JSON object."""
    meta = meta or {}
    pid = int(meta.get("pid", os.getpid()))
    out: List[Dict[str, Any]] = []
    # compact tids: Perfetto renders raw pthread ids poorly
    tid_map: Dict[int, int] = {}

    def tid_of(raw) -> int:
        t = tid_map.get(raw)
        if t is None:
            t = tid_map[raw] = len(tid_map)
        return t

    for ev in events:
        args = dict(ev.get("args") or {})
        if ev.get("exec"):
            args["exec"] = ev["exec"]
        # per-event tenant/session identity (serving tier: one engine
        # trace interleaves N sessions, so identity rides the events)
        if ev.get("tenant"):
            args["tenant"] = ev["tenant"]
        if ev.get("sid"):
            args["sid"] = ev["sid"]
        out.append({
            "ph": "X", "cat": ev.get("cat", ""), "name": ev["name"],
            "ts": round(float(ev["ts"]), 3),
            "dur": round(float(ev.get("dur", 0.0)), 3),
            "pid": pid, "tid": tid_of(ev.get("tid", 0)),
            "args": args,
        })
    end_ts = max((e["ts"] + e["dur"] for e in out), default=0.0)
    for name, value in (meta.get("counters") or {}).items():
        out.append({"ph": "C", "name": name, "ts": round(end_ts, 3),
                    "pid": pid, "tid": 0, "args": {"value": value}})
    out.append({"ph": "M", "name": "process_name", "ts": 0, "pid": pid,
                "tid": 0, "args": {"name": "spark_rapids_tpu"}})
    if meta.get("session_id"):
        # session id as a Perfetto process label, so traces from several
        # sessions stay distinguishable after merging
        out.append({"ph": "M", "name": "process_labels", "ts": 0,
                    "pid": pid, "tid": 0,
                    "args": {"labels": f"session={meta['session_id']}"}})
    for raw, t in tid_map.items():
        out.append({"ph": "M", "name": "thread_name", "ts": 0, "pid": pid,
                    "tid": t, "args": {"name": f"thread-{t} ({raw})"}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {k: v for k, v in meta.items()
                          if k not in ("counters",)}}


def write_chrome_trace(path: str, events: List[Dict[str, Any]],
                       meta: Optional[Dict[str, Any]] = None) -> str:
    with open(path, "w") as fh:
        json.dump(chrome_trace(events, meta), fh)
    return path


# --------------------------------------------------------------------------
# JSONL event log (eventLog/history analog)
# --------------------------------------------------------------------------

def write_event_log(path: str, events: List[Dict[str, Any]],
                    meta: Optional[Dict[str, Any]] = None) -> str:
    """Append one query's timeline to ``path`` (header line + events).
    Append-only: successive queries pointed at the same file stack their
    logs, each self-delimited by its meta header."""
    with open(path, "a") as fh:
        fh.write(json.dumps({"meta": meta or {}}) + "\n")
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    return path


def read_event_log(path: str
                   ) -> List[Tuple[Dict[str, Any], List[Dict[str, Any]]]]:
    """Parse a JSONL event log back into [(meta, events), ...] — one
    entry per appended query."""
    out: List[Tuple[Dict[str, Any], List[Dict[str, Any]]]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "meta" in rec and "name" not in rec:
                out.append((rec["meta"], []))
            elif out:
                out[-1][1].append(rec)
            else:  # tolerate logs whose header line was truncated away
                out.append(({}, [rec]))
    return out


def event_log_path(sink_dir: str, query_id: int) -> str:
    """Per-query log file under the configured sink directory."""
    os.makedirs(sink_dir, exist_ok=True)
    return os.path.join(sink_dir, f"query-{os.getpid()}-{query_id}.jsonl")
