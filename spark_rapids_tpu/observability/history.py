"""Query flight recorder — a bounded ring of per-query records, in
memory always and as an on-disk JSONL ring when a path is configured.

Every executed query leaves one record: plan fingerprint + outline,
wall-clock duration, ``last_query_metrics``, the compact
``trace_summary`` (when traced), decode-engagement and wire-byte
sub-views, session/query ids, and status.  ``sess.query_history()``
reads it back; the on-disk ring survives the process (the Spark
history-server analog at flight-recorder weight: JSONL, newest last,
compacted in place when it outgrows twice the bound).

Write cost per query is one dict build + one appended JSON line —
negligible next to a collect — so the in-memory recorder is ON by
default (``spark.rapids.tpu.history.enabled``); the disk ring engages
only when ``spark.rapids.tpu.history.path`` is set.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_MAX_QUERIES = 128

#: metric-key prefixes folded into the record's ``decode_engagement``
#: sub-view (io_/decode_stats.py + encoded-execution counters)
_ENGAGEMENT_PREFIXES = ("parquet", "orc", "csv", "json", "encoded")
#: metric keys folded into the ``wire`` sub-view
_WIRE_KEYS = ("shuffleBytesOnWire", "shuffleFramesWritten",
              "shuffleEncodedBytesSaved", "prepackBytesOnWire",
              "prepackBytesNaive")


def plan_fingerprint(phys) -> str:
    """Stable fingerprint of a physical plan's SHAPE: node names over the
    tree structure, independent of literals and instance identity — two
    runs of the same query shape share a fingerprint, which is what the
    plan-fingerprint → cached-result tier (ROADMAP item 1) keys on."""
    parts: List[str] = []

    def walk(node, depth: int) -> None:
        parts.append(f"{depth}:{node.node_name()}")
        for c in node.children:
            walk(c, depth + 1)

    walk(phys, 0)
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def plan_outline(phys, max_nodes: int = 40) -> List[str]:
    """Indented node-name outline (bounded) for human-readable records."""
    out: List[str] = []

    def walk(node, depth: int) -> None:
        if len(out) >= max_nodes:
            return
        out.append("  " * depth + node.node_name())
        for c in node.children:
            walk(c, depth + 1)

    walk(phys, 0)
    if len(out) >= max_nodes:
        out.append("...")
    return out


def build_record(*, query_id: int, session_id: str, ok: bool,
                 duration_ms: float, phys=None,
                 metrics: Optional[Dict[str, Any]] = None,
                 trace_summary: Optional[Dict[str, Any]] = None,
                 error: Optional[str] = None,
                 tenant: str = "") -> Dict[str, Any]:
    """One flight-recorder record (schema documented in
    docs/observability.md)."""
    rec: Dict[str, Any] = {
        "ts": round(time.time(), 3),
        "query": int(query_id),
        "session": session_id,
        "status": "ok" if ok else "failed",
        "duration_ms": round(float(duration_ms), 3),
    }
    if tenant:
        rec["tenant"] = tenant
    if phys is not None:
        rec["plan_fingerprint"] = plan_fingerprint(phys)
        rec["plan"] = plan_outline(phys)
    if error:
        rec["error"] = str(error)[:500]
    if metrics:
        rec["metrics"] = {k: v for k, v in metrics.items()}
        engagement = {k: v for k, v in metrics.items()
                      if k.startswith(_ENGAGEMENT_PREFIXES)}
        if engagement:
            rec["decode_engagement"] = engagement
        wire = {k: metrics[k] for k in _WIRE_KEYS if metrics.get(k)}
        if wire:
            rec["wire"] = wire
    if trace_summary:
        rec["trace_summary"] = trace_summary
    return rec


#: one QueryHistory per on-disk path, process-wide: concurrent sessions
#: configured with the same JSONL ring MUST share one instance (and thus
#: one append lock) — separate instances would interleave partial lines
#: through independent file handles and double-compact each other's
#: rewrites.  In-memory-only histories stay per-session (empty path).
_SHARED_LOCK = threading.Lock()
_SHARED: Dict[str, "QueryHistory"] = {}


def shared_history(max_queries: int, path: str) -> "QueryHistory":
    """The process-wide QueryHistory for ``path`` (a fresh private one
    when ``path`` is empty).  All appends to one file serialize through
    the shared instance's lock; ``tail(session=...)`` filters a shared
    ring back down to one session's queries."""
    if not path:
        return QueryHistory(max_queries, "")
    key = os.path.abspath(path)
    with _SHARED_LOCK:
        h = _SHARED.get(key)
        if h is None:
            h = _SHARED[key] = QueryHistory(max_queries, path)
        return h


class QueryHistory:
    """Bounded in-memory ring + optional on-disk JSONL ring."""

    def __init__(self, max_queries: int = DEFAULT_MAX_QUERIES,
                 path: str = ""):
        self._lock = threading.Lock()
        self.max_queries = max(1, int(max_queries))
        self.path = path or ""
        self._ring: deque = deque(maxlen=self.max_queries)

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(rec)
            if self.path:
                try:
                    self._append_disk(rec)
                except OSError:
                    pass  # the recorder must never fail the query

    def _append_disk(self, rec: Dict[str, Any]) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
        # compact once the file holds > 2x the bound: rewrite the newest
        # max_queries records atomically (tmp + rename)
        try:
            with open(self.path) as fh:
                lines = fh.readlines()
        except OSError:
            return
        if len(lines) <= 2 * self.max_queries:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.writelines(lines[-self.max_queries:])
        os.replace(tmp, self.path)

    def tail(self, n: Optional[int] = None,
             session: Optional[str] = None,
             tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        """Newest-last records; ``n`` bounds the result (None = all).
        ``session``/``tenant`` filter a SHARED ring down to one owner's
        records (the multi-session contract: every record is stamped
        with both, so a session reading a ring other sessions also feed
        still sees exactly its own queries)."""
        with self._lock:
            out = list(self._ring)
        if session is not None:
            out = [r for r in out if r.get("session") == session]
        if tenant is not None:
            out = [r for r in out if r.get("tenant", "") == tenant]
        if n is not None:
            out = out[-max(0, int(n)):]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def read_history_file(path: str) -> List[Dict[str, Any]]:
    """Parse an on-disk history ring back into records (newest last);
    tolerates a torn final line from a killed writer."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
