"""Process-wide metrics registry — counters, gauges and log-bucketed
latency histograms with Prometheus text-format and JSON snapshot export.

The tracer answers "where did THIS query's wall time go"; the registry
answers "what does this PROCESS look like over many queries": spill
bytes by tier direction, shuffle frame sizes, kernel-cache hit rates,
semaphore wait distributions, per-category span latencies — each series
labeled with the owning query id and session id (and, for span-fed
series, the exec node), which is the groundwork the multi-tenant serving
layer (ROADMAP item 1) needs for per-tenant accounting.

Cost model: the same single-dict-lookup gate as ``TRACING`` /
``PROFILING`` — every feed site checks ``METRICS["on"]`` before building
anything, so a disabled registry costs one dict lookup per chokepoint.
The session flips the flag per query from
``spark.rapids.tpu.metrics.enabled`` (save/restore like the tracing
flags, so an exception mid-query cannot leak it).

Histograms are log2-bucketed: bucket upper bounds are powers of two over
a fixed span, so ``observe`` is a ``frexp`` + one locked increment, and
p50/p95/p99 are interpolated from the cumulative bucket counts — the
fidelity/overhead point the Theseus-style movement-distribution argument
needs (a flat millisecond sum hides the one 300ms sync among a thousand
30µs ones).

Cardinality is bounded (``spark.rapids.tpu.metrics.maxSeries``): past
the cap, NEW series are dropped and counted in ``metrics_dropped_series``
so an exec-name explosion can never OOM the driver.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional, Tuple

#: master switch — flipped per query by the session (restored in a
#: ``finally``).  Near-zero overhead when off: one dict lookup per site.
METRICS = {"on": False}

#: histogram bucket upper bounds: powers of two from 2^-14 (~61µs when
#: observing ms, ~0.06 when observing counts) through 2^20, plus +Inf.
#: Chosen so both millisecond latencies (0.1ms..10s) and byte/row sizes
#: land in the resolved middle of the span.
_BUCKET_EXP_LO = -14
_BUCKET_EXP_HI = 20
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    float(2.0 ** e) for e in range(_BUCKET_EXP_LO, _BUCKET_EXP_HI + 1)
) + (math.inf,)

_N_BUCKETS = len(BUCKET_BOUNDS)


def _bucket_index(value: float) -> int:
    """Index of the first bucket whose upper bound is >= value — one
    frexp, no search.  Non-positive values land in bucket 0."""
    if value <= 0.0 or value != value:  # <=0 or NaN
        return 0
    # frexp: value = m * 2**e with 0.5 <= m < 1  =>  2**(e-1) <= v < 2**e
    # bucket bound 2**e covers it unless v == 2**(e-1) exactly (m == 0.5)
    m, e = math.frexp(value)
    exp = e if m > 0.5 else e - 1
    idx = exp - _BUCKET_EXP_LO
    if idx < 0:
        return 0
    if idx >= _N_BUCKETS:
        return _N_BUCKETS - 1
    return idx


class _Histogram:
    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        self.buckets[_bucket_index(value)] += 1
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, q: float) -> float:
        """Interpolated quantile from cumulative bucket counts; exact at
        the recorded min/max ends, linear within a bucket."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            if cum + n >= target:
                lo = 0.0 if i == 0 else BUCKET_BOUNDS[i - 1]
                hi = BUCKET_BOUNDS[i]
                if hi == math.inf:
                    return self.max
                frac = (target - cum) / n
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                # never report outside the observed range
                return max(self.min, min(self.max, est))
            cum += n
        return self.max

    def snapshot(self) -> Dict[str, Any]:
        out = {"count": self.count, "sum": round(self.sum, 6)}
        if self.count:
            out.update(min=round(self.min, 6), max=round(self.max, 6),
                       p50=round(self.quantile(0.50), 6),
                       p95=round(self.quantile(0.95), 6),
                       p99=round(self.quantile(0.99), 6))
        return out


#: series key: (name, tuple(sorted(label items)))
_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


class MetricsRegistry:
    """Thread-safe registry of counters / gauges / histograms keyed by
    (name, labels).  One instance per process (:func:`get_registry`)."""

    def __init__(self, max_series: int = 4096):
        self._lock = threading.Lock()
        self.max_series = int(max_series)
        self._counters: Dict[_SeriesKey, float] = {}
        self._gauges: Dict[_SeriesKey, float] = {}
        self._hists: Dict[_SeriesKey, _Histogram] = {}
        self.dropped_series = 0
        #: labels merged into every series (query id / session id) —
        #: set by the session at query start, single-driver model
        self._default_labels: Dict[str, str] = {}
        #: per-THREAD label overlay (tenant / session / query under the
        #: serving tier, where N driver threads record concurrently and
        #: one global default would cross-stamp tenants).  Thread labels
        #: override default labels; explicit call labels override both.
        self._tls = threading.local()

    # --- lifecycle --------------------------------------------------------
    def reset(self, max_series: Optional[int] = None) -> None:
        with self._lock:
            if max_series is not None:
                self.max_series = int(max_series)
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self.dropped_series = 0

    def set_default_labels(self, **labels: Any) -> None:
        """Labels stamped on every subsequently-recorded series (the
        session sets ``query``/``session`` here per query)."""
        with self._lock:
            self._default_labels = {k: str(v) for k, v in labels.items()
                                    if v is not None and str(v) != ""}

    def set_thread_labels(self, **labels: Any) -> None:
        """Labels stamped on series recorded from THIS thread (the
        serving tier sets ``tenant``/``session``/``query`` per admitted
        query on its driver thread).  Pool/prefetch helper threads do
        not inherit them — their series keep engine-scope labels only
        (docs/serving.md)."""
        self._tls.labels = {k: str(v) for k, v in labels.items()
                            if v is not None and str(v) != ""}

    def clear_thread_labels(self) -> None:
        self._tls.labels = None

    # --- recording --------------------------------------------------------
    def _key(self, name: str, labels: Dict[str, Any]) -> _SeriesKey:
        thread_labels = getattr(self._tls, "labels", None)
        if self._default_labels or thread_labels:
            merged = dict(self._default_labels)
            if thread_labels:
                merged.update(thread_labels)
            merged.update(labels)
        else:
            merged = labels
        return (name, tuple(sorted(
            (k, str(v)) for k, v in merged.items())))

    def _admit(self, table: dict, key: _SeriesKey) -> bool:
        """New-series cardinality gate (callers hold the lock)."""
        if key in table:
            return True
        total = (len(self._counters) + len(self._gauges)
                 + len(self._hists))
        if total >= self.max_series:
            self.dropped_series += 1
            return False
        return True

    def inc(self, name: str, value: float = 1.0, **labels: Any) -> None:
        with self._lock:
            key = self._key(name, labels)
            if not self._admit(self._counters, key):
                return
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: Any) -> None:
        with self._lock:
            key = self._key(name, labels)
            if not self._admit(self._gauges, key):
                return
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, **labels: Any) -> None:
        with self._lock:
            key = self._key(name, labels)
            h = self._hists.get(key)
            if h is None:
                if not self._admit(self._hists, key):
                    return
                h = self._hists[key] = _Histogram()
            h.observe(float(value))

    # --- readout ----------------------------------------------------------
    def json_snapshot(self) -> Dict[str, Any]:
        """Structured snapshot: {counters: [...], gauges: [...],
        histograms: [...]}, each entry {name, labels, ...values}."""

        def row(key: _SeriesKey, body: Dict[str, Any]) -> Dict[str, Any]:
            name, labels = key
            return dict({"name": name, "labels": dict(labels)}, **body)

        with self._lock:
            return {
                "counters": [row(k, {"value": round(v, 6)})
                             for k, v in sorted(self._counters.items())],
                "gauges": [row(k, {"value": round(v, 6)})
                           for k, v in sorted(self._gauges.items())],
                "histograms": [row(k, h.snapshot())
                               for k, h in sorted(self._hists.items())],
                "dropped_series": self.dropped_series,
            }

    def prometheus_text(self, prefix: str = "srt_") -> str:
        """Prometheus exposition text format.  Counters export with a
        ``_total`` suffix, histograms as cumulative ``_bucket{le=...}``
        series plus ``_sum``/``_count`` — the standard scrape contract,
        validated by ``tools/check_trace.py --prometheus``."""
        lines: List[str] = []

        def fmt_labels(labels, extra: str = "") -> str:
            parts = [f'{_sanitize(k)}="{_escape(v)}"' for k, v in labels]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        def fmt_val(v: float) -> str:
            if v == math.inf:
                return "+Inf"
            if v == int(v) and abs(v) < 1e15:
                return str(int(v))
            return repr(float(v))

        with self._lock:
            by_name: Dict[str, List[Tuple[_SeriesKey, Any]]] = {}
            for k, v in self._counters.items():
                by_name.setdefault((k[0], "counter"), []).append((k, v))
            for k, v in self._gauges.items():
                by_name.setdefault((k[0], "gauge"), []).append((k, v))
            for k, h in self._hists.items():
                by_name.setdefault((k[0], "histogram"), []).append((k, h))
            for (name, typ), rows in sorted(by_name.items()):
                base = prefix + _sanitize(name)
                if typ == "counter" and not base.endswith("_total"):
                    base += "_total"
                lines.append(f"# TYPE {base} {typ}")
                for key, v in sorted(rows):
                    labels = key[1]
                    if typ in ("counter", "gauge"):
                        lines.append(
                            f"{base}{fmt_labels(labels)} {fmt_val(v)}")
                        continue
                    cum = 0
                    for i, n in enumerate(v.buckets):
                        cum += n
                        if n == 0 and BUCKET_BOUNDS[i] != math.inf:
                            continue  # sparse: emit touched + +Inf only
                        le = f'le="{fmt_val(BUCKET_BOUNDS[i])}"'
                        lines.append(f"{base}_bucket"
                                     f"{fmt_labels(labels, le)} {cum}")
                    lines.append(f"{base}_sum{fmt_labels(labels)} "
                                 f"{fmt_val(round(v.sum, 6))}")
                    lines.append(f"{base}_count{fmt_labels(labels)} "
                                 f"{v.count}")
            lines.append(f"# TYPE {prefix}metrics_dropped_series gauge")
            lines.append(f"{prefix}metrics_dropped_series "
                         f"{self.dropped_series}")
        return "\n".join(lines) + "\n"


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


# --------------------------------------------------------------------------
# guarded convenience feeds (one flag lookup when off — call these from
# chokepoints instead of reaching for the registry directly)
# --------------------------------------------------------------------------

def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    if METRICS["on"]:
        _REGISTRY.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: Any) -> None:
    if METRICS["on"]:
        _REGISTRY.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels: Any) -> None:
    if METRICS["on"]:
        _REGISTRY.observe(name, value, **labels)


def write_json_snapshot(path: str) -> str:
    with open(path, "w") as fh:
        json.dump(_REGISTRY.json_snapshot(), fh, indent=1)
    return path


def write_prometheus(path: str) -> str:
    with open(path, "w") as fh:
        fh.write(_REGISTRY.prometheus_text())
    return path
