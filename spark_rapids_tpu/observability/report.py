"""Per-query attribution reports over tracer timelines.

Answers the question a bare rows/s number can't: where did the wall time
go — operator self-time, blocked device readbacks, kernel trace+compile,
bytes across the host link, spill, semaphore waits — per exec node.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

#: categories whose spans are host-BLOCKING device waits (the "sync time"
#: column): scalar readbacks and D2H fetches both stall the driver for a
#: full tunnel round trip
_BLOCKING_CATS = ("sync", "d2h")

_ZERO = {"sync_ms": 0.0, "sync_n": 0, "compile_ms": 0.0, "compile_n": 0,
         "h2d_bytes": 0, "d2h_bytes": 0, "spill_ms": 0.0,
         "sem_wait_ms": 0.0, "shuffle_ms": 0.0, "fault_n": 0,
         "stage_ms": 0.0, "stage_n": 0}


def aggregate_by_exec(events: List[Dict[str, Any]]
                      ) -> Dict[str, Dict[str, Any]]:
    """Fold a tracer snapshot into per-exec-node attribution rows.  The
    empty exec name (spans fired outside any plan node — e.g. the
    driver's final result fetch) reports as ``(driver)``."""
    out: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        node = ev.get("exec") or "(driver)"
        row = out.get(node)
        if row is None:
            row = out[node] = dict(_ZERO)
        cat = ev.get("cat", "")
        ms = float(ev.get("dur", 0.0)) / 1e3
        args = ev.get("args") or {}
        if cat in _BLOCKING_CATS:
            row["sync_ms"] += ms
            row["sync_n"] += 1
            if cat == "d2h":
                row["d2h_bytes"] += int(args.get("bytes", 0))
        elif cat == "kernel_compile":
            row["compile_ms"] += ms
            row["compile_n"] += 1
        elif cat == "h2d":
            row["h2d_bytes"] += int(args.get("bytes", 0))
        elif cat == "spill":
            row["spill_ms"] += ms
        elif cat == "sem_wait":
            row["sem_wait_ms"] += ms
        elif cat == "shuffle":
            row["shuffle_ms"] += ms
        elif cat == "fault":
            row["fault_n"] += 1
        elif cat == "stage":
            row["stage_ms"] += ms
            row["stage_n"] += 1
    return out


def trace_summary(events: List[Dict[str, Any]],
                  counters: Optional[Dict[str, float]] = None,
                  dropped: int = 0) -> Dict[str, Any]:
    """Compact whole-query summary for bench artifacts: blocking sync
    count/ms, kernel trace+compile ms, bytes on the wire."""
    agg = aggregate_by_exec(events)
    tot = dict(_ZERO)
    for row in agg.values():
        for k in tot:
            tot[k] += row[k]
    out = {
        "sync_count": int(tot["sync_n"]),
        "sync_ms": round(tot["sync_ms"], 3),
        "compile_count": int(tot["compile_n"]),
        "compile_ms": round(tot["compile_ms"], 3),
        "h2d_bytes": int(tot["h2d_bytes"]),
        "d2h_bytes": int(tot["d2h_bytes"]),
        "spill_ms": round(tot["spill_ms"], 3),
        "sem_wait_ms": round(tot["sem_wait_ms"], 3),
        "events": len(events),
    }
    if tot["stage_n"]:
        # whole-stage evidence (docs/whole_stage.md): fused-stage batch
        # spans + total device dispatches per traced query
        out["stage_count"] = int(tot["stage_n"])
        out["stage_ms"] = round(tot["stage_ms"], 3)
    if counters and counters.get("deviceDispatches"):
        out["device_dispatches"] = int(counters["deviceDispatches"])
    if tot["fault_n"]:
        out["fault_count"] = int(tot["fault_n"])
    # truncation is first-class: a doctor/bench consumer must never have
    # to infer from an absent key that the ring did NOT overflow
    out["trace_truncated"] = bool(dropped)
    if dropped:
        out["dropped_events"] = int(dropped)
    if counters:
        out["counters"] = {k: round(v, 3) for k, v in counters.items()}
    return out


def _fmt_bytes(n: int) -> str:
    if n >= 10 * 1024 * 1024:
        return f"{n / (1 << 20):.0f}M"
    if n >= 10 * 1024:
        return f"{n / (1 << 10):.0f}K"
    return str(int(n))


def attribution_table(phys, events: List[Dict[str, Any]],
                      dropped: int = 0) -> str:
    """The extended ``profile_last_query()`` view: the physical tree's
    inclusive/self wall time (from the PROFILING shim) joined with the
    tracer's per-exec sync/compile/transfer attribution.

    Attribution is keyed by node NAME: two instances of the same exec
    type share one attribution row (printed at the first occurrence, ``.``
    after) — per-instance split would need per-node ids on the exec
    stack, which the ring-buffer events deliberately keep small.
    """
    agg = aggregate_by_exec(events)
    lines = [f"{'exec':<34} {'incl_ms':>8} {'self_ms':>8} {'batches':>7}"
             f" | {'sync_ms':>8} {'n':>4} {'compile_ms':>10}"
             f" {'h2d':>7} {'d2h':>7}"]
    seen: set = set()

    def walk(node, level: int):
        incl = node._prof_ns / 1e6
        self_ms = (node._prof_ns
                   - sum(c._prof_ns for c in node.children)) / 1e6
        name = node.node_name()
        label = "  " * level + name
        row = agg.get(name)
        if row is not None and name not in seen:
            seen.add(name)
            trace_cols = (f" | {row['sync_ms']:>8.2f} {row['sync_n']:>4d}"
                          f" {row['compile_ms']:>10.2f}"
                          f" {_fmt_bytes(row['h2d_bytes']):>7}"
                          f" {_fmt_bytes(row['d2h_bytes']):>7}")
        elif row is not None:
            trace_cols = f" | {'.':>8} {'.':>4} {'.':>10} {'.':>7} {'.':>7}"
        else:
            trace_cols = (f" | {0.0:>8.2f} {0:>4d} {0.0:>10.2f}"
                          f" {'0':>7} {'0':>7}")
        lines.append(f"{label:<34} {incl:>8.2f} {max(self_ms, 0.0):>8.2f}"
                     f" {node._prof_batches:>7d}{trace_cols}")
        for c in node.children:
            walk(c, level + 1)

    walk(phys, 0)
    # spans outside the plan (driver-side result fetch, spill, …)
    for name in sorted(set(agg) - seen):
        row = agg[name]
        lines.append(f"{name:<34} {'-':>8} {'-':>8} {'-':>7}"
                     f" | {row['sync_ms']:>8.2f} {row['sync_n']:>4d}"
                     f" {row['compile_ms']:>10.2f}"
                     f" {_fmt_bytes(row['h2d_bytes']):>7}"
                     f" {_fmt_bytes(row['d2h_bytes']):>7}")
    extra = []
    tot = trace_summary(events, dropped=dropped)
    extra.append(f"sync {tot['sync_count']}x/{tot['sync_ms']}ms, "
                 f"compile {tot['compile_count']}x/{tot['compile_ms']}ms, "
                 f"h2d {_fmt_bytes(tot['h2d_bytes'])}B, "
                 f"d2h {_fmt_bytes(tot['d2h_bytes'])}B, "
                 f"spill {tot['spill_ms']}ms, "
                 f"sem_wait {tot['sem_wait_ms']}ms")
    if dropped:
        extra.append(f"WARNING: ring buffer overflowed, {dropped} oldest "
                     f"events dropped (raise "
                     f"spark.rapids.tpu.trace.bufferEvents)")
    return "\n".join(lines + ["", "totals: " + "; ".join(extra)])
