"""Self-driving perf sentry — live-window detection, evidence ledger,
machine-named follow-ups (ISSUE 18, ROADMAP item 2).

Every on-chip number before this PR depended on a human noticing a live
tunnel window.  The sentry closes the loop as a subsystem:

1. **Probe** — :func:`device_probe` is a cancellable bounded-timeout
   device probe built on the serving tier's QueryContext deadline
   machinery: the probe op runs on a daemon thread while the caller
   polls the context; at the deadline the context is cancelled and the
   probe banks ``outcome=timeout`` with its elapsed time.  No silently
   hung probe threads, ever — every attempt is telemetry
   (``ok | degraded | timeout | refused``).  Failed probes back off
   exponentially from the base interval.
2. **Capture** — on ``ok`` (a non-CPU backend answered) the sentry runs
   the bench shape set (join/sort/window/coalesce + encoded-vs-raw)
   through ``bench.run_shape_set`` — the same ``_run_phase`` watchdog
   machinery the shell bench uses, so one wedged shape never forfeits
   the window.
3. **Diff** — the fresh artifact is ``bench_diff``-ed against the last
   **live**-evidence artifact, auto-located from the ledger (never a
   stale replay; ``no-baseline`` when the ledger holds none).
4. **Ledger** — an append-only JSONL evidence ledger
   (``.bench_capture/ledger.jsonl``, ``srt-ledger/1``): artifact path,
   evidence class, regression verdicts, the doctor's ranked
   next-bottleneck verdict, and a machine-named follow-up with
   quantified lever evidence (doctor.followup — e.g. ``sync-bound:
   readbacks=18, ms_per_readback=6.7, top_exec=...``).  Torn trailing
   lines (a crash mid-append) are skipped on read; appends are single
   O_APPEND writes so the ledger never rewrites history.

Surfaces: the telemetry server's ``/sentry`` route (ledger tail,
last-live-evidence age, probe state, current phase — served by
:func:`status_payload` for whichever sentry is active in the process)
and ``sentry_*`` registry metrics so SLO/health tooling sees evidence
staleness as a first-class signal.

Drive it from ``tools/perf_sentry.py`` (the tunnel watcher is now a thin
wrapper over that CLI); embed it with::

    from spark_rapids_tpu.observability.sentry import PerfSentry
    sentry = PerfSentry.from_conf().start()   # honors sentry.* confs
    ...
    sentry.stop()   # leak-free: thread joined, probe contexts drained
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: evidence-ledger record schema (append-only JSONL)
LEDGER_SCHEMA = "srt-ledger/1"
#: /sentry route payload schema
STATUS_SCHEMA = "srt-sentry/1"
#: probe outcome classes (``wedged`` is bench.py's parent-side class for
#: a probe child that died without a verdict; in-process probes never
#: produce it)
PROBE_OUTCOMES = ("ok", "degraded", "timeout", "refused")
#: sentry lifecycle phases, in rough order of a capture cycle
PHASES = ("idle", "probe", "bench", "diff", "ledger", "stopped")
#: attempts kept in the in-memory probe telemetry window
PROBE_WINDOW = 64
#: exponential-backoff cap, as a multiple of the base probe interval
BACKOFF_MAX_X = 8

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_PROBE_IDS = itertools.count(1)
#: per-process artifact sequence — the timestamp in the artifact name is
#: second-resolution, so back-to-back windows (tests, tight simulated
#: loops) would otherwise collide on one path and the fresh artifact
#: would overwrite the baseline before the diff reads it
_ARTIFACT_IDS = itertools.count(1)

#: the process's active sentry (``/sentry`` route source); installed by
#: PerfSentry.start(), cleared by stop()
_ACTIVE: "Optional[PerfSentry]" = None


def _iso_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def default_ledger_path() -> str:
    return os.path.join(_REPO_ROOT, ".bench_capture", "ledger.jsonl")


# --------------------------------------------------------------------------
# cancellable bounded-timeout device probe
# --------------------------------------------------------------------------

def device_probe(timeout_s: float = 30.0,
                 op: Optional[Callable[[], Any]] = None) -> Dict[str, Any]:
    """One cancellable device probe with a hard deadline.

    The probe op (default: ``float(jnp.sum(jnp.ones(8)))`` + backend
    name) runs on a daemon thread; the caller polls a deadline-bearing
    :class:`~spark_rapids_tpu.serving.lifecycle.QueryContext` — the
    exact cancellation machinery queries use — and on expiry cancels the
    context and returns.  A wedged tunnel orphans one daemon thread
    holding a cancelled context; it never hangs the caller and its
    result (if it ever lands) is discarded.

    Returns ``{"outcome": ok|degraded|timeout|refused,
    "elapsed_ms": float, "platform"?: str, "error"?: str}`` —
    ``degraded`` means the op answered but on the CPU platform (jax
    fell back after a failed device-plugin init: a dead tunnel in its
    fail-fast mode, not a live window).
    """
    from ..serving import lifecycle as lc
    qctx = lc.QueryContext(query_id=next(_PROBE_IDS),
                           session_id="sentry",
                           deadline_ms=max(1, int(timeout_s * 1000)))
    lc.register(qctx)
    box: Dict[str, Any] = {}

    def _default_op() -> str:
        import jax
        import jax.numpy as jnp
        float(jnp.sum(jnp.ones(8)))
        return str(jax.default_backend())

    def run() -> None:
        try:
            platform = (op or _default_op)()
            if not qctx.cancelled:
                box["platform"] = platform
        except BaseException as e:  # noqa: BLE001 - classified below
            box["error"] = f"{type(e).__name__}: {e}"

    t0 = time.perf_counter()
    th = threading.Thread(target=run, daemon=True,
                          name="srt-sentry-probe")
    th.start()
    try:
        while th.is_alive():
            try:
                qctx.check("sentry.probe")
            except lc.QueryCancelled:  # includes QueryDeadlineExceeded
                break
            th.join(lc.POLL_S)
        out: Dict[str, Any] = {
            "elapsed_ms": round((time.perf_counter() - t0) * 1000, 1)}
        if th.is_alive():
            qctx.cancel(f"probe exceeded its {timeout_s:.0f}s budget")
            out["outcome"] = "timeout"
        elif "error" in box:
            out["outcome"] = "refused"
            out["error"] = str(box["error"])[:200]
        else:
            plat = box.get("platform")
            out["outcome"] = ("degraded" if plat in (None, "cpu")
                              else "ok")
            if plat is not None:
                out["platform"] = plat
        return out
    finally:
        lc.unregister(qctx)


def subprocess_probe(timeout_s: float = 30.0,
                     env: Optional[Dict[str, str]] = None
                     ) -> Dict[str, Any]:
    """:func:`device_probe` in a throwaway subprocess — the daemon-mode
    default: a wedged tunnel kills a child, not the long-lived sentry,
    and timed-out probe threads can never pile up in the daemon (the
    tunnel watcher's old 'never probe in-process' rule, kept)."""
    code = ("import json\n"
            "from spark_rapids_tpu.observability.sentry import "
            "device_probe\n"
            f"print('SRT-PROBE ' + json.dumps(device_probe({timeout_s!r})))"
            "\n")
    child_env = dict(env if env is not None else os.environ)
    child_env["PYTHONPATH"] = (_REPO_ROOT + os.pathsep
                               + child_env.get("PYTHONPATH", ""))
    t0 = time.perf_counter()
    try:
        # generous outer budget: the child's own deadline machinery does
        # the real bounding; this only catches a wedged interpreter
        proc = subprocess.run(
            [sys.executable, "-c", code], env=child_env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            timeout=timeout_s + 60.0)
    except subprocess.TimeoutExpired:
        return {"outcome": "timeout",
                "elapsed_ms": round((time.perf_counter() - t0) * 1000, 1),
                "error": "probe subprocess wedged past its budget"}
    for line in reversed(proc.stdout.decode(
            errors="replace").splitlines()):
        if line.startswith("SRT-PROBE "):
            try:
                return json.loads(line[len("SRT-PROBE "):])
            except ValueError:
                break
    return {"outcome": "refused",
            "elapsed_ms": round((time.perf_counter() - t0) * 1000, 1),
            "error": ("probe subprocess exited "
                      f"{proc.returncode}: "
                      + proc.stderr.decode(errors='replace')[-160:])}


# --------------------------------------------------------------------------
# append-only evidence ledger (srt-ledger/1)
# --------------------------------------------------------------------------

class EvidenceLedger:
    """Append-only JSONL evidence ledger.  One record per captured
    window; records are single ``O_APPEND`` line writes (fsync'd), reads
    skip torn or foreign lines — a crash mid-append can tear at most the
    final line and never loses banked history."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_ledger_path()

    def append(self, record: Dict[str, Any]) -> Dict[str, Any]:
        rec = dict(record)
        rec.setdefault("schema", LEDGER_SCHEMA)
        rec.setdefault("at", _iso_now())
        rec.setdefault("unix", round(time.time(), 3))
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        line = json.dumps(rec, default=str) + "\n"
        with open(self.path, "a") as fh:
            fh.write(line)
            fh.flush()
            os.fsync(fh.fileno())
        return rec

    def entries(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            fh = open(self.path)
        except OSError:
            return out
        with fh:
            for line in fh:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line (crash mid-append)
                if isinstance(rec, dict) \
                        and rec.get("schema") == LEDGER_SCHEMA:
                    out.append(rec)
        return out

    def tail(self, n: int = 10) -> List[Dict[str, Any]]:
        return self.entries()[-max(0, n):]

    def last_live(self) -> Optional[Dict[str, Any]]:
        """Newest ``evidence: live`` entry — THE comparison baseline;
        stale replays never qualify no matter how fresh their append."""
        for rec in reversed(self.entries()):
            if rec.get("evidence") == "live":
                return rec
        return None

    def last_live_age_s(self,
                        now: Optional[float] = None) -> Optional[float]:
        rec = self.last_live()
        if rec is None:
            return None
        return max(0.0, (now if now is not None else time.time())
                   - float(rec.get("unix", 0.0)))


# --------------------------------------------------------------------------
# default bench / diff plumbing (lazy, repo-checkout based)
# --------------------------------------------------------------------------

def _load_tool(name: str):
    """Import a repo tools/ or top-level module by file path (the repo
    is not pip-installed; bench.py and tools/*.py live beside the
    package).  Returns None when the file is absent (wheel install)."""
    import importlib.util
    for rel in (name + ".py", os.path.join("tools", name + ".py")):
        path = os.path.join(_REPO_ROOT, rel)
        if os.path.exists(path):
            spec = importlib.util.spec_from_file_location(
                f"srt_sentry_{name}", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod
    return None


def run_shape_set_inprocess(shapes, rows: int, budget_s: float,
                            artifact_path: Optional[str] = None,
                            evidence: Optional[str] = None
                            ) -> Dict[str, Any]:
    """bench.run_shape_set in this process (imports jax here — tests
    and CI simulated-window runs; the daemon uses the subprocess
    variant)."""
    bench = _load_tool("bench")
    if bench is None:
        return {"error": "bench.py not found beside the package"}
    return bench.run_shape_set(shapes=shapes, rows=rows,
                               budget_s=budget_s,
                               artifact_path=artifact_path,
                               evidence=evidence)


def subprocess_shape_set(shapes, rows: int, budget_s: float,
                         artifact_path: Optional[str] = None,
                         evidence: Optional[str] = None,
                         env: Optional[Dict[str, str]] = None
                         ) -> Dict[str, Any]:
    """bench.run_shape_set in a subprocess — the daemon-mode default,
    keeping the long-lived sentry jax-free (bench.py's own parent rule).
    On a timeout the partial artifact banked shape-by-shape at
    ``artifact_path`` is recovered, so a wedged shape set still yields
    whatever finished."""
    code = (
        "import json, sys\n"
        f"sys.path.insert(0, {_REPO_ROOT!r})\n"
        "import bench\n"
        f"r = bench.run_shape_set(shapes={list(shapes)!r}, "
        f"rows={int(rows)!r}, budget_s={float(budget_s)!r}, "
        f"artifact_path={artifact_path!r}, evidence={evidence!r})\n"
        "print('SRT-ARTIFACT ' + json.dumps(r, default=str))\n")
    child_env = dict(env if env is not None else os.environ)
    child_env["PYTHONPATH"] = (_REPO_ROOT + os.pathsep
                               + child_env.get("PYTHONPATH", ""))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=child_env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            timeout=budget_s + 120.0)
        for line in reversed(proc.stdout.decode(
                errors="replace").splitlines()):
            if line.startswith("SRT-ARTIFACT "):
                return json.loads(line[len("SRT-ARTIFACT "):])
        err = ("shape-set subprocess exited "
               f"{proc.returncode}: "
               + proc.stderr.decode(errors='replace')[-200:])
    except subprocess.TimeoutExpired:
        err = "shape-set subprocess exceeded its budget"
    except ValueError as e:
        err = f"unparseable shape-set artifact line: {e}"
    # recover the shape-by-shape partial artifact, if any
    if artifact_path:
        try:
            with open(artifact_path) as fh:
                rec = json.loads(fh.read())
            rec["note"] = ((rec.get("note", "") + "; ").lstrip("; ")
                           + "recovered partial artifact: " + err)
            return rec
        except (OSError, ValueError):
            pass
    return {"error": err}


# --------------------------------------------------------------------------
# the sentry daemon
# --------------------------------------------------------------------------

class PerfSentry:
    """The autonomous probe → bench → diff → ledger loop.

    Every collaborator is injectable (``probe``, ``bench``, ``ledger``)
    so tests and the CI simulated-window mode drive the full pipeline
    with a fake probe and a tiny bench.  ``start()`` runs the loop on a
    daemon thread named ``srt-sentry``; ``stop()`` is leak-free by
    contract (thread joined, probe QueryContexts unregistered —
    tools/leak_sentinel.py --sentry asserts both).
    """

    def __init__(self,
                 probe: Optional[Callable[[], Dict[str, Any]]] = None,
                 bench: Optional[Callable[[List[str]],
                                          Dict[str, Any]]] = None,
                 ledger: Any = None,
                 shapes=("join", "sort", "window", "coalesce",
                         "encoded"),
                 rows: int = 4_000_000,
                 interval_s: float = 480.0,
                 probe_timeout_s: float = 30.0,
                 bench_budget_s: float = 1800.0,
                 diff_threshold: float = 0.10,
                 capture_dir: Optional[str] = None,
                 entry_extra: Optional[Dict[str, Any]] = None):
        self._probe = probe
        self._bench = bench
        self.ledger = (ledger if isinstance(ledger, EvidenceLedger)
                       else EvidenceLedger(ledger))
        self.shapes = [str(s) for s in shapes]
        self.rows = int(rows)
        self.interval_s = float(interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.bench_budget_s = float(bench_budget_s)
        self.diff_threshold = float(diff_threshold)
        self.capture_dir = capture_dir or os.path.dirname(
            os.path.abspath(self.ledger.path))
        self.entry_extra = dict(entry_extra or {})
        self.phase = "idle"
        self.backoff_s = self.interval_s
        self.windows = 0
        self.probe_attempts: List[Dict[str, Any]] = []
        self.last_entry: Optional[Dict[str, Any]] = None
        self.last_error: Optional[str] = None
        self._consecutive_failures = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- conf plumbing ----------------------------------------------------
    @classmethod
    def from_conf(cls, conf=None, **overrides) -> "PerfSentry":
        """Build from the ``spark.rapids.tpu.sentry.*`` confs (kwargs
        win over conf values)."""
        from ..config import (RapidsConf, SENTRY_LEDGER_PATH,
                              SENTRY_PROBE_INTERVAL_MS,
                              SENTRY_PROBE_TIMEOUT_MS, SENTRY_SHAPES)
        conf = conf or RapidsConf.get_global()
        kw: Dict[str, Any] = {
            "interval_s": int(conf.get(SENTRY_PROBE_INTERVAL_MS)) / 1000,
            "probe_timeout_s":
                int(conf.get(SENTRY_PROBE_TIMEOUT_MS)) / 1000,
            "shapes": [s.strip() for s in
                       str(conf.get(SENTRY_SHAPES)).split(",")
                       if s.strip()],
            "ledger": str(conf.get(SENTRY_LEDGER_PATH) or "") or None,
        }
        kw.update(overrides)
        return cls(**kw)

    @staticmethod
    def enabled(conf=None) -> bool:
        from ..config import RapidsConf, SENTRY_ENABLED
        conf = conf or RapidsConf.get_global()
        return bool(conf.get(SENTRY_ENABLED))

    # --- metrics ----------------------------------------------------------
    def _reg(self):
        from . import metrics as OM
        return OM.get_registry()

    def _metric(self, kind: str, name: str, value: float = 1.0,
                **labels: Any) -> None:
        # the sentry IS observability infrastructure: it records into
        # the registry unconditionally (tiny bounded cardinality), not
        # behind the METRICS kill switch
        try:
            reg = self._reg()
            getattr(reg, kind)(name, value, **labels)
        except Exception:  # noqa: BLE001 - metrics never take it down
            pass

    def _set_phase(self, phase: str) -> None:
        self.phase = phase
        self._metric("set_gauge", "sentry_phase_code",
                     float(PHASES.index(phase) if phase in PHASES
                           else -1))

    # --- one cycle --------------------------------------------------------
    def _probe_once(self) -> Dict[str, Any]:
        self._set_phase("probe")
        fn = self._probe or (
            lambda: device_probe(self.probe_timeout_s))
        try:
            att = dict(fn())
        except BaseException as e:  # noqa: BLE001 - probes never raise out
            att = {"outcome": "refused",
                   "error": f"{type(e).__name__}: {e}"}
        att.setdefault("outcome", "refused")
        att.setdefault("at", _iso_now())
        with self._lock:
            self.probe_attempts.append(att)
            del self.probe_attempts[:-PROBE_WINDOW]
        self._metric("inc", "sentry_probe_attempts_total",
                     outcome=str(att["outcome"]))
        if "elapsed_ms" in att:
            self._metric("observe", "sentry_probe_ms",
                         float(att["elapsed_ms"]))
        age = self.ledger.last_live_age_s()
        if age is not None:
            self._metric("set_gauge", "sentry_last_live_evidence_age_s",
                         float(age))
        return att

    def run_once(self) -> Optional[Dict[str, Any]]:
        """One probe tick; on a live window, the full capture cycle.
        Returns the appended ledger entry, or None when no window
        opened.  Exceptions are banked (``last_error`` + metrics), never
        raised — the loop must survive anything."""
        try:
            att = self._probe_once()
            if att.get("outcome") != "ok":
                self._consecutive_failures += 1
                self.backoff_s = min(
                    self.interval_s * BACKOFF_MAX_X,
                    self.interval_s
                    * (2 ** min(self._consecutive_failures - 1, 10)))
                self._set_phase("idle")
                return None
            self._consecutive_failures = 0
            self.backoff_s = self.interval_s
            self.windows += 1
            self._metric("inc", "sentry_windows_total")
            entry = self._capture_window(att)
            self._metric("inc", "sentry_runs_total", result="ok")
            return entry
        except BaseException as e:  # noqa: BLE001 - loop must survive
            self.last_error = f"{type(e).__name__}: {e}"
            self._metric("inc", "sentry_runs_total", result="error")
            return None
        finally:
            self._set_phase("idle")

    def _capture_window(self,
                        probe_att: Dict[str, Any]) -> Dict[str, Any]:
        stamp = time.strftime("%Y-%m-%dT%H-%M-%SZ", time.gmtime())
        artifact_path = os.path.join(
            self.capture_dir,
            f"sentry_{stamp}_{os.getpid()}_{next(_ARTIFACT_IDS)}.json")
        self._set_phase("bench")
        bench_fn = self._bench or (
            lambda shapes: subprocess_shape_set(
                shapes, self.rows, self.bench_budget_s,
                artifact_path=artifact_path))
        artifact = dict(bench_fn(self.shapes) or {})
        # persist the artifact beside the ledger whatever produced it
        try:
            os.makedirs(self.capture_dir, exist_ok=True)
            tmp = f"{artifact_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                fh.write(json.dumps(artifact, default=str) + "\n")
            os.replace(tmp, artifact_path)
        except OSError:
            pass

        self._set_phase("diff")
        diff_verdict = self._diff_against_baseline(artifact)

        self._set_phase("ledger")
        from . import doctor as OD
        diag = OD.diagnose_artifact(artifact)
        entry = self.ledger.append(dict(self.entry_extra, **{
            "artifact": artifact_path,
            "evidence": str(artifact.get("evidence")
                            or ("cpu-fallback"
                                if artifact.get("platform")
                                in (None, "cpu") else "live")),
            "platform": artifact.get("platform"),
            "probe": {k: probe_att.get(k)
                      for k in ("outcome", "elapsed_ms", "platform",
                                "at") if probe_att.get(k) is not None},
            "shapes": self.shapes,
            "diff": diff_verdict,
            "doctor": OD.compact(diag),
            "followup": OD.followup(diag),
        }))
        with self._lock:
            self.last_entry = entry
        self._metric("inc", "sentry_ledger_entries_total")
        age = self.ledger.last_live_age_s()
        if age is not None:
            self._metric("set_gauge", "sentry_last_live_evidence_age_s",
                         float(age))
        return entry

    def _diff_against_baseline(self,
                               artifact: Dict[str, Any]
                               ) -> Dict[str, Any]:
        """bench_diff the fresh artifact against the newest live-evidence
        ledger entry (never a stale replay — tools/bench_diff.py
        --ledger shares this resolution)."""
        base = self.ledger.last_live()
        if base is None or not base.get("artifact"):
            return {"verdict": "no-baseline", "baseline": None}
        bd = _load_tool("bench_diff")
        if bd is None:
            return {"verdict": "unavailable",
                    "baseline": base.get("artifact"),
                    "note": "tools/bench_diff.py not found"}
        try:
            a = bd.comparable_metrics(bd.load_artifact(base["artifact"]))
            b = bd.comparable_metrics(artifact)
            rows = bd.diff(a, b, self.diff_threshold)
        except (OSError, ValueError) as e:
            return {"verdict": "error",
                    "baseline": base.get("artifact"),
                    "note": f"{type(e).__name__}: {e}"}
        regressed = [r for r in rows if r["verdict"] == "REGRESSED"]
        improved = [r for r in rows if r["verdict"] == "IMPROVED"]
        out = {
            "verdict": "regressed" if regressed else "ok",
            "baseline": base["artifact"],
            "baseline_at": base.get("at"),
            "threshold": self.diff_threshold,
            "regressed": len(regressed),
            "improved": len(improved),
            "compared": len(rows),
            "top_regressions": [
                {"metric": r["metric"], "a": r["a"], "b": r["b"],
                 "ratio": r.get("ratio")}
                for r in sorted(
                    regressed,
                    key=lambda r: (r.get("ratio") or 0.0))[:5]],
        }
        self._metric("set_gauge", "sentry_last_diff_regressions",
                     float(len(regressed)))
        return out

    # --- daemon lifecycle -------------------------------------------------
    def start(self) -> "PerfSentry":
        """Run the probe loop on a daemon thread (idempotent) and
        install this sentry as the process's ``/sentry`` source."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="srt-sentry", daemon=True)
            self._thread.start()
        set_active(self)
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            self._stop.wait(max(0.05, self.backoff_s))
        self._set_phase("stopped")

    def stop(self, timeout: float = 10.0) -> None:
        """Leak-free shutdown: signal the loop, join the thread, drop
        the active-sentry registration (idempotent)."""
        self._stop.set()
        th = self._thread
        self._thread = None
        if th is not None:
            th.join(timeout)
        if _ACTIVE is self:
            set_active(None)
        if self.phase != "stopped":
            self._set_phase("stopped")

    @property
    def running(self) -> bool:
        th = self._thread
        return th is not None and th.is_alive()

    # --- /sentry route payload --------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            attempts = list(self.probe_attempts)
            last_entry = self.last_entry
        outcomes: Dict[str, int] = {}
        for a in attempts:
            k = str(a.get("outcome"))
            outcomes[k] = outcomes.get(k, 0) + 1
        entries = self.ledger.entries()
        return {
            "schema": STATUS_SCHEMA,
            "phase": self.phase,
            "running": self.running,
            "windows": self.windows,
            "probe": {
                "attempts": len(attempts),
                "outcomes": outcomes,
                "last": attempts[-1] if attempts else None,
                "interval_s": self.interval_s,
                "timeout_s": self.probe_timeout_s,
                "next_delay_s": self.backoff_s,
            },
            "ledger": {
                "path": self.ledger.path,
                "entries": len(entries),
                "tail": entries[-5:],
            },
            "last_live_age_s": self.ledger.last_live_age_s(),
            "last_entry_at": (last_entry or {}).get("at"),
            "last_error": self.last_error,
            "shapes": self.shapes,
        }


# --------------------------------------------------------------------------
# process-global active sentry (the /sentry telemetry route source)
# --------------------------------------------------------------------------

def set_active(sentry: Optional[PerfSentry]) -> None:
    global _ACTIVE
    _ACTIVE = sentry


def get_active() -> Optional[PerfSentry]:
    return _ACTIVE


def status_payload() -> Dict[str, Any]:
    """What the telemetry server's ``/sentry`` route serves: the active
    sentry's status, or a minimal 'none' payload that still reports the
    default ledger so staleness is visible from any process."""
    s = _ACTIVE
    if s is not None:
        return s.status()
    led = EvidenceLedger()
    return {
        "schema": STATUS_SCHEMA,
        "phase": "none",
        "running": False,
        "note": "no active sentry in this process",
        "ledger": {"path": led.path, "entries": len(led.entries()),
                   "tail": led.tail(3)},
        "last_live_age_s": led.last_live_age_s(),
    }


def maybe_start_from_conf(conf=None, **overrides) -> Optional[PerfSentry]:
    """Start a conf-configured sentry iff the master switch is on
    (``spark.rapids.tpu.sentry.enabled``); returns None otherwise."""
    if not PerfSentry.enabled(conf):
        return None
    return PerfSentry.from_conf(conf, **overrides).start()
