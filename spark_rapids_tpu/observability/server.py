"""Embedded telemetry plane: a kill-switched stdlib HTTP server.

One daemon-threaded :class:`ThreadingHTTPServer` bound to 127.0.0.1
(``spark.rapids.tpu.telemetry.{enabled,port}``) exposes the process's
existing observability surfaces to scrapers and load balancers without
adding any dependency:

==============  ===========================================================
``/metrics``    Prometheus exposition text — the metrics registry's
                ``prometheus_text()`` (``text/plain; version=0.0.4``)
``/healthz``    JSON liveness/readiness: engine degraded + quarantine
                state (serving/engine.py), admission queue depth,
                device-semaphore saturation.  **HTTP 503** while the
                engine is degraded, 200 otherwise — a load balancer can
                drain a degraded engine from rotation on status alone.
``/queries``    the flight-recorder ring (observability/history.py) as a
                JSON array, newest last
``/doctor``     last ranked doctor verdicts (per-query and per-tenant),
                including the ``slo-burn`` verdict when a tenant burns
``/slo``        per-tenant multi-window SLO burn rates
                (observability/slo.py)
``/sentry``     perf-sentry status (observability/sentry.py,
                ``srt-sentry/1``): current phase, probe telemetry,
                evidence-ledger tail and last-live-evidence age.  By
                default served from the process's active sentry (a
                'none' payload that still reports ledger staleness when
                no sentry runs here); owners may inject their own
                source.
==============  ===========================================================

Ownership and lifecycle: the ServingEngine starts one server in
``__init__`` and closes it in ``close()``; a classic (non-serving)
TpuSession does the same when the conf enables it.  ``close()`` is
leak-free by contract — it shuts the serve loop down, closes the
listening socket and joins the serve thread, which tools/leak_sentinel.py
asserts (no lingering thread, the port rebinds).

The server holds no state of its own: every route is a callable injected
by the owner, evaluated per request under a broad exception guard (a
failing source yields HTTP 500 with the error, never a dead serve
thread).  With the kill switch off (default) nothing binds, nothing
starts, and no behavior changes anywhere — asserted bit-identical by
tests/test_telemetry.py.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple


class TelemetryServer:
    """Serve the injected observability sources over HTTP until closed.

    ``healthz`` returns ``(healthy: bool, payload: dict)`` — unhealthy
    maps to HTTP 503.  ``metrics_text`` returns exposition text; the
    remaining sources return JSON-serializable objects.
    """

    def __init__(self,
                 metrics_text: Callable[[], str],
                 healthz: Callable[[], Tuple[bool, Dict[str, Any]]],
                 queries: Callable[[], Any],
                 doctor: Callable[[], Any],
                 slo: Callable[[], Any],
                 host: str = "127.0.0.1", port: int = 0,
                 sentry: Optional[Callable[[], Any]] = None):
        self._routes: Dict[str, Callable[[], Any]] = {
            "/queries": queries, "/doctor": doctor, "/slo": slo,
            "/sentry": sentry or _default_sentry_source}
        self._metrics_text = metrics_text
        self._healthz = healthz
        self._httpd: Optional[ThreadingHTTPServer] = ThreadingHTTPServer(
            (host, int(port)), self._make_handler())
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"srt-telemetry-{self.port}", daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        """Stop serving, release the port and join the serve thread
        (idempotent)."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        self._thread.join(timeout=5.0)

    # --- request handling -------------------------------------------------
    def _make_handler(self):
        server = self

        class _Handler(BaseHTTPRequestHandler):
            # request threads are short-lived daemons; never let a slow
            # or dead client pin one forever
            timeout = 10.0

            def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = server._metrics_text().encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                        status = 200
                    elif path == "/healthz":
                        healthy, payload = server._healthz()
                        body = _to_json(payload)
                        ctype = "application/json"
                        status = 200 if healthy else 503
                    elif path in server._routes:
                        body = _to_json(server._routes[path]())
                        ctype = "application/json"
                        status = 200
                    else:
                        body = _to_json(
                            {"error": f"no route {path!r}",
                             "routes": ["/metrics", "/healthz",
                                        "/queries", "/doctor", "/slo",
                                        "/sentry"]})
                        ctype = "application/json"
                        status = 404
                except Exception as e:  # noqa: BLE001 — route isolation
                    body = _to_json(
                        {"error": f"{type(e).__name__}: {e}"})
                    ctype = "application/json"
                    status = 500
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-reply

            def log_message(self, fmt, *log_args):
                pass  # no per-request stderr chatter

        return _Handler


def _default_sentry_source() -> Any:
    # lazy: the sentry module is only imported when /sentry is hit
    from . import sentry as _sentry
    return _sentry.status_payload()


def _to_json(obj: Any) -> bytes:
    return json.dumps(obj, default=str).encode()
