"""Per-tenant SLO objectives and multi-window burn-rate tracking.

Objectives are declared in conf (``spark.rapids.tpu.slo.*``): a latency
objective (queries slower than ``latencyObjectiveMs`` are *slow* events
against a ``1 - latencyTarget`` error budget) and an availability
objective (non-ok ``queries_total`` outcomes against a
``1 - availabilityTarget`` budget).  The tracker is fed entirely from
the metrics registry's per-tenant series — the ``query_ms`` histograms
and ``queries_total{status}`` counters the serving session already
emits — so it adds no new instrumentation to the query path.

The registry is lifetime-cumulative, so the tracker owns the windowing:
every :meth:`SloTracker.report` appends a timestamped cumulative
snapshot to a bounded ring and computes, for each conf window (shortest
first, e.g. 5m/1h), the delta-rate of bad events over that window
divided by the error budget — the classic *burn rate*.  Burn >= 1 in
the shortest window means the tenant is consuming its budget faster
than allotted: the tenant is **burning**, surfaces in ``/slo``, and
yields a ranked ``slo-burn`` doctor verdict naming the tenant and its
dominant bottleneck (from the flight recorder's per-tenant diagnosis).

Hook point: the ServingEngine wires :meth:`SloTracker.admission_hint`
onto ``AdmissionController.slo_hook`` — the admission controller does
not consult it yet, but a later PR can shed or deprioritize a burning
tenant at the acquire site without new plumbing.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import metrics as _metrics

SCHEMA = "srt-slo/1"

#: doctor category emitted for a burning tenant (registered in
#: observability/doctor.py VERDICTS and tools/check_trace.py)
SLO_BURN = "slo-burn"


class SloObjectives:
    """Declared objectives, resolved from conf once at tracker build."""

    __slots__ = ("latency_ms", "latency_target", "error_target",
                 "windows_s")

    def __init__(self, latency_ms: float = 0.0,
                 latency_target: float = 0.99,
                 error_target: float = 0.999,
                 windows_s: Optional[List[float]] = None):
        self.latency_ms = float(latency_ms)
        self.latency_target = min(float(latency_target), 1.0 - 1e-9)
        self.error_target = min(float(error_target), 1.0 - 1e-9)
        self.windows_s = sorted(windows_s or [300.0, 3600.0])

    @classmethod
    def from_conf(cls, conf) -> "SloObjectives":
        from ..config import (SLO_ERROR_TARGET, SLO_LATENCY_MS,
                              SLO_LATENCY_TARGET, SLO_WINDOWS_S)
        windows = [float(w) for w in
                   str(conf.get(SLO_WINDOWS_S)).split(",") if w.strip()]
        return cls(latency_ms=float(conf.get(SLO_LATENCY_MS)),
                   latency_target=float(conf.get(SLO_LATENCY_TARGET)),
                   error_target=float(conf.get(SLO_ERROR_TARGET)),
                   windows_s=windows or [300.0, 3600.0])

    def as_dict(self) -> Dict[str, Any]:
        return {"latencyObjectiveMs": self.latency_ms,
                "latencyTarget": self.latency_target,
                "availabilityTarget": self.error_target,
                "windowsS": list(self.windows_s)}


def _count_under(hist, bound_ms: float) -> float:
    """Observations <= bound in a registry log2 histogram, linearly
    interpolated within the straddling bucket (same estimator as
    ``_Histogram.quantile``, inverted)."""
    bounds = _metrics.BUCKET_BOUNDS
    cum = 0.0
    lo = 0.0
    for i, n in enumerate(hist.buckets):
        hi = bounds[i]
        if hi <= bound_ms:
            cum += n
        elif lo < bound_ms:
            cum += n * (bound_ms - lo) / (hi - lo)
        else:
            break
        lo = hi
    return cum


class SloTracker:
    """Bounded ring of cumulative per-tenant samples + burn computation.

    Thread-safe; reads the registry under its lock, never blocks the
    query path (the query path never calls in here — only scrapes,
    doctor runs and the admission hook do).
    """

    #: plenty for days of scrape-driven sampling; entries older than the
    #: longest window are pruned anyway
    _MAX_SAMPLES = 4096

    def __init__(self, objectives: SloObjectives,
                 clock: Callable[[], float] = time.monotonic):
        self.objectives = objectives
        self._clock = clock
        self._lock = threading.Lock()
        # seed with an empty baseline at build time: the first report is
        # a delta from "engine start", not an undefined window
        self._samples: deque = deque([(clock(), {})],
                                     maxlen=self._MAX_SAMPLES)
        self._last_report: Optional[Dict[str, Any]] = None

    # --- sampling ---------------------------------------------------------
    def _snapshot(self, reg) -> Dict[str, Dict[str, float]]:
        """Cumulative per-tenant counts from the registry (summed across
        the query/session label dimensions)."""
        per: Dict[str, Dict[str, float]] = {}

        def row(tenant: str) -> Dict[str, float]:
            return per.setdefault(tenant, {
                "total": 0.0, "errors": 0.0,
                "lat_count": 0.0, "lat_slow": 0.0, "lat_sum_ms": 0.0})

        latency_ms = self.objectives.latency_ms
        with reg._lock:
            for (name, labels), v in reg._counters.items():
                if name != "queries_total":
                    continue
                lab = dict(labels)
                d = row(lab.get("tenant", ""))
                d["total"] += v
                if lab.get("status", "ok") != "ok":
                    d["errors"] += v
            for (name, labels), h in reg._hists.items():
                if name != "query_ms":
                    continue
                lab = dict(labels)
                d = row(lab.get("tenant", ""))
                d["lat_count"] += h.count
                d["lat_sum_ms"] += h.sum
                if latency_ms > 0:
                    d["lat_slow"] += h.count - _count_under(h, latency_ms)
        return per

    # --- burn computation -------------------------------------------------
    def report(self, registry=None, now: Optional[float] = None
               ) -> Dict[str, Any]:
        """Sample the registry and return the burn report (srt-slo/1)."""
        reg = registry or _metrics.get_registry()
        t = self._clock() if now is None else now
        cur = self._snapshot(reg)
        with self._lock:
            self._samples.append((t, cur))
            horizon = t - max(self.objectives.windows_s) * 2
            while len(self._samples) > 1 and self._samples[0][0] < horizon:
                self._samples.popleft()
            samples = list(self._samples)
        tenants: Dict[str, Any] = {}
        burning: List[str] = []
        budget_err = 1.0 - self.objectives.error_target
        budget_lat = 1.0 - self.objectives.latency_target
        for tenant, c in sorted(cur.items()):
            windows: Dict[str, Any] = {}
            max_burn = 0.0
            for w in self.objectives.windows_s:
                # newest sample at or before the window's left edge; the
                # seed baseline bounds the delta when history is short
                old_t, old = samples[0]
                for st, snap in samples:
                    if st <= t - w:
                        old_t, old = st, snap
                    else:
                        break
                o = old.get(tenant, {})
                d_total = c["total"] - o.get("total", 0.0)
                d_err = c["errors"] - o.get("errors", 0.0)
                d_lat = c["lat_count"] - o.get("lat_count", 0.0)
                d_slow = c["lat_slow"] - o.get("lat_slow", 0.0)
                err_rate = d_err / d_total if d_total > 0 else 0.0
                slow_rate = d_slow / d_lat if d_lat > 0 else 0.0
                err_burn = err_rate / budget_err
                lat_burn = (slow_rate / budget_lat
                            if self.objectives.latency_ms > 0 else 0.0)
                max_burn = max(max_burn, err_burn, lat_burn)
                windows[f"{int(w)}s"] = {
                    "queries": round(d_total, 3),
                    "error_rate": round(err_rate, 6),
                    "error_burn": round(err_burn, 3),
                    "slow_rate": round(slow_rate, 6),
                    "latency_burn": round(lat_burn, 3),
                    "covered_s": round(t - old_t, 3),
                }
            # burning = budget consumed faster than allotted in the
            # SHORTEST window (the fast-burn page condition)
            shortest = windows[f"{int(self.objectives.windows_s[0])}s"]
            is_burning = max(shortest["error_burn"],
                             shortest["latency_burn"]) >= 1.0
            tenants[tenant] = {"windows": windows,
                               "max_burn": round(max_burn, 3),
                               "burning": is_burning,
                               "bad_events": round(
                                   c["errors"] + c["lat_slow"], 3),
                               "lat_sum_ms": round(c["lat_sum_ms"], 3)}
            if is_burning:
                burning.append(tenant)
        out = {"schema": SCHEMA,
               "objectives": self.objectives.as_dict(),
               "tenants": tenants,
               "burning": burning}
        with self._lock:
            self._last_report = out
        return out

    # --- consumers --------------------------------------------------------
    def admission_hint(self, tenant: str) -> Dict[str, Any]:
        """Hook point for the admission controller (wired onto
        ``AdmissionController.slo_hook``): cheap read of the last burn
        report for one tenant — no registry scan on the acquire path."""
        with self._lock:
            rep = self._last_report
        info = (rep or {}).get("tenants", {}).get(tenant)
        if not info:
            return {"burning": False, "max_burn": 0.0}
        return {"burning": info["burning"], "max_burn": info["max_burn"]}

    def doctor_verdict(self, registry=None,
                       tenant_diagnoses: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """A ranked srt-doctor/1 verdict over the burn report: one
        ``slo-burn`` entry per burning tenant (sorted by the query
        milliseconds spent violating the objective), naming the tenant
        and its dominant bottleneck from the per-tenant diagnosis."""
        rep = self.report(registry)
        ranked = []
        for tenant in rep["burning"]:
            info = rep["tenants"][tenant]
            shortest = next(iter(info["windows"].values()))
            bad = info["bad_events"]
            total = max(1.0, shortest["queries"])
            bad_frac = min(1.0, max(shortest["error_rate"],
                                    shortest["slow_rate"]))
            dominant = ""
            diag = (tenant_diagnoses or {}).get(tenant) or {}
            dv = (diag.get("diagnosis") or {}).get("verdict") \
                or diag.get("verdict")
            if dv:
                dominant = f"; dominant bottleneck: {dv}"
            ranked.append({
                "category": SLO_BURN,
                # query milliseconds spent in violation (approx: tenant
                # query time weighted by the bad fraction)
                "ms": round(info["lat_sum_ms"] * bad_frac, 3),
                "count": int(bad),
                "share": round(bad_frac, 4),
                "evidence": (
                    f"tenant {tenant!r} burning error budget at "
                    f"{info['max_burn']}x (shortest window: "
                    f"error_burn {shortest['error_burn']}, latency_burn "
                    f"{shortest['latency_burn']}, {shortest['queries']} "
                    f"queries){dominant}"),
                "tenant": tenant,
            })
        ranked.sort(key=lambda e: -e["ms"])
        return {"schema": "srt-doctor/1",
                "verdict": ranked[0]["category"] if ranked
                else "no-bottleneck",
                "ranked": ranked,
                "trace_truncated": False,
                "caveats": [] if ranked else
                ["no tenant is burning its SLO budget"],
                "slo": {"burning": rep["burning"],
                        "objectives": rep["objectives"]}}


# --------------------------------------------------------------------------
# module singleton (one engine per process is the supported serving
# configuration — docs/serving.md)
# --------------------------------------------------------------------------

_TRACKER: Optional[SloTracker] = None


def configure(conf) -> SloTracker:
    """(Re)build the process tracker from conf; returns it."""
    global _TRACKER
    _TRACKER = SloTracker(SloObjectives.from_conf(conf))
    return _TRACKER


def get_tracker() -> Optional[SloTracker]:
    return _TRACKER
