"""Structured query-timeline tracer.

One process-wide :class:`QueryTracer` holds a thread-safe bounded ring
buffer of events.  Instrumented chokepoints (columnar/convert.py,
sql/physical/transitions.py, kernel_cache.py, memory/spill.py,
memory/semaphore.py, shuffle/serializer.py, the join sizing readbacks)
guard every emission on the module-level ``TRACING`` flag — the same
single-dict pattern as ``PROFILING`` in sql/physical/base.py — so a
disabled tracer costs one dict lookup per chokepoint, nothing else.

Event categories:

=================  =========================================================
``op``             exec-node batch production (and join pipeline stages)
``kernel_compile`` a cached_jit kernel's trace+compile (first call / new
                   input signature)
``sync``           blocking scalar readbacks (join sizing, speculation)
``h2d``            host -> device uploads (arrow decode, transitions)
``d2h``            device -> host fetches (bulk/prepacked device_get)
``spill``          spill-catalog tier movement
``shuffle``        exchange materialization + frame (de)serialization
``sem_wait``       device-semaphore acquisition waits
``fault``          chaos fault injections, shuffle fetch retries, peer
                   blacklisting, lost-block recompute (robustness/)
``queue``          async-prefetch queue waits (consumer blocked on the
                   bounded prefetch queue; sql/physical/async_exec.py)
``encode``         encoded-column lifecycle: scan-side dictionary encode
                   and decline-site materializations (columnar/encoded.py)
``stage``          whole-stage program execution: one span per fused-stage
                   batch (map-chain program call or terminal-stage batch
                   production; sql/physical/fusion.py)
=================  =========================================================

Spans attribute to the *owning exec node* via a thread-local exec stack:
the profiled ``execute`` wrapper (base.py) pushes each node's name around
its own batch production, so the innermost executing exec is always on
top — a ``d2h`` fetch fired while ``DeviceToHost`` pulls a batch lands on
``DeviceToHost`` even though outer nodes are also mid-pull.  The stack
composes with :meth:`TaskContext.as_current` nesting (exchange map-side
tasks): pushes/pops are strictly scoped, so a nested task restores the
outer attribution on exit.

Concurrency model: the tracer is PROCESS-wide, like the reference's
per-executor GpuMetric sinks.  The engine runs a single driver per
process (sessions execute queries serially on the calling thread; only
the shuffle/IO pools fan out, and those belong to the one running query),
so per-query reset-and-snapshot from the session is sound.  Two sessions
collecting *concurrently* from different threads would interleave events
— that configuration is unsupported for tracing, documented in
docs/observability.md.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

#: master switch — flipped per query by the session (restored in a
#: ``finally``, so an exception mid-query cannot leak tracing into the
#: next session's query).  Near-zero overhead when off.
TRACING = {"on": False}

#: known span categories (exported traces may add more; the checker and
#: the report treat unknown categories as opaque)
CATEGORIES = ("op", "kernel_compile", "sync", "h2d", "d2h", "spill",
              "shuffle", "sem_wait", "fault", "queue", "encode", "stage",
              "admission", "cancel", "fatal")

#: default ring capacity (spark.rapids.tpu.trace.bufferEvents)
DEFAULT_CAPACITY = 65536


# --------------------------------------------------------------------------
# exec-node attribution stack (thread-local)
# --------------------------------------------------------------------------

_tls = threading.local()


def _stack() -> List[str]:
    s = getattr(_tls, "exec_stack", None)
    if s is None:
        s = _tls.exec_stack = []
    return s


def push_exec(name: str) -> None:
    """Mark ``name`` as the exec producing batches on this thread."""
    _stack().append(name)


def pop_exec() -> None:
    s = _stack()
    if s:
        s.pop()


def current_exec() -> str:
    """Innermost exec node executing on this thread ('' outside a plan —
    e.g. the driver's final result fetch)."""
    s = _stack()
    return s[-1] if s else ""


def set_thread_context(tenant: str = "", sid: str = "") -> None:
    """Stamp ``tenant`` (and an overriding ``sid``) on spans emitted from
    THIS thread — the serving tier's per-admitted-query attribution: the
    tracer ring is engine-scoped under concurrent sessions (one reset for
    the engine's lifetime), so per-query identity rides the events
    instead of the ring's single session label.  Spans from pool/prefetch
    helper threads keep the engine-scope label only (docs/serving.md)."""
    _tls.tenant = tenant
    _tls.sid = sid


def clear_thread_context() -> None:
    _tls.tenant = ""
    _tls.sid = ""


def thread_tenant() -> str:
    return getattr(_tls, "tenant", "")


# --------------------------------------------------------------------------
# distributed trace context (cross-process stitching)
# --------------------------------------------------------------------------

_SPAN_SEQ = itertools.count(1)


def next_span_id() -> str:
    """Process-unique span id stamped on wire-crossing spans (shuffle
    remote fetch/serve, frame serialization) so tools/trace_merge.py can
    connect the two sides of a cross-process edge with a flow event."""
    return f"{os.getpid():x}.{next(_SPAN_SEQ)}"


def current_trace_context() -> Optional[Dict[str, Any]]:
    """Trace context of the query running on THIS thread:
    ``{trace, query, tenant}``.  Derived from the installed lifecycle
    token (valid on shuffle reader-pool threads too — the manager
    reinstalls the query context there), falling back to the tracer's
    session label for untracked callers.  None when tracing is off."""
    if not TRACING["on"]:
        return None
    from ..serving import lifecycle as _lc  # deferred: avoid import cycle
    q = _lc.current()
    if q is not None:
        return {"trace": f"{q.session_id}:q{q.query_id}",
                "query": q.query_id,
                "tenant": q.tenant or thread_tenant()}
    sid = getattr(_tls, "sid", "") or _TRACER.session_label
    return {"trace": sid or f"pid-{os.getpid()}", "query": 0,
            "tenant": thread_tenant()}


def set_fetch_trace(ctx: Optional[Dict[str, Any]]) -> None:
    """Install the trace context the transport should propagate on the
    next shuffle fetch from THIS thread (shuffle/manager.py sets it
    around ``transport.fetch``; shuffle/tcp.py reads it).  Riding a
    thread-local keeps the ShuffleTransport SPI ``fetch(peer, block)``
    signature unchanged, so duck-typed test transports keep working."""
    _tls.fetch_trace = ctx


def fetch_trace() -> Optional[Dict[str, Any]]:
    return getattr(_tls, "fetch_trace", None)


# --------------------------------------------------------------------------
# the tracer
# --------------------------------------------------------------------------

class QueryTracer:
    """Bounded ring buffer of trace events (newest kept on overflow, with
    a ``dropped_events`` counter) plus aggregate named counters."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(16, int(capacity)))
        self.dropped_events = 0
        #: high-water value last pushed to the metrics registry gauge —
        #: the feed is strided (every 1024 events) so the scrape surface
        #: sees ring fill without one registry write per span
        self._hw_reported = 0
        #: most events the ring ever held this query — with
        #: dropped_events, the evidence that a truncated trace cannot
        #: silently skew doctor attribution (high_water == capacity and
        #: dropped > 0 means the window was too small)
        self.high_water = 0
        #: stable session label stamped on every event (``sid``) — set by
        #: the session at query start, groundwork for per-tenant metrics
        self.session_label = ""
        self._epoch = time.perf_counter()
        self._epoch_wall = time.time()
        self.counters: Dict[str, float] = {}

    # --- lifecycle --------------------------------------------------------
    def reset(self, capacity: Optional[int] = None,
              session: Optional[str] = None) -> None:
        """Start a fresh timeline (called by the session at query start)."""
        with self._lock:
            if capacity is not None and \
                    int(capacity) != self._events.maxlen:
                self._events = deque(maxlen=max(16, int(capacity)))
            else:
                self._events.clear()
            self.dropped_events = 0
            self.high_water = 0
            self._hw_reported = 0
            if session is not None:
                self.session_label = str(session)
            self.counters = {}
            self._epoch = time.perf_counter()
            self._epoch_wall = time.time()
        if _metrics.METRICS["on"]:
            _metrics.get_registry().set_gauge("trace_ring_high_water", 0)

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    # --- emission ---------------------------------------------------------
    def complete(self, cat: str, name: str, t0: float, dur_s: float,
                 exec_: Optional[str] = None, **args: Any) -> None:
        """Record a retroactive complete span: ``t0`` is the
        ``time.perf_counter()`` at span start, ``dur_s`` its duration in
        seconds.  ``exec_`` defaults to the thread's current exec node."""
        ev: Dict[str, Any] = {
            "cat": cat, "name": name,
            "ts": (t0 - self._epoch) * 1e6,          # µs from trace epoch
            "dur": max(dur_s, 0.0) * 1e6,
            "tid": threading.get_ident(),
            "exec": current_exec() if exec_ is None else exec_,
        }
        tsid = getattr(_tls, "sid", "")
        if tsid or self.session_label:
            ev["sid"] = tsid or self.session_label
        tenant = getattr(_tls, "tenant", "")
        if tenant:
            ev["tenant"] = tenant
        if args:
            ev["args"] = args
        with self._lock:
            dropped = len(self._events) == self._events.maxlen
            if dropped:
                self.dropped_events += 1
            self._events.append(ev)
            if len(self._events) > self.high_water:
                self.high_water = len(self._events)
            report_hw = 0
            if self._hw_reported == 0 \
                    or self.high_water >= self._hw_reported + 1024 \
                    or (dropped and self._hw_reported < self.high_water):
                # a full ring always reports at the true high-water:
                # the scraper must see "at capacity" the moment events
                # start dropping, not a stride later
                report_hw = self._hw_reported = self.high_water
        # registry feed: per-category latency distribution, exec-labeled
        # (one dict lookup when the registry is off); ring health rides
        # along so a scrape sees trace truncation without a query
        # epilogue (gauge strided; the drop counter is exact)
        if _metrics.METRICS["on"]:
            reg = _metrics.get_registry()
            reg.observe("trace_span_ms", max(dur_s, 0.0) * 1e3,
                        cat=cat, exec=ev["exec"] or "(driver)")
            if dropped:
                reg.inc("trace_dropped_events_total")
            if report_hw:
                reg.set_gauge("trace_ring_high_water", report_hw)

    def counter(self, name: str, value: float = 1.0) -> None:
        """Accumulate a named aggregate counter (no per-event storage)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value

    # --- readout ----------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        """Events oldest-first (a copy; safe to hold across resets)."""
        with self._lock:
            return list(self._events)

    def meta(self) -> Dict[str, Any]:
        """Trace metadata for exports: wall-clock epoch + drop stats."""
        import os
        with self._lock:
            out = {"epoch_unix_s": self._epoch_wall,
                   "pid": os.getpid(),
                   "capacity": self._events.maxlen,
                   "dropped_events": self.dropped_events,
                   "ring_high_water": self.high_water,
                   "counters": dict(self.counters)}
            if self.session_label:
                out["session_id"] = self.session_label
            return out


_TRACER = QueryTracer()


def get_tracer() -> QueryTracer:
    return _TRACER


# --------------------------------------------------------------------------
# span context manager (null-object when disabled)
# --------------------------------------------------------------------------

class _NullSpan:
    """Shared no-op span — the disabled-path cost is one flag lookup."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("cat", "name", "args", "t0")

    def __init__(self, cat: str, name: str, args: Dict[str, Any]):
        self.cat, self.name, self.args = cat, name, args

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _TRACER.complete(self.cat, self.name, self.t0,
                         time.perf_counter() - self.t0, **self.args)
        return False


def span(cat: str, name: str, **args: Any):
    """Context manager recording a complete span when tracing is on; a
    shared null object otherwise.  Callers computing *expensive* span
    args should guard on ``TRACING["on"]`` themselves."""
    if not TRACING["on"]:
        return _NULL_SPAN
    return _Span(cat, name, args)
