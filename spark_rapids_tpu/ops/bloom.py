"""Bloom-filter kernels for join runtime filters.

TPU-native re-design of the reference's bloom-filter join optimization
(``GpuBloomFilterMightContain.scala:1``, ``shims/BloomFilterShims.scala``
spark330+, jni ``BloomFilter`` — SURVEY §2.10): the build side of a
shuffled hash join constructs a bloom filter over its join keys and the
probe side drops non-members BELOW its exchange, shrinking both the
shuffle and the join probe.

Layout: the filter is a flat ``bool[m]`` device array (XLA scatters/
gathers vectorize cleanly over it; no bit-packing on device — HBM is
cheap next to a shuffle of dead rows).  Indexing uses Kirsch-Mitzenmacher
double hashing over one xxhash64 evaluation: ``idx_i = h1 + i*h2 (mod m)``
with ``h1 = low32(h)``, ``h2 = high32(h) | 1`` — k gathers instead of k
independent hash passes.

False positives only cost wasted probe rows; a false NEGATIVE would drop
a matching row, so every row present at build time must hit set bits —
guaranteed by using the identical hash evaluation on both sides.
"""

from __future__ import annotations

import math

import numpy as np

#: observability (tests + task metrics)
STATS = {"blooms_built": 0, "probe_rows_in": 0, "probe_rows_kept": 0}


def bloom_params(n_rows: int, bits_per_row: int = 8):
    """(m_bits, k): power-of-two bit count and hash count for the target
    density (k = bits_per_row * ln 2 rounds to the optimal count)."""
    m = 1 << max(6, int(math.ceil(math.log2(max(n_rows, 1)
                                            * max(bits_per_row, 1)))))
    k = max(1, int(round(bits_per_row * math.log(2))))
    return m, min(k, 8)


def _split_hash(xp, h_i64):
    """int64 xxhash64 -> (h1 u32, h2 u32|1) for double hashing."""
    h = h_i64.astype(xp.uint64)
    h1 = h.astype(xp.uint32)
    h2 = (h >> np.uint64(32)).astype(xp.uint32) | np.uint32(1)
    return h1, h2


def bloom_build(xp, bits, h_i64, mask, k: int):
    """OR the rows' k bit positions into ``bits`` (bool[m]); functional —
    returns the updated array.  m and k are static (traced shapes).
    Dead rows scatter to index m, which ``mode="drop"`` discards."""
    m = np.uint32(bits.shape[0])
    h1, h2 = _split_hash(xp, h_i64)
    for i in range(k):
        idx = ((h1 + np.uint32(i) * h2) % m).astype(xp.int32)
        if xp.__name__ == "numpy":
            bits[np.asarray(idx)[np.asarray(mask)]] = True
        else:
            bits = bits.at[xp.where(mask, idx,
                                    np.int32(int(m)))].set(True, mode="drop")
    return bits


def bloom_might_contain(xp, bits, h_i64, k: int):
    """bool[n]: True where all k bits are set (possible member)."""
    m = np.uint32(bits.shape[0])
    h1, h2 = _split_hash(xp, h_i64)
    ok = None
    for i in range(k):
        idx = ((h1 + np.uint32(i) * h2) % m).astype(xp.int32)
        hit = bits[idx]
        ok = hit if ok is None else (ok & hit)
    return ok
