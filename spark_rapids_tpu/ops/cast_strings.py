"""Device string<->number/date/bool casts — the ``CastStrings`` analog
(reference ``com.nvidia.spark.rapids.jni.CastStrings``: Spark-exact
string casts as native kernels; consumed by ``GpuCast.scala``).

All kernels are vectorized over the padded byte-matrix layout
([rows, width] uint8 + int32 lengths) and traceable under jnp, so string
casts fuse into whole-stage programs instead of bouncing to the host.
Spark (non-ANSI) semantics: unparsable input -> NULL, overflow -> NULL
for string->integral, whitespace trimmed.

Shapes are static: the parse runs positionally over the width dimension
with masks — no data-dependent control flow, MXU/VPU-friendly.
"""

from __future__ import annotations

import numpy as np

_SP = 32  # space
_PLUS, _MINUS, _DOT = 43, 45, 46
_ZERO, _NINE = 48, 57
_E_LO, _E_UP = 101, 69


def _trimmed(xp, chars, lengths):
    """(start, end) of the content after trimming ASCII whitespace
    (space, \\t..\\r) on both sides; chars int16-safe."""
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    in_str = pos < lengths[:, None]
    is_ws = in_str & ((c == _SP) | ((c >= 9) & (c <= 13)))
    non_ws = in_str & ~is_ws
    any_content = xp.any(non_ws, axis=1)
    big = xp.asarray(width, dtype=xp.int32)
    first = xp.min(xp.where(non_ws, pos, big), axis=1)
    last = xp.max(xp.where(non_ws, pos, -1), axis=1)
    start = xp.where(any_content, first, 0)
    end = xp.where(any_content, last + 1, 0)
    return start.astype(xp.int32), end.astype(xp.int32)


def parse_long(xp, chars, lengths, validity):
    """(int64 values, ok mask): Spark-exact string -> long.  Accepts
    optional +/- then 1..19 digits; anything else (or 64-bit overflow)
    is not-ok.  Also accepts a trailing fractional part ('12.9' -> 12,
    truncation like Spark's cast to integral... Spark 3 casts '12.9' to
    NULL for integral targets, so we reject dots)."""
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    start, end = _trimmed(xp, chars, lengths)
    n = end - start
    has_sign = (n > 0) & ((_take(xp, c, start) == _PLUS)
                          | (_take(xp, c, start) == _MINUS))
    neg = (n > 0) & (_take(xp, c, start) == _MINUS)
    dstart = start + has_sign.astype(xp.int32)
    ndig = end - dstart
    in_digits = (pos >= dstart[:, None]) & (pos < end[:, None])
    is_digit = (c >= _ZERO) & (c <= _NINE)
    all_digits = xp.all(~in_digits | is_digit, axis=1)
    # magnitude bound counts SIGNIFICANT digits — leading zeros are legal
    # at any length ('0...01' parses as 1; zero digits also make the
    # clipped place values beyond 10^18 harmless: 0 * anything = 0)
    nonzero = in_digits & is_digit & (c != _ZERO)
    bigw = xp.asarray(width, dtype=xp.int32)
    first_sig = xp.min(xp.where(nonzero, pos, bigw), axis=1).astype(xp.int32)
    n_sig = xp.maximum(end - xp.minimum(first_sig, end), 0)
    ok = validity & (ndig >= 1) & (n_sig <= 19) & all_digits
    # accumulate value * 10^(digits after) — uint64 wraps on overflow,
    # which the 19-digit magnitude check below catches
    digit = xp.where(in_digits & is_digit, (c - _ZERO), 0)
    # place value: 10^(end-1-pos) for positions inside the digit run
    exp = xp.clip(end[:, None] - 1 - pos, 0, 18)
    pow10 = xp.asarray((10 ** np.arange(19, dtype=np.uint64))
                   .astype(np.uint64))
    place = pow10[exp]
    acc = xp.sum(xp.where(in_digits, digit.astype(xp.uint64) * place,
                          xp.asarray(0, dtype=xp.uint64)), axis=1)
    # 19-digit values may exceed int64: detect via uint64 comparison
    lim_pos = xp.asarray(np.uint64(2**63 - 1))
    lim_neg = xp.asarray(np.uint64(2**63))
    fits = xp.where(neg, acc <= lim_neg, acc <= lim_pos)
    ok = ok & fits
    signed = xp.where(neg, (~acc + xp.asarray(1, dtype=xp.uint64)),
                      acc).astype(xp.int64)
    return signed, ok


def _take(xp, c, idx):
    """c[row, idx[row]] with idx clipped into width."""
    width = c.shape[1]
    rows = xp.arange(c.shape[0], dtype=xp.int32)
    return c[rows, xp.clip(idx, 0, width - 1)]


def _word_is(xp, lower, at, end, word_s):
    """True where the content from ``at`` to ``end`` is exactly
    ``word_s`` (lowercased chars) — the one fixed-word matcher shared by
    bool/double/timestamp parsing."""
    m = (end - at) == len(word_s)
    for i, ch in enumerate(word_s):
        m = m & (_take(xp, lower, at + i) == ord(ch))
    return m


def _mantissa_parts(xp, c, pos, start, end):
    """Shared decimal-number scaffolding for parse_double/parse_decimal:
    sign, mantissa span, dot/exponent positions and digit masks — ONE
    copy of the split rules so the two parsers cannot drift."""
    width = c.shape[1]
    n = end - start
    has_sign = (n > 0) & ((_take(xp, c, start) == _PLUS)
                          | (_take(xp, c, start) == _MINUS))
    neg = (n > 0) & (_take(xp, c, start) == _MINUS)
    dstart = start + has_sign.astype(xp.int32)
    lower = xp.where((c >= 65) & (c <= 90), c + 32, c)
    is_digit = (c >= _ZERO) & (c <= _NINE)
    bigw = xp.asarray(width, dtype=xp.int32)
    is_e = (lower == _E_LO) & (pos >= dstart[:, None]) & \
        (pos < end[:, None])
    e_pos = xp.min(xp.where(is_e, pos, bigw), axis=1).astype(xp.int32)
    has_e = e_pos < end
    mant_end = xp.where(has_e, e_pos, end)
    is_dot = (c == _DOT) & (pos >= dstart[:, None]) & \
        (pos < mant_end[:, None])
    dot_pos = xp.min(xp.where(is_dot, pos, bigw), axis=1).astype(xp.int32)
    has_dot = dot_pos < mant_end
    n_dots = xp.sum(is_dot.astype(xp.int32), axis=1)
    int_end = xp.where(has_dot, dot_pos, mant_end)
    in_int = (pos >= dstart[:, None]) & (pos < int_end[:, None])
    in_frac = has_dot[:, None] & (pos > dot_pos[:, None]) & \
        (pos < mant_end[:, None])
    digits_ok = xp.all(~(in_int | in_frac) | is_digit, axis=1)
    return dict(n=n, neg=neg, dstart=dstart, lower=lower,
                is_digit=is_digit, e_pos=e_pos, has_e=has_e,
                mant_end=mant_end, dot_pos=dot_pos, has_dot=has_dot,
                n_dots=n_dots, int_end=int_end, in_int=in_int,
                in_frac=in_frac, digits_ok=digits_ok)


def _exponent_value(xp, c, pos, mp, end):
    """(exp int32, exp_ok): the [eE][+-]digits suffix.  n_exp_digits is
    capped at 9 so the int32 digit sum cannot wrap back into range."""
    es = mp["e_pos"] + 1
    e_sign_ch = _take(xp, c, es)
    e_has_sign = mp["has_e"] & ((e_sign_ch == _PLUS)
                                | (e_sign_ch == _MINUS))
    e_neg = mp["has_e"] & (e_sign_ch == _MINUS)
    ed = es + e_has_sign.astype(xp.int32)
    in_exp = mp["has_e"][:, None] & (pos >= ed[:, None]) & \
        (pos < end[:, None])
    exp_digits_ok = xp.all(~in_exp | mp["is_digit"], axis=1)
    n_exp = xp.sum(in_exp.astype(xp.int32), axis=1)
    eexp = xp.clip(end[:, None] - 1 - pos, 0, 8)
    mag = xp.sum(xp.where(in_exp, (c - _ZERO) * xp.power(10, eexp), 0),
                 axis=1).astype(xp.int32)
    exp_ok = ~mp["has_e"] | ((n_exp >= 1) & (n_exp <= 9)
                             & exp_digits_ok)
    return xp.where(e_neg, -mag, mag), exp_ok


def parse_double(xp, chars, lengths, validity):
    """(float64 values, ok): string -> double for the standard decimal
    forms [+-]digits[.digits][eE[+-]digits] plus Infinity/inf/NaN words
    (case-insensitive, Spark CastStringToDouble).  Magnitudes accumulate
    positionally in float64 — one rounding per digit, a few ULPs against
    libc's exact parse (documented error class, fuzz-bounded <1e-13)."""
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    start, end = _trimmed(xp, chars, lengths)
    mp = _mantissa_parts(xp, c, pos, start, end)
    neg, dstart, lower = mp["neg"], mp["dstart"], mp["lower"]
    is_digit = mp["is_digit"]

    is_inf = _word_is(xp, lower, dstart, end, "infinity") | \
        _word_is(xp, lower, dstart, end, "inf")
    is_nan = _word_is(xp, lower, start, end, "nan")

    in_int, in_frac = mp["in_int"], mp["in_frac"]
    dot_pos, int_end = mp["dot_pos"], mp["int_end"]
    n_mant_digits = xp.sum((in_int | in_frac).astype(xp.int32), axis=1)

    dig = xp.where(is_digit, c - _ZERO, 0).astype(xp.float64)
    # integer part: digit * 10^(int_end-1-pos)
    iexp = xp.clip(int_end[:, None] - 1 - pos, 0, 308)
    int_val = xp.sum(xp.where(in_int, dig * xp.power(
        xp.asarray(10.0, dtype=xp.float64), iexp.astype(xp.float64)), 0.0),
        axis=1)
    # fraction: digit * 10^-(pos-dot_pos)
    fexp = xp.clip(pos - dot_pos[:, None], 0, 308)
    frac_val = xp.sum(xp.where(in_frac, dig * xp.power(
        xp.asarray(10.0, dtype=xp.float64), -fexp.astype(xp.float64)), 0.0),
        axis=1)
    mant = int_val + frac_val

    exp_val, exp_ok = _exponent_value(xp, c, pos, mp, end)
    exp_f = xp.clip(exp_val.astype(xp.float64), -400.0, 400.0)

    val = mant * xp.power(xp.asarray(10.0, dtype=xp.float64), exp_f)
    val = xp.where(neg, -val, val)

    plain_ok = (validity & (mp["n"] > 0) & mp["digits_ok"] & exp_ok
                & (n_mant_digits >= 1) & (mp["n_dots"] <= 1))
    inf = xp.where(neg, -xp.inf, xp.inf)
    out = xp.where(is_inf, inf, xp.where(is_nan, xp.nan, val))
    ok = validity & (is_inf | is_nan | plain_ok)
    return out, ok


def _date_section_end(xp, c, pos, start, end):
    """Position of the first 'T'/space after ``start`` (else ``end``) —
    the boundary between the date part and an optional time section.
    Shared by parse_date and parse_timestamp so the split rule can't
    drift; inside one jit XLA CSEs the duplicate trim/cut subgraphs."""
    width = c.shape[1]
    bigw = xp.asarray(width, dtype=xp.int32)
    t_or_sp = ((c == 84) | (c == _SP)) & (pos > start[:, None]) & \
        (pos < end[:, None])
    return xp.min(xp.where(t_or_sp, pos, bigw), axis=1).astype(xp.int32)


def parse_date(xp, chars, lengths, validity):
    """(int32 days-since-epoch, ok): 'yyyy-MM-dd' / 'yyyy-M-d' plus bare
    'yyyy' and 'yyyy-MM' (Spark accepts those, defaulting month/day 1)."""
    c = chars.astype(xp.int32)
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    start, end = _trimmed(xp, chars, lengths)
    # Spark's stringToDate accepts a trailing time section ('T...' or
    # ' ...'): the date part ends at the first T/space after the start
    end = xp.minimum(end, _date_section_end(xp, c, pos, start, end))
    is_digit = (c >= _ZERO) & (c <= _NINE)
    dash = c == _MINUS
    in_str = (pos >= start[:, None]) & (pos < end[:, None])
    # dash positions (first two)
    big = xp.asarray(width, dtype=xp.int32)
    d_mask = dash & in_str & (pos > start[:, None])  # leading '-' unsupported
    d1 = xp.min(xp.where(d_mask, pos, big), axis=1).astype(xp.int32)
    d2_mask = d_mask & (pos > d1[:, None])
    d2 = xp.min(xp.where(d2_mask, pos, big), axis=1).astype(xp.int32)
    has_d1 = d1 < end
    has_d2 = d2 < end

    def seg_val(lo, hi):
        """numeric value of digits in [lo, hi); (value, ok, len)."""
        seg = (pos >= lo[:, None]) & (pos < hi[:, None])
        okd = xp.all(~seg | is_digit, axis=1)
        ln = hi - lo
        e = xp.clip(hi[:, None] - 1 - pos, 0, 8)
        v = xp.sum(xp.where(seg, (c - _ZERO) * xp.power(10, e), 0), axis=1)
        return v.astype(xp.int32), okd, ln

    y_end = xp.where(has_d1, d1, end)
    y, y_ok, y_len = seg_val(start, y_end)
    m_end = xp.where(has_d2, d2, end)
    m, m_ok, m_len = seg_val(xp.where(has_d1, d1 + 1, end), m_end)
    d, d_ok, d_len = seg_val(xp.where(has_d2, d2 + 1, end), end)
    m = xp.where(has_d1, m, 1)
    d = xp.where(has_d2, d, 1)
    ok = (validity & (end > start) & y_ok & m_ok & d_ok
          & (y_len == 4)
          & (~has_d1 | ((m_len >= 1) & (m_len <= 2)))
          & (~has_d2 | ((d_len >= 1) & (d_len <= 2)))
          & (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31))
    days, cal_ok = _civil_to_days(xp, y, m, d)
    return days.astype(xp.int32), ok & cal_ok


def _civil_to_days(xp, y, m, d):
    """Days since 1970-01-01 for proleptic-Gregorian (y, m, d) + validity
    of the day-of-month (Howard Hinnant's civil algorithm, branch-free)."""
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    mdays = xp.asarray(np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31,
                                 30, 31], dtype=np.int32))
    md = mdays[xp.clip(m - 1, 0, 11)]
    md = xp.where((m == 2) & leap, 29, md)
    ok = d <= md
    yy = y - (m <= 2)
    era = xp.where(yy >= 0, yy, yy - 399) // 400
    yoe = yy - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468, ok


def parse_bool(xp, chars, lengths, validity):
    """Spark string->boolean: true/t/yes/y/1 and false/f/no/n/0
    (case-insensitive, trimmed)."""
    c = chars.astype(xp.int32)
    lower = xp.where((c >= 65) & (c <= 90), c + 32, c)
    start, end = _trimmed(xp, chars, lengths)

    def word(w):
        return _word_is(xp, lower, start, end, w)

    t = word("true") | word("t") | word("yes") | word("y") | word("1")
    f = word("false") | word("f") | word("no") | word("n") | word("0")
    return t, validity & (t | f)


def format_long(xp, vals, validity, width: int = 20):
    """int64 -> byte matrix (Spark number->string): minus sign + digits,
    no padding.  Returns (chars uint8[n, width], lengths int32[n])."""
    neg = vals < 0
    # magnitude as uint64 (abs of INT64_MIN is representable there)
    mag = xp.where(neg, (~vals.astype(xp.uint64))
                   + xp.asarray(1, dtype=xp.uint64),
                   vals.astype(xp.uint64))
    pow10 = xp.asarray((10 ** np.arange(19, dtype=np.uint64))
                   .astype(np.uint64))
    # digits most-significant-first over 19 positions
    digs = (mag[:, None] // pow10[None, ::-1]) % xp.asarray(
        10, dtype=xp.uint64)
    ndig = xp.maximum(
        xp.sum((mag[:, None] >= pow10[None, :]).astype(xp.int32), axis=1),
        1)
    lengths = ndig + neg.astype(xp.int32)
    # layout: row i writes sign at 0 (if neg) then its ndig digits
    out_pos = xp.arange(width, dtype=xp.int32)[None, :]
    # digit index d (0 = most significant of the VALUE) sits at
    # out position neg + d; source digit column = 19 - ndig + d
    d_idx = out_pos - neg.astype(xp.int32)[:, None]
    src_col = 19 - ndig[:, None] + d_idx
    in_digits = (d_idx >= 0) & (d_idx < ndig[:, None])
    gathered = xp.take_along_axis(
        digs, xp.clip(src_col, 0, 18).astype(xp.int32), axis=1)
    chars = xp.where(in_digits, gathered.astype(xp.uint8) + _ZERO, 0)
    chars = xp.where((out_pos == 0) & neg[:, None],
                     xp.asarray(_MINUS, dtype=xp.uint8), chars)
    return chars.astype(xp.uint8), xp.where(validity, lengths, 0)


def parse_timestamp(xp, chars, lengths, validity):
    """(int64 micros-since-epoch UTC, ok): 'yyyy[-M[-d]][ |T HH:mm[:ss
    [.fraction]]][zone]' where zone is 'Z', 'UTC', 'GMT' or a numeric
    offset [+-]HH[:MM] (applied to UTC).  Named region zones and other
    layouts return NULL — same rows the engine's host path rejects (the
    engine runs in UTC; there is no per-row host fallback)."""
    days, date_ok = parse_date(xp, chars, lengths, validity)
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    start, end = _trimmed(xp, chars, lengths)
    cut = _date_section_end(xp, c, pos, start, end)
    has_time = cut < end
    ts = cut + 1  # first char of the time section

    is_digit = (c >= _ZERO) & (c <= _NINE)

    def two_digits(at):
        """1-2 digit group starting at `at`: (value, ndigits); ndigits=0
        when the first char is not a digit (no partial matches)."""
        d0 = _take(xp, c, at)
        d1 = _take(xp, c, at + 1)
        d0_ok = (at < end) & (d0 >= _ZERO) & (d0 <= _NINE)
        d1_ok = d0_ok & (at + 1 < end) & (d1 >= _ZERO) & (d1 <= _NINE)
        v = xp.where(d1_ok, (d0 - _ZERO) * 10 + (d1 - _ZERO),
                     d0 - _ZERO)
        n = xp.where(d1_ok, 2, xp.where(d0_ok, 1, 0))
        return xp.where(d0_ok, v, 0), n

    hh, hn = two_digits(ts)
    c1 = ts + hn
    has_min = has_time & (hn >= 1) & (_take(xp, c, c1) == 58) & (c1 < end)
    mm, mn = two_digits(c1 + 1)
    c2 = xp.where(has_min, c1 + 1 + mn, c1)
    has_sec = has_min & (mn >= 1) & (_take(xp, c, c2) == 58) & (c2 < end)
    ss_v, sn = two_digits(c2 + 1)
    c3 = xp.where(has_sec, c2 + 1 + sn, c2)
    has_frac = has_sec & (sn >= 1) & (_take(xp, c, c3) == _DOT) & (c3 < end)
    # fraction: up to 6 digits of micros (deeper digits truncate; a bare
    # trailing dot is legal, matching Spark's fraction segment)
    fstart = c3 + 1
    in_frac = has_frac[:, None] & (pos >= fstart[:, None]) & \
        (pos < end[:, None]) & is_digit
    # fraction digits run until the first non-digit (zone may follow)
    non_digit_after = has_frac[:, None] & (pos >= fstart[:, None]) & \
        (pos < end[:, None]) & ~is_digit
    bigw = xp.asarray(width, dtype=xp.int32)
    frac_stop = xp.min(xp.where(non_digit_after, pos, bigw),
                       axis=1).astype(xp.int32)
    frac_stop = xp.minimum(frac_stop, end)
    in_frac = in_frac & (pos < frac_stop[:, None])
    n_frac = xp.sum(in_frac.astype(xp.int32), axis=1)
    fidx = pos - fstart[:, None]  # 0-based fraction digit index
    fweight = xp.where((fidx >= 0) & (fidx < 6),
                       xp.asarray(
                           np.array([100000, 10000, 1000, 100, 10, 1],
                                    dtype=np.int64))[xp.clip(fidx, 0, 5)],
                       0)
    micros_frac = xp.sum(xp.where(in_frac, (c - _ZERO) * fweight, 0),
                         axis=1).astype(xp.int64)

    time_end = xp.where(has_frac, frac_stop,
                        xp.where(has_sec, c2 + 1 + sn,
                                 xp.where(has_min, c1 + 1 + mn,
                                          ts + hn)))

    # rows without a time section have no time_end; anchor it at end so
    # the zone logic below sees "no zone" for bare dates
    time_end = xp.where(has_time, time_end, end)

    # zone suffix after the time: Z | UTC | GMT | [+-]HH[:MM], with one
    # optional space before it ('... 12:03:17 UTC')
    lower = xp.where((c >= 65) & (c <= 90), c + 32, c)
    z_at = time_end + ((_take(xp, c, time_end) == _SP)
                       & (time_end < end)).astype(xp.int32)
    no_zone = z_at == end
    z_named = _word_is(xp, lower, z_at, end, "z") | \
        _word_is(xp, lower, z_at, end, "utc") | \
        _word_is(xp, lower, z_at, end, "gmt")
    sign_ch = _take(xp, c, z_at)
    z_sign = (sign_ch == _PLUS) | (sign_ch == _MINUS)
    oh, ohn = two_digits(z_at + 1)
    oc1 = z_at + 1 + ohn
    off_has_min = z_sign & (ohn >= 1) & (_take(xp, c, oc1) == 58)
    om, omn = two_digits(oc1 + 1)
    off_end = xp.where(off_has_min, oc1 + 1 + omn, oc1)
    z_offset_ok = (z_sign & (ohn >= 1) & (oh <= 18) & (off_end == end)
                   & (~off_has_min | ((omn == 2) & (om <= 59)))
                   # Java ZoneOffset caps at exactly +-18:00
                   & ((oh < 18) | (xp.where(off_has_min, om, 0) == 0)))
    om = xp.where(off_has_min, om, 0)
    offset_us = (oh.astype(xp.int64) * 3_600_000_000
                 + om.astype(xp.int64) * 60_000_000)
    offset_us = xp.where(sign_ch == _MINUS, -offset_us, offset_us)
    zone_ok = no_zone | (has_time & (z_named | z_offset_ok))
    offset_us = xp.where(has_time & z_offset_ok, offset_us, 0)

    time_ok = zone_ok & ((~has_time) | (
        (hn >= 1) & (hh <= 23)
        & (~has_min | (mm <= 59))
        & (~has_sec | (ss_v <= 59))
        & (~has_frac | (n_frac <= 9))  # Spark caps the fraction segment
        & has_min))  # Spark needs at least HH:mm after the separator
    mm = xp.where(has_min, mm, 0)
    ss_v = xp.where(has_sec, ss_v, 0)
    micros = (days.astype(xp.int64) * 86_400_000_000
              + xp.where(has_time, hh.astype(xp.int64), 0) * 3_600_000_000
              + xp.where(has_time, mm.astype(xp.int64), 0) * 60_000_000
              + xp.where(has_time, ss_v.astype(xp.int64), 0) * 1_000_000
              + xp.where(has_time, micros_frac, 0)
              - offset_us)
    return micros, date_ok & time_ok


def parse_decimal(xp, chars, lengths, validity, precision: int,
                  scale: int):
    """(int64 unscaled values, ok): string -> decimal(p<=18, s), exact
    integer arithmetic (no float round trip).  Accepts
    [+-]digits[.digits][eE[+-]digits]; the value rounds HALF_UP to
    ``scale``; overflow of ``precision`` digits -> not-ok (Spark
    null-on-overflow).  Only the first 19 SIGNIFICANT mantissa digits
    enter the integer accumulator; deeper digits fold into the scale
    shift (they are below the rounding ulp except exact-half ties)."""
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    start, end = _trimmed(xp, chars, lengths)
    mp = _mantissa_parts(xp, c, pos, start, end)
    in_mant = mp["in_int"] | mp["in_frac"]
    is_digit = mp["is_digit"]
    n_frac = xp.sum(mp["in_frac"].astype(xp.int32), axis=1)
    n_mant = xp.sum(in_mant.astype(xp.int32), axis=1)
    bigw = xp.asarray(width, dtype=xp.int32)

    # significant digits (leading zeros free); only the first 19 enter
    # the accumulator — deeper ones shift the exponent instead
    nonzero = in_mant & is_digit & (c != _ZERO)
    first_sig = xp.min(xp.where(nonzero, pos, bigw), axis=1) \
        .astype(xp.int32)
    sig = in_mant & (pos >= first_sig[:, None])
    sig_idx = xp.cumsum(sig.astype(xp.int32), axis=1) - sig.astype(
        xp.int32)  # 0-based ordinal among significant digits
    kept = sig & (sig_idx < 19)
    n_sig = xp.sum(sig.astype(xp.int32), axis=1)
    n_kept = xp.minimum(n_sig, 19)
    dropped = n_sig - n_kept  # trailing sig digits folded into the shift
    after = (xp.cumsum(kept[:, ::-1].astype(xp.int32), axis=1)[:, ::-1]
             - kept.astype(xp.int32))
    pow10 = xp.asarray((10 ** np.arange(20, dtype=np.uint64))
                       .astype(np.uint64))
    place = pow10[xp.clip(after, 0, 18)]
    mant = xp.sum(xp.where(kept, (c - _ZERO).astype(xp.uint64) * place,
                           xp.asarray(0, dtype=xp.uint64)), axis=1)

    exp_val, exp_ok = _exponent_value(xp, c, pos, mp, end)

    # unscaled = mant * 10^shift, HALF_UP when shift < 0
    shift = scale - n_frac + exp_val + dropped
    # below -19 the value rounds to zero (mant < 10^19 => mant/10^20 < .1)
    rounds_to_zero = shift < -19
    shift_c = xp.clip(shift, -19, 18)
    up = pow10[xp.clip(shift_c, 0, 18)]
    down = pow10[xp.clip(-shift_c, 0, 19)]
    scaled_up = mant * up
    q = mant // down
    r = mant - q * down
    q = q + ((2 * r >= down) & (shift_c < 0)).astype(xp.uint64)
    unscaled = xp.where(rounds_to_zero, xp.asarray(0, dtype=xp.uint64),
                        xp.where(shift_c >= 0, scaled_up, q))
    bound = xp.asarray(np.uint64(10 ** min(precision, 18) - 1))
    # positive shifts must keep the product inside the 19-digit table
    headroom_ok = rounds_to_zero | \
        ((n_kept + xp.maximum(shift_c, 0)) <= 19)
    ok = (validity & (n_mant >= 1) & (mp["n_dots"] <= 1)
          & mp["digits_ok"] & exp_ok
          & headroom_ok & (unscaled <= bound))
    signed = xp.where(mp["neg"],
                      (~unscaled + xp.asarray(1, dtype=xp.uint64)),
                      unscaled).astype(xp.int64)
    return signed, ok


def parse_decimal128(xp, chars, lengths, validity, precision: int,
                     scale: int):
    """(lo, hi, ok): string -> decimal(19 <= p <= 38, s) as 128-bit
    (lo, hi) int64 word pairs, exact integer arithmetic.

    Unlike the <=18 path (single uint64 mantissa, post-hoc scale shift),
    this computes a per-digit RESULT exponent e = digits-after + shift
    and buckets each digit's contribution directly: e in [19, 37] ->
    high accumulator A (place 10^(e-19)), e in [0, 18] -> low
    accumulator B, e == -1 -> the HALF_UP rounding digit (round up iff
    >= 5), e < -1 -> below the ulp.  The value is then A*10^19 + B (+1),
    assembled with the chunked 128-bit ops (ops/decimal128.py), so a
    variable per-row shift never needs a >2^31 multiplier."""
    from . import decimal128 as D
    width = chars.shape[1]
    pos = xp.arange(width, dtype=xp.int32)[None, :]
    c = chars.astype(xp.int32)
    start, end = _trimmed(xp, chars, lengths)
    mp = _mantissa_parts(xp, c, pos, start, end)
    in_mant = mp["in_int"] | mp["in_frac"]
    is_digit = mp["is_digit"]
    n_frac = xp.sum(mp["in_frac"].astype(xp.int32), axis=1)
    n_mant = xp.sum(in_mant.astype(xp.int32), axis=1)
    bigw = xp.asarray(width, dtype=xp.int32)

    nonzero = in_mant & is_digit & (c != _ZERO)
    first_sig = xp.min(xp.where(nonzero, pos, bigw), axis=1) \
        .astype(xp.int32)
    sig = in_mant & (pos >= first_sig[:, None])
    sig_idx = xp.cumsum(sig.astype(xp.int32), axis=1) - sig.astype(
        xp.int32)
    # keep 39 digits: precision + 1 GUARD digit, so a 39th significant
    # digit can still land at e == -1 and drive HALF_UP (same reason the
    # <=18 path keeps 19)
    kept = sig & (sig_idx < 39)
    n_sig = xp.sum(sig.astype(xp.int32), axis=1)
    dropped = n_sig - xp.minimum(n_sig, 39)
    after = (xp.cumsum(kept[:, ::-1].astype(xp.int32), axis=1)[:, ::-1]
             - kept.astype(xp.int32))

    exp_val, exp_ok = _exponent_value(xp, c, pos, mp, end)
    shift = scale - n_frac + exp_val + dropped
    e = after + shift[:, None]          # result-place exponent per digit

    pow10 = xp.asarray((10 ** np.arange(20, dtype=np.uint64))
                       .astype(np.uint64))
    d_u = (c - _ZERO).astype(xp.uint64)
    hi_mask = kept & (e >= 19) & (e <= 37)
    lo_mask = kept & (e >= 0) & (e <= 18)
    a = xp.sum(xp.where(hi_mask, d_u * pow10[xp.clip(e - 19, 0, 18)],
                        xp.asarray(0, dtype=xp.uint64)), axis=1)
    b = xp.sum(xp.where(lo_mask, d_u * pow10[xp.clip(e, 0, 18)],
                        xp.asarray(0, dtype=xp.uint64)), axis=1)
    round_up = xp.any(kept & (e == -1) & (c >= _ZERO + 5), axis=1)
    too_big = xp.any(kept & nonzero & (e > 37), axis=1)

    # value = A * 10^19 + B (+ round_up), in chunk space
    a_lo = a.astype(xp.int64)
    zero = xp.zeros_like(a_lo)
    vlo, vhi, _ = D.mul_small(xp, a_lo, zero, 10 ** 9)
    vlo, vhi, _ = D.mul_small(xp, vlo, vhi, 10 ** 9)
    vlo, vhi, _ = D.mul_small(xp, vlo, vhi, 10)
    add = b.astype(xp.int64) + xp.where(round_up, 1, 0).astype(xp.int64)
    # B + round_up < 10^19 never overflows uint64; 128-bit add of the
    # non-negative addend via chunk merge
    c0, c1, c2, c3 = D.split_chunks(xp, vlo, vhi)
    b0, b1, _, _ = D.split_chunks(xp, add, zero)
    vlo, vhi, _ = D.carry_merge(xp, c0 + b0, c1 + b1, c2, c3)

    oob = D.out_of_bounds(xp, vlo, vhi, precision)
    ok = (validity & (n_mant >= 1) & (mp["n_dots"] <= 1)
          & mp["digits_ok"] & exp_ok & ~too_big & ~oob)
    nlo, nhi = D.neg128(xp, vlo, vhi)
    lo = xp.where(mp["neg"], nlo, vlo)
    hi = xp.where(mp["neg"], nhi, vhi)
    return lo, hi, ok


def format_decimal(xp, unscaled, validity, scale: int, width: int = 24):
    """int64 unscaled decimal(p<=18, s) -> byte matrix: sign, integer
    digits (at least one), '.' + exactly ``scale`` fraction digits when
    scale > 0 (Java BigDecimal.toPlainString shapes)."""
    neg = unscaled < 0
    mag = xp.where(neg, (~unscaled.astype(xp.uint64))
                   + xp.asarray(1, dtype=xp.uint64),
                   unscaled.astype(xp.uint64))
    pow10 = xp.asarray((10 ** np.arange(19, dtype=np.uint64))
                       .astype(np.uint64))
    digs = (mag[:, None] // pow10[None, ::-1]) % xp.asarray(
        10, dtype=xp.uint64)  # 19 digits, most significant first
    ndig = xp.maximum(
        xp.sum((mag[:, None] >= pow10[None, :]).astype(xp.int32), axis=1),
        1)
    n_int = xp.maximum(ndig - scale, 1)  # integer digits incl. lone 0
    total = n_int + (1 + scale if scale > 0 else 0) + neg.astype(xp.int32)
    out_pos = xp.arange(width, dtype=xp.int32)[None, :]
    sgn = neg.astype(xp.int32)[:, None]
    # layout: [sign][int digits][. frac digits]
    dot_at = sgn + n_int[:, None]
    is_sign = (out_pos == 0) & neg[:, None]
    is_dot = (scale > 0) & (out_pos == dot_at)
    # digit ordinal (0 = most significant of the PRINTED number, which has
    # max(ndig, scale+1) digits)
    n_print = xp.maximum(ndig, scale + 1)
    d_idx = xp.where(out_pos < dot_at, out_pos - sgn,
                     out_pos - sgn - 1)  # skip the dot
    in_digits = (out_pos >= sgn) & ~is_dot & \
        (d_idx < n_print[:, None]) & (d_idx >= 0) & \
        (out_pos < total[:, None])
    src_col = 19 - n_print[:, None] + d_idx
    gathered = xp.take_along_axis(
        digs, xp.clip(src_col, 0, 18).astype(xp.int32), axis=1)
    chars = xp.where(in_digits, gathered.astype(xp.uint8) + _ZERO, 0)
    chars = xp.where(is_sign, xp.asarray(_MINUS, dtype=xp.uint8), chars)
    chars = xp.where(is_dot & (out_pos < total[:, None]),
                     xp.asarray(_DOT, dtype=xp.uint8), chars)
    return chars.astype(xp.uint8), xp.where(validity, total, 0)
