"""Grouped collection aggregates — device kernels behind collect_list /
collect_set / approx_percentile (reference: cuDF GroupByAggregation
collectList/collectSet consumed by ``AggregateFunctions.scala:2277`` and
``GpuApproximatePercentile.scala`` riding cuDF t-digest).

TPU design: one stable sort puts contributing rows in (group, order) order;
positions within the group come from a running segment start; a single
scatter builds a flat ``[OUT * W]`` slot->source-row index map, and ONE
generic gather materializes the element child (works for every column
kind — numeric, string byte-matrix, decimal — because ``DeviceColumn.gather``
already handles them).  W (max list width) is a static shape picked by the
host from the observed max group count, same two-phase pattern as the hash
aggregate's group-count sync.

approx_percentile returns EXACT percentiles (sorted-selection): the
reference's t-digest is itself approximate and documented incompat vs
Spark; sorted selection is deterministic and at least as accurate.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..columnar.column import DeviceColumn


def _cummax(xp, v):
    if xp.__name__ == "numpy":
        return np.maximum.accumulate(v)
    import jax
    return jax.lax.associative_scan(xp.maximum, v)


def grouped_order(xp, rank, contrib, order_key=None):
    """Stable sort of contributing rows by (group, [order_key], row idx).

    Returns (perm, r_s, pos, is_start):
      perm      int32[cap] — source row per sorted slot (non-contributing
                rows sort last),
      r_s       int64[cap] — group id per sorted slot (cap for dead),
      pos       int32[cap] — position within the group,
      is_start  bool[cap]  — first slot of each group run.
    """
    cap = int(rank.shape[0])
    idx = xp.arange(cap, dtype=xp.int64)
    r = xp.where(contrib, rank.astype(xp.int64), cap)
    if order_key is None:
        key = r * cap + idx
        perm = xp.argsort(key)
    else:
        from .ranks import lex_sort
        perm, _sorted = lex_sort(xp, [r] + list(order_key) + [idx])
    r_s = r[perm]
    sidx = xp.arange(cap, dtype=xp.int64)
    is_start = xp.concatenate([xp.ones(1, dtype=bool),
                               r_s[1:] != r_s[:-1]])
    seg_start = _cummax(xp, xp.where(is_start, sidx, 0))
    pos = (sidx - seg_start).astype(xp.int32)
    return perm.astype(xp.int32), r_s, pos, is_start


def slot_index_map(xp, perm, r_s, pos, keep_mask, OUT: int, W: int):
    """Build the flat slot->source map: for sorted slot j with group g and
    in-group position p (< W), slot g*W+p reads source row perm[j].
    Returns (slot_source int32[OUT*W], slot_valid bool[OUT*W])."""
    cap = int(perm.shape[0])
    flat = (r_s * W + pos).astype(xp.int64)
    ok = keep_mask & (r_s < OUT) & (pos < W)
    tgt = xp.where(ok, flat, OUT * W)  # OOB scatters drop
    slot_source = xp.zeros(OUT * W, dtype=xp.int32).at[tgt].set(
        perm) if xp.__name__ != "numpy" else np_scatter_set(
        np.zeros(OUT * W, dtype=np.int32), tgt, perm)
    sv = xp.zeros(OUT * W, dtype=bool)
    ones = xp.ones(cap, dtype=bool)
    slot_valid = sv.at[tgt].set(ones) if xp.__name__ != "numpy" else \
        np_scatter_set(np.zeros(OUT * W, dtype=bool), tgt, ones)
    return slot_source, slot_valid


def np_scatter_set(out, idx, vals, bound=None):
    """Bounded numpy scatter-set with drop semantics (the one shared
    masking-scatter helper; jnp paths rely on XLA's high-side drop)."""
    idx = np.asarray(idx)
    m = idx < (out.shape[0] if bound is None else bound)
    out[idx[m]] = np.asarray(vals)[m]
    return out


def collect_into_arrays(xp, value_col: DeviceColumn, rank, contrib,
                        OUT: int, W: int, distinct: bool,
                        group_ok) -> DeviceColumn:
    """collect_list / collect_set kernel: per group, the contributing
    values (insertion order for list, first-occurrence order for set) as
    an ARRAY column of OUT rows with width-W element slots."""
    from .ranks import column_sort_keys
    from ..columnar.column import make_array_column
    from .. import types as T

    val_valid = (value_col.validity if value_col.validity is not None
                 else xp.ones(rank.shape[0], dtype=bool))
    contrib = contrib & val_valid
    order_key = None
    if distinct:
        order_key = [(~val_valid).astype(xp.int64)] + \
            list(column_sort_keys(xp, value_col))
    perm, r_s, pos, is_start = grouped_order(xp, rank, contrib, order_key)
    keep = r_s < int(rank.shape[0])
    if distinct:
        # equal values are now adjacent within the group: keep the first
        same_group = xp.concatenate([xp.zeros(1, dtype=bool),
                                     r_s[1:] == r_s[:-1]])
        eq_prev = xp.ones(r_s.shape[0], dtype=bool)
        for k in order_key:
            ks = k[perm.astype(xp.int64)]
            eq_prev = eq_prev & xp.concatenate(
                [xp.zeros(1, dtype=bool), ks[1:] == ks[:-1]])
        dup = same_group & eq_prev
        keep = keep & ~dup
        # recompute dense positions over survivors
        kept_before = xp.cumsum(keep.astype(xp.int64)) - keep.astype(xp.int64)
        seg_start_kept = _cummax(
            xp, xp.where(is_start, kept_before, 0))
        pos = (kept_before - seg_start_kept).astype(xp.int32)
    slot_source, slot_valid = slot_index_map(xp, perm, r_s, pos, keep,
                                             OUT, W)
    elem = value_col.gather(slot_source, slot_valid)
    counts = xp.zeros(OUT, dtype=xp.int32).at[
        xp.where(keep & (pos < W), r_s, OUT * xp.ones_like(r_s))
    ].add(xp.ones_like(pos)) if xp.__name__ != "numpy" else None
    if xp.__name__ == "numpy":
        counts = np.zeros(OUT, dtype=np.int32)
        sel = np.asarray(keep & (pos < W) & (r_s < OUT))
        np.add.at(counts, np.asarray(r_s)[sel], 1)
    return make_array_column(T.ArrayType(value_col.dtype), counts, (elem,),
                             group_ok)


def grouped_percentiles(xp, value_col: DeviceColumn, rank, contrib,
                        OUT: int, percentages: Sequence[float], group_ok
                        ) -> Tuple:
    """Exact grouped percentile selection: per group g and percentage p,
    the element at ordinal max(ceil(p*count)-1, 0) of the group's sorted
    values (Spark's percentile ordinal rule).  Returns (per-p gathered
    DeviceColumns, counts int64[OUT])."""
    from .ranks import column_sort_keys
    val_valid = (value_col.validity if value_col.validity is not None
                 else xp.ones(rank.shape[0], dtype=bool))
    contrib = contrib & val_valid
    order_key = [(~val_valid).astype(xp.int64)] + \
        list(column_sort_keys(xp, value_col))
    perm, r_s, pos, is_start = grouped_order(xp, rank, contrib, order_key)
    cap = int(rank.shape[0])
    keep = r_s < cap
    # per-group first sorted slot + counts
    sidx = xp.arange(cap, dtype=xp.int64)
    big = xp.asarray(cap, dtype=xp.int64)
    first_slot = xp.full(OUT, cap, dtype=xp.int64).at[
        xp.where(keep & is_start, r_s, big)].min(sidx) \
        if xp.__name__ != "numpy" else None
    if xp.__name__ == "numpy":
        first_slot = np.full(OUT, cap, dtype=np.int64)
        sel = np.asarray(keep & is_start) & (np.asarray(r_s) < OUT)
        np.minimum.at(first_slot, np.asarray(r_s)[sel],
                      np.asarray(sidx)[sel])
    counts = xp.zeros(OUT, dtype=xp.int64)
    if xp.__name__ == "numpy":
        sel = np.asarray(keep) & (np.asarray(r_s) < OUT)
        np.add.at(counts, np.asarray(r_s)[sel], 1)
    else:
        counts = counts.at[xp.where(keep, r_s, big)].add(
            xp.ones(cap, dtype=xp.int64))
    outs = []
    for p in percentages:
        ordinal = xp.clip(xp.ceil(p * counts.astype(xp.float64)
                                  ).astype(xp.int64) - 1, 0,
                          xp.maximum(counts - 1, 0))
        slot = xp.clip(first_slot + ordinal, 0, cap - 1).astype(xp.int32)
        src = perm[slot]
        valid = group_ok & (counts > 0)
        outs.append(value_col.gather(src, valid))
    return outs, counts
