"""Datetime kernels — the TPU replacement for cuDF's datetime ops +
``com.nvidia.spark.rapids.jni.DateTimeRebase``-style Spark-exact semantics
(reference ``datetimeExpressions.scala`` 1170 LoC + ``DateUtils.scala``;
SURVEY §2.4 datetime family).

Layout: DATE = int32 days since 1970-01-01 (proleptic Gregorian), TIMESTAMP
= int64 microseconds since epoch UTC.  Civil-date conversions use Howard
Hinnant's branchless algorithms — pure integer arithmetic, fully vectorized
on the VPU; no per-row host work anywhere."""

from __future__ import annotations

import numpy as np

MICROS_PER_SEC = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SEC


def civil_from_days(xp, z):
    """days-since-epoch -> (year, month, day), elementwise int32."""
    z = z.astype(xp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097                                  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)         # [0, 365]
    mp = (5 * doy + 2) // 153                               # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                       # [1, 31]
    m = mp + xp.where(mp < 10, 3, -9)                       # [1, 12]
    y = y + (m <= 2)
    return y.astype(xp.int32), m.astype(xp.int32), d.astype(xp.int32)


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> days since epoch, elementwise."""
    y = y.astype(xp.int64) - (m <= 2)
    era = y // 400
    yoe = y - era * 400                                     # [0, 399]
    mp = (m.astype(xp.int64) + xp.where(m > 2, -3, 9))      # [0, 11]
    doy = (153 * mp + 2) // 5 + d.astype(xp.int64) - 1      # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy           # [0, 146096]
    return (era * 146097 + doe - 719468).astype(xp.int32)


def day_of_year(xp, days):
    y, _, _ = civil_from_days(xp, days)
    jan1 = days_from_civil(xp, y, xp.full_like(y, 1), xp.full_like(y, 1))
    return (days.astype(xp.int32) - jan1 + 1).astype(xp.int32)


def day_of_week(xp, days):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""
    return (((days.astype(xp.int64) + 4) % 7) + 1).astype(xp.int32)


def weekday(xp, days):
    """Spark weekday: 0 = Monday ... 6 = Sunday."""
    return ((days.astype(xp.int64) + 3) % 7).astype(xp.int32)


def week_of_year(xp, days):
    """ISO 8601 week number (Spark weekofyear)."""
    # ISO week of d = (dayofyear(thursday of d's week) - 1) / 7 + 1
    dow_mon0 = (days.astype(xp.int64) + 3) % 7          # 0=Mon
    thursday = days.astype(xp.int64) + (3 - dow_mon0)
    return ((day_of_year(xp, thursday) - 1) // 7 + 1).astype(xp.int32)


def is_leap_year(xp, y):
    y = y.astype(xp.int64)
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


_DAYS_IN_MONTH = np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                          dtype=np.int32)


def days_in_month(xp, y, m):
    m = xp.clip(m.astype(xp.int32), 1, 12)  # callers validate range separately
    base = xp.asarray(_DAYS_IN_MONTH)[m - 1]
    feb = (m == 2) & is_leap_year(xp, y)
    return xp.where(feb, 29, base).astype(xp.int32)


def last_day(xp, days):
    y, m, _ = civil_from_days(xp, days)
    return days_from_civil(xp, y, m, days_in_month(xp, y, m))


def add_months(xp, days, num):
    """Spark add_months: clamps day-of-month to the target month's end,
    preserving 'last day stays last day' is NOT Spark behavior — Spark
    clamps only when overflowing (e.g. Jan 31 + 1 month = Feb 28)."""
    y, m, d = civil_from_days(xp, days)
    months0 = y.astype(xp.int64) * 12 + (m.astype(xp.int64) - 1) + \
        num.astype(xp.int64)
    ny = months0 // 12
    nm = months0 % 12 + 1
    nd = xp.minimum(d.astype(xp.int32), days_in_month(xp, ny, nm))
    return days_from_civil(xp, ny, nm, nd)


def months_between(xp, ts1, ts2, round8: bool = True):
    """Spark months_between over timestamps (micros).  If both dates are the
    same day-of-month or both last days, fractional part is 0; else based on
    31-day months, time-of-day included."""
    d1 = xp.floor_divide(ts1, MICROS_PER_DAY).astype(xp.int32)
    d2 = xp.floor_divide(ts2, MICROS_PER_DAY).astype(xp.int32)
    y1, m1, dd1 = civil_from_days(xp, d1)
    y2, m2, dd2 = civil_from_days(xp, d2)
    whole = (y1.astype(xp.float64) - y2) * 12 + (m1 - m2)
    last1 = days_in_month(xp, y1, m1) == dd1
    last2 = days_in_month(xp, y2, m2) == dd2
    same = (dd1 == dd2) | (last1 & last2)
    sec1 = (ts1 - d1.astype(xp.int64) * MICROS_PER_DAY).astype(xp.float64) \
        / MICROS_PER_SEC
    sec2 = (ts2 - d2.astype(xp.int64) * MICROS_PER_DAY).astype(xp.float64) \
        / MICROS_PER_SEC
    frac = ((dd1 - dd2).astype(xp.float64) * 86400 + (sec1 - sec2)) \
        / (31.0 * 86400)
    out = whole + xp.where(same, 0.0, frac)
    if round8:
        out = xp.round(out * 1e8) / 1e8
    return out


def trunc_date(xp, days, unit: str):
    """truncate a date to year/quarter/month/week."""
    y, m, d = civil_from_days(xp, days)
    one = xp.full_like(y, 1)
    u = unit.lower()
    if u in ("year", "yyyy", "yy"):
        return days_from_civil(xp, y, one, one)
    if u in ("quarter",):
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(xp, y, qm, one)
    if u in ("month", "mon", "mm"):
        return days_from_civil(xp, y, m, one)
    if u in ("week",):
        return (days.astype(xp.int64) - weekday(xp, days)).astype(xp.int32)
    raise ValueError(f"unsupported trunc unit {unit!r}")


def timestamp_to_date_days(xp, micros):
    return xp.floor_divide(micros, MICROS_PER_DAY).astype(xp.int32)


def time_of_day_micros(xp, micros):
    return micros - xp.floor_divide(micros, MICROS_PER_DAY) * MICROS_PER_DAY


def extract_hour(xp, micros):
    tod = time_of_day_micros(xp, micros)
    return (tod // (3600 * MICROS_PER_SEC)).astype(xp.int32)


def extract_minute(xp, micros):
    tod = time_of_day_micros(xp, micros)
    return ((tod // (60 * MICROS_PER_SEC)) % 60).astype(xp.int32)


def extract_second(xp, micros):
    tod = time_of_day_micros(xp, micros)
    return ((tod // MICROS_PER_SEC) % 60).astype(xp.int32)


def extract_micros(xp, micros):
    return (time_of_day_micros(xp, micros) % MICROS_PER_SEC).astype(xp.int64)


# ---------------------------------------------------------------------------
# Device-side formatting / parsing of fixed-width patterns
# ---------------------------------------------------------------------------

# token -> (field id, width)
_TOKENS = {
    "yyyy": ("year", 4), "MM": ("month", 2), "dd": ("day", 2),
    "HH": ("hour", 2), "mm": ("minute", 2), "ss": ("second", 2),
    "SSSSSS": ("micros", 6), "SSS": ("millis", 3),
}


def compile_format(fmt: str):
    """Compile a Spark datetime pattern into (template_bytes, fields) where
    fields = [(field_id, start, width)].  Returns None for patterns with
    variable-width or unsupported tokens (callers tag those host-side)."""
    out_bytes = bytearray()
    fields = []
    i = 0
    while i < len(fmt):
        matched = False
        for tok, (fid, width) in sorted(_TOKENS.items(),
                                        key=lambda kv: -len(kv[0])):
            if fmt.startswith(tok, i):
                fields.append((fid, len(out_bytes), width))
                out_bytes.extend(b"0" * width)
                i += len(tok)
                matched = True
                break
        if matched:
            continue
        ch = fmt[i]
        if ch.isalpha():
            return None  # unsupported/variable-width token
        if ch == "'":
            j = fmt.find("'", i + 1)
            if j < 0:
                return None
            out_bytes.extend(fmt[i + 1:j].encode())
            i = j + 1
            continue
        out_bytes.extend(ch.encode())
        i += 1
    return bytes(out_bytes), fields


def _field_values(xp, micros):
    days = timestamp_to_date_days(xp, micros)
    y, m, d = civil_from_days(xp, days)
    return {
        "year": y.astype(xp.int64), "month": m.astype(xp.int64),
        "day": d.astype(xp.int64), "hour": extract_hour(xp, micros).astype(xp.int64),
        "minute": extract_minute(xp, micros).astype(xp.int64),
        "second": extract_second(xp, micros).astype(xp.int64),
        "micros": extract_micros(xp, micros),
        "millis": extract_micros(xp, micros) // 1000,
    }


def format_timestamp(xp, micros, fmt: str, out_width: int):
    """Format micros with a compiled fixed-width pattern into a byte matrix.
    Returns (chars[rows, out_width], lengths)."""
    compiled = compile_format(fmt)
    if compiled is None:
        raise ValueError(f"format {fmt!r} is not device-compilable")
    template, fields = compiled
    rows = micros.shape[0]
    tmpl = np.frombuffer(template, dtype=np.uint8)
    width = max(out_width, len(template))
    base = np.zeros(width, dtype=np.uint8)
    base[:len(tmpl)] = tmpl
    chars = xp.broadcast_to(xp.asarray(base), (rows, width))
    vals = _field_values(xp, micros)
    cols = []
    for j in range(width):
        col = chars[:, j]
        for fid, start, fwidth in fields:
            if start <= j < start + fwidth:
                digit_pos = start + fwidth - 1 - j  # digits right-aligned
                v = (vals[fid] // (10 ** digit_pos)) % 10
                col = (v + ord("0")).astype(xp.uint8)
        cols.append(col)
    out = xp.stack(cols, axis=1)
    lengths = xp.full((rows,), len(template), dtype=xp.int32)
    return out, lengths


def parse_timestamp(xp, chars, lens, fmt: str):
    """Parse byte-matrix strings against a fixed-width pattern.  Returns
    (micros, ok)."""
    compiled = compile_format(fmt)
    if compiled is None:
        raise ValueError(f"format {fmt!r} is not device-parseable")
    template, fields = compiled
    rows, width = chars.shape
    tlen = len(template)
    ok = lens == tlen
    # literal separator bytes must match
    tmpl = np.frombuffer(template, dtype=np.uint8)
    field_mask = np.zeros(tlen, dtype=bool)
    for _fid, start, fwidth in fields:
        field_mask[start:start + fwidth] = True
    # absent date fields default to the 1970-01-01 epoch base (Spark)
    present = {f[0] for f in fields}
    defaults = {"year": 1970, "month": 1, "day": 1}
    vals = {k: xp.full((rows,), defaults.get(k, 0) if k not in present else 0,
                       dtype=xp.int64)
            for k in ("year", "month", "day", "hour", "minute", "second",
                      "micros", "millis")}
    for j in range(min(tlen, width)):
        c = chars[:, j].astype(xp.int64)
        if field_mask[j]:
            is_digit = (c >= ord("0")) & (c <= ord("9"))
            ok = ok & is_digit
        else:
            ok = ok & (c == int(tmpl[j]))
    for fid, start, fwidth in fields:
        v = xp.zeros((rows,), dtype=xp.int64)
        for j in range(start, min(start + fwidth, width)):
            v = v * 10 + (chars[:, j].astype(xp.int64) - ord("0"))
        vals[fid] = v
    ok = ok & (vals["month"] >= 1) & (vals["month"] <= 12) if \
        any(f[0] == "month" for f in fields) else ok
    if any(f[0] == "day" for f in fields):
        ok = ok & (vals["day"] >= 1) & \
            (vals["day"] <= days_in_month(xp, vals["year"],
                                          xp.maximum(vals["month"], 1)))
    days = days_from_civil(xp, vals["year"], xp.maximum(vals["month"], 1),
                           xp.maximum(vals["day"], 1))
    micros = days.astype(xp.int64) * MICROS_PER_DAY \
        + vals["hour"] * 3600 * MICROS_PER_SEC \
        + vals["minute"] * 60 * MICROS_PER_SEC \
        + vals["second"] * MICROS_PER_SEC \
        + vals["micros"] + vals["millis"] * 1000
    ok = ok & (vals["hour"] < 24) & (vals["minute"] < 60) & \
        (vals["second"] < 60)
    return micros, ok
