"""128-bit decimal arithmetic as chunked int64 XLA programs.

The reference aggregates decimal128 on device by splitting each value
into four int32 chunks, summing the chunks into int64 accumulators
(which cannot overflow below 2^31 rows per group), and carry-merging the
chunk sums back into a 128-bit result with an overflow check
(``AggregateFunctions.scala:902`` ``Aggregation128Utils.extractInt32Chunk``
+ JNI kernels).  This module is that design expressed as jax-traceable
int64 ops: everything here runs under jit on the MXU host's VPU lanes —
no Python ints, no host round trips.

Representation: a decimal128 unscaled value is a pair ``(lo, hi)`` of
int64 words (lo = low 64 bits as a raw bit pattern, hi = high 64 bits,
two's complement) — matching ``DeviceColumn.data``/``.aux``.

All functions take ``xp`` (the array namespace) first so they stay
backend-agnostic and trivially testable against numpy.
"""

from __future__ import annotations

_M32 = 0xFFFFFFFF
_MIN64 = -(1 << 63)


def split_chunks(xp, lo, hi):
    """(lo, hi) -> four int64 arrays holding the 32-bit chunks c0..c3.
    c0..c2 are the unsigned values of bits 0-31 / 32-63 / 64-95; c3 is
    the SIGNED top chunk (bits 96-127), carrying the value's sign."""
    c0 = lo & _M32
    c1 = (lo >> 32) & _M32
    c2 = hi & _M32
    c3 = hi >> 32              # arithmetic shift: signed top chunk
    return c0, c1, c2, c3


def sign_extend_lo(xp, lo):
    """hi word for a long-backed (64-bit) unscaled value."""
    return lo >> 63


def dec_words(xp, col):
    """(lo, hi) int64 word pair for a decimal DeviceColumn — the ONE
    place that knows the aux-column contract (aux carries the high word
    only for 128-bit-backed columns; long-backed values sign-extend)."""
    lo = col.data.astype(xp.int64)
    dt = col.dtype
    if getattr(dt, "is_long_backed", True) is False and col.aux is not None:
        return lo, col.aux
    return lo, sign_extend_lo(xp, lo)


def carry_merge(xp, s0, s1, s2, s3):
    """Merge four int64 chunk sums back into (lo, hi, overflow).

    Each s_i may exceed 32 bits (it is a SUM of 32-bit chunks) and may be
    negative (top chunks are signed).  Standard ripple-carry with
    arithmetic shifts propagates both positive carries and borrows.
    ``overflow`` flags results outside the signed 128-bit range."""
    t0 = s0 & _M32
    c = s0 >> 32
    u1 = s1 + c
    t1 = u1 & _M32
    c = u1 >> 32
    u2 = s2 + c
    t2 = u2 & _M32
    c = u2 >> 32
    u3 = s3 + c
    t3 = u3 & _M32
    lo = t0 | (t1 << 32)
    hi = t2 | (t3 << 32)
    # the true top chunk u3 must equal the sign-extension the packed hi
    # word implies, else the value left the 128-bit range
    overflow = u3 != (hi >> 32)
    return lo, hi, overflow


def cmp_unsigned_gt(xp, a, b):
    """a > b comparing int64 bit patterns as UNSIGNED 64-bit."""
    return (a ^ _MIN64) > (b ^ _MIN64)


def gt_const(xp, lo, hi, const: int):
    """(hi, lo) > const, const a Python int within signed 128-bit."""
    chi, clo = const >> 64, const & ((1 << 64) - 1)
    clo_signed = clo - (1 << 64) if clo >= (1 << 63) else clo
    return (hi > chi) | ((hi == chi) & cmp_unsigned_gt(xp, lo, clo_signed))


def lt_const(xp, lo, hi, const: int):
    chi, clo = const >> 64, const & ((1 << 64) - 1)
    clo_signed = clo - (1 << 64) if clo >= (1 << 63) else clo
    return (hi < chi) | ((hi == chi) & cmp_unsigned_gt(xp, clo_signed, lo))


def out_of_bounds(xp, lo, hi, precision: int):
    """|value| exceeds the given decimal precision (10^p - 1)."""
    bound = 10 ** precision - 1
    return gt_const(xp, lo, hi, bound) | lt_const(xp, lo, hi, -bound)


def neg128(xp, lo, hi):
    """Two's-complement negation of (lo, hi)."""
    nlo = (~lo) + 1
    borrow = (nlo == 0) & (lo != 0)   # ~lo+1 wrapped -> carry into hi
    # carry exists only when lo == 0 (then ~lo+1 wraps to 0 with carry)
    carry = xp.where(lo == 0, 1, 0)
    nhi = (~hi) + carry
    del borrow
    return nlo, nhi


def abs128(xp, lo, hi):
    """(|value| as (lo, hi), sign) — sign is -1/+1 int64."""
    neg = hi < 0
    nlo, nhi = neg128(xp, lo, hi)
    alo = xp.where(neg, nlo, lo)
    ahi = xp.where(neg, nhi, hi)
    sign = xp.where(neg, -1, 1)
    return alo, ahi, sign


def mul_small(xp, lo, hi, m: int):
    """(lo, hi) * m for a small non-negative Python int (m < 2^31),
    returning (lo, hi, overflow).  Chunked schoolbook: each 32-bit chunk
    times m fits int64; ripple the carries."""
    c0, c1, c2, c3 = split_chunks(xp, lo, hi)
    return carry_merge(xp, c0 * m, c1 * m, c2 * m, c3 * m)


def divmod_nonneg_small(xp, lo, hi, d):
    """(lo, hi) // d and remainder, value NON-NEGATIVE, d a positive
    int64 array (or scalar) < 2^31.  Chunked long division, top chunk
    first: the running remainder stays < d < 2^31, so r*2^32 + chunk
    fits int64."""
    c0, c1, c2, c3 = split_chunks(xp, lo, hi)
    q3, r = xp.divmod(c3, d)
    cur = (r << 32) | c2
    q2, r = xp.divmod(cur, d)
    cur = (r << 32) | c1
    q1, r = xp.divmod(cur, d)
    cur = (r << 32) | c0
    q0, r = xp.divmod(cur, d)
    qlo = q0 | (q1 << 32)
    qhi = q2 | (q3 << 32)
    return qlo, qhi, r


def add128(xp, alo, ahi, blo, bhi):
    """Signed 128-bit a + b -> (lo, hi, overflow)."""
    a0, a1, a2, a3 = split_chunks(xp, alo, ahi)
    b0, b1, b2, b3 = split_chunks(xp, blo, bhi)
    return carry_merge(xp, a0 + b0, a1 + b1, a2 + b2, a3 + b3)


def sub128(xp, alo, ahi, blo, bhi):
    """Signed 128-bit a - b -> (lo, hi, overflow)."""
    a0, a1, a2, a3 = split_chunks(xp, alo, ahi)
    b0, b1, b2, b3 = split_chunks(xp, blo, bhi)
    return carry_merge(xp, a0 - b0, a1 - b1, a2 - b2, a3 - b3)


def _split16(xp, lo, hi):
    """Eight 16-bit chunks (int64 each) — the multiply representation:
    16x16 partial products stay < 2^32, so a column of eight partials
    plus carry fits int64 with room to spare."""
    m16 = 0xFFFF
    return [(lo >> s) & m16 for s in (0, 16, 32, 48)] + \
           [(hi >> s) & m16 for s in (0, 16, 32, 48)]


def mul128(xp, alo, ahi, blo, bhi):
    """Signed 128-bit a * b -> (lo, hi, overflow).  Schoolbook over
    16-bit chunks on magnitudes; overflow when any partial product lands
    at or above chunk 8, or the magnitude exceeds the signed range."""
    alo_m, ahi_m, sa = abs128(xp, alo, ahi)
    blo_m, bhi_m, sb = abs128(xp, blo, bhi)
    a = _split16(xp, alo_m, ahi_m)
    b = _split16(xp, blo_m, bhi_m)
    m16 = 0xFFFF
    cols = [xp.zeros_like(alo) for _ in range(8)]
    high_spill = xp.zeros_like(alo, dtype=bool)
    for i in range(8):
        for j in range(8):
            p = a[i] * b[j]
            k = i + j
            if k < 8:
                cols[k] = cols[k] + p
            else:
                high_spill = high_spill | (p != 0)
    # ripple 16-bit carries (columns hold sums of <=8 products < 2^35)
    out = []
    carry = xp.zeros_like(alo)
    for k in range(8):
        v = cols[k] + carry
        out.append(v & m16)
        carry = v >> 16
    high_spill = high_spill | (carry != 0)
    lo = out[0] | (out[1] << 16) | (out[2] << 32) | (out[3] << 48)
    hi = out[4] | (out[5] << 16) | (out[6] << 32) | (out[7] << 48)
    # magnitude must fit signed 127 bits (hi's sign bit clear), except
    # the exact value -2^127 which we simply flag as overflow too (it
    # cannot be a valid decimal anyway: 10^38 < 2^127)
    ovf = high_spill | (hi < 0)
    neg = (sa * sb) < 0
    nlo, nhi = neg128(xp, lo, hi)
    return (xp.where(neg, nlo, lo), xp.where(neg, nhi, hi), ovf)


def rescale_div_round(xp, lo, hi, mul: int, d):
    """Signed ((lo, hi) * mul) / d with HALF_UP rounding, WITHOUT the
    128-bit intermediate overflowing when |value| * mul exceeds 2^127
    (decimal AVG: sum x 10^4 can top 128 bits even when the quotient is
    tiny).  Divides first and propagates the remainder:

        (v * mul) / d  =  (v // d) * mul  +  (v % d) * mul / d

    where v % d < d < 2^31 keeps the second term in int64.  Returns
    (lo, hi, overflow) — overflow only when the RESULT leaves the
    128-bit range."""
    alo, ahi, sign = abs128(xp, lo, hi)
    qlo, qhi, r1 = divmod_nonneg_small(xp, alo, ahi, d)
    qlo, qhi, ovf = mul_small(xp, qlo, qhi, mul)
    t = r1 * mul
    q2, r2 = xp.divmod(t, d)
    add = q2 + xp.where((2 * r2) >= d, 1, 0)
    c0, c1, c2, c3 = split_chunks(xp, qlo, qhi)
    a0, a1, _, _ = split_chunks(xp, add, xp.zeros_like(add))
    rlo, rhi, ovf2 = carry_merge(xp, c0 + a0, c1 + a1, c2, c3)
    nlo, nhi = neg128(xp, rlo, rhi)
    return (xp.where(sign < 0, nlo, rlo),
            xp.where(sign < 0, nhi, rhi),
            ovf | ovf2)


def div_round_half_up(xp, lo, hi, d):
    """Signed (lo, hi) / d with HALF_UP rounding (Spark decimal
    division for AVG), d positive int64 < 2^31.  Returns (lo, hi)."""
    alo, ahi, sign = abs128(xp, lo, hi)
    qlo, qhi, r = divmod_nonneg_small(xp, alo, ahi, d)
    bump = (2 * r) >= d
    blo = qlo + xp.where(bump, 1, 0)
    carry = xp.where(cmp_unsigned_gt(xp, qlo, blo), 1, 0)  # wrapped
    bhi = qhi + carry
    nlo, nhi = neg128(xp, blo, bhi)
    return (xp.where(sign < 0, nlo, blo),
            xp.where(sign < 0, nhi, bhi))


def scale_up(xp, lo, hi, k: int):
    """(lo, hi) * 10^k via <=9-digit mul_small steps (each multiplier
    stays < 2^31).  Returns (lo, hi, overflow)."""
    ovf = xp.zeros_like(lo, dtype=bool)
    while k > 0:
        step = min(k, 9)
        lo, hi, o = mul_small(xp, lo, hi, 10 ** step)
        ovf = ovf | o
        k -= step
    return lo, hi, ovf


def scale_down_half_up(xp, lo, hi, k: int):
    """(lo, hi) / 10^k with HALF_UP rounding.  HALF_UP over a k-digit
    drop depends only on the FIRST dropped digit, so truncating k-1
    digits (in <=9-digit steps on the magnitude) then one half-up
    divide-by-10 is exact for any k."""
    if k <= 0:
        return lo, hi
    alo, ahi, sign = abs128(xp, lo, hi)
    rem = k - 1
    while rem > 0:
        step = min(rem, 9)
        alo, ahi, _r = divmod_nonneg_small(xp, alo, ahi, 10 ** step)
        rem -= step
    qlo, qhi, r = divmod_nonneg_small(xp, alo, ahi, 10)
    bump = r >= 5
    blo = qlo + xp.where(bump, 1, 0)
    carry = xp.where(cmp_unsigned_gt(xp, qlo, blo), 1, 0)  # lo wrapped
    bhi = qhi + carry
    nlo, nhi = neg128(xp, blo, bhi)
    return (xp.where(sign < 0, nlo, blo),
            xp.where(sign < 0, nhi, bhi))
