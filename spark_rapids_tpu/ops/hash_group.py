"""Exact hash-based group ids — the TPU-native replacement for cuDF's hash
groupby (reference ``Table.groupBy`` device hash tables; SURVEY §2.10) on
the path where we previously used sort-based dense ranks.

Group-by does not need *ordered* ranks, only exact ids with
``equal keys ⇔ equal id``.  A sort costs O(n log n) with a big constant in
XLA; this kernel is O(n) per probe round:

1. mix all key words into a 32-bit hash per row (murmur3-style);
2. leader election into a power-of-two table of 2×capacity slots:
   unresolved rows scatter-min their row index into ``table[slot]``;
3. every row compares its full key (all key words — exact, not hashed)
   against the slot owner's; equal rows adopt the owner as their group
   representative, the rest linear-probe the next slot (``lax.while_loop``);
   same-key rows always move in lockstep, so each key resolves exactly once.
4. representatives get dense ids by cumsum over the row order
   (first-occurrence order, deterministic).

Dead (padding) rows get id == capacity: XLA drops out-of-bounds scatters,
and every caller masks their contributions.

The numpy backend keeps the independent sort-based path (ops/ranks.py), so
host-vs-device comparisons exercise two different grouping algorithms.
"""

from __future__ import annotations

import numpy as np

from ..columnar.column import DeviceColumn
from .ranks import column_sort_keys, dense_rank_columns


def _hash_words(jnp, keys):
    """murmur3-style mix of the rows' key words into uint32."""
    h = jnp.full(keys[0].shape[0], np.uint32(0x9747b28c), dtype=jnp.uint32)
    for k in keys:
        words = [k.astype(jnp.uint32)]
        if k.dtype.itemsize == 8:
            words.append((k >> 32).astype(jnp.uint32))
        for w in words:
            w = w * np.uint32(0xcc9e2d51)
            w = (w << 15) | (w >> 17)
            w = w * np.uint32(0x1b873593)
            h = h ^ w
            h = (h << 13) | (h >> 19)
            h = h * np.uint32(5) + np.uint32(0xe6546b64)
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85ebca6b)
    h = h ^ (h >> 13)
    return h


#: compact-code fast path: product of per-word value ranges must fit this
#: many codes.  64Ki keeps every intermediate product < 2^32 (no int64
#: overflow) and the remap tables cache-resident.
_COMPACT_MAX_CODES = 1 << 16


def _compact_prelude(jnp, col_words, row_mask):
    """Range-compaction feasibility + per-row codes (cheap, always run).
    ``col_words``: per key column, ``(null_flags_bool, [int64 words])`` —
    computed once by the caller and shared with the fallback kernel.

    Treats every key word as a mixed-radix digit:
    ``code = Σ (word_i - min_i) * stride_i`` where ``stride`` is the
    running product of the per-word ranges.  Null flags are {0,1} digits
    whose range comes from a boolean ``any`` (4-8x cheaper than an int64
    min/max pass).  Returns ``(ok, codes)`` — ``ok`` is a traced scalar
    that is True iff every range is sane and the total code space fits
    ``_COMPACT_MAX_CODES``; ``codes`` are exact collision-free group codes
    when ``ok`` holds (garbage otherwise — callers must gate on ``ok``
    via ``lax.cond``).

    Cost: two fused reductions per data word plus one elementwise pass —
    no serial probe rounds.  This is the common case for real group-bys
    (low-cardinality ints/dates/bools/flags); wide ranges (floats,
    strings, ids) fail ``ok`` and take the fallback kernel instead.
    """
    B = _COMPACT_MAX_CODES
    cap = int(row_mask.shape[0])
    any_live = jnp.any(row_mask)
    imax = np.int64(np.iinfo(np.int64).max)
    imin = np.int64(np.iinfo(np.int64).min)
    one = jnp.asarray(1, dtype=jnp.int64)
    ok = jnp.asarray(True)
    p = one
    codes = jnp.zeros(cap, dtype=jnp.int64)

    def add_digit(digit, r, okd):
        nonlocal ok, p, codes
        ok = ok & okd
        codes = codes + digit * p
        p_next = p * jnp.clip(r, 1, B)  # clip: bounded even pre-check
        ok = ok & (p_next <= B)
        p = jnp.where(ok, p_next, one)

    for col_nulls, words in col_words:
        nulls = col_nulls & row_mask
        has_null = jnp.any(nulls)
        # null digit: 1 for null rows; range 2 only when nulls exist
        add_digit(nulls.astype(jnp.int64),
                  jnp.where(has_null, 2, 1).astype(jnp.int64),
                  jnp.asarray(True))
        for w in words:
            wmin = jnp.min(jnp.where(row_mask, w, imax))
            wmax = jnp.max(jnp.where(row_mask, w, imin))
            r = jnp.where(any_live, wmax - wmin + 1, one)
            # r >= 1 also rejects int64 wraparound (a true range near 2^64
            # wraps to a value <= 0, never to a small positive)
            add_digit(w - wmin, r, (r >= 1) & (r <= B))
    return ok, codes


def _first_occurrence_ids(jnp, slot_of_row, row_mask, table_size):
    """Dense first-occurrence group ids from any collision-free per-row
    slot assignment (compact codes, sorted-order ranks, ...).

    One scatter-min finds each slot's first row; a row-order cumsum over
    "this row IS its slot's first" numbers the groups in first-occurrence
    order — no sort needed (an argsort-based remap here doubled TPU
    compile time; sorts are the expensive op for the remote compiler).
    ``slot_of_row`` must be in [0, table_size) for live rows."""
    cap = int(row_mask.shape[0])
    row_idx = jnp.arange(cap, dtype=jnp.int32)
    slot_live = jnp.where(row_mask, slot_of_row,
                          table_size).astype(jnp.int32)
    first_row = jnp.full(table_size, cap, dtype=jnp.int32
                         ).at[slot_live].min(row_idx)
    fr_of_row = first_row[jnp.clip(slot_live, 0, table_size - 1)]
    is_first = row_mask & (fr_of_row == row_idx)
    dense = jnp.cumsum(is_first.astype(jnp.int64)) - 1
    ids = dense[jnp.clip(fr_of_row, 0, cap - 1)]
    return jnp.where(row_mask, ids, cap - 1)


def _compact_finish(jnp, codes, row_mask):
    """Exact dense first-occurrence group ids from in-range codes —
    bit-identical to the probing kernel's numbering, so either branch of
    the ``lax.cond`` agrees with the host path."""
    B = _COMPACT_MAX_CODES
    return _first_occurrence_ids(jnp, jnp.clip(codes, 0, B), row_mask, B)


def _probe_beats_sort(jnp) -> bool:
    """Trace-time fallback choice for codes that don't compact: the
    leader-election probe loop wins on XLA CPU (0.55s vs ~1.5s sort-based
    at 4M rows), but serial while_loop rounds of scatters are catastrophic
    on TPU (measured 4.7s at 4M rows vs 0.45s for the sort-based path —
    lax.sort is a tuned TPU kernel, the probe loop is not)."""
    import jax
    return jax.default_backend() == "cpu"


def _sorted_ids(jnp, keys, row_mask):
    """Exact first-occurrence-dense group ids via ONE variadic lex sort —
    the high-cardinality fallback on backends where sorts beat probe
    rounds (TPU).  Identical output to the probe kernel: dense ids in
    [0, n_groups) in first-occurrence order, dead rows parked at cap-1."""
    from .ranks import _ranks_from_lex, lex_sort
    cap = int(row_mask.shape[0])
    # liveness leads the sort key: live rows sort first, so live ranks are
    # exactly [0, n_groups).  bool (not int64): a radix-path sort then
    # pays ONE pass for this flag instead of 64
    sort_keys = [~row_mask] + list(keys)
    perm, skeys = lex_sort(jnp, sort_keys)
    rank = _ranks_from_lex(jnp, perm, skeys)
    # remap sorted-key rank order -> first-occurrence order (the probe
    # kernel's order, and the host path's) without a second sort
    return _first_occurrence_ids(jnp, jnp.clip(rank, 0, cap), row_mask, cap)


def _device_ids(jnp, cols, row_mask, make_probe):
    """Shared device-path scaffolding for :func:`group_ids` /
    :func:`group_ids_small`: build each key word ONCE (shared by the
    compact prelude and the fallback), then dispatch
    ``lax.cond(compact_ok, compact, fallback)`` where the fallback is the
    caller's probe kernel on XLA CPU or the sorted kernel on TPU."""
    import jax
    col_words = [((~c.validity), column_sort_keys(jnp, c)) for c in cols]
    keys = [w for nulls, ws in col_words
            for w in (nulls.astype(jnp.int64), *ws)]
    compact_ok, compact_codes = _compact_prelude(jnp, col_words, row_mask)
    fallback = make_probe(keys) if _probe_beats_sort(jnp) else (
        lambda _: _sorted_ids(jnp, keys, row_mask))
    return jax.lax.cond(compact_ok,
                        lambda _: _compact_finish(jnp, compact_codes,
                                                  row_mask),
                        fallback, None)


def group_ids(xp, cols, row_mask):
    """int64[cap] exact group ids over the key columns.

    Live rows with equal keys (nulls equal nulls, Spark semantics — the
    validity word is part of the key) share one id; ids are dense in
    ``[0, n_groups)`` in first-occurrence order on BOTH backends (so host
    and device agree on group order bit-for-bit).  Dead rows get
    id == cap - 1, which is provably unused by live groups whenever dead
    rows exist (n_groups <= cap - n_dead).
    """
    cap_n = int(row_mask.shape[0])
    if xp.__name__ == "numpy":
        # independent sort-based host path, remapped from sorted-key order
        # to the same first-occurrence order the device hash table produces
        rank = dense_rank_columns(xp, cols, row_mask)
        row_idx = np.arange(cap_n, dtype=np.int64)
        first_row = np.full(cap_n, cap_n, dtype=np.int64)
        live = np.asarray(row_mask)
        np.minimum.at(first_row, rank[live], row_idx[live])
        order = np.argsort(first_row, kind="stable")
        remap = np.empty(cap_n, dtype=np.int64)
        remap[order] = np.arange(cap_n, dtype=np.int64)
        ids = remap[rank]
        return np.where(live, ids, cap_n - 1)
    import jax
    import jax.numpy as jnp

    cap = int(row_mask.shape[0])

    def make_probe(keys):
        return lambda _: _probe_impl(keys)

    def _probe_impl(keys):
        M = 1 << (max(2 * cap, 16) - 1).bit_length()
        mask_m = np.uint32(M - 1)
        h = _hash_words(jnp, keys)
        row_idx = jnp.arange(cap, dtype=jnp.int32)
        sentinel = jnp.asarray(cap, dtype=jnp.int32)
        # one [cap, k] matrix so the per-round owner compare is a single
        # row gather instead of k scattered 1-D gathers
        key_mat = jnp.stack(keys, axis=1)

        def cond(state):
            _table, rep, off, rounds = state
            return jnp.any(rep < 0) & (rounds < M)

        def body(state):
            table, rep, off, rounds = state
            unresolved = rep < 0
            slot = ((h + off) & mask_m).astype(jnp.int32)
            cand = jnp.where(unresolved, row_idx, sentinel)
            table = table.at[slot].min(cand)
            owner = table[slot]
            safe_owner = jnp.clip(owner, 0, cap - 1)
            eq = (owner < cap) & jnp.all(key_mat == key_mat[safe_owner],
                                         axis=1)
            newly = unresolved & eq
            rep = jnp.where(newly, owner, rep)
            off = jnp.where(unresolved & ~eq, off + np.uint32(1), off)
            return table, rep, off, rounds + 1

        table0 = jnp.full(M, cap, dtype=jnp.int32)
        # dead rows resolve to themselves immediately (masked by callers)
        rep0 = jnp.where(row_mask, -1, row_idx)
        off0 = jnp.zeros(cap, dtype=jnp.uint32)
        _table, rep, _off, _r = jax.lax.while_loop(
            cond, body, (table0, rep0, off0, jnp.asarray(0, dtype=jnp.int32)))

        # defensive: the M-round bound guarantees resolution (a cohort
        # visits every slot within M probes); if that invariant ever broke,
        # making the row its own group keeps results mergeable instead of
        # corrupting them
        rep = jnp.where(rep < 0, row_idx, rep)

        is_rep = row_mask & (rep == row_idx)
        dense = jnp.cumsum(is_rep.astype(jnp.int64)) - 1
        ids = dense[jnp.clip(rep, 0, cap - 1)]
        return jnp.where(row_mask, ids, cap - 1)

    return _device_ids(jnp, cols, row_mask, make_probe)


def group_ids_small(xp, cols, row_mask, expected_groups: int):
    """Speculative small-table variant of :func:`group_ids`.

    The exact kernel's leader-election table is sized 2x capacity (16M
    slots for an 8M-row batch) — correct for any cardinality but ~60% of
    a fused aggregate's runtime.  When the speculation layer already
    predicts ``expected_groups`` (<= the group-table size), a table of
    ``4 * expected_groups`` slots with a BOUNDED probe suffices; rows
    still unresolved when the bound hits report ``expected_groups`` extra
    groups, which makes the observed count exceed any speculation <= it —
    the deferred-validation re-run then takes the exact path.  So the
    fast path is exact whenever it reports success, and mis-speculation
    (too many distinct keys OR pathological clustering) is detected by
    the SAME group-count check that guards table sizing.
    """
    cap = int(row_mask.shape[0])
    if xp.__name__ == "numpy":  # host path has no table to size
        return group_ids(xp, cols, row_mask)
    import jax
    import jax.numpy as jnp

    def make_probe(keys):
        return lambda _: _probe_impl(keys)

    def _probe_impl(keys):
        M = 1 << (max(4 * int(expected_groups), 64) - 1).bit_length()
        M2 = min(M, 1 << (max(2 * cap, 16) - 1).bit_length())
        max_rounds = min(M2, 64)
        mask_m = np.uint32(M2 - 1)
        h = _hash_words(jnp, keys)
        row_idx = jnp.arange(cap, dtype=jnp.int32)
        sentinel = jnp.asarray(cap, dtype=jnp.int32)
        key_mat = jnp.stack(keys, axis=1)

        def cond(state):
            _table, rep, off, rounds = state
            return jnp.any(rep < 0) & (rounds < max_rounds)

        def body(state):
            table, rep, off, rounds = state
            unresolved = rep < 0
            slot = ((h + off) & mask_m).astype(jnp.int32)
            cand = jnp.where(unresolved, row_idx, sentinel)
            table = table.at[slot].min(cand)
            owner = table[slot]
            # gather each slot WINNER's keys once into the tiny [M, k]
            # table, then compare rows against win_keys[slot] — streaming
            # reads of key_mat plus cache-resident table lookups, instead
            # of a cap-wide random gather into key_mat (the big kernel's
            # cost)
            win_keys = key_mat[jnp.clip(table, 0, cap - 1)]
            eq = (owner < cap) & jnp.all(key_mat == win_keys[slot], axis=1)
            newly = unresolved & eq
            rep = jnp.where(newly, owner, rep)
            off = jnp.where(unresolved & ~eq, off + np.uint32(1), off)
            return table, rep, off, rounds + 1

        table0 = jnp.full(M2, cap, dtype=jnp.int32)
        rep0 = jnp.where(row_mask, -1, row_idx)
        off0 = jnp.zeros(cap, dtype=jnp.uint32)
        _table, rep, _off, _r = jax.lax.while_loop(
            cond, body, (table0, rep0, off0, jnp.asarray(0, dtype=jnp.int32)))

        overflow = row_mask & (rep < 0)
        rep = jnp.where(rep < 0, row_idx, rep)
        is_rep = row_mask & (rep == row_idx)
        dense = jnp.cumsum(is_rep.astype(jnp.int64)) - 1
        ids = dense[jnp.clip(rep, 0, cap - 1)]
        # unresolved rows: burn the count so ng > any speculation <=
        # expected (their own ids are representatives already counted by
        # the cumsum; adding `expected_groups` to them guarantees the
        # overflow is visible in max(rank)+1 regardless of how many groups
        # resolved)
        ids = jnp.where(overflow, ids + int(expected_groups), ids)
        return jnp.where(row_mask, ids, cap - 1)

    # compact branch is EXACT (no burning needed): whenever the code space
    # fits, the ids are the true dense first-occurrence ids, and a count
    # above the speculated table size is caught by the same ng check.
    # The sorted fallback (TPU) is likewise exact — overflow burning only
    # applies to the bounded probe.
    return _device_ids(jnp, cols, row_mask, make_probe)
