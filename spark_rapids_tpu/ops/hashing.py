"""Spark-exact hash kernels (murmur3-x86-32 and xxhash64), vectorized for
TPU/VPU execution.  These mirror the semantics of the reference's JNI
``Hash`` kernels (``com.nvidia.spark.rapids.jni.Hash`` — murmur3/xxhash64
"Spark-compatible"; SURVEY §2.10): hash partitioning and the hash()/xxhash64()
SQL functions must produce the very values CPU Spark produces, or shuffles
and tests diverge.

All functions take/return arrays under either jnp or numpy (``xp``).
Integer ops are done in uint32/uint64 with wrapping arithmetic.
"""

from __future__ import annotations

import numpy as np

DEFAULT_SEED = 42

_C1 = np.uint32(0xcc9e2d51)
_C2 = np.uint32(0x1b873593)
_M5 = np.uint32(0xe6546b64)
_FX1 = np.uint32(0x85ebca6b)
_FX2 = np.uint32(0xc2b2ae35)


def _rotl32(xp, x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k1(xp, k1):
    k1 = (k1 * _C1).astype(xp.uint32)
    k1 = _rotl32(xp, k1, 15)
    return (k1 * _C2).astype(xp.uint32)


def _mix_h1(xp, h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(xp, h1, 13)
    return (h1 * np.uint32(5) + _M5).astype(xp.uint32)


def _fmix(xp, h1, length):
    h1 = h1 ^ xp.asarray(length, dtype=xp.uint32)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = (h1 * _FX1).astype(xp.uint32)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = (h1 * _FX2).astype(xp.uint32)
    return h1 ^ (h1 >> np.uint32(16))


def murmur3_int(xp, values_i32, seed_u32):
    """hashInt: values int32 array, seed uint32 array/scalar -> int32."""
    k1 = _mix_k1(xp, values_i32.astype(xp.uint32))
    h1 = _mix_h1(xp, xp.asarray(seed_u32, dtype=xp.uint32), k1)
    return _fmix(xp, h1, 4).astype(xp.int32)


def murmur3_long(xp, values_i64, seed_u32):
    if xp.__name__ != "numpy":
        # real chip: VPU Pallas kernel (bit-identical; validated against
        # the C++ oracle and this jnp path in tests/test_native.py)
        from .pallas_kernels import (murmur3_available, murmur3_long_pallas,
                                     on_tpu)
        if on_tpu() and values_i64.ndim == 1 and murmur3_available():
            return murmur3_long_pallas(values_i64, seed_u32)
    low = values_i64.astype(xp.uint32)
    high = (values_i64.astype(xp.uint64) >> np.uint64(32)).astype(xp.uint32)
    h1 = _mix_h1(xp, xp.asarray(seed_u32, dtype=xp.uint32), _mix_k1(xp, low))
    h1 = _mix_h1(xp, h1, _mix_k1(xp, high))
    return _fmix(xp, h1, 8).astype(xp.int32)


def murmur3_bytes(xp, chars_u8, lengths_i32, seed_u32):
    """Spark hashUnsafeBytes: 4-byte little-endian blocks, then the tail
    processed one SIGNED byte at a time (Spark-specific, not standard
    murmur3 tail)."""
    rows, width = chars_u8.shape
    nblocks = (lengths_i32 // 4).astype(xp.int32)
    h1 = xp.broadcast_to(xp.asarray(seed_u32, dtype=xp.uint32), (rows,)).astype(xp.uint32)
    c = chars_u8.astype(xp.uint32)
    max_blocks = width // 4
    for j in range(max_blocks):
        block = (c[:, 4 * j] | (c[:, 4 * j + 1] << np.uint32(8))
                 | (c[:, 4 * j + 2] << np.uint32(16))
                 | (c[:, 4 * j + 3] << np.uint32(24)))
        mixed = _mix_h1(xp, h1, _mix_k1(xp, block))
        h1 = xp.where(j < nblocks, mixed, h1)
    sbytes = chars_u8.astype(xp.int8).astype(xp.int32)
    for p in range(width):
        is_tail = (p >= 4 * nblocks) & (p < lengths_i32)
        mixed = _mix_h1(xp, h1, _mix_k1(xp, sbytes[:, p].astype(xp.uint32)))
        h1 = xp.where(is_tail, mixed, h1)
    return _fmix(xp, h1, lengths_i32.astype(xp.uint32)).astype(xp.int32)


# --------------------------------------------------------------------------
# xxhash64 (Spark XxHash64 expression semantics, seed 42)
# --------------------------------------------------------------------------

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(xp, x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _xx_fmix(xp, h):
    h = h ^ (h >> np.uint64(33))
    h = (h * _P2).astype(xp.uint64)
    h = h ^ (h >> np.uint64(29))
    h = (h * _P3).astype(xp.uint64)
    return h ^ (h >> np.uint64(32))


def _xx_process_long(xp, h, k):
    k = (k * _P2).astype(xp.uint64)
    k = _rotl64(xp, k, 31)
    k = (k * _P1).astype(xp.uint64)
    h = h ^ k
    h = _rotl64(xp, h, 27)
    return (h * _P1 + _P4).astype(xp.uint64)


def _xx_process_int(xp, h, k_u32):
    h = h ^ ((k_u32.astype(xp.uint64) * _P1).astype(xp.uint64))
    h = _rotl64(xp, h, 23)
    return (h * _P2 + _P3).astype(xp.uint64)


def _xx_process_byte(xp, h, b_u8):
    h = h ^ ((b_u8.astype(xp.uint64) * _P5).astype(xp.uint64))
    h = _rotl64(xp, h, 11)
    return (h * _P1).astype(xp.uint64)


def xxhash64_long(xp, values_i64, seed_u64):
    h = (xp.asarray(seed_u64, dtype=xp.uint64) + _P5 + np.uint64(8)).astype(xp.uint64)
    h = _xx_process_long(xp, h, values_i64.astype(xp.uint64))
    return _xx_fmix(xp, h).astype(xp.int64)


def xxhash64_int(xp, values_i32, seed_u64):
    # Spark promotes int-ish types to long before hashing
    return xxhash64_long(xp, values_i32.astype(xp.int64), seed_u64)


def xxhash64_bytes(xp, chars_u8, lengths_i32, seed_u64):
    """Standard XXH64 over each row's bytes (Spark hashUnsafeBytes for
    xxhash64): 32-byte stripes with 4 accumulators, then 8/4/1-byte tails."""
    rows, width = chars_u8.shape
    length = lengths_i32.astype(xp.uint64)
    seed = xp.broadcast_to(xp.asarray(seed_u64, dtype=xp.uint64), (rows,)).astype(xp.uint64)
    c = chars_u8.astype(xp.uint64)

    def get64(start_col):
        out = xp.zeros((rows,), dtype=xp.uint64)
        for b in range(8):
            col = start_col + b
            if col < width:
                out = out | (c[:, col] << np.uint64(8 * b))
        return out

    def get32(start_col):
        out = xp.zeros((rows,), dtype=xp.uint64)
        for b in range(4):
            col = start_col + b
            if col < width:
                out = out | (c[:, col] << np.uint64(8 * b))
        return out.astype(xp.uint32)

    n_stripes = (lengths_i32 // 32).astype(xp.int32)
    max_stripes = (width + 31) // 32

    v1 = (seed + _P1 + _P2).astype(xp.uint64)
    v2 = (seed + _P2).astype(xp.uint64)
    v3 = seed
    v4 = (seed - _P1).astype(xp.uint64)

    def round_(acc, inp):
        acc = (acc + (inp * _P2).astype(xp.uint64)).astype(xp.uint64)
        acc = _rotl64(xp, acc, 31)
        return (acc * _P1).astype(xp.uint64)

    any_stripe = False
    for s in range(max_stripes):
        base = 32 * s
        if base + 32 > width:
            break
        any_stripe = True
        m = s < n_stripes
        v1 = xp.where(m, round_(v1, get64(base)), v1)
        v2 = xp.where(m, round_(v2, get64(base + 8)), v2)
        v3 = xp.where(m, round_(v3, get64(base + 16)), v3)
        v4 = xp.where(m, round_(v4, get64(base + 24)), v4)

    merged = (_rotl64(xp, v1, 1) + _rotl64(xp, v2, 7)
              + _rotl64(xp, v3, 12) + _rotl64(xp, v4, 18)).astype(xp.uint64)

    def merge(acc, v):
        acc = acc ^ round_(xp.zeros_like(acc), v)
        return (acc * _P1 + _P4).astype(xp.uint64)

    merged = merge(merged, v1)
    merged = merge(merged, v2)
    merged = merge(merged, v3)
    merged = merge(merged, v4)

    small = (seed + _P5).astype(xp.uint64)
    has_stripes = n_stripes > 0
    h = xp.where(has_stripes, merged, small)
    h = (h + length).astype(xp.uint64)

    # tail: 8-byte chunks
    stripe_end = (n_stripes * 32).astype(xp.int32)
    max_longs = width // 8
    for j in range(max_longs + 1):
        pos = None
        # position of the j-th tail long for each row is stripe_end + 8*j
        start = stripe_end + 8 * j
        m = (start + 8) <= lengths_i32
        if not _may_be_true(xp, m):
            continue
        k = _gather64(xp, c, start, width)
        h = xp.where(m, _xx_process_long(xp, h, k), h)
    # 4-byte chunk
    longs_done = ((lengths_i32 - stripe_end) // 8) * 8
    pos4 = stripe_end + longs_done
    m4 = (pos4 + 4) <= lengths_i32
    k4 = _gather32(xp, c, pos4, width)
    h = xp.where(m4, _xx_process_int(xp, h, k4), h)
    pos_b = pos4 + xp.where(m4, 4, 0)
    # remaining single bytes
    for b in range(8):
        p = pos_b + b
        m = p < lengths_i32
        if not _may_be_true(xp, m):
            continue
        byte = _gather8(xp, c, p, width)
        h = xp.where(m, _xx_process_byte(xp, h, byte), h)
    return _xx_fmix(xp, h).astype(xp.int64)


def _may_be_true(xp, m):
    if xp.__name__ == "numpy":
        return bool(np.any(m))
    return True  # traced: keep the op, XLA prunes nothing but it's correct


def _gather8(xp, c_u64, pos, width):
    idx = xp.clip(pos, 0, width - 1)
    rows = xp.arange(c_u64.shape[0])
    return c_u64[rows, idx]


def _gather64(xp, c_u64, start, width):
    out = xp.zeros((c_u64.shape[0],), dtype=xp.uint64)
    for b in range(8):
        out = out | (_gather8(xp, c_u64, start + b, width) << np.uint64(8 * b))
    return out


def _gather32(xp, c_u64, start, width):
    out = xp.zeros((c_u64.shape[0],), dtype=xp.uint64)
    for b in range(4):
        out = out | (_gather8(xp, c_u64, start + b, width) << np.uint64(8 * b))
    return out.astype(xp.uint32)
